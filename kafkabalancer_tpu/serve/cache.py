"""Digest-keyed incremental tensorize cache for the planning daemon.

The outer automation loop re-reads cluster state and re-invokes the
planner once per move, so consecutive requests differ by ONE partition's
replica list (plus whatever drifted in between). A fresh tensorize pass
re-encodes every row from Python objects — O(P) list comprehensions and
per-row dict work that costs a visible slice of the warm-request budget
at 10k-partition scale. This cache keeps the previous dense encoding and
its per-row content keys; when the next request matches the same broker
universe and bucket shapes, only rows whose key changed are re-encoded
and everything else is a vectorized array copy.

Correctness model: a row's key covers every field the dense encoding
reads (topic, partition id, replicas, weight, num_replicas,
num_consumers, the allowed-brokers content), and the reuse precondition
pins the broker universe and the (P, R, B) buckets byte-for-byte — any
mismatch, a new topic, an unexpected broker, or too much churn falls
back to the full encode (which re-primes the cache). The cache returns
fresh copies and keeps its masters private, so callers may do anything
with the arrays.

Installed by the daemon via ``ops.tensorize.set_row_cache``; the
stateless CLI path never constructs one. Thread-safe (the daemon's
dispatcher serializes plans, but probe threads may race it).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.models import Partition
from kafkabalancer_tpu.ops.tensorize import (
    dense_replica_row,
    encode_allowed_row,
)

RowKey = Tuple[Any, ...]

# past this churn fraction the patch loop stops beating the vectorized
# full encode; fall back (and re-prime) instead
_MAX_CHANGED_FRACTION = 0.25
_MIN_CHANGED_ALLOWANCE = 64

_ARRAY_FIELDS = (
    "weights",
    "replicas",
    "nrep_cur",
    "nrep_tgt",
    "ncons",
    "allowed",
    "member",
    "pvalid",
    "bvalid",
    "topic_id",
)


def row_keys(parts: List[Partition]) -> List[RowKey]:
    """Per-partition content keys over every field tensorize encodes.

    The allowed-brokers term memoizes by list identity: after
    FillDefaults most partitions share ONE brokers-list object, so the
    tuple-ification cost is paid once per distinct list, not per row.
    """
    brokers_fp: Dict[int, Tuple[int, ...]] = {}
    keys: List[RowKey] = []
    for p in parts:
        if p.brokers is None:
            bfp: Optional[Tuple[int, ...]] = None
        else:
            ident = id(p.brokers)
            bfp = brokers_fp.get(ident)
            if bfp is None:
                bfp = brokers_fp[ident] = tuple(p.brokers)
        keys.append((
            p.topic,
            p.partition,
            tuple(p.replicas),
            p.weight,
            p.num_replicas,
            p.num_consumers,
            bfp,
        ))
    return keys


class TensorizeRowCache:
    """Previous dense encoding + per-row keys; see module docstring."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._meta: Optional[Tuple[bytes, int, int, int]] = None
        self._ids: Optional[np.ndarray] = None
        self._keys: List[RowKey] = []
        self._arrays: Dict[str, np.ndarray] = {}
        self._topics: List[str] = []
        self._topic_idx: Dict[str, int] = {}
        self.hits = 0
        self.misses = 0
        self.rows_reused = 0

    def _encode_row(
        self, p: Partition, ids: np.ndarray, B: int
    ) -> Optional[Tuple[int, np.ndarray, np.ndarray]]:
        """(topic_id, dense_replicas, allowed_row) for one changed
        partition, or None when it cannot be expressed in the cached
        vocabulary (new topic / out-of-universe broker). Encoding
        semantics live in ops/tensorize's shared per-row helpers — the
        patch path cannot drift from the full encode."""
        tid = self._topic_idx.get(p.topic)
        if tid is None:
            return None
        dense = dense_replica_row(p.replicas, ids)
        if dense is None:
            return None
        allowed_row = encode_allowed_row(p.brokers, ids, len(ids), B)
        return tid, dense, allowed_row

    def lookup(
        self,
        parts: List[Partition],
        ids: np.ndarray,
        P: int,
        R: int,
        B: int,
    ) -> Optional[Dict[str, Any]]:
        """Incrementally re-encode against the cached pass — the entry
        point ``ops.tensorize`` calls before its full encode.

        Returns ``{"arrays": {...}, "topics": [...]}`` (fresh copies)
        when the cached encoding covers this input, else None (caller
        runs the full encode and calls :meth:`prime`).
        """
        keys = row_keys(parts)
        with self._lock:
            meta = (ids.tobytes(), P, R, B)
            if (
                self._meta != meta
                or len(keys) != len(self._keys)
                or self._ids is None
            ):
                self.misses += 1
                return None
            changed = [
                i for i, k in enumerate(keys) if k != self._keys[i]
            ]
            if len(changed) > max(
                _MIN_CHANGED_ALLOWANCE,
                int(len(keys) * _MAX_CHANGED_FRACTION),
            ):
                self.misses += 1
                return None
            # validate EVERY changed row before mutating the masters —
            # a mid-patch bail would leave the cache half-updated
            patches = []
            for i in changed:
                enc = self._encode_row(parts[i], self._ids, B)
                if enc is None:
                    self.misses += 1
                    return None
                patches.append((i, parts[i], enc))
            a = self._arrays
            for i, p, (tid, dense, allowed_row) in patches:
                a["weights"][i] = p.weight
                a["nrep_cur"][i] = len(p.replicas)
                a["nrep_tgt"][i] = p.num_replicas
                a["ncons"][i] = p.num_consumers
                a["replicas"][i, :] = -1
                a["replicas"][i, : dense.size] = dense
                a["member"][i, :] = False
                a["member"][i, dense] = True
                a["allowed"][i, :] = allowed_row
                a["topic_id"][i] = tid
                self._keys[i] = keys[i]
            self.hits += 1
            self.rows_reused += len(keys) - len(changed)
            obs.metrics.count("tensorize.cache_hits")
            obs.metrics.count(
                "tensorize.rows_reused", len(keys) - len(changed)
            )
            return {
                "arrays": {f: a[f].copy() for f in _ARRAY_FIELDS},
                "topics": list(self._topics),
            }

    def prime(
        self,
        parts: List[Partition],
        ids: np.ndarray,
        P: int,
        R: int,
        B: int,
        arrays: Dict[str, np.ndarray],
        topics: List[str],
    ) -> None:
        """Prime the cache from a completed full encode (copies taken —
        the caller keeps exclusive ownership of its arrays)."""
        keys = row_keys(parts)
        with self._lock:
            self._meta = (ids.tobytes(), P, R, B)
            self._ids = np.array(ids, copy=True)
            self._keys = list(keys)
            self._arrays = {f: arrays[f].copy() for f in _ARRAY_FIELDS}
            self._topics = list(topics)
            self._topic_idx = {t: i for i, t in enumerate(topics)}

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "rows_reused": self.rows_reused,
            }
