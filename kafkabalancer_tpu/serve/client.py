"""The jax-free forwarding client embedded in the CLI.

Every normal CLI invocation asks this module whether a live daemon is
reachable on the resolved socket; if so, the parsed flags + input text
are forwarded and the daemon's stdout/stderr/exit code are relayed
verbatim. EVERY failure mode — no socket, stale socket, version skew,
truncated response, daemon death mid-plan — returns ``None`` and the CLI
falls back to the ordinary in-process path, byte-identical to a build
without a daemon (pinned by tests/test_serve.py).

Nothing here may import jax (directly or transitively): a forwarded
invocation must stay as light as an error exit — the whole point of the
daemon is that the client process never pays the jax import.
"""

from __future__ import annotations

import contextlib
import os
import random
import socket
import time
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Tuple

from kafkabalancer_tpu import __version__
from kafkabalancer_tpu.serve.protocol import (
    PROTO_V2,
    PROTO_VERSION,
    read_frame,
    read_frame2,
    resolve_socket_path,  # noqa: F401  — re-exported for the CLI
    write_frame,
    write_frame2,
)

# connect + handshake must be near-free when a daemon exists and exactly
# one failed connect() when it does not; the plan response itself gets a
# generous HARD ceiling (a convergence-scale session runs minutes) —
# but the wait is no longer one blind 3600 s read: see _await_reply
CONNECT_TIMEOUT_S = 2.0
PLAN_TIMEOUT_S = 3600.0

# the progress-aware plan wait (the -serve-client-timeout=0 default):
# while no reply byte has arrived, the client wakes every tick and
# probes the daemon's hello on a fresh connection. A daemon that stops
# answering hello — or answers but holds NO in-flight work and makes no
# progress (it accepted our frame and lost it) — is presumed wedged
# after PROGRESS_GRACE_PROBES consecutive bad probes, and the client
# takes its byte-identical in-process fallback in seconds instead of an
# hour, attributed serve.fallbacks.daemon_wedged. (A daemon-side wedged
# LANE is the daemon watchdog's job — it answers a structured error;
# this ladder only has to catch process-level wedges.)
PROGRESS_TICK_S = 5.0
PROGRESS_GRACE_PROBES = 2
# once the first reply byte is visible the frame is in flight; draining
# it gets a plain bounded timeout (generous: a -full-output plan for a
# very large cluster is tens of MB)
REPLY_DRAIN_TIMEOUT_S = 600.0

# the overload backoff ladder: a daemon shedding under load answers a
# structured {op:"overload", retry_after_ms} frame; the client sleeps
# max(retry_after, base*2^attempt) — capped, jittered — and retries on
# the same connection before giving up to the in-process fallback
RETRY_MAX_ATTEMPTS = 4
RETRY_BACKOFF_BASE_S = 0.05
RETRY_BACKOFF_CAP_S = 2.0


class _Wedged(Exception):
    """The daemon accepted the request but is presumed wedged (stopped
    answering hello / lost the request) or the wait budget ran out."""


class _Overload(Exception):
    """The daemon shed the request with a structured overload frame."""

    def __init__(self, retry_after_ms: int) -> None:
        super().__init__(f"shed; retry after {retry_after_ms}ms")
        self.retry_after_ms = max(0, int(retry_after_ms))


class ServedResult(NamedTuple):
    """One forwarded invocation's outcome, relayed verbatim.

    ``trace`` is the daemon's reply footer when the request carried a
    trace context (v2 only): the request's trace id, daemon wall and
    the bounded daemon span subtree — raw daemon ``perf_counter_ns``
    stamps the caller maps through its clock-offset estimate
    (obs/edge.py). None on v1 exchanges and for trace-less requests."""

    rc: int
    stdout: str
    stderr: str
    trace: Optional[Dict[str, Any]] = None


class SessionSpec(NamedTuple):
    """What the client needs for the resident-session exchange with a
    protocol-v2 daemon: the session identity plus the raw input (for
    the digest and, on a full re-sync, the register payload)."""

    tenant: str
    text: str
    is_json: bool
    topics: List[str]


# a row-resync whose diff exceeds this never beats re-registering: past
# ~25% changed rows the daemon's patch path falls back to a full encode
# anyway (serve/cache.py), so ship the whole state once instead
_MAX_RESYNC_ROWS_FRACTION = 0.25
_MIN_RESYNC_ROWS = 64


def socket_exists(path: str) -> bool:
    """Cheap pre-check (one stat) so invocations on daemon-less hosts
    pay nothing at all."""
    try:
        return os.path.exists(path)
    except OSError:
        return False


def _connect(path: str, timeout: float) -> Optional[socket.socket]:
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(path)
        return sock
    except OSError:
        return None


def _hello_ok(resp: Optional[Dict[str, Any]]) -> bool:
    """A usable daemon: right protocol AND right package version — a
    daemon left over from an older install must not answer for a newer
    client (its plans could silently differ from the in-process path)."""
    return (
        isinstance(resp, dict)
        and bool(resp.get("ok"))
        and resp.get("v") == PROTO_VERSION
        and resp.get("version") == __version__
    )


def _await_reply(
    sock: socket.socket,
    path: str,
    deadline: float,
    progress: bool,
) -> None:
    """Block until the daemon's reply starts arriving (first byte
    visible via ``MSG_PEEK`` — probing can never desynchronize a frame
    already in flight), then set the drain timeout. Raises
    :class:`_Wedged` when the budget runs out or — in progress-aware
    mode — the daemon is presumed wedged; ``ConnectionError`` on EOF
    before any reply byte (dead peer)."""
    probes_dead = 0
    stalls = 0
    last_done: Any = None
    while True:
        remaining = deadline - time.monotonic()
        if remaining <= 0:
            raise _Wedged("plan wait budget exhausted")
        sock.settimeout(min(PROGRESS_TICK_S, remaining))
        try:
            head = sock.recv(1, socket.MSG_PEEK)
        except socket.timeout:
            if not progress:
                continue  # explicit -serve-client-timeout: budget only
            hello = daemon_alive(path, timeout=2.0)
            if hello is None:
                probes_dead += 1
                if probes_dead >= PROGRESS_GRACE_PROBES:
                    raise _Wedged("daemon stopped answering hello")
                continue
            probes_dead = 0
            inflight = hello.get("requests_inflight")
            done = hello.get("requests")
            if hello.get("warming") or (
                isinstance(inflight, int) and inflight > 0
            ):
                # our request is plausibly queued/running (or the
                # daemon is still building its dispatcher): keep
                # waiting — slow is not wedged
                stalls = 0
                last_done = done
                continue
            # alive, warm, and holding NO in-flight work while we wait:
            # the daemon lost our request. Two consecutive such probes
            # (with no completions in between) confirm it.
            if done == last_done:
                stalls += 1
            else:
                stalls = 0
            last_done = done
            if stalls >= PROGRESS_GRACE_PROBES:
                raise _Wedged("request lost daemon-side")
            continue
        if head == b"":
            raise ConnectionError("EOF before reply")
        sock.settimeout(
            max(CONNECT_TIMEOUT_S, min(remaining, REPLY_DRAIN_TIMEOUT_S))
        )
        return


def _overload_sleep(
    attempt: int, retry_after_ms: int, deadline: float
) -> Optional[float]:
    """The backoff ladder's next sleep: the daemon's ``retry_after_ms``
    is a FLOOR (retrying earlier would arrive at a still-full queue and
    burn an attempt), the exponential term is capped, and jitter goes
    UP (0–50%) so a thundering herd of shed clients decorrelates
    without ever undercutting the advertised earliest-admit time.
    None when the remaining budget cannot cover the sleep (give up and
    fall back in-process)."""
    base = min(RETRY_BACKOFF_BASE_S * (2 ** attempt), RETRY_BACKOFF_CAP_S)
    sleep = max(retry_after_ms / 1000.0, base)
    sleep *= 1.0 + 0.5 * random.random()
    if deadline - time.monotonic() <= sleep:
        return None
    return sleep


def daemon_alive(
    path: str, timeout: float = CONNECT_TIMEOUT_S
) -> Optional[Dict[str, Any]]:
    """Handshake with the daemon at ``path``; its hello response dict
    when live and compatible, else None (absent, stale, or skewed)."""
    sock = _connect(path, timeout)
    if sock is None:
        return None
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "hello"})
        resp = read_frame(sock)
        return resp if _hello_ok(resp) else None
    except Exception:
        return None
    finally:
        sock.close()


def _remaining_ms(deadline: float) -> int:
    return max(1, int((deadline - time.monotonic()) * 1000.0))


def forward_plan(
    path: str,
    argv: List[str],
    stdin_text: Optional[str],
    connect_timeout: float = CONNECT_TIMEOUT_S,
    plan_timeout: float = PLAN_TIMEOUT_S,
    on_fallback: Optional[Callable[[str], None]] = None,
    session: Optional[SessionSpec] = None,
    note: Optional[Callable[[str], None]] = None,
    tenant: str = "",
    client_timeout: float = 0.0,
    edge: Any = None,
    cached_state: Any = None,
) -> Optional[ServedResult]:
    """Forward one invocation to the daemon at ``path``.

    ``argv`` is the canonical flag list the CLI built (``-no-daemon``
    included); ``stdin_text`` is the raw input when no ``-input``/
    ``-from-zk`` names a source. Returns the daemon's result, or None on
    ANY failure — the caller falls back in-process.

    ``on_fallback`` receives the REASON when the daemon positively
    declined the request (a structured ``op: "error"`` frame — oversized
    payload, unparseable frame) or the payload exceeds the protocol's
    frame cap client-side, so the CLI can log why it planned in-process
    instead of a generic silent fallback. Silent failure modes (no
    daemon, dead socket, version skew) deliberately stay silent on
    stderr — the daemon-down path must remain byte-identical to a build
    without a daemon — but every one of them reports its reason through
    ``note`` (daemon_down, handshake_mismatch, frame_cap, declined,
    transport_error, session_digest_mismatch), which the CLI turns into
    ``serve.fallbacks.<reason>`` counters so a degraded fleet is
    diagnosable from metrics instead of log archaeology.

    ``session`` opts this invocation into the resident-session exchange
    when the daemon negotiates protocol v2: steady state sends only a
    state digest (``plan-delta``); a mismatch ships just the changed
    rows (``plan-rows``); structural drift re-registers the full state.
    A v1 daemon — or ``session=None`` — gets the exact v1 byte sequence
    this function always sent.

    ``tenant`` is a pure telemetry label: a v2 daemon attributes the
    request's latency/counters to it in its per-tenant scrape block
    (docs/observability.md § Per-tenant attribution). It defaults to
    the session's tenant when a session spec is given; it never
    affects planning, and v1 framing never carries it.

    ``client_timeout`` bounds the whole plan wait (``-serve-client-
    timeout``). The default 0 keeps the generous ``plan_timeout``
    ceiling but waits PROGRESS-AWARE (see ``_await_reply``): a daemon
    that accepts the request and then wedges is detected in seconds
    and falls back, attributed ``daemon_wedged``. An explicit timeout
    is also SENT as the request's ``deadline_ms`` budget so the daemon
    can shed it from the queue once it can no longer be useful. Shed
    (``op: "overload"``) responses are retried with capped, jittered
    exponential backoff honoring ``retry_after_ms`` before the
    in-process fallback (attributed ``overload``).

    ``edge`` is the CLI's edge recorder (obs/edge.py ``EdgeContext``),
    DUCK-TYPED so this module never imports ``obs``: when given, the
    connect/handshake/digest/send/wait/receive phases are timed, the
    hello requests the daemon's clock stamps (one NTP-style offset
    sample per handshake), and every plan-family v2 header carries the
    recorder's trace context. ``edge=None`` (every pre-existing caller)
    changes nothing — and a v1 exchange stays byte-identical either
    way except for the opt-in ``clock`` hello key.

    ``cached_state`` is an edge-residency state (serve/edge_cache.py
    ``CachedState``, duck-typing ``ClientState``): when given alongside
    ``session``, the O(P) parse+digest is skipped entirely — the digest
    ships from the cache, and canon/rows/text materialize lazily only
    on the rare resync/register rungs. ``stdin_text`` may then be None
    even for session requests; any path that genuinely needs the raw
    input (a v1 daemon, a register) loads it from the cached state and
    degrades to the in-process fallback if the cache cannot deliver.
    """

    def _declined(reason: str) -> None:
        if on_fallback is not None:
            try:
                on_fallback(reason)
            except Exception:
                pass

    def _note(reason: str) -> None:
        if note is not None:
            try:
                note(reason)
            except Exception:
                pass

    def _phase(name: str) -> "contextlib.AbstractContextManager[Any]":
        if edge is not None:
            return edge.phase(name)
        return contextlib.nullcontext()

    with _phase("connect"):
        sock = _connect(path, connect_timeout)
    if sock is None:
        _note("daemon_down")
        return None
    # the plan-wait budget: an explicit -serve-client-timeout bounds
    # everything (and travels as the request's deadline_ms); the
    # default keeps the generous ceiling but waits progress-aware
    progress = client_timeout <= 0
    budget = client_timeout if client_timeout > 0 else plan_timeout
    deadline = time.monotonic() + budget
    try:
        hello_req: Dict[str, Any] = {
            "v": PROTO_VERSION, "op": "hello", "max_v": PROTO_V2,
        }
        if edge is not None:
            # opt-in clock handshake: ONLY a hello carrying this key
            # gets monotonic stamps back, so scrape hellos (and their
            # hello-vs-stats key parity pin) are untouched
            hello_req["clock"] = True
        with _phase("handshake"):
            t_hello0 = time.perf_counter_ns()
            write_frame(sock, hello_req)
            hello = read_frame(sock)
            t_hello1 = time.perf_counter_ns()
        if not _hello_ok(hello):
            _note("handshake_mismatch")
            return None
        assert isinstance(hello, dict)
        if edge is not None:
            edge.note_clock_sample(
                t_hello0, hello.get("clock"), t_hello1
            )
        max_v = hello.get("max_v")
        v2 = isinstance(max_v, int) and max_v >= PROTO_V2
        # writes need a generous timeout too: a multi-MB register blob
        # to a GIL-saturated daemon can take longer than the 2 s
        # connect timeout to drain into the socket buffer (reads set
        # their own timeouts per _await_reply call)
        sock.settimeout(
            max(CONNECT_TIMEOUT_S, min(budget, REPLY_DRAIN_TIMEOUT_S))
        )
        # the session digest is attempt-invariant: compute it once and
        # share across overload retries (a multi-MB parse must not be
        # re-paid 4 times in the middle of an overload storm). An
        # edge-residency hit pre-seeds it — the parse never happens.
        state_cache: Dict[str, Any] = {}
        if cached_state is not None and session is not None:
            state_cache["state"] = cached_state
        attempt = 0
        while True:
            try:
                if v2:
                    return _forward_v2(
                        sock, argv, stdin_text, session,
                        tenant or (
                            session.tenant if session is not None else ""
                        ),
                        _declined, _note,
                        path=path, deadline=deadline, progress=progress,
                        send_deadline=not progress,
                        state_cache=state_cache,
                        edge=edge,
                    )
                req: Dict[str, Any] = {
                    "v": PROTO_VERSION, "op": "plan", "argv": argv,
                }
                if not progress:
                    req["deadline_ms"] = _remaining_ms(deadline)
                if stdin_text is None and cached_state is not None:
                    # a v1 daemon cannot use the digest: materialize
                    # the raw input from the cache (or degrade)
                    try:
                        stdin_text = cached_state.load_text()
                    except Exception:
                        _note("edge_cache_error")
                        return None
                if stdin_text is not None:
                    req["stdin"] = stdin_text
                try:
                    with _phase("send"):
                        write_frame(sock, req)
                except ValueError as exc:
                    # the input is too large for one protocol frame — a
                    # positive local refusal, not a daemon failure
                    _declined(
                        f"request exceeds the protocol frame cap: {exc}"
                    )
                    _note("frame_cap")
                    return None
                with _phase("wait_first_byte"):
                    _await_reply(sock, path, deadline, progress)
                with _phase("receive"):
                    resp = read_frame(sock)
                if (
                    isinstance(resp, dict)
                    and resp.get("op") == "overload"
                    and resp.get("reason") != "shutdown"
                ):
                    # a "shutdown" shed falls through to the declined
                    # path below — retrying against a dying daemon
                    # only delays the in-process fallback
                    raise _Overload(
                        int(resp.get("retry_after_ms", 0) or 0)
                    )
                if (
                    not isinstance(resp, dict)
                    or not resp.get("ok")
                    or resp.get("v") != PROTO_VERSION
                ):
                    if isinstance(resp, dict) and resp.get("error"):
                        _declined(str(resp["error"]))
                        _note("declined")
                    else:
                        _note("transport_error")
                    return None
                return ServedResult(
                    rc=int(resp["rc"]),
                    stdout=str(resp.get("stdout", "")),
                    stderr=str(resp.get("stderr", "")),
                )
            except _Overload as ov:
                # the backoff ladder: honor retry_after_ms (capped,
                # jittered), retry on the same connection, give up to
                # the in-process fallback when attempts/budget run out
                sleep = _overload_sleep(
                    attempt, ov.retry_after_ms, deadline
                )
                attempt += 1
                if sleep is None or attempt > RETRY_MAX_ATTEMPTS:
                    _note("overload")
                    return None
                time.sleep(sleep)
    except _Wedged:
        _note("daemon_wedged")
        return None
    except Exception:
        _note("transport_error")
        return None
    finally:
        sock.close()


def _v2_result(
    resp: "Optional[Tuple[Dict[str, Any], bytes]]",
    _declined: Callable[[str], None],
    _note: Callable[[str], None],
) -> Optional[ServedResult]:
    """Decode a v2 plan response (stdout rides in the blob, everything
    else in the header); None on any shape the caller must fall back
    from; raises :class:`_Overload` on a structured shed frame (the
    caller's backoff ladder owns the retry)."""
    if resp is None:
        _note("transport_error")
        return None
    hdr, blob = resp
    if hdr.get("op") == "overload" and hdr.get("reason") != "shutdown":
        raise _Overload(int(hdr.get("retry_after_ms", 0) or 0))
    if not hdr.get("ok") or hdr.get("v") != PROTO_V2:
        if hdr.get("error"):
            _declined(str(hdr["error"]))
            _note("declined")
        else:
            _note("transport_error")
        return None
    footer = hdr.get("trace")
    return ServedResult(
        rc=int(hdr["rc"]),
        stdout=blob.decode("utf-8", errors="replace"),
        stderr=str(hdr.get("stderr", "")),
        trace=footer if isinstance(footer, dict) else None,
    )


def _forward_v2(
    sock: socket.socket,
    argv: List[str],
    stdin_text: Optional[str],
    session: Optional[SessionSpec],
    tenant: str,
    _declined: Callable[[str], None],
    _note: Callable[[str], None],
    *,
    path: str,
    deadline: float,
    progress: bool,
    send_deadline: bool,
    state_cache: Dict[str, Any],
    edge: Any = None,
) -> Optional[ServedResult]:
    """The v2 exchange after a successful hello negotiation: the
    session ladder (plan-delta -> plan-rows -> register) when a session
    spec is usable, else a plain v2 ``plan`` with the input as a raw
    blob (no JSON string escaping either way). Every plan-family read
    waits through ``_await_reply`` (progress-aware wedge detection);
    ``send_deadline`` adds the remaining budget as ``deadline_ms``.
    The wait-contract parameters are keyword-REQUIRED: a caller that
    forgot them would silently disable wedge detection and deadlines."""
    def _phase(name: str) -> "contextlib.AbstractContextManager[Any]":
        if edge is not None:
            return edge.phase(name)
        return contextlib.nullcontext()

    # loading serve/state pulls in the codecs readers — a multi-ms
    # one-time cost that is digest machinery, so on the session path it
    # must land in the digest phase rather than an unattributed gap
    with (_phase("digest") if session is not None
          else contextlib.nullcontext()):
        from kafkabalancer_tpu.serve import state as sstate
        from kafkabalancer_tpu.serve.edge_cache import EdgeCacheError

    def _read2() -> "Optional[Tuple[Dict[str, Any], bytes]]":
        with _phase("wait_first_byte"):
            _await_reply(sock, path, deadline, progress)
        with _phase("receive"):
            return read_frame2(sock)

    def _stamp(hdr: Dict[str, Any]) -> Dict[str, Any]:
        if send_deadline:
            hdr["deadline_ms"] = _remaining_ms(deadline)
        if edge is not None:
            # the trace context rides EVERY plan-family v2 header (the
            # pre-send client phases are final by the first send; a
            # ladder follow-up or overload retry re-stamps the same id)
            hdr["trace"] = edge.trace_context()
        return hdr

    state = None
    if session is not None:
        # parse + digest through the very codecs reader the planner
        # uses; None (unusual input) falls through to the full-state
        # path and the daemon surfaces any real error normally. The
        # caller's cache shares the result across overload retries —
        # the input is attempt-invariant.
        if "state" in state_cache:
            state = state_cache["state"]
        else:
            with _phase("digest"):
                state = state_cache["state"] = sstate.client_state(
                    session.text, session.is_json, session.topics
                )
    if state is None or session is None:
        hdr: Dict[str, Any] = {
            "v": PROTO_V2, "op": "plan", "argv": argv,
            "has_stdin": stdin_text is not None,
        }
        if tenant:
            # telemetry-only: the daemon's per-tenant attribution for
            # requests that skip the session ladder
            hdr["tenant"] = tenant
        blob = stdin_text.encode("utf-8") if stdin_text is not None else b""
        try:
            with _phase("send"):
                write_frame2(sock, _stamp(hdr), blob)
        except ValueError as exc:
            _declined(f"request exceeds the protocol frame cap: {exc}")
            _note("frame_cap")
            return None
        return _v2_result(_read2(), _declined, _note)

    # an edge-residency state knows its row count without materializing
    # the canonical rows (the whole point of the stat-hit rung)
    nrows = getattr(state, "nrows", None)
    if not isinstance(nrows, int):
        nrows = len(state.canon)
    with _phase("send"):
        write_frame2(sock, _stamp({
            "v": PROTO_V2, "op": "plan-delta", "tenant": session.tenant,
            "digest": state.digest, "nrows": nrows,
            "argv": argv,
        }))
    resp = _read2()
    if resp is None:
        _note("transport_error")
        return None
    hdr2, blob2 = resp
    resync = hdr2.get("resync")
    try:
        if resync == "rows":
            _note("session_digest_mismatch")
            try:
                theirs = sstate.unpack_hash_table(blob2)
            except ValueError:
                theirs = None
            # per-row hashes are computed HERE, lazily: only a mismatch
            # pays them (the steady state digests the canonical bytes
            # once) — and an edge-residency state already carries its
            # row-hash ladder, so even a resync pays O(changed)
            mine = getattr(state, "row_hashes", None)
            if mine is None:
                mine = sstate.hashes_of(state.canon)
            changed = (
                sstate.diff_rows(mine, theirs)
                if theirs is not None else None
            )
            if changed is not None and len(changed) <= max(
                _MIN_RESYNC_ROWS,
                int(nrows * _MAX_RESYNC_ROWS_FRACTION),
            ):
                rows_blob = sstate.pack_rows(
                    [(i, state.rows[i]) for i in changed]
                )
                try:
                    with _phase("send"):
                        write_frame2(sock, _stamp({
                            "v": PROTO_V2, "op": "plan-rows",
                            "tenant": session.tenant,
                            "digest": state.digest,
                            "argv": argv,
                        }), rows_blob)
                except ValueError as exc:
                    _declined(
                        f"request exceeds the protocol frame cap: {exc}"
                    )
                    _note("frame_cap")
                    return None
                resp = _read2()
                if resp is None:
                    _note("transport_error")
                    return None
                hdr2, blob2 = resp
                if not hdr2.get("resync"):
                    return _v2_result((hdr2, blob2), _declined, _note)
            resync = "full"
        if resync:
            # structural drift (or the daemon could not use the rows):
            # re-register the full state — the blob is the raw text, so
            # even this worst case skips the JSON escape pass
            _note("session_resync_full")
            reg_text = session.text
            if reg_text == "" and hasattr(state, "load_text"):
                reg_text = state.load_text()
            try:
                with _phase("send"):
                    write_frame2(sock, _stamp({
                        "v": PROTO_V2, "op": "register",
                        "tenant": session.tenant,
                        "argv": argv, "has_stdin": True,
                    }), reg_text.encode("utf-8"))
            except ValueError as exc:
                _declined(
                    f"request exceeds the protocol frame cap: {exc}"
                )
                _note("frame_cap")
                return None
            return _v2_result(_read2(), _declined, _note)
    except EdgeCacheError:
        # the cached body could not be materialized for a resync —
        # degrade to the in-process fallback (content is then re-read
        # from the real source; never a wrong plan, only a slower one)
        _note("edge_cache_error")
        return None
    return _v2_result((hdr2, blob2), _declined, _note)


def _scrape(
    path: str, op: str, timeout: float
) -> Optional[Dict[str, Any]]:
    """One non-plan op round trip (``stats`` / ``dump-trace``) with the
    same hello version gate as forwarding — None on any failure (the
    caller reports "no live daemon")."""
    sock = _connect(path, CONNECT_TIMEOUT_S)
    if sock is None:
        return None
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "hello"})
        if not _hello_ok(read_frame(sock)):
            return None
        sock.settimeout(timeout)
        write_frame(sock, {"v": PROTO_VERSION, "op": op})
        resp = read_frame(sock)
        if (
            not isinstance(resp, dict)
            or not resp.get("ok")
            or resp.get("v") != PROTO_VERSION
        ):
            return None
        return resp
    except Exception:
        return None
    finally:
        sock.close()


def fetch_stats(
    path: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """The live telemetry scrape (``-serve-stats[-json]`` /
    ``-metrics-prom``): the daemon's stats document, or None when no
    live, version-compatible daemon answers on ``path``."""
    return _scrape(path, "stats", timeout)


def fetch_trace(
    path: str, timeout: float = 60.0
) -> Optional[Dict[str, Any]]:
    """The flight-recorder export (``-serve-dump-trace``): a response
    whose ``trace`` key is a Perfetto-loadable document, or None."""
    return _scrape(path, "dump-trace", timeout)


def fetch_watch(
    path: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """The watch-lag scrape (the ``watch`` protocol op): a response
    carrying the daemon's ``watch`` block (ticks/reads/lag/emitted
    plans) and its ``speculation`` block, or None when no live,
    version-compatible daemon answers. Much cheaper than ``stats`` —
    the replay harness polls it between fake-ZK mutations."""
    return _scrape(path, "watch", timeout)


def release_session(
    path: str, tenant: str, timeout: float = 10.0
) -> Optional[int]:
    """Drop a tenant's resident sessions on a live v2 daemon — hot
    residents AND warm spill records (a released tenant must not be
    silently restorable from disk); the total number released across
    both tiers, or None when no v2 daemon answers."""
    sock = _connect(path, CONNECT_TIMEOUT_S)
    if sock is None:
        return None
    try:
        write_frame(
            sock, {"v": PROTO_VERSION, "op": "hello", "max_v": PROTO_V2}
        )
        hello = read_frame(sock)
        if not _hello_ok(hello):
            return None
        assert isinstance(hello, dict)
        max_v = hello.get("max_v")
        if not (isinstance(max_v, int) and max_v >= PROTO_V2):
            return None
        sock.settimeout(timeout)
        write_frame2(
            sock, {"v": PROTO_V2, "op": "release", "tenant": tenant}
        )
        resp = read_frame2(sock)
        if resp is None or not resp[0].get("ok"):
            return None
        return int(resp[0].get("released", 0)) + int(
            resp[0].get("released_warm", 0) or 0
        )
    except Exception:
        return None
    finally:
        sock.close()


def request_shutdown(path: str, timeout: float = 10.0) -> bool:
    """Ask the daemon at ``path`` to exit; True when acknowledged."""
    sock = _connect(path, timeout)
    if sock is None:
        return False
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "shutdown"})
        resp = read_frame(sock)
        return isinstance(resp, dict) and bool(resp.get("ok"))
    except Exception:
        return False
    finally:
        sock.close()
