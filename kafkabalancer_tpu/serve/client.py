"""The jax-free forwarding client embedded in the CLI.

Every normal CLI invocation asks this module whether a live daemon is
reachable on the resolved socket; if so, the parsed flags + input text
are forwarded and the daemon's stdout/stderr/exit code are relayed
verbatim. EVERY failure mode — no socket, stale socket, version skew,
truncated response, daemon death mid-plan — returns ``None`` and the CLI
falls back to the ordinary in-process path, byte-identical to a build
without a daemon (pinned by tests/test_serve.py).

Nothing here may import jax (directly or transitively): a forwarded
invocation must stay as light as an error exit — the whole point of the
daemon is that the client process never pays the jax import.
"""

from __future__ import annotations

import os
import socket
from typing import Any, Callable, Dict, List, NamedTuple, Optional

from kafkabalancer_tpu import __version__
from kafkabalancer_tpu.serve.protocol import (
    PROTO_VERSION,
    read_frame,
    resolve_socket_path,  # noqa: F401  — re-exported for the CLI
    write_frame,
)

# connect + handshake must be near-free when a daemon exists and exactly
# one failed connect() when it does not; the plan response itself gets a
# generous ceiling (a convergence-scale session runs minutes)
CONNECT_TIMEOUT_S = 2.0
PLAN_TIMEOUT_S = 3600.0


class ServedResult(NamedTuple):
    """One forwarded invocation's outcome, relayed verbatim."""

    rc: int
    stdout: str
    stderr: str


def socket_exists(path: str) -> bool:
    """Cheap pre-check (one stat) so invocations on daemon-less hosts
    pay nothing at all."""
    try:
        return os.path.exists(path)
    except OSError:
        return False


def _connect(path: str, timeout: float) -> Optional[socket.socket]:
    try:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        sock.settimeout(timeout)
        sock.connect(path)
        return sock
    except OSError:
        return None


def _hello_ok(resp: Optional[Dict[str, Any]]) -> bool:
    """A usable daemon: right protocol AND right package version — a
    daemon left over from an older install must not answer for a newer
    client (its plans could silently differ from the in-process path)."""
    return (
        isinstance(resp, dict)
        and bool(resp.get("ok"))
        and resp.get("v") == PROTO_VERSION
        and resp.get("version") == __version__
    )


def daemon_alive(
    path: str, timeout: float = CONNECT_TIMEOUT_S
) -> Optional[Dict[str, Any]]:
    """Handshake with the daemon at ``path``; its hello response dict
    when live and compatible, else None (absent, stale, or skewed)."""
    sock = _connect(path, timeout)
    if sock is None:
        return None
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "hello"})
        resp = read_frame(sock)
        return resp if _hello_ok(resp) else None
    except Exception:
        return None
    finally:
        sock.close()


def forward_plan(
    path: str,
    argv: List[str],
    stdin_text: Optional[str],
    connect_timeout: float = CONNECT_TIMEOUT_S,
    plan_timeout: float = PLAN_TIMEOUT_S,
    on_fallback: Optional[Callable[[str], None]] = None,
) -> Optional[ServedResult]:
    """Forward one invocation to the daemon at ``path``.

    ``argv`` is the canonical flag list the CLI built (``-no-daemon``
    included); ``stdin_text`` is the raw input when no ``-input``/
    ``-from-zk`` names a source. Returns the daemon's result, or None on
    ANY failure — the caller falls back in-process.

    ``on_fallback`` receives the REASON when the daemon positively
    declined the request (a structured ``op: "error"`` frame — oversized
    payload, unparseable frame) or the payload exceeds the protocol's
    frame cap client-side, so the CLI can log why it planned in-process
    instead of a generic silent fallback. Silent failure modes (no
    daemon, dead socket, version skew) deliberately stay silent — the
    daemon-down path must remain byte-identical to a build without a
    daemon.
    """

    def _declined(reason: str) -> None:
        if on_fallback is not None:
            try:
                on_fallback(reason)
            except Exception:
                pass

    sock = _connect(path, connect_timeout)
    if sock is None:
        return None
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "hello"})
        if not _hello_ok(read_frame(sock)):
            return None
        req: Dict[str, Any] = {"v": PROTO_VERSION, "op": "plan", "argv": argv}
        if stdin_text is not None:
            req["stdin"] = stdin_text
        sock.settimeout(plan_timeout)
        try:
            write_frame(sock, req)
        except ValueError as exc:
            # the input is too large for one protocol frame — a positive
            # local refusal, not a daemon failure
            _declined(f"request exceeds the protocol frame cap: {exc}")
            return None
        resp = read_frame(sock)
        if (
            not isinstance(resp, dict)
            or not resp.get("ok")
            or resp.get("v") != PROTO_VERSION
        ):
            if isinstance(resp, dict) and resp.get("error"):
                _declined(str(resp["error"]))
            return None
        return ServedResult(
            rc=int(resp["rc"]),
            stdout=str(resp.get("stdout", "")),
            stderr=str(resp.get("stderr", "")),
        )
    except Exception:
        return None
    finally:
        sock.close()


def _scrape(
    path: str, op: str, timeout: float
) -> Optional[Dict[str, Any]]:
    """One non-plan op round trip (``stats`` / ``dump-trace``) with the
    same hello version gate as forwarding — None on any failure (the
    caller reports "no live daemon")."""
    sock = _connect(path, CONNECT_TIMEOUT_S)
    if sock is None:
        return None
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "hello"})
        if not _hello_ok(read_frame(sock)):
            return None
        sock.settimeout(timeout)
        write_frame(sock, {"v": PROTO_VERSION, "op": op})
        resp = read_frame(sock)
        if (
            not isinstance(resp, dict)
            or not resp.get("ok")
            or resp.get("v") != PROTO_VERSION
        ):
            return None
        return resp
    except Exception:
        return None
    finally:
        sock.close()


def fetch_stats(
    path: str, timeout: float = 10.0
) -> Optional[Dict[str, Any]]:
    """The live telemetry scrape (``-serve-stats[-json]`` /
    ``-metrics-prom``): the daemon's stats document, or None when no
    live, version-compatible daemon answers on ``path``."""
    return _scrape(path, "stats", timeout)


def fetch_trace(
    path: str, timeout: float = 60.0
) -> Optional[Dict[str, Any]]:
    """The flight-recorder export (``-serve-dump-trace``): a response
    whose ``trace`` key is a Perfetto-loadable document, or None."""
    return _scrape(path, "dump-trace", timeout)


def request_shutdown(path: str, timeout: float = 10.0) -> bool:
    """Ask the daemon at ``path`` to exit; True when acknowledged."""
    sock = _connect(path, timeout)
    if sock is None:
        return False
    try:
        write_frame(sock, {"v": PROTO_VERSION, "op": "shutdown"})
        resp = read_frame(sock)
        return isinstance(resp, dict) and bool(resp.get("ok"))
    except Exception:
        return False
    finally:
        sock.close()
