"""The persistent planning daemon (``-serve``).

One long-lived process owns everything a stateless invocation re-pays:
the jax import, the backend/relay attach, the deserialized AOT
executables (``ops.aot._loaded``), the persistent-cache-configured
runtime, and the incremental tensorize cache (serve/cache.py). Requests
arrive as canonical flag lists over a unix socket (serve/protocol.py)
and run through the very same ``cli.run`` the stateless path uses — the
response relays its stdout/stderr/exit code verbatim, so the
``kafka-reassign-partitions.sh`` contract and the outer loop are
unchanged.

Structure:

- an accept loop (one thread per connection) that answers ``hello``
  liveness handshakes immediately and enqueues ``plan`` requests;
- ONE dispatcher (:class:`Coalescer`) that serializes planning — the
  device is a single resource, and serializing is also what keeps the
  process-global telemetry registry/tracer coherent per request. Each
  request runs on its own named thread (``serve-req-N``) so its spans
  render on their own track;
- request coalescing: when requests queue up concurrently, the
  dispatcher probes each waiting request's shape bucket (the same
  jax-free ``prefetch_hints`` arithmetic the coldstart predictor uses)
  and drains all same-bucket requests into one dispatch window — they
  share the one resident executable for that padded bucket, each still
  producing its own plan. The probe runs only under contention, so the
  common single-request case pays nothing;
- an idle-timeout shutdown, a pidfile next to the socket, and stale
  socket handling (a dead daemon's socket file is unlinked at startup;
  a live one refuses the second daemon).

Observability: daemon-lifetime counters ride into every request's
metrics as gauges (``served: true``, ``serve.requests``,
``serve.coalesced``, ``serve.cache_hits``), so a ``-metrics-json`` line
from a served invocation is attributable at a glance.
"""

from __future__ import annotations

import io
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Tuple

from kafkabalancer_tpu import __version__
from kafkabalancer_tpu.serve.protocol import (
    PROTO_VERSION,
    pidfile_path,
    read_frame,
    write_frame,
)

BucketKey = Tuple[int, int, int, bool]
LogFn = Callable[[str], None]

# a connection sitting in a queued/coalesced plan can legitimately wait
# minutes for the device; the read timeout only bounds DEAD peers
PLAN_CONNECTION_TIMEOUT_S = 7200.0


def _argv_value(argv: List[str], name: str) -> Optional[str]:
    """Last value of ``-name=value`` in a canonical argv (the client
    emits every forwarded flag in exactly that spelling)."""
    prefix = f"-{name}="
    val: Optional[str] = None
    for a in argv:
        if a.startswith(prefix):
            val = a[len(prefix):]
    return val


class PlanRequest:
    """One queued ``plan`` request plus its completion latch."""

    __slots__ = ("argv", "stdin", "done", "response", "bucket", "bucketed")

    def __init__(self, argv: List[str], stdin: Optional[str]) -> None:
        self.argv = argv
        self.stdin = stdin
        self.done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        self.bucket: Optional[BucketKey] = None
        self.bucketed = False  # probe memo (None is a valid "no bucket")


class Coalescer:
    """Serialize plan handling, draining same-bucket queue runs together.

    ``handle(req, coalesced)`` runs every request (in arrival order
    within a group); ``bucket_of(req)`` is the jax-free shape probe,
    called lazily and only when more than one request is waiting — the
    uncontended case never pays it.
    """

    def __init__(
        self,
        handle: Callable[[PlanRequest, bool], None],
        bucket_of: Callable[[PlanRequest], Optional[BucketKey]],
    ) -> None:
        self._handle = handle
        self._bucket_of = bucket_of
        self._dq: Deque[PlanRequest] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._active = 0  # requests popped but not yet completed
        self._thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True
        )
        self._thread.start()

    def busy(self) -> bool:
        """Queued or in-flight work — the daemon's idle-timeout check
        must not count a long-running plan as idleness."""
        with self._cv:
            return bool(self._dq) or self._active > 0

    def _bucket(self, req: PlanRequest) -> Optional[BucketKey]:
        if not req.bucketed:
            req.bucketed = True
            try:
                req.bucket = self._bucket_of(req)
            except Exception:
                req.bucket = None
        return req.bucket

    def submit(self, req: PlanRequest) -> Dict[str, Any]:
        with self._cv:
            if self._stop:
                return {
                    "v": PROTO_VERSION, "ok": False,
                    "error": "daemon shutting down",
                }
            self._dq.append(req)
            self._cv.notify_all()
        req.done.wait()
        return req.response or {
            "v": PROTO_VERSION, "ok": False, "error": "request dropped",
        }

    def stop(self, timeout: float = 30.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout)

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._dq and not self._stop:
                    self._cv.wait()
                if not self._dq:
                    return  # stopping, queue drained
                first = self._dq.popleft()
                self._active += 1
                contended = bool(self._dq)
            try:
                group = [first]
                if contended:
                    # the bucket probes (input read + parse) run OUTSIDE
                    # the lock: submitters must stay able to enqueue
                    # while the window is being assembled. Safe because
                    # this loop is the only consumer — a snapshotted
                    # request cannot be removed by anyone else.
                    b0 = self._bucket(first)
                    if b0 is not None:
                        with self._cv:
                            pending = list(self._dq)
                        same = [r for r in pending if self._bucket(r) == b0]
                        if same:
                            with self._cv:
                                for r in same:
                                    self._dq.remove(r)
                                self._active += len(same)
                            group.extend(same)
                for idx, req in enumerate(group):
                    try:
                        self._handle(req, idx > 0)
                    except Exception as exc:  # never wedge a waiter
                        req.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": f"{type(exc).__name__}: {exc}",
                        }
                    finally:
                        with self._cv:
                            self._active -= 1
                        req.done.set()
            except Exception:
                # group-assembly failure: the popped requests must not
                # wedge their waiters nor leak the active count
                with self._cv:
                    self._active -= sum(
                        1 for r in group if not r.done.is_set()
                    )
                for r in group:
                    if not r.done.is_set():
                        r.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": "dispatch failed",
                        }
                        r.done.set()


class Daemon:
    """The ``-serve`` daemon; see the module docstring."""

    def __init__(
        self,
        socket_path: str,
        idle_timeout: float = 900.0,
        prewarm_shapes: str = "",
        log: Optional[LogFn] = None,
        warm: bool = True,
    ) -> None:
        self.socket_path = socket_path
        self.idle_timeout = idle_timeout
        self.prewarm_shapes = prewarm_shapes
        self.warm = warm
        self._log: LogFn = log or (
            lambda msg: print(msg, file=sys.stderr, flush=True)
        )
        self._stop = threading.Event()
        self._warm_done = threading.Event()
        self._lock = threading.Lock()
        self._requests = 0
        self._coalesced = 0
        self._started = time.monotonic()
        self._last_activity = time.monotonic()
        self._seq = 0
        from kafkabalancer_tpu.serve.cache import TensorizeRowCache

        self.tensorize_cache = TensorizeRowCache()
        self._coalescer: Optional[Coalescer] = None

    # -- warmup ----------------------------------------------------------
    def _warm_body(self) -> None:
        """Background startup warm: backend attach, then (optionally)
        AOT-prewarm a shape grid and pull its executables resident so
        request 1 skips even the blob load. Never raises — a warm
        failure costs latency on request 1, not availability."""
        try:
            from kafkabalancer_tpu.ops.coldstart import (
                mark_process_warm,
                warm_backend,
            )

            warm_backend()
            self._log("serve: backend warm")
            # requests in this process now skip their per-request warm
            # thread: the one-time costs it overlaps are already paid
            mark_process_warm()
            if self.prewarm_shapes:
                from kafkabalancer_tpu import prewarm

                summary = prewarm.warm_store(self.prewarm_shapes, load=True)
                self._log(f"serve: prewarm {summary}")
        except Exception as exc:
            self._log(f"serve: warmup failed: {exc!r}")
        finally:
            # the idle clock starts HERE: a long -serve-prewarm compile
            # must not count as idleness (the daemon would shut itself
            # down mid-warm before serving a single request)
            self._touch()
            self._warm_done.set()

    # -- request handling ------------------------------------------------
    def _bucket_of(self, req: PlanRequest) -> Optional[BucketKey]:
        """Jax-free shape-bucket probe of one queued request — the same
        ``prefetch_hints`` arithmetic the coldstart predictor uses, so
        two requests coalesce exactly when they would reuse one padded
        executable. None (= never coalesced) for zookeeper inputs and
        anything that fails to parse (the real run surfaces the error)."""
        if _argv_value(req.argv, "from-zk"):
            return None
        input_path = _argv_value(req.argv, "input")
        if input_path:
            with open(input_path, "r") as fh:
                text = fh.read()
        elif req.stdin is not None:
            text = req.stdin
        else:
            return None
        from kafkabalancer_tpu.codecs import get_partition_list_from_reader
        from kafkabalancer_tpu.ops.coldstart import prefetch_hints
        from kafkabalancer_tpu.utils.flags import go_atoi

        as_json = _argv_value(req.argv, "input-json") == "true"
        topics_raw = _argv_value(req.argv, "topics") or ""
        topics = [t for t in topics_raw.split(",") if len(t) >= 1]
        pl = get_partition_list_from_reader(io.StringIO(text), as_json, topics)
        brokers: Optional[List[int]] = None
        brokers_raw = _argv_value(req.argv, "broker-ids")
        if brokers_raw and brokers_raw != "auto":
            brokers = [go_atoi(b) for b in brokers_raw.split(",")]
        hints = prefetch_hints(pl, brokers)
        return (
            int(hints["P"]), int(hints["R"]), int(hints["B"]),
            bool(hints["all_allowed"]),
        )

    def _handle_plan(self, req: PlanRequest, coalesced: bool) -> None:
        from kafkabalancer_tpu import cli

        with self._lock:
            self._requests += 1
            if coalesced:
                self._coalesced += 1
            n = self._requests
            n_coal = self._coalesced
            self._seq += 1
            seq = self._seq
        cache_stats = self.tensorize_cache.stats()
        attrs: Dict[str, Any] = {
            "served": True,
            "serve.requests": float(n),
            "serve.coalesced": float(n_coal),
            "serve.cache_hits": float(cache_stats["hits"]),
        }
        i = io.StringIO(req.stdin or "")
        out, err = io.StringIO(), io.StringIO()
        rc_box: List[int] = []

        def body() -> None:
            rc_box.append(
                cli.run(
                    i, out, err, ["kafkabalancer"] + req.argv, attrs=attrs
                )
            )

        # a named thread per request: the request's telemetry spans get
        # their own track ("serve-req-N") in -stats / -trace output
        t = threading.Thread(target=body, name=f"serve-req-{seq}")
        t.start()
        t.join()
        if not rc_box:
            # cli.run raised: a daemon-side crash must NOT masquerade as
            # one of the CLI's documented exit codes — an ok:false
            # response makes the client fall back and plan in-process
            self._log(f"serve: request {seq} crashed (see traceback above)")
            req.response = {
                "v": PROTO_VERSION,
                "ok": False,
                "error": "internal error: planner thread died",
            }
            self._touch()
            return
        req.response = {
            "v": PROTO_VERSION,
            "ok": True,
            "rc": rc_box[0],
            "stdout": out.getvalue(),
            "stderr": err.getvalue(),
        }
        self._touch()

    def _hello(self) -> Dict[str, Any]:
        with self._lock:
            n, n_coal = self._requests, self._coalesced
        return {
            "v": PROTO_VERSION,
            "ok": True,
            "op": "hello",
            "pid": os.getpid(),
            "version": __version__,
            "uptime_s": round(time.monotonic() - self._started, 3),
            "requests": n,
            "coalesced": n_coal,
            "cache": self.tensorize_cache.stats(),
        }

    def _touch(self) -> None:
        self._last_activity = time.monotonic()

    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(PLAN_CONNECTION_TIMEOUT_S)
            while True:
                try:
                    msg = read_frame(conn)
                except Exception:
                    return
                if msg is None:
                    return
                if msg.get("v") != PROTO_VERSION:
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": False,
                        "error": f"protocol version {msg.get('v')!r}",
                    })
                    return
                op = msg.get("op")
                self._touch()
                if op == "hello":
                    write_frame(conn, self._hello())
                elif op == "plan":
                    argv = [str(a) for a in msg.get("argv", [])]
                    stdin = msg.get("stdin")
                    req = PlanRequest(
                        argv, str(stdin) if stdin is not None else None
                    )
                    assert self._coalescer is not None
                    write_frame(conn, self._coalescer.submit(req))
                elif op == "shutdown":
                    write_frame(conn, {"v": PROTO_VERSION, "ok": True})
                    self._stop.set()
                    return
                else:
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": False,
                        "error": f"unknown op {op!r}",
                    })
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- lifecycle -------------------------------------------------------
    def _preflight_socket(self) -> Optional[str]:
        """None when the socket path is free (stale files unlinked), an
        error string when a live daemon already owns it."""
        if not os.path.exists(self.socket_path):
            return None
        from kafkabalancer_tpu.serve import client

        hello = client.daemon_alive(self.socket_path, timeout=1.0)
        if hello is not None:
            return (
                f"daemon already running on {self.socket_path} "
                f"(pid {hello.get('pid')})"
            )
        try:
            os.unlink(self.socket_path)
            self._log(f"serve: removed stale socket {self.socket_path}")
        except OSError as exc:
            return f"cannot remove stale socket {self.socket_path}: {exc}"
        return None

    def serve_forever(self) -> int:
        """Run until shutdown/idle-timeout/signal; 0 on a clean exit,
        3 when the socket is unusable (live daemon, bind failure)."""
        err = self._preflight_socket()
        if err is not None:
            self._log(f"serve: {err}")
            return 3
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            listener.bind(self.socket_path)
        except OSError as exc:
            self._log(f"serve: cannot bind {self.socket_path}: {exc}")
            listener.close()
            return 3
        listener.listen(16)
        listener.settimeout(0.5)
        pid_path = pidfile_path(self.socket_path)
        try:
            with open(pid_path, "w") as f:
                f.write(f"{os.getpid()}\n")
        except OSError:
            pid_path = ""

        from kafkabalancer_tpu.ops.tensorize import set_row_cache

        set_row_cache(self.tensorize_cache)
        self._coalescer = Coalescer(self._handle_plan, self._bucket_of)
        if self.warm:
            threading.Thread(
                target=self._warm_body, name="serve-warm", daemon=True
            ).start()
        else:
            self._warm_done.set()

        old_handlers: List[Tuple[int, Any]] = []
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                old_handlers.append((sig, signal.getsignal(sig)))
                signal.signal(sig, lambda *_a: self._stop.set())

        self._log(
            f"serve: listening on {self.socket_path} "
            f"(pid {os.getpid()}, idle timeout "
            f"{self.idle_timeout:g}s)" if self.idle_timeout > 0 else
            f"serve: listening on {self.socket_path} (pid {os.getpid()})"
        )
        self._touch()
        try:
            while not self._stop.is_set():
                if (
                    self.idle_timeout > 0
                    and self._warm_done.is_set()
                    and not self._coalescer.busy()
                    and time.monotonic() - self._last_activity
                    > self.idle_timeout
                ):
                    self._log(
                        f"serve: idle for {self.idle_timeout:g}s, "
                        "shutting down"
                    )
                    break
                try:
                    conn, _addr = listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(
                    target=self._serve_connection,
                    args=(conn,),
                    name="serve-conn",
                    daemon=True,
                ).start()
        finally:
            listener.close()
            if self._coalescer is not None:
                self._coalescer.stop()
            set_row_cache(None)
            for sig, handler in old_handlers:
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass
            for path in (self.socket_path, pid_path):
                if path:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        with self._lock:
            n, n_coal = self._requests, self._coalesced
        cache_stats = self.tensorize_cache.stats()
        self._log(
            f"serve: exiting after {n} request"
            f"{'s' if n != 1 else ''} ({n_coal} coalesced, "
            f"{cache_stats['hits']} tensorize cache hits)"
        )
        return 0
