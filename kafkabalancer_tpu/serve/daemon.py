"""The persistent planning daemon (``-serve``).

One long-lived process owns everything a stateless invocation re-pays:
the jax import, the backend/relay attach, the deserialized AOT
executables (``ops.aot._loaded``), the persistent-cache-configured
runtime, and the incremental tensorize cache (serve/cache.py). Requests
arrive as canonical flag lists over a unix socket (serve/protocol.py)
and run through the very same ``cli.run`` the stateless path uses — the
response relays its stdout/stderr/exit code verbatim, so the
``kafka-reassign-partitions.sh`` contract and the outer loop are
unchanged.

Structure:

- an accept loop (one thread per connection) that answers ``hello``
  liveness handshakes immediately and enqueues ``plan`` requests;
- ONE dispatcher (:class:`Coalescer`) that serializes planning — the
  device is a single resource, and serializing is also what keeps the
  process-global telemetry registry/tracer coherent per request. Each
  request runs on its own named thread (``serve-req-N``) so its spans
  render on their own track;
- request coalescing: when requests queue up concurrently, the
  dispatcher probes each waiting request's shape bucket (the same
  jax-free ``prefetch_hints`` arithmetic the coldstart predictor uses)
  and drains all same-bucket requests into one dispatch window — they
  share the one resident executable for that padded bucket, each still
  producing its own plan. The probe runs only under contention, so the
  common single-request case pays nothing;
- an idle-timeout shutdown, a pidfile next to the socket, and stale
  socket handling (a dead daemon's socket file is unlinked at startup;
  a live one refuses the second daemon).

Observability: daemon-lifetime counters ride into every request's
metrics as gauges (``served: true``, ``serve.requests``,
``serve.coalesced``, ``serve.cache_hits``), so a ``-metrics-json`` line
from a served invocation is attributable at a glance. Fusion/residency
gauges are RE-SNAPSHOTTED at export time (the ``refresh_attrs`` seam in
cli.run) so a request's own fused dispatch shows in its own line.
Beyond per-request attribution the daemon records ALWAYS-ON live
telemetry: every span site feeds the tracer's observer hook
(obs/trace.py) into streaming per-phase histograms (obs/hist.py —
``serve.phase.*``, ``serve.request_s``) and the bounded flight recorder
(obs/flight.py), scraped live through the ``stats`` / ``dump-trace``
protocol ops WITHOUT touching the plan dispatcher, and auto-dumped on
daemon-side crashes or requests over ``-serve-slow-ms``
(docs/observability.md).
"""

from __future__ import annotations

import contextlib
import io
import os
import signal
import socket
import sys
import threading
import time
from collections import deque
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterator,
    List,
    Optional,
    Tuple,
)

from kafkabalancer_tpu import __version__, obs
from kafkabalancer_tpu.obs.edge import FOOTER_SPAN_CAP
from kafkabalancer_tpu.obs.flight import PHASE_OF_SPAN, FlightRecorder
from kafkabalancer_tpu.obs.hist import OTHER_LABEL
from kafkabalancer_tpu.obs.trace import Span
from kafkabalancer_tpu.serve import faults
from kafkabalancer_tpu.serve import speculate as spec_mod
from kafkabalancer_tpu.serve import spill as spill_mod
from kafkabalancer_tpu.serve.admission import AdmissionController
from kafkabalancer_tpu.serve.devmem import device_memory_stats
from kafkabalancer_tpu.serve.protocol import (
    PROTO_V2,
    PROTO_VERSION,
    STATS_SCHEMA,
    pidfile_path,
    read_frame,
    read_frame2,
    write_frame,
    write_frame2,
)

BucketKey = Tuple[int, int, int, bool]
LogFn = Callable[[str], None]

# a connection sitting in a queued/coalesced plan can legitimately wait
# minutes for the device; the read timeout only bounds DEAD peers
PLAN_CONNECTION_TIMEOUT_S = 7200.0

# a plan arriving during startup waits for the dispatcher (built on the
# warm thread — lane resolution performs the backend attach); far past
# this the warm thread is presumed wedged and the request is refused
DISPATCHER_WAIT_S = 600.0

# the per-tenant label families the daemon feeds (obs.metrics registry,
# bounded top-K + "other"); created at startup so the configured
# tenant cap applies before the first observation
_TENANT_HIST_FAMILIES = (
    "serve.request_s", "serve.phase.queue", "serve.edge_ms",
)
_TENANT_COUNTER_FAMILIES = (
    "serve.requests", "serve.crashed_requests", "serve.delta_hits",
    "serve.resyncs_rows", "serve.resyncs_full", "serve.fallbacks",
    "serve.sheds", "serve.restores", "serve.spec.hits",
)


def _deadline_of(hdr: Dict[str, Any]) -> Optional[float]:
    """A request header's ``deadline_ms`` budget as an absolute
    monotonic deadline (None when absent/invalid — no deadline)."""
    ms = hdr.get("deadline_ms")
    if isinstance(ms, bool) or not isinstance(ms, (int, float)) or ms <= 0:
        return None
    return time.monotonic() + float(ms) / 1000.0


def _argv_value(argv: List[str], name: str) -> Optional[str]:
    """Last value of ``-name=value`` in a canonical argv (the client
    emits every forwarded flag in exactly that spelling)."""
    prefix = f"-{name}="
    val: Optional[str] = None
    for a in argv:
        if a.startswith(prefix):
            val = a[len(prefix):]
    return val


def _argv_brokers(argv: List[str]) -> Optional[List[int]]:
    """The ``-broker-ids`` list of a canonical argv (None = auto) —
    the ONE parse shared by the bucket probe (via ``_parse_request``)
    and the session bucket memoization, so the two can never drift."""
    from kafkabalancer_tpu.utils.flags import go_atoi

    raw = _argv_value(argv, "broker-ids")
    if not raw or raw == "auto":
        return None
    return [go_atoi(b) for b in raw.split(",")]


class PlanRequest:
    """One queued ``plan`` request plus its completion latch."""

    __slots__ = (
        "argv", "stdin", "done", "response", "bucket", "bucketed", "staged",
        "mb_entered", "t_submit", "session_ctx", "tenant", "deadline",
        "started", "internal", "trace",
    )

    def __init__(
        self,
        argv: List[str],
        stdin: Optional[str],
        tenant: str = "",
        deadline: Optional[float] = None,
    ) -> None:
        self.argv = argv
        self.stdin = stdin
        self.done = threading.Event()
        self.response: Optional[Dict[str, Any]] = None
        self.bucket: Optional[BucketKey] = None
        self.bucketed = False  # probe memo (None is a valid "no bucket")
        self.staged = False  # lane pipelining: host-encode stage fired
        self.mb_entered = False  # joined its microbatch barrier
        self.t_submit: Optional[float] = None  # queue-wait hist anchor
        # resident-session context (serve/sessions.py
        # PlanSessionContext) for the protocol-v2 session ops
        self.session_ctx: Optional[Any] = None
        # telemetry attribution label (the v2 session identity, or the
        # plan header's "tenant"); "" lands in the scrape's "other"
        # rollup — never a correctness input, only an attribution key
        self.tenant = tenant
        # absolute monotonic deadline from the client's ``deadline_ms``
        # budget; QUEUED requests past it are shed (serve/admission.py),
        # in-flight ones always run to completion
        self.deadline = deadline
        # _handle_plan entered (the ``requests`` counter includes it):
        # the health monitor's ``abandoned`` accounting counts only
        # requests that never began handling, so the conservation
        # identity admitted == requests + abandoned cannot double-count
        # a wedged-mid-handling request
        self.started = False
        # daemon-internal work (serve/speculate.py): "spec" for a
        # speculative plan-ahead, "watch" for a watch-mode re-plan,
        # None for real client traffic. Internal requests never touch
        # the idle clock, serve.requests/request_s, admission feedback,
        # the flight request log or the `abandoned` identity — they
        # carry their own serve.spec.*/serve.watch.* telemetry
        self.internal: Optional[str] = None
        # the client's trace context from the v2 header ("trace" key:
        # id / parent / pre-send client phases / edge_pre_ms / rtt_ns,
        # serve/protocol.py § End-to-end tracing); None on v1 frames
        # and trace-less clients. Pure telemetry — never a correctness
        # input, like `tenant`.
        self.trace: Optional[Dict[str, Any]] = None


class Coalescer:
    """Serialize plan handling, draining same-bucket queue runs together.

    ``handle(req, coalesced)`` runs every request (in arrival order
    within a group); ``bucket_of(req)`` is the jax-free shape probe,
    called lazily and only when more than one request is waiting — the
    uncontended case never pays it.
    """

    def __init__(
        self,
        handle: Callable[[PlanRequest, bool], None],
        bucket_of: Callable[[PlanRequest], Optional[BucketKey]],
    ) -> None:
        self._handle = handle
        self._bucket_of = bucket_of
        self._dq: Deque[PlanRequest] = deque()
        self._cv = threading.Condition()
        self._stop = False
        self._active = 0  # requests popped but not yet completed
        # the popped-but-unfinished group, for health_tick: a dispatch
        # thread dying mid-group must not leave its waiters blocked
        self._current: List[PlanRequest] = []
        self.quarantines = 0
        self.recoveries = 0
        self.abandoned = 0
        self._thread = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True
        )
        self._thread.start()

    def busy(self) -> bool:
        """Queued or in-flight work — the daemon's idle-timeout check
        must not count a long-running plan as idleness."""
        with self._cv:
            return bool(self._dq) or self._active > 0

    def health_stats(self) -> Dict[str, Any]:
        """The single-lane half of the scrape's ``lane_health`` block
        (the Coalescer has no per-lane watchdog; its one failure mode
        is dispatch-thread death, recovered by :meth:`health_tick`)."""
        with self._cv:
            return {
                "watchdog_s": 0.0,
                "quarantined": [],
                "quarantines": self.quarantines,
                "requeues": 0,
                "recoveries": self.recoveries,
                "abandoned": self.abandoned,
            }

    def health_tick(
        self, log: Optional[LogFn] = None
    ) -> None:
        """Detect and recover a dead dispatch thread: queued requests
        are answered with a structured error (their submitters would
        otherwise block forever) and a fresh loop thread takes over."""
        if self._thread.is_alive() or self._stop:
            return
        with self._cv:
            if self._stop:
                return
            pending = list(self._current) + list(self._dq)
            self._current = []
            self._dq.clear()
            self._active = 0
            self.quarantines += 1
        flushed = 0
        for r in pending:
            if not r.done.is_set():
                r.response = {
                    "v": PROTO_VERSION, "ok": False,
                    "error": "dispatcher died; request abandoned",
                }
                r.done.set()
                # internal (speculative/watch) requests never passed
                # admission, so counting them here would break the
                # admitted == requests + abandoned identity
                if getattr(r, "internal", None) is None:
                    flushed += 1
        with self._cv:
            self.abandoned += flushed
        t = threading.Thread(
            target=self._loop, name="serve-dispatch", daemon=True
        )
        try:
            t.start()
        except Exception:
            return  # no thread to spare; retried next tick
        with self._cv:
            self._thread = t
            self.recoveries += 1
        if log is not None:
            log(
                "serve: dispatch thread died — restarted "
                f"({len(pending)} queued requests answered with errors)"
            )
        obs.metrics.event("serve_dispatcher_restarted", flushed=len(pending))

    def _bucket(self, req: PlanRequest) -> Optional[BucketKey]:
        from kafkabalancer_tpu.serve.lanes import probe_bucket

        return probe_bucket(req, self._bucket_of)

    def submit(self, req: PlanRequest) -> Dict[str, Any]:
        with self._cv:
            if self._stop:
                return {
                    "v": PROTO_VERSION, "ok": False,
                    "error": "daemon shutting down",
                }
            self._dq.append(req)
            self._cv.notify_all()
        req.done.wait()
        return req.response or {
            "v": PROTO_VERSION, "ok": False, "error": "request dropped",
        }

    def stop(self, timeout: float = 30.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        self._thread.join(timeout)

    # thread-role: request
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._dq and not self._stop:
                    self._cv.wait()
                if not self._dq:
                    return  # stopping, queue drained
                first = self._dq.popleft()
                self._active += 1
                contended = bool(self._dq)
                group = [first]
                # alias, not copy: group extensions below stay visible
                # to health_tick's flush
                self._current = group
            try:
                if contended:
                    # the bucket probes (input read + parse) run OUTSIDE
                    # the lock: submitters must stay able to enqueue
                    # while the window is being assembled. Safe because
                    # this loop is the only consumer — a snapshotted
                    # request cannot be removed by anyone else.
                    b0 = self._bucket(first)
                    if b0 is not None:
                        with self._cv:
                            pending = list(self._dq)
                        same = [r for r in pending if self._bucket(r) == b0]
                        if same:
                            with self._cv:
                                for r in same:
                                    self._dq.remove(r)
                                self._active += len(same)
                            group.extend(same)
                for idx, req in enumerate(group):
                    try:
                        self._handle(req, idx > 0)
                    except Exception as exc:  # never wedge a waiter
                        req.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": f"{type(exc).__name__}: {exc}",
                        }
                    finally:
                        with self._cv:
                            self._active -= 1
                        req.done.set()
                with self._cv:
                    self._current = []
            except Exception:
                # group-assembly failure: the popped requests must not
                # wedge their waiters nor leak the active count
                with self._cv:
                    self._active -= sum(
                        1 for r in group if not r.done.is_set()
                    )
                    self._current = []
                for r in group:
                    if not r.done.is_set():
                        r.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": "dispatch failed",
                        }
                        r.done.set()


class Daemon:
    """The ``-serve`` daemon; see the module docstring."""

    def __init__(
        self,
        socket_path: str,
        idle_timeout: float = 900.0,
        prewarm_shapes: str = "",
        log: Optional[LogFn] = None,
        warm: bool = True,
        lanes: int = 1,
        microbatch: int = 1,
        batch_mode: str = "continuous",
        admission_hold: int = 0,
        slow_ms: float = 0.0,
        flight_dir: str = "",
        session_cap: int = 64,
        session_idle_s: float = 3600.0,
        tenant_cap: int = 32,
        max_queue: int = 256,
        tenant_inflight: int = 64,
        watchdog_s: float = 120.0,
        faults_spec: str = "",
        spill_dir: str = "",
        warm_cap_mb: float = 256.0,
        speculate: bool = False,
        watch_conn: str = "",
        watch_emit: str = "",
        watch_poll: float = 5.0,
        watch_argv: Optional[List[str]] = None,
    ) -> None:
        self.socket_path = socket_path
        self.idle_timeout = idle_timeout
        self.prewarm_shapes = prewarm_shapes
        self.warm = warm
        # slow_ms: a served request slower than this (milliseconds)
        # auto-dumps the flight recorder (0 disables); flight_dir
        # overrides the dump directory (default: the system tempdir)
        self.slow_ms = max(0.0, slow_ms)
        self.flight_dir = flight_dir
        self.flight = FlightRecorder()
        # lanes: 1 = today's single-lane Coalescer, byte for byte (and no
        # jax import before the warm thread); 0/negative = one lane per
        # visible device; N>1 = min(N, devices). microbatch: MAX
        # OCCUPANCY of one fused device dispatch (1 disables fusion).
        # batch_mode: "continuous" re-forms the fused batch at every
        # solver chunk round (mid-flight admission, variable-K padded
        # dispatch); "oneshot" keeps the fixed-membership barrier (the
        # measured control). admission_hold: deterministic batch forming
        # — a lane holds its pop until this many admission-predicted
        # requests are queued or the hold window expires (0 disables).
        self.lanes = lanes
        self.microbatch = max(1, microbatch)
        self.batch_mode = batch_mode
        self.admission_hold = max(0, admission_hold)
        self._log: LogFn = log or (
            lambda msg: print(msg, file=sys.stderr, flush=True)
        )
        self._stop = threading.Event()
        self._warm_done = threading.Event()
        self._lock = threading.Lock()
        self._requests = 0
        self._coalesced = 0
        self._inflight = 0
        # daemon-lifetime outcome counters: the registry counters of the
        # same names are wiped by the next request's begin_invocation in
        # single-lane (per-invocation-epoch) mode, so the scrape reads
        # THESE, never the registry
        self._slow = 0
        self._crashed = 0
        self._started = time.monotonic()
        self._last_activity = time.monotonic()
        self._seq = 0
        from kafkabalancer_tpu.serve.cache import TensorizeRowCache
        from kafkabalancer_tpu.serve.sessions import SessionStore

        self.tensorize_cache = TensorizeRowCache()
        # the warm session tier (serve/spill.py): evicted/expired/
        # flushed sessions spill to versioned checksummed records under
        # spill_dir (empty = tier disabled, the pre-durability shape);
        # the store itself is opened in serve_forever — opening claims
        # the directory, and a CONSTRUCTED-but-never-served daemon must
        # not leave pidfiles behind
        self.spill_dir = spill_dir
        self.warm_cap_mb = warm_cap_mb
        self.spill: Optional[Any] = None
        # resident cluster sessions (protocol v2; serve/sessions.py):
        # LRU-capped per-tenant parsed/settled state + primed row cache
        self.sessions = SessionStore(cap=session_cap, idle_s=session_idle_s)
        # speculative plan-ahead (serve/speculate.py): the idle-priority
        # worker that plans request N+1 on the resident session and
        # memoizes the answer; the store retires memos through it so
        # the speculation block's conservation identity stays exact.
        # Always constructed (the scrape block exists with the feature
        # off); its worker thread starts in serve_forever.
        self.speculator = spec_mod.Speculator(self, enabled=speculate)
        self.sessions.spec = self.speculator
        # watch mode (serve/speculate.py ZkWatcher): the daemon itself
        # subscribes to Zookeeper and streams plans to watch_emit — no
        # client process in the steady state
        self.watch_conn = watch_conn
        self.watch_emit = watch_emit
        self.watch_poll = watch_poll
        self.watch_argv = list(watch_argv) if watch_argv else None
        self.watcher: Optional[spec_mod.ZkWatcher] = None
        # per-tenant telemetry label bound: top-K tenants by recent
        # activity keep individual hists/counters, the rest roll into
        # "other" (obs/hist.py HistFamily) — a million-tenant fleet
        # cannot grow the scrape payload or daemon memory unboundedly
        self.tenant_cap = max(1, tenant_cap)
        # daemon-observed client fallback/resync reasons, scraped as
        # the stats doc's "fallbacks" block (satellite: a degraded
        # fleet is diagnosable without log archaeology)
        self._fallbacks: Dict[str, int] = {}
        self._coalescer: Optional[Any] = None
        self._dispatcher_ready = threading.Event()
        self._lanes: "List[Any]" = []
        # request-thread -> lane map for the span-driven heartbeat:
        # every span completing on a serve-req thread beats its lane,
        # so a legitimately slow plan (chunk rounds, phase spans keep
        # completing) never reads as a wedged lane — only a call that
        # produces NO observable progress past -serve-watchdog does
        self._thread_lanes: Dict[str, Any] = {}
        # overload protection (serve/admission.py): per-tenant fair
        # queueing + caps in FRONT of whichever dispatcher gets built.
        # The window starts sized for the single-lane case and is
        # re-sized once lane resolution knows the device count; the
        # admission-hold depth must fit inside it (a held batch needs
        # that many requests queued on the lane simultaneously)
        self.watchdog_s = max(0.0, watchdog_s)
        self.faults_spec = faults_spec
        self._admission = AdmissionController(
            window=max(4, 2 * self.microbatch, self.admission_hold),
            max_queue=max_queue,
            tenant_inflight=tenant_inflight,
            parallel=1,
        )
        # every real plan-family ARRIVAL (admitted or shed) preempts
        # any in-flight speculative dispatch (serve/speculate.py):
        # idle plan-ahead work must never cost live traffic its p95
        self._admission.on_arrival = self.speculator.note_real_traffic

    # -- warmup ----------------------------------------------------------
    # thread-role: warm
    def _warm_body(self) -> None:
        """Background startup warm: dispatcher construction FIRST (lane
        resolution performs the jax import + device query — the backend
        attach this thread exists to overlap; the accept loop answers
        hello immediately while it runs), then the backend warm and
        (optionally) an AOT-prewarm of a shape grid whose executables
        are pulled resident so request 1 skips even the blob load.
        Never raises — a warm failure costs latency on request 1, not
        availability."""
        try:
            self._coalescer = self._make_dispatcher()
        except Exception as exc:
            # a broken backend must not cost availability: fall back to
            # the single-lane dispatcher (no jax needed), with every
            # lane-mode side effect undone
            self._log(f"serve: dispatcher init failed ({exc!r}); 1 lane")
            from kafkabalancer_tpu.ops.tensorize import set_row_cache

            obs.set_shared_registry(False)
            self._lanes = []
            set_row_cache(self.tensorize_cache)
            self._coalescer = Coalescer(self._handle_plan, self._bucket_of)
        finally:
            self._dispatcher_ready.set()
        try:
            from kafkabalancer_tpu.ops.coldstart import (
                mark_process_warm,
                warm_backend,
            )

            warm_backend()
            self._log("serve: backend warm")
            # requests in this process now skip their per-request warm
            # thread: the one-time costs it overlaps are already paid
            mark_process_warm()
            if self.prewarm_shapes:
                from kafkabalancer_tpu import prewarm

                summary = prewarm.warm_store(self.prewarm_shapes, load=True)
                self._log(f"serve: prewarm {summary}")
                if self._lanes:
                    # lane-pinned residency: resident keys carry the
                    # execution device, so the unpinned load above is
                    # invisible to the lanes — re-load each grid entry
                    # under every lane's pin (store hits: deserialize
                    # only, off the request path) so request 1 PER LANE
                    # skips the blob load too
                    from kafkabalancer_tpu.ops import aot

                    for lane in self._lanes:
                        if lane.device is None:
                            continue
                        try:
                            aot.set_execution_device(lane.device)
                            prewarm.warm_store(
                                self.prewarm_shapes, load=True
                            )
                        finally:
                            aot.set_execution_device(None)
                    self._log(
                        "serve: prewarm resident on "
                        f"{len(self._lanes)} lanes"
                    )
        except Exception as exc:
            self._log(f"serve: warmup failed: {exc!r}")
        finally:
            # the idle clock starts HERE: a long -serve-prewarm compile
            # must not count as idleness (the daemon would shut itself
            # down mid-warm before serving a single request)
            self._touch()
            self._warm_done.set()

    # -- live telemetry ---------------------------------------------------
    def _observe_span(self, sp: Span) -> None:
        """The tracer's always-on observer (obs/trace.py): every
        completed span — tracing flags or not — lands in the flight
        recorder ring, and phase-chain spans feed the streaming
        per-phase histograms. Cheap by construction: one ring append +
        at most one histogram observation, no locks shared with the
        dispatcher."""
        t1 = sp.t1_ns if sp.t1_ns is not None else sp.t0_ns
        self.flight.note_span(
            sp.name, sp.t0_ns, t1, sp.thread_name, sp.tid, sp.attrs
        )
        lane = self._thread_lanes.get(sp.thread_name)
        if lane is not None:
            # watchdog heartbeat: observable request progress on this
            # lane (one dict get + a float store per span)
            lane.last_beat = time.monotonic()
        phase = PHASE_OF_SPAN.get(sp.name)
        if phase is not None and not sp.thread_name.startswith(
            "serve-int-"
        ):
            # internal (speculative/watch) runs keep the lane heartbeat
            # above but stay out of the serve.phase.* histograms — the
            # per-phase breakdowns must describe real traffic only
            obs.metrics.hist_observe(
                f"serve.phase.{phase}", (t1 - sp.t0_ns) / 1e9
            )

    # -- request handling ------------------------------------------------
    def _parse_request(
        self, req: PlanRequest
    ) -> "Optional[Tuple[Any, Optional[List[int]]]]":
        """Parse one queued request's input the way the real run will
        (reader + -input-json + -topics + -broker-ids semantics) — the
        ONE request-argv parse shared by the bucket probe and the lane
        stage hook, so the two cannot drift. Returns ``(partition_list,
        brokers)`` or None (zookeeper input / nothing to read — the
        real run surfaces any error)."""
        if _argv_value(req.argv, "from-zk"):
            return None
        input_path = _argv_value(req.argv, "input")
        if input_path:
            with open(input_path, "r") as fh:
                text = fh.read()
        elif req.stdin is not None:
            text = req.stdin
        else:
            return None
        from kafkabalancer_tpu.codecs import get_partition_list_from_reader

        as_json = _argv_value(req.argv, "input-json") == "true"
        topics_raw = _argv_value(req.argv, "topics") or ""
        topics = [t for t in topics_raw.split(",") if len(t) >= 1]
        pl = get_partition_list_from_reader(io.StringIO(text), as_json, topics)
        return pl, _argv_brokers(req.argv)

    def _count_resync_full(self, tenant: str) -> None:
        """One full re-sync landed for ``tenant``: the store's global
        monotone counter plus the tenant family (session thrash is a
        per-tenant signal — the replay artifact's thrash rate)."""
        self.sessions.count_resync_full()
        obs.metrics.tenant_count(
            "serve.resyncs_full", tenant or OTHER_LABEL
        )

    def _count_fallback(self, reason: str, tenant: str = "") -> None:
        with self._lock:
            self._fallbacks[reason] = self._fallbacks.get(reason, 0) + 1
        # tenant attribution rides the bounded label family: which
        # tenant is eating the fallback budget (untenanted reasons —
        # bad frames, version skew — roll up under "other")
        obs.metrics.tenant_count(
            "serve.fallbacks", tenant or OTHER_LABEL
        )

    def _bucket_of(self, req: PlanRequest) -> Optional[BucketKey]:
        """Jax-free shape-bucket probe of one queued request — the same
        ``prefetch_hints`` arithmetic the coldstart predictor uses, so
        two requests coalesce exactly when they would reuse one padded
        executable. None (= never coalesced) for zookeeper inputs and
        anything that fails to parse (the real run surfaces the error).
        Resident-session requests carry no input text; their bucket is
        the session's memoized one (computed once, after its first
        request)."""
        ctx = req.session_ctx
        if ctx is not None:
            bucket: Optional[BucketKey] = ctx.session.bucket
            if bucket is not None or req.stdin is None:
                return bucket
        parsed = self._parse_request(req)
        if parsed is None:
            return None
        pl, brokers = parsed
        from kafkabalancer_tpu.ops.coldstart import prefetch_hints

        hints = prefetch_hints(pl, brokers)
        return (
            int(hints["P"]), int(hints["R"]), int(hints["B"]),
            bool(hints["all_allowed"]),
        )

    def _handle_plan(
        self,
        req: PlanRequest,
        coalesced: bool,
        lane: Optional[Any] = None,
        mb: Optional[Any] = None,
    ) -> None:
        from kafkabalancer_tpu import cli

        # handling BEGINS here (before any injected wedge): a request
        # the watchdog later abandons mid-handling still lands in the
        # requests counter when it resumes, never in `abandoned`
        req.started = True
        internal = req.internal
        if internal == "spec" and (
            self.speculator.preempted() or self._admission.busy()
        ):
            # abort-before-start: real traffic arrived while the
            # speculative request sat queued — defer, never delay a
            # live request behind idle work (the speculator counts the
            # non-ok response as aborted)
            req.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": "speculation deferred (real traffic waiting)",
            }
            return
        # chaos seam (serve/faults.py; inert unless -serve-faults armed):
        # a scheduled dispatch_delay sleeps HERE — observable by the
        # lane watchdog exactly like a wedged host call
        faults.fire("dispatch_delay")
        t_start = time.perf_counter()
        tenant_label = req.tenant or OTHER_LABEL
        if req.t_submit is not None and internal is None:
            # queue wait: accept-thread submit to dispatcher pickup —
            # global hist AND the tenant family (who waits behind whom)
            queue_s = t_start - req.t_submit
            obs.metrics.hist_observe("serve.phase.queue", queue_s)
            obs.metrics.tenant_hist_observe(
                "serve.phase.queue", tenant_label, queue_s
            )
        with self._lock:
            if internal is None:
                # internal (speculative/watch) work is NOT a request:
                # serve.requests stays the real-traffic truth and the
                # admitted == requests + abandoned identity holds
                self._requests += 1
                if coalesced:
                    self._coalesced += 1
            n = self._requests
            n_coal = self._coalesced
            self._seq += 1
            seq = self._seq
        attrs: Dict[str, Any] = {
            "served": True,
            "serve.requests": float(n),
            "serve.coalesced": float(n_coal),
        }
        trace = req.trace if isinstance(req.trace, dict) else None
        if trace is not None and internal is None:
            # the client's trace context rides INTO the daemon-written
            # -metrics-json line: one causal record per invocation —
            # the trace id plus the client's pre-send edge phases as
            # client.phase.* gauges (obs/edge.py glossary). The tenant
            # serve.edge_ms family attributes client+network overhead
            # (pre-send phases + measured RTT) per label in the scrape.
            tid_hex = str(trace.get("id") or "")
            if tid_hex:
                attrs["trace_id"] = tid_hex
            cphases = trace.get("phases")
            if isinstance(cphases, dict):
                for key, val in sorted(cphases.items()):
                    if isinstance(val, (int, float)) and not isinstance(
                        val, bool
                    ):
                        attrs[f"client.phase.{key}"] = round(
                            float(val), 6
                        )
            edge_pre = trace.get("edge_pre_ms")
            if isinstance(edge_pre, (int, float)) and not isinstance(
                edge_pre, bool
            ):
                total_ms = float(edge_pre)
                rtt_ns = trace.get("rtt_ns")
                if isinstance(rtt_ns, int) and rtt_ns > 0:
                    total_ms += rtt_ns / 1e6
                attrs["client.edge_pre_ms"] = round(total_ms, 3)
                obs.metrics.tenant_hist_observe(
                    "serve.edge_ms", tenant_label, total_ms
                )
            ech = trace.get("edge_cache_hit")
            if isinstance(ech, bool):
                # the edge-residency attribution (serve/edge_cache.py):
                # True when this request's digest came from the shadow
                # cache without a client-side read+parse — the gate and
                # bench assert it so a silent full-read can't masquerade
                # as residency
                attrs["client.edge_cache_hit"] = ech
        ctx = req.session_ctx
        if req.tenant:
            # the tenant rides the request's own -metrics-json line too:
            # a served invocation's export names whose traffic it was
            attrs["serve.tenant"] = req.tenant
        if ctx is not None:
            ss = self.sessions.stats()
            attrs["serve.sessions"] = float(ss["count"])
            attrs["serve.session_bytes"] = float(ss["bytes"])
            attrs["serve.delta_hits"] = float(ss["delta_hits"])
            if getattr(ctx, "restored", False) and ctx.kind in (
                "delta", "rebuild"
            ):
                # answered from a warm spill record with NO resync —
                # the restart-recovery acceptance gauge
                # (docs/serving.md); a restored session whose digest
                # drifted takes the rows path and is a restore but not
                # a hit, matching paging.restore_hits exactly
                attrs["serve.restore_hit"] = True
            elif ctx.kind in ("delta", "rebuild"):
                attrs["serve.delta_hit"] = True
        sched = self._coalescer
        if lane is not None and hasattr(sched, "stats"):
            s = sched.stats()
            attrs.update({
                "serve.lanes": s["lanes"],
                "serve.lane": float(lane.index),
                "serve.lane_busy_s": s["lane_busy_s"],
                "serve.steals": s["steals"],
                "serve.microbatched": s["microbatched"],
                "serve.mb_occupancy_max": s["occupancy_max"],
                "serve.mb_padded_slots": s["padded_slots"],
                "serve.residency_hits": s["residency_hits"],
                "serve.cache_hits": s["cache_hits"],
                "serve.residency_bytes": float(
                    lane.stage_cache.device_bytes()
                ),
            })
            hbm0 = (
                device_memory_stats(lane.device)
                if lane.device is not None else None
            )
            if hbm0 is not None and "bytes_in_use" in hbm0:
                attrs["serve.hbm_bytes_in_use"] = float(
                    hbm0["bytes_in_use"]
                )
        else:
            attrs["serve.lanes"] = 1.0
            attrs["serve.residency_hits"] = 0.0
            attrs["serve.cache_hits"] = float(
                self.tensorize_cache.stats()["hits"]
                + self.sessions.cache_stats()["hits"]
            )

        def refresh() -> Dict[str, Any]:
            # the PR-6 gap, fixed: scheduler gauges were snapshotted at
            # request START, so a request's own fusion never showed in
            # its own -metrics-json line. cli.run calls this at export
            # time (after the fused dispatch committed — the batcher's
            # sink runs before member responses release), so the
            # re-snapshot includes it.
            sched2 = self._coalescer
            if lane is None or not hasattr(sched2, "stats"):
                return {}
            s2 = sched2.stats()
            out2 = {
                "serve.mb_occupancy_max": s2["occupancy_max"],
                "serve.mb_padded_slots": s2["padded_slots"],
                "serve.residency_hits": s2["residency_hits"],
                "serve.residency_bytes": float(
                    lane.stage_cache.device_bytes()
                ),
            }
            hbm2 = (
                device_memory_stats(lane.device)
                if lane.device is not None else None
            )
            if hbm2 is not None and "bytes_in_use" in hbm2:
                out2["serve.hbm_bytes_in_use"] = float(
                    hbm2["bytes_in_use"]
                )
            return out2

        i = io.StringIO(req.stdin or "")
        out, err = io.StringIO(), io.StringIO()
        rc_box: List[int] = []

        def body() -> None:  # thread-role: request
            import contextlib

            # chaos seam: a scheduled transfer_fail raises before the
            # device work — the request crashes server-side and is
            # answered with a structured error, never a wrong plan
            faults.fire("transfer_fail")
            with contextlib.ExitStack() as st:
                if internal == "spec":
                    # the cooperative preemption hook: checked per
                    # solver chunk round and per applied move; a raise
                    # unwinds the whole run (caught below)
                    spec_mod.install_abort_check(
                        self.speculator.maybe_abort
                    )
                    st.callback(spec_mod.install_abort_check, None)
                if lane is not None:
                    st.enter_context(lane.context())
                if ctx is not None:
                    # session activation AFTER the lane context: the
                    # session's trusted-delta row cache overrides the
                    # lane's, and the mutation tap mirrors every
                    # applied move into the session's raw shadow
                    st.enter_context(ctx.activate())
                if mb is not None:
                    st.enter_context(mb.member(req))
                try:
                    rc_box.append(
                        cli.run(
                            i, out, err, ["kafkabalancer"] + req.argv,
                            attrs=attrs,
                            refresh_attrs=(
                                refresh if lane is not None else None
                            ),
                            session=ctx,
                        )
                    )
                except spec_mod.SpeculationAborted:
                    # a preempted speculative run: no rc, no traceback
                    # noise — the empty rc_box reads as a non-ok
                    # response and the speculator counts it aborted
                    pass

        # a named thread per request: the request's telemetry spans get
        # their own track ("serve-req-N"; internal speculative/watch
        # work runs as "serve-int-N" so the phase histograms can skip
        # it) in -stats / -trace output, and the flight recorder
        # attributes phase spans to it by name
        thread_name = (
            f"serve-int-{seq}" if internal is not None
            else f"serve-req-{seq}"
        )
        t = threading.Thread(target=body, name=thread_name)
        if lane is not None:
            self._thread_lanes[thread_name] = lane
        try:
            t.start()
            t.join()
            rc: Optional[int] = rc_box[0] if rc_box else None
            if rc is None:
                # cli.run raised: a daemon-side crash must NOT
                # masquerade as one of the CLI's documented exit codes —
                # an ok:false response makes the client fall back and
                # plan in-process
                if internal is None:
                    self._log(
                        f"serve: request {seq} crashed "
                        "(see traceback above)"
                    )
                if mb is not None and not req.mb_entered:
                    # the body died BEFORE joining its microbatch
                    # barrier (lane-context entry failure): release the
                    # slot, or the healthy peers stall at the barrier
                    # until its timeout
                    mb.abandon()
                req.response = {
                    "v": PROTO_VERSION,
                    "ok": False,
                    "error": (
                        "speculation aborted" if internal == "spec"
                        else "internal error: planner thread died"
                    ),
                }
            else:
                req.response = {
                    "v": PROTO_VERSION,
                    "ok": True,
                    "rc": rc,
                    "stdout": out.getvalue(),
                    "stderr": err.getvalue(),
                }
            if internal is None:
                # internal work must not reset the idle clock: a daemon
                # that is only speculating (or watch-ticking) still
                # honors -serve-idle-timeout (the PR-12 hello/scrape
                # rule extended)
                self._touch()
        finally:
            # the flight-recorder request summary + the reconciliation
            # histogram: EVERY _handle_plan call (crash paths included)
            # lands exactly one serve.request_s observation, so a
            # post-traffic scrape's hist count equals serve.requests
            wall = time.perf_counter() - t_start
            if internal is None:
                obs.metrics.hist_observe("serve.request_s", wall)
                # feed the admission layer's retry-after estimate
                self._admission.note_service(wall)
                # the tenant dimension: same invariant per label —
                # every _handle_plan call lands exactly one
                # serve.request_s family observation and one
                # serve.requests count, so a replay driver's per-tenant
                # issued counts reconcile EXACTLY against the scrape
                # (kafkabalancer_tpu/replay/)
                obs.metrics.tenant_hist_observe(
                    "serve.request_s", tenant_label, wall
                )
                obs.metrics.tenant_count("serve.requests", tenant_label)
            else:
                # speculative/watch work carries its OWN wall hist —
                # never serve.request_s (its count must equal
                # serve.requests exactly) and never the retry-after
                # EWMA (idle work must not skew overload estimates)
                obs.metrics.hist_observe(f"serve.{internal}.plan_s", wall)
            phases = self.flight.pop_request_phases(thread_name)
            self._thread_lanes.pop(thread_name, None)
            rc_val = rc_box[0] if rc_box else None
            if ctx is not None:
                # revert the unemitted complete-partition probe
                # applies (post-run: the output already aliased them),
                # fold the tapped mutations into the session's
                # predicted digest (or poison it on failure), refresh
                # the byte estimate, and memoize the shape bucket once
                # — the connection thread still holds the session lock
                ctx.apply_unemitted_reverts()
                ctx.session.finish(rc_val)
                if ctx.session.bucket is None and ctx.session.raw:
                    try:
                        from kafkabalancer_tpu.models.partition import (
                            PartitionList,
                        )
                        from kafkabalancer_tpu.ops.coldstart import (
                            prefetch_hints,
                        )

                        # hints run on the RAW shadow (pre-settle
                        # semantics, moves applied): the bucket must
                        # equal what the probe computes on the next
                        # request's freshly parsed input, or session
                        # requests would never coalesce with stateless
                        # same-cluster peers
                        hints = prefetch_hints(
                            PartitionList(
                                version=ctx.session.version,
                                partitions=ctx.session.raw,
                            ),
                            _argv_brokers(req.argv),
                        )
                        ctx.session.bucket = (
                            int(hints["P"]), int(hints["R"]),
                            int(hints["B"]), bool(hints["all_allowed"]),
                        )
                    except Exception:
                        pass  # bucket stays unmemoized; probe-only loss
                if self.spill is not None and internal is None:
                    # the CONTINUOUS spill: every clean session request
                    # refreshes the warm record (skipped when the
                    # digest has not moved), so a SIGKILL at any later
                    # instant loses at most the in-flight request —
                    # restart recovery works from exactly this write.
                    # One O(P) struct pack + an atomic tmp+rename per
                    # completed request; a failed write only costs
                    # durability, never the answer (write_failures).
                    # INTERNAL (speculative/watch) runs never spill:
                    # their post-run state is ahead of what the client
                    # has seen — the last real request's record is the
                    # one a restore must match (serve/sessions.py)
                    self.spill.spill(
                        (ctx.session.tenant, ctx.session.sig),
                        ctx.session,
                    )
            if internal is None:
                self.flight.record_request({
                    "req": seq,
                    "t": round(time.time(), 3),
                    "lane": lane.index if lane is not None else 0,
                    "tenant": req.tenant or None,
                    "bucket": list(req.bucket) if req.bucket else None,
                    "rc": rc_val,
                    "coalesced": coalesced,
                    "wall_s": round(wall, 6),
                    "phases": {k: round(v, 6) for k, v in sorted(
                        phases.items()
                    )},
                    # end-to-end reconciliation (replay/harness.py):
                    # every served request's flight record carries the
                    # client's trace id, exactly; None for trace-less
                    # (v1 / non-edge) clients
                    "trace": (
                        str(trace["id"])
                        if trace is not None and trace.get("id")
                        else None
                    ),
                })
            if (
                trace is not None
                and internal is None
                and isinstance(req.response, dict)
                and req.response.get("ok")
            ):
                # the reply footer: this request's bounded daemon span
                # subtree rides back for the client's merged -trace
                # timeline (serve/protocol.py § End-to-end tracing).
                # Raw perf_counter_ns stamps — the client maps them
                # through its handshake clock-offset estimate.
                req.response["trace"] = {
                    "id": trace.get("id"),
                    "wall_s": round(wall, 6),
                    "spans": self.flight.spans_for_thread(
                        thread_name, cap=FOOTER_SPAN_CAP
                    ),
                }
            if rc_val is None and internal is None:
                with self._lock:
                    self._crashed += 1
                obs.metrics.count("serve.crashed_requests")
                obs.metrics.tenant_count(
                    "serve.crashed_requests", tenant_label
                )
                self.flight.autodump(
                    f"crash-req-{seq}",
                    directory=self.flight_dir or None,
                    log=self._log,
                )
            elif (
                internal is None
                and self.slow_ms > 0
                and wall * 1000.0 >= self.slow_ms
            ):
                with self._lock:
                    self._slow += 1
                obs.metrics.count("serve.slow_requests")
                self.flight.autodump(
                    f"slow-req-{seq}",
                    directory=self.flight_dir or None,
                    log=self._log,
                )

    # -- lanes -----------------------------------------------------------
    def _resolve_lanes(self) -> int:
        """How many lanes to run: 1 stays the Coalescer (and never
        imports jax here); auto (<=0) and N>1 resolve against the
        visible device count. One visible device always degrades to 1."""
        if self.lanes == 1:
            return 1
        try:
            import jax

            ndev = len(jax.devices())
        except Exception as exc:
            self._log(f"serve: lane resolution failed ({exc!r}); 1 lane")
            return 1
        n = ndev if self.lanes <= 0 else min(self.lanes, ndev)
        return max(1, n)

    def _make_dispatcher(self) -> Any:
        """The request dispatcher: today's single-lane Coalescer when one
        lane suffices (byte-for-byte PR-4 behavior), else the multi-lane
        scheduler with per-device lanes, affinity routing, stealing and
        (with ``microbatch > 1``) cross-request fusion."""
        n_lanes = self._resolve_lanes()
        # explicit -serve-lanes=1 is the PR-4 contract pin: the plain
        # Coalescer regardless of microbatch. Auto/multi keep the lane
        # scheduler whenever it buys something (several lanes, or
        # single-lane fusion with microbatch > 1).
        if self.lanes == 1 or (n_lanes <= 1 and self.microbatch <= 1):
            from kafkabalancer_tpu.ops.tensorize import set_row_cache

            set_row_cache(self.tensorize_cache)
            return Coalescer(self._handle_plan, self._bucket_of)
        from kafkabalancer_tpu import obs
        from kafkabalancer_tpu.serve.cache import TensorizeRowCache
        from kafkabalancer_tpu.serve.lanes import Lane, LaneScheduler

        try:
            import jax

            devices = list(jax.devices())[:n_lanes]
        except Exception:
            devices = []
        self._lanes = []
        for i in range(n_lanes):
            lane = Lane(i, devices[i] if i < len(devices) else None)
            lane.row_cache = TensorizeRowCache()
            self._lanes.append(lane)
        scheduler = LaneScheduler(
            self._handle_plan,
            self._bucket_of,
            self._lanes,
            microbatch=self.microbatch,
            stage=self._stage_request,
            admissible=self._admissible_request,
            batch_mode=self.batch_mode,
            admission_hold=self.admission_hold,
            watchdog_s=self.watchdog_s,
            exclusive=self._mesh_exclusive_request,
        )
        # the admission window scales with the real lane count: each
        # lane can batch up to `microbatch` members and should have a
        # queued same-bucket feed for mid-flight admission
        self._admission.set_window(max(
            4, self.admission_hold,
            2 * self.microbatch * len(self._lanes),
        ))
        self._admission.set_parallel(len(self._lanes))
        # concurrent request bodies share the daemon-lifetime registry:
        # a per-request reset would wipe an in-flight peer's attribution.
        # Set only AFTER the scheduler constructed — a construction
        # failure falls back to the Coalescer, which must keep the
        # per-invocation metrics epochs.
        obs.set_shared_registry(True)
        self._log(
            f"serve: {n_lanes} device lane{'s' if n_lanes != 1 else ''}"
            + (
                f", {self.batch_mode} batching up to {self.microbatch}"
                if self.microbatch > 1
                else ""
            )
        )
        return scheduler

    @staticmethod
    def _admissible_request(req: PlanRequest) -> bool:
        """ADMISSION prediction: will this request's planning reach the
        fusible dispatch (the XLA fused session)? Only such requests are
        admitted into the continuous batcher (or a one-shot fusion
        group) — see LaneScheduler._run_group/_run_continuous.
        Conservative on purpose: a false negative costs a missed fusion,
        a false positive stalls the batch's live peers."""
        if req.internal is not None:
            # idle speculative/watch work must never couple its
            # lifetime to a live request's fused batch
            return False
        if _argv_value(req.argv, "fused") != "true":
            return False
        if _argv_value(req.argv, "rebalance-leader") == "true":
            return False
        engine = _argv_value(req.argv, "fused-engine") or "auto"
        return (
            engine in ("auto", "xla")
            and _argv_value(req.argv, "fused-shard") != "true"
        )

    @staticmethod
    def _mesh_exclusive_request(req: PlanRequest) -> bool:
        """MESH-EXCLUSIVE prediction: a ``-fused-shard`` plan shard_maps
        over EVERY attached device, so it must never race lane-pinned
        dispatches — the scheduler drains all lanes before running it
        and holds new dispatches until it returns
        (serve/lanes.py ``LaneScheduler._run_exclusive``). It is also
        predicted NON-admissible for continuous batching above (a
        member that owns the mesh could never fuse with lane peers)."""
        return _argv_value(req.argv, "fused-shard") == "true"

    def _stage_request(self, req: PlanRequest, lane: Any) -> None:
        """Host-encode stage of the lane pipeline (runs on the lane's
        stage thread while the device executes the request ahead): parse
        + settle + tensorize the NEXT request — priming the lane's row
        cache — and ``device_put`` its dense tensors onto the lane's
        device, digest-keyed so the dispatch reuses the transfer. Pure
        overlap: any failure or misprediction costs nothing."""
        fused = _argv_value(req.argv, "fused") == "true"
        solver = _argv_value(req.argv, "solver") or "greedy"
        if not fused and solver != "tpu":
            return  # host-only planning: nothing to stage
        parsed = self._parse_request(req)
        if parsed is None:
            return
        pl, brokers = parsed
        from kafkabalancer_tpu.models import default_rebalance_config
        from kafkabalancer_tpu.utils.flags import go_atoi

        # the config subset that shapes settle/tensorize; staging is
        # fail-open, so a flag this prediction misses costs only the
        # overlap (digest misses), never correctness
        cfg = default_rebalance_config()
        cfg.brokers = brokers
        if _argv_value(req.argv, "allow-leader") == "true":
            cfg.allow_leader_rebalancing = True
        mr = _argv_value(req.argv, "min-replicas")
        if mr is not None:
            cfg.min_replicas_for_rebalancing = go_atoi(mr)
        budget_raw = _argv_value(req.argv, "max-reassign")
        budget = go_atoi(budget_raw) if budget_raw is not None else 1
        if budget <= 0:
            return
        with lane.context():
            from kafkabalancer_tpu.ops import aot
            from kafkabalancer_tpu.ops.tensorize import tensorize
            from kafkabalancer_tpu.solvers.scan import _settle_head

            # no clear here: the request AHEAD of this one may not have
            # consumed its staged buffers yet (that is the overlap this
            # stage exists for). Consumed entries are popped at dispatch
            # (_stage_args); mispredictions are bounded by the stage
            # cap in stage_host_arrays.
            _settle_head(pl, cfg, budget)
            with obs.span("serve.stage_encode", lane=lane.index):
                dp = tensorize(pl, cfg)
            staged = aot.stage_host_arrays(
                lane.stage_cache,
                (
                    dp.replicas, dp.weights, dp.nrep_cur, dp.nrep_tgt,
                    dp.ncons, dp.allowed, dp.pvalid, dp.bvalid,
                ),
            )
        obs.metrics.count("serve.staged_requests")
        obs.metrics.gauge("serve.last_staged_arrays", float(staged))

    def _memory_snapshot(self) -> List[Dict[str, Any]]:
        """Per-lane device-memory attribution: HBM live bytes (via the
        jax-free-safe ``serve.devmem`` seam — null until the backend has
        attached, and on backends without memory introspection) plus
        the residency pool's device bytes. One entry per lane; the
        single-lane Coalescer reports lane 0 with no pool."""
        out: List[Dict[str, Any]] = []
        if self._lanes:
            for ln in self._lanes:
                # a device-less lane must not fall into the no-device
                # query (which could block on a backend attach)
                hbm = (
                    device_memory_stats(ln.device)
                    if ln.device is not None else None
                ) or {}
                out.append({
                    "lane": ln.index,
                    "hbm_bytes_in_use": hbm.get("bytes_in_use"),
                    "hbm_bytes_limit": hbm.get("bytes_limit"),
                    "residency_bytes": ln.stage_cache.device_bytes(),
                    "residency_entries": len(ln.stage_cache),
                })
        else:
            # no-device query ONLY once the backend is known-attached:
            # during the warm window jax may be imported but unattached,
            # and jax.devices() would block this (connection) thread on
            # the attach — hello must keep answering instantly
            hbm = (
                device_memory_stats() if self._warm_done.is_set() else None
            ) or {}
            out.append({
                "lane": 0,
                "hbm_bytes_in_use": hbm.get("bytes_in_use"),
                "hbm_bytes_limit": hbm.get("bytes_limit"),
                "residency_bytes": 0,
                "residency_entries": 0,
            })
        return out

    def _core_snapshot(self) -> Dict[str, Any]:
        """The ONE daemon-state snapshot both ``hello`` and ``stats``
        render from — the two scrape paths cannot drift (the satellite
        pin in tests/test_serve.py compares them key for key)."""
        with self._lock:
            n, n_coal, inflight = (
                self._requests, self._coalesced, self._inflight,
            )
            slow, crashed = self._slow, self._crashed
            fallbacks = dict(self._fallbacks)
        fault_plan = faults.active()
        # tensorize-cache attribution: the process-wide cache plus every
        # resident session's trusted-delta cache (retired sessions
        # folded in, so the counters stay monotone)
        sess_cache = self.sessions.cache_stats()
        base_cache = self.tensorize_cache.stats()
        cache = {
            k: base_cache.get(k, 0) + sess_cache.get(k, 0)
            for k in ("hits", "misses", "rows_reused")
        }
        out: Dict[str, Any] = {
            "pid": os.getpid(),
            "version": __version__,
            "uptime_s": round(time.monotonic() - self._started, 3),
            # still inside the startup warm window: a client progress
            # probe must not read "no in-flight work" as a wedge while
            # the dispatcher is still being built
            "warming": not self._warm_done.is_set(),
            "requests": n,
            "coalesced": n_coal,
            "requests_inflight": inflight,
            "slow_requests": slow,
            "crashed_requests": crashed,
            "cache": cache,
            "memory": self._memory_snapshot(),
            # resident cluster sessions (serve/sessions.py): count,
            # resident bytes, delta hits/resyncs — serve-stats/3
            "sessions": self.sessions.stats(),
            # the warm session tier (serve/spill.py; serve-stats/8):
            # spill/restore/corrupt-drop counters under the
            # conservation identity spills + adopted == restores +
            # corrupt_drops + evictions + warm_entries, plus the live
            # warm footprint; key set identical with the tier disabled
            "paging": (
                self.spill.stats() if self.spill is not None
                else spill_mod.SpillStore.disabled_stats()
            ),
            # speculative plan-ahead (serve-stats/8; serve/speculate.py)
            # under the exact identity attempts == hits + misses +
            # poisoned + memos at every scrape instant
            "speculation": self.speculator.stats(),
            # the watch-driven continuous controller (serve-stats/8):
            # ticks/reads/lag + emitted-plan attribution; same key set
            # with the mode off
            "watch": (
                self.watcher.stats() if self.watcher is not None
                else spec_mod.ZkWatcher.disabled_stats(self.watch_conn)
            ),
            # daemon-observed fallback/resync reasons, by name
            "fallbacks": fallbacks,
            # overload protection (serve-stats/5): fair-queue occupancy,
            # caps, shed counts by reason, the live retry_after estimate
            "admission": self._admission.stats(),
            # the chaos seam: armed spec (null when inert) + per-site
            # fired counts — a chaos run's scrape names what it injected
            "faults": {
                "armed": fault_plan.spec if fault_plan is not None else None,
                "fired": (
                    fault_plan.fired_counts()
                    if fault_plan is not None else {}
                ),
            },
        }
        sched = self._coalescer
        if sched is not None and hasattr(sched, "health_stats"):
            out["lane_health"] = sched.health_stats()
        else:
            out["lane_health"] = {
                "watchdog_s": self.watchdog_s, "quarantined": [],
                "quarantines": 0, "requeues": 0, "recoveries": 0,
                "abandoned": 0,
            }
        if self._lanes and hasattr(sched, "stats"):
            s = sched.stats()
            out["lanes"] = int(s["lanes"])
            out["steals"] = int(s["steals"])
            # mesh-exclusive runs (-fused-shard: drained the fleet and
            # owned every device for the dispatch)
            out["mesh_exclusive"] = int(s.get("mesh_exclusive", 0))
            out["microbatched"] = int(s["microbatched"])
            out["batch_mode"] = self.batch_mode
            out["mb_occupancy"] = sched.occupancy_hist()
            out["mb_padded_slots"] = int(s["padded_slots"])
            out["residency"] = {
                "hits": int(s["residency_hits"]),
                "misses": int(s["residency_misses"]),
            }
            out["lane_busy_s"] = [
                round(ln.busy_s, 3) for ln in self._lanes
            ]
            out["lane_requests"] = [ln.requests for ln in self._lanes]
            out["cache"] = {
                "hits": sess_cache["hits"] + sum(
                    ln.cache_stats()["hits"] for ln in self._lanes
                ),
                "misses": sess_cache["misses"] + sum(
                    ln.cache_stats()["misses"] for ln in self._lanes
                ),
                "rows_reused": sess_cache["rows_reused"] + sum(
                    ln.cache_stats()["rows_reused"] for ln in self._lanes
                ),
            }
        return out

    def _hello(self) -> Dict[str, Any]:
        return {
            "v": PROTO_VERSION, "ok": True, "op": "hello",
            # v2 negotiation: always advertised; only clients that
            # ALSO advertised it switch the connection's framing
            "max_v": PROTO_V2,
            **self._core_snapshot(),
        }

    def _tenants_block(self) -> Dict[str, Any]:
        """The serve-stats/5 per-tenant attribution block: one entry
        per live top-K tenant (keyed off the ``serve.request_s`` family
        — request activity is the authority on who is "top") carrying
        request counts, latency hists, queue time, the session
        delta/resync ladder, fallback counts and resident session
        bytes; demoted tenants aggregate under ``other``. Reads only
        the registry's label families and the session store — locks
        the plan dispatcher never holds across a dispatch."""
        snap = obs.metrics.tenant_snapshot()
        hfams, cfams = snap["hists"], snap["counters"]
        req_fam = hfams.get("serve.request_s") or {
            "cap": self.tenant_cap, "demoted": 0, "other": None,
            "labels": {},
        }
        queue_fam = hfams.get("serve.phase.queue") or {
            "other": None, "labels": {},
        }
        edge_fam = hfams.get("serve.edge_ms") or {
            "other": None, "labels": {},
        }

        def cval(name: str, label: str) -> int:
            fam = cfams.get(name)
            if fam is None:
                return 0
            if label == OTHER_LABEL:
                # the families LRU independently: a label demoted from
                # the request_s family may still hold live counters in
                # a sparser family (delta_hits is only touched on
                # hits). The rollup absorbs every count NOT attributed
                # to a live top-K label, so the table's totals always
                # reconcile with the global blocks.
                return int(
                    fam.get("other", 0)
                    + sum(
                        v for lbl, v in fam["labels"].items()
                        if lbl not in top_labels
                    )
                )
            return int(fam["labels"].get(label, 0))

        by_tenant = self.sessions.stats_by_tenant()
        # the rollup's session footprint: everything resident (hot OR
        # warm) that is NOT attributed to a live top-K label (demoted
        # tenants keep their sessions; the table must still reconcile
        # with the global "sessions"/"paging" blocks)
        top_labels = set(req_fam["labels"])
        rolled = {
            "sessions": 0, "bytes": 0,
            "warm_sessions": 0, "warm_bytes": 0,
        }
        for t_label, s in by_tenant.items():
            if t_label not in top_labels:
                rolled["sessions"] += s["sessions"]
                rolled["bytes"] += s["bytes"]
                rolled["warm_sessions"] += s.get("warm_sessions", 0)
                rolled["warm_bytes"] += s.get("warm_bytes", 0)

        def entry(label: str, hist: Optional[Dict[str, Any]]) -> Dict[str, Any]:
            sess = rolled if label == OTHER_LABEL else by_tenant.get(
                label, {}
            )
            queue = (
                queue_fam.get("other") if label == OTHER_LABEL
                else queue_fam["labels"].get(label)
            )
            edge = (
                edge_fam.get("other") if label == OTHER_LABEL
                else edge_fam["labels"].get(label)
            )
            return {
                "requests": cval("serve.requests", label),
                "crashed": cval("serve.crashed_requests", label),
                "request_s": hist,
                "queue_s": queue,
                # serve-stats/8: client+network edge overhead per label
                # (pre-send client phases + measured RTT, milliseconds)
                # — None until a tracing client reports (obs/edge.py)
                "edge_ms": edge,
                "delta_hits": cval("serve.delta_hits", label),
                "spec_hits": cval("serve.spec.hits", label),
                "resyncs_rows": cval("serve.resyncs_rows", label),
                "resyncs_full": cval("serve.resyncs_full", label),
                "fallbacks": cval("serve.fallbacks", label),
                "sheds": cval("serve.sheds", label),
                "restores": cval("serve.restores", label),
                "sessions": int(sess.get("sessions", 0)),
                "session_bytes": int(sess.get("bytes", 0)),
                # the warm tier column: a fully demoted tenant keeps
                # its byte attribution here instead of vanishing
                "warm_sessions": int(sess.get("warm_sessions", 0)),
                "warm_bytes": int(sess.get("warm_bytes", 0)),
            }

        other = entry(OTHER_LABEL, req_fam.get("other"))
        has_other = req_fam.get("other") is not None or any(
            other[k] for k in (
                "requests", "crashed", "delta_hits", "spec_hits",
                "resyncs_rows", "resyncs_full", "fallbacks", "sheds",
                "restores", "warm_sessions",
            )
        )
        return {
            "cap": int(req_fam.get("cap", self.tenant_cap)),
            "demoted": int(req_fam.get("demoted", 0)),
            "top": {
                label: entry(label, hist)
                for label, hist in req_fam["labels"].items()
            },
            "other": other if has_other else None,
        }

    def _stats_doc(self) -> Dict[str, Any]:
        """The ``stats`` scrape document (``STATS_SCHEMA``): the shared
        core snapshot plus every streaming histogram, the per-tenant
        attribution block and the flight recorder's occupancy. Built
        entirely from locks the plan dispatcher never holds across a
        dispatch, so a scrape cannot pause planning."""
        doc: Dict[str, Any] = {
            "v": PROTO_VERSION, "ok": True, "op": "stats",
            "schema": STATS_SCHEMA,
            "ts_epoch": round(time.time(), 3),
            **self._core_snapshot(),
        }
        doc["batch_mode"] = self.batch_mode
        doc["hists"] = obs.metrics.hist_snapshot()
        doc["tenants"] = self._tenants_block()
        doc["flight"] = self.flight.stats()
        return doc

    def _touch(self) -> None:
        self._last_activity = time.monotonic()

    def _dispatch_plan(self, req: PlanRequest) -> Optional[Dict[str, Any]]:
        """Route one plan request through admission control and the
        dispatcher (waiting out the startup race), with the in-flight
        gauge held; None when the dispatcher never became ready. A shed
        returns the structured overload frame WITHOUT touching the
        dispatcher — shed latency lands in ``serve.shed_s``, never in
        the served-request histograms."""
        self._dispatcher_ready.wait(DISPATCHER_WAIT_S)
        dispatcher = self._coalescer
        if dispatcher is None:
            return None
        # t_submit anchors the queue-wait histogram at ARRIVAL: the
        # fair-queue wait is part of what a tenant waits behind
        # (admission.acquire preempts any in-flight speculation via
        # its arrival hook — idle work never costs live traffic p95)
        req.t_submit = time.perf_counter()
        shed = self._admission.acquire(req)
        if shed is not None:
            return shed
        try:
            return dispatcher.submit(req)
        finally:
            self._admission.release(req)

    @contextlib.contextmanager
    def _inflight_op(self) -> "Iterator[None]":
        """Hold the ``requests_inflight`` gauge across one plan-family
        connection op — from frame decode through response build, the
        session-op pre-dispatch work (register parse/digest, row
        patching) INCLUDED: a client's progress probe reads
        ``requests_inflight > 0`` as "my request is being worked on",
        and that must be true for every phase the daemon can spend
        real time in, or a slow register reads as a lost request."""
        with self._lock:
            self._inflight += 1
        try:
            yield
        finally:
            with self._lock:
                self._inflight -= 1

    # -- protocol v2: session ops ----------------------------------------
    def _v2_plan_resp(
        self, resp: Optional[Dict[str, Any]]
    ) -> Tuple[Dict[str, Any], bytes]:
        """A dispatcher response as a v2 frame: stdout rides the blob
        (no JSON escaping), the rest in the header."""
        if resp is None:
            return {
                "v": PROTO_V2, "ok": False, "op": "error",
                "error": "daemon dispatcher not ready",
            }, b""
        if not resp.get("ok"):
            if resp.get("op") == "overload":
                # the structured shed frame survives v2 framing intact:
                # the client's backoff ladder reads retry_after_ms
                return {
                    "v": PROTO_V2, "ok": False, "op": "overload",
                    "reason": str(resp.get("reason", "overload")),
                    "retry_after_ms": int(resp.get("retry_after_ms", 0)),
                    "error": str(resp.get("error", "request shed")),
                }, b""
            return {
                "v": PROTO_V2, "ok": False, "op": "error",
                "error": str(resp.get("error", "request failed")),
            }, b""
        hdr: Dict[str, Any] = {
            "v": PROTO_V2, "ok": True, "rc": int(resp.get("rc", -1)),
            "stderr": str(resp.get("stderr", "")),
        }
        if isinstance(resp.get("trace"), dict):
            # the reply footer (daemon span subtree + wall) rides the
            # v2 header back to tracing clients — ONLY when the request
            # carried a trace context, so trace-less clients see the
            # exact pre-tracing header shape
            hdr["trace"] = resp["trace"]
        return hdr, str(resp.get("stdout", "")).encode("utf-8")

    def _checkout_or_restore(
        self, key: Tuple[str, str], tenant: str
    ) -> Tuple[Optional[Any], bool, bool]:
        """Claim the hot session for ``key`` — or, when the hot tier
        has none and a warm tier is attached, RESTORE the spilled
        record into a fresh hot session (claimed before it is
        published, so no concurrent request can half-see it).

        Returns ``(session, busy, restored)``: a corrupt/absent warm
        record is simply ``(None, False, False)`` — a clean cold miss,
        the caller answers ``resync: full`` exactly as before the
        tier existed."""
        from kafkabalancer_tpu.serve.sessions import session_from_rows

        sess, busy = self.sessions.checkout(key)
        if sess is not None or busy:
            return sess, busy, False
        if self.spill is None:
            return None, False, False
        # snapshot the tenant's release generation BEFORE reading the
        # record: a `release` op racing this restore must win — the
        # restored session is dropped, never served
        gen0 = self.sessions.release_gen(tenant)
        loaded = self.spill.load(key)
        if loaded is None:
            return None, False, False
        hdr, rows = loaded
        version = hdr.get("version")
        sess = session_from_rows(
            tenant, key[1],
            version if isinstance(version, int) else 1,
            rows,
        )
        sess.lock.acquire()
        sess.in_use = True
        if not self.sessions.adopt(key, sess):
            # a concurrent register won the key during the disk read:
            # the fresh session holds newer state — drop the restore
            # and claim the winner instead
            sess.in_use = False
            sess.lock.release()
            hot, busy = self.sessions.checkout(key)
            return hot, busy, False
        if self.sessions.release_gen(tenant) != gen0:
            # the tenant was released while we were restoring: honor
            # the forget — sweep the just-adopted session back out and
            # answer a clean cold miss (the record itself is already
            # consumed and counted); only THIS session is dropped, so
            # a fresh register that beat us to the key survives
            self.sessions.discard(key, sess)
            self.sessions.checkin(sess)
            return None, False, False
        obs.metrics.tenant_count("serve.restores", tenant or OTHER_LABEL)
        return sess, False, True

    def _answer_from_memo(
        self,
        key: Tuple[str, str],
        sess: Any,
        memo: Any,
        tenant: str,
        deadline: Optional[float],
        argv: List[str],
        t0: float,
        trace: Optional[Dict[str, Any]] = None,
    ) -> Dict[str, Any]:
        """Answer a digest-and-argv-matching ``plan-delta`` from the
        speculative memo (serve/speculate.py): ZERO dispatch, ZERO
        parse — the answer was computed during the idle window after
        the previous request. The memo hit is a REAL request: it rides
        admission (so the fairness caps and the conservation identity
        ``admitted == requests + abandoned`` hold), counts in
        ``serve.requests``/``serve.request_s``/the flight log like any
        served request (with its near-zero wall — that IS the
        speedup), counts a delta hit (it is the delta fast path at its
        fastest) and carries the ``serve.spec.*`` hit attribution the
        acceptance gate reads. The caller still holds the session
        checkout and has already CONSUMED the memo via
        ``Speculator.take_memo`` (the CAS that makes hit-vs-poison
        retirement exactly-once)."""
        req = PlanRequest(argv, None, tenant, deadline=deadline)
        shed = self._admission.acquire(req)
        if shed is not None:
            # the answer was never delivered: put the memo back so the
            # client's backoff retry (same digest) can still hit
            self.speculator.untake_memo(sess, memo)
            return shed
        try:
            tenant_label = tenant or OTHER_LABEL
            obs.metrics.tenant_count("serve.spec.hits", tenant_label)
            self.sessions.count_delta_hit()
            obs.metrics.tenant_count("serve.delta_hits", tenant_label)
            with self._lock:
                self._requests += 1
                self._seq += 1
                seq = self._seq
            sess.last_used = time.monotonic()
            if self.spill is not None:
                # the continuous-spill invariant moves with the hit:
                # the client now advances to the memo's post-move
                # state, which is exactly the session's current raw
                # shadow — persist it so a SIGKILL still restores with
                # a digest match
                self.spill.spill(key, sess)
            wall = time.perf_counter() - t0
            obs.metrics.hist_observe("serve.spec.hit_s", wall)
            obs.metrics.hist_observe("serve.request_s", wall)
            obs.metrics.tenant_hist_observe(
                "serve.request_s", tenant_label, wall
            )
            obs.metrics.tenant_count("serve.requests", tenant_label)
            self.flight.record_request({
                "req": seq,
                "t": round(time.time(), 3),
                "lane": 0,
                "tenant": tenant or None,
                "bucket": list(sess.bucket) if sess.bucket else None,
                "rc": memo.rc,
                "coalesced": False,
                "spec_hit": True,
                "wall_s": round(wall, 6),
                "phases": {},
                "trace": (
                    str(trace["id"])
                    if trace is not None and trace.get("id")
                    else None
                ),
            })
            self._touch()
            resp: Dict[str, Any] = {
                "v": PROTO_VERSION, "ok": True, "rc": memo.rc,
                "stdout": memo.stdout, "stderr": memo.stderr,
            }
            if trace is not None:
                # the memo hit ran no request thread, so the footer's
                # span subtree is empty — spec_hit marks WHY for the
                # merged timeline (the answer predates the question)
                resp["trace"] = {
                    "id": trace.get("id"),
                    "wall_s": round(wall, 6),
                    "spans": [],
                    "spec_hit": True,
                }
            return resp
        finally:
            self._admission.release(req)

    def _session_op(
        self, op: str, hdr: Dict[str, Any], blob: bytes, argv: List[str]
    ) -> Tuple[Dict[str, Any], bytes]:
        """One v2 plan-family op (``plan``/``register``/``plan-delta``/
        ``plan-rows``) — the resident-session ladder of
        serve/sessions.py. Returns the response (header, blob)."""
        from kafkabalancer_tpu.serve import state as sstate
        from kafkabalancer_tpu.serve.sessions import (
            ClusterSession,
            PlanSessionContext,
            flags_signature,
        )

        def _resync_full() -> Tuple[Dict[str, Any], bytes]:
            return {
                "v": PROTO_V2, "ok": True, "op": op, "resync": "full",
            }, b""

        tenant = str(hdr.get("tenant", ""))
        deadline = _deadline_of(hdr)
        # the client's trace context (obs/edge.py), v2-only by
        # construction — v1 frames never reach this parser. Telemetry
        # only: it is threaded onto every PlanRequest the op creates
        # and NEVER read by planning.
        trace_hdr = hdr.get("trace")
        trace = trace_hdr if isinstance(trace_hdr, dict) else None
        if op == "plan":
            stdin = (
                blob.decode("utf-8", errors="replace")
                if hdr.get("has_stdin") else None
            )
            req = PlanRequest(argv, stdin, tenant, deadline=deadline)
            req.trace = trace
            return self._v2_plan_resp(self._dispatch_plan(req))

        key = (tenant, flags_signature(argv))
        if op == "register":
            text = blob.decode("utf-8", errors="replace")
            sess = ClusterSession(tenant, key[1])
            ctx = PlanSessionContext("register", sess)
            # the fresh session is private until put(); hold its lock
            # anyway so the store can never hand it out half-built
            with sess.lock:
                sess.in_use = True
                try:
                    req = PlanRequest(argv, text, tenant, deadline=deadline)
                    req.trace = trace
                    req.session_ctx = ctx
                    sess.last_argv = list(argv)
                    resp = self._dispatch_plan(req)
                finally:
                    sess.in_use = False
            if (
                resp is not None
                and resp.get("ok")
                and resp.get("rc") == 0
                and ctx.snapshotted
            ):
                self.sessions.put(key, sess)
                # the freshly registered session's next move can start
                # computing right away (idle-priority)
                self.speculator.enqueue(key)
            return self._v2_plan_resp(resp)

        if op == "plan-delta":
            digest = str(hdr.get("digest", ""))
            spec = self.speculator
            t_hit0 = time.perf_counter()
            sess, busy, restored = self._checkout_or_restore(key, tenant)
            if sess is None and busy and spec.wait_for_key(
                key, digest, argv,
                (deadline - time.monotonic()) if deadline else 120.0,
            ):
                # speculation held the session: a MATCHING in-flight
                # run just computed this very answer (the memo path
                # below consumes it); a mismatching one was aborted —
                # either way, re-claim and proceed
                sess, busy, restored = self._checkout_or_restore(
                    key, tenant
                )
            if sess is None:
                self._count_fallback(
                    "session_busy" if busy else "session_absent", tenant
                )
                return _resync_full()
            enqueue_spec = False
            try:
                memo = sess.spec_memo
                if memo is not None:
                    if (
                        memo.key_digest == digest
                        and memo.argv == argv
                        and spec.take_memo(sess, memo)
                    ):
                        # the tentpole fast path: the answer was
                        # planned before it was asked for (take_memo
                        # is the CAS — a concurrently poisoned memo
                        # falls through to the live ladder below)
                        resp = self._answer_from_memo(
                            key, sess, memo, tenant, deadline, argv,
                            t_hit0, trace=trace,
                        )
                        enqueue_spec = bool(resp.get("ok"))
                        if enqueue_spec and spec.rearm_memo(sess, memo):
                            # fixed point: the plan moved nothing, so
                            # the session did not advance — the same
                            # memo keeps answering the same digest
                            # with no re-dispatch
                            enqueue_spec = False
                        return self._v2_plan_resp(resp)
                    # the memo cannot serve this request (drifted
                    # digest or changed flags): drop it and fall back
                    # to the live ladder — parity over latency, always
                    spec.retire_miss(sess, memo)
                if sess.digest is not None and digest == sess.digest:
                    # a just-restored session has no settled list yet;
                    # like universe_dirty, it re-derives one from the
                    # raw shadow (the "rebuild" kind) — still no state
                    # transfer, still one request back to steady state
                    kind = (
                        "rebuild"
                        if restored or sess.universe_dirty or sess.pl is None
                        else "delta"
                    )
                    ctx = PlanSessionContext(
                        kind, sess,
                        resident_pl=sess.pl if kind == "delta" else None,
                        restored=restored,
                    )
                    if restored:
                        # the acceptance counter: a digest-matching
                        # request answered from the warm tier, no
                        # re-register storm
                        self.spill.note_restore_hit()
                    else:
                        self.sessions.count_delta_hit()
                        obs.metrics.tenant_count(
                            "serve.delta_hits", tenant or OTHER_LABEL
                        )
                    req = PlanRequest(
                        argv, None, tenant, deadline=deadline
                    )
                    req.trace = trace
                    req.session_ctx = ctx
                    sess.last_argv = list(argv)
                    resp = self._dispatch_plan(req)
                    enqueue_spec = (
                        resp is not None
                        and bool(resp.get("ok"))
                        and resp.get("rc") == 0
                    )
                    return self._v2_plan_resp(resp)
                # mismatch: offer the row-level diff — the client ships
                # only the rows whose hashes differ
                self._count_fallback("session_digest_mismatch", tenant)
                table = sess.hash_table()
                return {
                    "v": PROTO_V2, "ok": True, "op": op,
                    "resync": "rows", "nrows": len(sess.raw),
                }, table
            finally:
                self.sessions.checkin(sess)
                if enqueue_spec:
                    # plan-ahead AFTER the checkin (the speculator
                    # needs the session lock): the next request's
                    # answer starts computing in the idle window
                    spec.enqueue(key)

        if op == "plan-rows":
            digest = str(hdr.get("digest", ""))
            # restore applies here too: the row diff the client built
            # against a (possibly restored) hash table patches onto the
            # restored raw shadow the same as onto a hot one
            spec = self.speculator
            sess, busy, restored = self._checkout_or_restore(key, tenant)
            if sess is None and busy and spec.wait_for_key(
                key, "", [],
                (deadline - time.monotonic()) if deadline else 30.0,
            ):
                # a resync can never use an in-flight speculation:
                # abort it, wait it out, re-claim
                sess, busy, restored = self._checkout_or_restore(
                    key, tenant
                )
            if sess is None:
                self._count_fallback(
                    "session_busy" if busy else "session_absent", tenant
                )
                return _resync_full()
            enqueue_spec = False
            try:
                rows_memo = sess.spec_memo
                if rows_memo is not None:
                    # a resyncing client has drifted past the memo
                    spec.retire_miss(sess, rows_memo)
                try:
                    patches = sstate.unpack_rows(blob)
                except ValueError:
                    self._count_fallback("session_rows_invalid", tenant)
                    self._count_resync_full(tenant)
                    return _resync_full()
                if not sess.apply_row_patches(patches):
                    self._count_fallback("session_rows_mismatch", tenant)
                    self._count_resync_full(tenant)
                    return _resync_full()
                if sess.digest != digest:
                    # the diff was computed against a table an
                    # interleaved request has since invalidated;
                    # re-register from ground truth
                    self._count_fallback("session_rows_mismatch", tenant)
                    self._count_resync_full(tenant)
                    return _resync_full()
                self.sessions.count_resync_rows()
                obs.metrics.tenant_count(
                    "serve.resyncs_rows", tenant or OTHER_LABEL
                )
                ctx = PlanSessionContext("rows", sess, restored=restored)
                req = PlanRequest(argv, None, tenant, deadline=deadline)
                req.trace = trace
                req.session_ctx = ctx
                sess.last_argv = list(argv)
                resp = self._dispatch_plan(req)
                enqueue_spec = (
                    resp is not None
                    and bool(resp.get("ok"))
                    and resp.get("rc") == 0
                )
                return self._v2_plan_resp(resp)
            finally:
                self.sessions.checkin(sess)
                if enqueue_spec:
                    spec.enqueue(key)

        return {
            "v": PROTO_V2, "ok": False, "op": "error",
            "error": f"unknown op {op!r}",
        }, b""

    def _serve_v2(self, conn: socket.socket) -> None:
        """The per-connection loop after a v2 hello negotiation: same
        ops as v1 plus the session family, all in binary frames."""
        while True:
            try:
                t_read0 = time.perf_counter()
                frame = read_frame2(conn)
                read_s = time.perf_counter() - t_read0
            except ValueError as exc:
                self._count_fallback("bad_frame")
                self._log(f"serve: refused v2 frame: {exc}")
                try:
                    write_frame2(conn, {
                        "v": PROTO_V2, "ok": False, "op": "error",
                        "error": f"bad frame: {exc}",
                    })
                except Exception:
                    pass
                return
            except Exception:
                return
            if frame is None:
                return
            hdr, blob = frame
            if hdr.get("v") != PROTO_V2:
                self._count_fallback("version_mismatch")
                write_frame2(conn, {
                    "v": PROTO_V2, "ok": False, "op": "error",
                    "error": f"protocol version {hdr.get('v')!r}",
                })
                return
            op = str(hdr.get("op", ""))
            if op == "hello":
                write_frame2(conn, {**self._hello(), "v": PROTO_V2})
            elif op == "stats":
                write_frame2(conn, {**self._stats_doc(), "v": PROTO_V2})
            elif op == "watch":
                write_frame2(conn, {
                    "v": PROTO_V2, "ok": True, "op": "watch",
                    "watch": (
                        self.watcher.stats()
                        if self.watcher is not None
                        else spec_mod.ZkWatcher.disabled_stats(
                            self.watch_conn
                        )
                    ),
                    "speculation": self.speculator.stats(),
                })
            elif op == "release":
                # an explicit forget covers BOTH tiers: dropping only
                # the hot session would leave a warm record that
                # silently restores the "released" state later. Warm
                # FIRST — once the records are gone no new restore can
                # begin, and the hot sweep (which also bumps the
                # release generation and marks in-flight sessions
                # `released`) then catches everything resident
                rel_tenant = str(hdr.get("tenant", ""))
                warm = (
                    self.spill.release(rel_tenant)
                    if self.spill is not None else 0
                )
                n = self.sessions.release(rel_tenant)
                if self.spill is not None:
                    # second warm sweep AFTER the hot sweep marked
                    # in-flight sessions `released`: a continuous
                    # spill that indexed its record between the first
                    # sweep and the mark would otherwise survive both
                    # its own released re-check and the sweep above
                    warm += self.spill.release(rel_tenant)
                write_frame2(conn, {
                    "v": PROTO_V2, "ok": True, "op": "release",
                    "released": n, "released_warm": warm,
                })
            elif op == "shutdown":
                write_frame2(conn, {"v": PROTO_V2, "ok": True})
                self._stop.set()
                return
            elif op in ("plan", "register", "plan-delta", "plan-rows"):
                self._touch()
                raw_argv = hdr.get("argv", [])
                if not isinstance(raw_argv, list):
                    self._count_fallback("plan_invalid")
                    write_frame2(conn, {
                        "v": PROTO_V2, "ok": False, "op": "error",
                        "error": "plan payload: argv is not a list",
                    })
                    return
                obs.metrics.hist_observe("serve.phase.read", read_s)
                argv = [str(a) for a in raw_argv]
                with self._inflight_op():
                    resp_hdr, resp_blob = self._session_op(
                        op, hdr, blob, argv
                    )
                if faults.should("socket_drop"):
                    # chaos seam: vanish mid-exchange instead of
                    # replying — the client sees a dead peer and takes
                    # its transport-error path (retry, then fallback)
                    return
                t_reply0 = time.perf_counter()
                write_frame2(conn, resp_hdr, resp_blob)
                obs.metrics.hist_observe(
                    "serve.phase.reply", time.perf_counter() - t_reply0
                )
            else:
                write_frame2(conn, {
                    "v": PROTO_V2, "ok": False,
                    "error": f"unknown op {op!r}",
                })

    # thread-role: accept-loop
    def _serve_connection(self, conn: socket.socket) -> None:
        try:
            conn.settimeout(PLAN_CONNECTION_TIMEOUT_S)
            while True:
                try:
                    t_read0 = time.perf_counter()
                    msg = read_frame(conn)
                    read_s = time.perf_counter() - t_read0
                except ValueError as exc:
                    # a structured refusal instead of a dropped
                    # connection: an oversized length prefix or an
                    # unparseable payload gets an op-"error" frame with
                    # the reason, so the client can log WHY it fell back
                    # in-process instead of a generic fallback
                    self._count_fallback("bad_frame")
                    self._log(f"serve: refused frame: {exc}")
                    try:
                        write_frame(conn, {
                            "v": PROTO_VERSION, "ok": False, "op": "error",
                            "error": f"bad frame: {exc}",
                        })
                    except Exception:
                        pass
                    return
                except Exception:
                    return  # dead peer / mid-frame EOF: nothing to tell
                if msg is None:
                    return
                if msg.get("v") != PROTO_VERSION:
                    self._count_fallback("version_mismatch")
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": False, "op": "error",
                        "error": f"protocol version {msg.get('v')!r}",
                    })
                    return
                op = msg.get("op")
                # NOTE: only PLAN work resets the idle clock. hello and
                # the scrape ops are passive — a periodic monitoring
                # scraper (-metrics-prom on a cron) must not pin an
                # otherwise-idle daemon alive past -serve-idle-timeout
                if op == "hello":
                    t_hello_ns = time.perf_counter_ns()
                    doc = self._hello()
                    if msg.get("clock"):
                        # the opt-in clock handshake (obs/edge.py):
                        # daemon-monotonic receive/send stamps for the
                        # client's NTP-style offset estimate. STRICTLY
                        # request-gated — a plain hello (liveness
                        # probes, the stats scraper's handshake) gets
                        # the exact historical doc, preserving the
                        # hello/stats key-parity contract. recv is
                        # stamped post-read, so any parse delay inflates
                        # the client's RTT bound, never skews the
                        # offset midpoint.
                        doc["clock"] = {
                            "recv_ns": t_hello_ns,
                            "send_ns": time.perf_counter_ns(),
                        }
                    write_frame(conn, doc)
                    mv = msg.get("max_v")
                    if isinstance(mv, int) and mv >= PROTO_V2:
                        # both sides advertised v2: every further frame
                        # on this connection is binary-framed. A v1
                        # client never sends max_v, so its byte
                        # sequences mean exactly what they always did.
                        self._serve_v2(conn)
                        return
                elif op == "stats":
                    # answered HERE, on the connection thread: a live
                    # scrape must never queue behind (or pause) planning
                    write_frame(conn, self._stats_doc())
                elif op == "dump-trace":
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": True, "op": "dump-trace",
                        "trace": self.flight.to_perfetto(),
                    })
                elif op == "watch":
                    # the watch-lag scrape: answered on the connection
                    # thread like stats, passive for the idle clock —
                    # the replay harness polls it to sequence fake-ZK
                    # mutations against the watcher's reads
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": True, "op": "watch",
                        "watch": (
                            self.watcher.stats()
                            if self.watcher is not None
                            else spec_mod.ZkWatcher.disabled_stats(
                                self.watch_conn
                            )
                        ),
                        "speculation": self.speculator.stats(),
                    })
                elif op == "plan":
                    self._touch()
                    raw_argv = msg.get("argv", [])
                    if not isinstance(raw_argv, list):
                        write_frame(conn, {
                            "v": PROTO_VERSION, "ok": False, "op": "error",
                            "error": "plan payload: argv is not a list",
                        })
                        return
                    # the wire half of the served phase chain: how long
                    # the daemon spent reading this plan frame off the
                    # socket (client encode + transfer)
                    obs.metrics.hist_observe("serve.phase.read", read_s)
                    argv = [str(a) for a in raw_argv]
                    stdin = msg.get("stdin")
                    req = PlanRequest(
                        argv,
                        str(stdin) if stdin is not None else None,
                        deadline=_deadline_of(msg),
                    )
                    # startup race: the dispatcher is built on the warm
                    # thread; a plan arriving first waits for it
                    with self._inflight_op():
                        resp = self._dispatch_plan(req)
                    if resp is None:
                        write_frame(conn, {
                            "v": PROTO_VERSION, "ok": False, "op": "error",
                            "error": "daemon dispatcher not ready",
                        })
                        return
                    if faults.should("socket_drop"):
                        return  # chaos seam: dead peer instead of a reply
                    t_reply0 = time.perf_counter()
                    write_frame(conn, resp)
                    obs.metrics.hist_observe(
                        "serve.phase.reply",
                        time.perf_counter() - t_reply0,
                    )
                elif op == "shutdown":
                    write_frame(conn, {"v": PROTO_VERSION, "ok": True})
                    self._stop.set()
                    return
                else:
                    write_frame(conn, {
                        "v": PROTO_VERSION, "ok": False,
                        "error": f"unknown op {op!r}",
                    })
        except Exception:
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    # -- lifecycle -------------------------------------------------------
    # the ONE pidfile-verification rule set (liveness probe + pid-
    # recycling guard), shared with the warm tier's spill-directory
    # claim — the socket takeover and the spill-dir takeover cannot
    # drift (serve/spill.py holds the implementations)
    _pid_alive = staticmethod(spill_mod.pid_alive)
    _pid_looks_like_daemon = staticmethod(spill_mod.pid_looks_like_daemon)

    def _pidfile_owner(self) -> Optional[int]:
        """The pid recorded next to the socket, or None."""
        try:
            with open(pidfile_path(self.socket_path)) as f:
                return int(f.read().strip())
        except (OSError, ValueError):
            return None

    def _preflight_socket(self) -> Optional[str]:
        """None when the socket path is free (stale files swept), an
        error string when a live daemon already owns it.

        The refusal is PIDFILE-VERIFIED: a socket that answers hello is
        a live daemon (refuse); a socket that does NOT answer is only
        refused when the pidfile's process is still alive (it may be
        mid-startup, wedged, or a different package version — hijacking
        its socket would orphan it). Leftovers from a SIGKILL'd daemon
        — socket + pidfile with a dead pid — are swept and replaced
        instead of blocking the restart."""
        if not os.path.exists(self.socket_path):
            # the socket is gone but a SIGKILL can still leave the
            # pidfile; sweep it so the liveness story stays coherent
            pid = self._pidfile_owner()
            if pid is not None and not self._pid_alive(pid):
                try:
                    os.unlink(pidfile_path(self.socket_path))
                except OSError:
                    pass
            return None
        from kafkabalancer_tpu.serve import client

        hello = client.daemon_alive(self.socket_path, timeout=1.0)
        if hello is not None:
            return (
                f"daemon already running on {self.socket_path} "
                f"(pid {hello.get('pid')})"
            )
        pid = self._pidfile_owner()
        if (
            pid is not None
            and pid != os.getpid()
            and self._pid_alive(pid)
            and self._pid_looks_like_daemon(pid)
        ):
            return (
                f"socket {self.socket_path} is unresponsive but its "
                f"pidfile process {pid} is still alive; refusing to "
                "take it over (kill the process or remove "
                f"{pidfile_path(self.socket_path)} first)"
            )
        for path, what in (
            (self.socket_path, "socket"),
            (pidfile_path(self.socket_path), "pidfile"),
        ):
            try:
                os.unlink(path)
                self._log(
                    f"serve: swept stale {what} {path}"
                    + (f" (pid {pid} dead)" if pid is not None else "")
                )
            except FileNotFoundError:
                pass
            except OSError as exc:
                if what == "socket":
                    return f"cannot remove stale socket {path}: {exc}"
        return None

    # thread-role: accept-loop
    def serve_forever(self) -> int:
        """Run until shutdown/idle-timeout/signal; 0 on a clean exit,
        3 when the socket or spill dir is unusable (live daemon, bind
        failure, live spill-dir owner)."""
        err = self._preflight_socket()
        if err is not None:
            self._log(f"serve: {err}")
            return 3
        if self.spill_dir:
            # the warm tier claims its directory with the same
            # pidfile-verification rules as the socket: records from a
            # DEAD previous owner are adopted (SIGKILL recovery), its
            # half-written *.tmp orphans swept, a LIVE owner refused
            store = spill_mod.SpillStore(
                self.spill_dir, cap_mb=self.warm_cap_mb, log=self._log,
            )
            err = store.open()
            if err is not None:
                self._log(f"serve: {err}")
                return 3
            self.spill = store
            self.sessions.spill = store
            st = store.stats()
            self._log(
                f"serve: warm session tier on {self.spill_dir} "
                f"(cap {st['cap_bytes'] >> 20}MB, "
                f"{st['warm_entries']} records adopted)"
            )
        listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        try:
            listener.bind(self.socket_path)
        except OSError as exc:
            self._log(f"serve: cannot bind {self.socket_path}: {exc}")
            listener.close()
            if self.spill is not None:
                self.spill.close()
            return 3
        listener.listen(16)
        listener.settimeout(0.5)
        pid_path = pidfile_path(self.socket_path)
        try:
            with open(pid_path, "w") as f:
                f.write(f"{os.getpid()}\n")
        except OSError:
            pid_path = ""

        from kafkabalancer_tpu.ops.tensorize import set_row_cache

        # the always-on live-telemetry feed: every completed span — with
        # or without the flag trio — lands in the flight recorder and
        # the per-phase streaming histograms (fixed memory, no jax).
        # Histograms reset HERE so they are daemon-lifetime: the stats
        # scrape's reconciliation invariant (serve.request_s count ==
        # serve.requests) holds exactly from request 1
        obs.metrics.reset_hists()
        # the tenant dimension resets on the same boundary, and the
        # families are created NOW so the configured cap binds before
        # the first observation (cap applies at first creation)
        obs.metrics.reset_tenants()
        for fam in _TENANT_HIST_FAMILIES:
            obs.metrics.tenant_hist(fam, cap=self.tenant_cap)
        for fam in _TENANT_COUNTER_FAMILIES:
            obs.metrics.tenant_counter(fam, cap=self.tenant_cap)
        obs.tracer.set_observer(self._observe_span)

        # the chaos seam: armed ONLY here, by explicit operator intent
        # (-serve-faults, or the env var when the flag is empty); a
        # malformed spec refuses startup — a chaos run with a typo'd
        # schedule must not silently run un-chaos'd
        spec = self.faults_spec or os.environ.get(
            "KAFKABALANCER_TPU_FAULTS", ""
        )
        if spec:
            try:
                plan = faults.arm(spec)
            except ValueError as exc:
                self._log(f"serve: bad -serve-faults spec: {exc}")
                listener.close()
                if self.spill is not None:
                    self.spill.close()
                for path in (self.socket_path, pid_path):
                    if path:
                        try:
                            os.unlink(path)
                        except OSError:
                            pass
                return 3
            self._log(f"serve: FAULT INJECTION ARMED: {plan.spec}")

        # speculative plan-ahead worker (idle-priority; no-op thread
        # unless -serve-speculate) and, with -watch, the continuous
        # controller — both wait out the dispatcher-ready latch before
        # touching planning, so startup order is unchanged
        self.speculator.start()
        if self.speculator.enabled:
            self._log("serve: speculative plan-ahead enabled")
        if self.watch_conn:
            self.watcher = spec_mod.ZkWatcher(
                self,
                self.watch_conn,
                emit=self.watch_emit,
                poll_s=self.watch_poll,
                argv=self.watch_argv,
            )
            self.watcher.start()
            self._log(
                f"serve: watching zookeeper {self.watch_conn} "
                f"(poll {self.watch_poll:g}s"
                + (
                    f", emitting plans to {self.watch_emit}"
                    if self.watch_emit else ""
                )
                + ")"
            )

        if self.warm:
            # the dispatcher is built on the warm thread (its lane
            # resolution pays the backend attach) so the accept loop
            # answers hello immediately; plans wait on _dispatcher_ready
            threading.Thread(
                target=self._warm_body, name="serve-warm", daemon=True
            ).start()
        else:
            self._coalescer = self._make_dispatcher()
            self._dispatcher_ready.set()
            self._warm_done.set()

        old_handlers: List[Tuple[int, Any]] = []
        if threading.current_thread() is threading.main_thread():
            for sig in (signal.SIGTERM, signal.SIGINT):
                old_handlers.append((sig, signal.getsignal(sig)))
                signal.signal(sig, lambda *_a: self._stop.set())

        self._log(
            f"serve: listening on {self.socket_path} "
            f"(pid {os.getpid()}, idle timeout "
            f"{self.idle_timeout:g}s)" if self.idle_timeout > 0 else
            f"serve: listening on {self.socket_path} (pid {os.getpid()})"
        )
        self._touch()
        try:
            while not self._stop.is_set():
                self.sessions.sweep()
                # overload/health maintenance, every accept tick
                # (~0.5 s): shed queued requests past their deadline,
                # and run the lane watchdog (quarantine / requeue /
                # recover — docs/serving.md § Lane health)
                self._admission.sweep()
                tick_disp = self._coalescer
                if tick_disp is not None and hasattr(
                    tick_disp, "health_tick"
                ):
                    tick_disp.health_tick(log=self._log)
                if (
                    self.idle_timeout > 0
                    and self._warm_done.is_set()
                    and self._coalescer is not None
                    and not self._coalescer.busy()
                    and not self._admission.busy()
                    and time.monotonic() - self._last_activity
                    > self.idle_timeout
                ):
                    self._log(
                        f"serve: idle for {self.idle_timeout:g}s, "
                        "shutting down"
                    )
                    break
                try:
                    conn, _addr = listener.accept()
                except socket.timeout:
                    continue
                except OSError:
                    break
                threading.Thread(
                    target=self._serve_connection,
                    args=(conn,),
                    name="serve-conn",
                    daemon=True,
                ).start()
        finally:
            listener.close()
            # internal producers first: the speculator/watcher stop
            # FEEDING the dispatcher (their in-flight runs abort at the
            # next preemption check and drain through dispatcher stop)
            self.speculator.request_stop()
            if self.watcher is not None:
                self.watcher.request_stop()
            # flush the fair queue FIRST (its waiters would otherwise
            # block their connection threads through dispatcher stop)
            self._admission.stop()
            if self._coalescer is not None:
                self._coalescer.stop()
            self.speculator.join()
            if self.watcher is not None:
                self.watcher.join()
            if self.spill is not None:
                # the SHUTDOWN FLUSH (idle timeout, SIGTERM, and the
                # shutdown op all route through here): with the
                # dispatchers drained, every idle resident spills so
                # the next daemon restores instead of re-registering.
                # SIGKILL never reaches this line — that path recovers
                # from the continuous per-request spill instead.
                flushed = self.sessions.flush_spill()
                if flushed:
                    self._log(
                        f"serve: flushed {flushed} resident session"
                        f"{'s' if flushed != 1 else ''} to the warm tier"
                    )
                self.spill.close()
            faults.disarm()
            obs.tracer.set_observer(None)
            obs.set_shared_registry(False)
            # ops.tensorize is numpy-only at import — no backend attach
            # jaxlint: disable=R8 — clearing a module-global hook
            set_row_cache(None)
            for sig, handler in old_handlers:
                try:
                    signal.signal(sig, handler)
                except (ValueError, OSError):
                    pass
            for path in (self.socket_path, pid_path):
                if path:
                    try:
                        os.unlink(path)
                    except OSError:
                        pass
        with self._lock:
            n, n_coal = self._requests, self._coalesced
        if self._lanes:
            sched = self._coalescer
            s = sched.stats() if hasattr(sched, "stats") else {}
            per_lane = ", ".join(
                f"lane{ln.index}: {ln.requests} req / {ln.busy_s:.1f}s busy"
                for ln in self._lanes
            )
            self._log(
                f"serve: exiting after {n} request"
                f"{'s' if n != 1 else ''} ({n_coal} coalesced, "
                f"{int(s.get('microbatched', 0))} microbatched, "
                f"{int(s.get('steals', 0))} steals, "
                f"{int(s.get('cache_hits', 0))} tensorize cache hits; "
                f"{per_lane})"
            )
            return 0
        cache_stats = self.tensorize_cache.stats()
        self._log(
            f"serve: exiting after {n} request"
            f"{'s' if n != 1 else ''} ({n_coal} coalesced, "
            f"{cache_stats['hits']} tensorize cache hits)"
        )
        return 0
