"""Device-memory attribution behind a jax-free-safe seam.

The serving daemon wants per-lane HBM gauges (how many live bytes do the
resident executables + residency pool hold?) in ``hello``/``stats``/
``-metrics-prom`` — but those scrape paths answer on connection threads
that may run BEFORE the backend warm thread has imported jax, and a
scrape must never pay (or block on) a backend attach. The seam:
:func:`device_memory_stats` only queries a device when jax is ALREADY
imported in this process (``sys.modules`` check — importing jax here is
forbidden), and degrades to ``None`` on backends that expose no memory
introspection (XLA:CPU returns nothing useful; TPU/GPU report
``bytes_in_use``/``bytes_limit``).

Lives under ``serve/`` (not ``ops/``) deliberately: the ``ops`` package
``__init__`` imports the jax cost model, and this module must be
importable by the daemon BEFORE its warm thread pays the backend
attach.
"""

from __future__ import annotations

import sys
from typing import Any, Dict, Optional

# the memory_stats keys worth exporting, when the backend reports them
_KEYS = ("bytes_in_use", "bytes_limit", "peak_bytes_in_use")


def device_memory_stats(device: Any = None) -> Optional[Dict[str, int]]:
    """Live memory stats for ``device`` (default: device 0), or None.

    None means "not knowable right now": jax not yet imported (the
    jax-free-safe contract — this function NEVER triggers the import),
    no device, or a backend without memory introspection. Never raises.

    CALLER CONTRACT for ``device=None``: only call once the backend is
    known-attached (the daemon gates on its warm-done latch) —
    ``jax.devices()`` on a merely-imported jax would BLOCK the calling
    thread on the backend attach, exactly the stall the scrape paths
    must never pay (a hello during the warm window would stop
    answering). An explicit ``device`` is always safe: holding the
    object means someone already paid the attach.
    """
    if "jax" not in sys.modules:
        return None
    try:
        import jax  # already imported per the guard above

        dev = device if device is not None else jax.devices()[0]
        stats = dev.memory_stats()
        if not stats:
            return None
        out: Dict[str, int] = {}
        for key in _KEYS:
            v = stats.get(key)
            if isinstance(v, int) and not isinstance(v, bool):
                out[key] = v
        return out or None
    except Exception:
        return None
