"""Client-side edge residency: the shadow digest cache (protocol v2).

BENCH_r06 proved the daemon is no longer the hot path — a speculative
memo hit is ~0.12 ms daemon-side but ~132 ms end-to-end, because every
steady-state request still re-reads, re-canonicalizes and re-digests the
FULL cluster client-side (O(P) at 10k rows). This module makes the
client resident too: a per-tenant cache entry persisted beside the
daemon socket remembers the last input's identity, canonical rows and
digest, so an outer-loop process tree shares it across invocations.

Three rungs, strongest first (cli.py walks them top to bottom):

1. **stat hit** — the input file's ``(st_dev, st_ino, st_mtime_ns,
   st_size)`` matches the entry and the entry is *stable* (written
   safely outside the file's mtime tick): the client skips
   read+canonicalize+digest entirely and goes straight to the
   ``plan-delta`` op with the cached digest. O(1).
2. **content hit** — the stat key is doubtful (*unstable* entry: the
   write landed within one mtime tick of the entry's own persist — the
   PR-2 manifest staleness bug class, now client-side — or the stat key
   changed but the bytes may not have): the client reads the input and
   memcmp's it against the cached text. Equal ⇒ the cached digest is
   proven; an unstable entry re-verified after the tick closes is
   promoted to stable. O(P) read, zero parse.
3. **incremental splice** — the text changed: the entry's per-row
   character offsets let the client align the common prefix/suffix of
   old and new text to row boundaries and re-parse ONLY the middle
   region, splicing cached canonical rows around it. The digest is one
   sha256 pass over the spliced frames — O(changed) parse instead of
   O(P). Any structural surprise (header/footer drift, separator
   soup, a field the codecs reader would reject) degrades to the full
   parse.

The correctness contract mirrors the spill tier (serve/state.py KBSP):
an entry that is truncated, bit-flipped, format-skewed or written by a
foreign platform NEVER resolves — every read is checksummed before
trust, and every degradation lands on the full read+parse path. The
cache can cost a re-read; it can never produce a wrong digest. Even a
hypothetically wrong digest could not produce a wrong plan: the
daemon's session digest gate (serve/sessions.py) degrades a mismatch
to a row or full resync, and the resync rows are re-derived from real
content.

The ``-from-zk`` fast path (:func:`probe_zk`) applies the same idea to
the PR-15 watcher seam: the client reads ``/brokers/topics`` itself
(FileZkClient or kazoo), keeps a per-topic payload-hash index in the
entry, and on a change re-decodes ONLY the changed topics, splicing
the synthesized version-1 JSON (codecs/writer.py byte-compatible
encoder) around the cached row spans. The synthesized text then rides
the ordinary session ladder — tenant ``zk:<conn>`` — so a steady
cluster costs one digest exchange instead of a daemon-side ZK walk.

Entry format (one file per tenant, ``<socket>.edge/<sha-24>.kbec``):

    magic "KBEC" | u32 format version | u32 header_len | header JSON
    | 32-byte sha256 over everything before it   (header checksum)
    | text utf-8 | row offsets (2 x u64 per row, character indices)
    | canonical frames (u32 len + bytes per row) | row-hash table
    | 32-byte sha256 over everything before it   (full checksum)

The doubled checksum is what makes rung 1 cheap: a stat hit reads and
verifies ONLY the head (~4 KB) — digest, row count and version live in
the header — while anything that needs the body (resync, splice,
register) verifies the full trailer first.
"""

from __future__ import annotations

import bisect
import hashlib
import json
import os
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from kafkabalancer_tpu.serve import state as sstate

EC_MAGIC = b"KBEC"
EC_FORMAT_VERSION = 1

_EC_HEAD = struct.Struct(">4sII")
_EC_OFF = struct.Struct(">QQ")
_EC_SUM_BYTES = 32
_EC_MAX_HEADER = 1 << 20
# how much of the entry head to read for a stat probe before deciding
# whether the header needs more bytes
_EC_PROBE_BYTES = 4096

# A persist that lands within this window of the input's mtime cannot
# rule out a later same-tick rewrite (coarse filesystem timestamps,
# in-place writers): the entry is marked unstable and rung 1 degrades
# to a content memcmp until a later probe re-proves it after the tick
# closed.
UNSTABLE_WINDOW_NS = 2_000_000_000

_WS = " \t\n\r"


class EdgeCacheError(Exception):
    """A lazy body load that could not be satisfied from the entry OR
    from re-reading the input source — the caller degrades to the
    non-cached path."""


class _Corrupt(ValueError):
    """An entry that must not resolve (internal)."""


def cache_dir(sock: str) -> str:
    """The per-daemon cache directory, beside the socket like the
    spill directory — same lifecycle, same tenancy."""
    return sock + ".edge"


def entry_path(sock: str, tenant: str) -> str:
    name = hashlib.sha256(tenant.encode("utf-8")).hexdigest()[:24]
    return os.path.join(cache_dir(sock), name + ".kbec")


def _now_ns() -> int:
    return time.time_ns()


# --- entry codec -----------------------------------------------------------


class _Entry:
    """One loaded cache entry. The header is always present and
    checksum-verified; the body (text / offsets / canon / hashes) is
    loaded lazily and verified against the full-file trailer before
    first use."""

    __slots__ = (
        "path", "header", "text", "offsets", "canon", "hashes",
        "_body_loaded",
    )

    def __init__(self, path: str, header: Dict[str, object]) -> None:
        self.path = path
        self.header = header
        self.text: Optional[str] = None
        self.offsets: Optional[List[Tuple[int, int]]] = None
        self.canon: Optional[List[bytes]] = None
        self.hashes: Optional[List[bytes]] = None
        self._body_loaded = False

    # typed header accessors (validated in _check_header)
    @property
    def digest(self) -> str:
        return self.header["digest"]  # type: ignore[return-value]

    @property
    def version(self) -> int:
        return self.header["version"]  # type: ignore[return-value]

    @property
    def nrows(self) -> int:
        return self.header["rows"]  # type: ignore[return-value]

    def stat_key(self) -> Tuple[int, int, int, int]:
        h = self.header
        return (
            h.get("dev", 0), h.get("ino", 0),
            h.get("mtime_ns", 0), h.get("size", 0),
        )  # type: ignore[return-value]

    def load_body(self) -> None:
        if self._body_loaded:
            return
        with open(self.path, "rb") as f:
            buf = f.read()
        text, offsets, canon, hashes = _unpack_body(buf, self.header)
        self.text = text
        self.offsets = offsets
        self.canon = canon
        self.hashes = hashes
        self._body_loaded = True


def _check_header(hdr: object) -> Dict[str, object]:
    if not isinstance(hdr, dict):
        raise _Corrupt("entry header is not a JSON object")
    if hdr.get("platform") != sstate.spill_platform():
        raise _Corrupt("foreign-platform entry")
    digest = hdr.get("digest")
    if not isinstance(digest, str) or len(digest) != 64:
        raise _Corrupt("entry header digest is malformed")
    for key in ("version", "rows", "text_len", "offsets_len",
                "canon_len", "hashes_len"):
        v = hdr.get(key)
        if isinstance(v, bool) or not isinstance(v, int) or v < 0:
            raise _Corrupt(f"entry header {key} is malformed")
    n = hdr["rows"]
    if hdr["hashes_len"] != n * sstate.ROW_HASH_BYTES:
        raise _Corrupt("entry hash table length disagrees with row count")
    if hdr["offsets_len"] not in (0, n * _EC_OFF.size):
        raise _Corrupt("entry offsets length disagrees with row count")
    return hdr


def _parse_head(buf: bytes) -> Tuple[Dict[str, object], int]:
    """Validate the entry head from an initial read; returns
    (header, body_offset). Raises :class:`_Corrupt` if ``buf`` is not
    a well-formed, checksummed head (callers re-read with more bytes
    when ``buf`` was merely too short — that surfaces as truncation
    here, so they check the needed length first)."""
    if len(buf) < _EC_HEAD.size:
        raise _Corrupt("truncated entry head")
    magic, fmt, hlen = _EC_HEAD.unpack_from(buf, 0)
    if magic != EC_MAGIC:
        raise _Corrupt(f"bad entry magic {magic!r}")
    if fmt != EC_FORMAT_VERSION:
        raise _Corrupt(f"entry format version {fmt}")
    if hlen > _EC_MAX_HEADER:
        raise _Corrupt(f"entry header length {hlen} is absurd")
    need = _EC_HEAD.size + hlen + _EC_SUM_BYTES
    if len(buf) < need:
        raise _Corrupt("truncated entry header")
    body = buf[:_EC_HEAD.size + hlen]
    want = buf[_EC_HEAD.size + hlen: need]
    if hashlib.sha256(body).digest() != want:
        raise _Corrupt("entry header checksum mismatch")
    try:
        hdr = json.loads(buf[_EC_HEAD.size: _EC_HEAD.size + hlen])
    except ValueError as exc:
        raise _Corrupt(f"entry header is not JSON: {exc}") from None
    return _check_header(hdr), need


def _header_need(buf: bytes) -> int:
    """How many bytes a complete head needs, from a partial read."""
    if len(buf) < _EC_HEAD.size:
        raise _Corrupt("truncated entry head")
    magic, fmt, hlen = _EC_HEAD.unpack_from(buf, 0)
    if magic != EC_MAGIC or fmt != EC_FORMAT_VERSION:
        raise _Corrupt("bad entry head")
    if hlen > _EC_MAX_HEADER:
        raise _Corrupt("entry header length is absurd")
    return _EC_HEAD.size + hlen + _EC_SUM_BYTES


def _unpack_body(
    buf: bytes, header: Dict[str, object]
) -> Tuple[str, Optional[List[Tuple[int, int]]], List[bytes], List[bytes]]:
    """Full-file verification + section slicing. The trailer checksum
    is verified BEFORE any decode — a bit-flipped body is rejected
    wholesale, never partially trusted."""
    hdr2, off = _parse_head(buf)
    if hdr2 != header:
        raise _Corrupt("entry header changed between probe and body load")
    if len(buf) < off + _EC_SUM_BYTES:
        raise _Corrupt("truncated entry (no trailer)")
    body, want = buf[:-_EC_SUM_BYTES], buf[-_EC_SUM_BYTES:]
    if hashlib.sha256(body).digest() != want:
        raise _Corrupt("entry checksum mismatch")
    tl = header["text_len"]
    ol = header["offsets_len"]
    cl = header["canon_len"]
    hl = header["hashes_len"]
    if off + tl + ol + cl + hl != len(body):  # type: ignore[operator]
        raise _Corrupt("entry section lengths disagree with record size")
    try:
        text = buf[off: off + tl].decode("utf-8")  # type: ignore[misc]
    except UnicodeDecodeError as exc:
        raise _Corrupt(f"entry text is not utf-8: {exc}") from None
    p = off + tl  # type: ignore[operator]
    offsets: Optional[List[Tuple[int, int]]] = None
    if ol:
        offsets = [
            _EC_OFF.unpack_from(buf, p + i * _EC_OFF.size)
            for i in range(ol // _EC_OFF.size)  # type: ignore[operator]
        ]
    p += ol  # type: ignore[operator]
    canon: List[bytes] = []
    end = p + cl  # type: ignore[operator]
    n = header["rows"]
    while p < end:
        if p + 4 > end:
            raise _Corrupt("truncated canonical frame header")
        flen = int.from_bytes(buf[p: p + 4], "big")
        p += 4
        if p + flen > end:
            raise _Corrupt("truncated canonical frame")
        canon.append(buf[p: p + flen])
        p += flen
    if len(canon) != n:
        raise _Corrupt("canonical frame count disagrees with row count")
    hashes = [
        buf[end + i * sstate.ROW_HASH_BYTES:
            end + (i + 1) * sstate.ROW_HASH_BYTES]
        for i in range(n)  # type: ignore[arg-type]
    ]
    if offsets is not None:
        tlen = len(text)
        last = 0
        for (s, e) in offsets:
            if not (last <= s < e <= tlen):
                raise _Corrupt("entry row offsets are not monotonic")
            last = e
    return text, offsets, canon, hashes


def _pack_entry(
    header: Dict[str, object],
    text: str,
    offsets: Optional[Sequence[Tuple[int, int]]],
    canon: Sequence[bytes],
    hashes: Sequence[bytes],
) -> bytes:
    tb = text.encode("utf-8")
    ob = (
        b"".join(_EC_OFF.pack(s, e) for (s, e) in offsets)
        if offsets else b""
    )
    cb = b"".join(len(c).to_bytes(4, "big") + c for c in canon)
    hb = b"".join(hashes)
    hdr = dict(header)
    hdr["rows"] = len(canon)
    hdr["platform"] = sstate.spill_platform()
    hdr["text_len"] = len(tb)
    hdr["offsets_len"] = len(ob)
    hdr["canon_len"] = len(cb)
    hdr["hashes_len"] = len(hb)
    hj = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
    head = _EC_HEAD.pack(EC_MAGIC, EC_FORMAT_VERSION, len(hj))
    body = b"".join((
        head, hj, hashlib.sha256(head + hj).digest(), tb, ob, cb, hb,
    ))
    return body + hashlib.sha256(body).digest()


# --- in-memory layer -------------------------------------------------------
#
# In-process outer loops (the bench probe, the replay harness) call
# cli.run repeatedly in one process; re-reading and re-verifying the
# entry file every step would dominate the stat-hit budget. The memory
# layer caches parsed entries keyed by entry path, validated against
# the entry FILE's own stat on every probe so a cross-process update
# is always observed.

_mem_lock = threading.Lock()
_mem: Dict[str, Tuple[Tuple[int, int, int], _Entry]] = {}


def _entry_file_key(path: str) -> Optional[Tuple[int, int, int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_ino, st.st_mtime_ns, st.st_size)


def _load_entry(path: str) -> Optional[_Entry]:
    """Header-verified entry at ``path`` (memory layer first), or None
    when absent/corrupt — corruption is silently a miss."""
    fkey = _entry_file_key(path)
    if fkey is None:
        return None
    with _mem_lock:
        hit = _mem.get(path)
        if hit is not None and hit[0] == fkey:
            return hit[1]
    try:
        with open(path, "rb") as f:
            buf = f.read(_EC_PROBE_BYTES)
            try:
                need = _header_need(buf)
            except _Corrupt:
                return None
            if need > len(buf):
                buf += f.read(need - len(buf))
        header, _off = _parse_head(buf)
    except (OSError, _Corrupt):
        return None
    entry = _Entry(path, header)
    with _mem_lock:
        _mem[path] = (fkey, entry)
    return entry


def _store_entry(
    sock: str,
    tenant: str,
    header: Dict[str, object],
    text: str,
    offsets: Optional[Sequence[Tuple[int, int]]],
    canon: Sequence[bytes],
    hashes: Sequence[bytes],
) -> None:
    """Atomic tmp+rename persist; failures are silent (the cache is an
    optimization, never a correctness dependency)."""
    path = entry_path(sock, tenant)
    try:
        d = cache_dir(sock)
        os.makedirs(d, mode=0o700, exist_ok=True)
        blob = _pack_entry(header, text, offsets, canon, hashes)
        tmp = f"{path}.tmp-{os.getpid()}"
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, path)
    except OSError:
        return
    # re-parse our own blob's header for the memory layer (cheap, and
    # guarantees the cached object matches what a fresh load would see)
    fkey = _entry_file_key(path)
    if fkey is None:
        return
    try:
        header2, _off = _parse_head(blob)
    except _Corrupt:
        return
    entry = _Entry(path, header2)
    entry.text = text
    entry.offsets = list(offsets) if offsets is not None else None
    entry.canon = list(canon)
    entry.hashes = list(hashes)
    entry._body_loaded = True
    with _mem_lock:
        _mem[path] = (fkey, entry)


def reset_memory_layer() -> None:
    """Test hook: drop the in-process layer (disk entries survive)."""
    with _mem_lock:
        _mem.clear()


# --- lazy client-state view ------------------------------------------------


class _LazyRows:
    """``state.rows`` for a cached state: row ``i`` parses on demand
    from the text via its character offsets (JSON entries), with a
    one-shot full-parse fallback for describe-format entries."""

    def __init__(self, owner: "CachedState") -> None:
        self._owner = owner
        self._cache: Dict[int, sstate.RowFields] = {}
        self._full: Optional[List[sstate.RowFields]] = None

    def seed(self, idx: int, fields: sstate.RowFields) -> None:
        self._cache[idx] = fields

    def __len__(self) -> int:
        return self._owner.nrows

    def __getitem__(self, idx: int) -> sstate.RowFields:
        got = self._cache.get(idx)
        if got is not None:
            return got
        if self._full is not None:
            return self._full[idx]
        owner = self._owner
        offsets = owner._offsets()
        if offsets is not None:
            text = owner.load_text()
            s, e = offsets[idx]
            try:
                fields = sstate.row_fields_from_obj(json.loads(text[s:e]))
            except (ValueError, sstate._BadField) as exc:
                raise EdgeCacheError(f"cached row {idx}: {exc}") from None
            self._cache[idx] = fields
            return fields
        full = sstate.client_state(
            owner.load_text(), owner.is_json, owner.topics
        )
        if full is None or full.digest != owner.digest:
            raise EdgeCacheError("cached text no longer parses to digest")
        self._full = full.rows
        return full.rows[idx]


class CachedState:
    """Duck-type of :class:`serve.state.ClientState` whose expensive
    members load lazily. A pure stat hit materializes ONLY the digest,
    version and row count (from the checksummed entry header); canon,
    rows, row hashes and text load on first touch — which only the
    rare resync/register paths ever do. Every lazy load falls back to
    re-reading the input source itself before giving up with
    :class:`EdgeCacheError`."""

    __slots__ = (
        "digest", "version", "nrows", "is_json", "topics", "_entry",
        "_path", "_text", "_canon", "_hashes", "_offs", "rows",
    )

    def __init__(
        self,
        digest: str,
        version: int,
        nrows: int,
        is_json: bool,
        topics: Optional[List[str]],
        entry: Optional[_Entry] = None,
        path: str = "",
        text: Optional[str] = None,
        canon: Optional[List[bytes]] = None,
        hashes: Optional[List[bytes]] = None,
        offsets: Optional[List[Tuple[int, int]]] = None,
    ) -> None:
        self.digest = digest
        self.version = version
        self.nrows = nrows
        self.is_json = is_json
        self.topics = topics
        self._entry = entry
        self._path = path
        self._text = text
        self._canon = canon
        self._hashes = hashes
        self._offs = offsets
        self.rows = _LazyRows(self)

    def _load_entry_body(self) -> Optional[_Entry]:
        e = self._entry
        if e is None:
            return None
        try:
            e.load_body()
        except (OSError, _Corrupt):
            return None
        return e

    def _full_reparse(self) -> None:
        """Last resort: the entry body is gone/corrupt — re-read the
        input file and recompute. Content is re-derived from the real
        source, so a corrupt cache can cost a read but never a wrong
        row."""
        if self._path == "":
            raise EdgeCacheError("entry body unavailable and no source path")
        try:
            with open(self._path, "r", encoding="utf-8") as f:
                text = f.read()
        except (OSError, UnicodeDecodeError) as exc:
            raise EdgeCacheError(f"re-read failed: {exc}") from None
        full = sstate.client_state(text, self.is_json, self.topics)
        if full is None:
            raise EdgeCacheError("re-read input no longer parses")
        self._text = text
        self._canon = full.canon
        self._hashes = None
        self._offs = None
        self.rows._full = full.rows
        # NOTE: if the file changed since the stat probe, this digest
        # may differ from the one already sent; the daemon's digest
        # gate turns that into a resync against these (real) rows.
        self.digest = full.digest
        self.version = full.version
        self.nrows = len(full.canon)

    def load_text(self) -> str:
        if self._text is not None:
            return self._text
        e = self._load_entry_body()
        if e is not None and e.text is not None:
            self._text = e.text
            return e.text
        self._full_reparse()
        assert self._text is not None
        return self._text

    def _offsets(self) -> Optional[List[Tuple[int, int]]]:
        if self._offs is not None:
            return self._offs
        e = self._load_entry_body()
        if e is not None:
            self._offs = e.offsets
            return e.offsets
        return None

    @property
    def canon(self) -> List[bytes]:
        if self._canon is not None:
            return self._canon
        e = self._load_entry_body()
        if e is not None and e.canon is not None:
            self._canon = e.canon
            return e.canon
        self._full_reparse()
        assert self._canon is not None
        return self._canon

    @property
    def row_hashes(self) -> List[bytes]:
        if self._hashes is not None:
            return self._hashes
        e = self._load_entry_body()
        if e is not None and e.hashes is not None:
            self._hashes = e.hashes
            return e.hashes
        self._hashes = sstate.hashes_of(self.canon)
        return self._hashes


# --- row-offset construction (JSON inputs) ---------------------------------


def build_offsets(
    text: str, canon: Sequence[bytes]
) -> Optional[List[Tuple[int, int]]]:
    """Character offsets of every partition object in ``text``,
    verified row-for-row against the authoritative ``canon`` (the full
    parse's output). None on ANY structural doubt — an entry without
    offsets still serves stat/content hits, it just cannot splice."""
    if not canon:
        return None
    if text.count('"partitions"') != 1:
        return None
    dec = json.JSONDecoder()
    p = text.find('"partitions"') + len('"partitions"')
    n = len(text)
    try:
        while p < n and text[p] in _WS:
            p += 1
        if p >= n or text[p] != ":":
            return None
        p += 1
        while p < n and text[p] in _WS:
            p += 1
        if p >= n or text[p] != "[":
            return None
        p += 1
        offsets: List[Tuple[int, int]] = []
        need_obj = True  # '[' just opened: object or ']' next
        while True:
            while p < n and text[p] in _WS:
                p += 1
            if p >= n:
                return None
            c = text[p]
            if c == "]":
                if offsets and need_obj:
                    return None  # trailing comma
                break
            if c == ",":
                if need_obj:
                    return None
                need_obj = True
                p += 1
                continue
            if not need_obj:
                return None
            i = len(offsets)
            if i >= len(canon):
                return None
            obj, end = dec.raw_decode(text, p)
            fields = sstate.row_fields_from_obj(obj)
            if sstate.canonical_row_bytes(*fields) != canon[i]:
                return None
            offsets.append((p, end))
            p = end
            need_obj = False
    except (ValueError, sstate._BadField):
        return None
    if len(offsets) != len(canon):
        return None
    return offsets


# --- incremental splice ----------------------------------------------------


def _common_prefix(a: str, b: str) -> int:
    n = min(len(a), len(b))
    p = 0
    step = 1 << 16
    while p < n:
        q = min(p + step, n)
        if a[p:q] == b[p:q]:
            p = q
            continue
        lo, hi = p, q
        while lo < hi:
            mid = (lo + hi) // 2
            if a[p:mid + 1] == b[p:mid + 1]:
                lo = mid + 1
            else:
                hi = mid
        return lo
    return n


def _common_suffix(a: str, b: str, limit: int) -> int:
    n = min(len(a), len(b), limit)
    s = 0
    step = 1 << 16
    while s < n:
        q = min(s + step, n)
        if a[len(a) - q:len(a) - s or None] == b[len(b) - q:len(b) - s or None]:
            s = q
            continue
        lo, hi = s, q
        while lo < hi:
            mid = (lo + hi) // 2
            if a[len(a) - mid - 1:len(a) - s or None] == (
                b[len(b) - mid - 1:len(b) - s or None]
            ):
                lo = mid + 1
            else:
                hi = mid
        return lo
    return n


def _scan_middle(
    text: str, m0: int, m1: int, items_before: bool, items_after: bool
) -> Optional[Tuple[List[object], List[Tuple[int, int]]]]:
    """Strictly validate the changed region of the new text as a
    partial partitions-array body: objects and separating commas only,
    comma placement consistent with the surrounding unchanged rows.
    None on any doubt — the caller degrades to the full parse."""
    dec = json.JSONDecoder()
    p = m0
    objs: List[object] = []
    offs: List[Tuple[int, int]] = []
    have_prev = items_before
    need_obj = False  # a comma was consumed and awaits its object
    while True:
        while p < m1 and text[p] in _WS:
            p += 1
        if p >= m1:
            break
        c = text[p]
        if c == ",":
            if not have_prev or need_obj:
                return None
            need_obj = True
            p += 1
            continue
        if have_prev and not need_obj:
            return None
        try:
            obj, end = dec.raw_decode(text, p)
        except ValueError:
            return None
        if end > m1:
            return None
        objs.append(obj)
        offs.append((p, end))
        p = end
        have_prev = True
        need_obj = False
    if items_after:
        if not need_obj:
            return None
    else:
        if need_obj:
            return None
    return objs, offs


def splice_state(
    entry: _Entry,
    new_text: str,
    is_json: bool,
    topics: Optional[List[str]],
    path: str,
) -> Optional[CachedState]:
    """The O(changed) rung: align old and new text on the common
    prefix/suffix, re-parse only the middle, splice cached canonical
    rows around it. None whenever ANY invariant is in doubt; the
    result's digest is then provably what the full parse would
    compute, because byte-identical prefix/suffix rows parse
    identically and the middle went through the very same
    ``row_fields_from_obj`` the full pass uses."""
    try:
        entry.load_body()
    except (OSError, _Corrupt):
        return None
    old = entry.text
    offsets = entry.offsets
    old_canon = entry.canon
    old_hashes = entry.hashes
    if old is None or offsets is None or old_canon is None or (
        old_hashes is None
    ):
        return None
    n = len(offsets)
    if n == 0:
        return None
    pre = _common_prefix(old, new_text)
    suf = _common_suffix(old, new_text, min(len(old), len(new_text)) - pre)
    # header (everything before row 0) must sit inside the common
    # prefix, footer (everything after the last row) inside the common
    # suffix: then the new document's top-level structure is
    # byte-identical and only array members changed.
    if offsets[0][0] > pre:
        return None
    if len(old) - offsets[-1][1] > suf:
        return None
    delta = len(new_text) - len(old)
    # rows fully inside the prefix / suffix
    ends = [e for (_s, e) in offsets]
    starts = [s for (s, _e) in offsets]
    i0 = bisect.bisect_right(ends, pre)
    j0 = bisect.bisect_left(starts, len(old) - suf)
    if j0 < i0:
        return None
    m0 = offsets[i0 - 1][1] if i0 > 0 else offsets[0][0]
    m1 = (offsets[j0][0] + delta) if j0 < n else (offsets[n - 1][1] + delta)
    if m1 < m0:
        return None
    scanned = _scan_middle(new_text, m0, m1, i0 > 0, j0 < n)
    if scanned is None:
        return None
    objs, mid_offs = scanned
    try:
        mid_fields = [sstate.row_fields_from_obj(o) for o in objs]
    except sstate._BadField:
        return None
    mid_canon = [sstate.canonical_row_bytes(*f) for f in mid_fields]
    new_canon = old_canon[:i0] + mid_canon + old_canon[j0:]
    if not new_canon:
        return None  # the reader rejects an empty partition list
    new_offsets = (
        offsets[:i0]
        + mid_offs
        + [(s + delta, e + delta) for (s, e) in offsets[j0:]]
    )
    new_hashes = (
        old_hashes[:i0]
        + [sstate.row_hash(c) for c in mid_canon]
        + old_hashes[j0:]
    )
    version = entry.version
    state = CachedState(
        digest=sstate.rows_digest(version, new_canon),
        version=version,
        nrows=len(new_canon),
        is_json=is_json,
        topics=topics,
        entry=None,
        path=path,
        text=new_text,
        canon=new_canon,
        hashes=new_hashes,
        offsets=new_offsets,
    )
    for k, f in enumerate(mid_fields):
        state.rows.seed(i0 + k, f)
    return state


# --- file probe / resolve / persist ----------------------------------------


class FileProbe:
    """The result of rung-1 classification for one input file."""

    __slots__ = (
        "sock", "tenant", "path", "is_json", "topics", "stat",
        "entry", "state", "hit", "needs_text", "note",
    )

    def __init__(
        self, sock: str, tenant: str, path: str,
        is_json: bool, topics: Optional[List[str]],
    ) -> None:
        self.sock = sock
        self.tenant = tenant
        self.path = path
        self.is_json = is_json
        self.topics = topics
        self.stat: Optional[Tuple[int, int, int, int]] = None
        self.entry: Optional[_Entry] = None
        self.state: Optional[CachedState] = None
        self.hit = False
        self.needs_text = True
        self.note = "miss"


def _stat_key(path: str) -> Optional[Tuple[int, int, int, int]]:
    try:
        st = os.stat(path)
    except OSError:
        return None
    return (st.st_dev, st.st_ino, st.st_mtime_ns, st.st_size)


def _entry_matches(
    entry: _Entry, tenant: str, is_json: bool, topics: Optional[List[str]]
) -> bool:
    h = entry.header
    return (
        h.get("tenant") == tenant
        and h.get("is_json") == is_json
        and h.get("topics") == (topics or [])
        and "zk" not in h
    )


def probe_file(
    sock: str,
    tenant: str,
    path: str,
    is_json: bool,
    topics: Optional[List[str]],
) -> FileProbe:
    """Rung 1: stat the input, load the entry header, classify.

    ``probe.needs_text == False`` means a proven stat hit: the caller
    may skip the input read entirely and use ``probe.state``.
    Otherwise the caller reads the text and calls
    :func:`resolve_text`."""
    probe = FileProbe(sock, tenant, path, is_json, topics)
    try:
        probe.stat = _stat_key(path)
        entry = _load_entry(entry_path(sock, tenant))
        if entry is not None and not _entry_matches(
            entry, tenant, is_json, topics
        ):
            entry = None
        probe.entry = entry
        if probe.stat is None or entry is None:
            return probe
        if entry.stat_key() != probe.stat:
            probe.note = "stat_changed"
            return probe
        state = CachedState(
            digest=entry.digest,
            version=entry.version,
            nrows=entry.nrows,
            is_json=is_json,
            topics=topics,
            entry=entry,
            path=path,
        )
        if entry.header.get("unstable"):
            # same-tick persist: the stat key cannot prove content
            # identity — verify by memcmp (rung 2)
            probe.state = state
            probe.note = "unstable"
            return probe
        probe.state = state
        probe.hit = True
        probe.needs_text = False
        probe.note = "stat_hit"
        return probe
    except Exception:
        return FileProbe(sock, tenant, path, is_json, topics)


def resolve_text(
    probe: FileProbe, text: str
) -> Tuple[Optional[CachedState], bool]:
    """Rungs 2 and 3, with the text in hand: content memcmp against
    the cached text (proves the cached digest; promotes a stable
    entry), else the incremental splice. ``(None, False)`` sends the
    caller to the full parse."""
    entry = probe.entry
    if entry is None:
        return None, False
    try:
        try:
            entry.load_body()
        except (OSError, _Corrupt):
            return None, False
        if entry.text == text:
            state = probe.state or CachedState(
                digest=entry.digest,
                version=entry.version,
                nrows=entry.nrows,
                is_json=probe.is_json,
                topics=probe.topics,
                entry=entry,
                path=probe.path,
            )
            state._text = text
            if probe.stat is not None and (
                entry.stat_key() != probe.stat
                or entry.header.get("unstable")
            ):
                # same bytes under a new/unproven stat key: re-persist
                # so the next probe can stat-hit
                persist_state(
                    probe.sock, probe.tenant, probe.path,
                    probe.is_json, probe.topics, text, state,
                    pre_stat=probe.stat,
                )
            return state, True
        if not probe.is_json:
            return None, False
        state = splice_state(
            entry, text, probe.is_json, probe.topics, probe.path
        )
        if state is None:
            return None, False
        persist_state(
            probe.sock, probe.tenant, probe.path, probe.is_json,
            probe.topics, text, state, pre_stat=probe.stat,
        )
        return state, False
    except Exception:
        return None, False


def persist_state(
    sock: str,
    tenant: str,
    path: str,
    is_json: bool,
    topics: Optional[List[str]],
    text: str,
    state: object,
    pre_stat: Optional[Tuple[int, int, int, int]],
) -> None:
    """Persist a computed state for the NEXT invocation. The stat key
    is re-taken now and the entry only lands if it matches the probe's
    (the text provably belongs to one stable stat point); a persist
    within the mtime tick is marked unstable so rung 1 keeps
    re-verifying content until the tick closes."""
    try:
        st = _stat_key(path)
        # pre_stat is REQUIRED: the caller stats before reading the
        # text, and the entry only lands when the file provably sat
        # still across the read — otherwise a rewrite between read and
        # persist would key someone else's bytes to the new stat point
        # and the next probe would serve a wrong digest.
        if st is None or pre_stat is None or st != pre_stat:
            return
        canon = list(state.canon)  # type: ignore[attr-defined]
        version = int(state.version)  # type: ignore[attr-defined]
        digest = state.digest  # type: ignore[attr-defined]
        hashes = getattr(state, "row_hashes", None)
        if hashes is None:
            hashes = sstate.hashes_of(canon)
        else:
            hashes = list(hashes)
        offsets = None
        if is_json:
            offsets = getattr(state, "_offs", None)
            if offsets is None:
                # a content-hit promotion re-persists the SAME text the
                # entry already indexed — reuse its offsets instead of
                # paying the O(P) raw_decode walk again (guarded by
                # byte equality, the same proof the hit itself used)
                ent = getattr(state, "_entry", None)
                if ent is not None:
                    try:
                        ent.load_body()
                        if ent.text == text:
                            offsets = ent.offsets
                    except (OSError, _Corrupt):
                        offsets = None
            if offsets is None:
                offsets = build_offsets(text, canon)
        unstable = (_now_ns() - st[2]) <= UNSTABLE_WINDOW_NS
        header: Dict[str, object] = {
            "tenant": tenant,
            "path": path,
            "dev": st[0],
            "ino": st[1],
            "mtime_ns": st[2],
            "size": st[3],
            "is_json": is_json,
            "topics": topics or [],
            "digest": digest,
            "version": version,
            "unstable": bool(unstable),
        }
        _store_entry(sock, tenant, header, text, offsets, canon, hashes)
    except Exception:
        return


# --- the -from-zk fast path ------------------------------------------------


class ZkResult:
    """A successful client-side ZK read: the synthesized version-1
    JSON text (byte-identical to ``encode_partition_list`` over
    ``read_cluster``'s rows), its state, and whether the per-topic
    payload index proved the whole cluster unchanged."""

    __slots__ = ("state", "hit", "changed_topics")

    def __init__(
        self, state: CachedState, hit: bool, changed_topics: int
    ) -> None:
        self.state = state
        self.hit = hit
        self.changed_topics = changed_topics


_ZK_TEXT_HEAD = '{"version":1,"partitions":['
_ZK_TEXT_TAIL = ']}\n'


def _zk_rows_for_topic(
    topic: str, data: bytes
) -> Tuple[List[str], List[sstate.RowFields]]:
    """Decode one topic payload into per-row JSON texts + fields,
    byte-compatible with ``codecs.writer._encode_partition`` over
    ``decode_topic_state``'s partitions."""
    from kafkabalancer_tpu.codecs import writer as _writer
    from kafkabalancer_tpu.codecs.zookeeper import decode_topic_state

    parts = decode_topic_state(topic, data)
    texts = [_writer._encode_partition(p) for p in parts]
    fields = [sstate.partition_fields(p) for p in parts]
    return texts, fields


def probe_zk(
    sock: str, conn: str, topics: Optional[List[str]]
) -> Optional[ZkResult]:
    """Client-side ``-from-zk`` read through the watcher seam
    (FileZkClient / kazoo / installed factory), with per-topic
    payload-hash change detection: an unchanged cluster resolves to
    the cached digest without decoding a single topic; a changed one
    re-decodes ONLY the changed topics and splices text/canon around
    the cached row spans. None on ANY doubt (connect failure, decode
    error, topic-set drift with an unusable cache…) — the caller
    degrades to forwarding ``-from-zk`` for the daemon to read,
    byte-identical behaviour."""
    from kafkabalancer_tpu.codecs.zookeeper import make_zk_client

    tenant = f"zk:{conn}"
    try:
        zk = make_zk_client(conn)
    except Exception:
        return None
    payloads: List[Tuple[str, bytes]] = []
    try:
        names = sorted(zk.get_children("/brokers/topics"))
        for t in names:
            if topics and t not in topics:
                continue
            data, _st = zk.get(f"/brokers/topics/{t}")
            payloads.append((t, data))
    except Exception:
        return None
    finally:
        try:
            zk.stop()
            zk.close()
        except Exception:
            pass
    try:
        return _resolve_zk(sock, conn, tenant, topics, payloads)
    except Exception:
        return None


def _zk_entry_index(entry: _Entry) -> Optional[List[Tuple[str, str, int, int]]]:
    zki = entry.header.get("zk")
    if not isinstance(zki, dict) or not isinstance(zki.get("topics"), list):
        return None
    out: List[Tuple[str, str, int, int]] = []
    for item in zki["topics"]:  # type: ignore[index]
        if not (isinstance(item, list) and len(item) == 4):
            return None
        t, sha, r0, r1 = item
        if not (isinstance(t, str) and isinstance(sha, str)
                and isinstance(r0, int) and isinstance(r1, int)):
            return None
        out.append((t, sha, r0, r1))
    return out


def _resolve_zk(
    sock: str,
    conn: str,
    tenant: str,
    topics: Optional[List[str]],
    payloads: List[Tuple[str, bytes]],
) -> Optional[ZkResult]:
    cur = [
        (t, hashlib.sha256(data).hexdigest()) for (t, data) in payloads
    ]
    entry = _load_entry(entry_path(sock, tenant))
    index = None
    if entry is not None:
        h = entry.header
        if (
            h.get("tenant") == tenant
            and h.get("topics") == (topics or [])
            and h.get("is_json") is True
        ):
            index = _zk_entry_index(entry)
        if index is None:
            entry = None

    if entry is not None and index is not None and (
        [(t, sha) for (t, sha, _r0, _r1) in index] == cur
    ):
        # whole cluster unchanged: digest from the verified header,
        # body stays lazy
        state = CachedState(
            digest=entry.digest,
            version=entry.version,
            nrows=entry.nrows,
            is_json=True,
            topics=topics,
            entry=entry,
        )
        return ZkResult(state, hit=True, changed_topics=0)

    reuse: Dict[str, Tuple[str, int, int]] = {}
    if entry is not None and index is not None and (
        [t for (t, _sha, _r0, _r1) in index] == [t for (t, _sha) in cur]
    ):
        try:
            entry.load_body()
        except (OSError, _Corrupt):
            entry = None
        if entry is not None and entry.text is not None and (
            entry.offsets is not None and entry.canon is not None
            and entry.hashes is not None
        ):
            for (t, sha, r0, r1) in index:
                reuse[t] = (sha, r0, r1)

    row_texts: List[str] = []
    canon: List[bytes] = []
    hashes: List[bytes] = []
    fields_seed: List[Tuple[int, sstate.RowFields]] = []
    zk_index: List[List[object]] = []
    changed = 0
    for (t, sha) in cur:
        r0 = len(canon)
        hit = reuse.get(t)
        if hit is not None and hit[0] == sha:
            _sha, o0, o1 = hit
            assert entry is not None
            text0 = entry.text
            offs0 = entry.offsets
            assert text0 is not None and offs0 is not None
            for k in range(o0, o1):
                s, e = offs0[k]
                row_texts.append(text0[s:e])
            canon.extend(entry.canon[o0:o1])  # type: ignore[index]
            hashes.extend(entry.hashes[o0:o1])  # type: ignore[index]
        else:
            changed += 1
            data = next(d for (tt, d) in payloads if tt == t)
            texts_t, fields_t = _zk_rows_for_topic(t, data)
            row_texts.extend(texts_t)
            for ft in fields_t:
                fields_seed.append((len(canon), ft))
                cb = sstate.canonical_row_bytes(*ft)
                canon.append(cb)
                hashes.append(sstate.row_hash(cb))
        zk_index.append([t, sha, r0, len(canon)])
    if not canon:
        return None  # empty cluster: the reference errors; not ours to mask
    # assemble the synthesized document + fresh offsets
    parts: List[str] = [_ZK_TEXT_HEAD]
    offsets: List[Tuple[int, int]] = []
    pos = len(_ZK_TEXT_HEAD)
    for i, rt in enumerate(row_texts):
        if i:
            parts.append(",")
            pos += 1
        parts.append(rt)
        offsets.append((pos, pos + len(rt)))
        pos += len(rt)
    parts.append(_ZK_TEXT_TAIL)
    text = "".join(parts)
    digest = sstate.rows_digest(1, canon)
    state = CachedState(
        digest=digest,
        version=1,
        nrows=len(canon),
        is_json=True,
        topics=topics,
        text=text,
        canon=canon,
        hashes=hashes,
        offsets=offsets,
    )
    for idx, ft in fields_seed:
        state.rows.seed(idx, ft)
    header: Dict[str, object] = {
        "tenant": tenant,
        "path": "",
        "dev": 0,
        "ino": 0,
        "mtime_ns": 0,
        "size": 0,
        "is_json": True,
        "topics": topics or [],
        "digest": digest,
        "version": 1,
        "unstable": False,
        "zk": {"conn": conn, "topics": zk_index},
    }
    _store_entry(sock, tenant, header, text, offsets, canon, hashes)
    return ZkResult(state, hit=False, changed_topics=changed)
