"""Fault injection for the serving layer — the chaos seam.

The overload/fault-tolerance layer (serve/admission.py, the lane health
monitor in serve/lanes.py, the client's backoff ladder) exists for
failure modes that never occur on a healthy dev box: a lane worker
dying mid-batch, a dispatch wedging, a peer vanishing mid-frame, a
device transfer failing. This module makes those failures INJECTABLE so
the replay harness (``python -m kafkabalancer_tpu.replay --chaos``) and
the failure-path tests can exercise the whole layer closed-loop, with
plan-byte parity asserted on every answered request.

**Inert by default, by construction.** The seam is armed ONLY by the
daemon's ``-serve-faults`` flag (or ``$KAFKABALANCER_TPU_FAULTS`` when
the flag is empty). Unarmed, every :func:`fire`/:func:`should` call is
one module-global ``is None`` check — the hot path carries no schedule,
no lock, no branch beyond that (pinned by
tests/test_overload.py::test_fault_seam_inert_by_default).

**Spec grammar** (deterministic — a seeded chaos run replays exactly)::

    site@n1,n2,...[:arg][;site@...]

Each ``n`` is the 1-based occurrence index of that SITE (every
``fire(site)`` call increments the site's counter; matching indexes
act). ``arg`` is a site-specific float (currently: the
``dispatch_delay`` sleep in seconds, default 0.05).

Sites (where the daemon calls in):

- ``lane_crash``     — a lane worker pop raises :class:`LaneCrash`
  (a ``BaseException``: it ESCAPES the worker's ``except Exception``
  nets exactly like a real thread death, so the health monitor — not a
  catch-all — must recover);
- ``dispatch_delay`` — a plan dispatch sleeps ``arg`` seconds before
  running (a wedged-lane simulacrum the watchdog can observe);
- ``socket_drop``    — the daemon closes the connection INSTEAD of
  writing a plan response (mid-frame peer death from the client's view;
  the caller checks :func:`should` and acts);
- ``transfer_fail``  — lane-context entry raises :class:`FaultError`
  (a failed device transfer/pin: the request crashes server-side and is
  answered with a structured error, never a wrong plan);
- ``spill_write_fail`` — a warm-tier session spill (serve/spill.py)
  raises :class:`FaultError` mid-write, like a full disk: the hot
  session is untouched, the record is simply not persisted
  (``paging.write_failures`` counts it);
- ``spill_corrupt``  — the spill write lands a BIT-FLIPPED record on
  disk (flipped after the checksum was computed, like media
  corruption); the later restore must detect it, prune, count
  ``paging.corrupt_drops``, and answer the request cold-but-correct.
  Acts through :func:`should` (the writer performs the flip);
- ``restore_delay``  — a warm-tier restore sleeps ``arg`` seconds
  before reading the record (a slow disk on the recovery path; the
  client's progress probes must ride it out, not misread it as a
  wedge).
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

SITES = (
    "lane_crash", "dispatch_delay", "socket_drop", "transfer_fail",
    "spill_write_fail", "spill_corrupt", "restore_delay",
)

# the dispatch_delay default sleep when the spec names no arg
DEFAULT_DELAY_S = 0.05


class FaultError(RuntimeError):
    """An injected request-scoped fault (device transfer, dispatch)."""


class LaneCrash(BaseException):
    """An injected lane-worker death. Deliberately a BaseException: the
    lane worker's ``except Exception`` survival nets must NOT absorb it
    — the point is to kill the worker thread the way a real interpreter
    -level failure would, and prove the health monitor recovers."""


class FaultPlan:
    """One parsed ``-serve-faults`` schedule plus its firing state."""

    def __init__(
        self, schedule: Dict[str, Tuple[List[int], float]], spec: str
    ) -> None:
        self._lock = threading.Lock()
        # site -> (sorted occurrence indexes, arg)
        self._schedule = schedule
        self._counts: Dict[str, int] = {}
        self.fired: List[Tuple[str, int]] = []
        self.spec = spec

    def _hit(self, site: str) -> Optional[float]:
        """Count one occurrence of ``site``; the site arg when this
        occurrence is scheduled to act, else None."""
        with self._lock:
            n = self._counts.get(site, 0) + 1
            self._counts[site] = n
            sched = self._schedule.get(site)
            if sched is None or n not in sched[0]:
                return None
            self.fired.append((site, n))
            return sched[1]

    def fired_counts(self) -> Dict[str, int]:
        with self._lock:
            out: Dict[str, int] = {}
            for site, _n in self.fired:
                out[site] = out.get(site, 0) + 1
            return out


def parse_spec(spec: str) -> FaultPlan:
    """Parse one spec string (module docstring grammar); raises
    ``ValueError`` on an unknown site or malformed entry — a chaos run
    with a typo'd schedule must refuse loudly, not run un-chaos'd."""
    schedule: Dict[str, Tuple[List[int], float]] = {}
    for part in spec.split(";"):
        part = part.strip()
        if not part:
            continue
        if "@" not in part:
            raise ValueError(f"fault spec entry {part!r}: expected site@n[,n...]")
        site, rest = part.split("@", 1)
        site = site.strip()
        if site not in SITES:
            raise ValueError(
                f"unknown fault site {site!r} (known: {', '.join(SITES)})"
            )
        arg = DEFAULT_DELAY_S
        if ":" in rest:
            rest, arg_s = rest.rsplit(":", 1)
            arg = float(arg_s)
        try:
            idxs = sorted(int(n) for n in rest.split(",") if n.strip())
        except ValueError as exc:
            raise ValueError(f"fault spec entry {part!r}: {exc}") from None
        if not idxs or idxs[0] < 1:
            raise ValueError(
                f"fault spec entry {part!r}: occurrence indexes are 1-based"
            )
        schedule[site] = (idxs, arg)
    return FaultPlan(schedule, spec)


# the one module global the hot path reads; None == inert
_PLAN: Optional[FaultPlan] = None


def arm(spec: str) -> FaultPlan:
    """Install a schedule (daemon startup, under ``-serve-faults``)."""
    global _PLAN
    plan = parse_spec(spec)
    _PLAN = plan
    return plan


def disarm() -> None:
    global _PLAN
    _PLAN = None


def active() -> Optional[FaultPlan]:
    return _PLAN


def fire(site: str) -> None:
    """The injection point: no-op unless armed AND this occurrence of
    ``site`` is scheduled — then raise/delay per the site contract."""
    plan = _PLAN
    if plan is None:
        return
    arg = plan._hit(site)
    if arg is None:
        return
    if site == "lane_crash":
        raise LaneCrash("injected lane crash (occurrence scheduled)")
    if site in ("dispatch_delay", "restore_delay"):
        import time

        time.sleep(arg)
        return
    if site == "transfer_fail":
        raise FaultError("injected device-transfer failure")
    if site == "spill_write_fail":
        raise FaultError("injected spill write failure")
    # socket_drop/spill_corrupt act through should(); reaching here
    # means a caller mis-used fire() — act as a request fault, not pass
    raise FaultError(f"injected fault at {site}")


def should(site: str) -> bool:
    """Non-raising twin of :func:`fire` for sites where the CALLER
    performs the fault (``socket_drop``: the connection loop closes the
    socket instead of replying)."""
    plan = _PLAN
    if plan is None:
        return False
    return plan._hit(site) is not None
