"""Device lanes: the multi-device scheduler and cross-request microbatching.

The PR-4 daemon funnels every request through ONE dispatcher onto one
device — correct, but it leaves every other attached device idle while
the outer automation loop queues up. This module turns the daemon into a
multi-device pipelined executor:

- :class:`Lane` — one worker lane per visible device, pinned to it: the
  lane's request threads deserialize AOT executables against the lane's
  device (``ops.aot.set_execution_device``), place jit dispatches on it
  (``jax.default_device``), keep a private digest-keyed tensorize row
  cache (``ops.tensorize.set_thread_row_cache``) and a private staging
  cache of pre-shipped device buffers;
- :class:`LaneScheduler` — routes queued requests across lanes with
  shape-bucket AFFINITY (a bucket sticks to the lane that already holds
  its compiled executable and primed row cache) plus WORK STEALING when
  a lane's queue is empty. Same ``submit``/``busy``/``stop`` interface
  as the single-lane ``Coalescer`` (serve/daemon.py). One visible
  device degrades to ONE lane; with microbatching also disabled
  (``-serve-microbatch=1``, or explicit ``-serve-lanes=1``) the daemon
  keeps the plain Coalescer — byte-for-byte the PR-4 dispatcher;
- per-lane 3-stage pipelining: while a lane executes request N on
  device, a stage thread host-encodes request N+1 (parse → settle →
  tensorize, priming the lane's row cache) and ``device_put``s its dense
  tensors into the lane's staging cache (``ops.aot.stage_host_arrays``),
  so N+1's dispatch finds its inputs already resident — double-buffered:
  at most one request staged ahead per lane;
- :class:`ContinuousBatcher` — ITERATION-LEVEL continuous batching
  (Orca, OSDI '22): the fused batch re-forms at every solver chunk
  round instead of running a fixed membership to collective completion.
  Members are ADMITTED dynamically — a request arriving while a batch
  is in flight joins at the next round boundary, into a slot freed by a
  converged member, instead of waiting out the whole window — and
  dispatch is VARIABLE-K PADDED over a small set of padding buckets
  (``PAD_BUCKETS``): live submissions stack along the leading instance
  axis (``parallel.sweep.stack_instances``), padded slots replay a
  no-op instance (``solvers.scan.pad_instance_args`` — budget zeroed),
  so ONE compiled ``session_packed_batched`` executable per bucket
  serves any occupancy. Each live request still receives its own
  bit-identical packed move log versus a solo dispatch (pinned by the
  differential tests in tests/test_serve.py, every occupancy 1..K);
- :class:`MicrobatchGroup` — the legacy ONE-SHOT fusion barrier (fixed
  membership, runs to collective completion), kept as the measured
  control (``-serve-batch-mode=oneshot``; bench.py's continuous-vs-
  oneshot throughput ratio comes from this pair) and as the shared base
  of the continuous batcher;
- shared device residency (serve/residency.py): each lane's staging
  structure is a digest-keyed refcounted :class:`ResidencyPool` —
  weights/allowed/validity arrays common across concurrent requests
  upload once per lane and are shared by every member, so steady-state
  staging traffic drops to the per-request delta rows.

Layering: this module imports jax/numpy/solvers only lazily inside
methods — constructing a scheduler with ``device=None`` lanes (tests)
touches neither.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.serve import faults
from kafkabalancer_tpu.serve.admission import overload_response
from kafkabalancer_tpu.serve.protocol import PROTO_VERSION
from kafkabalancer_tpu.serve.residency import ResidencyPool

BucketKey = Tuple[int, int, int, bool]
# handler contract (daemon._handle_plan): sets req.response, never sets
# req.done (the scheduler owns the completion latch)
LaneHandler = Callable[[Any, bool, "Lane", Optional["MicrobatchGroup"]], None]
BucketFn = Callable[[Any], Optional[BucketKey]]
StageFn = Callable[[Any, "Lane"], None]
# admission predicate: will this request's planning reach the fusible
# dispatch (the XLA fused session)? Only such requests are admitted into
# a fusion batch — a member that never dispatches would stall its peers
# until its whole request completes
FusibleFn = Callable[[Any], bool]


def probe_bucket(req: Any, bucket_of: BucketFn) -> Optional[BucketKey]:
    """The memoized shape-bucket probe shared by the Coalescer and the
    LaneScheduler (one definition: the memo-ordering subtleties must not
    drift between the two dispatchers). None is a valid 'no bucket'."""
    if not req.bucketed:
        req.bucketed = True
        try:
            req.bucket = bucket_of(req)
        except Exception:
            req.bucket = None
    bucket: Optional[BucketKey] = req.bucket
    return bucket

# a microbatch member waiting on the fusion barrier gives up and runs
# solo past this — the barrier fills as fast as the slowest member's
# host-side head (parse + settle + tensorize), seconds at flagship scale
MICROBATCH_WAIT_S = 120.0

# variable-K padding buckets: a fused round's occupancy pads up to the
# smallest bucket that holds it (no-op instances fill the dead slots),
# so one compiled batched executable per bucket serves any occupancy —
# occupancies past the largest bucket dispatch at their exact K
PAD_BUCKETS = (1, 2, 4, 8)

# -serve-admission-hold: how long a lane holds its pop waiting for the
# requested batch depth to queue before dispatching what it has — the
# bound that keeps a deterministic-batching daemon from wedging when
# fewer clients than the hold depth ever arrive. Generous: hold daemons
# are private test/bench tools where a missed batch costs a flaky run
# and a held singleton costs only this window once
ADMISSION_HOLD_WINDOW_S = 5.0

# continuous admission tick: how often the drain loop re-polls the lane
# queue for newly staged same-bucket requests while members are in
# flight (retirements notify the batcher's condition immediately; the
# tick only bounds the queue-poll latency)
ADMISSION_TICK_S = 0.02


class Lane:
    """One device lane: identity, pinned device, per-lane caches and
    counters. The worker thread lives in :class:`LaneScheduler`."""

    __slots__ = (
        "index", "device", "row_cache", "stage_cache", "busy_s", "requests",
        "quarantined", "quarantined_at", "last_beat",
    )

    def __init__(self, index: int, device: Any = None) -> None:
        self.index = index
        self.device = device
        # lane health (the daemon's watchdog — LaneScheduler.health_tick):
        # last_beat is touched at every pop/retire/round boundary; a lane
        # with active work and a stale beat is presumed wedged and
        # quarantined (excluded from routing) until it beats again
        self.quarantined = False
        self.quarantined_at = 0.0
        self.last_beat = time.monotonic()
        self.row_cache: Any = None  # TensorizeRowCache, daemon-installed
        # the lane's staging structure is the SHARED residency pool:
        # digest-keyed device buffers uploaded once per lane, shared by
        # every concurrent request over the same content, refcount-
        # evicted (serve/residency.py) — PR 5's single-use staging dict
        # generalized across requests
        self.stage_cache: ResidencyPool = ResidencyPool()
        self.busy_s = 0.0
        self.requests = 0

    @contextlib.contextmanager
    def context(self) -> Iterator[None]:
        """Pin the calling thread to this lane: AOT loads/staging and
        jit placement go to the lane's device, tensorize uses the lane's
        row cache, and the staging cache the stage thread fills is the
        one the dispatch consults."""
        from kafkabalancer_tpu.ops import aot
        # NOTE: ops/__init__ shadows the tensorize SUBMODULE with the
        # tensorize function; import the seam directly from the module
        from kafkabalancer_tpu.ops.tensorize import set_thread_row_cache

        aot.set_execution_device(self.device)
        aot.set_staging_cache(self.stage_cache)
        set_thread_row_cache(self.row_cache)
        try:
            if self.device is not None:
                import jax

                with jax.default_device(self.device):
                    yield
            else:
                yield
        finally:
            set_thread_row_cache(None)
            aot.set_staging_cache(None)
            aot.set_execution_device(None)
            # one serving thread == one in-flight request: drop this
            # request's pins on the shared pool so retired requests'
            # universes become evictable
            self.stage_cache.release_thread()

    def cache_stats(self) -> Dict[str, int]:
        if self.row_cache is None:
            return {"hits": 0, "misses": 0, "rows_reused": 0}
        stats: Dict[str, int] = self.row_cache.stats()
        return stats

    def residency_stats(self) -> Dict[str, int]:
        return self.stage_cache.stats()


class _MbEntry:
    """One member's pending submission at the microbatch barrier."""

    __slots__ = ("args", "statics", "result", "done", "solo")

    def __init__(self, args: Tuple, statics: Dict[str, Any]) -> None:
        self.args = args
        self.statics = statics
        self.result: Any = None
        self.done = False
        self.solo = False


def _mb_sig(args: Tuple, statics: Dict[str, Any]) -> Tuple[Any, ...]:
    """Fusion signature: leaf shapes/dtypes (None-ness included) plus the
    statics — two dispatches fuse only when they would compile the same
    program."""
    import numpy as np

    leaves = tuple(
        None if a is None else (np.asarray(a).shape, np.asarray(a).dtype.str)
        for a in args
    )
    return (leaves, tuple(sorted((k, repr(v)) for k, v in statics.items())))


class MicrobatchGroup:
    """ONE-SHOT fusion barrier for K concurrently-running same-bucket
    requests — fixed membership decided at formation, run to collective
    completion. Kept as the measured control for the continuous batcher
    (``-serve-batch-mode=oneshot``; bench.py reports the throughput
    ratio of the pair) and as its shared implementation base.

    Each member's request thread installs the group via :meth:`member`;
    ``solvers.scan._dispatch_chunk`` then offers every fused-session
    dispatch here. A round completes when every LIVE member has either
    submitted a dispatch or finished its request entirely; submissions
    sharing a program signature are stacked (sweep scenario layout) and
    run as ONE batched device dispatch, each member receiving its own
    packed move log slice — bit-identical to a solo dispatch. Everything
    else (singleton signatures, non-XLA engines, any batched failure)
    FAILS OPEN: ``dispatch`` returns None and the caller runs the
    ordinary solo path, so fusion can cost correctness nothing.
    """

    def __init__(self, size: int, wait_s: float = MICROBATCH_WAIT_S) -> None:
        self._cv = threading.Condition()
        self._live = size
        self._pending: List[_MbEntry] = []
        self._wait_s = wait_s
        self.fused_requests = 0
        self.fused_dispatches = 0
        # occupancy histogram (live members per fused dispatch) and the
        # padded-slot count — bench.py's occupancy/waste attribution
        self.occupancy: Dict[int, int] = {}
        self.padded_slots = 0
        # stats sink (the owning scheduler): called (occupancy, padded)
        # right after each fused dispatch commits, BEFORE the members'
        # responses return — a stats() read taken the instant a client
        # sees its response must already include its fusion
        self.sink: Optional[Callable[[int, int], None]] = None

    @contextlib.contextmanager
    def member(self, req: Any = None) -> Iterator[None]:
        """Install this group on the calling request thread; on exit the
        member leaves the barrier (so stragglers stop waiting for it).
        ``req`` (when given) is marked entered, so the scheduler can
        tell a member that died BEFORE joining from one that joined and
        left — see :meth:`abandon`."""
        from kafkabalancer_tpu.solvers import scan

        if req is not None:
            req.mb_entered = True
        scan.set_microbatcher(self)
        try:
            yield
        finally:
            scan.set_microbatcher(None)
            self._leave()

    def abandon(self) -> None:
        """A member failed before ever entering :meth:`member` (thread
        start failure, context-entry crash): release its barrier slot so
        the live peers' round can still complete instead of stalling to
        the timeout."""
        self._leave()

    def _leave(self) -> None:
        with self._cv:
            self._live -= 1
            batch = self._take_round_locked()
        if batch:
            self._execute(batch)

    def _take_round_locked(self) -> Optional[List[_MbEntry]]:
        if self._pending and len(self._pending) >= self._live:
            batch = self._pending
            self._pending = []
            return batch
        return None

    def dispatch(self, args: Tuple, statics: Dict[str, Any]) -> Optional[Any]:
        """Offer one dispatch for fusion; this member's packed move log,
        or None to run solo (declined / timed out / batch failed)."""
        if statics.get("engine") != "xla" or statics.get("leader"):
            return None  # kernel engines and the leader session run solo
        e = _MbEntry(args, statics)
        with self._cv:
            self._pending.append(e)
            batch = self._take_round_locked()
        if batch:
            self._execute(batch)
        deadline = time.monotonic() + self._wait_s
        with self._cv:
            while not e.done and not e.solo:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(left):
                    if e in self._pending:
                        self._pending.remove(e)
                    e.solo = True
        return None if e.solo else e.result

    def _execute(self, batch: List[_MbEntry]) -> None:
        by_sig: Dict[Tuple[Any, ...], List[_MbEntry]] = {}
        for e in batch:
            try:
                by_sig.setdefault(_mb_sig(e.args, e.statics), []).append(e)
            except Exception:
                with self._cv:
                    e.solo = True
        for entries in by_sig.values():
            if len(entries) == 1:
                with self._cv:
                    entries[0].solo = True
            else:
                self._run_fused(entries)
        with self._cv:
            self._cv.notify_all()

    def _pad_to(self, n: int) -> int:
        """Instance-axis width for an occupancy-``n`` round. The
        one-shot control dispatches at the exact K (the PR-5 behavior);
        the continuous batcher overrides with the padding buckets."""
        return n

    def _run_fused(self, entries: List[_MbEntry]) -> None:
        n = len(entries)
        try:
            import numpy as np

            from kafkabalancer_tpu.ops import aot
            from kafkabalancer_tpu.parallel.sweep import stack_instances
            from kafkabalancer_tpu.solvers import scan

            pad_k = max(n, self._pad_to(n))
            pad_args = (
                scan.pad_instance_args(entries[0].args) if pad_k > n else None
            )
            stacked: List[Any] = []
            for pos in range(len(entries[0].args)):
                vals = [e.args[pos] for e in entries]
                stacked.append(
                    None
                    if vals[0] is None
                    else stack_instances(
                        vals,
                        pad_to=pad_k,
                        pad_row=None if pad_args is None else pad_args[pos],
                    )
                )
            with obs.span("serve.microbatch_dispatch", k=n, padded_k=pad_k):
                out = np.asarray(
                    aot.call_or_compile(
                        "session_packed_batched",
                        scan.session_packed_batched,
                        tuple(stacked),
                        dict(entries[0].statics),
                    )
                )
            with self._cv:
                for k, e in enumerate(entries):
                    if not e.solo:  # a timed-out member already went solo
                        e.result = out[k]
                        e.done = True
                self.fused_requests += n
                self.fused_dispatches += 1
                self.occupancy[n] = self.occupancy.get(n, 0) + 1
                self.padded_slots += pad_k - n
            obs.metrics.count("serve.microbatched", n)
            if pad_k > n:
                obs.metrics.count("serve.mb_padded_slots", pad_k - n)
            if self.sink is not None:
                # members are still parked at the barrier (the round's
                # notify_all fires after this returns), so the sink's
                # accounting is visible before any response is
                try:
                    self.sink(n, pad_k - n)
                except Exception:
                    pass
        except Exception:
            # fail open: every waiter runs its own solo dispatch
            with self._cv:
                for e in entries:
                    if not e.done:
                        e.solo = True


class ContinuousBatcher(MicrobatchGroup):
    """Iteration-level continuous batching: the one-shot barrier with
    DYNAMIC membership and variable-K padded dispatch.

    Rounds work exactly like the base barrier — a round fires when every
    live member has submitted or left — but membership is no longer
    fixed at formation: the lane's drain loop :meth:`admit`\\ s a newly
    staged same-bucket request the moment a slot frees (a converged
    member leaving shrinks ``live``; the admit grows it back), so the
    next round re-forms with the new member's chunk-1 dispatch fused
    into its peers' chunk ``i+1`` instead of the request waiting out the
    whole window. Occupancy therefore varies round to round, and
    :meth:`_pad_to` pads each round up to the smallest ``PAD_BUCKETS``
    bucket so one compiled batched executable per bucket serves them
    all; a padded slot replays a no-op instance
    (``solvers.scan.pad_instance_args``) and live slots keep their
    bit-identical per-instance logs.
    """

    def __init__(
        self,
        max_k: int,
        wait_s: float = MICROBATCH_WAIT_S,
        pad_buckets: Sequence[int] = PAD_BUCKETS,
    ) -> None:
        super().__init__(0, wait_s)
        self._max_k = max(1, max_k)
        self._pad_buckets = tuple(sorted(set(int(b) for b in pad_buckets)))
        self.admitted = 0
        # occupancy-adaptive fast path: dispatches declined at
        # occupancy 1 without paying the round machinery (BENCH_r06's
        # continuous_vs_oneshot=0.89x was exactly this tax)
        self.solo_fast = 0

    def dispatch(self, args: Tuple, statics: Dict[str, Any]) -> Optional[Any]:
        with self._cv:
            if self._live == 1 and not self._pending:
                # sole live member and nothing staged to fuse with: a
                # round would only classify this entry solo after the
                # signature hash and two condition round-trips. Decline
                # immediately — the caller's solo dispatch is
                # bit-identical, and the next admission re-enables
                # fusion at the very next chunk boundary.
                self.solo_fast += 1
                return None
        return super().dispatch(args, statics)

    def admit(self) -> None:
        """Grow the live membership by one — called by the lane's drain
        loop BEFORE the member's request thread starts, so no round can
        fire without the newcomer (it either dispatches into the next
        round or leaves)."""
        with self._cv:
            self._live += 1
            self.admitted += 1

    def wait_change(self, timeout: float) -> None:
        """Block until membership/round state changes (a member leaves
        or submits) or ``timeout`` elapses — the drain loop's tick."""
        with self._cv:
            self._cv.wait(timeout)

    def _leave(self) -> None:
        super()._leave()
        # wake the drain loop promptly: a departure frees a slot the
        # next queued request can be admitted into
        with self._cv:
            self._cv.notify_all()

    def _pad_to(self, n: int) -> int:
        for b in self._pad_buckets:
            if b >= n:
                return b
        return n  # past the largest bucket: exact K


class LaneScheduler:
    """Multi-lane dispatcher with bucket affinity, work stealing and
    cross-request batching; Coalescer-compatible interface.

    ``batch_mode`` selects the fusion discipline for same-bucket
    admission-predicted requests: ``"continuous"`` (the default) runs
    them through a :class:`ContinuousBatcher` — mid-flight admission
    into freed slots, variable-K padded dispatch; ``"oneshot"`` keeps
    the PR-5 fixed-membership :class:`MicrobatchGroup` (the measured
    control). ``admission_hold`` (the deterministic admission latch,
    ``-serve-admission-hold``) makes a lane hold its pop until that many
    admission-predicted requests are queued — or the hold window
    expires — so tests and benchmarks can form a full batch without
    scheduler-timing luck."""

    def __init__(
        self,
        handle: LaneHandler,
        bucket_of: BucketFn,
        lanes: Sequence[Lane],
        microbatch: int = 1,
        stage: Optional[StageFn] = None,
        admissible: Optional[FusibleFn] = None,
        batch_mode: str = "continuous",
        admission_hold: int = 0,
        watchdog_s: float = 0.0,
        exclusive: Optional[FusibleFn] = None,
    ) -> None:
        self._handle = handle
        self._bucket_of = bucket_of
        self.lanes = list(lanes)
        self._microbatch = max(1, microbatch)
        self._stage = stage
        self._admissible = admissible
        self._batch_mode = batch_mode
        # MESH-EXCLUSIVE predicate (daemon: -fused-shard requests): a
        # matching request owns EVERY attached device (the sharded
        # session shard_maps over the whole mesh), so its lane first
        # DRAINS the fleet — waits until no other lane has in-flight
        # work — and holds every pop loop closed while it runs; nothing
        # lane-pinned can race the mesh collectives. Sequential by
        # construction: a second exclusive parks until the first
        # releases ownership.
        self._exclusive = exclusive
        self._cv = threading.Condition()
        self._queues: List[Deque[Any]] = [deque() for _ in self.lanes]
        self._active = [0] * len(self.lanes)
        # lane index currently owning the mesh, and per-lane count of
        # popped-but-parked exclusive requests (parked = waiting for the
        # drain, deliberately NOT counted as busy by the drain check so
        # two concurrent exclusives cannot deadlock waiting on each
        # other's active slot)
        self._mesh_owner: Optional[int] = None
        self._excl_parked = [0] * len(self.lanes)
        self.mesh_exclusive = 0
        # per-lane claimed-but-unfinished requests — what the health
        # monitor answers with a structured error when the lane dies
        self._current: List[List[Any]] = [[] for _ in self.lanes]
        self._affinity: Dict[BucketKey, int] = {}
        self._stop = False
        # lane health (docs/serving.md § Lane health): 0 disables the
        # watchdog; quarantine/requeue/recovery counters feed the
        # scrape's "lane_health" block
        self._watchdog_s = max(0.0, watchdog_s)
        self.quarantines = 0
        self.requeues = 0
        self.recoveries = 0
        # requests answered with a structured error because their lane
        # died/wedged under them (never requeued: an in-flight request
        # may have side effects — only queued-but-unstarted work moves)
        self.abandoned = 0
        self._hold_n = max(0, admission_hold)
        self._hold_window_s = ADMISSION_HOLD_WINDOW_S
        self._hold_since: List[Optional[float]] = [None] * len(self.lanes)
        self._admission_tick_s = ADMISSION_TICK_S
        self.steals = 0
        self.microbatched = 0
        self.padded_slots = 0
        # occupancy-adaptive fast-path engagements (solo inline runs
        # that skipped the continuous machinery; unit-pinned, not part
        # of the scrape schema)
        self.solo_fast = 0
        self._occupancy: Dict[int, int] = {}
        self._workers = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"serve-lane-{i}",
                daemon=True,
            )
            for i in range(len(self.lanes))
        ]
        for t in self._workers:
            t.start()

    # -- Coalescer-compatible surface ------------------------------------
    def busy(self) -> bool:
        """Queued or in-flight work on ANY lane — the daemon's
        idle-timeout check must not shut down under a long-running plan
        on one lane while the others sit empty."""
        with self._cv:
            return any(self._queues) or any(self._active)

    def submit(self, req: Any) -> Dict[str, Any]:
        # the routing probe runs OUTSIDE the lock (it parses the input)
        # and only when there is more than one lane to route between —
        # the single-lane scheduler keeps the Coalescer's probe-only-
        # under-contention economy (group assembly probes on demand).
        # Memoized on the request so group assembly never re-pays it.
        b = self._bucket(req) if len(self.lanes) > 1 else None
        with self._cv:
            if self._stop:
                return {
                    "v": PROTO_VERSION, "ok": False,
                    "error": "daemon shutting down",
                }
            if all(ln.quarantined for ln in self.lanes):
                # nothing can serve this request right now — a wedged
                # fleet must answer a structured retry-after shed, not
                # park the submitter on a queue nothing drains (the
                # client backs off, retries, and falls back; the
                # in-flight gauge would otherwise keep its progress
                # probe waiting the full budget)
                return overload_response(
                    "quarantine", 1000,
                    detail="every lane is quarantined",
                )
            i = self._route_locked(b)
            self._queues[i].append(req)
            self._cv.notify_all()
        req.done.wait()
        return req.response or {
            "v": PROTO_VERSION, "ok": False, "error": "request dropped",
        }

    def stop(self, timeout: float = 30.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._workers:
            t.join(timeout)

    def stats(self) -> Dict[str, float]:
        with self._cv:
            residency = [ln.residency_stats() for ln in self.lanes]
            return {
                "lanes": float(len(self.lanes)),
                "steals": float(self.steals),
                "mesh_exclusive": float(self.mesh_exclusive),
                "microbatched": float(self.microbatched),
                "padded_slots": float(self.padded_slots),
                "solo_fast": float(self.solo_fast),
                "occupancy_max": float(
                    max(self._occupancy, default=0)
                ),
                "residency_hits": float(sum(r["hits"] for r in residency)),
                "residency_misses": float(
                    sum(r["misses"] for r in residency)
                ),
                "lane_busy_s": float(sum(ln.busy_s for ln in self.lanes)),
                "cache_hits": float(
                    sum(ln.cache_stats()["hits"] for ln in self.lanes)
                ),
            }

    def occupancy_hist(self) -> Dict[str, int]:
        """Fused dispatches by live occupancy (string keys: the dict
        rides JSON hello responses)."""
        with self._cv:
            return {str(k): v for k, v in sorted(self._occupancy.items())}

    def _note_fused(self, occupancy: int, padded: int) -> None:
        """The batchers' stats sink: one fused dispatch of ``occupancy``
        live members and ``padded`` dead slots landed. Called before the
        members' responses release, so a stats() read racing a client's
        completion already counts its fusion."""
        with self._cv:
            self.microbatched += occupancy
            self.padded_slots += padded
            self._occupancy[occupancy] = (
                self._occupancy.get(occupancy, 0) + 1
            )
        # dispatch-TIME distributions (the BENCH_r06 diagnosis seam):
        # the start-gauge snapshots bench scraped before could only
        # show cumulative occupancy_max/padded_slots, hiding whether
        # continuous mode actually fuses wider per dispatch than
        # oneshot. One observation per fused dispatch, recorded as the
        # dispatch lands — the throughput artifact reads these hists
        # directly (bench.py).
        obs.metrics.hist_observe(
            "serve.dispatch_occupancy", float(occupancy)
        )
        obs.metrics.hist_observe("serve.dispatch_padded", float(padded))

    # -- lane health -------------------------------------------------------
    def health_stats(self) -> Dict[str, Any]:
        """The scrape's ``lane_health`` block (serve-stats/5)."""
        with self._cv:
            return {
                "watchdog_s": self._watchdog_s,
                "quarantined": [
                    ln.index for ln in self.lanes if ln.quarantined
                ],
                "quarantines": self.quarantines,
                "requeues": self.requeues,
                "recoveries": self.recoveries,
                "abandoned": self.abandoned,
            }

    def health_tick(
        self, log: Optional[Callable[[str], None]] = None
    ) -> None:
        """The lane watchdog (called from the daemon's accept-loop
        tick). Three verdicts per lane:

        - **crashed** — the worker thread is dead: quarantine, answer
          its claimed in-flight requests with a structured error (never
          a wrong plan), requeue its queued-but-unstarted work onto
          healthy lanes, then RESTART a fresh worker and re-admit the
          lane (the recovery re-probe for a dead worker is a restart);
        - **wedged** — the worker is alive but its lane has active work
          and no heartbeat for ``watchdog_s``: quarantine (routing and
          stealing exclude it), answer the stuck in-flight requests,
          requeue its queue — the wedged call may still be executing,
          so the thread is left alone;
        - **recovered** — a wedged-quarantined lane beat again (the
          stuck call finally finished) and has drained: re-admit it.
        """
        if self._watchdog_s <= 0 or self._stop:
            return
        now = time.monotonic()
        for i, lane in enumerate(self.lanes):
            worker = self._workers[i]
            if not worker.is_alive():
                self._quarantine(i, "crashed", log, restarting=True)
                # restart: the dead worker's active count can never be
                # decremented by it, so reset the lane's slate first —
                # including any mesh hold it died holding (a stuck
                # owner/parked flag would freeze every other lane's pop
                # loop forever)
                with self._cv:
                    self._active[i] = 0
                    self._current[i] = []
                    self._excl_parked[i] = 0
                    if self._mesh_owner == i:
                        self._mesh_owner = None
                nt = threading.Thread(
                    target=self._worker, args=(i,),
                    name=f"serve-lane-{i}", daemon=True,
                )
                try:
                    nt.start()
                except Exception:
                    continue  # no thread to spare; retried next tick
                with self._cv:
                    self._workers[i] = nt
                    lane.quarantined = False
                    lane.last_beat = time.monotonic()
                    self.recoveries += 1
                    self._cv.notify_all()
                if log is not None:
                    log(f"serve: lane {i} worker restarted (recovered)")
                obs.metrics.event("serve_lane_recovered", lane=i)
            elif lane.quarantined:
                # drain anything that slipped onto the quarantined
                # lane's queue in a race window — nothing else will
                self._drain_quarantined(i, log)
                with self._cv:
                    drained = (
                        self._active[i] == 0 and not self._current[i]
                    )
                    beat_since = lane.last_beat > lane.quarantined_at
                if drained or beat_since:
                    with self._cv:
                        lane.quarantined = False
                        lane.last_beat = time.monotonic()
                        self.recoveries += 1
                        self._cv.notify_all()
                    if log is not None:
                        log(f"serve: lane {i} recovered from quarantine")
                    obs.metrics.event("serve_lane_recovered", lane=i)
            else:
                with self._cv:
                    active = self._active[i] > 0 or bool(self._current[i])
                if active and now - lane.last_beat > self._watchdog_s:
                    self._quarantine(i, "wedged", log)

    def _drain_quarantined(
        self, i: int, log: Optional[Callable[[str], None]]
    ) -> None:
        """Move (or answer) work that landed on a STILL-quarantined
        lane's queue after its quarantine flush — the routing guard in
        :meth:`submit` makes this a race-window case, but a queued
        request must never sit where nothing drains it."""
        with self._cv:
            if not self._queues[i]:
                return
            queued = list(self._queues[i])
            self._queues[i].clear()
            healthy = [
                j for j, ln in enumerate(self.lanes)
                if j != i
                and not ln.quarantined
                and self._workers[j].is_alive()
            ]
            moved = 0
            orphaned: List[Any] = []
            for r in queued:
                if healthy:
                    j = min(
                        healthy,
                        key=lambda k: len(self._queues[k])
                        + self._active[k],
                    )
                    self._queues[j].append(r)
                    moved += 1
                else:
                    orphaned.append(r)
            self.requeues += moved
            # internal (speculative/watch) requests never passed
            # admission: excluded from `abandoned` so the identity
            # admitted == requests + abandoned stays exact
            self.abandoned += sum(
                1 for r in orphaned
                if getattr(r, "internal", None) is None
            )
            if moved:
                self._cv.notify_all()
        for r in orphaned:
            r.response = overload_response(
                "quarantine", 1000,
                detail=f"lane {i} quarantined and no healthy peer",
            )
            r.done.set()
        if (moved or orphaned) and log is not None:
            log(
                f"serve: drained {moved + len(orphaned)} request(s) "
                f"off quarantined lane {i} "
                f"({moved} requeued, {len(orphaned)} answered)"
            )

    def _quarantine(
        self,
        i: int,
        why: str,
        log: Optional[Callable[[str], None]],
        restarting: bool = False,
    ) -> None:
        """Quarantine lane ``i``: answer its claimed in-flight requests
        with a structured error, move its queued-but-unstarted work to
        healthy lanes — or, with no healthy lane, answer it too (an
        answered error beats an un-served queue) UNLESS ``restarting``
        (the crashed-worker path): a fresh worker is about to take over
        this very lane, so its queue stays in place and is served
        moments later instead of stampeding every client into the
        in-process fallback. Excluded from routing until health_tick
        re-admits it."""
        lane = self.lanes[i]
        with self._cv:
            if lane.quarantined:
                return
            lane.quarantined = True
            lane.quarantined_at = time.monotonic()
            self.quarantines += 1
            stuck = [
                r for r in self._current[i] if not r.done.is_set()
            ]
            healthy = [
                j for j, ln in enumerate(self.lanes)
                if j != i
                and not ln.quarantined
                and self._workers[j].is_alive()
            ]
            if healthy or not restarting:
                queued = list(self._queues[i])
                self._queues[i].clear()
            else:
                queued = []  # kept for the restarted worker
            requeued: List[Any] = []
            orphaned: List[Any] = []
            for r in queued:
                if healthy:
                    j = min(
                        healthy,
                        key=lambda k: len(self._queues[k])
                        + self._active[k],
                    )
                    self._queues[j].append(r)
                    requeued.append(r)
                else:
                    orphaned.append(r)
            self.requeues += len(requeued)
            # abandoned = admitted work that never BEGAN handling and
            # got an error instead; a request wedged mid-handling still
            # reaches the requests counter, so counting it here too
            # would double-book the conservation identity — and
            # internal (speculative/watch) requests never passed
            # admission at all, so they are excluded outright
            self.abandoned += len([
                r for r in stuck
                if not getattr(r, "started", False)
                and getattr(r, "internal", None) is None
            ]) + sum(
                1 for r in orphaned
                if getattr(r, "internal", None) is None
            )
            # affinity for buckets owned by the sick lane re-resolves
            # on the next route (a healthy lane takes ownership)
            self._affinity = {
                b: j for b, j in self._affinity.items() if j != i
            }
            self._cv.notify_all()
        # responses OUTSIDE the lock: a late-finishing wedged thread
        # setting req.response afterwards is harmless — done is already
        # set and the client has the structured error, never a plan
        for r in stuck:
            r.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": (
                    f"lane {i} {why}: in-flight request abandoned "
                    "(lane quarantined)"
                ),
            }
            r.done.set()
        for r in orphaned:
            r.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": (
                    f"lane {i} {why} and no healthy lane to requeue to"
                ),
            }
            r.done.set()
        obs.metrics.count("serve.quarantines")
        if requeued:
            obs.metrics.count("serve.requeues", len(requeued))
        obs.metrics.event(
            "serve_lane_quarantined", lane=i, why=why,
            stuck=len(stuck), requeued=len(requeued),
            orphaned=len(orphaned),
        )
        if log is not None:
            log(
                f"serve: lane {i} {why} — quarantined "
                f"({len(stuck)} in-flight answered, "
                f"{len(requeued)} requeued, {len(orphaned)} orphaned)"
            )

    # -- routing ----------------------------------------------------------
    def _bucket(self, req: Any) -> Optional[BucketKey]:
        return probe_bucket(req, self._bucket_of)

    def _route_locked(self, b: Optional[BucketKey]) -> int:
        healthy = [
            i for i, ln in enumerate(self.lanes) if not ln.quarantined
        ]
        if not healthy:
            # every lane quarantined: least-loaded of all is still the
            # best bet (recovery/restart re-drains the queue)
            healthy = list(range(len(self.lanes)))
        if b is not None:
            owner = self._affinity.get(b)
            if owner is not None and owner in healthy:
                return owner
        i = min(
            healthy,
            key=lambda j: len(self._queues[j]) + self._active[j],
        )
        if b is not None:
            self._affinity[b] = i
        return i

    def _steal_locked(self, i: int) -> Optional[Any]:
        """One request from the tail of the longest other queue (the
        victim's FIFO head keeps its lane + staged state).

        A run of requests sharing the victim's head bucket is left in
        place — the victim will drain it as one coalesced/fused group,
        and stealing out of it would trade a free ride on the resident
        executable for a cold load elsewhere — UNLESS the run is deeper
        than one fused dispatch can absorb (past the microbatch width
        the surplus gains nothing by waiting).

        A quarantined lane never steals (its worker is dead or wedged);
        stealing FROM a quarantined lane is allowed and desirable — it
        drains work the victim can no longer serve."""
        if self.lanes[i].quarantined:
            return None
        best, best_len = -1, 0
        for j, q in enumerate(self._queues):
            if j != i and len(q) > best_len:
                best, best_len = j, len(q)
        if best < 0:
            return None
        q = self._queues[best]
        head = q[0]
        head_b = head.bucket if head.bucketed else None
        for idx in range(len(q) - 1, -1, -1):
            r = q[idx]
            rb = r.bucket if r.bucketed else None
            if (
                head_b is None
                or rb != head_b
                or len(q) > self._microbatch
            ):
                del q[idx]
                self.steals += 1
                obs.metrics.count("serve.steals")
                return r
        return None

    def _hold_locked(self, i: int) -> bool:
        """The deterministic admission latch: True while lane ``i`` must
        keep its queue intact waiting for ``_hold_n`` admission-predicted
        (batchable) requests — or the hold window — only when the queue
        HEAD is itself admission-predicted, so a plain request (greedy
        solver, malformed input) never waits behind the latch, and only
        BATCHABLE requests count toward the target (a greedy request
        interleaving must not release a partial batch). Bucket equality
        is NOT checked (the probe parses input; this runs under the
        lock) — the deterministic-forming use case feeds same-shape
        clients by construction, and the window bounds any mix-up.
        Caller holds the lock; the argv-only admissibility predicate is
        lock-safe."""
        if self._hold_n <= 1 or self._stop or self._admissible is None:
            self._hold_since[i] = None
            return False
        q = self._queues[i]

        def batchable(r: Any) -> bool:
            try:
                return bool(self._admissible(r))
            except Exception:
                return False

        if not batchable(q[0]):
            self._hold_since[i] = None
            return False
        now = time.monotonic()
        since = self._hold_since[i]
        if since is None:
            self._hold_since[i] = since = now
        n_batchable = sum(1 for r in q if batchable(r))
        if n_batchable >= self._hold_n or now - since >= self._hold_window_s:
            self._hold_since[i] = None
            return False
        return True

    # -- the lane worker ---------------------------------------------------
    def _is_exclusive(self, req: Any) -> bool:
        """Does ``req`` take the whole mesh? argv-only predicate,
        lock-safe, fail-closed (an erroring predicate means a normal
        lane-pinned run — the pre-exclusive behavior)."""
        if self._exclusive is None:
            return False
        try:
            return bool(self._exclusive(req))
        except Exception:
            return False

    # thread-role: lane-worker
    def _worker(self, i: int) -> None:
        lane = self.lanes[i]
        while True:
            first: Any = None
            contended = False
            with self._cv:
                while True:
                    # mesh hold: while an exclusive request owns (or is
                    # draining toward) the mesh, no lane starts NEW
                    # work — in-flight requests finish, pops wait
                    if not self._stop and (
                        self._mesh_owner is not None
                        or any(self._excl_parked)
                    ):
                        self._cv.wait(0.1)
                        continue
                    if self._queues[i]:
                        if self._hold_locked(i):
                            self._cv.wait(0.02)
                            continue
                        first = self._queues[i].popleft()
                        contended = bool(self._queues[i])
                        break
                    stolen = self._steal_locked(i)
                    if stolen is not None:
                        first = stolen
                        break
                    if self._stop:
                        return
                    self._cv.wait()
                self._active[i] += 1
            excl = self._is_exclusive(first)
            if excl:
                contended = False  # never grouped: it runs the mesh alone
            group = [first]
            if contended:
                # same-bucket group assembly, probes OUTSIDE the lock
                # (the probe parses the request's input) — exactly the
                # Coalescer's contention-only economy. Snapshot, probe,
                # then re-check membership under the lock: a stealer may
                # have taken a snapshotted request in between.
                b0 = self._bucket(first)
                if b0 is not None:
                    with self._cv:
                        pending = list(self._queues[i])
                    same = [r for r in pending if self._bucket(r) == b0]
                    if same:
                        with self._cv:
                            taken = [
                                r for r in same if r in self._queues[i]
                            ]
                            for r in taken:
                                self._queues[i].remove(r)
                            self._active[i] += len(taken)
                        group.extend(taken)
            # ``claimed`` tracks every request this turn is responsible
            # for — continuous admission pulls MORE from the queue while
            # the batch runs, and each pull must ride the same answer-
            # everything / active-count guarantees as the initial group
            claimed = list(group)
            with self._cv:
                self._current[i] = claimed
                lane.last_beat = time.monotonic()
            # the chaos seam's worker-death injection (serve/faults.py):
            # LaneCrash is a BaseException — it skips the except/finally
            # nets below exactly like a real thread death, leaving the
            # claimed work for health_tick to answer and requeue
            faults.fire("lane_crash")
            t0 = time.monotonic()
            try:
                if excl:
                    self._run_exclusive(lane, first)
                else:
                    self._run_group(lane, group, claimed)
            except Exception as exc:
                # the worker must SURVIVE anything a group throws
                # (thread exhaustion in a fused run, a stage-thread
                # start failure): answer every unanswered member and
                # keep serving — a dead worker would wedge its queue's
                # clients forever (submit blocks on req.done with no
                # timeout, and affinity keeps routing here)
                obs.metrics.event(
                    "serve_lane_group_failed",
                    lane=lane.index,
                    error=type(exc).__name__,
                )
                for req in claimed:
                    if not req.done.is_set():
                        req.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": (
                                f"lane dispatch failed: "
                                f"{type(exc).__name__}: {exc}"
                            ),
                        }
                        req.done.set()
            finally:
                with self._cv:
                    self._active[i] -= len(claimed)
                    self._current[i] = []
                    lane.busy_s += time.monotonic() - t0
                    lane.requests += len(claimed)
                    lane.last_beat = time.monotonic()
                    self._cv.notify_all()

    def _run_exclusive(self, lane: Lane, req: Any) -> None:
        """Run one mesh-exclusive request: park until every OTHER lane
        has zero in-flight work (their pops are already held closed by
        the parked flag, so the fleet drains monotonically), claim mesh
        ownership, run the request solo on this lane's thread, release.
        Parked peers on other lanes do not count as busy — they are
        waiting on this same drain, and counting them would deadlock
        two concurrent exclusives; ownership arbitration under the lock
        serializes them instead. Shutdown mid-park NEVER runs the
        request without ownership — dispatching a mesh-wide collective
        beside still-in-flight lane work is exactly the race this
        mechanism exists to prevent (and can wedge the device worker
        uncatchably); the parked request is answered with a structured
        shutdown error instead."""
        i = lane.index
        owned = False
        with self._cv:
            self._excl_parked[i] += 1
            self._cv.notify_all()
            try:
                while not self._stop:
                    if self._mesh_owner is None and all(
                        self._active[j] - self._excl_parked[j] <= 0
                        for j in range(len(self.lanes))
                        if j != i
                    ):
                        self._mesh_owner = i
                        owned = True
                        break
                    self._cv.wait(0.05)
            finally:
                self._excl_parked[i] -= 1
            if owned:
                self.mesh_exclusive += 1
        if not owned:
            req.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": (
                    "daemon shutting down (mesh-exclusive request "
                    "not dispatched)"
                ),
            }
            req.done.set()
            return
        obs.metrics.count("serve.mesh_exclusive")
        try:
            self._run_one(lane, req, coalesced=False, mb=None)
        finally:
            with self._cv:
                self._mesh_owner = None
                self._cv.notify_all()

    def _stage_ahead(self, lane: Lane) -> None:
        """Kick the host-encode stage for this lane's NEXT queued request
        on a stage thread — double-buffered (the `staged` memo on the
        request bounds it to one stage per request)."""
        if self._stage is None:
            return
        with self._cv:
            q = self._queues[lane.index]
            nxt = q[0] if q else None
            if nxt is None or getattr(nxt, "staged", False):
                return
            nxt.staged = True
        stage = self._stage

        def body() -> None:  # thread-role: lane-worker
            try:
                stage(nxt, lane)
            except Exception:
                pass  # staging is an overlap, never a correctness step

        try:
            threading.Thread(
                target=body, name=f"serve-lane-{lane.index}-stage",
                daemon=True,
            ).start()
        except Exception:
            pass  # no thread to spare: the overlap is skipped, that's all

    def _run_group(
        self, lane: Lane, group: List[Any], claimed: List[Any]
    ) -> None:
        self._stage_ahead(lane)
        k = self._microbatch
        if k > 1 and len(group) > 1 and self._admissible is not None:
            # only ADMISSION-PREDICTED requests join a fusion batch: a
            # member that never reaches the fusible dispatch (greedy
            # solver, kernel engine, leader session) would stall its
            # peers until its entire request completed. Everything else
            # runs serially after, still coalesced in the window.
            fusible: List[Any] = []
            rest: List[Any] = []
            for req in group:
                try:
                    (fusible if self._admissible(req) else rest).append(req)
                except Exception:
                    rest.append(req)
            first = True
            if fusible and self._batch_mode != "oneshot":
                solo_run = False
                if len(fusible) == 1:
                    # occupancy-adaptive batch mode: one fusible
                    # request and an empty lane queue at dispatch time
                    # means the continuous machinery (batcher, member
                    # thread, drain loop, admission ticks) can only
                    # ever produce occupancy-1 rounds — run it inline
                    # instead. A request arriving a tick later re-pops
                    # into its own group; fusion re-engages whenever
                    # the queue actually has company.
                    with self._cv:
                        if not self._queues[lane.index]:
                            solo_run = True
                            self.solo_fast += 1
                if solo_run:
                    self._run_one(lane, fusible[0], coalesced=not first)
                else:
                    # non-batchable riders waiting in this window gate
                    # the feed: with `rest` pending, no new arrivals
                    # are pulled (the batch drains, the riders run, the
                    # worker re-pops) — mid-flight admission must never
                    # starve them
                    self._run_continuous(
                        lane, fusible, claimed, first=first,
                        feed=not rest,
                    )
                first = False
            else:
                # the one-shot control (-serve-batch-mode=oneshot): the
                # PR-5 fixed-membership barrier, run to completion
                for j in range(0, len(fusible), k):
                    run = fusible[j : j + k]
                    if len(run) == 1:
                        self._run_one(lane, run[0], coalesced=not first)
                    else:
                        self._run_fused(lane, run, first=first)
                    first = False
            for req in rest:
                self._run_one(lane, req, coalesced=not first)
                first = False
        else:
            for idx, req in enumerate(group):
                self._run_one(lane, req, coalesced=idx > 0)

    def _pull_admissible(
        self, lane: Lane, bucket: Optional[BucketKey]
    ) -> List[Any]:
        """Claim the queue-HEAD PREFIX of same-bucket admission-predicted
        requests from this lane's queue — the continuous batcher's
        mid-flight admission feed. Prefix only, never a leapfrog: the
        first non-batchable or different-bucket request stops the feed,
        so under sustained fused traffic an older queued greedy/other-
        bucket request is reached the moment the current batch drains
        instead of starving behind an endless stream of newer
        admissions. Probes run OUTSIDE the lock (they parse the
        request's input; memoized per request), membership re-checked
        under it (a stealer may have taken a snapshotted request —
        stealing only removes, so the prefix property survives)."""
        if bucket is None:
            return []
        i = lane.index
        with self._cv:
            if (
                self._stop
                or self._mesh_owner is not None
                or any(self._excl_parked)
                or not self._queues[i]
            ):
                # a draining/held mesh also stops the continuous feed:
                # mid-flight admission is new work too
                return []
            pending = list(self._queues[i])
        want = []
        for r in pending:
            try:
                if self._bucket(r) == bucket and (
                    self._admissible is not None and self._admissible(r)
                ):
                    want.append(r)
                else:
                    break
            except Exception:
                break
        if not want:
            return []
        with self._cv:
            taken = [r for r in want if r in self._queues[i]]
            for r in taken:
                self._queues[i].remove(r)
            self._active[i] += len(taken)
        return taken

    def _run_continuous(
        self, lane: Lane, fusible: List[Any], claimed: List[Any],
        first: bool, feed: bool = True,
    ) -> None:
        """The continuous-batching drain loop: admit up to K members
        into one :class:`ContinuousBatcher`, reap members as their
        requests retire (their slots free immediately), and — with
        ``feed`` — keep admitting newly staged same-bucket requests into
        the freed slots until both the batch and the feed drain (prefix
        pulls only; ``feed=False`` when non-batchable riders wait in
        this window). The batcher's rounds re-form at every solver chunk
        boundary, so an admission mid-way through its peers' sessions
        fuses its chunk 1 with their chunk i+1 — no request ever waits
        out a whole window."""
        cb = ContinuousBatcher(self._microbatch)
        cb.sink = self._note_fused
        waiting: Deque[Any] = deque(fusible)
        bucket = (
            fusible[0].bucket if feed and fusible[0].bucketed else None
        )
        running: Dict[Any, threading.Thread] = {}
        n_started = 0
        while True:
            # per-round live-telemetry samples (obs/hist.py): this
            # lane's queue depth and the batcher's live occupancy —
            # the Orca-style time series the stats scrape exposes.
            # Each round is also a watchdog heartbeat: a healthy
            # continuous batch must never read as a wedged lane
            lane.last_beat = time.monotonic()
            with self._cv:
                depth = len(self._queues[lane.index])
            obs.metrics.hist_observe(
                f"serve.lane{lane.index}.queue_depth", float(depth)
            )
            obs.metrics.hist_observe(
                "serve.cb_occupancy", float(len(running))
            )
            # the per-lane occupancy twin of the queue-depth series:
            # -metrics-prom renders both as lane-labeled series
            # (lane="N") beside the deprecated name-embedded spelling
            # (docs/observability.md)
            obs.metrics.hist_observe(
                f"serve.lane{lane.index}.occupancy", float(len(running))
            )
            while waiting and len(running) < self._microbatch:
                req = waiting.popleft()
                coalesced = n_started > 0 or not first
                cb.admit()
                t = threading.Thread(
                    target=self._run_one,
                    args=(lane, req, coalesced, cb),
                    name=f"serve-lane-{lane.index}-cb{n_started}",
                )
                n_started += 1
                try:
                    t.start()
                except Exception:
                    # can't start the member thread (thread exhaustion):
                    # release its batcher slot so the live members'
                    # rounds still complete, and run it inline, solo
                    cb.abandon()
                    self._run_one(lane, req, coalesced, None)
                    continue
                running[req] = t
            for req in [r for r in running if r.done.is_set()]:
                running.pop(req).join()
            if (
                len(running) + len(waiting) < self._microbatch
                and not self._stop
            ):
                pulled = self._pull_admissible(lane, bucket)
                if pulled:
                    claimed.extend(pulled)
                    waiting.extend(pulled)
                    continue
            if not running and not waiting:
                break
            if waiting and len(running) < self._microbatch:
                continue
            # members in flight and no free work to admit: wait for a
            # retirement (notified by the batcher) or the next poll tick
            cb.wait_change(self._admission_tick_s)

    # thread-role: lane-worker
    def _run_one(
        self,
        lane: Lane,
        req: Any,
        coalesced: bool,
        mb: Optional[MicrobatchGroup] = None,
    ) -> None:
        try:
            self._handle(req, coalesced, lane, mb)
        except Exception as exc:  # never wedge a waiter
            req.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": f"{type(exc).__name__}: {exc}",
            }
            if mb is not None and not getattr(req, "mb_entered", False):
                # the member died before joining the barrier: its slot
                # must not leave the live peers waiting for a join that
                # will never come
                mb.abandon()
        finally:
            req.done.set()

    def _run_fused(self, lane: Lane, run: List[Any], first: bool) -> None:
        mb = MicrobatchGroup(len(run))
        mb.sink = self._note_fused
        started: List[threading.Thread] = []
        inline: List[Tuple[Any, bool]] = []
        for idx, req in enumerate(run):
            coalesced = idx > 0 or not first
            t = threading.Thread(
                target=self._run_one,
                args=(lane, req, coalesced, mb),
                name=f"serve-lane-{lane.index}-mb{idx}",
            )
            try:
                t.start()
            except Exception:
                # can't start the member thread (thread exhaustion):
                # release its barrier slot so the started peers' rounds
                # still complete, and run it inline after them, solo
                mb.abandon()
                inline.append((req, coalesced))
                continue
            started.append(t)
        for t in started:
            t.join()
        for req, coalesced in inline:
            self._run_one(lane, req, coalesced, None)
