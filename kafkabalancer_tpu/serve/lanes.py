"""Device lanes: the multi-device scheduler and cross-request microbatching.

The PR-4 daemon funnels every request through ONE dispatcher onto one
device — correct, but it leaves every other attached device idle while
the outer automation loop queues up. This module turns the daemon into a
multi-device pipelined executor:

- :class:`Lane` — one worker lane per visible device, pinned to it: the
  lane's request threads deserialize AOT executables against the lane's
  device (``ops.aot.set_execution_device``), place jit dispatches on it
  (``jax.default_device``), keep a private digest-keyed tensorize row
  cache (``ops.tensorize.set_thread_row_cache``) and a private staging
  cache of pre-shipped device buffers;
- :class:`LaneScheduler` — routes queued requests across lanes with
  shape-bucket AFFINITY (a bucket sticks to the lane that already holds
  its compiled executable and primed row cache) plus WORK STEALING when
  a lane's queue is empty. Same ``submit``/``busy``/``stop`` interface
  as the single-lane ``Coalescer`` (serve/daemon.py). One visible
  device degrades to ONE lane; with microbatching also disabled
  (``-serve-microbatch=1``, or explicit ``-serve-lanes=1``) the daemon
  keeps the plain Coalescer — byte-for-byte the PR-4 dispatcher;
- per-lane 3-stage pipelining: while a lane executes request N on
  device, a stage thread host-encodes request N+1 (parse → settle →
  tensorize, priming the lane's row cache) and ``device_put``s its dense
  tensors into the lane's staging cache (``ops.aot.stage_host_arrays``),
  so N+1's dispatch finds its inputs already resident — double-buffered:
  at most one request staged ahead per lane;
- :class:`MicrobatchGroup` — cross-request microbatching: when a lane
  pops a same-bucket run deeper than one request, up to K requests run
  concurrently and their fused-session device dispatches are fused into
  ONE padded batched dispatch (``solvers.scan.session_packed_batched``
  over the sweep's per-scenario stacking layout). Today's coalescing
  dedupes the *window*; this fuses *distinct* requests into one device
  call, each still receiving its own bit-identical packed move log
  (pinned by the differential tests in tests/test_serve.py).

Layering: this module imports jax/numpy/solvers only lazily inside
methods — constructing a scheduler with ``device=None`` lanes (tests)
touches neither.
"""

from __future__ import annotations

import contextlib
import threading
import time
from collections import deque
from typing import (
    Any,
    Callable,
    Deque,
    Dict,
    Iterator,
    List,
    Optional,
    Sequence,
    Tuple,
)

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.serve.protocol import PROTO_VERSION

BucketKey = Tuple[int, int, int, bool]
# handler contract (daemon._handle_plan): sets req.response, never sets
# req.done (the scheduler owns the completion latch)
LaneHandler = Callable[[Any, bool, "Lane", Optional["MicrobatchGroup"]], None]
BucketFn = Callable[[Any], Optional[BucketKey]]
StageFn = Callable[[Any, "Lane"], None]
# predicate: will this request's planning reach the fusible dispatch
# (the XLA fused session)? Only such requests join a fusion barrier — a
# member that never dispatches would stall its peers until its whole
# request completes
FusibleFn = Callable[[Any], bool]


def probe_bucket(req: Any, bucket_of: BucketFn) -> Optional[BucketKey]:
    """The memoized shape-bucket probe shared by the Coalescer and the
    LaneScheduler (one definition: the memo-ordering subtleties must not
    drift between the two dispatchers). None is a valid 'no bucket'."""
    if not req.bucketed:
        req.bucketed = True
        try:
            req.bucket = bucket_of(req)
        except Exception:
            req.bucket = None
    bucket: Optional[BucketKey] = req.bucket
    return bucket

# a microbatch member waiting on the fusion barrier gives up and runs
# solo past this — the barrier fills as fast as the slowest member's
# host-side head (parse + settle + tensorize), seconds at flagship scale
MICROBATCH_WAIT_S = 120.0


class Lane:
    """One device lane: identity, pinned device, per-lane caches and
    counters. The worker thread lives in :class:`LaneScheduler`."""

    __slots__ = (
        "index", "device", "row_cache", "stage_cache", "busy_s", "requests",
    )

    def __init__(self, index: int, device: Any = None) -> None:
        self.index = index
        self.device = device
        self.row_cache: Any = None  # TensorizeRowCache, daemon-installed
        self.stage_cache: Dict[Any, Any] = {}
        self.busy_s = 0.0
        self.requests = 0

    @contextlib.contextmanager
    def context(self) -> Iterator[None]:
        """Pin the calling thread to this lane: AOT loads/staging and
        jit placement go to the lane's device, tensorize uses the lane's
        row cache, and the staging cache the stage thread fills is the
        one the dispatch consults."""
        from kafkabalancer_tpu.ops import aot
        # NOTE: ops/__init__ shadows the tensorize SUBMODULE with the
        # tensorize function; import the seam directly from the module
        from kafkabalancer_tpu.ops.tensorize import set_thread_row_cache

        aot.set_execution_device(self.device)
        aot.set_staging_cache(self.stage_cache)
        set_thread_row_cache(self.row_cache)
        try:
            if self.device is not None:
                import jax

                with jax.default_device(self.device):
                    yield
            else:
                yield
        finally:
            set_thread_row_cache(None)
            aot.set_staging_cache(None)
            aot.set_execution_device(None)

    def cache_stats(self) -> Dict[str, int]:
        if self.row_cache is None:
            return {"hits": 0, "misses": 0, "rows_reused": 0}
        stats: Dict[str, int] = self.row_cache.stats()
        return stats


class _MbEntry:
    """One member's pending submission at the microbatch barrier."""

    __slots__ = ("args", "statics", "result", "done", "solo")

    def __init__(self, args: Tuple, statics: Dict[str, Any]) -> None:
        self.args = args
        self.statics = statics
        self.result: Any = None
        self.done = False
        self.solo = False


def _mb_sig(args: Tuple, statics: Dict[str, Any]) -> Tuple[Any, ...]:
    """Fusion signature: leaf shapes/dtypes (None-ness included) plus the
    statics — two dispatches fuse only when they would compile the same
    program."""
    import numpy as np

    leaves = tuple(
        None if a is None else (np.asarray(a).shape, np.asarray(a).dtype.str)
        for a in args
    )
    return (leaves, tuple(sorted((k, repr(v)) for k, v in statics.items())))


class MicrobatchGroup:
    """Fusion barrier for K concurrently-running same-bucket requests.

    Each member's request thread installs the group via :meth:`member`;
    ``solvers.scan._dispatch_chunk`` then offers every fused-session
    dispatch here. A round completes when every LIVE member has either
    submitted a dispatch or finished its request entirely; submissions
    sharing a program signature are stacked (sweep scenario layout) and
    run as ONE batched device dispatch, each member receiving its own
    packed move log slice — bit-identical to a solo dispatch. Everything
    else (singleton signatures, non-XLA engines, any batched failure)
    FAILS OPEN: ``dispatch`` returns None and the caller runs the
    ordinary solo path, so fusion can cost correctness nothing.
    """

    def __init__(self, size: int, wait_s: float = MICROBATCH_WAIT_S) -> None:
        self._cv = threading.Condition()
        self._live = size
        self._pending: List[_MbEntry] = []
        self._wait_s = wait_s
        self.fused_requests = 0
        self.fused_dispatches = 0

    @contextlib.contextmanager
    def member(self, req: Any = None) -> Iterator[None]:
        """Install this group on the calling request thread; on exit the
        member leaves the barrier (so stragglers stop waiting for it).
        ``req`` (when given) is marked entered, so the scheduler can
        tell a member that died BEFORE joining from one that joined and
        left — see :meth:`abandon`."""
        from kafkabalancer_tpu.solvers import scan

        if req is not None:
            req.mb_entered = True
        scan.set_microbatcher(self)
        try:
            yield
        finally:
            scan.set_microbatcher(None)
            self._leave()

    def abandon(self) -> None:
        """A member failed before ever entering :meth:`member` (thread
        start failure, context-entry crash): release its barrier slot so
        the live peers' round can still complete instead of stalling to
        the timeout."""
        self._leave()

    def _leave(self) -> None:
        with self._cv:
            self._live -= 1
            batch = self._take_round_locked()
        if batch:
            self._execute(batch)

    def _take_round_locked(self) -> Optional[List[_MbEntry]]:
        if self._pending and len(self._pending) >= self._live:
            batch = self._pending
            self._pending = []
            return batch
        return None

    def dispatch(self, args: Tuple, statics: Dict[str, Any]) -> Optional[Any]:
        """Offer one dispatch for fusion; this member's packed move log,
        or None to run solo (declined / timed out / batch failed)."""
        if statics.get("engine") != "xla" or statics.get("leader"):
            return None  # kernel engines and the leader session run solo
        e = _MbEntry(args, statics)
        with self._cv:
            self._pending.append(e)
            batch = self._take_round_locked()
        if batch:
            self._execute(batch)
        deadline = time.monotonic() + self._wait_s
        with self._cv:
            while not e.done and not e.solo:
                left = deadline - time.monotonic()
                if left <= 0 or not self._cv.wait(left):
                    if e in self._pending:
                        self._pending.remove(e)
                    e.solo = True
        return None if e.solo else e.result

    def _execute(self, batch: List[_MbEntry]) -> None:
        by_sig: Dict[Tuple[Any, ...], List[_MbEntry]] = {}
        for e in batch:
            try:
                by_sig.setdefault(_mb_sig(e.args, e.statics), []).append(e)
            except Exception:
                with self._cv:
                    e.solo = True
        for entries in by_sig.values():
            if len(entries) == 1:
                with self._cv:
                    entries[0].solo = True
            else:
                self._run_fused(entries)
        with self._cv:
            self._cv.notify_all()

    def _run_fused(self, entries: List[_MbEntry]) -> None:
        try:
            import numpy as np

            from kafkabalancer_tpu.ops import aot
            from kafkabalancer_tpu.parallel.sweep import stack_instances
            from kafkabalancer_tpu.solvers import scan

            stacked: List[Any] = []
            for pos in range(len(entries[0].args)):
                vals = [e.args[pos] for e in entries]
                stacked.append(
                    None if vals[0] is None else stack_instances(vals)
                )
            with obs.span("serve.microbatch_dispatch", k=len(entries)):
                out = np.asarray(
                    aot.call_or_compile(
                        "session_packed_batched",
                        scan.session_packed_batched,
                        tuple(stacked),
                        dict(entries[0].statics),
                    )
                )
            with self._cv:
                for k, e in enumerate(entries):
                    if not e.solo:  # a timed-out member already went solo
                        e.result = out[k]
                        e.done = True
                self.fused_requests += len(entries)
                self.fused_dispatches += 1
            obs.metrics.count("serve.microbatched", len(entries))
        except Exception:
            # fail open: every waiter runs its own solo dispatch
            with self._cv:
                for e in entries:
                    if not e.done:
                        e.solo = True


class LaneScheduler:
    """Multi-lane dispatcher with bucket affinity, work stealing and
    optional microbatching; Coalescer-compatible interface."""

    def __init__(
        self,
        handle: LaneHandler,
        bucket_of: BucketFn,
        lanes: Sequence[Lane],
        microbatch: int = 1,
        stage: Optional[StageFn] = None,
        fusible: Optional[FusibleFn] = None,
    ) -> None:
        self._handle = handle
        self._bucket_of = bucket_of
        self.lanes = list(lanes)
        self._microbatch = max(1, microbatch)
        self._stage = stage
        self._fusible = fusible
        self._cv = threading.Condition()
        self._queues: List[Deque[Any]] = [deque() for _ in self.lanes]
        self._active = [0] * len(self.lanes)
        self._affinity: Dict[BucketKey, int] = {}
        self._stop = False
        self.steals = 0
        self.microbatched = 0
        self._workers = [
            threading.Thread(
                target=self._worker, args=(i,), name=f"serve-lane-{i}",
                daemon=True,
            )
            for i in range(len(self.lanes))
        ]
        for t in self._workers:
            t.start()

    # -- Coalescer-compatible surface ------------------------------------
    def busy(self) -> bool:
        """Queued or in-flight work on ANY lane — the daemon's
        idle-timeout check must not shut down under a long-running plan
        on one lane while the others sit empty."""
        with self._cv:
            return any(self._queues) or any(self._active)

    def submit(self, req: Any) -> Dict[str, Any]:
        # the routing probe runs OUTSIDE the lock (it parses the input)
        # and only when there is more than one lane to route between —
        # the single-lane scheduler keeps the Coalescer's probe-only-
        # under-contention economy (group assembly probes on demand).
        # Memoized on the request so group assembly never re-pays it.
        b = self._bucket(req) if len(self.lanes) > 1 else None
        with self._cv:
            if self._stop:
                return {
                    "v": PROTO_VERSION, "ok": False,
                    "error": "daemon shutting down",
                }
            i = self._route_locked(b)
            self._queues[i].append(req)
            self._cv.notify_all()
        req.done.wait()
        return req.response or {
            "v": PROTO_VERSION, "ok": False, "error": "request dropped",
        }

    def stop(self, timeout: float = 30.0) -> None:
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        for t in self._workers:
            t.join(timeout)

    def stats(self) -> Dict[str, float]:
        with self._cv:
            return {
                "lanes": float(len(self.lanes)),
                "steals": float(self.steals),
                "microbatched": float(self.microbatched),
                "lane_busy_s": float(sum(ln.busy_s for ln in self.lanes)),
                "cache_hits": float(
                    sum(ln.cache_stats()["hits"] for ln in self.lanes)
                ),
            }

    # -- routing ----------------------------------------------------------
    def _bucket(self, req: Any) -> Optional[BucketKey]:
        return probe_bucket(req, self._bucket_of)

    def _route_locked(self, b: Optional[BucketKey]) -> int:
        if b is not None:
            owner = self._affinity.get(b)
            if owner is not None:
                return owner
        load = [len(q) + a for q, a in zip(self._queues, self._active)]
        i = load.index(min(load))
        if b is not None:
            self._affinity[b] = i
        return i

    def _steal_locked(self, i: int) -> Optional[Any]:
        """One request from the tail of the longest other queue (the
        victim's FIFO head keeps its lane + staged state).

        A run of requests sharing the victim's head bucket is left in
        place — the victim will drain it as one coalesced/fused group,
        and stealing out of it would trade a free ride on the resident
        executable for a cold load elsewhere — UNLESS the run is deeper
        than one fused dispatch can absorb (past the microbatch width
        the surplus gains nothing by waiting)."""
        best, best_len = -1, 0
        for j, q in enumerate(self._queues):
            if j != i and len(q) > best_len:
                best, best_len = j, len(q)
        if best < 0:
            return None
        q = self._queues[best]
        head = q[0]
        head_b = head.bucket if head.bucketed else None
        for idx in range(len(q) - 1, -1, -1):
            r = q[idx]
            rb = r.bucket if r.bucketed else None
            if (
                head_b is None
                or rb != head_b
                or len(q) > self._microbatch
            ):
                del q[idx]
                self.steals += 1
                obs.metrics.count("serve.steals")
                return r
        return None

    # -- the lane worker ---------------------------------------------------
    def _worker(self, i: int) -> None:
        lane = self.lanes[i]
        while True:
            first: Any = None
            contended = False
            with self._cv:
                while True:
                    if self._queues[i]:
                        first = self._queues[i].popleft()
                        contended = bool(self._queues[i])
                        break
                    stolen = self._steal_locked(i)
                    if stolen is not None:
                        first = stolen
                        break
                    if self._stop:
                        return
                    self._cv.wait()
                self._active[i] += 1
            group = [first]
            if contended:
                # same-bucket group assembly, probes OUTSIDE the lock
                # (the probe parses the request's input) — exactly the
                # Coalescer's contention-only economy. Snapshot, probe,
                # then re-check membership under the lock: a stealer may
                # have taken a snapshotted request in between.
                b0 = self._bucket(first)
                if b0 is not None:
                    with self._cv:
                        pending = list(self._queues[i])
                    same = [r for r in pending if self._bucket(r) == b0]
                    if same:
                        with self._cv:
                            taken = [
                                r for r in same if r in self._queues[i]
                            ]
                            for r in taken:
                                self._queues[i].remove(r)
                            self._active[i] += len(taken)
                        group.extend(taken)
            t0 = time.monotonic()
            try:
                self._run_group(lane, group)
            except Exception as exc:
                # the worker must SURVIVE anything a group throws
                # (thread exhaustion in a fused run, a stage-thread
                # start failure): answer every unanswered member and
                # keep serving — a dead worker would wedge its queue's
                # clients forever (submit blocks on req.done with no
                # timeout, and affinity keeps routing here)
                obs.metrics.event(
                    "serve_lane_group_failed",
                    lane=lane.index,
                    error=type(exc).__name__,
                )
                for req in group:
                    if not req.done.is_set():
                        req.response = {
                            "v": PROTO_VERSION, "ok": False,
                            "error": (
                                f"lane dispatch failed: "
                                f"{type(exc).__name__}: {exc}"
                            ),
                        }
                        req.done.set()
            finally:
                with self._cv:
                    self._active[i] -= len(group)
                    lane.busy_s += time.monotonic() - t0
                    lane.requests += len(group)
                    self._cv.notify_all()

    def _stage_ahead(self, lane: Lane) -> None:
        """Kick the host-encode stage for this lane's NEXT queued request
        on a stage thread — double-buffered (the `staged` memo on the
        request bounds it to one stage per request)."""
        if self._stage is None:
            return
        with self._cv:
            q = self._queues[lane.index]
            nxt = q[0] if q else None
            if nxt is None or getattr(nxt, "staged", False):
                return
            nxt.staged = True
        stage = self._stage

        def body() -> None:
            try:
                stage(nxt, lane)
            except Exception:
                pass  # staging is an overlap, never a correctness step

        try:
            threading.Thread(
                target=body, name=f"serve-lane-{lane.index}-stage",
                daemon=True,
            ).start()
        except Exception:
            pass  # no thread to spare: the overlap is skipped, that's all

    def _run_group(self, lane: Lane, group: List[Any]) -> None:
        self._stage_ahead(lane)
        k = self._microbatch
        if k > 1 and len(group) > 1 and self._fusible is not None:
            # only PREDICTED-fusible requests join a fusion barrier: a
            # member that never reaches the fusible dispatch (greedy
            # solver, kernel engine, leader session) would stall its
            # peers until its entire request completed. Non-fusible
            # riders run serially after, still coalesced in the window.
            fusible: List[Any] = []
            rest: List[Any] = []
            for req in group:
                try:
                    (fusible if self._fusible(req) else rest).append(req)
                except Exception:
                    rest.append(req)
            first = True
            for j in range(0, len(fusible), k):
                run = fusible[j : j + k]
                if len(run) == 1:
                    self._run_one(lane, run[0], coalesced=not first)
                else:
                    self._run_fused(lane, run, first=first)
                first = False
            for req in rest:
                self._run_one(lane, req, coalesced=not first)
                first = False
        else:
            for idx, req in enumerate(group):
                self._run_one(lane, req, coalesced=idx > 0)

    def _run_one(
        self,
        lane: Lane,
        req: Any,
        coalesced: bool,
        mb: Optional[MicrobatchGroup] = None,
    ) -> None:
        try:
            self._handle(req, coalesced, lane, mb)
        except Exception as exc:  # never wedge a waiter
            req.response = {
                "v": PROTO_VERSION, "ok": False,
                "error": f"{type(exc).__name__}: {exc}",
            }
            if mb is not None and not getattr(req, "mb_entered", False):
                # the member died before joining the barrier: its slot
                # must not leave the live peers waiting for a join that
                # will never come
                mb.abandon()
        finally:
            req.done.set()

    def _run_fused(self, lane: Lane, run: List[Any], first: bool) -> None:
        mb = MicrobatchGroup(len(run))
        started: List[threading.Thread] = []
        inline: List[Tuple[Any, bool]] = []
        for idx, req in enumerate(run):
            coalesced = idx > 0 or not first
            t = threading.Thread(
                target=self._run_one,
                args=(lane, req, coalesced, mb),
                name=f"serve-lane-{lane.index}-mb{idx}",
            )
            try:
                t.start()
            except Exception:
                # can't start the member thread (thread exhaustion):
                # release its barrier slot so the started peers' rounds
                # still complete, and run it inline after them, solo
                mb.abandon()
                inline.append((req, coalesced))
                continue
            started.append(t)
        for t in started:
            t.join()
        for req, coalesced in inline:
            self._run_one(lane, req, coalesced, None)
        with self._cv:
            self.microbatched += mb.fused_requests
