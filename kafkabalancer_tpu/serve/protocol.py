"""The daemon wire protocol: versioned length-prefixed frames.

**v1** (the baseline every peer speaks): one frame = a 4-byte big-endian
payload length followed by that many bytes of UTF-8 JSON. Every message
carries ``{"v": PROTO_VERSION}``; a peer speaking a different version is
treated as unreachable (the client falls back to the in-process path
rather than risk a half-understood plan).

**v2** (negotiated at hello, see below): one frame = an 8-byte header
(two 4-byte big-endian lengths: JSON header, raw blob) followed by the
UTF-8 JSON header and then the raw binary blob. The blob carries bulk
payloads — the full input text on ``register``/``plan``, the packed
changed-row records on ``plan-rows``, the plan stdout on responses —
WITHOUT JSON string escaping, so a megabyte of cluster state costs one
memcpy instead of an escape/unescape pass on each side.

Negotiation: a v2-capable client adds ``"max_v": PROTO_V2`` to its v1
``hello``; the daemon always answers with its own ``max_v``. When BOTH
sides advertised v2, every subsequent frame on that connection (both
directions) is a v2 frame. A v1 client never sends ``max_v``, so the
daemon keeps v1 framing for it and every pre-v2 byte sequence means
exactly what it always did.

Requests carry ``"op"``:

- ``hello``    — liveness/identity handshake; the response carries the
  daemon pid, package version, uptime and request counters, and is what
  distinguishes a live daemon from a stale socket file;
- ``plan``     — one CLI invocation: ``argv`` (the canonical flag list
  the client built, ``-no-daemon`` included so the daemon never
  re-forwards) plus ``stdin`` (the input text when no ``-input``/
  ``-from-zk`` names a source). v2 plan headers may carry ``tenant``
  (the client's session identity) purely for telemetry attribution —
  an untenanted request lands in the scrape's ``other`` rollup. The
  response carries ``rc``/``stdout``/``stderr`` verbatim;
- ``stats``    — live telemetry scrape: the daemon's shared snapshot
  (requests/inflight/lane attribution) plus every streaming histogram's
  lifetime + windowed percentiles, as a schema-versioned document
  (``STATS_SCHEMA``). Answered on the connection thread, NEVER through
  the plan dispatcher — a scrape must not pause planning;
- ``dump-trace`` — the flight recorder's span ring + request log as a
  Perfetto-loadable Chrome trace document (the client writes the file);
- ``watch``    — the watch-mode lag scrape (serve/speculate.py
  ``ZkWatcher``): ticks/reads/errors, emitted-plan and speculation-hit
  counts, ``last_read_age_s`` / ``last_event_lag_s`` staleness, and
  the watcher's current state digest — answered on the connection
  thread like ``stats`` and equally passive for the idle clock. The
  replay harness polls it to sequence fake-ZK mutations against the
  watcher's reads; the same block also rides the ``stats`` document;
- ``shutdown`` — orderly daemon exit (acknowledged before the listener
  closes).

Overload protection (serve/admission.py, docs/serving.md § Overload):
a ``plan``-family request may carry ``deadline_ms`` — the client's
remaining wait budget. The daemon sheds a QUEUED request whose deadline
has passed (never one already dispatched), and sheds arrivals past its
queue/tenant caps, answering a structured

    ``{"ok": false, "op": "overload", "reason": <overload|tenant|
    deadline|quarantine|shutdown>, "retry_after_ms": N, "error": ...}``

frame instead of queueing forever. ``retry_after_ms`` is the daemon's
live estimate of when a retry could be admitted; the client honors it
with capped, jittered exponential backoff before taking its
byte-identical in-process fallback. Both framings carry the same keys
(v1: the JSON frame verbatim; v2: in the response header).

v2-only session ops (serve/sessions.py, docs/serving.md):

- ``register``   — create/replace a resident cluster session for
  ``(tenant, flags signature)``: the blob is the raw input text; the
  daemon parses it once, plans, and keeps the parsed + settled state
  resident. The response IS the plan result.
- ``plan-delta`` — the steady-state request: tenant + the client's
  state digest + argv, NO state payload. On a digest match the daemon
  plans from the resident session (parse/settle/encode all skipped);
  on a mismatch it answers ``resync: "rows"`` with its row-hash table
  (or ``resync: "full"`` when no compatible session exists).
- ``plan-rows``  — the row-level re-sync: the blob is the packed
  changed-row records (serve/state.py); the daemon patches its
  resident raw rows, re-settles, and plans.
- ``release``    — drop a tenant's resident sessions, hot AND warm
  (the response reports both: ``released`` / ``released_warm``).

End-to-end tracing (obs/edge.py, docs/observability.md § End-to-end
tracing): a plan-family **v2** header may carry ``"trace"`` — the
client's compact trace context ``{"id": <16 hex>, "parent": <client
forward-span sid>, "phases": {<pre-send client phase>: seconds},
"edge_pre_ms": N, "rtt_ns": N}``. The daemon adopts the remote trace:
its request span attribution carries the trace id, its flight record
stores it, and the client's pre-send phases land in the served
request's metrics export as ``client.phase.*`` gauges. The matching
**reply footer** rides the v2 response header as ``"trace"``: ``{"id",
"wall_s", "spans": [<= FOOTER span records from the request thread's
flight ring, raw daemon perf_counter_ns stamps]}`` — bounded, so a
footer can never dominate a reply. Clock alignment: a client hello may
carry ``"clock": true``; ONLY then does the hello response add
``"clock": {"recv_ns", "send_ns"}`` (daemon ``perf_counter_ns`` at
hello receipt/reply), giving the client one NTP-style 4-stamp sample
per handshake (obs/edge.py ``estimate_offset``). v1 frames NEVER carry
any of this — a v1 exchange stays byte-identical to every prior
release, and scrape hellos that omit the clock key get the exact
pre-v8 hello document.

Session durability (serve/spill.py, docs/serving.md § Session
durability): with ``-serve-session-spill-dir`` set, evicted/expired/
flushed sessions persist as checksummed disk records, and a
``plan-delta``/``plan-rows`` for an absent session first tries to
RESTORE the spilled record — the ``resync: "full"`` answer only
remains for true cold misses (no record, corrupt record, foreign
record). The wire shapes above are unchanged; durability is invisible
to the client except as fewer full resyncs.

Nothing in this module (or ``serve.client``) imports jax: the client
side of a forwarded invocation must stay as light as an error exit —
and that pin extends to the scrape verbs (``-serve-stats[-json]``,
``-serve-dump-trace``, ``-metrics-prom``), which are pure protocol
clients.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import tempfile
from typing import Any, Callable, Dict, Optional, Tuple

PROTO_VERSION = 1
# the binary-frame extension, negotiated per connection at hello; the
# baseline PROTO_VERSION stays 1 so every existing peer's handshake and
# plan exchange is byte-identical (see module docstring)
PROTO_V2 = 2

# the stats scrape document's schema id — versioned independently of the
# wire protocol (adding a scrape field bumps this, not PROTO_VERSION).
# v2: + "memory" (per-lane HBM/residency-pool attribution)
# v3: + "sessions" (resident cluster sessions: count/bytes/delta hits)
#     + "fallbacks" (daemon-observed client fallback/resync reasons)
# v4: + "tenants" (per-tenant attribution: bounded top-K label families
#     — request counts, latency hists, session/fallback attribution,
#     with demoted tenants rolled into "other")
# v5: + "admission" (fair-queue occupancy, caps, shed counts by reason,
#     the live retry_after estimate), "lane_health" (quarantines /
#     requeues / recoveries, quarantined lane list), "faults" (the
#     chaos seam's armed spec + fired counts), per-tenant "sheds", and
#     the flight recorder's "autodumps_suppressed"
# v6: + "paging" (the warm session tier, serve/spill.py: spills /
#     adopted / restores / restore_hits / corrupt_drops / evictions /
#     write_failures under the conservation identity spills + adopted
#     == restores + corrupt_drops + evictions + warm_entries, plus the
#     live warm_bytes/warm_entries footprint; same key set with the
#     tier disabled), and per-tenant "restores" / "warm_sessions" /
#     "warm_bytes" in the tenants block
# v7: + "speculation" (speculative plan-ahead, serve/speculate.py:
#     attempts / hits / misses / poisoned / aborted / deferred /
#     wasted_dispatches / memos / inflight under the exact identity
#     attempts == hits + misses + poisoned + memos), "watch" (the
#     -watch continuous controller: ticks / reads / events / resyncs /
#     plans_emitted / lag fields; same key set with the mode off), and
#     per-tenant "spec_hits" in the tenants block
# v8: + per-tenant "edge_ms" in the tenants block (the client-reported
#     edge cost — pre-send phase wall + wire RTT — as a streaming hist
#     per top-K tenant, from each request's trace context; null for
#     tenants whose clients never sent one)
STATS_SCHEMA_VERSION = 8
STATS_SCHEMA = f"kafkabalancer-tpu.serve-stats/{STATS_SCHEMA_VERSION}"

# a frame larger than this is a protocol error, not a payload: the
# biggest legitimate frame is a -full-output plan for a very large
# cluster (tens of MB), and an unframed/garbage peer must not make the
# reader allocate gigabytes from four random length bytes
MAX_FRAME_BYTES = 1 << 28

_LEN = struct.Struct(">I")


def default_socket_path() -> str:
    """The per-user default socket: ``$KAFKABALANCER_TPU_SOCKET`` when
    set, else ``<tmpdir>/kafkabalancer-tpu-<uid>.sock`` (per-uid so two
    operators on one host get independent daemons)."""
    env = os.environ.get("KAFKABALANCER_TPU_SOCKET", "")
    if env:
        return env
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"kafkabalancer-tpu-{uid}.sock")


def resolve_socket_path(flag_value: str = "") -> str:
    """The one precedence rule shared by daemon and client:
    ``-serve-socket`` flag > ``$KAFKABALANCER_TPU_SOCKET`` > default."""
    return flag_value or default_socket_path()


def pidfile_path(socket_path: str) -> str:
    """The liveness pidfile rides next to the socket."""
    return socket_path + ".pid"


def write_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes, or None on a clean EOF at a frame
    boundary (mid-frame EOF raises — that is a truncation, not a
    close)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ConnectionError(f"EOF mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(
    sock: socket.socket,
    on_first: Optional[Callable[[], None]] = None,
) -> Optional[Dict[str, Any]]:
    """One frame as a dict, or None on clean EOF. Raises on truncation,
    an oversized length prefix, or non-JSON payload. ``on_first`` (when
    given) fires once the length prefix has arrived — the seam the edge
    recorder uses to split ``wait_first_byte`` from ``receive`` without
    a second syscall layer; it must not raise."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    if on_first is not None:
        on_first()
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
    body = _recv_exact(sock, n) if n else b""
    if body is None:
        raise ConnectionError("EOF after frame header")
    obj = json.loads(body.decode("utf-8"))
    if not isinstance(obj, dict):
        raise ValueError("frame payload is not a JSON object")
    return obj


# --- v2 binary frames ------------------------------------------------------

_LEN2 = struct.Struct(">II")


def write_frame2(
    sock: socket.socket, obj: Dict[str, Any], blob: bytes = b""
) -> None:
    """One v2 frame: JSON header + raw binary blob, each length-capped
    like a v1 frame. The blob is shipped as-is — no JSON escaping."""
    header = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(header) > MAX_FRAME_BYTES:
        raise ValueError(f"frame header too large: {len(header)} bytes")
    if len(blob) > MAX_FRAME_BYTES:
        raise ValueError(f"frame blob too large: {len(blob)} bytes")
    # the blob is sent as-is, never concatenated: a register payload is
    # the whole cluster text, and building one joined bytes object
    # would re-copy the very megabytes this framing exists not to touch
    sock.sendall(_LEN2.pack(len(header), len(blob)) + header)
    if blob:
        sock.sendall(blob)


def read_frame2(
    sock: socket.socket,
    on_first: Optional[Callable[[], None]] = None,
) -> Optional[Tuple[Dict[str, Any], bytes]]:
    """One v2 frame as ``(header, blob)``, or None on clean EOF at a
    frame boundary. Raises on truncation, oversized lengths, or a
    non-JSON header — exactly the v1 error model. ``on_first`` is the
    same first-byte seam as :func:`read_frame`."""
    head = _recv_exact(sock, _LEN2.size)
    if head is None:
        return None
    if on_first is not None:
        on_first()
    hn, bn = _LEN2.unpack(head)
    if hn > MAX_FRAME_BYTES or bn > MAX_FRAME_BYTES:
        raise ValueError(
            f"frame lengths {hn}+{bn} exceed {MAX_FRAME_BYTES}"
        )
    header = _recv_exact(sock, hn) if hn else b""
    if header is None:
        raise ConnectionError("EOF after v2 frame header lengths")
    blob = _recv_exact(sock, bn) if bn else b""
    if blob is None:
        raise ConnectionError("EOF inside v2 frame blob")
    obj = json.loads(header.decode("utf-8"))
    if not isinstance(obj, dict):
        raise ValueError("v2 frame header is not a JSON object")
    return obj, blob
