"""The daemon wire protocol: versioned length-prefixed JSON frames.

One frame = a 4-byte big-endian payload length followed by that many
bytes of UTF-8 JSON. Every message carries ``{"v": PROTO_VERSION}``; a
peer speaking a different version is treated as unreachable (the client
falls back to the in-process path rather than risk a half-understood
plan). Requests carry ``"op"``:

- ``hello``    — liveness/identity handshake; the response carries the
  daemon pid, package version, uptime and request counters, and is what
  distinguishes a live daemon from a stale socket file;
- ``plan``     — one CLI invocation: ``argv`` (the canonical flag list
  the client built, ``-no-daemon`` included so the daemon never
  re-forwards) plus ``stdin`` (the input text when no ``-input``/
  ``-from-zk`` names a source). The response carries ``rc``/``stdout``/
  ``stderr`` verbatim;
- ``stats``    — live telemetry scrape: the daemon's shared snapshot
  (requests/inflight/lane attribution) plus every streaming histogram's
  lifetime + windowed percentiles, as a schema-versioned document
  (``STATS_SCHEMA``). Answered on the connection thread, NEVER through
  the plan dispatcher — a scrape must not pause planning;
- ``dump-trace`` — the flight recorder's span ring + request log as a
  Perfetto-loadable Chrome trace document (the client writes the file);
- ``shutdown`` — orderly daemon exit (acknowledged before the listener
  closes).

Nothing in this module (or ``serve.client``) imports jax: the client
side of a forwarded invocation must stay as light as an error exit —
and that pin extends to the scrape verbs (``-serve-stats[-json]``,
``-serve-dump-trace``, ``-metrics-prom``), which are pure protocol
clients.
"""

from __future__ import annotations

import json
import os
import socket
import struct
import tempfile
from typing import Any, Dict, Optional

PROTO_VERSION = 1

# the stats scrape document's schema id — versioned independently of the
# wire protocol (adding a scrape field bumps this, not PROTO_VERSION).
# v2: + "memory" (per-lane HBM/residency-pool attribution)
STATS_SCHEMA_VERSION = 2
STATS_SCHEMA = f"kafkabalancer-tpu.serve-stats/{STATS_SCHEMA_VERSION}"

# a frame larger than this is a protocol error, not a payload: the
# biggest legitimate frame is a -full-output plan for a very large
# cluster (tens of MB), and an unframed/garbage peer must not make the
# reader allocate gigabytes from four random length bytes
MAX_FRAME_BYTES = 1 << 28

_LEN = struct.Struct(">I")


def default_socket_path() -> str:
    """The per-user default socket: ``$KAFKABALANCER_TPU_SOCKET`` when
    set, else ``<tmpdir>/kafkabalancer-tpu-<uid>.sock`` (per-uid so two
    operators on one host get independent daemons)."""
    env = os.environ.get("KAFKABALANCER_TPU_SOCKET", "")
    if env:
        return env
    uid = os.getuid() if hasattr(os, "getuid") else 0
    return os.path.join(tempfile.gettempdir(), f"kafkabalancer-tpu-{uid}.sock")


def resolve_socket_path(flag_value: str = "") -> str:
    """The one precedence rule shared by daemon and client:
    ``-serve-socket`` flag > ``$KAFKABALANCER_TPU_SOCKET`` > default."""
    return flag_value or default_socket_path()


def pidfile_path(socket_path: str) -> str:
    """The liveness pidfile rides next to the socket."""
    return socket_path + ".pid"


def write_frame(sock: socket.socket, obj: Dict[str, Any]) -> None:
    payload = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    if len(payload) > MAX_FRAME_BYTES:
        raise ValueError(f"frame too large: {len(payload)} bytes")
    sock.sendall(_LEN.pack(len(payload)) + payload)


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    """Read exactly ``n`` bytes, or None on a clean EOF at a frame
    boundary (mid-frame EOF raises — that is a truncation, not a
    close)."""
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(min(n - got, 1 << 20))
        if not chunk:
            if got == 0:
                return None
            raise ConnectionError(f"EOF mid-frame ({got}/{n} bytes)")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def read_frame(sock: socket.socket) -> Optional[Dict[str, Any]]:
    """One frame as a dict, or None on clean EOF. Raises on truncation,
    an oversized length prefix, or non-JSON payload."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (n,) = _LEN.unpack(head)
    if n > MAX_FRAME_BYTES:
        raise ValueError(f"frame length {n} exceeds {MAX_FRAME_BYTES}")
    body = _recv_exact(sock, n) if n else b""
    if body is None:
        raise ConnectionError("EOF after frame header")
    obj = json.loads(body.decode("utf-8"))
    if not isinstance(obj, dict):
        raise ValueError("frame payload is not a JSON object")
    return obj
