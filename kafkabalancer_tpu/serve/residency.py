"""Shared device residency: one pool of device-resident arrays per lane.

PR 5's per-lane staging cache was SINGLE-USE: the stage thread shipped
the next request's tensors ahead, the dispatch popped them, and that was
the end of the buffer's life. But K concurrent requests over the same
broker universe share most of their dense encoding byte-for-byte —
weights, allowed masks, broker validity — and each one staged its own
private copy of identical content (K transfers of the same bytes per
batching round). This module generalizes ``solvers.scan
._dev_cached_asarray``'s session-scoped digest reuse ACROSS requests and
lanes, vLLM-style: device arrays are keyed by content digest, uploaded
once per lane, and shared by every concurrent member, so steady-state
staging traffic drops to the per-request delta rows (the arrays that
actually differ between clusters).

Eviction is refcounted: every lookup/insert on a request thread pins the
entry for that thread (one serving thread == one in-flight request), and
``release_thread`` — called when the lane context unwinds — drops the
pins. Only UNREFERENCED entries are evicted, LRU past the cap, so a
buffer can never be dropped out from under an in-flight dispatch's next
chunk. Buffers already captured by a dispatched computation stay alive
through jax's own references regardless; the refcount is about keeping
the SHARED copies hot while any member of the lane's active set still
plans over that universe.

Layering: jax-free at import (buffers are opaque objects put here by the
callers); safe to construct in tests with no backend at all.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, Set, Tuple

from kafkabalancer_tpu import obs

# entries with no holder beyond this many are evicted oldest-first; the
# working set of a lane is a handful of arrays per shape bucket, so the
# default is generous without letting a bucket-churning daemon pin
# unbounded device memory through the pool
DEFAULT_POOL_CAP = 64

# one request thread pins at most this many entries at a time; past it
# the OLDEST pins release (the entries stay pooled, merely evictable).
# A session's genuinely shared arrays (weights/allowed/validity — well
# under this) stay pinned because every chunk's lookup re-freshens
# them, while per-round transients (post-commit replicas, each round's
# freshly stacked batch args) age out of the pinned set instead of
# accumulating unevictable device buffers for the whole request — a
# long multi-chunk session would otherwise grow device memory linearly
# with its round count
THREAD_PIN_CAP = 16

# (shape, dtype.str, content digest) — the same key layout as
# ops.aot._stage_key, so the staging path and the pool cannot drift
PoolKey = Tuple[Any, ...]


class ResidencyPool:
    """Digest-keyed, refcounted pool of device-resident arrays.

    The pool replaces the single-use per-lane staging dict: lookups do
    NOT consume (the whole point is that the next request over the same
    universe hits the same buffer), and inserts from the dispatch path
    mean request 2 skips the transfer request 1 already paid. Counters
    feed the ``serve.residency_hits`` attribution gauge.
    """

    def __init__(self, cap: int = DEFAULT_POOL_CAP) -> None:
        self._lock = threading.RLock()
        # key -> device buffer; insertion order doubles as recency
        self._entries: "OrderedDict[PoolKey, Any]" = OrderedDict()
        # key -> thread idents currently pinning the entry
        self._refs: Dict[PoolKey, Set[int]] = {}
        # thread ident -> its pinned keys in pin order (the per-thread
        # pin LRU behind THREAD_PIN_CAP)
        self._pins: Dict[int, "OrderedDict[PoolKey, None]"] = {}
        self._cap = cap
        self.hits = 0
        self.misses = 0
        self.uploads = 0
        self.evictions = 0

    # -- mapping-ish surface (the staging call sites in ops/aot.py) -----
    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def __contains__(self, key: PoolKey) -> bool:
        with self._lock:
            return key in self._entries

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._refs.clear()
            self._pins.clear()

    # -- the shared-residency protocol ----------------------------------
    def _pin_locked(self, key: PoolKey) -> None:
        """Pin ``key`` for the calling thread, releasing the thread's
        OLDEST pins past ``THREAD_PIN_CAP`` (released entries stay
        pooled, merely evictable — see the cap's comment)."""
        ident = threading.get_ident()
        pins = self._pins.setdefault(ident, OrderedDict())
        pins.pop(key, None)
        pins[key] = None  # most-recent pin position
        self._refs.setdefault(key, set()).add(ident)
        while len(pins) > THREAD_PIN_CAP:
            old, _ = pins.popitem(last=False)
            self._unref_locked(old, ident)

    def _unref_locked(self, key: PoolKey, ident: int) -> None:
        refs = self._refs.get(key)
        if refs is not None:
            refs.discard(ident)
            if not refs:
                del self._refs[key]

    def lookup(self, key: PoolKey, retain: bool = True) -> Any:
        """The resident buffer for ``key`` (refreshing recency and, with
        ``retain``, pinning it for the calling thread), or None."""
        with self._lock:
            buf = self._entries.pop(key, None)
            if buf is None:
                self.misses += 1
                obs.metrics.count("serve.residency_misses")
                return None
            self._entries[key] = buf  # most-recent position
            if retain:
                self._pin_locked(key)
            self.hits += 1
        obs.metrics.count("serve.residency_hits")
        return buf

    def put(self, key: PoolKey, buf: Any, retain: bool = True) -> None:
        """Insert (or refresh) a device-resident buffer, pinning it for
        the calling thread when ``retain``; evicts unreferenced entries
        LRU past the cap."""
        with self._lock:
            self._entries.pop(key, None)
            self._entries[key] = buf
            if retain:
                self._pin_locked(key)
            self.uploads += 1
            self._evict_locked()
        obs.metrics.count("serve.residency_uploads")

    def release_thread(self) -> None:
        """Drop every pin held by the calling thread (the lane context's
        unwind — one serving thread is one in-flight request) and evict
        past the cap."""
        ident = threading.get_ident()
        with self._lock:
            for key in self._pins.pop(ident, {}):
                self._unref_locked(key, ident)
            self._evict_locked()

    def _evict_locked(self) -> None:
        if self._cap <= 0:
            return
        for key in list(self._entries):
            if len(self._entries) <= self._cap:
                break
            if self._refs.get(key):
                continue  # pinned by an in-flight request
            del self._entries[key]
            self.evictions += 1

    def device_bytes(self) -> int:
        """Total device bytes held by pooled entries — the
        residency-pool half of the per-lane memory attribution
        (``hello``/``stats``/``-metrics-prom``). Keys carry the host
        array's (shape, dtype) so jax-array ``nbytes`` is exact; opaque
        test buffers without ``nbytes`` count 0."""
        with self._lock:
            total = 0
            for buf in self._entries.values():
                n = getattr(buf, "nbytes", 0)
                if isinstance(n, int):
                    total += n
            return total

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "uploads": self.uploads,
                "evictions": self.evictions,
                "entries": len(self._entries),
                "referenced": sum(1 for r in self._refs.values() if r),
            }

    def hit_rate(self) -> float:
        with self._lock:
            seen = self.hits + self.misses
            return self.hits / seen if seen else 0.0
