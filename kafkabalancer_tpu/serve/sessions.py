"""Resident cluster sessions for the planning daemon.

The paper's deployment model re-invokes the planner once per move with
freshly read cluster state (PAPER.md §0); consecutive requests therefore
differ by exactly the move the planner itself emitted. The per-phase
histograms (PR 9) attribute most of the remaining served latency to
re-materializing that state per request: protocol transfer + parse +
settle + tensorize of a cluster the daemon already knows. A resident
session keeps everything the next request needs live in the daemon —
vLLM's state-residency argument applied to planning state, with
Clipper's per-tenant session structure for isolation (PAPERS.md).

One :class:`ClusterSession` per ``(tenant, flags-signature)`` holds:

- ``raw``      — the parsed, PRE-settle partition rows (copies), the
  shadow of what the client's outer loop observes. Every replica
  mutation the planner applies is mirrored here through the
  ``obs.convergence`` mutation tap, so after a request completes the
  session can predict the digest of the client's NEXT read (base state
  + the moves the outer loop will apply).
- ``pl``       — the SETTLED live list the previous plan ran on, moves
  applied in place (the reference's slice-aliasing state threading).
  On a digest match the next request plans directly on it: no parse,
  no text transfer, and settle degenerates to its no-repair prescreen.
- ``row_cache``— a trusted-delta :class:`~kafkabalancer_tpu.serve.cache.
  TensorizeRowCache`: the tap marks exactly the mutated rows, so the
  steady-state tensorize patches those rows without the O(P) key scan.

Correctness model — "never wrong answers": the ONLY fast path is gated
on the client's state digest equalling the digest of the session's
predicted raw state (serve/state.py, order-sensitive, every parsed
field). Anything else — a mutation the tap missed, an applied-but-
unemitted complete-partition probe move, external drift, a daemon
restart — makes the digests differ and degrades to a row-level or full
re-sync that rebuilds from ground truth. The one prediction-adjacent
subtlety handled explicitly: ``fill_defaults`` derives default
allowed-broker lists from the OBSERVED broker set, so when a session
whose rows use defaulted brokers sees that set change (a move vacating
a broker's last replica), the resident settled list is discarded and
rebuilt from raw even on a digest match (``universe_dirty``).

The :class:`SessionStore` is per-tenant, LRU-capped with idle expiry,
and reports bytes + hit/resync counters into the stats scrape's
``sessions`` block (docs/serving.md).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Any, Dict, Iterable, Iterator, List, Optional, Set, Tuple

from kafkabalancer_tpu.models import Partition, PartitionList
from kafkabalancer_tpu.serve import speculate, state as sstate

SessionKey = Tuple[str, str]

# flags that do not change planning-state evolution: two invocations
# differing only in these share one resident session. Everything else
# (solver, budgets, constraint knobs, input format, topics filter…)
# keys a separate session — conservative on purpose.
_SIG_EXCLUDE = (
    "-metrics-json=", "-trace=", "-explain=", "-stats=",
    "-full-output=", "-unique=", "-no-daemon=",
)


def flags_signature(argv: Iterable[str]) -> str:
    """The planning-relevant flag signature of a canonical forwarded
    argv (client argvs are sorted, so this is deterministic)."""
    return "\x00".join(
        a for a in argv if not a.startswith(_SIG_EXCLUDE)
    )


def _fields_of(p: Partition) -> sstate.RowFields:
    return sstate.partition_fields(p)


def _partition_from_fields(f: sstate.RowFields) -> Partition:
    topic, partition, replicas, weight, nrep, brokers, ncons = f
    return Partition(
        topic=topic,
        partition=partition,
        replicas=list(replicas),
        weight=weight,
        num_replicas=nrep,
        brokers=None if brokers is None else list(brokers),
        num_consumers=ncons,
    )


class ClusterSession:
    """One tenant's resident planning state; see the module docstring.

    Not internally locked: the owning daemon holds :attr:`lock` (via
    the store checkout) for the whole request that touches it."""

    def __init__(self, tenant: str, sig: str) -> None:
        self.tenant = tenant
        self.sig = sig
        self.lock = threading.Lock()
        self.in_use = False
        # set by SessionStore.release: an explicitly forgotten session
        # must not be re-persisted by an in-flight request's continuous
        # spill (the spill layer refuses released sessions)
        self.released = False
        self.last_used = time.monotonic()
        self.version = 1
        # the raw-row shadow + its canonical row bytes; digest is None
        # until the first request completes cleanly (and after any event
        # that makes prediction unsafe — a crashed request, a tap miss)
        self.raw: List[Partition] = []
        self.canon: List[bytes] = []
        self.digest: Optional[str] = None
        self._dirty: Set[int] = set()
        # the settled live list + identity map (live object -> row)
        self.pl: Optional[PartitionList] = None
        self._idmap: Dict[int, int] = {}
        # observed-broker multiset over raw replicas, for the
        # universe_dirty detection (only meaningful when any row's
        # allowed brokers were defaulted at parse)
        self._broker_counts: Dict[int, int] = {}
        self.default_brokers = False
        self.universe_dirty = False
        self.bucket: Optional[Any] = None
        self.approx_bytes = 0
        # speculative plan-ahead (serve/speculate.py): the canonical
        # argv of the last clean session request (what the speculator
        # re-plans with) and the live memoized answer, if any — the
        # memo is owned/retired through the Speculator's counters
        self.last_argv: Optional[List[str]] = None
        self.spec_memo: Optional[Any] = None
        from kafkabalancer_tpu.serve.cache import TensorizeRowCache

        self.row_cache = TensorizeRowCache()
        self.row_cache.enable_trusted_deltas()

    # -- snapshots --------------------------------------------------------
    def snapshot_from(self, pl: PartitionList) -> None:
        """Adopt ``pl`` as this session's live list and shadow its raw
        (pre-settle) rows. Called at parse time, BEFORE fill_defaults
        touches anything — the shadow must capture what the CLIENT
        read, not what settle derived."""
        parts = list(pl.iter_partitions())
        self.version = pl.version
        self.raw = [p.copy() for p in parts]
        self.canon = [
            sstate.canonical_row_bytes(*_fields_of(p)) for p in self.raw
        ]
        self._dirty = set()
        self.pl = pl
        self._idmap = {id(p): i for i, p in enumerate(parts)}
        self._rebuild_broker_counts()
        self.universe_dirty = False
        self.digest = sstate.rows_digest(self.version, self.canon)

    def _rebuild_broker_counts(self) -> None:
        """Recompute the observed-broker multiset (and whether any row
        relies on defaulted allowed brokers) from the raw shadow — the
        ONE definition shared by snapshot and row-patch paths."""
        counts: Dict[int, int] = {}
        default_brokers = False
        for p in self.raw:
            if p.brokers is None:
                default_brokers = True
            for b in p.replicas:
                counts[b] = counts.get(b, 0) + 1
        self._broker_counts = counts
        self.default_brokers = default_brokers

    def rebuild_pl(self) -> PartitionList:
        """A fresh pre-settle list from the raw shadow (the row-resync
        path): new Partition copies, new identity map. The caller runs
        the ordinary settle+plan pipeline on it, which re-derives every
        default — including the observed-broker universe — from ground
        truth, clearing :attr:`universe_dirty`."""
        parts = [p.copy() for p in self.raw]
        pl = PartitionList(version=self.version, partitions=parts)
        self.pl = pl
        self._idmap = {id(p): i for i, p in enumerate(parts)}
        self.universe_dirty = False
        return pl

    # -- the mutation tap -------------------------------------------------
    def _update_counts(
        self, old: List[int], new: List[int]
    ) -> None:
        """Maintain the observed-broker multiset across one replica
        change; flags ``universe_dirty`` whenever MEMBERSHIP changes
        (a vacated or brand-new broker — the defaulted allowed lists
        a fresh settle would derive are different then)."""
        counts = self._broker_counts
        for b in old:
            c = counts.get(b, 0) - 1
            if c <= 0:
                counts.pop(b, None)
                # a broker lost its last replica: the next fresh
                # settle would drop it from every defaulted allowed
                # list — the resident settled state is stale even if
                # the digest matches
                self.universe_dirty = True
            else:
                counts[b] = c
        for b in new:
            c = counts.get(b, 0)
            if c == 0:
                self.universe_dirty = True
            counts[b] = c + 1

    def change(self, part: Partition) -> "Optional[Tuple[int, List[int]]]":
        """Mirror one applied replica mutation into the raw shadow
        (the ``obs.convergence`` tap target). O(1) plus the replica
        lists' length. Returns ``(row, previous replicas)`` so the
        per-request context can revert an applied-but-unemitted probe
        move; None when the mutated object is untracked (prediction
        poisoned — the next request re-syncs instead of fast-pathing)."""
        i = self._idmap.get(id(part))
        if i is None:
            self.digest = None
            return None
        old = self.raw[i].replicas
        new = list(part.replicas)
        if self.default_brokers:
            self._update_counts(old, new)
        self.raw[i].replicas = new
        self._dirty.add(i)
        self.row_cache.mark_changed(i)
        return i, old

    def revert_change(self, i: int, old: List[int]) -> None:
        """Undo one mirrored mutation on BOTH the raw shadow and the
        settled live row — the complete-partition probe move is applied
        to the live list but never emitted, so the cluster will not see
        it; keeping it resident would force a re-sync on every
        steady-state step under the DEFAULT flag set."""
        if self.pl is None or self.pl.partitions is None:
            self.digest = None
            return
        live = self.pl.partitions[i]
        if self.default_brokers:
            self._update_counts(self.raw[i].replicas, old)
        live.replicas[:] = old
        self.raw[i].replicas = list(old)
        self._dirty.add(i)
        self.row_cache.mark_changed(i)

    # -- row patches (resync) ---------------------------------------------
    def apply_row_patches(
        self, patches: List[Tuple[int, sstate.RowFields]]
    ) -> bool:
        """Overwrite raw rows from client-shipped records; False when
        any index is out of range (structural drift — the caller falls
        back to a full re-sync)."""
        n = len(self.raw)
        for idx, _f in patches:
            if idx < 0 or idx >= n:
                return False
        for idx, fields in patches:
            self.raw[idx] = _partition_from_fields(fields)
            self.canon[idx] = sstate.canonical_row_bytes(*fields)
            self._dirty.discard(idx)
            self.row_cache.mark_changed(idx)
        # broker counts are rebuilt wholesale — patches are the rare
        # path and the incremental bookkeeping is not worth the risk
        self._rebuild_broker_counts()
        self._refresh_digest()
        return True

    # -- request lifecycle ------------------------------------------------
    def _refresh_digest(self) -> None:
        for i in self._dirty:
            self.canon[i] = sstate.canonical_row_bytes(
                *_fields_of(self.raw[i])
            )
        self._dirty = set()
        self.digest = sstate.rows_digest(self.version, self.canon)

    def finish(self, rc: Optional[int]) -> None:
        """Request end: on a clean exit, fold the tapped mutations into
        the per-row hashes and predict the client's next digest; on any
        failure, poison the prediction (the planner may have mutated
        state partway) — the next request re-syncs from ground truth."""
        if rc == 0 and self.digest is not None:
            self._refresh_digest()
        else:
            self.digest = None
        self.last_used = time.monotonic()
        self.approx_bytes = self._approx_bytes()

    def _approx_bytes(self) -> int:
        rows = 0
        for p in self.raw:
            rows += 120 + 16 * len(p.replicas)
            if p.brokers is not None:
                rows += 8 * len(p.brokers)
        # raw shadow + settled live list are comparable in size
        return (
            2 * rows
            + sum(len(b) for b in self.canon)
            + self.row_cache.approx_bytes()
        )

    def hash_table(self) -> bytes:
        """The resync diff table of the CURRENT raw shadow (dirty rows
        re-canonicalized first, so a poisoned session still diffs
        truthfully). Per-row hashes are derived here, lazily — only a
        resync pays them."""
        for i in self._dirty:
            self.canon[i] = sstate.canonical_row_bytes(
                *_fields_of(self.raw[i])
            )
        self._dirty = set()
        return sstate.pack_hash_table(sstate.hashes_of(self.canon))


def session_from_rows(
    tenant: str, sig: str, version: int, rows: List[sstate.RowFields]
) -> ClusterSession:
    """Rebuild a session from spilled raw rows (serve/spill.py): the
    raw shadow, canonical bytes, broker multiset and predicted digest
    are all re-derived from the record — the settled live list and the
    trusted-delta cache re-prime on the restored session's FIRST
    request (the ``rebuild`` kind re-settles from raw), after which
    the tenant is back on the delta fast path."""
    sess = ClusterSession(tenant, sig)
    sess.version = version
    sess.raw = [_partition_from_fields(f) for f in rows]
    sess.canon = [sstate.canonical_row_bytes(*f) for f in rows]
    sess._rebuild_broker_counts()
    sess.digest = sstate.rows_digest(version, sess.canon)
    sess.approx_bytes = sess._approx_bytes()
    return sess


class SessionStore:
    """The daemon's resident sessions: per-tenant, LRU-capped, idle
    expiry, bytes accounted. All methods thread-safe; sessions checked
    out ``in_use`` are never evicted.

    With a warm tier attached (:attr:`spill`, serve/spill.py), the hot
    cap stops being a discard boundary: LRU eviction and idle expiry
    DEMOTE the session to a disk record instead of dropping it, and
    explicit :meth:`release` forgets both tiers. The spill writes run
    inside the store lock — demotion is the rare path, and a spill
    racing a concurrent restore of the same key would be worse."""

    def __init__(
        self,
        cap: int = 64,
        idle_s: float = 3600.0,
        spill: Optional[Any] = None,
    ) -> None:
        self.cap = max(1, cap)
        self.idle_s = idle_s
        self.spill = spill
        # the daemon's Speculator (serve/speculate.py), when one is
        # attached: session removal retires any live memo as poisoned
        self.spec: Optional[Any] = None
        self._lock = threading.Lock()
        self._sessions: Dict[SessionKey, ClusterSession] = {}
        self.registered = 0
        self.delta_hits = 0
        self.resyncs_rows = 0
        self.resyncs_full = 0
        self.released = 0
        self.evicted_lru = 0
        self.expired_idle = 0
        # tensorize-cache attribution of sessions that no longer exist:
        # folded in at removal so the daemon's aggregate cache counters
        # are monotone (a scraper's rate() must never see them rewind).
        # A removed-but-still-checked-out session parks in _zombies
        # until its in-flight request checks in — retiring it early
        # would snapshot the cache BEFORE that request's lookups land
        # and under-count forever.
        self._retired_cache = {"hits": 0, "misses": 0, "rows_reused": 0}
        self._zombies: List[ClusterSession] = []
        # per-tenant release generations (see release/release_gen)
        self._release_gens: Dict[str, int] = {}

    def _retire(self, sess: ClusterSession) -> None:
        # a removed/replaced session's memoized answer can never be
        # served: retire it as poisoned BEFORE the zombie park (the
        # state it predicts is superseded either way)
        if sess.spec_memo is not None:
            if self.spec is not None:
                self.spec.poison_session(sess)
            else:
                sess.spec_memo = None
        if sess.in_use:
            self._zombies.append(sess)
            return
        st = sess.row_cache.stats()
        for k in self._retired_cache:
            self._retired_cache[k] += st.get(k, 0)

    def cache_stats(self) -> Dict[str, int]:
        """Aggregate tensorize-cache attribution across live, zombie
        (removed but still checked out) AND retired sessions."""
        with self._lock:
            out = dict(self._retired_cache)
            for s in list(self._sessions.values()) + self._zombies:
                st = s.row_cache.stats()
                for k in out:
                    out[k] += st.get(k, 0)
            return out

    def get(self, key: SessionKey) -> Optional[ClusterSession]:
        with self._lock:
            return self._sessions.get(key)

    def count_delta_hit(self) -> None:
        with self._lock:
            self.delta_hits += 1

    def count_resync_rows(self) -> None:
        with self._lock:
            self.resyncs_rows += 1

    def count_resync_full(self) -> None:
        with self._lock:
            self.resyncs_full += 1

    def checkout(
        self, key: SessionKey
    ) -> Tuple[Optional[ClusterSession], bool]:
        """Look up AND exclusively claim a session; ``(session, False)``
        on success (the caller must :meth:`checkin` after its request),
        ``(None, True)`` when the session exists but another request
        holds it, ``(None, False)`` when there is none.

        NON-blocking on purpose: a second concurrent request for the
        same tenant must not queue behind the first — the daemon
        answers it ``resync: full`` and it plans through the stateless
        register path, which coalesces/microbatches like any other
        request. Sessions accelerate the sequential outer loop; they
        must never serialize a concurrent burst."""
        with self._lock:
            sess = self._sessions.get(key)
        if sess is None:
            return None, False
        if not sess.lock.acquire(blocking=False):
            return None, True
        with self._lock:
            # re-validate: the session may have been released/evicted
            # between the lookup and the claim
            if self._sessions.get(key) is not sess:
                sess.lock.release()
                return None, False
            sess.in_use = True
        return sess, False

    def checkin(self, sess: ClusterSession) -> None:
        with self._lock:
            sess.in_use = False
            if sess in self._zombies:
                # removed (replaced/released) while this request held
                # it: fold its final cache counters now that they are
                # complete
                self._zombies.remove(sess)
                self._retire(sess)
        sess.lock.release()

    def _spill_locked(self, key: SessionKey, sess: ClusterSession) -> None:
        """Demote one session to the warm tier (no-op without one, or
        for a session whose prediction is poisoned — the spill layer
        refuses untrustworthy state itself). A session with a LIVE
        speculative memo is deliberately NOT re-spilled: its in-memory
        state has advanced past the answer the client has seen, while
        the continuous spill of the last REAL request already persisted
        exactly the state the client will describe next — overwriting
        that record would turn the next restore into a resync."""
        if self.spill is not None and sess.spec_memo is None:
            self.spill.spill(key, sess)

    def put(self, key: SessionKey, sess: ClusterSession) -> None:
        """Insert/replace a freshly registered session, demoting the
        least-recently-used idle sessions past the cap to the warm
        tier (or discarding them when no spill dir is configured)."""
        self._insert(key, sess, registered=True)

    def adopt(self, key: SessionKey, sess: ClusterSession) -> bool:
        """Insert a session RESTORED from the warm tier — same LRU
        discipline as :meth:`put`, but not counted as a register (the
        client never re-sent the cluster; that is the point). Returns
        False — nothing inserted — when the key is already occupied: a
        concurrent register that won the restore window holds newer
        state and must survive, never be clobbered by the older
        spilled record."""
        return self._insert(
            key, sess, registered=False, only_if_absent=True
        )

    def _insert(
        self, key: SessionKey, sess: ClusterSession, registered: bool,
        only_if_absent: bool = False,
    ) -> bool:
        with self._lock:
            prev = self._sessions.get(key)
            if only_if_absent and prev is not None:
                return False
            if registered:
                self.registered += 1
            sess.last_used = time.monotonic()
            if prev is not None and prev is not sess:
                self._retire(prev)
            self._sessions[key] = sess
            if len(self._sessions) > self.cap:
                idle = sorted(
                    (
                        (s.last_used, k)
                        for k, s in self._sessions.items()
                        if not s.in_use and s is not sess
                    ),
                )
                for _ts, k in idle[: len(self._sessions) - self.cap]:
                    victim = self._sessions[k]
                    self._spill_locked(k, victim)
                    self._retire(victim)
                    del self._sessions[k]
                    self.evicted_lru += 1
        return True

    def release(self, tenant: str) -> int:
        """Drop every session of ``tenant`` (all flag signatures) from
        the HOT tier — an explicit forget, never a demotion; the
        caller (the daemon's ``release`` op) drops the warm tier's
        records separately (warm FIRST, so no new restore can begin
        once the hot sweep runs). Returns how many were dropped.

        Every dropped session — zombies of the tenant included — is
        marked ``released`` so an in-flight request's continuous spill
        cannot resurrect it to disk, and the tenant's release
        GENERATION bumps so a restore racing this call is detected and
        dropped (daemon._checkout_or_restore)."""
        with self._lock:
            self._release_gens[tenant] = (
                self._release_gens.get(tenant, 0) + 1
            )
            keys = [k for k in self._sessions if k[0] == tenant]
            for k in keys:
                self._sessions[k].released = True
                self._retire(self._sessions[k])
                del self._sessions[k]
            for z in self._zombies:
                if z.tenant == tenant:
                    z.released = True
                    if z.spec_memo is not None:
                        if self.spec is not None:
                            self.spec.poison_session(z)
                        else:
                            z.spec_memo = None
            self.released += len(keys)
            return len(keys)

    def discard(self, key: SessionKey, sess: ClusterSession) -> None:
        """Drop ONE just-adopted session from the hot tier — the
        restore-vs-release race unwind (daemon._checkout_or_restore).
        Only the exact ``sess`` is swept: a fresh session registered
        under the same key while the restore was in flight must
        survive. Nothing is counted as a client-issued release — no
        generation bump, no ``released`` fold — but the session is
        marked ``released`` so its continuous spill cannot resurrect
        the forgotten state to disk."""
        with self._lock:
            sess.released = True
            if self._sessions.get(key) is sess:
                self._retire(sess)
                del self._sessions[key]

    def release_gen(self, tenant: str) -> int:
        """How many times ``tenant`` has been released — the restore
        path snapshots this before reading a warm record and drops the
        restored session when it moved underneath."""
        with self._lock:
            return self._release_gens.get(tenant, 0)

    def sweep(self, now: Optional[float] = None) -> int:
        """Expire idle sessions (demoting them to the warm tier when
        one is attached); called from the daemon's accept-loop tick.
        Returns how many expired."""
        if self.idle_s <= 0:
            return 0
        t = time.monotonic() if now is None else now
        with self._lock:
            expired = [
                k for k, s in self._sessions.items()
                if not s.in_use and t - s.last_used > self.idle_s
            ]
            for k in expired:
                self._spill_locked(k, self._sessions[k])
                self._retire(self._sessions[k])
                del self._sessions[k]
            self.expired_idle += len(expired)
            return len(expired)

    def flush_spill(self) -> int:
        """The shutdown flush: spill every idle resident session (the
        daemon calls this after its dispatchers drained, so in-use
        sessions are stragglers of crashed connections — skipped, the
        continuous spill already persisted their last clean state).
        Sessions STAY hot; only the disk copy is refreshed. Returns
        how many records were written."""
        if self.spill is None:
            return 0
        with self._lock:
            flushed = 0
            for k, s in self._sessions.items():
                # spec-memo sessions keep their last REAL spill record
                # (see _spill_locked) — flushing the advanced state
                # would break the next restore's digest match
                if (
                    not s.in_use
                    and s.spec_memo is None
                    and self.spill.spill(k, s)
                ):
                    flushed += 1
            return flushed

    def stats_by_tenant(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant footprint across BOTH tiers (hot session count +
        approx bytes, warm record count + bytes), summed across flag
        signatures — the scrape's ``tenants`` block reads session
        attribution through this. The warm half is the demotion-
        accounting fix: a tenant whose sessions were all demoted keeps
        its byte attribution visible (the top-tenants table shows a
        hot/warm tier column) instead of silently vanishing, while its
        delta-hit/latency counters live on in the label families."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (tenant, _sig), s in self._sessions.items():
                e = out.setdefault(tenant, {
                    "sessions": 0, "bytes": 0,
                    "warm_sessions": 0, "warm_bytes": 0,
                })
                e["sessions"] += 1
                e["bytes"] += s.approx_bytes
        if self.spill is not None:
            for tenant, w in self.spill.stats_by_tenant().items():
                e = out.setdefault(tenant, {
                    "sessions": 0, "bytes": 0,
                    "warm_sessions": 0, "warm_bytes": 0,
                })
                e["warm_sessions"] += w["warm_sessions"]
                e["warm_bytes"] += w["warm_bytes"]
        return out

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {
                "count": len(self._sessions),
                "bytes": sum(
                    s.approx_bytes for s in self._sessions.values()
                ),
                "cap": self.cap,
                "registered": self.registered,
                "delta_hits": self.delta_hits,
                "resyncs_rows": self.resyncs_rows,
                "resyncs_full": self.resyncs_full,
                "released": self.released,
                "evicted_lru": self.evicted_lru,
                "expired_idle": self.expired_idle,
            }


class PlanSessionContext:
    """The per-request seam handed to ``cli.run(session=...)`` AND
    installed as the convergence mutation tap.

    - ``kind`` — ``"register"`` (parse + snapshot), ``"delta"``
      (resident fast path: :attr:`resident_pl` set, parse skipped) or
      ``"rows"`` (rebuild from the patched raw shadow).
    - :meth:`on_parsed` — called by the CLI right after a successful
      parse, before settle mutates anything.
    - :meth:`change` — the mutation tap target.
    """

    def __init__(
        self,
        kind: str,
        session: ClusterSession,
        resident_pl: Optional[PartitionList] = None,
        restored: bool = False,
    ) -> None:
        # kind: "register" (parse+snapshot) | "delta" (resident fast
        # path) | "rebuild" (digest matched but the settled list is
        # stale — universe_dirty, or the session was just restored
        # from a warm spill record and has no settled list yet — so
        # re-derive it from the raw shadow) | "rows" (client-shipped
        # row patches applied, then rebuild)
        self.kind = kind
        self.session = session
        self.resident_pl = resident_pl
        # this request re-homed the session from the warm tier (the
        # daemon attributes it serve.restore_hit)
        self.restored = restored
        self.snapshotted = False
        # this request's mirrored-mutation log, for probe-move reverts
        self._log: List[Tuple[int, List[int]]] = []
        self._unemitted = 0

    def resident(self) -> Optional[PartitionList]:
        """The list the CLI should plan on instead of parsing input —
        None for ``register`` (the CLI parses, then snapshots via
        :meth:`on_parsed`). The ``rows``/``rebuild`` paths rebuild
        lazily HERE so the O(P) copy lands inside the CLI's parse span
        (honest phase attribution) on the request thread."""
        if self.kind == "delta":
            return self.resident_pl
        if self.kind in ("rows", "rebuild"):
            if self.resident_pl is None:
                self.resident_pl = self.session.rebuild_pl()
            return self.resident_pl
        return None

    def on_parsed(self, pl: PartitionList) -> None:
        if self.kind == "register":
            self.session.snapshot_from(pl)
            self.snapshotted = True

    def change(self, part: Partition) -> None:
        # per-applied-move preemption seam: a speculative run aborts
        # here (one getattr for every real request — see
        # serve/speculate.py maybe_abort_dispatch)
        speculate.maybe_abort_dispatch()
        rec = self.session.change(part)
        if rec is not None:
            self._log.append(rec)

    def mark_last_unemitted(self, k: int) -> None:
        """The CLI's complete-partition break: the last ``k`` applied
        moves will NOT reach the plan (the probe move and any
        applied-after peers). Only RECORDED here — the actual revert
        runs in :meth:`apply_unemitted_reverts`, AFTER ``cli.run`` has
        written its output: an emitted entry can alias the probe
        partition (the reference's slice aliasing), so reverting
        before the write would change the emitted bytes."""
        if k > 0:
            self._unemitted += k

    def apply_unemitted_reverts(self) -> None:
        """Undo the recorded unemitted applies (daemon-side, post-run,
        pre-``finish``) so the session still predicts the client's
        next read — the cluster only ever sees the emitted plan."""
        k = self._unemitted
        self._unemitted = 0
        if k <= 0:
            return
        if k > len(self._log):
            # fewer mirrored mutations than unemitted applies: some
            # mutation escaped the tap — prediction is untrustworthy
            self.session.digest = None
            return
        for i, old in reversed(self._log[-k:]):
            self.session.revert_change(i, old)
        del self._log[-k:]

    @contextmanager
    def activate(self) -> Iterator[None]:
        """Install this session on the calling request thread: its
        trusted-delta row cache (overriding any lane cache) and the
        convergence mutation tap. Always uninstalled on exit — daemon
        request threads are reused."""
        from kafkabalancer_tpu.obs import convergence
        from kafkabalancer_tpu.ops.tensorize import set_thread_row_cache

        set_thread_row_cache(self.session.row_cache)
        convergence.set_mutation_tap(self)
        try:
            yield
        finally:
            convergence.set_mutation_tap(None)
            set_thread_row_cache(None)
