"""Speculative plan-ahead and the watch-driven continuous controller.

The resident-session steady state (serve/sessions.py) already removed
parse/settle/tensorize from the served hot path — what remains of the
~53 ms daemon-side p50 is the DISPATCH itself. But after answering
request N the lane sits idle, and the session already holds exactly the
post-move state the next request will describe (the mutation tap
mirrored the daemon's own emitted moves into the raw shadow). So keep
the *answer* resident, not just the state:

- :class:`Speculator` — after a clean session-backed plan, an
  idle-priority worker re-plans the NEXT move on the (already settled,
  trusted-delta-primed) resident session and memoizes the full answer
  (rc + plan stdout + stderr) keyed by the digest it predicts the
  client will send. A digest-and-argv-matching next request answers
  from the memo with ZERO dispatch (serve/daemon.py
  ``_answer_from_memo``); anything else drops the memo and falls back
  to the live delta/resync ladder with byte parity intact — the memo
  can make a request *faster*, never *different*.

  Speculation is PREEMPTIBLE: it only starts when the daemon is idle,
  any real plan-family dispatch sets the preempt flag
  (:meth:`Speculator.note_real_traffic`, wired through admission
  arrival), and the in-flight speculative run aborts cooperatively at
  the next solver chunk round or applied move
  (:func:`maybe_abort_dispatch`, raised as
  :class:`SpeculationAborted`) so live-traffic p95 cannot regress.
  An aborted run leaves the session's prediction poisoned — the next
  request re-syncs from ground truth, degraded but never wrong.

  Accounting model (the scrape's ``speculation`` block): every
  completed speculative run either produces a memo (``attempts``) or
  not (``aborted``); every memo retires exactly one way — ``hits``
  (consumed by a matching request), ``misses`` (a request arrived but
  could not use it: digest/argv mismatch or a resync path), or
  ``poisoned`` (lifecycle retirement: release / eviction / external
  drift / a crashed request). The exact identity
  ``attempts == hits + misses + poisoned + memos`` holds at every
  scrape instant (``memos`` = memos currently live);
  ``wasted_dispatches = misses + poisoned`` is the device work paid
  without payoff.

- :class:`ZkWatcher` — the ``-watch`` mode: the daemon subscribes to
  Zookeeper itself (codecs/zookeeper.py; kazoo watches where the
  client supports them, a poll-interval fallback everywhere), applies
  change events to a resident session, re-plans — speculation makes
  the steady-state re-plan a memo read — and streams reassignment
  plans to a sink (``-watch-emit <dir|->``). No client process exists
  in the steady state at all; the ``watch`` protocol op exposes watch
  lag for ``-serve-stats`` and the replay harness.

Neither class imports jax; the speculative run itself executes through
the ordinary dispatcher as an INTERNAL request (``PlanRequest.internal``)
that never touches the idle-timeout clock, ``serve.requests``,
``serve.request_s`` or the flight-recorder request log — it carries its
own ``serve.spec.plan_s`` / ``serve.watch.plan_s`` histograms instead
(docs/observability.md § Speculation).
"""

from __future__ import annotations

import json
import os
import sys
import threading
import time
from collections import deque
from typing import Any, Callable, Deque, Dict, List, Optional, Set, Tuple

from kafkabalancer_tpu import obs

SessionKey = Tuple[str, str]
LogFn = Callable[[str], None]

# forwarded-argv prefixes that make an answer non-memoizable: the
# telemetry trio / explain write per-invocation side effects (files,
# appended stdout), profiling pins work to a process, zookeeper input
# re-reads external state, and a mesh-exclusive -fused-shard run must
# never be launched as idle work (it drains every lane)
_NON_MEMOIZABLE = (
    "-metrics-json=", "-trace=", "-stats=", "-explain=",
    "-pprof", "-jax-profile=", "-from-zk=", "-fused-shard=",
)

# how long the idle-priority worker waits for the daemon to go idle
# before deferring a queued speculation (the next real request
# re-enqueues it)
IDLE_WAIT_S = 30.0
# how long a mismatching request waits for an aborted in-flight
# speculative run to unwind before giving up (resync-full fallback)
ABORT_WAIT_S = 30.0
# busy-session retry: the enqueue can race the enqueuing request's own
# checkin by microseconds
BUSY_RETRIES = 20
BUSY_RETRY_SLEEP_S = 0.05


class SpeculationAborted(BaseException):
    """Raised inside a speculative run when real traffic preempts it.

    A ``BaseException`` on purpose: the solver's fail-open ladders catch
    ``Exception`` broadly, and a preemption must unwind the whole run,
    not degrade it to a slower engine."""


_tls = threading.local()


def install_abort_check(fn: Optional[Callable[[], None]]) -> None:
    """Install (or clear, with None) the calling thread's speculative
    abort check — set by the daemon around an internal speculative
    ``cli.run`` and consulted by the dispatch seams below."""
    _tls.fn = fn


def maybe_abort_dispatch() -> None:
    """The cooperative preemption seam: a no-op on every thread without
    an installed check (one getattr), called from
    ``solvers.scan._dispatch_chunk`` (per device chunk round) and
    ``serve.sessions.PlanSessionContext.change`` (per applied move).
    Raises :class:`SpeculationAborted` when preempted."""
    fn = getattr(_tls, "fn", None)
    if fn is not None:
        fn()


class SpecMemo:
    """One memoized answer: the full response a digest-matching next
    request receives, plus the post-move digest the session advanced
    to (the next prediction)."""

    __slots__ = ("key_digest", "argv", "rc", "stdout", "stderr",
                 "next_digest")

    def __init__(
        self,
        key_digest: str,
        argv: List[str],
        rc: int,
        stdout: str,
        stderr: str,
        next_digest: str,
    ) -> None:
        self.key_digest = key_digest
        self.argv = argv
        self.rc = rc
        self.stdout = stdout
        self.stderr = stderr
        self.next_digest = next_digest


class _Inflight:
    __slots__ = ("key", "digest", "argv", "done")

    def __init__(self, key: SessionKey, digest: str, argv: List[str]) -> None:
        self.key = key
        self.digest = digest
        self.argv = argv
        self.done = threading.Event()


def memoizable_argv(argv: List[str]) -> bool:
    """Whether a forwarded canonical argv's answer is safe to memoize
    (pure function of session state — no per-invocation side effects)."""
    return not any(a.startswith(_NON_MEMOIZABLE) for a in argv)


class Speculator:
    """The idle-priority plan-ahead worker; see the module docstring.

    Thread-safety: one lock owns the counters and the memo population
    count; the inflight slot is written under it and read racily by the
    cheap preemption checks (a stale read only costs one conservative
    abort or one extra wait tick, never correctness)."""

    def __init__(self, daemon: Any, enabled: bool = False) -> None:
        self._d = daemon
        self.enabled = enabled
        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._dq: Deque[Tuple[SessionKey, int]] = deque()
        self._queued: Set[SessionKey] = set()
        self._stop_flag = False
        self._preempt = threading.Event()
        self._inflight: Optional[_Inflight] = None
        self._thread: Optional[threading.Thread] = None
        # the accounting model (module docstring): attempts == hits +
        # misses + poisoned + memos, at every instant
        self.attempts = 0
        self.hits = 0
        self.misses = 0
        self.poisoned = 0
        self.aborted = 0
        self.deferred = 0
        self._memos = 0

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if not self.enabled or self._thread is not None:
            return
        self._thread = threading.Thread(
            target=self._loop, name="serve-spec", daemon=True
        )
        self._thread.start()

    def request_stop(self) -> None:
        """Flag shutdown: the in-flight run aborts at its next check,
        the worker exits after it unwinds (join separately)."""
        with self._cv:
            self._stop_flag = True
            self._cv.notify_all()
        self._preempt.set()

    def join(self, timeout: float = 15.0) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)

    # -- preemption -------------------------------------------------------
    def note_real_traffic(self) -> None:
        """A real plan-family request arrived (admission arrival hook):
        preempt any in-flight speculative dispatch."""
        if self._inflight is not None:
            self._preempt.set()

    def preempted(self) -> bool:
        return self._preempt.is_set() or self._stop_flag

    def maybe_abort(self) -> None:
        if self.preempted():
            raise SpeculationAborted("preempted by real traffic")

    def wait_for_key(
        self,
        key: SessionKey,
        digest: str,
        argv: List[str],
        budget_s: float,
    ) -> bool:
        """A plan-family request found its session busy: if speculation
        holds it, wait it out — a MATCHING in-flight run is this very
        request's answer being computed (wait the full budget), a
        mismatching one is aborted and waited briefly. Returns True
        when speculation was involved (the caller re-claims the
        session), False when the session is busy for another reason."""
        inf = self._inflight
        if inf is None or inf.key != key:
            return False
        if inf.digest == digest and inf.argv == argv:
            inf.done.wait(max(0.1, budget_s))
            return True
        self._preempt.set()
        inf.done.wait(min(max(0.1, budget_s), ABORT_WAIT_S))
        return True

    # -- the queue --------------------------------------------------------
    def enqueue(self, key: SessionKey) -> None:
        """Ask for a plan-ahead of ``key``'s next move (idle-priority;
        deduplicated; a no-op when speculation is off)."""
        if not self.enabled:
            return
        with self._cv:
            if self._stop_flag or key in self._queued:
                return
            self._queued.add(key)
            self._dq.append((key, 0))
            self._cv.notify_all()

    # -- memo accounting (the one owner of the counters) ------------------
    # Every sess.spec_memo mutation is a compare-and-swap under THIS
    # lock: a memo retires exactly once (hit, miss, or poisoned) even
    # when a `release`/replacement poisons it concurrently with a
    # request consuming it — a double retirement would break the
    # attempts == hits + misses + poisoned + memos identity forever.
    def attach_memo(self, sess: Any, memo: SpecMemo) -> None:
        with self._lock:
            sess.spec_memo = memo
            self.attempts += 1
            self._memos += 1

    def take_memo(self, sess: Any, memo: SpecMemo) -> bool:
        """Consume ``memo`` as a HIT iff it is still the session's live
        memo; False means a concurrent lifecycle event retired it first
        (the caller falls back to the live ladder)."""
        with self._lock:
            if getattr(sess, "spec_memo", None) is not memo:
                return False
            sess.spec_memo = None
            self.hits += 1
            self._memos -= 1
            return True

    def untake_memo(self, sess: Any, memo: SpecMemo) -> None:
        """Undo a :meth:`take_memo` whose answer was never delivered
        (the hit request was shed at admission): re-attach the memo so
        the client's backoff retry can still hit. Safe because the
        memo slot stayed None the whole time (no poison could land)."""
        with self._lock:
            if (
                getattr(sess, "spec_memo", None) is None
                and not sess.released
            ):
                sess.spec_memo = memo
                self.hits -= 1
                self._memos += 1

    def rearm_memo(self, sess: Any, memo: SpecMemo) -> bool:
        """Re-attach a just-consumed memo whose answer is a FIXED
        POINT: the plan moved nothing (``next_digest == key_digest``),
        so serving it did not advance the session and the identical
        next request deserves the identical answer — without burning a
        device dispatch re-deriving it. The steady-state poll loop
        (edge residency: an unchanged input stat-hitting the client
        cache every few seconds) collapses to zero speculative
        dispatches this way. Counted as a fresh zero-cost attempt, so
        the attempts == hits + misses + poisoned + memos identity is
        undisturbed. False when the slot is no longer re-armable (a
        concurrent release/poison or a newer memo won) — the caller
        falls back to a normal plan-ahead enqueue."""
        with self._lock:
            if (
                memo.next_digest != memo.key_digest
                or memo.rc != 0
                or getattr(sess, "spec_memo", None) is not None
                or getattr(sess, "released", False)
            ):
                return False
            sess.spec_memo = memo
            self.attempts += 1
            self._memos += 1
            return True

    def retire_miss(self, sess: Any, memo: SpecMemo) -> None:
        """Retire ``memo`` as a MISS (a request arrived that cannot use
        it) — a no-op when a concurrent event already retired it."""
        with self._lock:
            if getattr(sess, "spec_memo", None) is memo:
                sess.spec_memo = None
                self.misses += 1
                self._memos -= 1

    def poison_session(self, sess: Any) -> None:
        """Retire a session's live memo as poisoned (store removal,
        release, external drift) — safe to call with any lock held
        except this speculator's own."""
        with self._lock:
            if getattr(sess, "spec_memo", None) is not None:
                sess.spec_memo = None
                self.poisoned += 1
                self._memos -= 1

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "enabled": self.enabled,
                "attempts": self.attempts,
                "hits": self.hits,
                "misses": self.misses,
                "poisoned": self.poisoned,
                "aborted": self.aborted,
                "deferred": self.deferred,
                "wasted_dispatches": self.misses + self.poisoned,
                "memos": self._memos,
                "inflight": self._inflight is not None,
            }

    # -- the worker -------------------------------------------------------
    def _busy(self) -> bool:
        d = self._d
        if d._admission.busy():
            return True
        disp = d._coalescer
        return disp is not None and bool(disp.busy())

    # thread-role: speculate
    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._dq and not self._stop_flag:
                    self._cv.wait()
                if self._stop_flag:
                    return
                key, tries = self._dq.popleft()
                self._queued.discard(key)
            # idle gate: real traffic owns the device; speculation only
            # starts once the daemon has nothing better to do
            t0 = time.monotonic()
            deferred = False
            while self._busy():
                if self._stop_flag:
                    return
                if time.monotonic() - t0 > IDLE_WAIT_S:
                    with self._lock:
                        self.deferred += 1
                    deferred = True
                    break
                time.sleep(0.02)
            if deferred:
                continue
            try:
                self._run_one(key, tries)
            except Exception as exc:  # never kill the worker
                with self._lock:
                    self.aborted += 1
                self._d._log(f"serve: speculation failed: {exc!r}")

    def _requeue(self, key: SessionKey, tries: int) -> None:
        if tries >= BUSY_RETRIES:
            return
        time.sleep(BUSY_RETRY_SLEEP_S)
        with self._cv:
            if self._stop_flag or key in self._queued:
                return
            self._queued.add(key)
            self._dq.append((key, tries + 1))
            self._cv.notify_all()

    def _run_one(self, key: SessionKey, tries: int) -> None:
        from kafkabalancer_tpu.serve.daemon import PlanRequest
        from kafkabalancer_tpu.serve.sessions import PlanSessionContext

        d = self._d
        dispatcher = d._coalescer
        if dispatcher is None:
            return
        sess, busy = d.sessions.checkout(key)
        if sess is None:
            if busy:
                # the enqueuing request may still be checking in
                self._requeue(key, tries)
            return
        inf: Optional[_Inflight] = None
        try:
            if (
                sess.released
                or sess.digest is None
                or sess.spec_memo is not None
                or not sess.last_argv
                or not memoizable_argv(sess.last_argv)
            ):
                return
            argv = list(sess.last_argv)
            digest0 = sess.digest
            # mirror the live plan-delta fast path exactly: a settled
            # resident list plans as "delta"; a stale/absent one
            # re-derives from the raw shadow ("rebuild")
            kind = (
                "rebuild"
                if sess.universe_dirty or sess.pl is None
                else "delta"
            )
            ctx = PlanSessionContext(
                kind, sess,
                resident_pl=sess.pl if kind == "delta" else None,
            )
            req = PlanRequest(argv, None, sess.tenant)
            req.internal = "spec"
            req.session_ctx = ctx
            inf = _Inflight(key, digest0, argv)
            self._preempt.clear()
            with self._lock:
                self._inflight = inf
            resp = dispatcher.submit(req)
            if (
                resp is not None
                and bool(resp.get("ok"))
                and resp.get("rc") == 0
                and sess.digest is not None
                and not sess.released
            ):
                self.attach_memo(sess, SpecMemo(
                    digest0, argv, 0,
                    str(resp.get("stdout", "")),
                    str(resp.get("stderr", "")),
                    sess.digest,
                ))
            else:
                # preempted / deferred / crashed: no memo, and a
                # partially-run plan left the prediction poisoned —
                # the next request re-syncs from ground truth
                with self._lock:
                    self.aborted += 1
        finally:
            with self._lock:
                self._inflight = None
            if inf is not None:
                inf.done.set()
            d.sessions.checkin(sess)


# --- the watch-driven continuous controller --------------------------------

_WATCH_DISABLED_KEYS: Tuple[str, ...] = (
    "enabled", "conn", "emit", "ticks", "reads", "errors", "events",
    "resyncs", "plans_emitted", "noop_plans", "spec_hits",
    "last_read_age_s", "last_plan_s", "last_event_lag_s", "state_digest",
)


class ZkWatcher:
    """The ``-watch`` loop; see the module docstring.

    One thread (``serve-watch``) polls Zookeeper every ``poll_s``
    seconds (kazoo watch events wake it early when the client supports
    the ``watcher=`` kwarg), maintains a resident session under tenant
    ``zk:<conn>``, and drives the planning through the ordinary
    dispatcher as INTERNAL requests — consuming the speculator's memo
    whenever the cluster state confirms the daemon's own last emitted
    plan, which is the steady state. Watch ticks never touch the
    daemon's idle clock (the PR-12 hello/scrape rule)."""

    def __init__(
        self,
        daemon: Any,
        conn: str,
        emit: str = "",
        poll_s: float = 5.0,
        argv: Optional[List[str]] = None,
        topics: Optional[List[str]] = None,
    ) -> None:
        from kafkabalancer_tpu.serve.sessions import flags_signature

        self._d = daemon
        self.conn = conn
        self.emit = emit
        self.poll_s = max(0.05, poll_s)
        self.argv = list(argv) if argv else ["-no-daemon=true"]
        self.topics = list(topics or [])
        self.tenant = f"zk:{conn}"
        self._sig = flags_signature(self.argv)
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._client: Optional[Any] = None
        self._lock = threading.Lock()
        self._seq = 0
        self._prev_digest: Optional[str] = None
        self._last_planned_digest: Optional[str] = None
        self._last_plan_moves: Optional[int] = None
        self._last_read_t: Optional[float] = None
        self.ticks = 0
        self.reads = 0
        self.errors = 0
        self.events = 0
        self.resyncs = 0
        self.plans_emitted = 0
        self.noop_plans = 0
        self.spec_hits = 0
        self.last_plan_s: Optional[float] = None
        self.last_event_lag_s: Optional[float] = None
        self.state_digest: Optional[str] = None

    # -- lifecycle --------------------------------------------------------
    def start(self) -> None:
        if self._thread is not None:
            return
        if self.emit and self.emit != "-":
            os.makedirs(self.emit, exist_ok=True)
        self._thread = threading.Thread(
            target=self._loop, name="serve-watch", daemon=True
        )
        self._thread.start()

    def request_stop(self) -> None:
        self._stop.set()
        self._wake.set()

    def join(self, timeout: float = 15.0) -> None:
        t = self._thread
        if t is not None:
            t.join(timeout)
        self._close_client()

    @staticmethod
    def disabled_stats(conn: str = "") -> Dict[str, Any]:
        """The ``watch`` scrape block with the mode off — same key set
        as a live watcher's, so the schema never shifts."""
        out: Dict[str, Any] = {k: 0 for k in _WATCH_DISABLED_KEYS}
        out.update({
            "enabled": False, "conn": conn or None, "emit": None,
            "last_read_age_s": None, "last_plan_s": None,
            "last_event_lag_s": None, "state_digest": None,
        })
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            age = (
                round(time.monotonic() - self._last_read_t, 3)
                if self._last_read_t is not None else None
            )
            return {
                "enabled": True,
                "conn": self.conn,
                "emit": self.emit or None,
                "ticks": self.ticks,
                "reads": self.reads,
                "errors": self.errors,
                "events": self.events,
                "resyncs": self.resyncs,
                "plans_emitted": self.plans_emitted,
                "noop_plans": self.noop_plans,
                "spec_hits": self.spec_hits,
                "last_read_age_s": age,
                "last_plan_s": self.last_plan_s,
                "last_event_lag_s": self.last_event_lag_s,
                "state_digest": self.state_digest,
            }

    # -- zookeeper --------------------------------------------------------
    def _on_zk_event(self, *_a: Any, **_kw: Any) -> None:
        """kazoo watch callback: wake the loop early (the poll interval
        stays as the fallback for clients without watch support)."""
        self._wake.set()

    def _close_client(self) -> None:
        zk = self._client
        self._client = None
        if zk is None:
            return
        try:
            zk.stop()
            zk.close()
        except Exception:
            pass

    def _read_state(self) -> Any:
        from kafkabalancer_tpu.codecs import zookeeper as zkmod

        if self._client is None:
            self._client = zkmod.make_zk_client(self.conn)
        return zkmod.read_cluster(
            self._client, self.topics, watcher=self._on_zk_event
        )

    # -- the loop ---------------------------------------------------------
    # thread-role: watch
    def _loop(self) -> None:
        d = self._d
        d._dispatcher_ready.wait(600.0)
        while not self._stop.is_set():
            self._wake.wait(self.poll_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            with self._lock:
                self.ticks += 1
            try:
                self._tick()
            except Exception as exc:
                with self._lock:
                    self.errors += 1
                self._close_client()
                d._log(f"serve: watch tick failed: {exc!r}")

    def _tick(self) -> None:
        from kafkabalancer_tpu.serve import state as sstate
        from kafkabalancer_tpu.serve.sessions import session_from_rows

        d = self._d
        t_read0 = time.perf_counter()
        try:
            pl = self._read_state()
        except Exception as exc:
            with self._lock:
                self.errors += 1
            self._close_client()
            d._log(f"serve: watch read failed: {exc}")
            return
        parts = list(pl.iter_partitions())
        fields = [sstate.partition_fields(p) for p in parts]
        canon = [sstate.canonical_row_bytes(*f) for f in fields]
        digest = sstate.rows_digest(pl.version, canon)
        with self._lock:
            self.reads += 1
            self._last_read_t = time.monotonic()
            self.state_digest = digest
            if self._prev_digest is not None and digest != self._prev_digest:
                self.events += 1
            self._prev_digest = digest

        key = (self.tenant, self._sig)
        spec = d.speculator
        sess, busy = d.sessions.checkout(key)
        if sess is None and busy and spec is not None:
            # speculation holds the watch session: its in-flight run is
            # (in the steady state) exactly this tick's answer
            spec.wait_for_key(key, digest, self.argv, 120.0)
            sess, busy = d.sessions.checkout(key)
        if sess is None and busy:
            return  # claimed elsewhere; next tick retries
        adopted = False
        if sess is None:
            sess = session_from_rows(
                self.tenant, self._sig, pl.version, fields
            )
            sess.lock.acquire()
            sess.in_use = True
            if not d.sessions.adopt(key, sess):
                sess.in_use = False
                sess.lock.release()
                return
            adopted = True
        try:
            memo = getattr(sess, "spec_memo", None)
            memo_hit = (
                memo is not None
                and memo.key_digest == digest
                and memo.argv == self.argv
            )
            if memo_hit:
                # the cluster just confirmed the very state the
                # speculative memo answers for (the session itself has
                # already advanced past it) — the steady state:
                # _plan_and_emit below serves the memo, zero dispatch
                pass
            elif sess.digest != digest:
                if digest == self._last_planned_digest:
                    # our last emitted plan has not been applied yet:
                    # the state is the one we already planned from —
                    # re-emitting would duplicate the plan
                    return
                # external drift (or a poisoned prediction): re-adopt
                # the freshly read state as ground truth; the settled
                # list is force-rebuilt from raw on the next plan
                if spec is not None:
                    spec.poison_session(sess)
                sess.snapshot_from(pl)
                sess.pl = None
                with self._lock:
                    self.resyncs += 1
            elif (
                not adopted
                and digest == self._last_planned_digest
                and (self._last_plan_moves or 0) == 0
            ):
                return  # converged and unchanged: nothing to do
            self._plan_and_emit(sess, key, digest, t_read0)
        finally:
            d.sessions.checkin(sess)

    def _plan_and_emit(
        self, sess: Any, key: SessionKey, digest: str, t_read0: float
    ) -> None:
        from kafkabalancer_tpu.serve.daemon import PlanRequest
        from kafkabalancer_tpu.serve.sessions import PlanSessionContext

        d = self._d
        spec = d.speculator
        t0 = time.perf_counter()
        stdout: Optional[str] = None
        used_memo = False
        memo = getattr(sess, "spec_memo", None)
        if memo is not None and spec is not None:
            if (
                memo.key_digest == digest
                and memo.argv == self.argv
                and spec.take_memo(sess, memo)
            ):
                obs.metrics.tenant_count("serve.spec.hits", self.tenant)
                stdout = memo.stdout
                used_memo = True
                with self._lock:
                    self.spec_hits += 1
            else:
                spec.retire_miss(sess, memo)
        if stdout is None:
            kind = (
                "rebuild"
                if sess.universe_dirty or sess.pl is None
                else "delta"
            )
            ctx = PlanSessionContext(
                kind, sess,
                resident_pl=sess.pl if kind == "delta" else None,
            )
            req = PlanRequest(self.argv, None, self.tenant)
            req.internal = "watch"
            req.session_ctx = ctx
            sess.last_argv = list(self.argv)
            dispatcher = d._coalescer
            if dispatcher is None:
                return
            resp = dispatcher.submit(req)
            if resp is None or not resp.get("ok") or resp.get("rc") != 0:
                with self._lock:
                    self.errors += 1
                d._log(
                    "serve: watch plan failed: "
                    f"{(resp or {}).get('error', (resp or {}).get('rc'))}"
                )
                return
            stdout = str(resp.get("stdout", ""))
        moves = self._count_moves(stdout)
        wall = time.perf_counter() - t0
        self._last_planned_digest = digest
        self._last_plan_moves = moves
        with self._lock:
            self.last_plan_s = round(wall, 6)
        if moves > 0:
            self._emit_plan(stdout, digest, moves, used_memo)
            with self._lock:
                self.plans_emitted += 1
                self.last_event_lag_s = round(
                    time.perf_counter() - t_read0, 6
                )
        else:
            with self._lock:
                self.noop_plans += 1
        if spec is not None:
            spec.enqueue(key)

    @staticmethod
    def _count_moves(stdout: str) -> int:
        try:
            doc = json.loads(stdout)
        except ValueError:
            return 0
        parts = doc.get("partitions") if isinstance(doc, dict) else None
        return len(parts) if isinstance(parts, list) else 0

    def _emit_plan(
        self, stdout: str, digest: str, moves: int, spec_hit: bool
    ) -> None:
        with self._lock:
            self._seq += 1
            seq = self._seq
        if self.emit == "-":
            sys.stdout.write(stdout)
            sys.stdout.flush()
            return
        if not self.emit:
            return
        # the .meta sidecar publishes FIRST: consumers key on the plan
        # file appearing and immediately read its sidecar — the reverse
        # order would open a window where the plan exists meta-less
        meta = {
            "seq": seq,
            "digest": digest,
            "moves": moves,
            "spec_hit": spec_hit,
            "ts_epoch": round(time.time(), 3),
        }
        mpath = os.path.join(self.emit, f"plan-{seq:06d}.meta")
        mtmp = mpath + ".tmp"
        with open(mtmp, "w") as f:
            json.dump(meta, f, sort_keys=True)
        os.replace(mtmp, mpath)
        path = os.path.join(self.emit, f"plan-{seq:06d}.json")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write(stdout)
        os.replace(tmp, path)
