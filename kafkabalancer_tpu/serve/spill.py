"""The warm session tier: durable, bounded spill of resident sessions.

The hot tier (serve/sessions.py ``SessionStore``) is LRU-capped at a
few dozen residents; before this module, eviction, idle expiry, and any
daemon restart simply discarded a tenant's session — the whole fleet
then re-paid the cold path (full cluster transfer + parse + tensorize)
exactly when the daemon was most fragile. The warm tier is vLLM's
paging argument (PAPERS.md) applied to session state: a demoted session
spills to disk as one versioned, checksummed record
(serve/state.py ``pack_spill_record``), and a later ``plan-delta``
whose digest matches the spilled state restores it WITHOUT the client
re-sending the cluster.

Durability model (docs/serving.md § Session durability):

- **continuous spill** — after every clean session request the daemon
  re-spills the session (skipped when the digest has not moved since
  the last write), so a SIGKILL loses at most the in-flight request;
- **shutdown flush** — idle timeout, SIGTERM and the ``shutdown`` op
  flush every idle resident before exit;
- **crash-safe writes** — records are written tmp + rename (atomic on
  POSIX), and the reader validates magic/format/platform/checksum
  before trusting a byte: a torn, truncated, bit-flipped or foreign
  record is PRUNED and counted (``corrupt_drops``), never restored —
  the PR-12 "never a wrong plan" invariant extended to disk;
- **single writer** — the spill directory carries a pidfile; a second
  live daemon is refused at startup (the PR-12 socket-takeover rules),
  while a dead owner's records are ADOPTED (that is the SIGKILL
  recovery) and its ``*.tmp`` write orphans swept.

Accounting is conservation-exact, scraped as the ``paging`` block of
``serve-stats/8``::

    spills + adopted == restores + corrupt_drops + evictions
                        + warm_entries

Every record that ever entered the warm tier (written this lifetime,
or adopted from a dead daemon at startup) is either still resident
(``warm_entries``), restored to hot, dropped as corrupt, or evicted
(LRU byte-budget sweep, replaced by a newer spill of the same session,
or released with its tenant). ``write_failures`` counts spill attempts
that never produced a record and sits outside the identity.

Nothing here imports jax or numpy; the fault seam (serve/faults.py
``spill_write_fail`` / ``spill_corrupt`` / ``restore_delay``) is inert
unless the daemon armed it.
"""

from __future__ import annotations

import hashlib
import os
import tempfile
import threading
from typing import Any, Dict, List, Optional, Tuple

from kafkabalancer_tpu.serve import faults
from kafkabalancer_tpu.serve import state as sstate

SessionKey = Tuple[str, str]

SPILL_SUFFIX = ".kbsp"
PIDFILE_NAME = "_spill.pid"
DEFAULT_WARM_CAP_MB = 256.0


def pid_alive(pid: int) -> bool:
    """Is ``pid`` a live process? (signal 0 probe; a process we may
    not signal still counts as alive). A ZOMBIE is dead for our
    purposes — a SIGKILL'd daemon whose parent never reaped it
    (containers without an init reaper) still answers the signal
    probe but cannot own a socket or a spill dir, and must not block
    a restart."""
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
    try:
        with open(f"/proc/{pid}/stat") as f:
            # field 3, after the parenthesized comm (which may itself
            # contain spaces/parens): parse from the LAST ')'
            state = f.read().rsplit(")", 1)[1].split()[0]
        return state != "Z"
    except (OSError, IndexError):
        return True  # no procfs: the signal probe's verdict stands


def pid_looks_like_daemon(pid: int) -> bool:
    """Does ``pid``'s command line look like one of OUR daemons?
    Guards the takeover refusal against PID RECYCLING: a SIGKILL'd
    daemon's recorded pid can be reborn as an unrelated process, and
    refusing forever over a stranger would re-create the
    manual-cleanup failure mode this preflight exists to remove.
    Unreadable cmdline (no procfs, permissions) says True — refusing
    when unsure beats hijacking a live daemon."""
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            cmd = f.read()
    except OSError:
        return True
    return b"kafkabalancer" in cmd or b"-serve" in cmd


def record_name(key: SessionKey) -> str:
    """The record filename for one ``(tenant, flags-signature)`` —
    a content hash, so arbitrary tenant strings (paths, unicode)
    cannot escape the directory or collide on case-folding."""
    h = hashlib.sha256()
    tenant, sig = key
    t = tenant.encode("utf-8")
    h.update(len(t).to_bytes(4, "big"))
    h.update(t)
    h.update(sig.encode("utf-8"))
    return h.hexdigest() + SPILL_SUFFIX


class SpillStore:
    """The on-disk warm tier: one record per spilled session, an
    in-memory index for byte accounting, and the conservation-exact
    counter set the ``paging`` scrape block reports. Thread-safe; the
    file I/O itself runs outside any lock the dispatcher holds."""

    def __init__(
        self,
        directory: str,
        cap_mb: float = DEFAULT_WARM_CAP_MB,
        log: Optional[Any] = None,
    ) -> None:
        self.dir = directory
        self.cap_bytes = max(0, int(cap_mb * (1 << 20)))
        self._log = log or (lambda _m: None)
        self._lock = threading.Lock()
        # key -> {"bytes": int, "tenant": str, "seq": int} — seq is a
        # monotone touch counter (the LRU axis; mtime granularity is
        # too coarse for sub-second churn)
        self._index: Dict[SessionKey, Dict[str, Any]] = {}
        # running byte total of the index — the cap sweep and stats
        # must not re-sum a 10^5-entry index on every request's
        # continuous spill
        self._warm_bytes = 0
        self._seq = 0
        # the digest last written per key: the continuous spill skips
        # a re-write when the session state has not moved — and a
        # chaos-corrupted record is not silently healed by the next
        # no-op spill of the same digest
        self._last_digest: Dict[SessionKey, str] = {}
        # records popped from the index whose restore is still in
        # flight (disk read + validation): counted as resident by
        # stats() so the conservation identity holds at EVERY instant
        # a scrape can observe, not just between requests
        self._loading = 0
        self.spills = 0
        self.adopted = 0
        self.restores = 0
        self.restore_hits = 0
        self.corrupt_drops = 0
        self.evictions = 0
        self.write_failures = 0

    # -- lifecycle -------------------------------------------------------
    def _pidfile(self) -> str:
        return os.path.join(self.dir, PIDFILE_NAME)

    def open(self) -> Optional[str]:
        """Claim the spill directory: None on success (records from a
        dead previous owner adopted, ``*.tmp`` write orphans swept),
        an error string when a LIVE daemon already owns it — two
        writers would corrupt each other's warm tier, so the refusal
        mirrors the PR-12 socket-takeover rules exactly."""
        try:
            os.makedirs(self.dir, exist_ok=True)
        except OSError as exc:
            return f"cannot create spill dir {self.dir}: {exc}"
        owner: Optional[int] = None
        try:
            with open(self._pidfile()) as f:
                owner = int(f.read().strip())
        except (OSError, ValueError):
            owner = None
        if (
            owner is not None
            and owner != os.getpid()
            and pid_alive(owner)
            and pid_looks_like_daemon(owner)
        ):
            return (
                f"spill dir {self.dir} is owned by live daemon pid "
                f"{owner}; refusing to share it (kill the process or "
                f"remove {self._pidfile()} first)"
            )
        try:
            with open(self._pidfile(), "w") as f:
                f.write(f"{os.getpid()}\n")
        except OSError as exc:
            return f"cannot write spill pidfile in {self.dir}: {exc}"
        swept = 0
        adopted = 0
        pruned = 0
        try:
            names = sorted(os.listdir(self.dir))
        except OSError as exc:
            return f"cannot list spill dir {self.dir}: {exc}"
        for name in names:
            path = os.path.join(self.dir, name)
            if name.endswith(".tmp"):
                # a write the dead owner never completed: the rename
                # never happened, so no reader can have trusted it
                try:
                    os.unlink(path)
                    swept += 1
                except OSError:
                    pass
                continue
            if not name.endswith(SPILL_SUFFIX):
                continue
            # index by header only (tenant + size); the checksum pass
            # runs at restore time — an adopted record that later
            # fails validation is counted corrupt_drops THERE, keeping
            # the conservation identity exact either way
            try:
                size = os.path.getsize(path)
                with open(path, "rb") as f:
                    head = f.read(sstate._SPILL_MAX_HEADER)
                hdr = sstate.read_spill_header(head)
                tenant = str(hdr.get("tenant", ""))
                sig = str(hdr.get("sig", ""))
                if record_name((tenant, sig)) != name:
                    raise sstate.SpillCorrupt(
                        "record name does not match its identity"
                    )
            except (OSError, sstate.SpillCorrupt):
                try:
                    os.unlink(path)
                except OSError:
                    pass
                pruned += 1
                continue
            with self._lock:
                self._seq += 1
                self._index[(tenant, sig)] = {
                    "bytes": size, "tenant": tenant, "seq": self._seq,
                }
                self._warm_bytes += size
                self.adopted += 1
                adopted += 1
        if swept or adopted or pruned:
            self._log(
                f"serve: spill dir {self.dir}: adopted {adopted} warm "
                f"record{'s' if adopted != 1 else ''}, swept {swept} "
                f"write orphan{'s' if swept != 1 else ''}, pruned "
                f"{pruned} unreadable"
            )
        self._sweep_to_cap()
        return None

    def close(self) -> None:
        """Release the directory claim (records stay — they ARE the
        durability). Only OUR claim is released: a daemon that lost a
        startup race (both wrote the pidfile, the socket bind decided
        the winner) must not delete the winner's claim and open the
        dir to a third writer."""
        try:
            with open(self._pidfile()) as f:
                if int(f.read().strip()) != os.getpid():
                    return
            os.unlink(self._pidfile())
        except (OSError, ValueError):
            pass

    # -- the write path --------------------------------------------------
    def spill(self, key: SessionKey, sess: Any) -> bool:
        """Write one session's raw rows as a warm record; False when
        the session is unspillable (poisoned prediction, empty) or the
        write failed. An overwrite of an existing key counts the
        replaced record as an eviction, so the conservation identity
        stays exact under the continuous spill."""
        digest = getattr(sess, "digest", None)
        raw = getattr(sess, "raw", None)
        if digest is None or not raw:
            return False  # nothing trustworthy to persist
        if getattr(sess, "released", False):
            # an explicitly released session (SessionStore.release) —
            # an in-flight request's continuous spill must not
            # resurrect state the operator just forgot
            return False
        if self._last_digest.get(key) == digest and key in self._index:
            return True  # state unchanged since the last write
        meta = {
            "tenant": key[0],
            "sig": key[1],
            "digest": digest,
            "version": getattr(sess, "version", 1),
        }
        path = os.path.join(self.dir, record_name(key))
        tmp: Optional[str] = None
        try:
            # chaos seam: a scheduled spill_write_fail dies HERE, like
            # a full disk — the hot session is untouched, the tier
            # just does not grow
            faults.fire("spill_write_fail")
            rows = [sstate.partition_fields(p) for p in raw]
            record = sstate.pack_spill_record(meta, rows)
            if faults.should("spill_corrupt"):
                # chaos seam: flip one payload byte AFTER the checksum
                # was computed — the record lands on disk plausible
                # but invalid, exactly like media corruption
                mid = len(record) // 2
                record = (
                    record[:mid]
                    + bytes([record[mid] ^ 0x40])
                    + record[mid + 1:]
                )
            # a UNIQUE tmp per write: two spills of the same key (a
            # same-tenant burst's second live session) must never
            # share a tmp file, or the rename publishes interleaved
            # bytes; the name still ends ".tmp" so a crash leaves a
            # sweepable orphan
            fd, tmp = tempfile.mkstemp(
                dir=self.dir, prefix=record_name(key) + ".",
                suffix=".tmp",
            )
            with os.fdopen(fd, "w+b") as f:
                f.write(record)
            with self._lock:
                # publish + index as ONE step so the record on disk
                # and its index entry always describe the same bytes
                # (and load()'s locked unlink check stays race-free)
                os.replace(tmp, path)
                tmp = None
                self._seq += 1
                prev = self._index.get(key)
                if prev is not None:
                    # the replaced record left the tier
                    self.evictions += 1
                    self._warm_bytes -= int(prev["bytes"])
                self._index[key] = {
                    "bytes": len(record), "tenant": key[0],
                    "seq": self._seq,
                }
                self._warm_bytes += len(record)
                self._last_digest[key] = digest
                self.spills += 1
        except Exception as exc:
            # a failed spill only ever costs durability, never the
            # answer — disk errors, the armed spill_write_fail fault,
            # AND codec bounds (struct.error on a >u16 field count,
            # encoding errors) all land here as a counted write
            # failure instead of escaping into the request path
            with self._lock:
                self.write_failures += 1
            self._log(f"serve: spill write failed for {key[0]!r}: {exc}")
            if tmp is not None:
                try:
                    os.unlink(tmp)
                except OSError:
                    pass
            return False
        self._sweep_to_cap()
        if getattr(sess, "released", False):
            # the tenant was released while this write was in flight
            # (the entry check above passed before the hot sweep
            # marked the session): unwind the record. Paired with the
            # release op's second warm sweep, every interleaving ends
            # with the forgotten state off disk
            self.release(key[0])
            return False
        return True

    # -- the read path ---------------------------------------------------
    def load(
        self, key: SessionKey
    ) -> Optional[Tuple[Dict[str, Any], List[sstate.RowFields]]]:
        """Consume one warm record: ``(header, rows)`` on a validated
        read, None on absence OR any corruption (the record is pruned
        and counted — a cold miss, never a wrong restore). A restored
        record leaves the tier either way: success re-homes the state
        in the hot store, failure destroys it."""
        with self._lock:
            entry = self._index.pop(key, None)
            self._last_digest.pop(key, None)
            if entry is not None:
                self._warm_bytes -= int(entry["bytes"])
                self._loading += 1
        if entry is None:
            return None
        path = os.path.join(self.dir, record_name(key))
        try:
            # chaos seam: a scheduled restore_delay sleeps HERE — a
            # slow disk on the restore path, observable by the
            # client's progress probes (requests_inflight covers the
            # session op)
            faults.fire("restore_delay")
            with open(path, "rb") as f:
                buf = f.read()
            hdr, rows = sstate.unpack_spill_record(buf)
        except (OSError, sstate.SpillCorrupt) as exc:
            with self._lock:
                self.corrupt_drops += 1
                self._loading -= 1
            self._unlink_unless_reindexed(key, path)
            self._log(
                f"serve: warm record for {key[0]!r} dropped: {exc}"
            )
            return None
        except BaseException:
            # anything else (an unexpectedly raising fault site, a
            # worker shutdown) must not leak the in-flight marker —
            # the identity would be off by one forever
            with self._lock:
                self._loading -= 1
            raise
        self._unlink_unless_reindexed(key, path)
        with self._lock:
            self.restores += 1
            self._loading -= 1
        return hdr, rows

    def _unlink_unless_reindexed(self, key: SessionKey, path: str) -> None:
        """Remove a consumed (or corrupt) record's file — unless a
        concurrent spill re-published the key while the read was in
        flight (the ``restore_delay`` seam widens exactly this
        window), in which case the path now holds THAT record and must
        stay. Runs under the store lock, which also serializes
        spill()'s publish+index step, so the check cannot go stale."""
        with self._lock:
            if key in self._index:
                return
            try:
                os.unlink(path)
            except OSError:
                pass

    def note_restore_hit(self) -> None:
        """The restored session answered a digest-matching request
        directly (no resync, no re-register) — the tier's headline
        acceptance counter."""
        with self._lock:
            self.restore_hits += 1

    # -- eviction / release ----------------------------------------------
    def _sweep_to_cap(self) -> None:
        """LRU-sweep the tier down to the byte budget (oldest touch
        first)."""
        if self.cap_bytes <= 0:
            return
        victims: List[SessionKey] = []
        with self._lock:
            if self._warm_bytes <= self.cap_bytes:
                return
            total = self._warm_bytes
            for key, e in sorted(
                self._index.items(), key=lambda kv: kv[1]["seq"]
            ):
                if total <= self.cap_bytes:
                    break
                victims.append(key)
                total -= int(e["bytes"])
            for key in victims:
                self._warm_bytes -= int(self._index[key]["bytes"])
                del self._index[key]
                self._last_digest.pop(key, None)
                self.evictions += 1
        for key in victims:
            self._unlink_unless_reindexed(
                key, os.path.join(self.dir, record_name(key))
            )
        if victims:
            self._log(
                f"serve: warm tier swept {len(victims)} record"
                f"{'s' if len(victims) != 1 else ''} past the "
                f"{self.cap_bytes} byte cap"
            )

    def release(self, tenant: str) -> int:
        """Drop every warm record of ``tenant`` (the ``release`` op's
        warm half — an explicit forget must cover both tiers)."""
        with self._lock:
            keys = [k for k in self._index if k[0] == tenant]
            for k in keys:
                self._warm_bytes -= int(self._index[k]["bytes"])
                del self._index[k]
                self._last_digest.pop(k, None)
                self.evictions += 1
        for k in keys:
            self._unlink_unless_reindexed(
                k, os.path.join(self.dir, record_name(k))
            )
        return len(keys)

    # -- accounting ------------------------------------------------------
    def stats(self) -> Dict[str, Any]:
        """The scrape's ``paging`` block (serve-stats/8)."""
        with self._lock:
            return {
                "enabled": True,
                "dir": self.dir,
                "cap_bytes": self.cap_bytes,
                "spills": self.spills,
                "adopted": self.adopted,
                "restores": self.restores,
                "restore_hits": self.restore_hits,
                "corrupt_drops": self.corrupt_drops,
                "evictions": self.evictions,
                "write_failures": self.write_failures,
                # an in-flight restore (index entry popped, outcome
                # not yet counted) is still resident for the identity
                "warm_entries": len(self._index) + self._loading,
                "warm_bytes": self._warm_bytes,
            }

    @staticmethod
    def disabled_stats() -> Dict[str, Any]:
        """The same block shape with the tier off — the scrape schema
        must not change key sets with configuration."""
        return {
            "enabled": False, "dir": "", "cap_bytes": 0,
            "spills": 0, "adopted": 0, "restores": 0, "restore_hits": 0,
            "corrupt_drops": 0, "evictions": 0, "write_failures": 0,
            "warm_entries": 0, "warm_bytes": 0,
        }

    def stats_by_tenant(self) -> Dict[str, Dict[str, int]]:
        """Per-tenant warm footprint — the demotion-accounting fix:
        a tenant whose sessions were all demoted to warm still shows
        its byte attribution in the top-tenants table instead of
        silently vanishing."""
        with self._lock:
            out: Dict[str, Dict[str, int]] = {}
            for (tenant, _sig), e in self._index.items():
                rec = out.setdefault(
                    tenant, {"warm_sessions": 0, "warm_bytes": 0}
                )
                rec["warm_sessions"] += 1
                rec["warm_bytes"] += int(e["bytes"])
            return out
