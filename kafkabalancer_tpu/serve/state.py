"""Canonical cluster-state digests and binary row records (protocol v2).

The resident-session machinery (serve/sessions.py, docs/serving.md) needs
BOTH ends of the wire to agree on one question: "is the cluster state the
client just read exactly the state the daemon predicts?" — without the
client shipping the state. The answer is a content digest over the RAW
PARSED rows (pre-settle: exactly what ``codecs.get_partition_list_from_
reader`` produces, before ``fill_defaults`` touches anything), order
sensitive because row order shapes the dense encoding and therefore the
plan.

Three layers, all jax- and numpy-free (the forwarding client imports this
module, and its no-jax/no-numpy pin extends here):

- **canonical row bytes** (:func:`canonical_row_bytes`): one partition's
  digest-relevant fields in a fixed rendering. The ONE definition both
  the client (from its freshly parsed input) and the daemon (from its
  resident raw rows, moves applied) hash — they cannot drift because
  they share this function, and the client parses through the very same
  codecs reader the daemon would use (pinned by tests/test_sessions.py).
- **per-row hashes + state digest** (:func:`row_hash`,
  :func:`rows_digest`): 8-byte blake2b per row, sha256 over the
  concatenation (plus the list version) for the whole state. The row
  hashes double as the resync diff unit: on a digest mismatch the daemon
  ships its row-hash table (``ROW_HASH_BYTES`` per row) and the client
  ships only the rows whose hashes differ.
- **packed row records** (:func:`pack_rows` / :func:`unpack_rows`): the
  pre-encoded binary payload of a ``plan-rows`` resync — struct-packed,
  no JSON escaping, so a one-row drift ships ~tens of bytes instead of a
  re-serialized full cluster.
"""

from __future__ import annotations

import hashlib
import json
import struct
from typing import Dict, List, NamedTuple, Optional, Sequence, Tuple

ROW_HASH_BYTES = 8

# one parsed row's digest-relevant fields, in codecs-reader semantics:
# (topic, partition, replicas, weight, num_replicas, brokers, n_consumers)
RowFields = Tuple[
    str, int, List[int], float, int, Optional[List[int]], int
]


class ClientState(NamedTuple):
    """The client side's view of its input, ready for the session
    exchange: the state digest, the canonical row bytes (per-row
    hashes derive from them lazily — only the rare resync diff needs
    them), the raw row fields (for packing changed rows), and the
    parsed list version."""

    digest: str
    canon: List[bytes]
    rows: List[RowFields]
    version: int


def canonical_row_bytes(
    topic: str,
    partition: int,
    replicas: Sequence[int],
    weight: float,
    num_replicas: int,
    brokers: Optional[Sequence[int]],
    num_consumers: int,
) -> bytes:
    """One row's canonical digest rendering. ``repr`` of a tuple of
    primitives is deterministic across processes (no hash
    randomization applies to ints/floats/str contents)."""
    return repr((
        topic,
        partition,
        tuple(replicas),
        float(weight),
        num_replicas,
        None if brokers is None else tuple(brokers),
        num_consumers,
    )).encode("utf-8")


def row_hash(canonical: bytes) -> bytes:
    return hashlib.blake2b(canonical, digest_size=ROW_HASH_BYTES).digest()


def fields_row_hash(fields: RowFields) -> bytes:
    return row_hash(canonical_row_bytes(*fields))


def partition_fields(p: object) -> RowFields:
    """A ``models.Partition``-shaped object's digest fields. Duck-typed
    (``object``) so this module stays importable without the models
    package loaded — the daemon passes real Partitions."""
    return (
        p.topic,  # type: ignore[attr-defined]
        p.partition,  # type: ignore[attr-defined]
        list(p.replicas),  # type: ignore[attr-defined]
        p.weight,  # type: ignore[attr-defined]
        p.num_replicas,  # type: ignore[attr-defined]
        (
            None
            if p.brokers is None  # type: ignore[attr-defined]
            else list(p.brokers)  # type: ignore[attr-defined]
        ),
        p.num_consumers,  # type: ignore[attr-defined]
    )


def rows_digest(version: int, canon: Sequence[bytes]) -> str:
    """The whole-state digest: list version + every canonical row
    (length-prefixed — no concatenation ambiguity), order sensitive.
    Defined over the canonical BYTES, not per-row hashes, so the
    steady-state client pays one sha256 pass instead of a per-row
    hash."""
    h = hashlib.sha256()
    h.update(f"v{version}:{len(canon)}:".encode("ascii"))
    for b in canon:
        h.update(len(b).to_bytes(4, "big"))
        h.update(b)
    return h.hexdigest()


def hashes_of(canon: Sequence[bytes]) -> List[bytes]:
    """Per-row hashes of canonical row bytes — the resync diff unit,
    computed lazily (only a digest mismatch needs them)."""
    return [row_hash(b) for b in canon]


def pack_hash_table(hashes: Sequence[bytes]) -> bytes:
    """The daemon's resync diff table: row hashes concatenated in row
    order (``ROW_HASH_BYTES`` each)."""
    return b"".join(hashes)


def unpack_hash_table(blob: bytes) -> List[bytes]:
    if len(blob) % ROW_HASH_BYTES:
        raise ValueError(
            f"row-hash table length {len(blob)} is not a multiple of "
            f"{ROW_HASH_BYTES}"
        )
    return [
        blob[i: i + ROW_HASH_BYTES]
        for i in range(0, len(blob), ROW_HASH_BYTES)
    ]


class _BadField(Exception):
    """A field shape the codecs reader would reject (CodecError)."""


def _json_int_list(v: object) -> List[int]:
    """Mirror of ``codecs.readers._require_int_list``: None is an
    empty list, non-int (or bool) members are a parse error."""
    if v is None:
        return []
    if not isinstance(v, list):
        raise _BadField()
    for item in v:
        if isinstance(item, bool) or not isinstance(item, int):
            raise _BadField()
    return list(v)


_ABSENT = object()


def row_fields_from_obj(o: object) -> RowFields:
    """One raw partition dict's digest fields, in codecs-reader
    semantics (absent-vs-null brokers, bool-is-not-int, float
    coercion…). Raises :class:`_BadField` on any shape the reader
    would reject — shared by :func:`_json_state`'s full pass and the
    edge cache's incremental re-parse (serve/edge_cache.py), which
    must agree field for field or the incremental digest would
    drift."""
    if not isinstance(o, dict):
        raise _BadField()
    topic = o.get("topic", "")
    if not isinstance(topic, str):
        raise _BadField()
    partition = o.get("partition", 0)
    if isinstance(partition, bool) or not isinstance(partition, int):
        raise _BadField()
    replicas = _json_int_list(o.get("replicas"))
    w = o.get("weight", _ABSENT)
    if w is _ABSENT:
        weight = 0.0
    elif isinstance(w, bool) or not isinstance(w, (int, float)):
        raise _BadField()
    else:
        weight = float(w)
    nrep = o.get("num_replicas", 0)
    if isinstance(nrep, bool) or not isinstance(nrep, int):
        raise _BadField()
    b = o.get("brokers", _ABSENT)
    brokers = None if b is _ABSENT else _json_int_list(b)
    ncons = o.get("num_consumers", 0)
    if isinstance(ncons, bool) or not isinstance(ncons, int):
        raise _BadField()
    return (topic, partition, replicas, weight, nrep, brokers, ncons)


def _json_state(text: str) -> Optional[ClientState]:
    """The JSON-format canonicalizer, WITHOUT building Partition
    objects: one ``json.loads`` plus a single pass over the raw dicts,
    mirroring ``codecs.readers._partition_from_obj`` field for field
    (absent-vs-null brokers, bool-is-not-int, float coercion…) — the
    equivalence is pinned by tests/test_sessions.py against the real
    reader. At 10k rows this halves the client's digest cost, which is
    the dominant client-side term of the delta fast path. None
    wherever the reader would raise."""
    try:
        obj = json.loads(text)
    except ValueError:
        return None
    if not isinstance(obj, dict):
        return None
    version = obj.get("version", 0)
    if isinstance(version, bool) or not isinstance(version, int):
        return None
    if version != 1:
        return None
    raw = obj.get("partitions")
    if raw is None:
        return None  # empty partition list: reader raises
    if not isinstance(raw, list):
        return None
    rows: List[RowFields] = []
    canon: List[bytes] = []
    try:
        for o in raw:
            fields = row_fields_from_obj(o)
            rows.append(fields)
            canon.append(canonical_row_bytes(*fields))
    except _BadField:
        return None
    if not rows:
        return None  # reader: "empty partition list"
    return ClientState(
        digest=rows_digest(version, canon),
        canon=canon,
        rows=rows,
        version=version,
    )


def client_state(
    text: str, is_json: bool, topics: Optional[List[str]]
) -> Optional[ClientState]:
    """Canonicalize + digest the client's input. JSON takes the fast
    raw-dict path above (the reader ignores the topics filter for
    JSON, and so does it); the describe format goes through the shared
    codecs reader. None when the input does not parse or is otherwise
    unusual — the caller falls back to shipping the full state and the
    daemon surfaces any real input error through the ordinary planning
    path, byte-identical to ``-no-daemon``."""
    if is_json:
        return _json_state(text)
    from kafkabalancer_tpu.codecs.readers import (
        CodecError,
        get_partition_list_from_reader,
    )

    try:
        pl = get_partition_list_from_reader(text, is_json, topics)
    except CodecError:
        return None
    except Exception:
        return None
    rows = []
    canon = []
    for p in pl.iter_partitions():
        fields = partition_fields(p)
        rows.append(fields)
        canon.append(canonical_row_bytes(*fields))
    return ClientState(
        digest=rows_digest(pl.version, canon),
        canon=canon,
        rows=rows,
        version=pl.version,
    )


# --- packed row records ----------------------------------------------------
#
# One record:
#   u32 row_index | u16 topic_len | topic utf-8 | i64 partition
#   | f64 weight | i32 num_replicas | i32 num_consumers
#   | u16 n_replicas | n x i64 replica broker IDs
#   | i32 n_brokers (-1 = None)   | n x i64 allowed broker IDs
#
# Raw struct-packed integers — the daemon reads the replica/broker runs
# straight into its resident rows with zero JSON escaping or per-field
# object decode.

_HEAD = struct.Struct(">IH")
_MID = struct.Struct(">qdiiH")
_NBROKERS = struct.Struct(">i")
_I64 = struct.Struct(">q")


def pack_rows(changed: Sequence[Tuple[int, RowFields]]) -> bytes:
    """Pack ``(row_index, fields)`` records into one resync payload."""
    parts: List[bytes] = []
    for idx, (topic, partition, replicas, weight, nrep, brokers, ncons) in (
        changed
    ):
        t = topic.encode("utf-8")
        parts.append(_HEAD.pack(idx, len(t)))
        parts.append(t)
        parts.append(_MID.pack(
            partition, float(weight), nrep, ncons, len(replicas)
        ))
        for r in replicas:
            parts.append(_I64.pack(r))
        if brokers is None:
            parts.append(_NBROKERS.pack(-1))
        else:
            parts.append(_NBROKERS.pack(len(brokers)))
            for b in brokers:
                parts.append(_I64.pack(b))
    return b"".join(parts)


def unpack_rows(blob: bytes) -> List[Tuple[int, RowFields]]:
    """Inverse of :func:`pack_rows`; raises ``ValueError`` on a
    malformed payload (truncation, absurd lengths)."""
    out: List[Tuple[int, RowFields]] = []
    off = 0
    n = len(blob)
    while off < n:
        if off + _HEAD.size > n:
            raise ValueError("truncated row record header")
        idx, tlen = _HEAD.unpack_from(blob, off)
        off += _HEAD.size
        if off + tlen + _MID.size > n:
            raise ValueError("truncated row record topic/body")
        topic = blob[off: off + tlen].decode("utf-8")
        off += tlen
        partition, weight, nrep, ncons, n_reps = _MID.unpack_from(blob, off)
        off += _MID.size
        if off + n_reps * _I64.size + _NBROKERS.size > n:
            raise ValueError("truncated replica run")
        replicas = [
            _I64.unpack_from(blob, off + i * _I64.size)[0]
            for i in range(n_reps)
        ]
        off += n_reps * _I64.size
        (n_brokers,) = _NBROKERS.unpack_from(blob, off)
        off += _NBROKERS.size
        brokers: Optional[List[int]]
        if n_brokers < 0:
            brokers = None
        else:
            if off + n_brokers * _I64.size > n:
                raise ValueError("truncated broker run")
            brokers = [
                _I64.unpack_from(blob, off + i * _I64.size)[0]
                for i in range(n_brokers)
            ]
            off += n_brokers * _I64.size
        out.append((
            idx, (topic, partition, replicas, weight, nrep, brokers, ncons)
        ))
    return out


# --- spill records (the warm session tier) ---------------------------------
#
# A spilled session is one self-contained, versioned, CHECKSUMMED file:
#
#   magic "KBSP" | u32 format version | u32 header_len | header JSON
#   | u64 blob_len | blob (pack_rows of every raw row, indexes 0..n-1)
#   | 32-byte sha256 over everything before it
#
# The header carries the session identity (tenant, flags signature), the
# predicted state digest, the list version, the row count, and the
# writer's platform fingerprint (byte order + package version). The
# correctness contract is the PR-12 invariant extended to disk: a
# record that is truncated, bit-flipped, format-version-skewed, or
# written by a foreign platform/package NEVER restores — it raises
# :class:`SpillCorrupt` (or fails the header gate) and the caller
# treats it as a clean cold miss. The digest gate in serve/sessions.py
# then guarantees a restored-but-stale record can still never produce
# a wrong plan: a non-matching digest degrades to a row/full resync.

SPILL_MAGIC = b"KBSP"
SPILL_FORMAT_VERSION = 1

_SPILL_HEAD = struct.Struct(">4sII")
_SPILL_BLOB_LEN = struct.Struct(">Q")
_SPILL_SUM_BYTES = 32
# a single record header has no business being megabytes
_SPILL_MAX_HEADER = 1 << 20


class SpillCorrupt(ValueError):
    """A spill record that must not restore: truncated, checksum
    mismatch, bad magic/format version, or malformed row payload."""


def spill_platform() -> str:
    """The writer fingerprint embedded in every record. The row codec
    packs explicit big-endian, so byte order is technically inert —
    but a record written by a different build is a clean cold miss BY
    POLICY (the restore path must never have to reason about foreign
    encodings), so the package version rides along too."""
    import sys

    from kafkabalancer_tpu import __version__

    return f"{sys.byteorder}:{__version__}"


def pack_spill_record(
    meta: Dict[str, object], rows: Sequence[RowFields]
) -> bytes:
    """One session's raw rows as a spill record. ``meta`` is the
    caller's header dict (identity + digest); the row count and
    platform fingerprint are stamped here so they cannot be forgotten."""
    hdr = dict(meta)
    hdr["rows"] = len(rows)
    hdr["platform"] = spill_platform()
    header = json.dumps(hdr, separators=(",", ":")).encode("utf-8")
    blob = pack_rows(list(enumerate(rows)))
    body = b"".join((
        _SPILL_HEAD.pack(SPILL_MAGIC, SPILL_FORMAT_VERSION, len(header)),
        header,
        _SPILL_BLOB_LEN.pack(len(blob)),
        blob,
    ))
    return body + hashlib.sha256(body).digest()


def read_spill_header(buf: bytes) -> Dict[str, object]:
    """Just the header of a spill record (no checksum pass) — the
    warm-tier INDEX scan uses this to attribute records to tenants
    without reading whole payloads. Raises :class:`SpillCorrupt` on
    anything that is not a well-formed record head."""
    if len(buf) < _SPILL_HEAD.size:
        raise SpillCorrupt("truncated spill record head")
    magic, fmt, hlen = _SPILL_HEAD.unpack_from(buf, 0)
    if magic != SPILL_MAGIC:
        raise SpillCorrupt(f"bad spill magic {magic!r}")
    if fmt != SPILL_FORMAT_VERSION:
        raise SpillCorrupt(
            f"spill format version {fmt} (want {SPILL_FORMAT_VERSION})"
        )
    if hlen > _SPILL_MAX_HEADER:
        raise SpillCorrupt(f"spill header length {hlen} is absurd")
    if len(buf) < _SPILL_HEAD.size + hlen:
        raise SpillCorrupt("truncated spill header")
    try:
        hdr = json.loads(
            buf[_SPILL_HEAD.size: _SPILL_HEAD.size + hlen].decode("utf-8")
        )
    except ValueError as exc:
        raise SpillCorrupt(f"spill header is not JSON: {exc}") from None
    if not isinstance(hdr, dict):
        raise SpillCorrupt("spill header is not a JSON object")
    return hdr


def unpack_spill_record(
    buf: bytes,
) -> Tuple[Dict[str, object], List[RowFields]]:
    """The full validated read: header + rows, or :class:`SpillCorrupt`.
    The checksum is verified BEFORE any row decode — a bit-flipped
    payload is rejected wholesale, never partially trusted."""
    hdr = read_spill_header(buf)
    if len(buf) < _SPILL_SUM_BYTES:
        raise SpillCorrupt("truncated spill record (no checksum)")
    body, want = buf[:-_SPILL_SUM_BYTES], buf[-_SPILL_SUM_BYTES:]
    if hashlib.sha256(body).digest() != want:
        raise SpillCorrupt("spill checksum mismatch")
    if hdr.get("platform") != spill_platform():
        raise SpillCorrupt(
            f"foreign-platform spill record ({hdr.get('platform')!r} "
            f"vs {spill_platform()!r})"
        )
    _magic, _fmt, hlen = _SPILL_HEAD.unpack_from(buf, 0)
    off = _SPILL_HEAD.size + hlen
    if off + _SPILL_BLOB_LEN.size > len(body):
        raise SpillCorrupt("truncated spill record (no blob length)")
    (blen,) = _SPILL_BLOB_LEN.unpack_from(buf, off)
    off += _SPILL_BLOB_LEN.size
    if off + blen != len(body):
        raise SpillCorrupt(
            f"spill blob length {blen} disagrees with record size"
        )
    try:
        packed = unpack_rows(buf[off: off + blen])
    except ValueError as exc:
        raise SpillCorrupt(f"spill row payload: {exc}") from None
    n = hdr.get("rows")
    if not isinstance(n, int) or n != len(packed):
        raise SpillCorrupt(
            f"spill row count {len(packed)} != header {n!r}"
        )
    rows: List[Optional[RowFields]] = [None] * n
    for idx, fields in packed:
        if idx >= n or rows[idx] is not None:
            raise SpillCorrupt(f"spill row index {idx} out of order")
        rows[idx] = fields
    if any(r is None for r in rows):
        raise SpillCorrupt("spill row indexes are not contiguous")
    return hdr, rows  # type: ignore[return-value]


def diff_rows(
    mine: Sequence[bytes], theirs: Sequence[bytes]
) -> Optional[List[int]]:
    """Row indices where ``mine`` (the client's hashes) differ from
    ``theirs`` (the daemon's table), or None when the shapes are
    incompatible (row count changed — structural drift takes the full
    re-sync path)."""
    if len(mine) != len(theirs):
        return None
    return [i for i, (a, b) in enumerate(zip(mine, theirs)) if a != b]
