"""Optimization backends.

- :mod:`kafkabalancer_tpu.solvers.tpu` — vectorized single-move search: all
  ``(partition, replica, target)`` candidates scored in one fused XLA pass
  (replaces the reference's O(P·R·B²) scalar scan, steps.go:145-232).
- :mod:`kafkabalancer_tpu.solvers.scan` — multi-move sessions fused
  on-device with ``lax.while_loop`` (replaces the host-side
  ``-max-reassign`` outer loop, kafkabalancer.go:177-221).
- :mod:`kafkabalancer_tpu.solvers.beam` — receding-horizon N-way beam
  search over move sequences with the same-topic anti-colocation
  objective (the upstream's planned-but-never-built feature,
  README.md:94-100); ``-solver=beam`` with ``-beam-width``/``-beam-depth``
  /``-beam-siblings``/``-anti-colocation`` knobs.
- :mod:`kafkabalancer_tpu.solvers.leader` — the fused ``-rebalance-leader``
  Balance loop (leader redistribution interleaved with greedy moves,
  steps.go:234-282 precedence).
- :mod:`kafkabalancer_tpu.solvers.pallas_session` — the whole-session TPU
  kernel behind ``-fused-engine=pallas``.
- :mod:`kafkabalancer_tpu.solvers.polish` — fused pair-swap polish
  (compound exchanges past the single-move local optimum).
"""
