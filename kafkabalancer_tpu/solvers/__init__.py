"""Optimization backends.

- :mod:`kafkabalancer_tpu.solvers.tpu` — vectorized single-move search: all
  ``(partition, replica, target)`` candidates scored in one fused XLA pass
  (replaces the reference's O(P·R·B²) scalar scan, steps.go:145-232).
- :mod:`kafkabalancer_tpu.solvers.scan` — multi-move sessions fused
  on-device with ``lax.while_loop`` (replaces the host-side
  ``-max-reassign`` outer loop, kafkabalancer.go:177-221).
- :mod:`kafkabalancer_tpu.solvers.beam` (planned, not yet shipped) — N-way
  beam search over move sequences (the upstream's planned-but-never-built
  feature, README.md:94-100). Until it lands, ``-solver=beam`` runs the
  tpu backend.
"""
