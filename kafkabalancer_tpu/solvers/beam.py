"""Beam search over move sequences.

The upstream reference lists "N-way swaps" and a same-topic anti-colocation
objective as planned-but-never-built features (README.md:94-100). This
module ships both, TPU-style: a width-W beam explores D-move lookahead
sequences entirely on device, so compound rebalances a single greedy move
cannot see — e.g. an uphill move that unlocks a large improvement, or a
2-way swap expressed as two moves — are found and applied atomically.

Search semantics:

- the objective is the reference unbalance (utils.go:119-147) plus, when
  ``cfg.anti_colocation > 0``, λ·Σ_{topic,broker} max(0, c−1) where c
  counts same-topic replicas sharing a broker;
- each depth expands every live beam via the shared factorized per-target
  scorer (ops/cost.py factored_target_best) — top-W of the W·B frontier
  survive. Sequences may include uphill moves; acceptance is sequence-level: the
  best state seen at any depth must beat the start by ``min_unbalance``
  (the per-move threshold semantics of the greedy/tpu solvers do not apply
  — beam is an extension, not a parity path);
- leader moves are candidates whenever ``allow_leader_rebalancing`` is set
  (no leader-first precedence inside a sequence) and are scored with their
  TRUE applied delta ``w·(replicas+consumers)`` like the batched session
  (solvers/scan.py) — the reference's plain-weight under-modelling would
  mis-rank whole sequences;
- each beam contributes its best candidate per TARGET broker (factorized
  rank-1 scoring), and the top-W of the W×B frontier survive;
- two beams can reach the same state by permuted move orders; such
  duplicates waste beam slots but are otherwise harmless.

``beam_plan`` repeats search→apply rounds (receding horizon) until no
sequence improves or the reassignment budget runs out.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, List, Optional, Tuple

from kafkabalancer_tpu.models import PartitionList, RebalanceConfig
from kafkabalancer_tpu.models.config import default_dtype
from kafkabalancer_tpu.models.partition import empty_partition_list
from kafkabalancer_tpu.ops.runtime import ensure_x64, next_bucket

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.ops import cost, tensorize  # noqa: E402
from kafkabalancer_tpu.solvers.scan import (  # noqa: E402
    _cfg_broker_mask,
    _settle_head,
)


def _scan_factory(
    allowed: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    topic_id: jax.Array,
    min_replicas: jax.Array,
    lam: Any,
    dtype: Any,
    P: int,
    R: int,
    B: int,
    *, width: int, depth: int, allow_leader: bool, n_topics: int,
    siblings: bool = False,
) -> Callable[..., Tuple[jax.Array, ...]]:
    """Build the depth-scan ``run(loads, replicas, member, depth_cap)``
    shared by :func:`beam_search` (one search) and :func:`beam_session`
    (the device-fused receding-horizon loop).

    ``depth_cap`` (traced) limits which depths may win the best-so-far
    tracking, so a caller with a small remaining move budget never adopts a
    sequence longer than it can afford. ``run`` returns ``(su0, best_u,
    best_beam, best_depth, parents [D, W], move_p/slot/tgt [D, W],
    best_loads [B], best_replicas [P, R], best_member [P, B])`` — the
    snapshots are the winning beam's state at its winning depth.
    """
    W, D = width, depth

    def state_cost(
        loads: jax.Array, bcount: jax.Array, colo: jax.Array
    ) -> jax.Array:
        """True objective from the INCREMENTAL beam state: broker validity
        via the per-broker replica counts (no [P, B] reduction) and the
        colocation total as the tracked scalar (no [T, B] reduction)."""
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        u = cost.unbalance(
            loads, bvalid, jnp.sum(bvalid, dtype=jnp.int32).astype(dtype)
        )
        if n_topics:
            u = u + colo
        return u

    def expand(
        loads: jax.Array,
        replicas: jax.Array,
        member: jax.Array,
        counts: Optional[jax.Array],
        bcount: jax.Array,
        colo: jax.Array,
        alive: jax.Array,
        last_p: jax.Array,
        last_t: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Per-TARGET best candidate of one beam via the shared factorized
        scorer (ops/cost.py factored_target_best); the frontier takes the
        top-W of the W×B per-target bests. Restricting to one candidate per
        target per beam loses same-target siblings, but those collide
        immediately at later depths anyway; the global best candidate is
        always included. ``vals`` are ABSOLUTE objective values including
        the beam's accumulated colocation cost, so cross-beam frontier
        ranking is unbiased.

        ``(last_p, last_t)`` bar the beam's own IMMEDIATE RE-MOVE: the
        replica the previous depth just placed on broker ``last_t`` may
        not be a source this depth (exclude_src in the scorer). On
        uphill plateaus the reversal is otherwise every beam's
        best-scoring child (it returns to the sequence's start value,
        beating any true continuation), so the frontier floods with undo
        moves and the search oscillates without ever completing a
        compound sequence — the rotation-locked workloads
        (utils/synth.py rotation_locked_cluster) made this observable:
        beam found NOTHING unless the width exceeded the number of
        simultaneously-open cycles. Any immediately-consecutive re-move
        of the same replica is dominated by the direct move (one depth
        shorter, same final state, same legality — allowed sets are
        static and only this beam's own move touched the replica), so
        the bar never loses a best sequence; crucially it bars only THAT
        replica, so forced-adjacent sequences that move a partition's
        OTHER replica onto a just-vacated broker stay reachable (r5
        review)."""
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid, dtype=jnp.int32).astype(dtype)

        if n_topics:
            # counts ride as INCREMENTAL beam state (updated per applied
            # move) — rebuilding them here was a [P, B]->[T, B]
            # scatter-add per beam per depth step and dominated beam
            # round cost at 10k x 100 (~1/3 of wall-clock); the colo
            # TOTAL likewise rides as the scalar ``colo``. The scorer
            # derives both colo terms from c_rows with no gathers.
            c_rows = counts[topic_id]  # [P, B]
            colo_now = colo
        else:
            c_rows = None
            colo_now = 0.0

        if siblings:
            # sibling expansion: the SECOND-best candidate per target (the
            # best one's partition excluded) joins the frontier — on
            # plateaus the per-target-best restriction loses compound
            # sequences whose later moves need a different source for the
            # same cold target (VERDICT r1 weak #9). top2 fetches both in
            # one pass (two masked argmins instead of a full re-score —
            # expand dominates beam round cost)
            _su, vals, p, slot, vals2, p2, slot2 = (
                cost.factored_target_best(
                    loads, replicas, allowed, member, bvalid, weights,
                    nrep_cur, nrep_tgt, ncons, pvalid, nb, min_replicas,
                    allow_leader=allow_leader,
                    c_rows=c_rows, lam=lam, top2=True,
                    exclude_src=(last_p, last_t),
                )
            )
            vals = jnp.stack([vals, vals2])  # [C=2, B]
            p = jnp.stack([p, p2])
            slot = jnp.stack([slot, slot2])
        else:
            _su, vals, p, slot = cost.factored_target_best(
                loads, replicas, allowed, member, bvalid, weights, nrep_cur,
                nrep_tgt, ncons, pvalid, nb, min_replicas,
                allow_leader=allow_leader,
                c_rows=c_rows, lam=lam,
                exclude_src=(last_p, last_t),
            )
            vals = vals[None, :]  # [C=1, B]
            p = p[None, :]
            slot = slot[None, :]
        vals = jnp.where(alive, vals + colo_now, jnp.inf)
        return vals, p, slot

    def apply_move_masked(
        loads: jax.Array,
        replicas: jax.Array,
        member: jax.Array,
        counts: Optional[jax.Array],
        bcount: jax.Array,
        colo: jax.Array,
        p: jax.Array,
        slot: jax.Array,
        t: jax.Array,
        ok: jax.Array,
    ) -> Tuple[Any, ...]:
        """Apply one move to one beam, as a NO-OP when ``ok`` is false —
        mask folded into the arithmetic so the whole [W] batch applies as
        one vmapped op (the round-3 version lax.cond-ed per beam inside a
        sequential lax.map, W latency-bound steps per depth)."""
        okf = ok.astype(dtype)
        oki = ok.astype(jnp.int32)
        p = jnp.clip(p, 0)
        s = replicas[p, slot]
        delta = (
            jnp.where(
                slot == 0,
                weights[p] * (nrep_cur[p].astype(dtype) + ncons[p]),
                weights[p],
            )
            * okf
        )
        loads = loads.at[s].add(-delta).at[t].add(delta)
        replicas = replicas.at[p, slot].add(
            ((t - s) * oki).astype(replicas.dtype)
        )
        member = (
            member.at[p, s].set(member[p, s] & ~ok)
            .at[p, t].set(member[p, t] | ok)
        )
        bcount = bcount.at[s].add(-oki).at[t].add(oki)
        if n_topics:
            tid = topic_id[p]
            # colocation delta in O(1): the indicators are exactly the
            # colo_sub/colo_add terms the candidate was scored with
            c_s = counts[tid, s]
            c_t = counts[tid, t]
            colo = colo + lam * okf * (
                (c_t >= 1).astype(dtype) - (c_s >= 2).astype(dtype)
            )
            counts = counts.at[tid, s].add(-okf).at[tid, t].add(okf)
        return loads, replicas, member, counts, bcount, colo

    def run(
        loads: jax.Array,
        replicas: jax.Array,
        member: jax.Array,
        depth_cap: jax.Array,
    ) -> Tuple[jax.Array, ...]:
        # colocation counts and per-broker replica counts build ONCE per
        # search (one scatter / one reduction), then ride as incremental
        # beam state through apply_move_masked
        counts0 = (
            jnp.zeros((n_topics, B), dtype).at[topic_id].add(
                member.astype(dtype)
            )
            if n_topics
            else None
        )
        colo0 = (
            lam * jnp.sum(jnp.maximum(counts0 - 1, 0))
            if n_topics
            else jnp.asarray(0.0, dtype)
        )
        bcount0 = jnp.sum(
            (member & pvalid[:, None]).astype(jnp.int32), axis=0,
            dtype=jnp.int32,
        )
        su0 = state_cost(loads, bcount0, colo0)

        # beam state: [W, ...] with beam 0 = the start, others dead
        loads_b = jnp.broadcast_to(loads, (W, B))
        replicas_b = jnp.broadcast_to(replicas, (W, P, R))
        member_b = jnp.broadcast_to(member, (W, P, B))
        counts_b = (
            jnp.broadcast_to(counts0, (W, n_topics, B)) if n_topics else None
        )
        bcount_b = jnp.broadcast_to(bcount0, (W, B))
        colo_b = jnp.broadcast_to(colo0, (W,))
        alive = jnp.zeros(W, bool).at[0].set(True)

        def depth_step(
            carry: Tuple[Any, ...], _: Any
        ) -> Tuple[Tuple[Any, ...], Any]:
            (loads_b, replicas_b, member_b, counts_b, bcount_b, colo_b,
             alive, last_p, last_t, best) = carry

            # bar each beam's immediate re-move: the replica the previous
            # depth placed on last_t may not be a source this depth (see
            # expand docstring); (-1, -1) bars nothing
            vals, cp, cslot = jax.vmap(expand)(
                loads_b, replicas_b, member_b, counts_b, bcount_b, colo_b,
                alive, last_p, last_t,
            )  # each [W, C, B] (C = 2 with sibling expansion)

            C = vals.shape[1]
            flat_vals = vals.reshape(-1)  # [W*C*B]
            neg, pick = lax.top_k(-flat_vals, W)
            new_u = -neg  # [W]
            parent = (pick // (C * B)).astype(jnp.int32)
            rem = pick % (C * B)
            which = (rem // B).astype(jnp.int32)
            child = rem % B  # the target broker index

            ok = jnp.isfinite(new_u)
            p_sel = jnp.where(ok, cp[parent, which, child], -1)
            slot_sel = jnp.where(ok, cslot[parent, which, child], 0)
            t_sel = jnp.where(ok, child.astype(jnp.int32), 0)

            # gather every surviving frontier state by parent, then apply
            # the chosen move to the whole batch in ONE vmapped masked op.
            # The big boolean member tensor routes through a one-hot
            # matmul (exact for 0/1 payloads): the W-row select hits the
            # MXU at ~2x the throughput of the general gather lowering
            # bf16 is NOT a precision decision: each output element sums
            # exactly one 0/1 payload, exact in any matmul dtype
            sel = jax.nn.one_hot(  # jaxlint: disable=R4 — exact 0/1 select
                parent, W, dtype=jnp.bfloat16
            )  # [W, W]
            member_b = (
                (
                    sel
                    @ member_b.reshape(W, -1).astype(
                        jnp.bfloat16  # jaxlint: disable=R4 — exact 0/1 select
                    )
                )
                > 0.5
            ).reshape(W, P, B)
            loads_b = loads_b[parent]
            replicas_b = replicas_b[parent]
            bcount_b = bcount_b[parent]
            colo_b = colo_b[parent]
            if n_topics:
                counts_b = counts_b[parent]
            # the applied move's (partition, target) — next depth bars
            # re-moving the replica it placed there
            last_p = jnp.where(ok, p_sel, -1)
            last_t = jnp.where(ok, t_sel, -1)
            (loads_b, replicas_b, member_b, counts_b, bcount_b, colo_b) = (
                jax.vmap(apply_move_masked)(
                    loads_b, replicas_b, member_b, counts_b, bcount_b,
                    colo_b, p_sel, slot_sel, t_sel, ok,
                )
            )
            alive = ok
            # re-evaluate the TRUE state cost (candidate scores are
            # incremental estimates; ranking/acceptance must use real
            # post-apply costs or whole sequences can be mis-accepted) —
            # [W, B]-scale work from the incremental state, batched
            su_b = jnp.where(
                ok,
                jax.vmap(state_cost)(loads_b, bcount_b, colo_b),
                jnp.inf,
            )

            (best_u, best_beam, best_depth, d,
             bs_loads, bs_replicas, bs_member) = best
            m = jnp.min(su_b)
            arg = lax.argmin(su_b, 0, jnp.int32)
            # the depth cap keeps sequences within the caller's remaining
            # move budget
            better = (m < best_u) & (d < depth_cap)
            best = (
                jnp.where(better, m, best_u),
                jnp.where(better, arg, best_beam),
                jnp.where(better, d, best_depth),
                d + 1,
                jnp.where(better, loads_b[arg], bs_loads),
                jnp.where(better, replicas_b[arg], bs_replicas),
                jnp.where(better, member_b[arg], bs_member),
            )
            carry = (
                loads_b, replicas_b, member_b, counts_b, bcount_b, colo_b,
                alive, last_p, last_t, best,
            )
            return carry, (parent, p_sel, slot_sel, t_sel)

        best0 = (
            su0, jnp.int32(-1), jnp.int32(-1), jnp.int32(0),
            loads, replicas, member,
        )
        no_last = jnp.full(W, -1, jnp.int32)
        carry0 = (
            loads_b, replicas_b, member_b, counts_b, bcount_b, colo_b,
            alive, no_last, no_last, best0,
        )
        (_, _, _, _, _, _, _, _, _, best), logs = lax.scan(
            depth_step, carry0, None, length=D
        )
        (best_u, best_beam, best_depth, _,
         bs_loads, bs_replicas, bs_member) = best
        parents, mp, mslot, mtgt = logs  # each [D, W]
        return (
            su0, best_u, best_beam, best_depth, parents, mp, mslot, mtgt,
            bs_loads, bs_replicas, bs_member,
        )

    return run


@partial(
    jax.jit,
    static_argnames=("width", "depth", "allow_leader", "n_topics", "siblings"),
)
def beam_search(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    allowed: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    topic_id: jax.Array,
    min_replicas: jax.Array,
    lam: Any,
    *,
    width: int,
    depth: int,
    allow_leader: bool,
    n_topics: int,
    siblings: bool = False,
) -> Tuple[jax.Array, ...]:
    """One beam search from a single start state.

    Returns ``(su0, best_u, best_beam, best_depth, parents [D, W],
    move_p/slot/tgt [D, W])`` — the move logs reconstruct the best sequence
    host-side. Entries for dead/no-op expansions carry ``move_p == -1``.
    """
    P, R = replicas.shape
    B = loads.shape[0]
    run = _scan_factory(
        allowed, weights, nrep_cur, nrep_tgt, ncons, pvalid, always_valid,
        universe_valid, topic_id, min_replicas, lam, loads.dtype, P, R, B,
        width=width, depth=depth, allow_leader=allow_leader,
        n_topics=n_topics, siblings=siblings,
    )
    out = run(loads, replicas, member, jnp.int32(depth))
    return out[:8]

@partial(
    jax.jit,
    static_argnames=(
        "width", "depth", "allow_leader", "n_topics", "max_moves", "siblings",
    ),
)
def beam_session(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    allowed: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    topic_id: jax.Array,
    min_replicas: jax.Array,
    lam: Any,
    min_unbalance: Any,
    budget: jax.Array,
    *,
    width: int,
    depth: int,
    allow_leader: bool,
    n_topics: int,
    max_moves: int,
    siblings: bool = False,
) -> jax.Array:
    """Device-fused receding-horizon beam planning: rounds of depth-``depth``
    beam search, each adopting the winning sequence's state, inside one
    ``while_loop`` — one dispatch for the whole plan (per-search host round
    trips dominate wall-clock on remote-attached TPUs).

    Returns the packed int32 concatenation ``[move_p | move_slot |
    move_tgt | n]`` with the accepted moves logged in order (dense
    indices, -1 past ``n``) — one array, one device->host transfer. The
    depth cap per round is ``min(depth, budget - n)``, so a sequence never
    overruns the budget (a truncated prefix could end on an uphill move).
    """
    P, R = replicas.shape
    B = loads.shape[0]
    ML = max_moves
    run = _scan_factory(
        allowed, weights, nrep_cur, nrep_tgt, ncons, pvalid, always_valid,
        universe_valid, topic_id, min_replicas, lam, loads.dtype, P, R, B,
        width=width, depth=depth, allow_leader=allow_leader,
        n_topics=n_topics, siblings=siblings,
    )

    mp0 = jnp.full(ML, -1, jnp.int32)

    def cond(state: Tuple[jax.Array, ...]) -> jax.Array:
        n, done = state[3], state[4]
        return (~done) & (n < budget)

    def body(state: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        loads, replicas, member, n, _done, mp, mslot, mtgt = state
        depth_cap = jnp.minimum(jnp.int32(depth), budget - n)
        (su0, best_u, best_beam, best_depth, parents, smp, sslot, smtgt,
         bs_loads, bs_replicas, bs_member) = run(
            loads, replicas, member, depth_cap
        )
        accept = (best_u < su0 - min_unbalance) & (best_u < su0)

        # walk the parent chain from best_depth back to 0, writing the
        # accepted prefix into the global logs at positions n..n+best_depth
        def walk(
            k: jax.Array, carry: Tuple[jax.Array, ...]
        ) -> Tuple[jax.Array, ...]:
            beam, mp, mslot, mtgt = carry
            idx = best_depth - k
            valid = accept & (k <= best_depth)
            i = jnp.clip(idx, 0)
            pos = jnp.clip(n + i, 0, ML - 1)
            mp = mp.at[pos].set(jnp.where(valid, smp[i, beam], mp[pos]))
            mslot = mslot.at[pos].set(
                jnp.where(valid, sslot[i, beam], mslot[pos])
            )
            mtgt = mtgt.at[pos].set(jnp.where(valid, smtgt[i, beam], mtgt[pos]))
            beam = jnp.where(valid, parents[i, beam], beam)
            return beam, mp, mslot, mtgt

        _, mp, mslot, mtgt = lax.fori_loop(
            jnp.int32(0), jnp.int32(depth), walk,
            (best_beam, mp, mslot, mtgt),
        )

        loads = jnp.where(accept, bs_loads, loads)
        replicas = jnp.where(accept, bs_replicas, replicas)
        member = jnp.where(accept, bs_member, member)
        n = n + jnp.where(accept, best_depth + 1, 0)
        return loads, replicas, member, n, ~accept, mp, mslot, mtgt

    state = (
        loads, replicas, member, jnp.int32(0), jnp.bool_(False),
        mp0, mp0, mp0,
    )
    loads, replicas, member, n, _done, mp, mslot, mtgt = lax.while_loop(
        cond, body, state
    )
    # one packed int32 output: each separate device->host fetch pays a
    # full relay round trip on a remote-attached TPU (see scan.plan)
    return jnp.concatenate(
        [mp, mslot, mtgt, n.astype(jnp.int32).reshape(1)]
    )


def _reconstruct(
    best_beam: Any,
    best_depth: Any,
    parents: Any,
    mp: Any,
    mslot: Any,
    mtgt: Any,
) -> List[Tuple[int, int, int]]:
    """Walk the parent pointers back to depth 0; returns [(p, slot, t_dense)]
    in application order."""
    seq = []
    beam = int(best_beam)
    for d in range(int(best_depth), -1, -1):
        p = int(mp[d, beam])
        if p >= 0:
            seq.append((p, int(mslot[d, beam]), int(mtgt[d, beam])))
        beam = int(parents[d, beam])
    seq.reverse()
    return seq


def _device_setup(
    pl: PartitionList, cfg: RebalanceConfig, dtype: Any
) -> Tuple[Any, ...]:
    """Shared device-setup for one search/round: dense plan, prepped
    device inputs (one compiled program — see scan._device_prep), dtype,
    colocation config. Keeps beam_move (_search_once) and _beam_round
    from drifting apart."""
    from kafkabalancer_tpu.solvers.scan import _prep_from_dp

    dp = tensorize(pl, cfg)
    if dtype is None:
        dtype = default_dtype()
    _, (loads, w_dev, nc_dev, allowed_dev, _ew) = _prep_from_dp(dp, dtype)
    lam = float(cfg.anti_colocation)
    n_topics = next_bucket(len(dp.topics), 2) if lam > 0 else 0
    return dp, dtype, loads, w_dev, nc_dev, allowed_dev, lam, n_topics


def _search_once(
    pl: PartitionList,
    cfg: RebalanceConfig,
    depth: int,
    dtype: Any = None,
) -> Optional[Tuple[Any, List[Tuple[int, int, int]]]]:
    """One beam search on the live list; returns the accepted move sequence
    as ``[(partition row, slot, target broker id)]`` with its DensePlan, or
    ``None`` when no sequence clears ``min_unbalance``."""
    dp, dtype, loads, w_dev, nc_dev, allowed_dev, lam, n_topics = (
        _device_setup(pl, cfg, dtype)
    )

    su0, best_u, best_beam, best_depth, parents, mp, mslot, mtgt = beam_search(
        loads,
        jnp.asarray(dp.replicas),
        jnp.asarray(dp.member),
        allowed_dev,
        w_dev,
        jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.nrep_tgt),
        nc_dev,
        jnp.asarray(dp.pvalid),
        jnp.asarray(_cfg_broker_mask(dp, cfg)),
        jnp.asarray(dp.bvalid),
        jnp.asarray(dp.topic_id),
        jnp.int32(cfg.min_replicas_for_rebalancing),
        jnp.asarray(lam, dtype),
        width=max(1, int(cfg.beam_width)),
        depth=max(1, depth),
        allow_leader=cfg.allow_leader_rebalancing,
        n_topics=n_topics,
        siblings=bool(getattr(cfg, "beam_siblings", False)),
    )
    su0, best_u = float(su0), float(best_u)
    if not (best_u < su0 - cfg.min_unbalance and best_u < su0):
        return None
    seq = _reconstruct(
        best_beam, best_depth, np.asarray(parents), np.asarray(mp),
        np.asarray(mslot), np.asarray(mtgt),
    )
    return dp, seq


def _auto_chunk(npart: int) -> int:
    """Beam moves per device dispatch, sized to keep one dispatch's
    wall-clock bounded: a beam round's cost scales with the ``[W, P, B]``
    scoring tensor, measured ~3.3 ms/move at 10k partitions (f32, W=8)
    after the gather-free scorer rewrite (round 3's ~20 ms/move budget
    dated from the gather formulation, and a long dispatch crashed the
    remote TPU worker's watchdog at ~85 s). Budgeting ~40M
    partition-moves per dispatch keeps one dispatch near 10-15 s across
    scales while amortizing per-chunk re-tensorize/re-entry."""
    return min(
        4096, max(64, 1 << (40_000_000 // max(npart, 1)).bit_length())
    )


def beam_plan(
    pl: PartitionList, cfg: RebalanceConfig, max_reassign: int,
    dtype: Any = None, chunk_moves: "int | None" = None,
) -> PartitionList:
    """Receding-horizon beam planning, fused on device: rounds of
    ``beam_depth`` lookahead, each adopting the best sequence, inside one
    dispatch (:func:`beam_session`). Output/mutation contract matches
    ``solvers.scan.plan`` (live partitions accumulated in move order).
    Sessions chunk at ``chunk_moves`` per dispatch (default: auto-scaled
    down with instance size, see :func:`_auto_chunk` — a single beam
    dispatch is ~100x more expensive per move than a move-session
    dispatch) and re-enter on the mutated assignment until converged or
    the budget is exhausted."""
    opl = empty_partition_list()
    if max_reassign <= 0:
        return opl
    repaired, budget = _settle_head(pl, cfg, max_reassign)
    opl.append(*repaired)
    if chunk_moves is None:
        chunk_moves = _auto_chunk(len(pl.partitions or []))
    chunk_moves = max(1, min(chunk_moves, 1 << 16))

    depth = max(1, int(cfg.beam_depth))
    # a chunk smaller than the lookahead could never search at full depth
    chunk_moves = max(chunk_moves, depth)

    remaining = budget
    while remaining > 0:
        chunk_cap = min(remaining, chunk_moves)
        n = _beam_round(pl, cfg, opl, chunk_cap, dtype)
        remaining -= n
        # converged ONLY if the session stopped with full lookahead still
        # affordable (n + depth <= chunk_cap): near the chunk boundary
        # beam_session caps depth_cap at the remaining chunk budget, so a
        # stop there may be boundary truncation (an improving sequence
        # longer than the leftover budget exists) — re-enter, don't
        # abandon the remaining global budget
        if n == 0 or n + depth <= chunk_cap:
            break
    return opl


def _beam_round(
    pl: PartitionList,
    cfg: RebalanceConfig,
    opl: PartitionList,
    budget: int,
    dtype: Any,
) -> int:
    """One fused beam dispatch of up to 2^16 moves; applies the moves to the
    live list and appends them to ``opl``; returns the move count."""
    dp, dtype, loads, w_dev, nc_dev, allowed_dev, lam, n_topics = (
        _device_setup(pl, cfg, dtype)
    )
    ML = next_bucket(min(budget, 1 << 16), 64)

    packed = np.asarray(beam_session(
        loads,
        jnp.asarray(dp.replicas),
        jnp.asarray(dp.member),
        allowed_dev,
        w_dev,
        jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.nrep_tgt),
        nc_dev,
        jnp.asarray(dp.pvalid),
        jnp.asarray(_cfg_broker_mask(dp, cfg)),
        jnp.asarray(dp.bvalid),
        jnp.asarray(dp.topic_id),
        jnp.int32(cfg.min_replicas_for_rebalancing),
        jnp.asarray(lam, dtype),
        jnp.asarray(cfg.min_unbalance, dtype),
        jnp.int32(min(budget, ML)),
        width=max(1, int(cfg.beam_width)),
        depth=max(1, int(cfg.beam_depth)),
        allow_leader=cfg.allow_leader_rebalancing,
        n_topics=n_topics,
        max_moves=ML,
        siblings=bool(getattr(cfg, "beam_siblings", False)),
    ))

    from kafkabalancer_tpu.solvers.scan import _decode_packed

    # beam is always an extension trajectory (no batch=1 parity mode), so
    # superseded same-slot writes are always safe to elide
    return _decode_packed(packed, dp, opl, drop_superseded=True)


def beam_move(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Pipeline-step adapter (``-solver=beam``): the first move of the best
    ``beam_depth``-lookahead sequence, emitted like any Move step so the
    CLI loop, complete-partition logic, and logging all apply unchanged.

    The reference loop's invariant — every emitted reassignment improves
    the objective by ``min_unbalance`` on its own (steps.go:227) — is
    preserved: when the best sequence *starts* with an uphill move (legal
    inside ``beam_plan``'s atomically-applied sequences, but not safe to
    emit alone into a budget that may end here), the search retries at
    depth 1, which can only yield an improving move or nothing."""
    from kafkabalancer_tpu.balancer import costmodel
    from kafkabalancer_tpu.balancer.steps import replace_replica
    from kafkabalancer_tpu.obs import convergence

    def _decline() -> None:
        # the stop-reason observable (plan.stop_reason /
        # plan.no_move_reason): beam's search does not expose a
        # below-threshold-vs-balanced split, so the note is the generic
        # "converged" and feasibility is refined lazily by the CLI —
        # without this, a converged beam plan fell through to the
        # budget_exhausted fallback heuristic
        convergence.note_outcome(
            "converged", min_unbalance=cfg.min_unbalance,
            feasible_unknown=True,
        )

    for depth in (int(cfg.beam_depth), 1):
        found = _search_once(pl, cfg, depth=depth)
        if found is None:
            _decline()
            return None
        dp, seq = found
        if not seq:
            _decline()
            return None
        p_row, slot, t_dense = seq[0]
        part = dp.partitions[p_row]
        t_id = int(dp.broker_ids[t_dense])
        if depth == 1:
            break
        # exact host check that the first move improves on its own
        loads = costmodel.get_broker_load(pl)
        for bid in cfg.brokers or []:
            loads.setdefault(bid, 0.0)
        bl = costmodel.get_bl(loads)
        su = costmodel.get_unbalance_bl(bl)
        rank = {bid: i for i, (bid, _) in enumerate(bl)}
        s_id = part.replicas[slot]
        bl[rank[s_id]][1] -= part.weight
        bl[rank[t_id]][1] += part.weight
        if costmodel.get_unbalance_bl(bl) < su - cfg.min_unbalance:
            break
    return replace_replica(part, part.replicas[slot], t_id)
