"""Beam search over move sequences.

The upstream reference lists "N-way swaps" and a same-topic anti-colocation
objective as planned-but-never-built features (README.md:94-100). This
module ships both, TPU-style: a width-W beam explores D-move lookahead
sequences entirely on device, so compound rebalances a single greedy move
cannot see — e.g. an uphill move that unlocks a large improvement, or a
2-way swap expressed as two moves — are found and applied atomically.

Search semantics:

- the objective is the reference unbalance (utils.go:119-147) plus, when
  ``cfg.anti_colocation > 0``, λ·Σ_{topic,broker} max(0, c−1) where c
  counts same-topic replicas sharing a broker;
- each depth expands every live beam's full ``[P, R, B]`` candidate tensor
  (rank-1 updates, ops/cost.py) — top-W of the W·W frontier survive.
  Sequences may include uphill moves; acceptance is sequence-level: the
  best state seen at any depth must beat the start by ``min_unbalance``
  (the per-move threshold semantics of the greedy/tpu solvers do not apply
  — beam is an extension, not a parity path);
- leader moves are candidates whenever ``allow_leader_rebalancing`` is set
  (slot 0 scored like any other movable slot — no leader-first precedence
  inside a sequence); applying a leader move shifts the true premium load
  (utils.go:96-101) while scoring uses the plain weight, exactly like the
  fused session (solvers/scan.py);
- two beams can reach the same state by permuted move orders; such
  duplicates waste beam slots but are otherwise harmless.

``beam_plan`` repeats search→apply rounds (receding horizon) until no
sequence improves or the reassignment budget runs out.
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional

from kafkabalancer_tpu.models import PartitionList, RebalanceConfig
from kafkabalancer_tpu.models.partition import empty_partition_list
from kafkabalancer_tpu.ops.runtime import ensure_x64, next_bucket

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.ops import cost, tensorize  # noqa: E402
from kafkabalancer_tpu.solvers.scan import _settle_head  # noqa: E402


def _colocation_cost(member, topic_id, n_topics, lam):
    """λ·Σ max(0, same-topic replicas per broker − 1)."""
    counts = jnp.zeros((n_topics, member.shape[1]), member.dtype).at[
        topic_id
    ].add(member)
    return lam * jnp.sum(jnp.maximum(counts - 1, 0))


@partial(jax.jit, static_argnames=("width", "depth", "allow_leader", "n_topics"))
def beam_search(
    loads,
    replicas,
    member,
    allowed,
    weights,
    nrep_cur,
    nrep_tgt,
    ncons,
    pvalid,
    always_valid,
    universe_valid,
    topic_id,
    min_replicas,
    lam,
    *,
    width: int,
    depth: int,
    allow_leader: bool,
    n_topics: int,
):
    """One beam search from a single start state.

    Returns ``(su0, best_u, best_depth, parents [D, W], move_p/slot/tgt
    [D, W])`` — the move logs reconstruct the best sequence host-side.
    Entries for dead/no-op expansions carry ``move_p == -1``.
    """
    P, R = replicas.shape
    B = loads.shape[0]
    dtype = loads.dtype
    W, D = width, depth

    slot_iota = jnp.arange(R)[None, :]
    movable = (slot_iota >= 0) if allow_leader else (slot_iota >= 1)

    def state_cost(loads, member):
        observed = jnp.any(member & pvalid[:, None], axis=0)
        bvalid = (always_valid | observed) & universe_valid
        u = cost.unbalance(loads, bvalid, jnp.sum(bvalid).astype(dtype))
        if n_topics:
            u = u + _colocation_cost(
                member.astype(dtype), topic_id, n_topics, lam
            )
        return u

    def expand(args):
        """Top-W candidates of one beam: (vals [W], p/slot/tgt [W])."""
        loads, replicas, member, alive = args
        observed = jnp.any(member & pvalid[:, None], axis=0)
        bvalid = (always_valid | observed) & universe_valid
        nb = jnp.sum(bvalid).astype(dtype)
        _, perm, rank_of = cost.rank_brokers(loads, bvalid)
        u, su = cost.move_candidate_scores(
            loads, replicas, allowed[:, perm], member[:, perm], bvalid,
            bvalid[perm], perm, rank_of, weights, nrep_cur, nrep_tgt,
            pvalid, nb, min_replicas,
        )
        u = jnp.where(movable[:, :, None], u, jnp.inf)
        if n_topics:
            # rank-1 colocation delta: +λ if the target broker already has
            # a same-topic replica, −λ if the source broker has ≥2
            counts = jnp.zeros((n_topics, B), dtype).at[topic_id].add(
                member.astype(dtype)
            )
            c_rows = counts[topic_id]  # [P, B]
            s = jnp.clip(replicas, 0)
            c_src = jnp.take_along_axis(c_rows, s, axis=1)  # [P, R]
            add = jnp.where(c_rows[:, perm] >= 1, lam, 0.0)  # [P, B] rank
            sub = jnp.where(c_src >= 2, lam, 0.0)  # [P, R]
            u = u + add[:, None, :] - sub[:, :, None]
        flat = jnp.where(alive, u, jnp.inf).reshape(-1)
        neg, idx = lax.top_k(-flat, W)
        p, rem = jnp.divmod(idx, R * B)
        slot, t_rank = jnp.divmod(rem, B)
        return -neg, p.astype(jnp.int32), slot.astype(jnp.int32), perm[
            t_rank
        ].astype(jnp.int32)

    def apply_move(loads, replicas, member, p, slot, t):
        s = replicas[p, slot]
        delta = jnp.where(
            slot == 0,
            weights[p] * (nrep_cur[p].astype(dtype) + ncons[p]),
            weights[p],
        )
        loads = loads.at[s].add(-delta).at[t].add(delta)
        replicas = replicas.at[p, slot].set(t.astype(replicas.dtype))
        member = member.at[p, s].set(False).at[p, t].set(True)
        return loads, replicas, member

    su0 = state_cost(loads, member)

    # beam state: [W, ...] with beam 0 = the start, others dead
    loads_b = jnp.broadcast_to(loads, (W, B))
    replicas_b = jnp.broadcast_to(replicas, (W, P, R))
    member_b = jnp.broadcast_to(member, (W, P, B))
    alive = jnp.zeros(W, bool).at[0].set(True)
    su_b = jnp.full(W, jnp.inf, dtype).at[0].set(su0)

    def depth_step(carry, _):
        loads_b, replicas_b, member_b, alive, su_b, best = carry

        vals, cp, cslot, ct = lax.map(
            expand, (loads_b, replicas_b, member_b, alive)
        )  # each [W, W]

        flat_vals = vals.reshape(-1)  # [W*W]
        neg, pick = lax.top_k(-flat_vals, W)
        new_u = -neg  # [W]
        parent = (pick // W).astype(jnp.int32)
        child = pick % W

        ok = jnp.isfinite(new_u)
        p_sel = jnp.where(ok, cp[parent, child], -1)
        slot_sel = jnp.where(ok, cslot[parent, child], 0)
        t_sel = jnp.where(ok, ct[parent, child], 0)

        def build(i):
            pl_, rp_, mb_ = (
                loads_b[parent[i]],
                replicas_b[parent[i]],
                member_b[parent[i]],
            )
            return lax.cond(
                ok[i],
                lambda a: apply_move(*a, p_sel[i], slot_sel[i], t_sel[i]),
                lambda a: a,
                (pl_, rp_, mb_),
            )

        loads_b, replicas_b, member_b = lax.map(build, jnp.arange(W))
        alive = ok
        # re-evaluate the TRUE state cost: candidate scores under-model
        # leader moves (plain weight scored, premium applied — the
        # reference's steps.go:185/:207 quirk), so ranking/acceptance on
        # the claimed values would accept sequences that are really worse
        su_b = jnp.where(
            ok,
            lax.map(lambda i: state_cost(loads_b[i], member_b[i]), jnp.arange(W)),
            jnp.inf,
        )

        best_u, best_beam, best_depth, d = best
        m = jnp.min(su_b)
        better = m < best_u
        best = (
            jnp.where(better, m, best_u),
            jnp.where(better, jnp.argmin(su_b).astype(jnp.int32), best_beam),
            jnp.where(better, d, best_depth),
            d + 1,
        )
        carry = (loads_b, replicas_b, member_b, alive, su_b, best)
        return carry, (parent, p_sel, slot_sel, t_sel)

    best0 = (su0, jnp.int32(-1), jnp.int32(-1), jnp.int32(0))
    carry0 = (loads_b, replicas_b, member_b, alive, su_b, best0)
    (_, _, _, _, _, best), logs = lax.scan(
        depth_step, carry0, None, length=D
    )
    best_u, best_beam, best_depth, _ = best
    parents, mp, mslot, mtgt = logs  # each [D, W]
    return su0, best_u, best_beam, best_depth, parents, mp, mslot, mtgt


def _reconstruct(best_beam, best_depth, parents, mp, mslot, mtgt):
    """Walk the parent pointers back to depth 0; returns [(p, slot, t_dense)]
    in application order."""
    seq = []
    beam = int(best_beam)
    for d in range(int(best_depth), -1, -1):
        p = int(mp[d, beam])
        if p >= 0:
            seq.append((p, int(mslot[d, beam]), int(mtgt[d, beam])))
        beam = int(parents[d, beam])
    seq.reverse()
    return seq


def _search_once(pl: PartitionList, cfg: RebalanceConfig, depth: int,
                 dtype=None):
    """One beam search on the live list; returns the accepted move sequence
    as ``[(partition row, slot, target broker id)]`` with its DensePlan, or
    ``None`` when no sequence clears ``min_unbalance``."""
    dp = tensorize(pl, cfg)
    if dtype is None:
        dtype = jnp.float64 if jax.config.jax_enable_x64 else jnp.float32
    loads = cost.broker_loads(
        jnp.asarray(dp.replicas),
        jnp.asarray(dp.weights, dtype),
        jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.ncons, dtype),
        dp.bvalid.shape[0],
    )
    from kafkabalancer_tpu.solvers.scan import _cfg_broker_mask

    lam = float(cfg.anti_colocation)
    n_topics = next_bucket(len(dp.topics), 2) if lam > 0 else 0

    su0, best_u, best_beam, best_depth, parents, mp, mslot, mtgt = beam_search(
        loads,
        jnp.asarray(dp.replicas),
        jnp.asarray(dp.member),
        jnp.asarray(dp.allowed),
        jnp.asarray(dp.weights, dtype),
        jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.nrep_tgt),
        jnp.asarray(dp.ncons, dtype),
        jnp.asarray(dp.pvalid),
        jnp.asarray(_cfg_broker_mask(dp, cfg)),
        jnp.asarray(dp.bvalid),
        jnp.asarray(dp.topic_id),
        jnp.int32(cfg.min_replicas_for_rebalancing),
        jnp.asarray(lam, dtype),
        width=max(1, int(cfg.beam_width)),
        depth=max(1, depth),
        allow_leader=cfg.allow_leader_rebalancing,
        n_topics=n_topics,
    )
    su0, best_u = float(su0), float(best_u)
    if not (best_u < su0 - cfg.min_unbalance and best_u < su0):
        return None
    seq = _reconstruct(
        best_beam, best_depth, np.asarray(parents), np.asarray(mp),
        np.asarray(mslot), np.asarray(mtgt),
    )
    return dp, seq


def beam_plan(
    pl: PartitionList, cfg: RebalanceConfig, max_reassign: int, dtype=None
) -> PartitionList:
    """Receding-horizon beam planning: search a ``beam_depth`` lookahead,
    apply the best sequence, repeat. Output/mutation contract matches
    ``solvers.scan.plan`` (live partitions accumulated in move order)."""
    opl = empty_partition_list()
    if max_reassign <= 0:
        return opl
    repaired, budget = _settle_head(pl, cfg, max_reassign)
    opl.append(*repaired)

    while budget > 0:
        found = _search_once(pl, cfg, depth=min(int(cfg.beam_depth), budget), dtype=dtype)
        if found is None:
            break
        dp, seq = found
        for p_row, slot, t_dense in seq[:budget]:
            part = dp.partitions[p_row]
            part.replicas[slot] = int(dp.broker_ids[t_dense])
            opl.append(part)
            budget -= 1
    return opl


def beam_move(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Pipeline-step adapter (``-solver=beam``): the first move of the best
    ``beam_depth``-lookahead sequence, emitted like any Move step so the
    CLI loop, complete-partition logic, and logging all apply unchanged.

    The reference loop's invariant — every emitted reassignment improves
    the objective by ``min_unbalance`` on its own (steps.go:227) — is
    preserved: when the best sequence *starts* with an uphill move (legal
    inside ``beam_plan``'s atomically-applied sequences, but not safe to
    emit alone into a budget that may end here), the search retries at
    depth 1, which can only yield an improving move or nothing."""
    from kafkabalancer_tpu.balancer import costmodel
    from kafkabalancer_tpu.balancer.steps import replace_replica

    for depth in (int(cfg.beam_depth), 1):
        found = _search_once(pl, cfg, depth=depth)
        if found is None:
            return None
        dp, seq = found
        if not seq:
            return None
        p_row, slot, t_dense = seq[0]
        part = dp.partitions[p_row]
        t_id = int(dp.broker_ids[t_dense])
        if depth == 1:
            break
        # exact host check that the first move improves on its own
        loads = costmodel.get_broker_load(pl)
        for bid in cfg.brokers or []:
            loads.setdefault(bid, 0.0)
        bl = costmodel.get_bl(loads)
        su = costmodel.get_unbalance_bl(bl)
        rank = {bid: i for i, (bid, _) in enumerate(bl)}
        s_id = part.replicas[slot]
        bl[rank[s_id]][1] -= part.weight
        bl[rank[t_id]][1] += part.weight
        if costmodel.get_unbalance_bl(bl) < su - cfg.min_unbalance:
            break
    return replace_replica(part, part.replicas[slot], t_id)
