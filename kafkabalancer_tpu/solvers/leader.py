"""Fused ``-rebalance-leader`` session: the full Balance loop on device.

With ``rebalance_leaders`` set, every reference ``Balance()`` call tries
``distributeLeaders`` FIRST (steps.go:301-307 -> 234-282) and only falls
through to the Move steps when it does not fire; the CLI loop repeats
this per reassignment (kafkabalancer.go:177-221). Round 1 ran that loop
host-side per move — minutes at 10k-partition scale. This module fuses
the whole loop into one ``lax.while_loop``: each device iteration
replays one ``Balance()`` call with exact step precedence:

1. **distributeLeaders** (steps.go:234-282): gate on TOTAL unbalance >=
   ``min_unbalance`` (steps.go:249-253 — a threshold on the state, not
   on the gain); take the most-loaded broker (ascending (load, ID)
   table, so ties resolve to the highest ID, utils.go:14-28), find the
   first partition IN LIST ORDER it leads with ``num_replicas >=
   min_replicas_for_rebalancing`` (steps.go:258-266), and hand its
   leadership to the least-loaded broker. If the target is already a
   follower the slots are exchanged in place — a leadership transfer
   with no data movement (``replacepl`` swap branch, utils.go:181-188)
   — logged with ``move_slot == -1`` (see :data:`SWAP_SLOT`); otherwise
   slot 0 is overwritten, moving the full leader load
   ``weight * (replicas + consumers)`` (utils.go:96-101).
2. **MoveLeaders / MoveNonLeaders** (steps.go:286-298): when the leader
   step does not fire, one greedy move exactly like
   ``scan.session``'s batch=1 body: leader candidates first when
   ``allow_leader`` (scored with the reference's plain follower weight,
   steps.go:185/:207), follower candidates otherwise; accept iff the
   best improves by more than ``min_unbalance``.

The session ends when neither step fires or the budget is exhausted —
identical to the CLI loop hitting "no candidate changes".

``batch > 1`` enables the convergent batched extension: per device
iteration the K heaviest brokers pair with the K lightest (the same
hot/cold pairing the polish swap phase uses, solvers/polish.py), and
each pair hands over the led partition whose transfer maximizes the
exact pair objective gain. Disjoint broker pairs make the deltas
exactly additive (the objective is a sum of per-broker penalties with a
transfer-invariant average), so a round of K transfers lands precisely
the sum of its scored gains. Two deliberate deviations from the
reference trajectory (which ``batch=1`` replays exactly):

- the transferred partition is chosen by gain, not first-in-list-order
  (steps.go:258-266 is weight-blind, which plateaus at coarse
  granularity and can oscillate);
- only strictly improving transfers fire, so the session terminates at
  ``su < min_unbalance`` (the reference gate, steps.go:249-253) or at
  the improving-action fixed point instead of replaying worsening
  transfers forever.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.ops import cost  # noqa: E402

# move_slot sentinel: leadership handed to a broker already in the replica
# set — decode as an in-place position swap, not a slot overwrite
SWAP_SLOT = -2


@partial(jax.jit, static_argnames=("max_moves", "allow_leader", "batch"))
def leader_session(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    allowed: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: jax.Array,
    budget: jax.Array,
    *,
    max_moves: int,
    allow_leader: bool,
    batch: int = 1,
) -> Tuple[jax.Array, ...]:
    """Fused rebalance-leaders Balance loop (see module docstring).

    Returns ``(replicas, loads, n, move_p, move_slot, move_tgt)``; log
    entries with ``move_slot == SWAP_SLOT`` are leadership swaps toward
    ``move_tgt`` (decode: exchange the positions of ``move_tgt`` and the
    current leader), all others are plain slot overwrites. ``batch=1``
    replays the reference trajectory exactly; ``batch>1`` runs the
    convergent batched extension (module docstring).
    """
    P, R = replicas.shape
    B = loads.shape[0]
    dtype = loads.dtype
    iota_p = jnp.arange(P, dtype=jnp.int32)
    iota_r = jnp.arange(R, dtype=jnp.int32)
    slot_iota = iota_r[None, :]
    K = max(1, min(batch, B))
    batched = batch > 1

    mp0 = jnp.full(max_moves + 1, -1, jnp.int32)

    bcount0 = jnp.sum(
        (member & pvalid[:, None]).astype(jnp.int32), axis=0, dtype=jnp.int32
    )

    def cond(st: Tuple[jax.Array, ...]) -> jax.Array:
        n, done = st[4], st[5]
        return (~done) & (n < budget) & (n < max_moves)

    def body(st: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        loads, replicas, member, bcount, n, _done, mp, mslot, mtgt = st
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid, dtype=jnp.int32)
        nbf = nb.astype(dtype)
        su = cost.unbalance(loads, bvalid, nbf)
        _, perm, rank_of = cost.rank_brokers(loads, bvalid)
        heavy = perm[jnp.clip(nb - 1, 0, B - 1)]
        light = perm[0]

        eligible_p = pvalid & (nrep_tgt >= min_replicas) & (nrep_cur >= 1)
        if batched:
            # pair the K heaviest with the K lightest valid brokers; pick
            # each pair's best-gain led partition; fire improving pairs only
            ii = jnp.arange(K, dtype=jnp.int32)
            hk = perm[jnp.clip(nb - 1 - ii, 0, B - 1)]
            lk = perm[jnp.clip(ii, 0, B - 1)]
            valid_pair = (nb - 1 - ii) > ii
            leaders_of = replicas[:, 0].astype(jnp.int32)
            fullw = weights * (nrep_cur.astype(dtype) + ncons)  # leader load
            extraw = fullw - weights  # premium over a follower
            elig = (leaders_of[None, :] == hk[:, None]) & eligible_p[None, :]
            is_fol = member.T[lk]  # [K, P]: light already a follower -> swap
            delta = jnp.where(is_fol, extraw[None, :], fullw[None, :])
            avg = jnp.sum(jnp.where(bvalid, loads, 0.0)) / nbf
            lh = loads[hk][:, None]
            ll = loads[lk][:, None]
            pen = cost.overload_penalty
            # exact pair gain: transfers conserve total load, so only the
            # two brokers' penalty terms change (avg is invariant)
            gain = (pen(lh, avg) + pen(ll, avg)) - (
                pen(lh - delta, avg) + pen(ll + delta, avg)
            )
            gain = jnp.where(elig, gain, -jnp.inf)
            p_star = lax.argmax(gain, 1, jnp.int32)
            g_star = jnp.max(gain, axis=1)
            fire0 = (
                valid_pair
                & (g_star > 0)
                & (hk != lk)
                & (su >= min_unbalance)
            )
            # replay the reference gate (steps.go:249-253) WITHIN the
            # round: a pair only fires while the objective, net of the
            # exactly-additive gains of the pairs before it, is still >=
            # min_unbalance. The exclusive cumsum over fire0 may overcount
            # gains of pairs this same gate trims, which only blocks
            # conservatively (fewer transfers); pair 0 sees su itself, so
            # rounds always progress.
            g_cum = jnp.cumsum(jnp.where(fire0, g_star, 0.0))
            su_before = su - (g_cum - jnp.where(fire0, g_star, 0.0))
            fire1 = fire0 & (su_before >= min_unbalance)
            cap = jnp.minimum(budget, jnp.int32(max_moves))
            fire = fire1 & (n + jnp.cumsum(fire1.astype(jnp.int32)) <= cap)
            leader_fire = jnp.any(fire)
        else:
            lead_mask = (
                replicas[:, 0].astype(jnp.int32) == heavy
            ) & eligible_p
            leader_fire = (su >= min_unbalance) & jnp.any(lead_mask)

        def _transfer(
            state: Tuple[jax.Array, ...], p: jax.Array,
            light: jax.Array, log_idx: jax.Array,
        ) -> Tuple[jax.Array, ...]:
            """Hand leadership of partition ``p`` to broker ``light`` —
            the shared replacepl analog (utils.go:166-197): swap branch
            when ``light`` is already a follower (positions exchange, only
            the premium moves), set branch otherwise (slot 0 overwritten,
            the full leader load moves, membership updates)."""
            loads, replicas, member, bcount, mp, mslot, mtgt = state
            w = weights[p]
            full = w * (nrep_cur[p].astype(dtype) + ncons[p])  # leader load
            extra = full - w  # leader premium over a follower

            eqj = (replicas[p, :].astype(jnp.int32) == light) & (
                iota_r < nrep_cur[p]
            )
            has = jnp.any(eqj)
            j = lax.argmax(eqj, 0, jnp.int32)

            old_leader = replicas[p, 0].astype(jnp.int32)
            new_row = jnp.where(
                iota_r == 0,
                light,
                jnp.where(has & (iota_r == j), old_leader, replicas[p, :]),
            ).astype(replicas.dtype)
            replicas = replicas.at[p, :].set(new_row)
            delta = jnp.where(has, extra, full)
            loads = loads.at[old_leader].add(-delta).at[light].add(delta)
            member = member.at[p, old_leader].set(
                jnp.where(has, member[p, old_leader], False)
            ).at[p, light].set(True)
            one = jnp.where(has, jnp.int32(0), jnp.int32(1))
            bcount = bcount.at[old_leader].add(-one).at[light].add(one)

            mp = mp.at[log_idx].set(p)
            mslot = mslot.at[log_idx].set(
                jnp.where(has, jnp.int32(SWAP_SLOT), jnp.int32(0))
            )
            mtgt = mtgt.at[log_idx].set(light)
            return loads, replicas, member, bcount, mp, mslot, mtgt

        if batched:

            def leader_branch(
                args: Tuple[jax.Array, ...]
            ) -> Tuple[jax.Array, ...]:
                def apply_k(
                    k: jax.Array, carry: Tuple[jax.Array, ...]
                ) -> Tuple[jax.Array, ...]:
                    state, cnt = carry

                    def do(
                        c: Tuple[jax.Array, ...]
                    ) -> Tuple[jax.Array, ...]:
                        state, cnt = c
                        state = _transfer(state, p_star[k], lk[k], n + cnt)
                        return state, cnt + 1

                    return lax.cond(fire[k], do, lambda c: c, (state, cnt))

                state, cnt = lax.fori_loop(
                    jnp.int32(0), jnp.int32(K), apply_k, (args, jnp.int32(0))
                )
                return (*state, cnt)

        else:

            def leader_branch(
                args: Tuple[jax.Array, ...]
            ) -> Tuple[jax.Array, ...]:
                p = jnp.min(jnp.where(lead_mask, iota_p, P))
                p = jnp.clip(p, 0, P - 1)
                return (*_transfer(args, p, light, n), jnp.int32(1))

        def move_branch(
            args: Tuple[jax.Array, ...]
        ) -> Tuple[jax.Array, ...]:
            loads, replicas, member, bcount, mp, mslot, mtgt = args
            # one greedy move, batch=1 parity semantics (mirror of
            # scan.session's non-batch body; the [P, R, B] scoring core is
            # shared via ops/cost.py)
            u, su2 = cost.move_candidate_scores(
                loads, replicas, allowed[:, perm], member[:, perm], bvalid,
                bvalid[perm], perm, rank_of, weights, nrep_cur, nrep_tgt,
                pvalid, nbf, min_replicas,
            )

            def best(mask_slots: jax.Array) -> Tuple[jax.Array, jax.Array]:
                flat = jnp.where(
                    mask_slots[None, :, None], u, jnp.inf
                ).reshape(-1)
                i = lax.argmin(flat, 0, jnp.int32)
                return flat[i], i

            fol_u, fol_i = best(slot_iota[0] >= 1)
            if allow_leader:
                lead_u, lead_i = best(slot_iota[0] == 0)
                accept_lead = (lead_u < su2 - min_unbalance) & (lead_u < su2)
            else:
                lead_i = jnp.zeros_like(fol_i)
                accept_lead = jnp.bool_(False)
            accept_fol = (fol_u < su2 - min_unbalance) & (fol_u < su2)
            accept = accept_lead | accept_fol
            chosen = jnp.where(accept_lead, lead_i, fol_i)

            p, rem = jnp.divmod(chosen, jnp.int32(R * B))
            slot, t_rank = jnp.divmod(rem, jnp.int32(B))
            t_dense = perm[t_rank]
            s_dense = replicas[p, slot]
            delta = jnp.where(
                slot == 0,
                weights[p] * (nrep_cur[p].astype(dtype) + ncons[p]),
                weights[p],
            )

            def apply(a: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
                loads, replicas, member, bcount, mp, mslot, mtgt = a
                loads = loads.at[s_dense].add(-delta).at[t_dense].add(delta)
                replicas = replicas.at[p, slot].set(
                    t_dense.astype(replicas.dtype)
                )
                member = member.at[p, s_dense].set(False).at[
                    p, t_dense
                ].set(True)
                bcount = bcount.at[s_dense].add(-1).at[t_dense].add(1)
                mp = mp.at[n].set(p.astype(jnp.int32))
                mslot = mslot.at[n].set(slot.astype(jnp.int32))
                mtgt = mtgt.at[n].set(t_dense.astype(jnp.int32))
                return loads, replicas, member, bcount, mp, mslot, mtgt

            loads, replicas, member, bcount, mp, mslot, mtgt = lax.cond(
                accept, apply, lambda a: a,
                (loads, replicas, member, bcount, mp, mslot, mtgt),
            )
            return (
                loads, replicas, member, bcount, mp, mslot, mtgt,
                accept.astype(jnp.int32),
            )

        loads, replicas, member, bcount, mp, mslot, mtgt, fired = lax.cond(
            leader_fire,
            leader_branch,
            move_branch,
            (loads, replicas, member, bcount, mp, mslot, mtgt),
        )
        n = n + fired
        return (
            loads, replicas, member, bcount, n, fired == 0, mp, mslot, mtgt
        )

    st = (
        loads, replicas, member, bcount0, jnp.int32(0), jnp.bool_(False),
        mp0, mp0, mp0,
    )
    loads, replicas, member, _bc, n, _done, mp, mslot, mtgt = lax.while_loop(
        cond, body, st
    )
    return (
        replicas, loads, n,
        mp[:max_moves], mslot[:max_moves], mtgt[:max_moves],
    )
