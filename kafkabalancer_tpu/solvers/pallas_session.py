"""Whole-session Pallas TPU kernel for the batched fused move loop.

The XLA version of the batched session (solvers/scan.py ``body_batch``)
dispatches ~15 small kernels per iteration; on a remote-compiled TPU
backend the per-kernel overhead (~0.1-0.3 ms each) dwarfs the arithmetic,
capping convergence speed. This kernel runs the ENTIRE session — scoring,
disjoint selection, application, move logging, convergence check — as one
``pallas_call``: every state array stays resident in VMEM across all
iterations and the device never returns to the dispatcher until the
session converges or exhausts its budget.

Same algorithm as ``scan.session`` with ``batch > 1`` (the candidate
union — per-TARGET winners plus hot/cold broker-PAIR winners — scored
with the factorized rank-1 objective ``u = su + A[p,r] + C[p,t]``, then
``scan.prefix_accept``'s prefix-exact acceptance with per-broker net
prefix sums, churn gate, dynamic broker-table membership), with
kernel-friendly re-formulations:

- ALL state lives TRANSPOSED with the partition axis on lanes
  (replicas ``[R, P]`` as exact-integer f32, per-partition columns
  packed ``[5, P]`` f32): VMEM tiles pad the lane dimension to 128, so
  the natural ``[P, small]`` orientation costs 128x its logical size
  and capped the kernel at a 16k-partition bucket — transposed, the
  verified ceiling is a 128k x 256 bucket (64k x 128 when an explicit
  per-partition broker list keeps the int8 ``[P, B]`` allowed matrix
  resident; scan.plan gates and falls back to the XLA session beyond);
- per-tile compute transposes lane slices back to ``[T, R]``/``[T, 5]``
  with one MXU identity-dot each (dynamic lane slicing at TILE_P-aligned
  offsets); commit writes blend one (slot, partition) cell inside the
  aligned lane tile holding the partition;
- no int<->float vector conversion exists anywhere: ``arith.sitofp``
  fails to legalize in Mosaic, so integers ride f32 exactly (< 2^24)
  and float iotas arrive as constant inputs (``tpu.iota`` is int-only);
- the ``loads[s]`` gather becomes a one-hot contraction per P-tile (MXU);
- each winner's attributes (slot, source, delta) are captured IN the
  tile loop as payload columns contracted with the winner one-hot — no
  post-selection re-reads;
- broker (load, ID) ranks for the hot/cold pairing come from pairwise
  ``[B, B]`` comparison counting (``lax.sort`` does not exist in
  Mosaic), and the pair columns are selected with masked one-hot
  matmuls (exact in any precision);
- the candidate union lives on ``K = B + B//2`` lanes, assembled with
  one-hot placement matmuls (lane-concatenating 1-D vectors at a
  non-tile-aligned offset crashes Mosaic layout inference), and the
  acceptance order/claims/net-prefix sums/cumsums are pairwise
  ``[K, K]`` masks and triangular contractions (no scatters, no sorts);
- move logs live in ``[max_moves/128, 128]`` VMEM buffers (exact (8,128)
  tiles) written with dynamic-sublane row selection + masked-lane
  blending. The replicas output aliases the replicas input.

Float32 only — this is the throughput path; parity modes stay on the
XLA/host solvers. Under the Pallas interpreter the kernel is
bit-identical to ``scan.session``'s batch path (pinned by
tests/test_pallas.py); on hardware, float reduction order may resolve
exact candidate ties differently — counts and final unbalance match.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402
from jax.experimental import pallas as pl  # noqa: E402
from jax.experimental.pallas import tpu as pltpu  # noqa: E402

from kafkabalancer_tpu import obs  # noqa: E402
from kafkabalancer_tpu.models.config import kernel_dtype  # noqa: E402
from kafkabalancer_tpu.ops.cost import overload_penalty as _pen  # noqa: E402
from kafkabalancer_tpu.solvers.scan import DEFAULT_CHURN_GATE  # noqa: E402

BIG = 1e30  # inf stand-in (avoids inf−inf NaNs in masking)
TILE_P = 128


def _kernel(
    # scalars (SMEM)
    budget_ref: Any,
    batch_ref: Any,
    minrep_ref: Any,
    minunb_ref: Any,
    churn_ref: Any,
    # arrays (VMEM)
    loads0_ref: Any,
    replicas0_ref: Any,  # [R, P] f32 TRANSPOSED (broker idx as exact floats)
    allowed_ref: Any,  # [P, B] i8 (placeholder [1, B] when all_allowed)
    cols_ref: Any,  # [5, P] f32 packed per-partition columns:
    #            [weight, nrep_cur, nrep_tgt, num_consumers, pvalid]
    always_ref: Any,
    universe_ref: Any,
    lanef_ref: Any,  # [1, B] f32 broker indices (tpu.iota is int-only and
    slotf_ref: Any,  # [1, R] f32 slot indices    sitofp fails to legalize)
    # outputs
    loads_ref: Any,
    replicas_ref: Any,
    n_ref: Any,
    mp_ref: Any,
    mslot_ref: Any,
    msrc_ref: Any,
    mtgt_ref: Any,
    # scratch
    bcount_ref: Any,
    *,
    P: int,
    R: int,
    B: int,
    ML: int,
    allow_leader: bool,
    all_allowed: bool,
) -> None:
    f32 = kernel_dtype()

    # ---- initialize mutable state from the inputs -----------------------
    # State lives TRANSPOSED ([R, P] replicas, [5, P] columns): the
    # partition axis on LANES keeps physical VMEM equal to logical size,
    # while the natural [P, small] orientation tile-pads its lane
    # dimension up to 128x — the single reason the previous layout capped
    # the kernel at a 16k-partition bucket. Replica entries are broker
    # indices carried as exact f32 (< 2^24); per-tile compute transposes
    # slices back to [T, R] on the MXU. Replica-set membership is DERIVED
    # per tile, never stored or transferred.
    loads_ref[:] = loads0_ref[:]
    replicas_ref[:] = replicas0_ref[:]
    bcount_ref[:] = jnp.zeros((1, B), jnp.int32)

    # [T, T] identity for MXU transposes of lane-sliced tiles and payload
    # columns (lane<->sublane reshapes are not portable Mosaic; a dot
    # with the identity is)
    eye_t = (
        lax.broadcasted_iota(jnp.int32, (TILE_P, TILE_P), 0)
        == lax.broadcasted_iota(jnp.int32, (TILE_P, TILE_P), 1)
    ).astype(f32)

    def _dot(
        a: jax.Array, b: jax.Array, ca: int, cb: int
    ) -> jax.Array:
        return jax.lax.dot_general(
            a, b,
            dimension_numbers=(((ca,), (cb,)), ((), ())),
            preferred_element_type=f32,
            precision=jax.lax.Precision.HIGHEST,
        )

    def read_tile(off: jax.Array) -> Tuple[jax.Array, ...]:
        """One partition tile in compute orientation: replicas [T, R] f32
        and per-partition columns w/nrc/nrt/ncons/pvalid (each [T, 1])."""
        reps = _dot(eye_t, replicas_ref[:, pl.ds(off, TILE_P)], 1, 1)
        colst = _dot(eye_t, cols_ref[:, pl.ds(off, TILE_P)], 1, 1)  # [T, 5]
        return (
            reps, colst[:, 0:1], colst[:, 1:2], colst[:, 2:3],
            colst[:, 3:4], colst[:, 4:5],
        )

    def _member_tile(off: jax.Array) -> jax.Array:
        reps, _w, nrc, _nrt, _nc, pv_t = read_tile(off)
        lanef0 = lanef_ref[:]
        m = jnp.zeros((TILE_P, B), jnp.int32)
        for r in range(R):
            col = reps[:, r].reshape(TILE_P, 1)
            valid = (nrc > r + 0.5) & (pv_t > 0.5)
            m = jnp.where((col == lanef0) & valid, jnp.ones_like(m), m)
        return m

    def init_tile(ti: jax.Array, _: Any) -> Any:
        bcount_ref[:] = bcount_ref[:] + jnp.sum(
            _member_tile(ti * TILE_P).astype(kernel_dtype()), axis=0,
            keepdims=True,
        ).astype(jnp.int32)
        return _

    lax.fori_loop(jnp.int32(0), jnp.int32(P // TILE_P), init_tile, jnp.int32(0))
    mp_ref[:] = jnp.full((ML // 128, 128), -1, jnp.int32)
    mslot_ref[:] = jnp.full((ML // 128, 128), -1, jnp.int32)
    msrc_ref[:] = jnp.full((ML // 128, 128), -1, jnp.int32)
    mtgt_ref[:] = jnp.full((ML // 128, 128), -1, jnp.int32)

    budget = budget_ref[0, 0]
    batch = batch_ref[0, 0]
    min_repl = minrep_ref[0, 0]  # f32 (compared against f32 columns)
    min_unb = minunb_ref[0, 0]
    churn = churn_ref[0, 0]

    lane_b = lax.broadcasted_iota(jnp.int32, (1, B), 1)  # [1, B]
    iota_r = lax.broadcasted_iota(jnp.int32, (1, R), 1)  # [1, R]

    iota_sub_t = lax.broadcasted_iota(jnp.int32, (TILE_P, 1), 0)

    B2 = max(1, B // 2)
    K = B + B2

    eye_b = (
        lax.broadcasted_iota(jnp.int32, (B, B), 0)
        == lax.broadcasted_iota(jnp.int32, (B, B), 1)
    ).astype(f32)

    def to_col0(vec_f32: jax.Array) -> jax.Array:  # [B] lanes -> [B, 1] sublanes (MXU transpose)
        return jax.lax.dot_general(
            eye_b,
            vec_f32.reshape(1, B),
            dimension_numbers=(((1,), (1,)), ((), ())),
            preferred_element_type=f32,
            precision=jax.lax.Precision.HIGHEST,
        )

    def iteration(
        carry: Tuple[jax.Array, jax.Array]
    ) -> Tuple[jax.Array, jax.Array]:
        n, _done = carry

        loads = loads_ref[0, :]  # [B]
        bvalid = (
            ((always_ref[0, :] > 0) | (bcount_ref[0, :] > 0))
            & (universe_ref[0, :] > 0)
        )  # [B] bool
        nb = jnp.sum(bvalid.astype(f32))
        avg = jnp.sum(jnp.where(bvalid, loads, jnp.zeros_like(loads))) / nb
        F = jnp.where(bvalid, _pen(loads, avg), jnp.zeros_like(loads))  # [B]
        su = jnp.sum(F)

        # ---- broker (load, ID) ranks + hot/cold pair one-hots -----------
        # pairwise rank counting replaces lax.sort (unavailable in
        # Mosaic): rank_b = #{b' : key_b' < key_b} with the pad key
        # (BIG, id) standing in for rank_brokers' (+inf, id) — identical
        # counts, so identical ranks. Hot rank nb-1-i pairs with cold
        # rank i (ops/cost.py paired_best).
        keyload = jnp.where(bvalid, loads, jnp.full_like(loads, BIG))
        lrow = keyload.reshape(1, B)
        lcol = to_col0(keyload)  # [B, 1]
        brow = lanef_ref[:]  # [1, B] broker ids f32
        bcol = to_col0(brow[0, :])
        lessb = (lcol < lrow) | ((lcol == lrow) & (bcol < brow))
        rank_row = jnp.sum(lessb.astype(f32), axis=0, keepdims=True)  # [1, B]
        rank_col = to_col0(rank_row[0, :])  # [B, 1]
        i2f = lanef_ref[:, :B2]  # [1, B2] float pair iota
        npair = jnp.floor(nb * 0.5)
        live_p = i2f[0, :] < npair  # [B2]
        s_sel = (rank_col == (nb - 1.0 - i2f)).astype(f32)  # [B, B2]
        t_sel = (rank_col == i2f).astype(f32)  # [B, B2]
        s_pair = _dot(brow, s_sel, 1, 0)[0, :]  # [B2] hot broker ids f32
        t_pair = _dot(brow, t_sel, 1, 0)[0, :]  # [B2] cold broker ids f32

        # ---- tile loop over partitions: best candidate per target -------
        # carries: (bestv [1,B], bestp [1,B])
        loadsF = jnp.concatenate(
            [loads.reshape(B, 1), F.reshape(B, 1)], axis=1
        )  # [B, 2]

        def tile_body(
            ti: jax.Array, bc: Tuple[jax.Array, ...]
        ) -> Tuple[jax.Array, ...]:
            (bestv, bestp, bestpay, bestv_l, bestp_l, bestpay_l,
             bv_pf, bp_pf, pay_pf, bv_pl, bp_pl, pay_pl) = bc
            off = ti * TILE_P
            reps, w_t, nrc, nrt, ncons_t, pv_t = read_tile(off)
            # one-hot contraction replaces the loads/F gather (replica
            # entries are exact f32 broker indices; pads are -1 and never
            # match a lane)
            onehot = (
                reps.reshape(TILE_P, R, 1)
                == lanef_ref[:].reshape(1, 1, B)
            ).astype(f32)  # [T, R, B]
            g = jax.lax.dot_general(
                onehot.reshape(TILE_P * R, B),
                loadsF,
                dimension_numbers=(((1,), (0,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST,
            ).reshape(TILE_P, R, 2)
            loads_s = g[:, :, 0]
            F_s = g[:, :, 1]

            elig = (pv_t > 0.5) & (nrt >= min_repl)  # [T, 1]
            # membership from the already-materialized onehot: max over
            # valid slots (pad slots hold -1 and never match a lane)
            # f32 mask: minor-dim insertion on sub-32-bit types fails to
            # lower in Mosaic at some shapes
            valid_slots = (
                (slotf_ref[:] < nrc) & (pv_t > 0.5)
            ).astype(f32)  # [T, R]
            memb = jnp.max(
                onehot * valid_slots[:, :, None], axis=1
            )  # [T, B] f32 0/1
            if all_allowed:
                # every partition allows the whole universe (the default
                # FillDefaults outcome): the [P, B] allowed matrix is
                # neither transferred nor stored
                tmask = (memb < 0.5) & bvalid.reshape(1, B)
            else:
                # NOTE: int8 loads are fine but int8 *comparisons* break
                # the Mosaic lowering — widen before comparing
                alw = allowed_ref[pl.ds(off, TILE_P), :].astype(jnp.int32)
                tmask = (alw > 0) & (memb < 0.5) & bvalid.reshape(1, B)

            # follower pass: slots >= 1, delta = w
            srcmask = (
                (slotf_ref[:] >= 0.5) & (slotf_ref[:] < nrc) & elig
            )  # [T, R]
            A = jnp.where(
                srcmask,
                _pen(loads_s - w_t, avg) - F_s,
                jnp.full_like(loads_s, BIG),
            )
            astar = jnp.min(A, axis=1, keepdims=True)  # [T, 1]
            rstar = lax.argmin(A, axis=1, index_dtype=jnp.int32)  # [T]
            C = _pen(loads.reshape(1, B) + w_t, avg) - F.reshape(1, B)
            V = jnp.where(
                tmask & (astar < BIG * 0.5), astar + C, jnp.full_like(C, BIG)
            )  # [T, B]
            vmin = jnp.min(V, axis=0, keepdims=True)  # [1, B]
            varg = lax.argmin(V, axis=0, index_dtype=jnp.int32).reshape(1, B)
            better = vmin < bestv
            bestv = jnp.where(better, vmin, bestv)
            bestp = jnp.where(better, off + varg, bestp)

            # payload capture for the winning rows: (rstar, source broker
            # at rstar, weight) as [T, 3], transposed on the MXU and
            # contracted with the winner one-hot — all winner attributes
            # travel with the selection, replacing a B-length scalar
            # fetch loop per iteration. Values < 2^24, exact in f32, and
            # produced by masked sums against FLOAT iotas: int->float
            # vector conversions (arith.sitofp) fail to legalize in
            # Mosaic at these layouts
            rstar_c = rstar.reshape(TILE_P, 1)
            sel_r = (iota_r == rstar_c).astype(f32)  # [T, R]
            lane_f = lanef_ref[:]  # [1, B]
            iota_rf = slotf_ref[:]  # [1, R]
            # (int iota_r vs int rstar: comparisons stay integer-legal)
            s_fol = jnp.sum(
                jnp.sum(onehot * sel_r[:, :, None], axis=1) * lane_f,
                axis=1, keepdims=True,
            )  # [T, 1] source broker id at slot rstar
            rstar_f = jnp.sum(iota_rf * sel_r, axis=1, keepdims=True)
            paymat = jnp.concatenate(
                [rstar_f, s_fol, w_t], axis=1
            )  # [T, 3]
            onehot_win = (iota_sub_t == varg).astype(f32)  # [T, B]
            paysel = _dot(_dot(paymat, eye_t, 0, 0), onehot_win, 1, 0)
            bestpay = jnp.where(better, paysel, bestpay)  # [3, B]

            # ---- follower PAIR candidates (cost.paired_best in kernel
            # form): best partition moving OFF each pair's hot broker INTO
            # its cold broker. The [T, B] membership formulation replaces
            # the per-slot one (each broker appears in at most one slot, so
            # the values coincide); one-hot column matmuls replace gathers.
            folmask = valid_slots * (slotf_ref[:] >= 0.5).astype(f32)  # [T, R]
            memb_fol = jnp.max(onehot * folmask[:, :, None], axis=1)  # [T, B]
            slotmat = jnp.sum(
                onehot * (folmask * slotf_ref[:])[:, :, None], axis=1
            )  # [T, B] slot index at each follower-member lane
            eligf = elig.astype(f32)  # [T, 1]
            srcm_f = memb_fol * eligf  # [T, B]
            A_pb = _pen(loads.reshape(1, B) - w_t, avg) - F.reshape(1, B)
            Af_sel = _dot(A_pb * srcm_f, s_sel, 1, 0)  # [T, B2]
            okS = _dot(srcm_f, s_sel, 1, 0) > 0.5
            tm_f = tmask.astype(f32)
            Cf_sel = _dot(C * tm_f, t_sel, 1, 0)
            okT = _dot(tm_f, t_sel, 1, 0) > 0.5
            Vp = jnp.where(okS & okT, Af_sel + Cf_sel, jnp.full_like(Af_sel, BIG))
            vminp = jnp.min(Vp, axis=0, keepdims=True)  # [1, B2]
            vargp = lax.argmin(Vp, axis=0, index_dtype=jnp.int32).reshape(1, B2)
            onehot_wp = (iota_sub_t[:, :1] == vargp).astype(f32)  # [T, B2]
            slot_selp = _dot(slotmat, s_sel, 1, 0)  # [T, B2]
            slotw = jnp.sum(slot_selp * onehot_wp, axis=0, keepdims=True)
            ww = jnp.sum(w_t * onehot_wp, axis=0, keepdims=True)
            betterp = vminp < bv_pf
            bv_pf = jnp.where(betterp, vminp, bv_pf)
            bp_pf = jnp.where(betterp, off + vargp, bp_pf)
            pay_pf = jnp.where(
                betterp, jnp.concatenate([slotw, ww], axis=0), pay_pf
            )  # [2, B2] (slot, w)

            if allow_leader:
                # leader pass: slot 0 scored with its TRUE applied delta
                # w*(replicas+consumers) — see scan.py body_batch for why
                # batch mode departs from the reference's plain-weight
                # under-modelling here. Tracked separately from the
                # follower best and merged globally AFTER the tile loop so
                # follower-vs-leader ties resolve identically to scan.py
                # (follower wins) regardless of which tile each lives in.
                wl = w_t * (nrc + ncons_t)  # [T, 1]
                A_l = jnp.where(
                    (nrc >= 1) & elig,
                    _pen(loads_s[:, :1] - wl, avg) - F_s[:, :1],
                    jnp.full_like(wl, BIG),
                )  # [T, 1]
                C_l = _pen(loads.reshape(1, B) + wl, avg) - F.reshape(1, B)
                V_l = jnp.where(
                    tmask & (A_l < BIG * 0.5), A_l + C_l, jnp.full_like(C_l, BIG)
                )
                vmin_l = jnp.min(V_l, axis=0, keepdims=True)
                varg_l = lax.argmin(V_l, axis=0, index_dtype=jnp.int32).reshape(1, B)
                better_l = vmin_l < bestv_l
                bestv_l = jnp.where(better_l, vmin_l, bestv_l)
                bestp_l = jnp.where(better_l, off + varg_l, bestp_l)

                # leader payloads: (source broker at slot 0, true applied
                # premium w*(replicas+consumers))
                s0 = jnp.sum(
                    onehot[:, 0, :] * lane_f, axis=1, keepdims=True
                )  # [T, 1]
                paymat_l = jnp.concatenate([s0, wl], axis=1)
                onehot_l = (iota_sub_t == varg_l).astype(f32)
                paysel_l = _dot(_dot(paymat_l, eye_t, 0, 0), onehot_l, 1, 0)
                bestpay_l = jnp.where(better_l, paysel_l, bestpay_l)

                # ---- leader PAIR candidates (true applied premium) ------
                lead_m = onehot[:, 0, :] * (
                    ((nrc > 0.5) & elig).astype(f32)
                )  # [T, B]
                A_lpb = _pen(loads.reshape(1, B) - wl, avg) - F.reshape(1, B)
                Al_sel = _dot(A_lpb * lead_m, s_sel, 1, 0)
                okSl = _dot(lead_m, s_sel, 1, 0) > 0.5
                Cl_sel = _dot(C_l * tm_f, t_sel, 1, 0)
                Vpl = jnp.where(
                    okSl & okT, Al_sel + Cl_sel, jnp.full_like(Al_sel, BIG)
                )
                vminpl = jnp.min(Vpl, axis=0, keepdims=True)
                vargpl = lax.argmin(
                    Vpl, axis=0, index_dtype=jnp.int32
                ).reshape(1, B2)
                onehot_wpl = (iota_sub_t[:, :1] == vargpl).astype(f32)
                wwl = jnp.sum(wl * onehot_wpl, axis=0, keepdims=True)
                betterpl = vminpl < bv_pl
                bv_pl = jnp.where(betterpl, vminpl, bv_pl)
                bp_pl = jnp.where(betterpl, off + vargpl, bp_pl)
                pay_pl = jnp.where(betterpl, wwl, pay_pl)  # [1, B2] (wl)

            return (
                bestv, bestp, bestpay, bestv_l, bestp_l, bestpay_l,
                bv_pf, bp_pf, pay_pf, bv_pl, bp_pl, pay_pl,
            )

        bestv0 = jnp.full((1, B), BIG, f32)
        bestp0 = jnp.zeros((1, B), jnp.int32)
        pay0 = jnp.zeros((3, B), f32)
        pay0_l = jnp.zeros((2, B), f32)
        bv0_p = jnp.full((1, B2), BIG, f32)
        bp0_p = jnp.zeros((1, B2), jnp.int32)
        pay0_pf = jnp.zeros((2, B2), f32)
        pay0_pl = jnp.zeros((1, B2), f32)
        (bestv, bestp, bestpay, bestv_l, bestp_l, bestpay_l,
         bv_pf, bp_pf, pay_pf, bv_pl, bp_pl, pay_pl) = lax.fori_loop(
            jnp.int32(0), jnp.int32(P // TILE_P), tile_body,
            (bestv0, bestp0, pay0, bestv0, bestp0, pay0_l,
             bv0_p, bp0_p, pay0_pf, bv0_p, bp0_p, pay0_pl)
        )
        # global leader-vs-follower merge, strict < (follower wins ties)
        lead = bestv_l < bestv
        bestv = jnp.where(lead, bestv_l, bestv)
        bestp = jnp.where(lead, bestp_l, bestp)
        vals = su + bestv[0, :]  # [B]
        cp = bestp[0, :]  # [B] candidate partition per target
        lead_lane = lead[0, :]

        # winner attributes straight from the captured payload rows (all
        # exact small integers or weights in f32)
        if allow_leader:
            cslot = jnp.where(
                lead_lane, jnp.int32(0), bestpay[0, :].astype(jnp.int32)
            )
            cs = jnp.where(
                lead_lane,
                bestpay_l[0, :].astype(jnp.int32),
                bestpay[1, :].astype(jnp.int32),
            )
            cdelta = jnp.where(lead_lane, bestpay_l[1, :], bestpay[2, :])
        else:
            cslot = bestpay[0, :].astype(jnp.int32)
            cs = bestpay[1, :].astype(jnp.int32)
            cdelta = bestpay[2, :]

        # ---- pair winners: leader-vs-follower merge + payloads ----------
        if allow_leader:
            leadp = bv_pl < bv_pf  # strict: follower wins ties
            bvp = jnp.where(leadp, bv_pl, bv_pf)[0, :]
            cp_p = jnp.where(leadp, bp_pl, bp_pf)[0, :]
            cslot_p = jnp.where(
                leadp[0, :], jnp.int32(0), pay_pf[0, :].astype(jnp.int32)
            )
            cdelta_p = jnp.where(leadp[0, :], pay_pl[0, :], pay_pf[1, :])
        else:
            bvp = bv_pf[0, :]
            cp_p = bp_pf[0, :]
            cslot_p = pay_pf[0, :].astype(jnp.int32)
            cdelta_p = pay_pf[1, :]
        vals_p = jnp.where(live_p, su + bvp, jnp.full_like(bvp, BIG))

        # ---- the union pool, K = B + B//2 lanes -------------------------
        # lane CONCATENATION via one-hot matmuls: jnp.concatenate of 1-D
        # lane vectors at a non-tile-aligned offset (B + B2) crashes
        # Mosaic's layout inference ("Check failed: offsets_[0] <
        # tiling_[0]"); placing each part with an exact one-hot
        # contraction sidesteps the layout entirely
        krow = lax.broadcasted_iota(jnp.int32, (1, K), 1).astype(f32)
        M1 = (bcol == krow).astype(f32)  # [B, K] lanes 0..B-1
        M2 = (bcol[:B2, :] == (krow - jnp.asarray(B, f32))).astype(f32)

        def cat(vt: jax.Array, vp: jax.Array) -> jax.Array:  # [B] lanes ++ [B2] lanes -> [K] lanes (exact)
            return (
                _dot(vt.reshape(1, B), M1, 1, 0)
                + _dot(vp.reshape(1, B2), M2, 1, 0)
            )[0, :]

        vals_u = cat(vals, vals_p)
        cp_uf = cat(cp.astype(f32), cp_p.astype(f32))
        cslot_uf = cat(cslot.astype(f32), cslot_p.astype(f32))
        cs_uf = cat(cs.astype(f32), s_pair)
        ct_uf = cat(lane_b[0, :].astype(f32), t_pair)
        w_u = cat(cdelta, cdelta_p)
        cp_u = cp_uf.astype(jnp.int32)
        cslot_u = cslot_uf.astype(jnp.int32)
        ct_u = ct_uf.astype(jnp.int32)
        cs_u = cs_uf.astype(jnp.int32)

        # scalar extraction from lane vectors via masked reduction (vector
        # dynamic-slice along lanes is not portable Mosaic)
        lane_k = lax.broadcasted_iota(jnp.int32, (1, K), 1)  # [1, K]

        def ext_k(vec: jax.Array, i: jax.Array) -> jax.Array:
            # exactly one lane matches and all extracted values are >= 0;
            # max does not promote the accumulator dtype (integer sums
            # would upcast to unsupported int64 under global x64)
            return jnp.max(jnp.where(lane_k[0, :] == i, vec, jnp.zeros_like(vec)))

        # ---- improvement + churn gate -----------------------------------
        improving = (
            (vals_u < su - min_unb) & (vals_u < su) & (vals_u < BIG * 0.5)
        )
        best_gain = su - jnp.min(vals_u)
        improving &= (su - vals_u) * churn >= best_gain

        # ---- PREFIX-EXACT acceptance (mirrors scan.py body_batch) -------
        # Order claimants by (gain, index): E[j, k] = "j strictly earlier".
        # Lane->sublane reshapes of vectors crash the Mosaic backend, so
        # column versions are produced with an MXU transpose (eye @ row);
        # values are exact in f32 (p < 2^24, brokers < 2^24, w < 2^24)
        iotaK_r = lax.broadcasted_iota(jnp.int32, (K, K), 0)
        iotaK_c = lax.broadcasted_iota(jnp.int32, (K, K), 1)
        eyeK = (iotaK_r == iotaK_c).astype(f32)

        def to_colK(vec_f32: jax.Array) -> jax.Array:  # [K] lanes -> [K, 1] sublanes
            return jax.lax.dot_general(
                eyeK,
                vec_f32.reshape(1, K),
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST,
            )

        lane_kf = krow[0, :]  # [K] float candidate iota
        vcol = to_colK(vals_u)
        vrow = vals_u.reshape(1, K)
        kcol = to_colK(lane_kf)
        krow = lane_kf.reshape(1, K)
        E = (vcol < vrow) | ((vcol == vrow) & (kcol < krow))  # [K, K]
        Ef = E.astype(f32)

        # partition first-claim (replica-row writes must be unique)
        onesK = jnp.ones(K, f32)
        zerosK = jnp.zeros(K, f32)
        imp_col = to_colK(jnp.where(improving, onesK, zerosK)) > 0.5
        cp_uf = cp_u.astype(f32)
        pcol = to_colK(cp_uf)
        prow = cp_uf.reshape(1, K)
        surv = improving & ~(
            jnp.max((E & imp_col & (pcol == prow)).astype(f32), axis=0) > 0.5
        )

        # per-broker net prefix sums over earlier survivors: each
        # candidate's source/target load AS OF ITS TURN, so d_k is the
        # EXACT sequential delta even when candidates share brokers
        w_col = to_colK(w_u)
        surv_col = to_colK(jnp.where(surv, onesK, zerosK))
        Ejw = Ef * surv_col * w_col  # [K, K]
        scol = to_colK(cs_uf)
        tcol = to_colK(ct_uf)
        srow = cs_uf.reshape(1, K)
        trow = ct_uf.reshape(1, K)
        to_s = (tcol == srow).astype(f32) - (scol == srow).astype(f32)
        to_t = (tcol == trow).astype(f32) - (scol == trow).astype(f32)
        netS = jnp.sum(Ejw * to_s, axis=0)  # [K]
        netT = jnp.sum(Ejw * to_t, axis=0)

        # loads at each candidate's source/target via one-hot contraction
        M_s = (bcol == srow).astype(f32)  # [B, K]
        M_t = (bcol == trow).astype(f32)
        Ls = _dot(loads.reshape(1, B), M_s, 1, 0)[0, :] + netS  # [K]
        Lt = _dot(loads.reshape(1, B), M_t, 1, 0)[0, :] + netT
        d_k = (
            _pen(Ls - w_u, avg)
            - _pen(Ls, avg)
            + _pen(Lt + w_u, avg)
            - _pen(Lt, avg)
        )
        ok = surv & (d_k < -min_unb) & (d_k < 0.0)
        # cut at the first survivor whose sequential delta fails — nets
        # for later candidates would assume commits that never happen
        fail_col = to_colK(jnp.where(surv & ~ok, onesK, zerosK))
        ok &= ~(jnp.max(Ef * fail_col, axis=0) > 0.5)
        # cap at the batch width and remaining budget, best-first
        ok_col = to_colK(jnp.where(ok, onesK, zerosK))
        pos = n + jnp.sum(Ef * ok_col, axis=0).astype(jnp.int32)  # [K]
        ok &= (pos < n + batch) & (pos < budget) & (pos < ML)
        oki = jnp.where(ok, jnp.ones(K, jnp.int32), jnp.zeros(K, jnp.int32))
        okif = jnp.where(ok, onesK, zerosK)
        cnt = jnp.sum(okif).astype(jnp.int32)

        # ---- apply: loads and bcount (vectorized one-hot scatters) ------
        okd = jnp.where(ok, w_u, jnp.zeros_like(w_u))  # [K]

        def scat(vec_k: jax.Array, M: jax.Array) -> jax.Array:  # Σ_k vec_k · onehot(broker axis) -> [B]
            return jax.lax.dot_general(
                vec_k.reshape(1, K),
                M,
                dimension_numbers=(((1,), (1,)), ((), ())),
                preferred_element_type=f32,
                precision=jax.lax.Precision.HIGHEST,
            ).reshape(B)

        loads_ref[0, :] = loads + scat(okd, M_t) - scat(okd, M_s)
        bcount_ref[0, :] = bcount_ref[0, :] + (
            scat(okif, M_t) - scat(okif, M_s)
        ).astype(jnp.int32)

        # ---- apply: replica rows + move logs (per commit) ---------------
        # commits are partition-disjoint, so each touched row is written by
        # exactly one candidate
        lane_t = lax.broadcasted_iota(jnp.int32, (1, TILE_P), 1)
        sub_r = lax.broadcasted_iota(jnp.int32, (R, 1), 0)

        def commit(i: jax.Array, n_acc: jax.Array) -> jax.Array:
            ok_i = ext_k(oki, i) > 0

            @pl.when(ok_i)
            def _() -> None:
                p_i = ext_k(cp_u, i)
                s_i = ext_k(cs_u, i)
                slot_i = ext_k(cslot_u, i)
                t_i = ext_k(ct_u, i)
                at = ext_k(jnp.where(ok, pos, jnp.zeros_like(pos)), i)
                # transposed replica write: blend one (slot, partition)
                # cell inside the TILE_P-aligned lane tile holding p_i;
                # the new entry is the target broker index as exact f32
                base = lax.mul(
                    lax.div(p_i, jnp.int32(TILE_P)), jnp.int32(TILE_P)
                )
                p_loc = lax.rem(p_i, jnp.int32(TILE_P))
                t_f = ext_k(ct_uf, i)
                tile = replicas_ref[:, pl.ds(base, TILE_P)]  # [R, T]
                tile = jnp.where(
                    (lane_t == p_loc) & (sub_r == slot_i), t_f, tile
                )
                replicas_ref[:, pl.ds(base, TILE_P)] = tile
                # packed log write: dynamic row + masked-lane blend (the
                # buffers are [ML/128, 128] — see module docstring)
                at_row = lax.div(at, jnp.int32(128))
                at_ln = lax.rem(at, jnp.int32(128))
                lane128 = lax.broadcasted_iota(jnp.int32, (1, 128), 1)
                hit = lane128 == at_ln

                def logw(ref: Any, val: jax.Array) -> None:
                    row = ref[pl.ds(at_row, 1), :]
                    ref[pl.ds(at_row, 1), :] = jnp.where(hit, val, row)

                logw(mp_ref, p_i)
                logw(mslot_ref, slot_i)
                logw(msrc_ref, s_i)
                logw(mtgt_ref, t_i)

            return n_acc

        lax.fori_loop(jnp.int32(0), jnp.int32(K), commit, jnp.int32(0))

        return n + cnt, cnt == 0

    def cond(carry: Tuple[jax.Array, jax.Array]) -> jax.Array:
        n, done = carry
        return (~done) & (n < budget) & (n < ML)

    n, _ = lax.while_loop(cond, iteration, (jnp.int32(0), jnp.bool_(False)))
    n_ref[0, 0] = n


@partial(
    jax.jit,
    static_argnames=("max_moves", "allow_leader", "interpret", "all_allowed"),
)
def pallas_session(
    loads: jax.Array,
    replicas: jax.Array,
    member: Optional[jax.Array],  # ignored (None accepted): membership is
    allowed: Optional[jax.Array],  # derived in-kernel from the replica
    weights: jax.Array,  # matrix and never stored or transferred
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: Any,
    budget: jax.Array,
    batch: Any,
    churn_gate: Any = DEFAULT_CHURN_GATE,
    *,
    max_moves: int,
    allow_leader: bool,
    interpret: bool = False,
    all_allowed: bool = False,
) -> Tuple[jax.Array, ...]:
    """Device-resident batched session; same contract as ``scan.session``
    restricted to the batch path: returns ``(replicas, loads, n, move_p,
    move_slot, move_src, move_tgt)`` (no final objective — the caller
    recomputes it host-side from the returned state).

    Shape requirements: the partition bucket must be a multiple of
    ``TILE_P`` (tensorize with ``min_bucket=TILE_P``); float32 only.
    ``interpret=True`` runs the Pallas interpreter (CPU testing).
    """
    P, R = replicas.shape
    B = loads.shape[0]
    if P % TILE_P:
        raise ValueError(f"partition bucket {P} not a multiple of {TILE_P}")
    if max_moves % 128:
        raise ValueError(f"max_moves {max_moves} not a multiple of 128")
    ML = max_moves

    # this body is jit-traced by session_packed / the gate probe, so the
    # registry write below fires once per TRACE — which is precisely the
    # host-visible kernel (re)compile event worth counting; per-dispatch
    # accounting lives at the host call sites (scan._dispatch_chunk)
    obs.metrics.count("pallas.kernel_traces")
    # P/R/B/ML are static shape ints, never traced values
    obs.metrics.gauge(
        "pallas.last_traced_shape",
        {"P": P, "R": R, "B": B, "max_moves": ML},
    )

    f32 = kernel_dtype()
    i32 = jnp.int32
    i8 = jnp.int8

    def scalar(x: Any, dt: Any) -> jax.Array:
        return jnp.asarray(x, dt).reshape(1, 1)

    smem = pl.BlockSpec(memory_space=pltpu.SMEM)
    vmem = pl.BlockSpec(memory_space=pltpu.VMEM)

    # NOTE: the kernel is strictly 32-bit by construction (max-based lane
    # extraction, f32-accumulated counts, lax.argmin with index_dtype) —
    # Mosaic has no 64-bit types and the process may run with x64 enabled
    # transposed device layout: replicas [R, P] as exact-integer f32,
    # per-partition columns packed [5, P] — see the kernel docstring
    replicas_t = jnp.asarray(replicas, i32).astype(f32).T
    cols_t = jnp.stack(
        [
            jnp.asarray(weights, f32).reshape(P),
            jnp.asarray(nrep_cur, i32).astype(f32).reshape(P),
            jnp.asarray(nrep_tgt, i32).astype(f32).reshape(P),
            jnp.asarray(ncons, f32).reshape(P),
            jnp.asarray(pvalid, i32).astype(f32).reshape(P),
        ]
    )  # [5, P]
    out = _call(
        partial(
            _kernel, P=P, R=R, B=B, ML=ML, allow_leader=allow_leader,
            all_allowed=all_allowed,
        ),
        P, R, B, ML, smem, vmem, interpret,
    )(
        scalar(budget, i32),
        scalar(batch, i32),
        scalar(min_replicas, f32),
        scalar(min_unbalance, f32),
        scalar(churn_gate, f32),
        jnp.asarray(loads, f32).reshape(1, B),
        replicas_t,
        # all_allowed: a [1, B] placeholder replaces the [P, B] matrix —
        # the largest kernel input both as transfer and as VMEM resident
        jnp.zeros((1, B), i8)
        if all_allowed
        else jnp.asarray(allowed, i8).reshape(P, B),
        cols_t,
        jnp.asarray(always_valid, i32).reshape(1, B),
        jnp.asarray(universe_valid, i32).reshape(1, B),
        jnp.arange(B, dtype=f32).reshape(1, B),
        jnp.arange(R, dtype=f32).reshape(1, R),
    )
    loads_out, replicas_t_out, n, mp, mslot, msrc, mtgt = out
    # packed [ML/128, 128] row-major == flat move order
    return (
        replicas_t_out.T.astype(i32),
        loads_out.reshape(B),
        n.reshape(()),
        mp.reshape(ML),
        mslot.reshape(ML),
        msrc.reshape(ML),
        mtgt.reshape(ML),
    )


def _call(
    kernel: Any,
    P: int,
    R: int,
    B: int,
    ML: int,
    smem: Any,
    vmem: Any,
    interpret: bool = False,
) -> Any:
    f32 = kernel_dtype()
    i32 = jnp.int32
    i8 = jnp.int8
    return pl.pallas_call(
        kernel,
        interpret=interpret,
        out_shape=(
            jax.ShapeDtypeStruct((1, B), f32),  # loads
            jax.ShapeDtypeStruct((R, P), f32),  # replicas (transposed)
            jax.ShapeDtypeStruct((1, 1), i32),  # n
            jax.ShapeDtypeStruct((ML // 128, 128), i32),  # move_p
            jax.ShapeDtypeStruct((ML // 128, 128), i32),  # move_slot
            jax.ShapeDtypeStruct((ML // 128, 128), i32),  # move_src
            jax.ShapeDtypeStruct((ML // 128, 128), i32),  # move_tgt
        ),
        in_specs=[smem] * 5 + [vmem] * 8,
        out_specs=(vmem, vmem, smem, vmem, vmem, vmem, vmem),
        # the replicas output aliases the replicas input (operand 6 of the
        # flattened inputs): without the alias a second lane-padded [P, R]
        # VMEM buffer doubles the largest resident
        input_output_aliases={6: 1},
        scratch_shapes=[
            pltpu.VMEM((1, B), i32),  # bcount
        ],
    )
