"""Pair-swap polish: escaping single-move local optima on device.

The greedy neighborhood (one replica, one target — steps.go:145-232) stalls
when every single move overshoots: at 10k x 100 scale the move session
converges to ~9e-5 unbalance while the north-star target is < 1e-5
(BASELINE.md). The exit is a *pair swap* — partition p1 moves a replica
from broker a to broker b while p2 moves one from b to a. The objective
only sees the net transfer ``d = w1 - w2`` between the two brokers, and

    g(d) = pen(L_a - d) + pen(L_b + d)

is convex in ``d`` (both terms are convex piecewise quadratics of the
asymmetric penalty, utils.go:134-143), so per broker pair the ideal
transfer has the closed form

    d* = (c_a (L_a - avg) - c_b (L_b - avg)) / (c_a + c_b)

with the current over/under coefficients, and the best achievable swap
uses the replica weights whose difference brackets ``d*``.

The search is sort-free and fully fused on device:

- follower replica entries are compacted host-side ONCE, sorted by weight
  (weights never change during a session) — the static *weight rank*;
- per iteration, the ``nb`` valid brokers are ranked by load and the
  hottest half is paired with a rotation of the coldest half (the
  rotation cycles so different pairings are tried before declaring
  convergence);
- per entry held by a hot broker: query ``w1 - d*`` in the static weight
  order (one ``searchsorted`` against the immutable sorted weights), then
  map to the nearest entries actually held by the paired cold broker
  with the occupied-rank lookup (``nearest_occupied`` — [pairs, Nc]
  next/prev scans; no per-iteration sort);
- the two bracketing candidates are evaluated EXACTLY (true penalty at
  the actual ``d``, so coefficient crossings cost nothing), feasibility-
  masked (allowed/member both directions, eligibility), reduced to the
  best swap per pair, partition-claimed (pairs are broker-disjoint by
  construction), and committed batched — every accepted swap improves the
  objective by exactly its scored delta.

``converge_session`` alternates fused move phases (solvers/scan.py
``session`` or the whole-session Pallas kernel) with swap phases inside
one dispatch until neither commits — a single host round trip for the
whole plan-to-convergence.

This is an extension beyond the reference (its greedy loop cannot express
compound moves; the upstream README lists "N-way swaps" as planned but
never built, README.md:94-100); swaps only exchange follower slots, so
leader premiums (utils.go:96-101) never enter the swap delta.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Optional, Tuple

from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.ops import cost  # noqa: E402
from kafkabalancer_tpu.solvers.scan import (  # noqa: E402
    DEFAULT_CHURN_GATE,
    member_from as _member_from,
)

# swap-phase convergence: shift rotations tried without progress before
# declaring the pairing exhausted
N_SHIFTS = 4
# adaptive acceptance floor: gains below su * SWAP_REL_EPS are noise-level
# churn, not progress
SWAP_REL_EPS = 1e-4


def nearest_occupied(
    holder: jax.Array, tgt_b: jax.Array, pair_live: jax.Array,
    pe_c: jax.Array, rq: jax.Array,
) -> Tuple[jax.Array, jax.Array]:
    """Per-query nearest entries held by the query's paired cold broker,
    in the static weight order. With ``occ[k, j] = (holder[j] ==
    tgt_b[k]) & pair_live[k]`` and ``k = pe_c[q]``:

        j_above[q] = min{ j >= min(rq[q], Nc-1) : occ[k, j] }   (else Nc+1)
        j_below[q] = max{ j <= clip(rq[q]-1, 0, Nc-1) : occ[k, j] }  (else -1)

    Implementation: per-pair next/prev occupied-rank tables via one
    reverse ``cummin`` and one ``cummax`` over the [pairs, Nc] occupancy
    mask, then two row gathers per query. Two alternatives were measured
    on the bench chip and rejected (r4): 128-wide windowed gathers per
    query cut the generated code 26.9 -> 24.3 MB but quadrupled the warm
    flagship wall-clock (TPU general-path gathers); packed 128-bit
    occupancy bitsets with ``population_count`` bit search kept the
    runtime but grew the code to 34 MB (uint32 legalization). The scans
    are the smallest program that stays fast. Outputs are pinned
    bit-identical to a brute-force reference by tests/test_polish.py.
    """
    Nc = holder.shape[0]
    iota_e = jnp.arange(Nc, dtype=jnp.int32)
    BIGI = jnp.int32(Nc + 1)
    occ = (holder[None, :] == tgt_b[:, None]) & pair_live[:, None]
    nxt = lax.cummin(
        jnp.where(occ, iota_e[None, :], BIGI), axis=1, reverse=True
    )
    prv = lax.cummax(jnp.where(occ, iota_e[None, :], -1), axis=1)
    j_above = nxt[pe_c, jnp.clip(rq, 0, Nc - 1)]
    j_below = prv[pe_c, jnp.clip(rq - 1, 0, Nc - 1)]
    return j_above.astype(jnp.int32), j_below.astype(jnp.int32)


def entry_table(
    dp: Any, min_replicas: int, min_bucket: int = 256
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Static weight-sorted follower-entry table for the swap search.

    Returns ``(ew, ep, er, evalid)`` — weights ascending (+inf padding),
    partition row, replica slot, validity. Only follower slots (slot >= 1;
    leader premiums never enter swap deltas) of eligible partitions
    (steps.go:168-170 min-replicas gate) participate. Weights are
    immutable during a session, so the table is built once per plan.
    """
    from kafkabalancer_tpu.ops.runtime import next_bucket

    P, R = dp.replicas.shape
    slot = np.arange(R)[None, :]
    mask = (
        (slot >= 1)
        & (slot < dp.nrep_cur[:, None])
        & dp.pvalid[:, None]
        & (dp.nrep_tgt >= min_replicas)[:, None]
    )
    p_idx, r_idx = np.nonzero(mask)
    w = dp.weights[p_idx]
    order = np.argsort(w, kind="stable")
    n = len(order)
    Nc = next_bucket(max(n, 1), min_bucket)
    ew = np.full(Nc, np.inf)
    ep = np.zeros(Nc, np.int32)
    er = np.zeros(Nc, np.int32)
    evalid = np.zeros(Nc, bool)
    ew[:n] = w[order]
    ep[:n] = p_idx[order]
    er[:n] = r_idx[order]
    evalid[:n] = True
    return ew, ep, er, evalid


def _swap_loop(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    n: jax.Array,
    mp: jax.Array,
    mslot: jax.Array,
    mtgt: jax.Array,
    *,
    ew: jax.Array,
    ep: jax.Array,
    er: jax.Array,
    evalid: jax.Array,
    allowed: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_unbalance: jax.Array,
    budget: jax.Array,
    ML: int,
    tid: Optional[jax.Array] = None,
    lam: Optional[jax.Array] = None,
    n_topics: int = 0,
) -> Tuple[jax.Array, ...]:
    """Fused pair-swap loop (see module docstring). Mutates the carried
    state/logs; logs each swap as its two constituent moves. Returns the
    updated ``(loads, replicas, member, n, mp, mslot, mtgt)``.

    ``n_topics > 0`` (with ``tid [P]``/scalar ``lam``) scores swaps on the
    COMBINED objective ``u + λ·Σ max(0, c-1)``: each candidate pair adds
    the colocation delta of its two membership changes (zero when both
    partitions share a topic — the counts cells cancel). Per-(topic,
    broker) counts recompute from the live membership each iteration, and
    exactness under batched commits holds because pairs are
    broker-disjoint, so no two accepted swaps touch the same (topic,
    broker) cell."""
    P, R = replicas.shape
    B = loads.shape[0]
    Nc = ew.shape[0]
    dtype = loads.dtype
    nh = B // 2
    iota_e = jnp.arange(Nc, dtype=jnp.int32)
    i_pair = jnp.arange(nh, dtype=jnp.int32)
    BIGI = jnp.int32(Nc + 1)

    def cond(st: Tuple[jax.Array, ...]) -> jax.Array:
        n, streak = st[3], st[4]
        return (streak < N_SHIFTS) & (n + 2 <= budget) & (n + 2 <= ML)

    def body(st: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        loads, replicas, member, n, streak, it, mp, mslot, mtgt = st

        bcount = jnp.sum(
            (member & pvalid[:, None]).astype(jnp.int32), axis=0,
            dtype=jnp.int32,
        )
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid.astype(jnp.int32), dtype=jnp.int32)
        avg = jnp.sum(jnp.where(bvalid, loads, 0.0)) / nb.astype(dtype)
        F = jnp.where(bvalid, cost.overload_penalty(loads, avg), 0.0)
        su = jnp.sum(F)
        eps = jnp.maximum(min_unbalance, su * SWAP_REL_EPS)
        if n_topics:
            # per-(topic, broker) replica counts, fresh from the live
            # membership (member mutates per iteration; recomputing is one
            # [P, B] scatter, the same cost class as the bcount reduction
            # above)
            counts = (
                jnp.zeros((n_topics, B), dtype)
                .at[tid]
                .add((member & pvalid[:, None]).astype(dtype))
            )

        # hottest half paired with a rotation of the coldest half; the
        # halves are disjoint rank ranges, so pairs are broker-disjoint
        # by construction (no broker claims needed)
        _, perm, _ = cost.rank_brokers(loads, bvalid)
        npair = nb // 2
        s = it % N_SHIFTS
        cold_rank = (i_pair + s) % jnp.maximum(npair, 1)
        hot_rank = nb - 1 - i_pair
        src_b = perm[jnp.clip(hot_rank, 0, B - 1)]
        tgt_b = perm[jnp.clip(cold_rank, 0, B - 1)]
        pair_live = i_pair < npair

        La = loads[src_b]
        Lb = loads[tgt_b]
        one, half = jnp.asarray(1.0, dtype), jnp.asarray(0.5, dtype)
        ca = jnp.where(La > avg, one, half)
        cb = jnp.where(Lb > avg, one, half)
        dstar = (ca * (La - avg) - cb * (Lb - avg)) / (ca + cb)  # [nh]

        # entry -> its holder's pair (via a trash slot at broker index B)
        pair_of_src = (
            jnp.full(B + 1, -1, jnp.int32)
            .at[jnp.where(pair_live, src_b, B)]
            .set(jnp.where(pair_live, i_pair, -1))
        )
        holder = jnp.where(
            evalid, replicas[ep, er].astype(jnp.int32), jnp.int32(B)
        )
        pe = pair_of_src[holder]  # [Nc] pair index or -1
        pe_c = jnp.clip(pe, 0)
        live_e = pe >= 0
        t_e = tgt_b[pe_c]

        feas1 = live_e & allowed[ep, t_e] & ~member[ep, t_e]

        # nearest cold-broker entries by weight around w1 - d*: one
        # searchsorted into the STATIC weight order, then the per-pair
        # occupied-rank lookup (nearest_occupied; see its docstring for
        # the measured code-size/runtime trade behind the scan tables)
        wq = ew - dstar[pe_c]
        rq = jnp.searchsorted(ew, wq).astype(jnp.int32)  # [Nc]
        j_above, j_below = nearest_occupied(
            holder, tgt_b, pair_live, pe_c, rq
        )
        va = (rq < Nc) & (j_above < BIGI)
        vb = (rq > 0) & (j_below >= 0)

        def cand_score(
            j2: jax.Array, ok2: jax.Array
        ) -> Tuple[jax.Array, jax.Array]:
            j2c = jnp.clip(j2, 0, Nc - 1)
            w2 = ew[j2c]
            p2 = ep[j2c]
            feas2 = ok2 & allowed[p2, holder % B] & ~member[p2, holder % B]
            d = ew - w2
            delta = (
                cost.overload_penalty(La[pe_c] - d, avg)
                + cost.overload_penalty(Lb[pe_c] + d, avg)
                - F[holder % B]
                - F[t_e]
            )
            if n_topics:
                # combined-objective swap delta: entry 1 (topic t1) moves
                # hot -> cold, entry 2 (topic t2) cold -> hot. Same topic
                # means both counts cells cancel exactly (net zero).
                hb = holder % B
                t1 = tid[ep]
                t2 = t1[j2c]
                sub1, _ = cost.colo_terms(counts[t1, hb], lam)
                _, add1 = cost.colo_terms(counts[t1, t_e], lam)
                sub2, _ = cost.colo_terms(counts[t2, t_e], lam)
                _, add2 = cost.colo_terms(counts[t2, hb], lam)
                delta = delta + jnp.where(
                    t1 == t2,
                    jnp.zeros_like(delta),
                    add1 - sub1 + add2 - sub2,
                )
            return jnp.where(feas1 & feas2, delta, jnp.inf), j2c

        sa, ja = cand_score(j_above, va)
        sb, jb = cand_score(j_below, vb)
        score = jnp.minimum(sa, sb)
        jsel = jnp.where(sa <= sb, ja, jb)

        # best entry per pair: scatter-min, then lowest-index winner
        improving = score < -eps
        pe_t = jnp.where(improving, pe_c, nh)  # trash pair nh
        best = jnp.full(nh + 1, jnp.inf, dtype).at[pe_t].min(score)
        is_win = improving & (score <= best[pe_c])
        win_e = (
            jnp.full(nh + 1, BIGI, jnp.int32)
            .at[jnp.where(is_win, pe_c, nh)]
            .min(jnp.where(is_win, iota_e, BIGI))
        )[:nh]
        ok = (win_e < BIGI) & pair_live  # [nh]
        e1 = jnp.clip(win_e, 0, Nc - 1)
        e2 = jsel[e1]
        p1w, r1w = ep[e1], er[e1]
        p2w, r2w = ep[e2], er[e2]
        # dead/rejected pairs index the +inf weight padding; their transfer
        # must be EXACTLY zero before the masked scatter-add below — the
        # usual zero-mask trick fails on inf payloads (inf * 0 = NaN, and
        # one NaN added to a broker load poisons every later phase)
        dw = jnp.where(ok, ew[e1] - ew[e2], 0.0)

        # partition claims: the same partition may hold replicas in two
        # different pairs; first claimant (lowest pair index) wins
        bigp = jnp.int32(nh + 1)
        prio = jnp.where(ok, i_pair, bigp)
        first_p = (
            jnp.full(P + 1, bigp, jnp.int32)
            .at[jnp.where(ok, p1w, P)]
            .min(prio)
            .at[jnp.where(ok, p2w, P)]
            .min(prio)
        )
        ok &= (first_p[p1w] == i_pair) & (first_p[p2w] == i_pair)

        # budget cap (2 log slots per swap)
        rank = jnp.cumsum(ok.astype(jnp.int32), dtype=jnp.int32) - 1
        ok &= (n + 2 * rank + 2 <= budget) & (n + 2 * rank + 2 <= ML)
        oki = ok.astype(jnp.int32)
        okf = oki.astype(dtype)
        cnt = jnp.sum(oki, dtype=jnp.int32)

        # apply: pairs are broker-disjoint, partitions claimed — rejected
        # candidates contribute zero-adds, so scatters cannot race
        loads = loads.at[src_b].add(-dw * okf).at[tgt_b].add(dw * okf)
        replicas = (
            replicas.at[p1w, r1w]
            .add(((tgt_b - src_b) * oki).astype(replicas.dtype))
            .at[p2w, r2w]
            .add(((src_b - tgt_b) * oki).astype(replicas.dtype))
        )
        toggles = (
            jnp.zeros((P, B), jnp.int32)
            .at[p1w, src_b]
            .add(oki)
            .at[p1w, tgt_b]
            .add(oki)
            .at[p2w, tgt_b]
            .add(oki)
            .at[p2w, src_b]
            .add(oki)
        )
        member = member ^ (toggles > 0)

        pos1 = jnp.where(ok, n + 2 * rank, ML)
        pos2 = jnp.where(ok, n + 2 * rank + 1, ML)
        mp = mp.at[pos1].set(jnp.where(ok, p1w, -1)).at[pos2].set(
            jnp.where(ok, p2w, -1)
        )
        mslot = mslot.at[pos1].set(jnp.where(ok, r1w, -1)).at[pos2].set(
            jnp.where(ok, r2w, -1)
        )
        mtgt = mtgt.at[pos1].set(jnp.where(ok, tgt_b, -1)).at[pos2].set(
            jnp.where(ok, src_b, -1)
        )

        n = n + 2 * cnt
        streak = jnp.where(cnt == 0, streak + 1, 0)
        return loads, replicas, member, n, streak, it + 1, mp, mslot, mtgt

    st = (loads, replicas, member, n, jnp.int32(0), jnp.int32(0), mp, mslot, mtgt)
    loads, replicas, member, n, _s, _i, mp, mslot, mtgt = lax.while_loop(
        cond, body, st
    )
    return loads, replicas, member, n, mp, mslot, mtgt


def _leader_shuffle_loop(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    n: jax.Array,
    mp: jax.Array,
    mslot: jax.Array,
    mtgt: jax.Array,
    *,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: jax.Array,
    budget: jax.Array,
    ML: int,
) -> Tuple[jax.Array, ...]:
    """Intra-partition leadership transfers: hand the leader role to one
    of the partition's OWN followers. This shifts exactly the leader
    premium ``w*(replicas+consumers) - w`` between two member brokers
    with no data movement and no membership change — a neighborhood
    neither the reference's ``move()`` (targets must be non-members,
    steps.go:199-201) nor the swap phase (followers only) can express,
    yet it is what closes the final gap when the residual unbalance is
    premium-granular. Logged with ``leader.SWAP_SLOT`` (decoded as the
    ``replacepl`` in-place position exchange, utils.go:181-188)."""
    from kafkabalancer_tpu.solvers.leader import SWAP_SLOT

    P, R = replicas.shape
    dtype = loads.dtype
    slot_iota = jnp.arange(R, dtype=jnp.int32)[None, :]

    def cond(st: Tuple[jax.Array, ...]) -> jax.Array:
        n, done = st[3], st[4]
        return (~done) & (n + 1 <= budget) & (n + 1 <= ML)

    def body(st: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        loads, replicas, member, n, _done, mp, mslot, mtgt = st
        bcount = jnp.sum(
            (member & pvalid[:, None]).astype(jnp.int32), axis=0,
            dtype=jnp.int32,
        )
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid.astype(jnp.int32), dtype=jnp.int32)
        avg = jnp.sum(jnp.where(bvalid, loads, 0.0)) / nb.astype(dtype)
        F = cost.overload_penalty(loads, avg)
        su_terms = jnp.where(bvalid, F, 0.0)
        su = jnp.sum(su_terms)
        eps = jnp.maximum(min_unbalance, su * SWAP_REL_EPS)

        lead = jnp.clip(replicas[:, 0], 0)  # [P]
        extra = weights * (nrep_cur.astype(dtype) + ncons) - weights  # [P]
        fol = jnp.clip(replicas, 0)  # [P, R]
        valid = (
            (slot_iota >= 1)
            & (slot_iota < nrep_cur[:, None])
            & pvalid[:, None]
            & (nrep_tgt >= min_replicas)[:, None]
            & (extra > 0)[:, None]
        )
        Ls = loads[lead][:, None]
        Lf = loads[fol]
        ex = extra[:, None]
        delta = (
            cost.overload_penalty(Ls - ex, avg)
            + cost.overload_penalty(Lf + ex, avg)
            - F[lead][:, None]
            - F[fol]
        )
        delta = jnp.where(valid, delta, jnp.inf)
        flat = delta.reshape(-1)
        i = lax.argmin(flat, 0, jnp.int32)
        accept = flat[i] < -eps
        p, r = jnp.divmod(i, jnp.int32(R))
        l_b = lead[p]
        f_b = replicas[p, r]

        def apply(a: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
            loads, replicas, mp, mslot, mtgt = a
            loads = loads.at[l_b].add(-extra[p]).at[f_b].add(extra[p])
            replicas = replicas.at[p, 0].set(f_b).at[p, r].set(
                l_b.astype(replicas.dtype)
            )
            mp = mp.at[n].set(p.astype(jnp.int32))
            mslot = mslot.at[n].set(jnp.int32(SWAP_SLOT))
            mtgt = mtgt.at[n].set(f_b.astype(jnp.int32))
            return loads, replicas, mp, mslot, mtgt

        loads, replicas, mp, mslot, mtgt = lax.cond(
            accept, apply, lambda a: a, (loads, replicas, mp, mslot, mtgt)
        )
        n = n + accept.astype(n.dtype)
        return loads, replicas, member, n, ~accept, mp, mslot, mtgt

    st = (loads, replicas, member, n, jnp.bool_(False), mp, mslot, mtgt)
    loads, replicas, member, n, _d, mp, mslot, mtgt = lax.while_loop(
        cond, body, st
    )
    return loads, replicas, member, n, mp, mslot, mtgt


@partial(
    jax.jit,
    static_argnames=(
        "max_moves", "allow_leader", "batch", "engine", "all_allowed",
        "n_topics",
    ),
)
def converge_session(
    loads: jax.Array,
    replicas: jax.Array,
    allowed: Optional[jax.Array],
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: jax.Array,
    budget: jax.Array,
    ew: jax.Array,
    ep: jax.Array,
    er: jax.Array,
    evalid: jax.Array,
    churn_gate: Any = DEFAULT_CHURN_GATE,
    tid: Optional[jax.Array] = None,
    lam: Optional[jax.Array] = None,
    *,
    max_moves: int,
    allow_leader: bool,
    batch: int,
    engine: str = "xla",
    all_allowed: bool = False,
    n_topics: int = 0,
) -> jax.Array:
    """Move phases and swap phases alternated on device until neither
    commits — one dispatch for the whole plan-to-convergence.

    With a Pallas engine the whole-session kernel runs ONCE up front (it
    fully converges the single-move neighborhood; embedding the kernel in
    the alternation ``while_loop`` would pin its buffers in scoped VMEM
    and overflow the 16 MB budget at the 16k-partition bucket), then the
    alternation loop interleaves XLA move phases (solvers/scan.py
    ``session`` — after a swap phase only a handful of single moves
    reopen) with swap phases until neither commits. Returns ``packed`` —
    the int32 concatenation ``[move_p | move_slot | move_tgt | n]`` sized
    ``3 * (2 * max_moves) + 1`` (one device->host transfer decodes the
    whole plan).

    ``n_topics > 0`` (with ``tid``/``lam``) runs every phase on the
    COMBINED anti-colocation objective: the move phase is the colocation
    session (scan.session with counts state, batch > 1 required), the
    swap phase scores the ±λ terms per candidate pair, and the
    leadership-shuffle phase needs no change at all — a leadership
    transfer moves no membership, so colocation counts are invariant.
    XLA engine only (the whole-session kernel has no colocation state).
    """
    from kafkabalancer_tpu.solvers.scan import session

    if n_topics and engine != "xla":
        raise ValueError(
            "the colocation-aware polish session is XLA-only (the "
            "whole-session kernel has no colocation state)"
        )

    B = loads.shape[0]
    ML = 2 * max_moves  # phase buffers merge into double-size global logs
    # the dynamic_update_slice merges at offset n are in-bounds only while
    # n <= budget <= max_moves (phase logs are max_moves+1 long and land in
    # the (ML+1)-sized global log); clamp so a caller passing budget >
    # max_moves degrades to a capped session instead of corrupting the log
    budget = jnp.minimum(budget, jnp.int32(max_moves))
    mp0 = jnp.full(ML + 1, -1, jnp.int32)
    use_pallas = engine in ("pallas", "pallas-interpret")

    n = jnp.int32(0)
    mp, mslot, mtgt = mp0, mp0, mp0
    if use_pallas:
        from kafkabalancer_tpu.solvers.pallas_session import pallas_session

        replicas, loads, n, pmp, pmslot, _pmsrc, pmtgt = pallas_session(
            loads, replicas, None, allowed, weights, nrep_cur, nrep_tgt,
            ncons, pvalid, always_valid, universe_valid, min_replicas,
            min_unbalance, budget, jnp.int32(max(1, batch)), churn_gate,
            max_moves=max_moves, allow_leader=allow_leader,
            interpret=(engine == "pallas-interpret"),
            all_allowed=all_allowed,
        )
        mp = lax.dynamic_update_slice(mp, pmp, (0,))
        mslot = lax.dynamic_update_slice(mslot, pmslot, (0,))
        mtgt = lax.dynamic_update_slice(mtgt, pmtgt, (0,))

    def outer_cond(st: Tuple[jax.Array, ...]) -> jax.Array:
        n, done = st[3], st[4]
        return (~done) & (n + 1 <= budget)

    def outer_body(st: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        loads, replicas, member, n, _done, mp, mslot, mtgt = st
        n0 = n

        # --- move phase (no-op pass after the pallas pre-phase) ----------
        replicas, loads, nm, pmp, pmslot, _pmsrc, pmtgt, _su = session(
            loads, replicas, member, allowed, weights, nrep_cur,
            nrep_tgt, ncons, pvalid, always_valid, universe_valid,
            min_replicas, min_unbalance, budget - n, churn_gate,
            tid, lam,
            max_moves=max_moves, allow_leader=allow_leader, batch=batch,
            n_topics=n_topics,
        )
        # merge the phase logs at offset n; entries past nm are -1 and get
        # overwritten by the next merge or ignored by the [:n] decode
        mp = lax.dynamic_update_slice(mp, pmp, (n,))
        mslot = lax.dynamic_update_slice(mslot, pmslot, (n,))
        mtgt = lax.dynamic_update_slice(mtgt, pmtgt, (n,))
        n = n + nm
        member = _member_from(replicas, nrep_cur, pvalid, B)

        # --- swap phase -------------------------------------------------
        loads, replicas, member, n, mp, mslot, mtgt = _swap_loop(
            loads, replicas, member, n, mp, mslot, mtgt,
            ew=ew, ep=ep, er=er, evalid=evalid, allowed=allowed,
            pvalid=pvalid, always_valid=always_valid,
            universe_valid=universe_valid, min_unbalance=min_unbalance,
            budget=budget, ML=ML, tid=tid, lam=lam, n_topics=n_topics,
        )

        # --- leadership-shuffle phase (allow_leader only) ---------------
        if allow_leader:
            loads, replicas, member, n, mp, mslot, mtgt = (
                _leader_shuffle_loop(
                    loads, replicas, member, n, mp, mslot, mtgt,
                    weights=weights, nrep_cur=nrep_cur, nrep_tgt=nrep_tgt,
                    ncons=ncons, pvalid=pvalid, always_valid=always_valid,
                    universe_valid=universe_valid,
                    min_replicas=min_replicas,
                    min_unbalance=min_unbalance, budget=budget, ML=ML,
                )
            )

        return loads, replicas, member, n, n == n0, mp, mslot, mtgt

    member = _member_from(replicas, nrep_cur, pvalid, B)
    # with a non-pallas engine the first move phase runs inside the loop
    # (swap phase on an unconverged state commits little and is cheap)
    st = (loads, replicas, member, n, jnp.bool_(False), mp, mslot, mtgt)
    loads, replicas, member, n, _done, mp, mslot, mtgt = lax.while_loop(
        outer_cond, outer_body, st
    )
    return jnp.concatenate(
        [mp[:ML], mslot[:ML], mtgt[:ML], n.astype(jnp.int32).reshape(1)]
    )
