"""Multi-move planning sessions fused on device.

The reference applies one move per ``Balance()`` call and loops on the host
(``-max-reassign`` outer loop, kafkabalancer.go:177-221), recomputing broker
loads from scratch each iteration. Here an entire k-move session runs as a
single XLA ``while_loop``: per iteration the full ``[P, R, B]`` candidate
tensor is scored (rank-1 objective update, see solvers/tpu.py), the winner
is applied on device, and the loop exits early once no candidate clears the
``min_unbalance`` threshold — zero host round-trips until the session ends.

Semantics relative to the per-move pipeline:

- step precedence per iteration matches the reference: leader candidates
  (gated on ``allow_leader_rebalancing``) are accepted first, follower
  candidates otherwise (balancer.go:42-43 MoveLeaders before
  MoveNonLeaders);
- candidate *scoring* uses the plain follower weight even for leader moves
  (the reference's under-modelling, steps.go:185/:207), but *applying* a
  leader move shifts the true load — weight × (replica count +
  num_consumers) — because the next iteration of the reference recomputes
  loads from the real assignment (utils.go:92-105);
- tie-breaks use candidate order (partition, slot, ascending (load, ID)
  target rank) with the *incremental* objective. The per-move ``tpu``
  solver re-scores ties with the oracle's accumulation-order floats for
  byte parity with Go; a fused session cannot, so mathematically tied
  candidates may resolve differently than the reference — plan quality is
  identical (same unbalance trajectory to float round-off);
- ``rebalance_leaders`` (forced leadership redistribution,
  steps.go:234-282) fires every iteration in the reference pipeline and is
  inherently host-sequential here; :func:`plan` falls back to the per-move
  pipeline when it is enabled.

``dtype`` selects the on-device precision: float64 matches the oracle to
round-off (TPU executes f64 in software); float32 is the throughput mode
for large clusters where the objective's ~1e-7 relative noise is far below
any real decision margin.
"""

from __future__ import annotations

import hashlib
import threading
from functools import partial
from typing import Any, List, Optional, Tuple

from kafkabalancer_tpu import obs
from kafkabalancer_tpu.obs import convergence
from kafkabalancer_tpu.models import Partition, PartitionList, RebalanceConfig
from kafkabalancer_tpu.models.config import (
    ENGINES,
    default_dtype,
    kernel_dtype,
)
from kafkabalancer_tpu.models.partition import empty_partition_list
from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.balancer.pipeline import _COMMON_HEAD  # noqa: E402
from kafkabalancer_tpu.balancer.steps import BalanceError  # noqa: E402
from kafkabalancer_tpu.ops import cost, tensorize  # noqa: E402
from kafkabalancer_tpu.ops.runtime import next_bucket  # noqa: E402

# batched-commit churn gate default: only commit moves whose gain is within
# this factor of the iteration's best. Swept at the 10k x 100 scale
# (mu=1e-5): 4.0 -> +26% commits vs the batch=1 trajectory; 1.5 -> +0.14%
# commits at BETTER final unbalance and equal wall-clock.
DEFAULT_CHURN_GATE = 1.5


def auto_chunk_moves(npart: int) -> int:
    """Per-dispatch move budget scaled to the instance, clamped to the
    watchdog bound. Convergence-scale sessions stay single-dispatch
    (profiled at 100k x 256: two chunks cost ~2.3 s of re-tensorize +
    re-entry for zero quality; moves-to-converge tracks ~P/8). Small
    instances keep the 8192 floor (one compiled bucket). Shared by
    ``plan`` and ``parallel.shard_session.plan_sharded`` so the heuristic
    cannot drift between the single-device and sharded paths."""
    return min(max(8192, 1 << (npart // 4).bit_length()), 1 << 20)


def prefix_accept(
    vals: jax.Array,
    p: jax.Array,
    s_: jax.Array,
    t: jax.Array,
    w_k: jax.Array,
    loads: jax.Array,
    avg: jax.Array,
    su: jax.Array,
    min_unbalance: Any,
    churn_gate: Any,
    n: jax.Array,
    batch: int,
    budget: jax.Array,
    max_moves: int,
    topic: Optional[jax.Array] = None,
    colo_d: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """PREFIX-EXACT batched-commit acceptance over a candidate pool.

    Replaces broker-disjointness: order claimants by (gain, index) —
    ``E[j, k]`` = "j strictly earlier" — claim partitions first-claimant
    (replica-row writes must be unique), then compute each candidate's
    source/target load *as of its turn* via per-broker net prefix sums
    over earlier survivors. ``d_k`` is then the EXACT sequential delta of
    move k even when candidates share brokers, and accepting the longest
    prefix of improving candidates preserves the invariant that every
    committed move improves the objective by precisely its delta. The
    pool's rank-0 candidate is the globally best single move, so the
    convergence criterion (``cnt == 0`` iff no improving move exists)
    matches one-at-a-time greedy exactly.

    Inputs are [K] candidate arrays (``vals`` ABSOLUTE su-based scores,
    +inf for dead candidates) plus the replicated scalars. Returns
    ``(ok, pos, cnt)`` — the accepted mask, each candidate's move-log
    position, and the accepted count. Shared by ``session``'s batch body
    and ``parallel.shard_session`` (the Pallas whole-session kernel
    re-derives it in kernel form) so the acceptance order cannot drift
    between engines.

    ``topic``/``colo_d`` (both [K], together) extend the exactness
    contract to the anti-colocation objective: ``colo_d`` is each
    candidate's colocation delta ±λ computed from pass-START counts, and
    it stays exact under batching because same-TOPIC claimants whose
    broker sets intersect are first-claimed like partitions — no two
    accepted moves this pass touch the same (topic, broker) cell, so no
    accepted move can invalidate another's colocation constant. ``d_k``
    then scores the COMBINED objective (load delta + colo_d).

    KNOWN APPROXIMATION (deliberate): partition and (topic, broker)
    claims are made by every IMPROVING candidate, not only by the
    finally-accepted set — a candidate can lose its claim to an earlier
    claimant that is itself later rejected (own lost claim, sequential
    delta failure, batch/budget cap). This is strictly conservative:
    exactness and the convergence criterion are untouched (the rank-0
    candidate always survives), it only forfeits some commits in the
    pass that the next iteration re-offers. Resolving it would mean
    iterating the claim graph to a fixed point ([K, K] passes inside the
    while_loop body); measured commits/pass (~50 at 131k x 256) left no
    wall-clock argument for that extra machinery.
    """
    dtype = loads.dtype
    K = vals.shape[0]
    kk = jnp.arange(K, dtype=jnp.int32)

    improving = jnp.isfinite(vals) & (vals < su - min_unbalance) & (vals < su)
    # churn gate: only commit candidates whose improvement is within
    # ``churn_gate``x of this iteration's best. Without it the pool
    # floods marginal moves that later iterations re-move, inflating
    # the emitted plan (= real Kafka data movement) for the same final
    # unbalance. The best candidate always passes, so the convergence
    # criterion is unchanged.
    best_gain = su - jnp.min(vals)
    improving &= (su - vals) * churn_gate >= best_gain

    E = (vals[:, None] < vals[None, :]) | (
        (vals[:, None] == vals[None, :]) & (kk[:, None] < kk[None, :])
    )
    samep = p[:, None] == p[None, :]
    claimed = E & improving[:, None] & samep
    if topic is not None:
        # (topic, broker) first-claim: an earlier same-topic claimant
        # sharing either broker would change this candidate's colocation
        # counts mid-pass — its ±λ constant is only exact if no accepted
        # earlier move touches its (topic, s/t) cells
        sametopic = topic[:, None] == topic[None, :]
        bshare = (
            (s_[:, None] == s_[None, :])
            | (s_[:, None] == t[None, :])
            | (t[:, None] == s_[None, :])
            | (t[:, None] == t[None, :])
        )
        claimed |= E & improving[:, None] & sametopic & bshare
    surv = improving & ~jnp.any(claimed, axis=0)

    Ej = (E & surv[:, None]).astype(dtype)  # [K, K] j earlier & survives
    wEj = Ej * w_k[:, None]
    to_s = (t[:, None] == s_[None, :]).astype(dtype) - (
        s_[:, None] == s_[None, :]
    ).astype(dtype)
    to_t = (t[:, None] == t[None, :]).astype(dtype) - (
        s_[:, None] == t[None, :]
    ).astype(dtype)
    Ls = loads[s_] + jnp.sum(wEj * to_s, axis=0)
    Lt = loads[t] + jnp.sum(wEj * to_t, axis=0)
    d_k = (
        cost.overload_penalty(Ls - w_k, avg)
        - cost.overload_penalty(Ls, avg)
        + cost.overload_penalty(Lt + w_k, avg)
        - cost.overload_penalty(Lt, avg)
    )
    if colo_d is not None:
        d_k = d_k + colo_d
    ok = surv & (d_k < -min_unbalance) & (d_k < 0)
    # cut at the first survivor whose sequential delta fails — nets for
    # later candidates would assume commits that never happen
    failed_before = jnp.any(E & (surv & ~ok)[:, None], axis=0)
    ok &= ~failed_before
    # cap at the batch width and the remaining budget, best-first; the
    # capped-out suffix is again a suffix of the acceptance order
    pos = n + jnp.sum(
        (E & ok[:, None]).astype(jnp.int32), axis=0, dtype=jnp.int32
    )
    ok &= (pos < n + batch) & (pos < budget) & (pos < max_moves)
    cnt = jnp.sum(ok.astype(jnp.int32), dtype=jnp.int32)
    return ok, pos, cnt


# whole-session kernel capacity PRIOR: partition-bucket x broker-bucket
# cells that fit the TPU v5e scoped-VMEM budget with the transposed
# compact layout (128k x 256 all-allowed and 64k x 128 restricted, both
# hardware-verified). These are one chip generation's calibration, NOT
# the gate itself: :func:`pallas_session_fits` decides from a persistent
# per-device-kind verdict cache, populated by compile probes (when the
# prior rejects) and by observed VMEM OOM fallbacks at dispatch (when
# the prior admits but the chip disagrees) — so on a different TPU the
# real budget wins over the literals either way.
PALLAS_VMEM_CELLS = 131072 * 256
PALLAS_VMEM_CELLS_RESTRICTED = 65536 * 128

_gate_mem: dict = {}


def _gate_cache_path() -> Optional[str]:
    from kafkabalancer_tpu.ops import aot

    d = aot.aot_dir()
    import os

    return None if d is None else os.path.join(d, "pallas_gate.json")


def _gate_key(
    P: int,
    B: int,
    R: int,
    all_allowed: bool,
    allow_leader: bool,
    max_moves: int,
) -> str:
    # allow_leader changes the kernel's traced program (the leader
    # scoring pass) and thus its VMEM footprint — one mode's verdict
    # must not be reused for the other (r5 review). max_moves (already a
    # power-of-two bucket) sizes the kernel's move-log buffers the same
    # way: a verdict earned at one buffer size must not admit (and OOM)
    # or ban a different one (ADVICE r5 — a probe-admitted shape could
    # OOM at a larger move log and the resulting ban stuck to every
    # max_moves).
    dev = jax.devices()[0]
    kind = getattr(dev, "device_kind", dev.platform)
    mode = "aa" if all_allowed else "restricted"
    lead = "lead" if allow_leader else "nolead"
    return f"{kind}|{P}x{B}x{R}|mm{max_moves}|{mode}|{lead}"


def _gate_load() -> dict:
    path = _gate_cache_path()
    if not _gate_mem and path:
        import json
        import os

        try:
            if os.path.exists(path):
                with open(path) as f:
                    _gate_mem.update(json.load(f))
        except Exception:
            pass  # unreadable cache = empty cache
    return _gate_mem


def _gate_record(key: str, fits: bool) -> None:
    # the verdict is observability gold: it decides engine routing for
    # every future invocation at this shape on this device kind
    obs.metrics.event("pallas_gate", key=key, fits=bool(fits))
    obs.metrics.gauge(f"pallas_gate.{key}", bool(fits))
    _gate_load()[key] = bool(fits)
    path = _gate_cache_path()
    if path:
        import json
        import os

        try:
            # re-read and MERGE before writing: a long-running process
            # holding a stale in-memory copy must not clobber verdicts
            # other processes persisted since (each verdict costs a
            # compile probe or a dispatch OOM to rediscover)
            if os.path.exists(path):
                with open(path) as f:
                    on_disk = json.load(f)
                for k, v in on_disk.items():
                    _gate_mem.setdefault(k, v)
            with open(path, "w") as f:
                json.dump(_gate_mem, f, sort_keys=True)
        except Exception:
            pass


def _is_vmem_oom(exc: BaseException) -> bool:
    """Broad OOM match — the ONE-SHOT fallback trigger. Deliberately
    loose (HBM exhaustion, device contention, allocator noise all
    qualify): any of these makes falling back to the XLA session for
    this chunk the right move. NOT sufficient for a persistent verdict —
    see :func:`_is_scoped_vmem_oom` (ADVICE r5: a transient HBM OOM must
    not permanently ban a shape that fits the kernel's VMEM budget)."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    return (
        "vmem" in msg
        or "resource_exhausted" in msg
        or "resource exhausted" in msg
        or "out of memory" in msg
    )


def _is_scoped_vmem_oom(exc: BaseException) -> bool:
    """Narrow match — the PERSISTENT-verdict trigger: only the Mosaic /
    scoped-VMEM signatures that mean the kernel itself exceeds this
    chip's VMEM budget (a deterministic property of the (shape, program)
    pair, safe to cache forever for this device kind)."""
    msg = f"{type(exc).__name__}: {exc}".lower()
    return "vmem" in msg or "mosaic" in msg


def pallas_session_fits(
    dp: Any, dtype: Any, all_allowed: bool, allow_leader: bool,
    max_moves: int,
) -> bool:
    """Does the whole-session kernel fit THIS device at ``dp``'s buckets
    with a ``max_moves``-sized move log?

    Decision ladder (r4 verdict #7 — the gate must derive from the
    device, not from one chip's literals):

    1. a cached verdict for (device kind, P, B, R, max_moves, mode)
       wins;
    2. if the cell-count prior ADMITS the shape, admit — a wrong admit
       self-corrects: a scoped-VMEM/Mosaic OOM at dispatch is caught by
       ``plan``, recorded as a lasting "doesn't fit" verdict for this
       exact key, and the chunk falls back to the XLA session (broader
       OOMs — transient HBM exhaustion, device contention — fall back
       for the chunk WITHOUT a lasting ban, ADVICE r5);
    3. if the prior REJECTS, run a one-shot compile probe of the kernel
       at the real bucketed shapes INCLUDING the real ``max_moves``
       (lower+compile, no execution — a fixed probe size previously let
       a probe-admitted shape OOM at a larger move-log buffer): a
       bigger-VMEM chip earns its larger ceiling, a Mosaic/scoped-VMEM
       error confirms the rejection. Only those two outcomes are cached
       persistently (an unrelated probe failure yields a no-verdict
       False; the successful probe's executable lands in the jax
       compile cache, so the real dispatch does not recompile).
    """
    P, R = dp.replicas.shape
    B = dp.bvalid.shape[0]
    key = _gate_key(P, B, R, all_allowed, allow_leader, max_moves)
    cache = _gate_load()
    if key in cache:
        return cache[key]
    prior = P * max(B, 128) <= (
        PALLAS_VMEM_CELLS if all_allowed else PALLAS_VMEM_CELLS_RESTRICTED
    )
    if prior:
        return True
    if jax.devices()[0].platform.lower() not in ("tpu", "axon"):
        return False  # no hardware to probe; the prior's no stands
    from kafkabalancer_tpu.solvers.pallas_session import pallas_session

    f32 = kernel_dtype()
    sds = jax.ShapeDtypeStruct
    args = (
        sds((B,), f32),                                 # loads
        sds((P, R), jnp.int32),                         # replicas
        None,                                           # member (unused)
        None if all_allowed else sds((P, B), bool),     # allowed
        sds((P,), f32),                                 # weights
        sds((P,), jnp.int32),                           # nrep_cur
        sds((P,), jnp.int32),                           # nrep_tgt
        sds((P,), f32),                                 # ncons
        sds((P,), bool),                                # pvalid
        sds((B,), bool),                                # always_valid
        sds((B,), bool),                                # universe_valid
        sds((), jnp.int32),                             # min_replicas
        sds((), f32),                                   # min_unbalance
        sds((), jnp.int32),                             # budget
        sds((), jnp.int32),                             # batch
        sds((), f32),                                   # churn_gate
    )
    try:
        obs.metrics.count("solver.gate_probes")
        with obs.span("solver.gate_probe", key=key):
            jax.jit(  # jaxlint: disable=R2 — compile probe; statics bound via partial
                partial(
                    pallas_session,
                    max_moves=max_moves,
                    allow_leader=allow_leader,
                    interpret=False,
                    all_allowed=all_allowed,
                )
            ).lower(*args).compile()
        fits = True
    except Exception as exc:
        if not _is_scoped_vmem_oom(exc):
            # unrelated/transient failure (including a broad HBM OOM):
            # trust the prior for this call, persist NO verdict
            return False
        fits = False
    _gate_record(key, fits)
    return fits


@partial(
    jax.jit,
    static_argnames=("max_moves", "allow_leader", "batch", "n_topics"),
)
def session(
    loads: jax.Array,
    replicas: jax.Array,
    member: jax.Array,
    allowed: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: Any,
    budget: jax.Array,
    churn_gate: Any = DEFAULT_CHURN_GATE,
    topic_id: Optional[jax.Array] = None,
    lam: Any = None,
    *,
    max_moves: int,
    allow_leader: bool,
    batch: int = 1,
    n_topics: int = 0,
) -> Tuple[jax.Array, ...]:
    """Run up to ``min(budget, max_moves)`` accepted moves on device.

    ``max_moves`` (static) sizes the move-log buffers and is bucketed by the
    caller so XLA compiles once per bucket; ``budget`` (dynamic) is the
    actual reassignment budget.

    ``batch > 1`` enables the fast commit mode: per device iteration, up
    to ``batch`` partition-distinct improving moves from the candidate
    pool (per-target winners ∪ hot/cold broker-pair winners, see
    ``body_batch``) are applied together in gain order. Commits MAY share
    brokers: :func:`prefix_accept` computes each move's source/target
    load *as of its turn* via per-broker net prefix sums, so every
    committed move improves the objective by precisely its exact
    sequential delta (total load — and thus the average — is
    move-invariant). The trajectory differs from strict one-at-a-time
    greedy (and leader/follower candidates pool together instead of the
    MoveLeaders-first precedence), so ``batch=1`` remains the
    pipeline-parity mode; batching is the throughput mode for
    convergence-scale sessions, cutting device iterations ~``batch``-fold.

    Broker-table membership is dynamic, like the reference: each iteration
    the table is the brokers currently holding a replica plus the
    ``always_valid`` configured set (``cfg.Brokers`` zero-fill,
    steps.go:150-155) — a broker fully drained mid-session drops out of the
    objective's average divisor exactly as it vanishes from
    ``getBrokerLoad``'s map (utils.go:92-105) on the reference's next
    ``Balance`` call. ``universe_valid`` masks padded broker columns.

    Returns ``(replicas, loads, n_moves, move_p, move_slot, move_src,
    move_tgt, final_su)`` where the ``move_*`` arrays log the accepted
    moves in order (dense indices; entries past ``n_moves`` are -1).
    """
    P, R = replicas.shape
    B = loads.shape[0]
    dtype = loads.dtype

    # one extra trash slot at index max_moves: the batched commit path
    # routes rejected candidates' scatter-writes there (conflict-free)
    move_p = jnp.full(max_moves + 1, -1, jnp.int32)
    move_slot = jnp.full(max_moves + 1, -1, jnp.int32)
    move_src = jnp.full(max_moves + 1, -1, jnp.int32)
    move_tgt = jnp.full(max_moves + 1, -1, jnp.int32)

    slot_iota = jnp.arange(R, dtype=jnp.int32)[None, :]
    # per-broker replica counts: observed-broker tracking in O(1) per move
    # instead of an O(P*B) reduction per iteration
    bcount0 = jnp.sum(
        (member & pvalid[:, None]).astype(jnp.int32), axis=0,
        dtype=jnp.int32,
    )
    # anti-colocation mode (n_topics > 0): per-(topic, broker) replica
    # counts ride as incremental state exactly like beam's (solvers/
    # beam.py); built once from the pad-masked membership, updated per
    # commit. The combined objective is u + lam*sum(max(0, c-1)).
    if n_topics:
        if batch <= 1:
            raise ValueError(
                "the anti-colocation session requires batch > 1 "
                "(the pooled batched selection)"
            )
        counts0 = (
            jnp.zeros((n_topics, B), dtype)
            .at[topic_id]
            .add((member & pvalid[:, None]).astype(dtype))
        )
    else:
        counts0 = jnp.zeros((1, 1), dtype)

    def cond(state: Tuple[jax.Array, ...]) -> jax.Array:
        n, done = state[4], state[5]
        return (~done) & (n < budget) & (n < max_moves)

    def _applied_delta(p: jax.Array, slot: jax.Array) -> jax.Array:
        # applied load delta: the leader premium travels with slot 0
        # (utils.go:96-101) even though scoring used the plain weight
        return jnp.where(
            slot == 0,
            weights[p] * (nrep_cur[p].astype(dtype) + ncons[p]),
            weights[p],
        )

    def _scored(
        loads: jax.Array,
        replicas: jax.Array,
        member: jax.Array,
        bcount: jax.Array,
    ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        # (load, ID) target ordering for reference-style tie-breaks
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid, dtype=jnp.int32).astype(dtype)
        _, perm, rank_of = cost.rank_brokers(loads, bvalid)
        u, su = cost.move_candidate_scores(
            loads, replicas, allowed[:, perm], member[:, perm], bvalid,
            bvalid[perm], perm, rank_of, weights, nrep_cur, nrep_tgt,
            pvalid, nb, min_replicas,
        )
        return u, su, perm

    def body_batch(state: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        (loads, replicas, member, bcount, n, done, mp, mslot, msrc, mtgt,
         counts) = state

        # Candidate pool = per-TARGET winners ∪ hot/cold broker-rank PAIR
        # winners. Per-target selection alone degenerates: the global best
        # source partition wins nearly every target's argmin, the partition
        # claim rejects all but one, and a "batched" pass commits ~1-3
        # moves (measured: 2.3/pass over the first 5k moves at 131k x 256).
        # The pair winners (ops/cost.py paired_best — hottest broker paired
        # with coldest, best partition per pair) supply distinct partitions,
        # sources, and targets by construction, and the per-target winners
        # keep the exact termination criterion: the pool's rank-0 candidate
        # IS the globally best single move.
        #
        # Leader moves are scored with their TRUE applied delta (the
        # reference's plain-weight under-modelling oscillates under batched
        # commits).
        bvalid = (always_valid | (bcount > 0)) & universe_valid
        nb = jnp.sum(bvalid, dtype=jnp.int32).astype(dtype)
        avg = jnp.sum(jnp.where(bvalid, loads, 0.0)) / nb
        c_rows = counts[topic_id] if n_topics else None
        su, vals_t, p_t, slot_t = cost.factored_target_best(
            loads, replicas, allowed, member, bvalid, weights, nrep_cur,
            nrep_tgt, ncons, pvalid, nb, min_replicas,
            allow_leader=allow_leader, c_rows=c_rows, lam=lam,
        )
        t_axis = jnp.arange(B, dtype=jnp.int32)
        s_t = replicas[p_t, slot_t].astype(jnp.int32)
        vals_p, p_p, slot_p, s_p, t_p, _live = cost.paired_best(
            loads, replicas, allowed, member, bvalid, weights, nrep_cur,
            nrep_tgt, ncons, pvalid, min_replicas,
            allow_leader=allow_leader, c_rows=c_rows, lam=lam,
        )

        # the union pool, K = B + B//2 candidates
        vals = jnp.concatenate([vals_t, vals_p])
        p = jnp.concatenate([p_t, p_p])
        slot = jnp.concatenate([slot_t, slot_p])
        s_ = jnp.concatenate([s_t, s_p])
        t = jnp.concatenate([t_axis, t_p])
        w_k = _applied_delta(p, slot)

        if n_topics:
            # per-candidate colocation constants from pass-START counts;
            # the (topic, broker) first-claims inside prefix_accept keep
            # them exact for every accepted move
            tid_k = topic_id[p]
            sub_s, _ = cost.colo_terms(counts[tid_k, s_], lam)
            _, add_t = cost.colo_terms(counts[tid_k, t], lam)
            colo_d = add_t - sub_s
        else:
            tid_k = colo_d = None
        ok, pos, cnt = prefix_accept(
            vals, p, s_, t, w_k, loads, avg, su,
            min_unbalance, churn_gate, n, batch, budget, max_moves,
            topic=tid_k, colo_d=colo_d,
        )
        oki = ok.astype(jnp.int32)

        delta = w_k * oki.astype(dtype)
        loads = loads.at[s_].add(-delta).at[t].add(delta)
        # rejected candidates contribute zero-adds / toggle-counts of zero,
        # so duplicate indices among them cannot race with the commits
        replicas = replicas.at[p, slot].add(((t - s_) * oki).astype(replicas.dtype))
        toggles = (
            jnp.zeros((P, B), jnp.int32).at[p, s_].add(oki).at[p, t].add(oki)
        )
        member = member ^ (toggles > 0)
        bcount = bcount.at[s_].add(-oki).at[t].add(oki)

        if n_topics:
            okd = oki.astype(dtype)
            counts = (
                counts.at[tid_k, s_].add(-okd).at[tid_k, t].add(okd)
            )

        logpos = jnp.where(ok, pos, max_moves)  # trash slot for rejected
        mp = mp.at[logpos].set(jnp.where(ok, p, -1))
        mslot = mslot.at[logpos].set(jnp.where(ok, slot, -1))
        msrc = msrc.at[logpos].set(jnp.where(ok, s_, -1))
        mtgt = mtgt.at[logpos].set(jnp.where(ok, t, -1))

        n = n + cnt
        return (
            loads, replicas, member, bcount, n, cnt == 0, mp, mslot, msrc,
            mtgt, counts,
        )

    def body(state: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
        (loads, replicas, member, bcount, n, done, mp, mslot, msrc, mtgt,
         counts) = state
        u, su, perm = _scored(loads, replicas, member, bcount)

        def best(mask_slots: jax.Array) -> Tuple[jax.Array, jax.Array]:
            flat = jnp.where(mask_slots[None, :, None], u, jnp.inf).reshape(-1)
            i = jnp.argmin(flat)
            return flat[i], i

        fol_u, fol_i = best(slot_iota[0] >= 1)
        if allow_leader:
            lead_u, lead_i = best(slot_iota[0] == 0)
            accept_lead = (lead_u < su - min_unbalance) & (lead_u < su)
        else:
            lead_i = jnp.zeros_like(fol_i)
            accept_lead = jnp.bool_(False)
        accept_fol = (fol_u < su - min_unbalance) & (fol_u < su)

        accept = accept_lead | accept_fol
        chosen = jnp.where(accept_lead, lead_i, fol_i)

        p, rem = jnp.divmod(chosen, R * B)
        slot, t_rank = jnp.divmod(rem, B)
        t_dense = perm[t_rank]
        s_dense = replicas[p, slot]
        delta = _applied_delta(p, slot)

        def apply(args: Tuple[jax.Array, ...]) -> Tuple[jax.Array, ...]:
            loads, replicas, member, bcount, mp, mslot, msrc, mtgt = args
            loads = loads.at[s_dense].add(-delta).at[t_dense].add(delta)
            replicas = replicas.at[p, slot].set(t_dense.astype(replicas.dtype))
            member = member.at[p, s_dense].set(False).at[p, t_dense].set(True)
            bcount = bcount.at[s_dense].add(-1).at[t_dense].add(1)
            mp = mp.at[n].set(p.astype(jnp.int32))
            mslot = mslot.at[n].set(slot.astype(jnp.int32))
            msrc = msrc.at[n].set(s_dense.astype(jnp.int32))
            mtgt = mtgt.at[n].set(t_dense.astype(jnp.int32))
            return loads, replicas, member, bcount, mp, mslot, msrc, mtgt

        loads, replicas, member, bcount, mp, mslot, msrc, mtgt = lax.cond(
            accept,
            apply,
            lambda args: args,
            (loads, replicas, member, bcount, mp, mslot, msrc, mtgt),
        )
        n = n + accept.astype(n.dtype)
        return (
            loads, replicas, member, bcount, n, ~accept, mp, mslot, msrc,
            mtgt, counts,
        )

    state = (
        loads,
        replicas,
        member,
        bcount0,
        jnp.int32(0),
        jnp.bool_(False),
        move_p,
        move_slot,
        move_src,
        move_tgt,
        counts0,
    )
    (loads, replicas, member, bcount, n, _done, mp, mslot, msrc, mtgt,
     _counts) = (
        lax.while_loop(cond, body_batch if batch > 1 else body, state)
    )
    bvalid = (always_valid | (bcount > 0)) & universe_valid
    final_su = cost.unbalance(
        loads, bvalid, jnp.sum(bvalid, dtype=jnp.int32).astype(dtype)
    )
    # drop the batched path's trash slot
    return (
        replicas, loads, n,
        mp[:max_moves], mslot[:max_moves], msrc[:max_moves], mtgt[:max_moves],
        final_su,
    )


def _cfg_broker_mask(dp: Any, cfg: RebalanceConfig) -> "np.ndarray":
    """Dense mask of the configured always-in-table brokers
    (``cfg.Brokers`` zero-fill, steps.go:150-155)."""
    mask = np.zeros(dp.bvalid.shape[0], dtype=bool)
    for bid in cfg.brokers or []:
        mask[dp.broker_index(bid)] = True
    return mask


@partial(jax.jit, static_argnames=("dtype", "all_allowed"))
def _device_prep(
    replicas: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    ncons: jax.Array,
    allowed: Optional[jax.Array],
    bvalid: jax.Array,
    ew: Optional[jax.Array],
    *,
    dtype: Any,
    all_allowed: bool,
) -> Tuple[Any, ...]:
    """All per-chunk device input preparation as ONE compiled program.

    A cold process pays a full relay round trip per jitted program it
    dispatches on a remote-attached TPU (~0.1-0.15 s each even on a
    persistent-cache hit); eagerly building the session inputs (dtype
    casts, the broker-load scatter, the all-allowed broadcast, the polish
    entry-table cast) dispatched ~25 tiny programs and dominated cold CLI
    latency. ``allowed``/``ew`` may be None (all-allowed mode / no polish
    phase). Returns ``(loads, weights, ncons, allowed_dev, ew)``."""
    w = weights.astype(dtype)
    nc = ncons.astype(dtype)
    B = bvalid.shape[0]
    loads = cost.broker_loads(replicas, w, nrep_cur, nc, B)
    if all_allowed:
        # the [P, B] allowed matrix is the broker validity row broadcast —
        # built on device from the [B] mask instead of transferred
        allowed_dev = jnp.broadcast_to(
            bvalid[None, :], (replicas.shape[0], B)
        )
    else:
        allowed_dev = allowed
    ew_c = None if ew is None else ew.astype(dtype)
    return loads, w, nc, allowed_dev, ew_c


@partial(jax.jit, static_argnames=())
def _pack_log(
    mp: jax.Array, mslot: jax.Array, mtgt: jax.Array, n: jax.Array
) -> jax.Array:
    """Device-side packing of the move log + count into one transfer."""
    return jnp.concatenate([mp, mslot, mtgt, n.astype(jnp.int32).reshape(1)])


def member_from(
    replicas: jax.Array, nrep_cur: jax.Array, pvalid: jax.Array, B: int
) -> jax.Array:
    """Recompute the ``[P, B]`` membership mask from the replica matrix
    on device (skips transferring the largest boolean session input)."""
    R = replicas.shape[1]
    slot = jnp.arange(R, dtype=jnp.int32)[None, :]
    valid = (slot < nrep_cur[:, None]) & pvalid[:, None]
    onehot = replicas[:, :, None] == jnp.arange(B, dtype=replicas.dtype)
    return jnp.any(onehot & valid[:, :, None], axis=1)


@partial(
    jax.jit,
    static_argnames=(
        "dtype", "all_allowed", "max_moves", "allow_leader", "batch",
        "engine", "polish", "leader", "n_topics",
    ),
)
def session_packed(
    replicas: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    ncons: jax.Array,
    allowed: Optional[jax.Array],
    pvalid: jax.Array,
    always_valid: jax.Array,
    universe_valid: jax.Array,
    min_replicas: jax.Array,
    min_unbalance: Any,
    budget: jax.Array,
    churn_gate: Any,
    ew: Optional[jax.Array],
    ep: Optional[jax.Array],
    er: Optional[jax.Array],
    evalid: Optional[jax.Array],
    tid: Optional[jax.Array] = None,
    lam: Any = None,
    *,
    dtype: Any,
    all_allowed: bool,
    max_moves: int,
    allow_leader: bool,
    batch: int,
    engine: str = "xla",
    polish: bool = False,
    leader: bool = False,
    n_topics: int = 0,
) -> jax.Array:
    """The ENTIRE per-chunk device program as ONE dispatch.

    A cold process on a remote-attached TPU pays a full relay round trip
    per jitted program (~0.1-0.15 s each even on persistent-cache hits);
    splitting prep / session / log-packing across programs dominated cold
    CLI latency. This entry fuses all of it: dtype casts, the broker-load
    scatter (utils.go:92-105), the all-allowed broadcast, membership
    recomputation, the session itself (move / polish-alternation /
    rebalance-leaders), and the move-log packing — raw host arrays in,
    one packed int32 log out.

    ``allowed``/``ew``/``ep``/``er``/``evalid`` may be None (all-allowed
    mode / no polish phase). Returns ``packed`` =
    ``[move_p | move_slot | move_tgt | n]`` (log length ``2 * max_moves``
    when ``polish`` else ``max_moves``).
    """
    w = weights.astype(dtype)
    nc = ncons.astype(dtype)
    B = universe_valid.shape[0]
    loads = cost.broker_loads(replicas, w, nrep_cur, nc, B)
    if all_allowed:
        allowed_dev = jnp.broadcast_to(
            universe_valid[None, :], (replicas.shape[0], B)
        )
    else:
        allowed_dev = allowed
    mu = min_unbalance.astype(dtype)
    cg = churn_gate.astype(dtype)

    if leader:
        from kafkabalancer_tpu.solvers.leader import leader_session

        member = member_from(replicas, nrep_cur, pvalid, B)
        _replicas, _loads, n, mp, mslot, mtgt = leader_session(
            loads, replicas, member, allowed_dev, w, nrep_cur, nrep_tgt,
            nc, pvalid, always_valid, universe_valid, min_replicas, mu,
            budget, max_moves=max_moves, allow_leader=allow_leader,
            batch=batch,
        )
    elif polish:
        from kafkabalancer_tpu.solvers.polish import converge_session

        return converge_session(
            loads, replicas, allowed_dev, w, nrep_cur, nrep_tgt, nc,
            pvalid, always_valid, universe_valid, min_replicas, mu,
            budget, ew if ew is None else ew.astype(dtype), ep, er,
            evalid, cg, tid, None if lam is None else lam.astype(dtype),
            max_moves=max_moves, allow_leader=allow_leader,
            batch=batch, engine=engine, all_allowed=all_allowed,
            n_topics=n_topics,
        )
    elif engine in ("pallas", "pallas-interpret"):
        from kafkabalancer_tpu.solvers.pallas_session import pallas_session

        _replicas, _loads, n, mp, mslot, _msrc, mtgt = pallas_session(
            loads, replicas, None, allowed_dev, w, nrep_cur, nrep_tgt,
            nc, pvalid, always_valid, universe_valid, min_replicas, mu,
            budget, jnp.int32(max(1, batch)), cg.astype(kernel_dtype()),
            max_moves=max_moves, allow_leader=allow_leader,
            interpret=(engine == "pallas-interpret"),
            all_allowed=all_allowed,
        )
    else:
        member = member_from(replicas, nrep_cur, pvalid, B)
        _replicas, _loads, n, mp, mslot, _msrc, mtgt, _su = session(
            loads, replicas, member, allowed_dev, w, nrep_cur, nrep_tgt,
            nc, pvalid, always_valid, universe_valid, min_replicas, mu,
            budget, cg, tid, None if lam is None else lam.astype(dtype),
            max_moves=max_moves, allow_leader=allow_leader,
            batch=batch, n_topics=n_topics,
        )
    return _pack_log(mp, mslot, mtgt, n)


def packed_call(
    dp: Any,
    cfg: RebalanceConfig,
    chunk: int,
    dtype: Any,
    batch: int,
    engine: str,
    polish: bool,
    leader: bool,
    all_allowed: bool,
    churn_gate: float,
    ew: Any = None,
    ep: Any = None,
    er: Any = None,
    evalid: Any = None,
    tid: Any = None,
    lam: Any = None,
    n_topics: int = 0,
) -> Tuple[Tuple[Any, ...], dict]:
    """Assemble :func:`session_packed`'s ``(args, statics)`` from a
    DensePlan — shared by :func:`_dispatch_chunk` (the live dispatch)
    and ``kafkabalancer_tpu.prewarm`` (which AOT-compiles the same
    signatures for the shape grid without dispatching), so the prewarmed
    store keys cannot drift from what a real invocation asks for.

    Args stay raw numpy (jit transfers them at dispatch) so the AOT
    executable store (ops/aot.py) can key, load, and call the stored
    executable with exactly the objects the jit path would see: on an AOT
    hit a fresh process skips tracing, lowering, the pallas import, and
    the compile-cache machinery entirely.
    """
    npdt = np.dtype(dtype)
    args = (
        dp.replicas,
        dp.weights,
        dp.nrep_cur,
        dp.nrep_tgt,
        dp.ncons,
        None if all_allowed else dp.allowed,
        dp.pvalid,
        _cfg_broker_mask(dp, cfg),
        dp.bvalid,
        np.int32(cfg.min_replicas_for_rebalancing),
        np.asarray(cfg.min_unbalance, npdt),
        np.int32(chunk),
        np.asarray(churn_gate, npdt),
        ew,
        ep,
        er,
        evalid,
        tid,
        None if lam is None else np.asarray(lam, npdt),
    )
    statics = dict(
        dtype=dtype,
        all_allowed=all_allowed,
        max_moves=next_bucket(chunk, 128),
        allow_leader=cfg.allow_leader_rebalancing,
        batch=max(1, batch),
        engine=engine,
        polish=polish,
        leader=leader,
        n_topics=n_topics,
    )
    return args, statics


# position of the dynamic move budget (``np.int32(chunk)``) in
# :func:`packed_call`'s args tuple — the ONE dynamic input that turns a
# whole session instance into a no-op when zeroed (the while_loop's
# ``n < budget`` condition fails at iteration 0). The serve batcher's
# variable-K padding keys off it; keep in sync with the tuple above.
PACKED_BUDGET_ARG = 11


def pad_instance_args(args: Tuple) -> Tuple:
    """A NO-OP padding instance for the variable-K batched dispatch:
    the same program signature (every leaf's shape/dtype identical, so
    it stacks into the same compiled executable) with the dynamic move
    budget zeroed — the padded slot's session while_loop exits at
    iteration 0 and its move log is discarded by the batcher. This is
    what lets one compiled :func:`session_packed_batched` executable per
    padding bucket serve ANY occupancy: live slots keep their own args
    (bit-identical per-instance logs, as ever), dead slots replay this."""
    padded = list(args)
    padded[PACKED_BUDGET_ARG] = np.zeros_like(
        np.asarray(args[PACKED_BUDGET_ARG])
    )
    return tuple(padded)


# --- serve batching seam ---------------------------------------------------
# A multi-lane daemon (serve/lanes.py) fuses K independent same-bucket
# requests into ONE padded batched device dispatch. The fusion point is
# here: each request's thread installs its batcher (the continuous
# batcher, or the legacy one-shot MicrobatchGroup), and _dispatch_chunk
# offers the batcher its (args, statics) at EVERY chunk round — the
# iteration-boundary offer continuous batching re-forms the batch at: a
# request admitted mid-flight fuses its chunk 1 with its peers' chunk
# i+1, and a converged member's departure shrinks the next round instead
# of holding the batch to collective completion. Thread-local so the
# stateless CLI and single-lane daemon never see it.
_mb_tls = threading.local()


def set_microbatcher(mb: "Optional[Any]") -> None:
    """Install (or, with None, clear) THIS thread's microbatch group —
    an object with ``dispatch(args, statics) -> Optional[np.ndarray]``
    returning this caller's packed move log, or None to run solo."""
    _mb_tls.mb = mb


def microbatcher() -> "Optional[Any]":
    return getattr(_mb_tls, "mb", None)


@partial(
    jax.jit,
    static_argnames=(
        "dtype", "all_allowed", "max_moves", "allow_leader", "batch",
        "engine", "polish", "leader", "n_topics",
    ),
)
def session_packed_batched(
    *args: Any,
    dtype: Any,
    all_allowed: bool,
    max_moves: int,
    allow_leader: bool,
    batch: int,
    engine: str = "xla",
    polish: bool = False,
    leader: bool = False,
    n_topics: int = 0,
) -> jax.Array:
    """K independent same-signature instances as ONE device dispatch.

    ``args`` is :func:`session_packed`'s argument tuple with every array
    carrying a leading instance axis (the sweep's per-scenario stacking
    layout, ``parallel.sweep.stack_instances``) and ``None`` positions
    passed through. ``lax.map`` runs the instances sequentially on
    device — one dispatch, one transfer each way, K move logs — and each
    instance traces the IDENTICAL ``session_packed`` subprogram, so per
    instance the packed log is bit-identical to a solo dispatch (pinned
    by the serve differential tests). Returns ``[K, L]`` packed logs.

    VARIABLE-K: the serve batcher pads the instance axis up to a small
    set of padding buckets (serve/lanes.py ``PAD_BUCKETS``) with no-op
    instances (:func:`pad_instance_args` — budget zeroed, loop exits at
    iteration 0), so one compiled executable per bucket serves any
    occupancy instead of one per exact K; live slots are unaffected.
    """
    def one(xs: Tuple) -> Any:
        return session_packed(
            *xs, dtype=dtype, all_allowed=all_allowed, max_moves=max_moves,
            allow_leader=allow_leader, batch=batch, engine=engine,
            polish=polish, leader=leader, n_topics=n_topics,
        )

    return lax.map(one, args)


def _dispatch_chunk(
    dp: Any, cfg: RebalanceConfig, chunk: int, *a: Any, **kw: Any
) -> "np.ndarray":
    """One chunk through the AOT dispatch policy (see :func:`packed_call`
    for the argument assembly and the raw-numpy contract). A thread with
    a microbatch group installed offers the dispatch for cross-request
    fusion first; a declined offer (or any group failure) runs solo.
    A SPECULATIVE daemon run (serve/speculate.py) checks its preemption
    flag here, once per chunk round — real traffic aborts idle
    plan-ahead work before the next device dispatch starts."""
    from kafkabalancer_tpu.ops import aot
    from kafkabalancer_tpu.serve.speculate import maybe_abort_dispatch

    maybe_abort_dispatch()

    args, statics = packed_call(dp, cfg, chunk, *a, **kw)
    obs.metrics.count("solver.chunks")
    mb = microbatcher()
    if mb is not None:
        fused = mb.dispatch(args, statics)
        if fused is not None:
            obs.metrics.count("solver.microbatched_chunks")
            return np.asarray(fused)
    with obs.span(
        "solver.dispatch_chunk",
        engine=statics["engine"], polish=statics["polish"],
        leader=statics["leader"], max_moves=statics["max_moves"],
    ):
        return np.asarray(
            aot.call_or_compile(
                "session_packed", session_packed, args, statics
            )
        )


# the one shared all-allowed detection (ops/tensorize.py), re-exported
# for the existing plan/_leader_plan/shard_session call sites
from kafkabalancer_tpu.ops.tensorize import all_allowed_of  # noqa: E402


def _dev_cached_asarray(
    cache: Optional[dict], name: str, arr: Any, upload: Any = None
) -> jax.Array:
    """``jnp.asarray`` behind a session-scoped digest-keyed reuse cache.

    A multi-chunk session re-tensorizes between chunks, producing FRESH
    numpy arrays whose content is mostly identical (weights, allowed
    masks and broker validity never change under moves), and a plain
    ``jnp.asarray`` re-uploads every one of them per chunk. With a cache
    dict (one per session), an array whose content digest matches the
    previous chunk's returns the already-device-resident buffer — jit
    then skips the transfer entirely. Digest-keyed rather than
    identity-keyed because the arrays ARE new objects each chunk; a
    changed array (replicas after commits) simply misses and replaces
    its slot, so staleness is impossible by construction.

    ``cache`` may also be a SHARED residency pool
    (``serve.residency.ResidencyPool`` — anything with a ``lookup``
    method): the key then drops the slot name and becomes pure content
    (shape, dtype, digest), so identical arrays are shared ACROSS
    sessions, requests and slots instead of within one session's slot —
    the serve lanes' cross-request generalization of this cache.

    ``upload`` (default ``jnp.asarray``) is the device-materialization
    seam: the scale tier reuses this exact cache discipline for
    mesh-global uploads (``parallel.shard_session._mesh_cached_put``
    passes ``shard_put``/``replicate_put`` closures) instead of
    maintaining a second digest cache."""
    if arr is None:
        return None
    up = jnp.asarray if upload is None else upload
    if cache is None:
        return up(arr)
    a = np.asarray(arr)
    digest = hashlib.md5(np.ascontiguousarray(a).tobytes()).digest()
    if hasattr(cache, "lookup"):
        pkey = (a.shape, a.dtype.str, digest)
        pooled = cache.lookup(pkey)
        if pooled is not None:
            obs.metrics.count("solver.dev_cache_hits")
            return pooled
        dev = up(a)
        cache.put(pkey, dev)
        return dev
    key = (name, a.shape, a.dtype.str)
    hit = cache.get(key)
    if hit is not None and hit[0] == digest:
        obs.metrics.count("solver.dev_cache_hits")
        return hit[1]
    dev = up(a)
    cache[key] = (digest, dev)
    return dev


def _prep_from_dp(
    dp: Any,
    dtype: Any,
    all_allowed: Optional[bool] = None,
    ew: Any = None,
    dev_cache: Optional[dict] = None,
) -> Tuple[bool, Tuple[Any, ...]]:
    """:func:`_device_prep` from a DensePlan — the one call site shared by
    ``plan``, ``_leader_plan`` and ``parallel.shard_session.plan_sharded``.

    ``all_allowed`` (computed from ``dp`` when None) skips transferring
    the ``[P, B]`` allowed matrix — the largest session input — when it
    is just the broker-validity row broadcast (the default FillDefaults
    outcome). ``dev_cache`` (a per-session dict) reuses already-device-
    resident buffers across chunks instead of re-uploading identical
    content every re-tensorize (see :func:`_dev_cached_asarray`). When
    no explicit cache is passed and the calling thread has a serve
    residency pool installed (a lane's request thread,
    ``ops.aot.set_staging_cache``), the pool stands in — the session's
    arrays then share the lane's cross-request device residency. An
    EXPLICIT dict keeps its session-private semantics (plan_sharded's
    mesh-sharded arrays must not mix into a single-device pool).
    Returns ``(all_allowed, (loads, weights, ncons, allowed_dev,
    ew_dev))``."""
    if dev_cache is None:
        from kafkabalancer_tpu.ops import aot

        pool = aot.staging_cache()
        if hasattr(pool, "lookup"):
            dev_cache = pool
    if all_allowed is None:
        all_allowed = all_allowed_of(dp)
    return all_allowed, _device_prep(
        _dev_cached_asarray(dev_cache, "replicas", dp.replicas),
        _dev_cached_asarray(dev_cache, "weights", dp.weights),
        _dev_cached_asarray(dev_cache, "nrep_cur", dp.nrep_cur),
        _dev_cached_asarray(dev_cache, "ncons", dp.ncons),
        None if all_allowed
        else _dev_cached_asarray(dev_cache, "allowed", dp.allowed),
        _dev_cached_asarray(dev_cache, "bvalid", dp.bvalid),
        None if ew is None else _dev_cached_asarray(dev_cache, "ew", ew),
        dtype=dtype,
        all_allowed=all_allowed,
    )


def _superseded_mask(mp: Any, mslot: Any) -> "np.ndarray":
    """``keep`` mask collapsing consecutive same-slot runs per partition.

    A batched session can re-move a (partition, slot) cell a later
    iteration already overwrites; each emitted entry is real Kafka data
    movement (kafkabalancer.go:177-221 — the deployment loop executes
    every move), so the intermediate write is pure churn. Dropping is
    exact ONLY within a consecutive run of plain moves on the same
    (partition, slot): nothing reads the partition's state in between
    (moves on other partitions never do; a later move on this partition
    breaks the run). Leadership swaps (slot == SWAP_SLOT) read positions
    via ``replicas.index`` — they are never dropped and break runs.
    """
    n = len(mp)
    keep = np.ones(n, dtype=bool)
    last_by_p: dict = {}
    for i in range(n):
        p, s = int(mp[i]), int(mslot[i])
        prev = last_by_p.get(p)
        if prev is not None and s >= 0 and prev[1] == s:
            keep[prev[0]] = False
        last_by_p[p] = (i, s)
    return keep


def _decode_packed(
    packed: "np.ndarray", dp: Any, opl: PartitionList,
    drop_superseded: bool = False,
) -> int:
    """Replay a packed ``[move_p | move_slot | move_tgt | n]`` move log
    onto the live partitions, appending each to ``opl`` in move order
    (the CLI main-loop output contract, kafkabalancer.go:177-221).

    A slot of ``leader.SWAP_SLOT`` is a leadership exchange (``replacepl``
    swap branch, utils.go:181-188): the target broker — already a
    follower — trades positions with the leader. Returns the move count
    CONSUMED from the session budget (the raw commit count — the caller's
    chunk accounting must see device-side progress even when
    ``drop_superseded`` elides emissions; see :func:`_superseded_mask`).
    """
    from kafkabalancer_tpu.solvers.leader import SWAP_SLOT

    n = int(packed[-1])
    ml = (packed.shape[0] - 1) // 3
    mp = packed[:n]
    mslot = packed[ml : ml + n]
    mtgt = packed[2 * ml : 2 * ml + n]
    keep = _superseded_mask(mp, mslot) if drop_superseded else None
    rec = convergence.recorder()  # -explain provenance (thread-local)
    tap = convergence.mutation_tap()  # resident-session raw-row shadow
    emitted = 0
    for i in range(n):
        part = dp.partitions[int(mp[i])]
        slot = int(mslot[i])
        tgt = int(dp.broker_ids[int(mtgt[i])])
        if keep is not None and not keep[i]:
            continue
        if keep is not None and slot >= 0 and part.replicas[slot] == tgt:
            # a collapsed run whose final write restores the original
            # broker is a net no-op — emitting it would burn a real
            # reassignment cycle on zero data movement
            continue
        old = list(part.replicas) if rec is not None else None
        if slot == SWAP_SLOT:
            j = part.replicas.index(tgt)
            part.replicas[j] = part.replicas[0]
            part.replicas[0] = tgt
        else:
            part.replicas[slot] = tgt
        if rec is not None:
            # O(1) append; the trajectory replay happens at finalize,
            # never inside the converge wall
            rec.record_change(part, old, list(part.replicas), "session")
        if tap is not None:
            tap.change(part)
        opl.append(part)
        emitted += 1
    # committed vs emitted is the churn-elision attribution (-stats):
    # device-side progress against what actually reaches the plan
    obs.metrics.count("solver.moves_committed", n)
    obs.metrics.count("solver.moves_emitted", emitted)
    return n


def _repairs_possible(pl: PartitionList, cfg: RebalanceConfig) -> bool:
    """Cheap O(P·R) prescreen: can any repair step (remove-extra,
    add-missing, move-disallowed — steps.go:70-143) fire at all?

    The full repair steps cost O(P·B) host work per pass (per-partition
    sorted broker scans); on an already-feasible 10k-partition input that
    is ~0.8 s of pure Python for zero fired steps. After ``fill_defaults``
    most partitions share one brokers-list *object*, so the allowed-set
    check caches by identity exactly like ``tensorize`` does.
    """
    full_ok: dict = {}
    for p in pl.iter_partitions():
        if p.num_replicas != len(p.replicas):
            return True
        key = id(p.brokers)
        bset = full_ok.get(key)
        if bset is None:
            bset = full_ok[key] = set(p.brokers)
        if not bset.issuperset(p.replicas):
            return True
    return False


def _settle_head(
    pl: PartitionList,
    cfg: RebalanceConfig,
    budget: int,
    include_reassign_leaders: bool = True,
) -> Tuple[List[Partition], int]:
    """Run the pipeline head (validations, defaults, repairs) until no step
    fires, applying each repair like the CLI loop does. Returns the applied
    live partitions (each counts against the reassignment budget).

    ``include_reassign_leaders=False`` settles only the repair steps that
    precede ``ReassignLeaders`` in the pipeline order — used by the fused
    leader session (solvers/leader.py), which replays the leader step on
    device. Repairs strictly precede it (balancer.go:34-44), so settling
    them first preserves the reference's step precedence exactly.
    """
    from kafkabalancer_tpu.balancer.pipeline import _HEAD_VALIDATE
    from kafkabalancer_tpu.cli import apply_assignment

    # validations + defaults always run once (exact error behavior);
    # the repair loop is skipped entirely when no repair can fire
    for _name, step in _HEAD_VALIDATE:
        step(pl, cfg)
    leaders_live = include_reassign_leaders and cfg.rebalance_leaders
    if not leaders_live and not _repairs_possible(pl, cfg):
        return [], budget

    head = (
        _COMMON_HEAD
        if include_reassign_leaders
        else [s for s in _COMMON_HEAD if s[0] != "ReassignLeaders"]
    )
    out: List[Partition] = []
    while budget > 0:
        fired = None
        for _name, step in head:
            fired = step(pl, cfg)
            if fired is not None:
                break
        if fired is None:
            break
        for changed in fired.partitions:
            out.append(apply_assignment(pl, changed))
        budget -= 1
    return out, budget


def _leader_plan(
    pl: PartitionList,
    cfg: RebalanceConfig,
    max_reassign: int,
    dtype: Any,
    chunk_moves: int,
    opl: PartitionList,
    batch: int = 1,
) -> PartitionList:
    """Fused ``rebalance_leaders`` planning: host repairs (strictly before
    ReassignLeaders in the pipeline order), then the device Balance loop
    of solvers/leader.py, chunked and decoded like the move sessions.
    ``batch > 1`` selects the convergent batched-transfer extension
    (solvers/leader.py module docstring); ``batch=1`` replays the
    reference trajectory."""
    with obs.span("settle"):
        repaired, budget = _settle_head(
            pl, cfg, max_reassign, include_reassign_leaders=False
        )
    opl.append(*repaired)
    if dtype is None:
        dtype = default_dtype()
    chunk_moves = max(1, min(chunk_moves, 1 << 20))

    remaining = budget
    while remaining > 0:
        with obs.span("tensorize"):
            dp = tensorize(pl, cfg)
        all_allowed = all_allowed_of(dp)
        chunk = min(remaining, chunk_moves)
        rec = convergence.recorder()
        if rec is not None:
            rec.note_round(dp, cfg, chunk=chunk, engine="leader")
        packed = _dispatch_chunk(
            dp, cfg, chunk, dtype, batch, "xla",
            polish=False, leader=True, all_allowed=all_allowed,
            churn_gate=DEFAULT_CHURN_GATE,
        )
        n = _decode_packed(packed, dp, opl, drop_superseded=batch > 1)
        remaining -= n
        if n < chunk:
            break
    _note_leader_outcome(pl, cfg, opl, remaining)
    return opl


def _note_leader_outcome(
    pl: PartitionList, cfg: RebalanceConfig, opl: PartitionList,
    remaining: int,
) -> None:
    """Outcome note for the fused leader session (the reference's
    ``distributeLeaders`` gate semantics, steps.go:249-253: it bails
    outright when total unbalance is below ``min_unbalance``)."""
    if opl.partitions:
        convergence.note_outcome(
            "budget_exhausted" if remaining <= 0 else "converged"
        )
        return
    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )

    loads = get_broker_load(pl)
    for bid in cfg.brokers or []:
        if bid not in loads:
            loads[bid] = 0.0
    su = get_unbalance_bl(get_bl(loads))
    if su != su:  # NaN objective (all-zero loads): Go's no-candidate exit
        convergence.note_outcome("already_balanced", unbalance=su)
    elif su < cfg.min_unbalance:
        convergence.note_outcome(
            "below_threshold", unbalance=su,
            min_unbalance=cfg.min_unbalance,
        )
    else:
        convergence.note_outcome("no_feasible_candidate", unbalance=su)
    return


def resolve_engine(engine: str) -> str:
    """Resolve ``engine="auto"`` to a concrete engine — the r4 verdict
    asked for the engine question decided IN CODE from the measured
    crossover, not in prose. The r5 A/B on the bench chip (warm, min of
    2, flagship config: allow-leader, batch=100, polish, f32):

        shape        pallas   xla
        2k x 50      0.231    0.225 s
        5k x 100     0.377    0.373 s
        10k x 100    0.528    0.511 s
        20k x 100    0.931    0.826 s
        30k x 100    1.097    0.879 s
        50k x 200    2.382    1.828 s

    The XLA while_loop session matches the whole-session kernel at small
    shapes and beats it increasingly past ~10k partitions (the
    prefix-exact batched commits removed the per-iteration dispatch
    overhead that was the kernel's founding premise), so ``auto``
    resolves to ``"xla"`` at EVERY single-chip shape — verified up to
    the 262144 x 256 bucket (160k x 250 converges in ~48 s cold). The
    kernel remains an explicitly-requested alternative
    (``engine="pallas"``, re-timed every round by suite config 7) and
    the ceiling-free streaming shard body (parallel/shard_kernel.py),
    where it is not merely faster but the only engine that SURVIVES:
    the shard_map-wrapped XLA session crashes the v5e worker at
    >= 131072 x 256 buckets, so ``plan_sharded`` has its own auto rule
    (kernel-on-TPU; see parallel/shard_session.py)."""
    return "xla" if engine == "auto" else engine


def anti_colocation_requested(
    cfg: RebalanceConfig,
    anti_colocation: "float | None",
    batch: int,
) -> "Tuple[float, bool]":
    """The engine-independent half of the activation convention: the
    penalty that WOULD activate under an XLA engine, plus whether it was
    an explicit request. ``plan_sharded``'s auto rule needs exactly this
    question BEFORE an engine exists (its answer decides the engine), so
    it lives here rather than being hand-duplicated (r5 review).
    Returns ``(lam, explicit)``."""
    if anti_colocation is None:
        lam = getattr(cfg, "anti_colocation", 0.0) or 0.0
        if lam and (batch <= 1 or cfg.rebalance_leaders):
            lam = 0.0
        return max(0.0, lam), False
    return max(0.0, anti_colocation), True


def resolve_anti_colocation(
    cfg: RebalanceConfig,
    anti_colocation: "float | None",
    batch: int,
    engine: str,
    what: str = "colocation session",
) -> "Tuple[float, str]":
    """The ONE definition of when an anti-colocation penalty activates,
    shared by ``plan`` and ``parallel.shard_session.plan_sharded`` (two
    hand-maintained copies would let the convention drift and silently
    break their bit-parity contract). Returns ``(lam, engine)``.

    The kwarg overrides; ``cfg.anti_colocation`` is the default — but a
    cfg-derived penalty only ACTIVATES where it changes nothing for
    legacy callers (a beam-config cfg reused for a load-only bulk
    session must keep planning loads, not raise, and an explicit engine
    request must stay honored). An EXPLICIT request validates hard:
    ``batch > 1`` and no ``rebalance_leaders`` (the fused leader session
    has no colocation state), and a non-XLA engine is overridden with a
    visible warning (the kernels have no colocation state either).
    """
    lam, explicit = anti_colocation_requested(cfg, anti_colocation, batch)
    if not explicit and lam and engine != "xla":
        # cfg-derived: an explicit engine request stays honored
        lam = 0.0
    if lam and batch <= 1:
        raise ValueError("anti_colocation requires batch > 1")
    if lam and cfg.rebalance_leaders:
        raise ValueError(
            "anti_colocation is not supported with rebalance_leaders "
            "(the fused leader session has no colocation state)"
        )
    if lam and engine != "xla":
        import warnings

        warnings.warn(
            f"anti_colocation runs the XLA {what}; explicit "
            f"engine={engine!r} request is overridden",
            UserWarning,
            stacklevel=3,
        )
        engine = "xla"
    return lam, engine


def plan(
    pl: PartitionList,
    cfg: RebalanceConfig,
    max_reassign: int,
    dtype: Any = None,
    batch: int = 1,
    chunk_moves: "int | None" = None,
    engine: str = "auto",
    polish: bool = False,
    churn_gate: float = DEFAULT_CHURN_GATE,
    anti_colocation: "float | None" = None,
) -> PartitionList:
    """Full multi-move planning session: host-side repairs, then a fused
    on-device move loop. The output accumulates live partitions in move
    order exactly like the CLI main loop's ``opl`` (so entries reflect the
    final assignment, kafkabalancer.go:177-221 + SURVEY.md §2.2); ``pl`` is
    mutated in place like the reference's aliasing does.

    With ``rebalance_leaders`` set, the whole Balance loop (leader
    redistribution interleaved with greedy moves, exact step precedence)
    runs as one fused device session (solvers/leader.py) — round 1 ran it
    host-side per move, minutes at 10k-partition scale.

    ``engine="auto"`` (the default) resolves per the measured crossover
    (:func:`resolve_engine` — currently the XLA while_loop session at
    every single-chip shape). ``engine="pallas"`` forces the
    whole-session Pallas kernel (solvers/pallas_session.py): float32
    only, always the pooled batched selection (even at ``batch=1`` there
    is no leader-first precedence), same results as the XLA batch path.
    ``engine="pallas-interpret"`` uses the Pallas interpreter (CPU
    testing).

    ``polish=True`` alternates the move session with fused pair-swap
    phases on device (solvers/polish.py) — compound two-move exchanges
    escape the single-move local optimum the reference's greedy
    neighborhood cannot (its upstream lists N-way swaps as planned but
    never built, README.md:94-100).

    ``anti_colocation=λ > 0`` optimizes the COMBINED objective
    ``u + λ·Σ_{topic,broker} max(0, c-1)`` (the same objective the beam
    solver searches, solvers/beam.py) directly in the batched session:
    per-(topic, broker) replica counts ride as incremental device state,
    candidates score with the ±λ colocation terms, and the prefix-exact
    acceptance first-claims (topic, broker) cells so every committed
    move improves the combined objective by exactly its delta. Greedy in
    the combined objective (no beam lookahead, no uphill sequences) at
    session speed — the bulk phase of the anti-colocation pipeline, with
    beam as the optional quality tail. Requires ``batch > 1``; forces
    the XLA engine (the kernel has no colocation state). Composes with
    ``polish``: every polish phase scores the combined objective too
    (swap candidates add their ±λ pair deltas; leadership shuffles move
    no membership, so counts are invariant — solvers/polish.py).
    """
    if engine not in ENGINES:
        raise ValueError(f"unknown engine {engine!r}")
    # "auto" resolves BEFORE the colocation resolver: auto is not an
    # explicit kernel request, so it must neither warn nor survive to
    # the dispatch statics
    engine = resolve_engine(engine)
    anti_colocation, engine = resolve_anti_colocation(
        cfg, anti_colocation, batch, engine
    )
    opl = empty_partition_list()
    if max_reassign <= 0:
        return opl

    if chunk_moves is None:
        chunk_moves = auto_chunk_moves(len(pl.partitions or []))

    if cfg.rebalance_leaders:
        return _leader_plan(
            pl, cfg, max_reassign, dtype, chunk_moves, opl, batch=batch
        )

    with obs.span("settle"):
        repaired, budget = _settle_head(pl, cfg, max_reassign)
    opl.append(*repaired)
    if dtype is None:
        dtype = default_dtype()

    # sessions chunk at ``chunk_moves`` per device dispatch (bounding the
    # wall-clock of any single device call — long-running dispatches can
    # trip runtime watchdogs) and re-enter with the mutated assignment
    # until converged or exhausted; identical chunk buckets reuse one
    # compiled executable
    chunk_moves = max(1, min(chunk_moves, 1 << 20))
    use_pallas = engine in ("pallas", "pallas-interpret")
    if use_pallas:
        from kafkabalancer_tpu.solvers.pallas_session import TILE_P

        dtype = kernel_dtype()

    remaining = budget
    while remaining > 0:
        # only the partition axis needs TILE_P alignment for the kernel
        with obs.span("tensorize"):
            dp = tensorize(pl, cfg, min_bucket=TILE_P if use_pallas else 8)
        # the default FillDefaults outcome allows every broker everywhere
        # (detected by value, before the capacity gate — the all-allowed
        # kernel mode stores no [P, B] matrix and has a far higher ceiling)
        all_allowed = all_allowed_of(dp)
        chunk = min(remaining, chunk_moves)
        rec = convergence.recorder()
        if rec is not None:
            # -explain candidate-space stats, from the dense encoding
            # this round already materialized (one numpy pass, no
            # device sync)
            rec.note_round(dp, cfg, chunk=chunk, engine=engine)
        if engine == "pallas" and not pallas_session_fits(
            dp, dtype, all_allowed, cfg.allow_leader_rebalancing,
            next_bucket(chunk, 128),
        ):
            # past this device's scoped-VMEM ceiling (cached verdict /
            # prior / compile probe, at the dispatch's own move-log
            # bucket) Mosaic compilation OOMs, so fall back to the XLA
            # while_loop session — same algorithm, HBM-resident state
            engine = "xla"
            use_pallas = False
            dp = tensorize(pl, cfg)
        if polish:
            from kafkabalancer_tpu.solvers.polish import entry_table

            ew_np, ep_, er_, evalid = entry_table(
                dp, cfg.min_replicas_for_rebalancing
            )
        else:
            ew_np = ep_ = er_ = evalid = None
        if anti_colocation:
            # bucket the topic-count static so topic-cardinality drift
            # re-uses compiled programs (counts rows past the real count
            # just stay zero)
            tid = dp.topic_id
            n_topics = next_bucket(max(1, len(dp.topics)), 64)
        else:
            tid = None
            n_topics = 0
        # ONE compiled program per chunk: input prep, the session, and the
        # move-log packing all fuse into a single dispatch (each separate
        # program is a full relay round trip on a cold process), and ONE
        # device->host transfer returns everything the decode needs
        try:
            packed = _dispatch_chunk(
                dp, cfg, chunk, dtype, batch, engine,
                polish=polish, leader=False, all_allowed=all_allowed,
                churn_gate=churn_gate,
                ew=ew_np, ep=ep_, er=er_, evalid=evalid,
                tid=tid,
                lam=anti_colocation if anti_colocation else None,
                n_topics=n_topics,
            )
        except BalanceError:
            raise
        except Exception as exc:
            if engine == "pallas" and _is_vmem_oom(exc):
                obs.metrics.count("solver.pallas_fallbacks")
                obs.metrics.event(
                    "pallas_fallback",
                    scoped=_is_scoped_vmem_oom(exc),
                    error=type(exc).__name__,
                )
                # fall back to the XLA session for this chunk — same
                # algorithm, HBM-resident state. A LASTING verdict is
                # recorded only for the scoped-VMEM/Mosaic signatures
                # (the prior admitted a shape THIS chip's kernel budget
                # cannot hold — deterministic, so future plans skip
                # straight to XLA); transient OOM flavors (HBM
                # exhaustion, device contention) stay one-shot and the
                # next plan() retries the kernel (ADVICE r5)
                if _is_scoped_vmem_oom(exc):
                    _gate_record(
                        _gate_key(
                            dp.replicas.shape[0], dp.bvalid.shape[0],
                            dp.replicas.shape[1], all_allowed,
                            cfg.allow_leader_rebalancing,
                            next_bucket(chunk, 128),
                        ),
                        False,
                    )
                engine = "xla"
                use_pallas = False
                continue
            if engine in ("pallas", "pallas-interpret"):
                # compiled Mosaic kernels need a TPU backend; surface a
                # planning failure (CLI exit 3) instead of a raw traceback
                raise BalanceError(
                    f"pallas engine failed ({exc!r}); use engine='xla' or "
                    f"'pallas-interpret'"
                ) from exc
            raise
        # polish interleaves swap/shuffle phases and the pallas kernel
        # always runs the pooled batched selection — neither is a batch=1
        # parity trajectory, so their superseded writes elide
        n = _decode_packed(
            packed, dp, opl,
            drop_superseded=polish or batch > 1 or use_pallas,
        )
        remaining -= n
        if n < chunk:
            break
    _note_session_outcome(pl, cfg, opl, remaining)
    return opl


def _note_session_outcome(
    pl: PartitionList, cfg: RebalanceConfig, opl: PartitionList,
    remaining: int,
) -> None:
    """Record WHY the fused session stopped (the convergence outcome
    slot behind the ``plan.stop_reason``/``plan.no_move_reason``
    gauges). The device early-exit only says "no candidate cleared the
    threshold"; WHICH constraint was binding takes a host
    ``steps.classify_no_move`` scan, so zero-move exits note a
    ``classify_pending`` marker instead of paying it here — the CLI
    resolves it ONCE, and only when a telemetry consumer exists
    (-stats/-metrics-json/-explain). A converged cluster's served
    steady state is exactly a stream of zero-move requests; an
    unconditional full candidate scan per request would tax it for
    telemetry nobody asked for. ``-explain`` refines converged-with-
    moves runs at finalize (outside the converge wall)."""
    if not opl.partitions:
        convergence.note_outcome("converged", classify_pending=True)
    elif remaining <= 0:
        convergence.note_outcome("budget_exhausted")
    else:
        convergence.note_outcome("converged")
