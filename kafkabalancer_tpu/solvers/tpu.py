"""Vectorized single-move solver.

Replaces the reference's greedy scalar scan (``move``, steps.go:145-232):
instead of mutating one broker-load table through O(P·R·B) candidate
what-ifs at O(B) objective re-evaluation each, every candidate
``(partition p, movable replica r, target broker t)`` is scored in one
fused XLA pass over a ``[P, R, B]`` tensor.

The O(B) objective re-evaluation collapses to an O(1) rank-1 update: a move
shifts weight ``w`` from source ``s`` to target ``t``, leaving the total
(and thus the average) load unchanged, so

    u(move) = Σ_b f(load_b) − f(load_s) − f(load_t)
                            + f(load_s − w) + f(load_t + w)

with ``f`` the asymmetric per-broker penalty (utils.go:134-143).

**Exact-parity tie resolution.** The reference's full O(B) recompute per
candidate accumulates floats in ``bl`` order, so mathematically tied
candidates (ubiquitous with the default weight 1.0) are separated by
last-ulp rounding noise — behaviour an order-free vectorized reduction
cannot reproduce. The device pass therefore returns the per-partition
candidate minima (pure reductions — no top_k, whose TPU sort machinery
alone was ~17 MB of compiled executable, a real cost per fresh process on
a remote-attached device); the host flags the partitions whose minimum
lands within tolerance of the global minimum and replays the ORACLE's own
per-partition scan (balancer/steps.py ``scan_partition_move`` — same bl
mutation order, same first-strict-improver rule, steps.go:211) over just
those rows. Result: byte-identical plans to the greedy oracle at
vectorized search cost.

The device pass is TIERED by precision (``find_best_move``): float32
first — the filter only has to bound the window, so f32's wider
error-bound tolerance costs host re-scan rows, never correctness, and it
halves-again the stored executable (f64 is software-emulated on TPU) and
cuts the dispatch ~4x — retrying with float64's last-ulp window when the
f32 window overflows the host re-scan budget, and falling back to the
full greedy scan only when even the f64 window does
(``MAX_WINDOW_CANDIDATES``).

Parity semantics pinned against the greedy oracle:

- candidate order: partitions in list order, movable slots in replica
  order (followers = slots 1.., leader = slot 0, steps.go:172-175),
  targets in ascending (load, broker-ID) ``bl`` rank order;
- the what-if delta uses the plain follower weight even when moving a
  leader (steps.go:185, :207 — the premium is *not* re-simulated;
  SURVEY.md §3.3);
- the load table is observed brokers ∪ ``cfg.brokers`` zero-filled
  (steps.go:150-155), computed host-side in the oracle's accumulation
  order so tie re-scores are bit-identical — see
  ``tensorize.broker_universe``;
- eligibility: ``num_replicas ≥ min_replicas_for_rebalancing``
  (steps.go:168-170); target must be allowed and not already a replica
  (steps.go:193-201);
- acceptance: best unbalance < current − ``min_unbalance``
  (steps.go:227-229), decided on exact host-rescored values; NaN
  objectives reject every candidate exactly like Go's always-false NaN
  comparisons.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import numpy as np

from kafkabalancer_tpu.models import PartitionList, RebalanceConfig
from kafkabalancer_tpu.models.config import HOST_FLOAT_DTYPE
from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from kafkabalancer_tpu.balancer import costmodel  # noqa: E402
from kafkabalancer_tpu.balancer.steps import greedy_move, replace_replica  # noqa: E402
from kafkabalancer_tpu.obs import convergence  # noqa: E402
from kafkabalancer_tpu.ops import cost, tensorize  # noqa: E402
from kafkabalancer_tpu.ops.tensorize import DensePlan, all_allowed_of  # noqa: E402

# Host tie-resolution budget: the oracle re-scan over window partitions
# covers at most this many (slot x target) candidate evaluations; a wider
# window (pervasive exact ties, e.g. all-uniform weights at scale) falls
# back to the full greedy scan.
MAX_WINDOW_CANDIDATES = 32768

# Below this candidate count the greedy scan beats device dispatch latency;
# since the tpu solver is byte-identical to greedy by contract, routing tiny
# instances to the host scan changes nothing but wall-clock.
MIN_DEVICE_CANDIDATES = 20_000


def score_moves(
    loads: jax.Array,
    replicas: jax.Array,
    allowed: Optional[jax.Array],
    member: jax.Array,
    weights: jax.Array,
    nrep_cur: jax.Array,
    nrep_tgt: jax.Array,
    pvalid: jax.Array,
    bvalid: jax.Array,
    nb: jax.Array,
    min_replicas: jax.Array,
    *,
    leaders: bool,
    tie_k: int = 0,
) -> Tuple[jax.Array, ...]:
    """Score every candidate move with the rank-1 objective update.

    Returns ``(u_min, flat_idx, su, perm)`` and, when ``tie_k > 0``,
    additionally ``perpart`` — the per-partition candidate minima the
    host uses to flag tie-window partitions. ``flat_idx`` indexes the
    candidate tensor flattened in ``(partition, replica slot, target
    bl-rank)`` order; ``perm`` maps bl rank → dense broker index. Inputs
    are dense index space
    (:class:`kafkabalancer_tpu.ops.tensorize.DensePlan`).
    """
    _, R = replicas.shape

    _, perm, rank_of = cost.rank_brokers(loads, bvalid)
    u, su = cost.move_candidate_scores(
        loads,
        replicas,
        allowed[:, perm],
        member[:, perm],
        bvalid,
        bvalid[perm],
        perm,
        rank_of,
        weights,
        nrep_cur,
        nrep_tgt,
        pvalid,
        nb,
        min_replicas,
    )

    slot = jnp.arange(R)[None, :]
    movable = (slot == 0) if leaders else (slot >= 1)
    masked = jnp.where(movable[:, :, None], u, jnp.inf)
    flat = masked.reshape(-1)
    idx = jnp.argmin(flat)
    u_min = flat[idx]
    if tie_k <= 0:
        return u_min, idx, su, perm
    # tie window as PER-PARTITION minima: pure reductions, no top_k (the
    # TPU sort machinery dominated the compiled executable at ~17 MB — a
    # real per-fresh-process cost on a remote-attached device) and no
    # index scatter (worse still: ~50 MB of scatter lowering). The host
    # flags partitions whose minimum lands in the tolerance window and
    # replays the ORACLE's own per-partition scan over just those rows
    # (balancer/steps.py scan_partition_move) — parity by construction.
    perpart = jnp.min(masked, axis=(1, 2))
    return u_min, idx, su, perm, perpart


def _score_window(
    ints: jax.Array, floats: jax.Array, allowed: Optional[jax.Array],
    *, leaders: bool, all_allowed: bool,
) -> Tuple[jax.Array, ...]:
    """``score_moves`` with the transfer layout of the stateless per-move
    deployment unit (one move per CLI run, README.md:21-33): on a
    remote-attached TPU every device_put and every fetch pays a full
    relay round trip, so the eleven logical inputs pack into TWO host
    arrays and the three outputs into ONE.

    ``ints [P, R+3]`` carries ``replicas | nrep_cur | nrep_tgt | pvalid``;
    ``floats [P+B+2]`` carries ``weights | loads | nb | min_replicas``
    (scalars ride in the tail: a separate device_put per scalar is a
    relay round trip, and a static would fork a multi-MB stored
    executable per config value) and its dtype selects the scoring
    precision (see ``find_best_move``'s tier ladder).
    The ``[P, B]`` membership mask is recomputed from the replica matrix
    on device and the allowed matrix is the validity-row broadcast in the
    default all-allowed case (``allowed=None``), so neither [P, B] input
    is ever transferred. Output: ``[u_min, su, relmax, wrel,
    perpart_min...]`` — ``relmax``/``wrel`` (the largest |load/avg - 1|
    over valid brokers and the largest weight/avg over eligible source
    rows) feed the tier's error-bound window tolerance: the dominant
    f32 error in a per-partition minimum is the CANCELLATION in
    ``rel = load/avg - 1`` (absolute error ~eps32 per rel, so ~eps32·rel
    per penalty term), which scales with rel, not with the objective —
    near balance a tolerance proportional to ``su ~ B·rel²`` alone
    underestimates it and the window could silently exclude the oracle
    winner (r5 review finding).
    """
    P, W = ints.shape
    R = W - 3
    replicas = ints[:, :R]
    nrep_cur = ints[:, R]
    nrep_tgt = ints[:, R + 1]
    pvalid = ints[:, R + 2] > 0
    B = floats.shape[0] - P - 2
    weights = floats[:P]
    loads = floats[P : P + B]
    nb = floats[P + B]
    min_replicas = floats[P + B + 1].astype(jnp.int32)
    # tensorize packs the real brokers contiguously (bvalid[:nb])
    bvalid = jnp.arange(B, dtype=jnp.int32) < nb.astype(jnp.int32)
    slot = jnp.arange(R, dtype=jnp.int32)[None, :]
    valid = (slot < nrep_cur[:, None]) & pvalid[:, None]
    member = jnp.any(
        (replicas[:, :, None] == jnp.arange(B, dtype=replicas.dtype))
        & valid[:, :, None],
        axis=1,
    )
    # Factored per-partition minima: the rank-1 objective decomposes as
    # u = su + A(p, slot) + C(p, target) (move_candidate_scores docstring),
    # so min over a partition's candidates is min_slot A + min_target C —
    # [P, R] + [P, B] work. The [P, R, B] tensor and the (load, ID) broker
    # sort exist only for exact candidate ORDER, which the host oracle
    # rescan supplies; dropping both here shrinks the stored executable
    # ~3x and the dispatch with it (score_moves keeps the full form for
    # the argmin consumers: shard_move, the graft entry, tests).
    avg = jnp.sum(jnp.where(bvalid, loads, 0.0)) / nb
    F = jnp.where(bvalid, cost.overload_penalty(loads, avg), 0.0)
    su = jnp.sum(F)
    w = weights[:, None]
    s = jnp.clip(replicas, 0)
    movable = (slot == 0) if leaders else (slot >= 1)
    srcok = (
        movable
        & valid
        & (nrep_tgt >= min_replicas)[:, None]
    )
    A = cost.overload_penalty(loads[s] - w, avg) - F[s]
    Amin = jnp.min(jnp.where(srcok, A, jnp.inf), axis=1)
    tmask = ~member & bvalid[None, :] if all_allowed else (
        allowed & ~member & bvalid[None, :]
    )
    C = cost.overload_penalty(loads[None, :] + w, avg) - F[None, :]
    Cmin = jnp.min(jnp.where(tmask, C, jnp.inf), axis=1)
    perpart = su + Amin + Cmin
    u_min = jnp.min(perpart)
    # error-scale witnesses for the host-side window tolerance (docstring)
    rel = loads / avg - 1.0
    relmax = jnp.max(jnp.where(bvalid, jnp.abs(rel), 0.0))
    wrel = jnp.max(jnp.where(pvalid, weights, 0.0)) / jnp.abs(avg)
    return jnp.concatenate(
        [u_min.reshape(1), su.reshape(1), relmax.reshape(1),
         wrel.reshape(1), perpart]
    )


_score_window_jit = jax.jit(
    _score_window, static_argnames=("leaders", "all_allowed")
)


def _pack_window_args(
    dp: DensePlan, loads_np: Any, cfg: RebalanceConfig
) -> Tuple[Any, Any, Any, bool]:
    """The window scorer's transfer layout (see ``_score_window``), in ONE
    place shared by ``find_best_move`` and the layout parity test —
    returns ``(ints, floats64, allowed_or_None, all_allowed)``; the caller
    casts ``floats64`` to the tier's dtype."""
    ints = np.concatenate(
        [
            dp.replicas,
            dp.nrep_cur[:, None],
            dp.nrep_tgt[:, None],
            dp.pvalid[:, None].astype(np.int32),
        ],
        axis=1,
    ).astype(np.int32)
    floats64 = np.concatenate(
        [
            dp.weights,
            loads_np,
            [float(dp.nb), float(cfg.min_replicas_for_rebalancing)],
        ]
    )
    all_allowed = all_allowed_of(dp)
    return ints, floats64, None if all_allowed else dp.allowed, all_allowed


def _oracle_loads(
    pl: PartitionList, cfg: RebalanceConfig
) -> Dict[int, float]:
    """Broker loads in the oracle's accumulation order, with the reference
    ``move()`` zero-fill of configured brokers (steps.go:150-155)."""
    loads = costmodel.get_broker_load(pl)
    for bid in cfg.brokers or []:
        if bid not in loads:
            loads[bid] = 0.0
    return loads


def find_best_move(
    dp: DensePlan,
    cfg: RebalanceConfig,
    leaders: bool,
    loads_map: Optional[Dict[int, float]] = None,
) -> Optional[Tuple[int, int, int]]:
    """Best accepted move on a dense plan, or ``None`` if no candidate
    improves by more than ``cfg.min_unbalance``.

    Returns ``(partition row, source broker ID, target broker ID)``.
    ``None`` also signals the caller must fall back to the greedy scan
    (tie-window overflow) via the :class:`TieOverflow` exception instead.
    """
    from kafkabalancer_tpu.balancer.steps import scan_moves

    nb = dp.nb
    B = dp.bvalid.shape[0]
    R = dp.replicas.shape[1]

    if loads_map is None:
        pl = PartitionList(version=1, partitions=dp.partitions)
        loads_map = _oracle_loads(pl, cfg)
    loads_np = np.zeros(B, dtype=HOST_FLOAT_DTYPE)
    for bid, load in loads_map.items():
        loads_np[dp.broker_index(bid)] = load

    # raw numpy args: the AOT executable store (ops/aot.py) keys, loads and
    # calls the stored single-move scorer with exactly the objects the jit
    # path would see — a fresh process (the reference's per-invocation
    # deployment unit) skips tracing and compilation entirely on a hit
    from kafkabalancer_tpu.ops import aot

    ints, floats64, allowed_arg, all_allowed = _pack_window_args(
        dp, loads_np, cfg
    )
    statics = dict(leaders=leaders, all_allowed=all_allowed)

    rec = convergence.recorder()
    if rec is not None:
        # -explain candidate-space stats from the dense encoding this
        # pass already built (one numpy pass, no device sync)
        rec.note_round(dp, cfg, chunk=1, engine="tpu-score")

    # --- tiered device scoring: f32 filter, f64 on window overflow -------
    # The device pass only FILTERS candidates; acceptance and ordering are
    # decided by the host-exact oracle rescan below, so precision buys
    # nothing but a narrower window. float32 halves the executable (f64 is
    # software-emulated on TPU: 12.1 -> 6.4 MB measured at 10k x 100, a
    # real per-fresh-process upload cost on a remote-attached device) and
    # cuts the dispatch ~4x (0.63 -> 0.17 s). Its window tolerance bounds
    # the f32 scorer's error at 4·B·eps32·scale — a summation-error bound
    # with ~100x margin over the drift measured vs f64 at the flagship
    # scale — and a window that overflows the host re-scan budget retries
    # with the f64 scorer's last-ulp window before giving up to greedy.
    rows = None
    # the tiered scorer ENUMERATES both precisions by design: f32
    # filters, f64 retries on window overflow — not a policy bypass
    for npdt in (np.float32, np.float64):  # jaxlint: disable=R4 — tier ladder
        args = (ints, floats64.astype(npdt), allowed_arg)
        f_out = np.asarray(
            aot.call_or_compile(
                "score_window", _score_window_jit, args, statics
            )
        )
        u_min, su_dev = float(f_out[0]), float(f_out[1])
        relmax, wrel = float(f_out[2]), float(f_out[3])
        perpart = f_out[4:]
        if not np.isfinite(u_min):
            # no candidate, or NaN objective (zero loads) — but only the
            # f64 tier may conclude that: loads representable in f64 can
            # underflow the f32 cast to a spurious 0/0 NaN, and the
            # pre-tiering scorer (always f64) handled such inputs
            if npdt is np.float64:  # jaxlint: disable=R4 — tier ladder
                convergence.note_outcome(
                    "no_feasible_candidate" if np.isinf(u_min)
                    else "already_balanced",
                    unbalance=float(su_dev),
                )
                return None
            continue
        # window tolerance = a sound bound on the tier's perpart error
        # RELATIVE to the tier's own u_min (the common su error cancels
        # in the comparison). Two regimes: objective-scaled rounding
        # (~B·eps·max(|u_min|,|su|), the summation bound) plus the
        # CANCELLATION term from rel = load/avg - 1 — each penalty
        # evaluation carries absolute error ~eps·ρ·(1+ρ) with
        # ρ = relmax + wrel bounding any perturbed |rel| the candidates
        # reach, so four evaluations plus additions stay under
        # ~32·eps·(1+ρ)². Near balance (ρ → 0) this floors the tolerance
        # at ~32·eps instead of collapsing with su, the unsound corner
        # the r4 round shipped (tol was exactly 0 at u_min == su == 0);
        # the widened near-balance window costs host re-scan rows or an
        # f64 retry, never correctness.
        rho = 1.0 + (relmax + wrel if np.isfinite(relmax + wrel) else 0.0)
        if npdt is np.float32:  # jaxlint: disable=R4 — tier ladder
            eps = float(np.finfo(np.float32).eps)  # jaxlint: disable=R4 — tier ladder
            tol = eps * (
                4.0 * B * max(abs(u_min), abs(su_dev)) + 32.0 * rho * rho
            )
        else:
            eps = float(np.finfo(np.float64).eps)  # jaxlint: disable=R4 — tier ladder
            tol = (
                1e-9 * max(1.0, abs(u_min), abs(su_dev))
                + 64.0 * eps * rho * rho
                + 1e-12
            )
        cand = np.nonzero(perpart <= u_min + tol)[0]
        if len(cand) * R * nb <= MAX_WINDOW_CANDIDATES:
            rows = cand
            break
    if rows is None:
        raise TieOverflow
    if rec is not None:
        rec.note_tie_window(int(len(rows)))

    # replay the ORACLE's own per-partition scan over just the flagged
    # rows — same bl table, same candidate order, same
    # first-strict-improver rule — byte parity by construction
    # (steps.scan_moves is the vectorized replay of scan_partition_move,
    # bit-identical by the column-order argument documented there)
    bl = costmodel.get_bl(loads_map)  # oracle bl, (load, ID) ascending
    su = costmodel.get_unbalance_bl(bl)
    cu, best, wpos = scan_moves(
        [dp.partitions[int(row)] for row in rows], bl, su, None, cfg, leaders
    )
    best_row = int(rows[wpos]) if wpos >= 0 else -1

    if best is None or not (cu < su - cfg.min_unbalance):
        # the decline classification the metrics line surfaces as
        # plan.no_move_reason (see balancer/steps.greedy_move)
        if best is not None and cu < su:
            convergence.note_outcome(
                "below_threshold", unbalance=su, best_unbalance=cu,
                min_unbalance=cfg.min_unbalance,
            )
        else:
            convergence.note_outcome(
                "already_balanced", unbalance=su,
                min_unbalance=cfg.min_unbalance,
            )
        return None
    _p, r_id, t_id = best
    return best_row, int(r_id), int(t_id)


class TieOverflow(Exception):
    """The near-minimal candidate window spans more partitions than the
    host re-scan budget covers: resolve with the full exact scan."""


def _tpu_move(
    pl: PartitionList, cfg: RebalanceConfig, leaders: bool
) -> Optional[PartitionList]:
    # real (unpadded, movable-slot-aware) candidate count, computed without
    # tensorizing so the fallback path pays no dense-encoding cost
    movable = (
        len(pl.partitions or ())
        if leaders
        else sum(max(0, len(p.replicas) - 1) for p in pl.iter_partitions())
    )
    from kafkabalancer_tpu.ops.tensorize import broker_universe

    if movable * len(broker_universe(pl, cfg)) < MIN_DEVICE_CANDIDATES:
        return greedy_move(pl, cfg, leaders)
    dp = tensorize(pl, cfg)
    try:
        best = find_best_move(dp, cfg, leaders)
    except TieOverflow:
        return greedy_move(pl, cfg, leaders)
    if best is None:
        return None
    p, s_id, t_id = best
    return replace_replica(dp.partitions[p], s_id, t_id)


def tpu_move_leaders(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Leader moves, gated like the reference (steps.go:292-298)."""
    if not cfg.allow_leader_rebalancing:
        return None
    return _tpu_move(pl, cfg, True)


def tpu_move_non_leaders(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Follower moves — always enabled (steps.go:286-288)."""
    return _tpu_move(pl, cfg, False)
