"""Vectorized single-move solver.

Replaces the reference's greedy scalar scan (``move``, steps.go:145-232):
instead of mutating one broker-load table through O(P·R·B) candidate
what-ifs at O(B) objective re-evaluation each, every candidate
``(partition p, movable replica r, target broker t)`` is scored in one
fused XLA pass over a ``[P, R, B]`` tensor.

The O(B) objective re-evaluation collapses to an O(1) rank-1 update: a move
shifts weight ``w`` from source ``s`` to target ``t``, leaving the total
(and thus the average) load unchanged, so

    u(move) = Σ_b f(load_b) − f(load_s) − f(load_t)
                            + f(load_s − w) + f(load_t + w)

with ``f`` the asymmetric per-broker penalty (utils.go:134-143).

**Exact-parity tie resolution.** The reference's full O(B) recompute per
candidate accumulates floats in ``bl`` order, so mathematically tied
candidates (ubiquitous with the default weight 1.0) are separated by
last-ulp rounding noise — behaviour an order-free vectorized reduction
cannot reproduce. The device pass therefore returns, besides the argmin,
the top-K near-minimal candidates; the host re-scores just that window
with the float64 oracle (same accumulation order as Go) and replays the
reference's first-strict-improver scan (steps.go:211) over it in candidate
order. Result: byte-identical plans to the greedy oracle at vectorized
search cost, falling back to the full greedy scan only if the tie window
overflows K.

Parity semantics pinned against the greedy oracle:

- candidate order: partitions in list order, movable slots in replica
  order (followers = slots 1.., leader = slot 0, steps.go:172-175),
  targets in ascending (load, broker-ID) ``bl`` rank order;
- the what-if delta uses the plain follower weight even when moving a
  leader (steps.go:185, :207 — the premium is *not* re-simulated;
  SURVEY.md §3.3);
- the load table is observed brokers ∪ ``cfg.brokers`` zero-filled
  (steps.go:150-155), computed host-side in the oracle's accumulation
  order so tie re-scores are bit-identical — see
  ``tensorize.broker_universe``;
- eligibility: ``num_replicas ≥ min_replicas_for_rebalancing``
  (steps.go:168-170); target must be allowed and not already a replica
  (steps.go:193-201);
- acceptance: best unbalance < current − ``min_unbalance``
  (steps.go:227-229), decided on exact host-rescored values; NaN
  objectives reject every candidate exactly like Go's always-false NaN
  comparisons.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np

from kafkabalancer_tpu.models import PartitionList, RebalanceConfig
from kafkabalancer_tpu.ops.runtime import ensure_x64

ensure_x64()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402
from jax import lax  # noqa: E402

from kafkabalancer_tpu.balancer import costmodel  # noqa: E402
from kafkabalancer_tpu.balancer.steps import greedy_move, replace_replica  # noqa: E402
from kafkabalancer_tpu.ops import cost, tensorize  # noqa: E402
from kafkabalancer_tpu.ops.tensorize import DensePlan  # noqa: E402

# Size of the near-tie window re-scored exactly on the host. Overflowing it
# (>TIE_K mathematically tied candidates) falls back to the greedy scan.
TIE_K = 1024

# Below this candidate count the greedy scan beats device dispatch latency;
# since the tpu solver is byte-identical to greedy by contract, routing tiny
# instances to the host scan changes nothing but wall-clock.
MIN_DEVICE_CANDIDATES = 20_000


def score_moves(
    loads,
    replicas,
    allowed,
    member,
    weights,
    nrep_cur,
    nrep_tgt,
    pvalid,
    bvalid,
    nb,
    min_replicas,
    *,
    leaders: bool,
    tie_k: int = 0,
):
    """Score every candidate move with the rank-1 objective update.

    Returns ``(u_min, flat_idx, su, perm)`` and, when ``tie_k > 0``,
    additionally ``(topk_vals, topk_idx)`` — the ``tie_k`` smallest
    candidates. ``flat_idx`` indexes the candidate tensor flattened in
    ``(partition, replica slot, target bl-rank)`` order; ``perm`` maps
    bl rank → dense broker index. Inputs are dense index space
    (:class:`kafkabalancer_tpu.ops.tensorize.DensePlan`).
    """
    _, R = replicas.shape

    _, perm, rank_of = cost.rank_brokers(loads, bvalid)
    u, su = cost.move_candidate_scores(
        loads,
        replicas,
        allowed[:, perm],
        member[:, perm],
        bvalid,
        bvalid[perm],
        perm,
        rank_of,
        weights,
        nrep_cur,
        nrep_tgt,
        pvalid,
        nb,
        min_replicas,
    )

    slot = jnp.arange(R)[None, :]
    movable = (slot == 0) if leaders else (slot >= 1)
    flat = jnp.where(movable[:, :, None], u, jnp.inf).reshape(-1)
    idx = jnp.argmin(flat)
    if tie_k <= 0:
        return flat[idx], idx, su, perm
    k = min(tie_k, flat.shape[0])
    neg_vals, top_idx = lax.top_k(-flat, k)
    return flat[idx], idx, su, perm, -neg_vals, top_idx


def _score_packed(*args, leaders: bool, tie_k: int):
    """``score_moves`` with outputs packed into ONE float and ONE int
    array device-side: each separate device->host fetch pays a full relay
    round trip on a remote-attached TPU, and the single-move path is the
    reference's per-invocation deployment unit (one move per CLI run,
    README.md:21-33) — six fetches dominated its latency.

    Requires ``tie_k > 0`` (the packed layout carries the tie window;
    ``score_moves`` itself remains the raw API for tie_k == 0 callers)."""
    if tie_k <= 0:
        raise ValueError("_score_packed requires tie_k > 0")
    u_min, idx, su, perm, tie_vals, tie_idx = score_moves(
        *args, leaders=leaders, tie_k=tie_k
    )
    f = jnp.concatenate([u_min.reshape(1), su.reshape(1), tie_vals])
    i = jnp.concatenate(
        [
            idx.reshape(1).astype(jnp.int64),
            perm.astype(jnp.int64),
            tie_idx.astype(jnp.int64),
        ]
    )
    return f, i


_score_packed_jit = jax.jit(
    _score_packed, static_argnames=("leaders", "tie_k")
)


def _oracle_loads(pl: PartitionList, cfg: RebalanceConfig):
    """Broker loads in the oracle's accumulation order, with the reference
    ``move()`` zero-fill of configured brokers (steps.go:150-155)."""
    loads = costmodel.get_broker_load(pl)
    for bid in cfg.brokers or []:
        if bid not in loads:
            loads[bid] = 0.0
    return loads


def _exact_rescore(
    bl: List[List], rank_of_idx: np.ndarray, w: float, s_dense: int, t_dense: int
) -> float:
    """Exact objective of one candidate: mutate a copy of ``bl`` like the
    reference (source −w, target +w; steps.go:179-208) and accumulate the
    objective in ``bl`` order — bit-identical to the Go scan."""
    s_rank = int(rank_of_idx[s_dense])
    t_rank = int(rank_of_idx[t_dense])
    # save/assign restore like the reference (steps.go:218, :221) — a ±w
    # round-trip would not restore the cells bitwise
    s_old = bl[s_rank][1]
    t_old = bl[t_rank][1]
    bl[s_rank][1] = s_old - w
    bl[t_rank][1] = t_old + w
    u = costmodel.get_unbalance_bl(bl)
    bl[s_rank][1] = s_old
    bl[t_rank][1] = t_old
    return u


def find_best_move(
    dp: DensePlan, cfg: RebalanceConfig, leaders: bool, loads_map=None
) -> Optional[Tuple[int, int, int]]:
    """Best accepted move on a dense plan, or ``None`` if no candidate
    improves by more than ``cfg.min_unbalance``.

    Returns ``(partition row, source broker ID, target broker ID)``.
    ``None`` also signals the caller must fall back to the greedy scan
    (tie-window overflow) via the :class:`TieOverflow` exception instead.
    """
    nb = dp.nb
    B = dp.bvalid.shape[0]
    R = dp.replicas.shape[1]

    if loads_map is None:
        pl = PartitionList(version=1, partitions=dp.partitions)
        loads_map = _oracle_loads(pl, cfg)
    loads_np = np.zeros(B, dtype=np.float64)
    for bid, load in loads_map.items():
        loads_np[dp.broker_index(bid)] = load

    f_out, i_out = _score_packed_jit(
        jnp.asarray(loads_np),
        jnp.asarray(dp.replicas),
        jnp.asarray(dp.allowed),
        jnp.asarray(dp.member),
        jnp.asarray(dp.weights),
        jnp.asarray(dp.nrep_cur),
        jnp.asarray(dp.nrep_tgt),
        jnp.asarray(dp.pvalid),
        jnp.asarray(dp.bvalid),
        float(nb),
        int(cfg.min_replicas_for_rebalancing),
        leaders=leaders,
        tie_k=TIE_K,
    )
    f_out, i_out = np.asarray(f_out), np.asarray(i_out)
    u_min, tie_vals = float(f_out[0]), f_out[2:]
    perm, tie_idx = i_out[1 : 1 + B], i_out[1 + B :]
    if not np.isfinite(u_min):  # no candidate, or NaN objective (zero loads)
        return None

    # --- host-exact tie resolution (module docstring) --------------------
    bl = costmodel.get_bl(loads_map)  # oracle bl, (load, ID) ascending
    su = costmodel.get_unbalance_bl(bl)
    rank_of_idx = np.empty(B, dtype=np.int64)
    rank_of_idx[np.asarray(perm)] = np.arange(B)

    tol = 1e-9 * max(1.0, abs(u_min), abs(su)) + 1e-12
    in_window = tie_vals <= u_min + tol
    k = len(tie_vals)
    if bool(in_window.all()) and k < R * B * dp.replicas.shape[0]:
        # the window may extend past the K candidates we fetched — the
        # vectorized result is unreliable, use the exact scan
        raise TieOverflow

    cand = np.sort(tie_idx[in_window])
    cu, best = su, None
    for flat in cand:
        p, rem = divmod(int(flat), R * B)
        r, t_rank = divmod(rem, B)
        s_dense = int(dp.replicas[p, r])
        t_dense = int(perm[t_rank])
        u = _exact_rescore(bl, rank_of_idx, float(dp.weights[p]), s_dense, t_dense)
        if u < cu:
            cu = u
            best = (p, s_dense, t_dense)

    if best is None or not (cu < su - cfg.min_unbalance):
        return None
    p, s_dense, t_dense = best
    return p, int(dp.broker_ids[s_dense]), int(dp.broker_ids[t_dense])


class TieOverflow(Exception):
    """More than TIE_K near-minimal candidates: resolve with the exact scan."""


def _tpu_move(
    pl: PartitionList, cfg: RebalanceConfig, leaders: bool
) -> Optional[PartitionList]:
    # real (unpadded, movable-slot-aware) candidate count, computed without
    # tensorizing so the fallback path pays no dense-encoding cost
    movable = (
        len(pl.partitions or ())
        if leaders
        else sum(max(0, len(p.replicas) - 1) for p in pl.iter_partitions())
    )
    from kafkabalancer_tpu.ops.tensorize import broker_universe

    if movable * len(broker_universe(pl, cfg)) < MIN_DEVICE_CANDIDATES:
        return greedy_move(pl, cfg, leaders)
    dp = tensorize(pl, cfg)
    try:
        best = find_best_move(dp, cfg, leaders)
    except TieOverflow:
        return greedy_move(pl, cfg, leaders)
    if best is None:
        return None
    p, s_id, t_id = best
    return replace_replica(dp.partitions[p], s_id, t_id)


def tpu_move_leaders(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Leader moves, gated like the reference (steps.go:292-298)."""
    if not cfg.allow_leader_rebalancing:
        return None
    return _tpu_move(pl, cfg, True)


def tpu_move_non_leaders(
    pl: PartitionList, cfg: RebalanceConfig
) -> Optional[PartitionList]:
    """Follower moves — always enabled (steps.go:286-288)."""
    return _tpu_move(pl, cfg, False)
