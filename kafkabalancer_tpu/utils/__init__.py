from kafkabalancer_tpu.utils.flags import FlagSet  # noqa: F401
from kafkabalancer_tpu.utils.logbuf import BufferingWriter, Logger  # noqa: F401
