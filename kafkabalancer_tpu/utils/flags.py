"""A Go-``flag``-compatible command-line parser.

The reference uses a stdlib ``flag.FlagSet`` named "kafkabalancer" with
``ContinueOnError`` (kafkabalancer.go:77-98). Python's argparse differs in
visible ways (``-input=x`` handling, usage format, error text), so this
module re-implements the Go semantics the reference relies on:

- ``-name``, ``--name``, ``-name=value``, ``-name value`` all accepted;
- boolean flags never consume the next argument (``-b false`` leaves
  ``false`` positional); explicit values need ``-b=false``;
- parsing stops at the first non-flag argument or at ``--``;
- unknown flags produce ``flag provided but not defined: -x`` plus usage;
- ``-h``/``-help``, when not defined, print usage without the "not defined"
  error (Go's ErrHelp);
- ``PrintDefaults``-style usage: flags sorted by name, type word after the
  name (none for booleans), usage on the next line indented with four
  spaces and a tab, non-zero defaults appended as ``(default X)`` with
  strings quoted;
- on error, the error and usage are printed to the output writer and
  parsing stops — like ``ContinueOnError``, the caller may keep going with
  the flags parsed so far (the reference ignores ``Parse``'s return,
  kafkabalancer.go:98).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, List, Optional, TextIO

_GO_INT_RE = re.compile(r"^[+-]?[0-9]+$")
_GO_FLOAT_RE = re.compile(
    r"^[+-]?([0-9]+(\.[0-9]*)?|\.[0-9]+)([eE][+-]?[0-9]+)?$"
    r"|^[+-]?([iI][nN][fF](inity)?|[nN][aA][nN])$"
)


def go_atoi(s: str) -> int:
    """``strconv.Atoi`` semantics: no underscores, no surrounding whitespace."""
    if not _GO_INT_RE.match(s):
        raise ValueError(f"parsing {s!r}: invalid syntax")
    return int(s, 10)


def go_parse_float(s: str) -> float:
    """``strconv.ParseFloat`` semantics (decimal forms; no underscores or
    whitespace, Inf/NaN spellings accepted)."""
    if not _GO_FLOAT_RE.match(s):
        raise ValueError(f"parsing {s!r}: invalid syntax")
    return float(s)


class Flag:
    __slots__ = ("name", "kind", "default", "usage", "value")

    def __init__(self, name: str, kind: str, default: Any, usage: str):
        self.name = name
        self.kind = kind  # bool | int | float | string
        self.default = default
        self.usage = usage
        self.value = default


class FlagParseError(Exception):
    pass


def _parse_go_bool(s: str) -> bool:
    # strconv.ParseBool accepted forms
    if s in ("1", "t", "T", "TRUE", "true", "True"):
        return True
    if s in ("0", "f", "F", "FALSE", "false", "False"):
        return False
    raise ValueError(f"invalid boolean value {s!r}")


def _format_default(fl: Flag) -> str:
    if fl.kind == "string":
        return f'"{fl.default}"'
    if fl.kind == "bool":
        return "true" if fl.default else "false"
    if fl.kind == "float":
        # Go %v on float64 — reuse the JSON formatter's shortest form
        from kafkabalancer_tpu.codecs.writer import format_go_float

        return format_go_float(fl.default)
    return str(fl.default)


class FlagSet:
    def __init__(self, name: str, output: Optional[TextIO] = None):
        self.name = name
        self.output = output
        self.flags: Dict[str, Flag] = {}
        self.args: List[str] = []  # positional remainder after parsing
        # names EXPLICITLY set by parse() — Go's flag.Visit equivalent:
        # "was this flag given?" is distinct from "does its value equal
        # the default?" (an explicit -serve-idle-timeout=900 must not
        # read as unset)
        self.seen: set = set()
        self.usage: Optional[Callable[[], None]] = None

    # --- definition -----------------------------------------------------
    def _add(self, name: str, kind: str, default: Any, usage: str) -> Flag:
        fl = Flag(name, kind, default, usage)
        self.flags[name] = fl
        return fl

    def bool(self, name: str, default: bool, usage: str) -> Flag:
        return self._add(name, "bool", default, usage)

    def int(self, name: str, default: int, usage: str) -> Flag:
        return self._add(name, "int", default, usage)

    def float(self, name: str, default: float, usage: str) -> Flag:
        return self._add(name, "float", default, usage)

    def string(self, name: str, default: str, usage: str) -> Flag:
        return self._add(name, "string", default, usage)

    # --- output ---------------------------------------------------------
    def _print(self, msg: str) -> None:
        if self.output is not None:
            self.output.write(msg)

    def print_defaults(self) -> None:
        for name in sorted(self.flags):
            fl = self.flags[name]
            type_word = "" if fl.kind == "bool" else f" {fl.kind}"
            line = f"  -{name}{type_word}\n    \t{fl.usage}"
            is_zero = (
                (fl.kind == "bool" and fl.default is False)
                or (fl.kind in ("int", "float") and fl.default == 0)
                or (fl.kind == "string" and fl.default == "")
            )
            if not is_zero:
                line += f" (default {_format_default(fl)})"
            self._print(line + "\n")

    def default_usage(self) -> None:
        self._print(f"Usage of {self.name}:\n")
        self.print_defaults()

    def _usage(self) -> None:
        if self.usage is not None:
            self.usage()
        else:
            self.default_usage()

    # --- parsing --------------------------------------------------------
    def parse(self, args: List[str]) -> bool:
        """Parse ``args``; returns False (after printing error + usage) on the
        first failure, mirroring ``ContinueOnError``."""
        self.args = list(args)
        while self.args:
            arg = self.args[0]
            if len(arg) < 2 or arg[0] != "-":
                return True  # first non-flag terminates parsing
            num_minuses = 1
            if arg[1] == "-":
                num_minuses = 2
                if len(arg) == 2:  # "--" terminates
                    self.args = self.args[1:]
                    return True
            name = arg[num_minuses:]
            if not name or name[0] == "-" or name[0] == "=":
                return self._fail(f"bad flag syntax: {arg}")
            self.args = self.args[1:]

            has_value = False
            value = ""
            if "=" in name:
                name, _, value = name.partition("=")
                has_value = True

            fl = self.flags.get(name)
            if fl is None:
                if name in ("help", "h"):  # Go's ErrHelp path
                    self._usage()
                    return False
                return self._fail(f"flag provided but not defined: -{name}")
            self.seen.add(name)

            if fl.kind == "bool":
                if has_value:
                    try:
                        fl.value = _parse_go_bool(value)
                    except ValueError:
                        return self._fail(
                            f'invalid boolean value "{value}" for -{name}: '
                            "parse error"
                        )
                else:
                    fl.value = True
                continue

            if not has_value:
                if not self.args:
                    return self._fail(f"flag needs an argument: -{name}")
                value = self.args[0]
                self.args = self.args[1:]

            try:
                if fl.kind == "int":
                    fl.value = go_atoi(value)
                elif fl.kind == "float":
                    fl.value = go_parse_float(value)
                else:
                    fl.value = value
            except ValueError:
                return self._fail(
                    f'invalid value "{value}" for flag -{name}: parse error'
                )
        return True

    def _fail(self, msg: str) -> bool:
        self._print(msg + "\n")
        self._usage()
        return False
