"""Buffered stderr logging.

Reference: ``logbuf.BufferingWriter`` (logbuf/logbuf.go) — a mutex-guarded,
size- and time-triggered buffered writer with a background flusher. The Go
version flushes on a 100 ms ticker, asynchronously when the buffer passes
half-full (logbuf.go:68-71), and on garbage-collection notifications via
gcnotifier (logbuf.go:121-128) — flushing when the buffer is about to be
collected anyway. The Python rebuild mirrors all three triggers: the GC hook
uses :mod:`gc` callbacks (fires after each collection pass), which is the
CPython analog of Go's AfterGC notification.
"""

from __future__ import annotations

import gc
import threading
import time
from typing import TextIO


class BufferingWriter:
    """Size/time/GC-flushed buffering writer (logbuf/logbuf.go:11-111)."""

    def __init__(
        self, w: TextIO, flush_time: float = 0.1, flush_size: int = 4096
    ):
        self._w = w
        self._flush_time = flush_time
        self._flush_size = flush_size
        self._buf: list = []  # list of strings; joined on flush
        self._buf_len = 0
        self._lock = threading.Lock()
        self._flush_req = False
        self._err = None
        self._closed = threading.Event()

        self._gc_cb = self._on_gc
        gc.callbacks.append(self._gc_cb)

        self._thread = None
        if flush_time > 0:
            self._thread = threading.Thread(
                target=self._run, name="logbuf-flusher", daemon=True
            )
            self._thread.start()

    # -- io.Writer ------------------------------------------------------
    def write(self, s: str) -> int:
        with self._lock:
            if self._err is not None:
                return 0
            if self._buf_len + len(s) >= self._flush_size:
                self._flush_locked(True)
                if self._err is not None:
                    return 0
                if len(s) >= self._flush_size:
                    self._writeall(s)
                    return len(s)
            self._buf.append(s)
            self._buf_len += len(s)
            if not self._flush_req and self._buf_len > self._flush_size // 2:
                # async flush once the buffer passes half-full (logbuf.go:68-71)
                self._flush_req = True
                threading.Thread(
                    target=self.flush, args=(True,), daemon=True
                ).start()
        return len(s)

    def flush(self, reuse_buf: bool = True) -> None:
        with self._lock:
            self._flush_locked(reuse_buf)

    def _flush_locked(self, _reuse_buf: bool) -> None:
        if self._err is not None:
            return
        data = "".join(self._buf)
        self._buf = []
        self._buf_len = 0
        self._flush_req = False
        if data:
            self._writeall(data)

    def _writeall(self, data: str) -> None:
        try:
            self._w.write(data)
            if hasattr(self._w, "flush"):
                try:
                    self._w.flush()
                except Exception:
                    pass
        except Exception as exc:
            self._err = exc

    def close(self) -> None:
        self._closed.set()
        try:
            gc.callbacks.remove(self._gc_cb)
        except ValueError:
            pass
        self.flush(False)

    # -- background triggers --------------------------------------------
    def _run(self) -> None:
        while not self._closed.wait(self._flush_time):
            self.flush(True)

    def _on_gc(self, phase: str, _info: dict) -> None:
        # Flush after each GC pass (gcnotifier analog, logbuf.go:121-128).
        if phase == "stop" and not self._closed.is_set():
            # never block the GC on the writer lock
            if self._lock.acquire(blocking=False):
                try:
                    self._flush_locked(False)
                finally:
                    self._lock.release()


class Logger:
    """Minimal Go-``log``-style logger: ``YYYY/MM/DD HH:MM:SS message``.

    The reference wires the stdlib logger to the buffering writer
    (kafkabalancer.go:73-75); messages gain a trailing newline if absent.
    """

    def __init__(self, w: "BufferingWriter"):
        self._w = w

    def printf(self, msg: str) -> None:
        stamp = time.strftime("%Y/%m/%d %H:%M:%S")
        if not msg.endswith("\n"):
            msg += "\n"
        self._w.write(f"{stamp} {msg}")
