"""pprof-format CPU profiles from cProfile data.

The reference's ``-pprof`` flag (via ``github.com/pkg/profile``,
kafkabalancer.go:85, :100-102) writes a profile that ``go tool pprof``
can read: a gzipped protobuf in the ``perftools.profiles.Profile``
schema. Python's cProfile speaks neither, so this module hand-encodes
the small subset of profile.proto the converter needs — varint/
length-delimited wire format only, no protobuf dependency.

Mapping: one sample per profiled function with a single-frame stack and
values ``(calls, self-time ns)``; sample types ``samples/count`` and
``cpu/nanoseconds`` (the conventional pair pprof's CPU view expects).
cProfile keeps caller→callee edges but not full stacks, so flame-graph
depth is inherently one frame — flat ``-top`` views are exact. Checked
against ``go tool pprof -raw/-top``.

profile.proto field numbers (github.com/google/pprof):
Profile{1 sample_type, 2 sample, 4 location, 5 function, 6 string_table,
9 time_nanos, 10 duration_nanos, 11 period_type, 12 period};
Sample{1 location_id*, 2 value*}; Location{1 id, 4 line};
Line{1 function_id, 2 line}; Function{1 id, 2 name, 3 system_name,
4 filename, 5 start_line}; ValueType{1 type, 2 unit}.
"""

from __future__ import annotations

import cProfile
import gzip
import time
from typing import Any, Iterable, List, Sequence


def _varint(n: int) -> bytes:
    out = bytearray()
    n &= (1 << 64) - 1  # proto uint64 wrap for negatives
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _field_varint(field: int, value: int) -> bytes:
    return _varint(field << 3 | 0) + _varint(value)


def _field_bytes(field: int, payload: bytes) -> bytes:
    return _varint(field << 3 | 2) + _varint(len(payload)) + payload


def _packed(field: int, values: Iterable[int]) -> bytes:
    body = b"".join(_varint(v) for v in values)
    return _field_bytes(field, body)


def _value_type(type_idx: int, unit_idx: int) -> bytes:
    return _field_varint(1, type_idx) + _field_varint(2, unit_idx)


def encode_profile(entries: Sequence[Any], duration_ns: int) -> bytes:
    """Encode ``cProfile.Profile.getstats()`` entries as an uncompressed
    profile.proto message."""
    strings: List[str] = [""]
    str_idx = {"": 0}

    def s(text: str) -> int:
        idx = str_idx.get(text)
        if idx is None:
            idx = str_idx[text] = len(strings)
            strings.append(text)
        return idx

    samples = b""
    functions = b""
    locations = b""
    for i, entry in enumerate(entries):
        code = entry.code
        if isinstance(code, str):  # builtin: '<built-in ...>' description
            name, filename, line = code, "~", 0
        else:
            name = code.co_name
            filename = code.co_filename
            line = code.co_firstlineno
        fid = i + 1
        functions += _field_bytes(
            5,
            _field_varint(1, fid)
            + _field_varint(2, s(name))
            + _field_varint(3, s(name))
            + _field_varint(4, s(filename))
            + _field_varint(5, line),
        )
        locations += _field_bytes(
            4,
            _field_varint(1, fid)
            + _field_bytes(
                4, _field_varint(1, fid) + _field_varint(2, line)
            ),
        )
        samples += _field_bytes(
            2,
            _packed(1, [fid])
            + _packed(
                2,
                [entry.callcount, int(entry.inlinetime * 1e9)],
            ),
        )

    sample_types = _field_bytes(
        1, _value_type(s("samples"), s("count"))
    ) + _field_bytes(1, _value_type(s("cpu"), s("nanoseconds")))
    period_type = _field_bytes(11, _value_type(s("cpu"), s("nanoseconds")))
    string_table = b"".join(
        _field_bytes(6, t.encode("utf-8")) for t in strings
    )
    return (
        sample_types
        + samples
        + locations
        + functions
        + string_table
        + _field_varint(9, time.time_ns())
        + _field_varint(10, max(0, duration_ns))
        + period_type
        + _field_varint(12, 1)
    )


def write_pprof(
    profiler: cProfile.Profile, path: str, duration_ns: int = 0
) -> None:
    """Write ``profiler`` (a ``cProfile.Profile``) as a gzipped pprof
    profile readable by ``go tool pprof``."""
    data = encode_profile(profiler.getstats(), duration_ns)
    with gzip.open(path, "wb") as f:
        f.write(data)
