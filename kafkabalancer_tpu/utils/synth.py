"""Synthetic cluster generation for benchmarks and dry runs.

Produces deliberately unbalanced assignments in the shape of the reference's
fixture (test/test.json: a few brokers hot, most cold) scaled to arbitrary
partition/broker counts. Deterministic per seed.
"""

from __future__ import annotations

import random


from kafkabalancer_tpu.models import Partition, PartitionList


def synth_cluster(
    n_partitions: int,
    n_brokers: int,
    rf: int = 3,
    seed: int = 0,
    weighted: bool = True,
    skew: float = 3.0,
    num_consumers_max: int = 0,
    zipf_topics: bool = False,
) -> PartitionList:
    """An unbalanced ``n_partitions`` × ``n_brokers`` assignment.

    Brokers are skewed: low-ID brokers are ``skew``× likelier to hold
    replicas, mimicking a cluster that grew by adding brokers (the
    README.md:109-124 scenario at scale).

    ``zipf_topics`` replaces the uniform 50-partition topic blocks with
    power-law topic sizes (a few huge topics, a long tail of small ones
    — the shape real Kafka clusters have) and gives each topic a base
    throughput so partitions of one topic carry similar weights. This is
    the realistic instance shape for the anti-colocation objective: big
    topics are exactly the ones whose replicas crowd onto hot brokers.
    """
    rng = random.Random(seed)
    brokers = list(range(1, n_brokers + 1))
    # population weights: broker i gets weight skew..1 linearly
    bw = [skew - (skew - 1.0) * i / max(1, n_brokers - 1) for i in range(n_brokers)]

    if zipf_topics and n_partitions > 0:
        # ~n/32 topics with power-law sizes normalized to sum to
        # n_partitions: a few hundred-partition topics, a long tail of
        # small ones (floor 2, shrunk when the instance is tiny so the
        # remainder distribution below always terminates)
        n_topics = max(1, min(n_partitions // 2, max(4, n_partitions // 32)))
        floor = 2 if n_partitions >= 2 * n_topics else 1
        raw = [1.0 / (t + 1) ** 0.9 for t in range(n_topics)]
        scale = n_partitions / sum(raw)
        sizes = [max(floor, int(r * scale)) for r in raw]
        total = sum(sizes)
        # distribute the rounding remainder over the largest topics
        t = 0
        while total != n_partitions:
            step = 1 if total < n_partitions else -1
            if sizes[t % n_topics] + step >= floor:
                sizes[t % n_topics] += step
                total += step
            t += 1
        topic_of = []
        for t, s in enumerate(sizes):
            base = rng.uniform(0.5, 2.0)
            topic_of.extend([(f"t{t}", i, base) for i in range(s)])
        rng.shuffle(topic_of)
    else:
        topic_of = [
            (f"t{i % max(1, n_partitions // 50)}", i, None)
            for i in range(n_partitions)
        ]

    parts = []
    for i in range(n_partitions):
        replicas: list = []
        while len(replicas) < min(rf, n_brokers):
            (b,) = rng.choices(brokers, weights=bw)
            if b not in replicas:
                replicas.append(b)
        topic, pid, base = topic_of[i]
        if weighted:
            if base is not None:
                # same-topic partitions carry similar throughput
                weight = round(base * rng.uniform(0.8, 1.25), 3)
            else:
                weight = round(rng.uniform(0.5, 2.0), 3)
        else:
            weight = 0.0
        parts.append(
            Partition(
                topic=topic,
                partition=pid,
                replicas=replicas,
                weight=weight,
                num_consumers=(
                    rng.randint(0, num_consumers_max) if num_consumers_max else 0
                ),
            )
        )
    return PartitionList(version=1, partitions=parts)
