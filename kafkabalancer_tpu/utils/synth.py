"""Synthetic cluster generation for benchmarks and dry runs.

Produces deliberately unbalanced assignments in the shape of the reference's
fixture (test/test.json: a few brokers hot, most cold) scaled to arbitrary
partition/broker counts. Deterministic per seed.
"""

from __future__ import annotations

import random
from typing import List, Optional

from kafkabalancer_tpu.models import Partition, PartitionList


def synth_cluster(
    n_partitions: int,
    n_brokers: int,
    rf: int = 3,
    seed: int = 0,
    weighted: bool = True,
    skew: float = 3.0,
    num_consumers_max: int = 0,
    zipf_topics: bool = False,
) -> PartitionList:
    """An unbalanced ``n_partitions`` × ``n_brokers`` assignment.

    Brokers are skewed: low-ID brokers are ``skew``× likelier to hold
    replicas, mimicking a cluster that grew by adding brokers (the
    README.md:109-124 scenario at scale).

    ``zipf_topics`` replaces the uniform 50-partition topic blocks with
    power-law topic sizes (a few huge topics, a long tail of small ones
    — the shape real Kafka clusters have) and gives each topic a base
    throughput so partitions of one topic carry similar weights. This is
    the realistic instance shape for the anti-colocation objective: big
    topics are exactly the ones whose replicas crowd onto hot brokers.
    """
    rng = random.Random(seed)
    brokers = list(range(1, n_brokers + 1))
    # population weights: broker i gets weight skew..1 linearly
    bw = [skew - (skew - 1.0) * i / max(1, n_brokers - 1) for i in range(n_brokers)]

    if zipf_topics and n_partitions > 0:
        # ~n/32 topics with power-law sizes normalized to sum to
        # n_partitions: a few hundred-partition topics, a long tail of
        # small ones (floor 2, shrunk when the instance is tiny so the
        # remainder distribution below always terminates)
        n_topics = max(1, min(n_partitions // 2, max(4, n_partitions // 32)))
        floor = 2 if n_partitions >= 2 * n_topics else 1
        raw = [1.0 / (t + 1) ** 0.9 for t in range(n_topics)]
        scale = n_partitions / sum(raw)
        sizes = [max(floor, int(r * scale)) for r in raw]
        total = sum(sizes)
        # distribute the rounding remainder over the largest topics
        t = 0
        while total != n_partitions:
            step = 1 if total < n_partitions else -1
            if sizes[t % n_topics] + step >= floor:
                sizes[t % n_topics] += step
                total += step
            t += 1
        topic_of = []
        for t, s in enumerate(sizes):
            base = rng.uniform(0.5, 2.0)
            topic_of.extend([(f"t{t}", i, base) for i in range(s)])
        rng.shuffle(topic_of)
    else:
        topic_of = [
            (f"t{i % max(1, n_partitions // 50)}", i, None)
            for i in range(n_partitions)
        ]

    parts = []
    for i in range(n_partitions):
        replicas: list = []
        while len(replicas) < min(rf, n_brokers):
            (b,) = rng.choices(brokers, weights=bw)
            if b not in replicas:
                replicas.append(b)
        topic, pid, base = topic_of[i]
        if weighted:
            if base is not None:
                # same-topic partitions carry similar throughput
                weight = round(base * rng.uniform(0.8, 1.25), 3)
            else:
                weight = round(rng.uniform(0.5, 2.0), 3)
        else:
            weight = 0.0
        parts.append(
            Partition(
                topic=topic,
                partition=pid,
                replicas=replicas,
                weight=weight,
                num_consumers=(
                    rng.randint(0, num_consumers_max) if num_consumers_max else 0
                ),
            )
        )
    return PartitionList(version=1, partitions=parts)


def rotation_locked_cluster(
    n_groups: int, weight: float = 1.0
) -> PartitionList:
    """Anti-colocation instances whose only improvements are 3-move
    ROTATIONS — the workload class where beam search's uphill sequences
    are provably necessary (benchmarks/RESULTS.md round-5 beam note).

    Each group owns three brokers (x, y, z) and three topics (A, B, C),
    six rf=2 partitions arranged so that per group (weights all equal,
    every broker's load exactly 6w, num_consumers 0):

    - three colocations are RESOLVABLE only by the follower rotation
      ``A2f: x->y, B2f: y->z, C2f: z->x`` (restricted broker lists allow
      exactly one foreign target per movable follower; the other three
      partitions are frozen — their only allowed targets are already
      members);
    - each rotation step alone is UPHILL for the combined objective
      (perfect load balance means any single move costs
      pen(5w)+pen(7w)-2*pen(6w) = 1/24 in rel^2 units; pick
      λ < 1/24 ≈ 0.0417 — e.g. 0.015 — so no single follower move and
      no broker-disjoint PAIR SWAP improves: the swap partners the
      polish phase would need are blocked by membership or the
      restricted lists);
    - the full 3-cycle returns every load to 6w and removes 3
      colocations: net -3λ, reachable ONLY through sequence-level
      acceptance of uphill prefixes (beam depth >= 3).

    Groups are independent and identical, so the certified gap between
    the greedy-session+polish floor and beam's floor is exactly
    3λ·n_groups.
    """
    parts = []
    for g in range(n_groups):
        x, y, z = 3 * g + 1, 3 * g + 2, 3 * g + 3
        A, B, C = f"rotA{g}", f"rotB{g}", f"rotC{g}"

        def part(
            topic: str, pid: int, leader: int, follower: int,
            allowed: Optional[List[int]],
        ) -> None:
            parts.append(
                Partition(
                    topic=topic,
                    partition=pid,
                    replicas=[leader, follower],
                    weight=weight,
                    brokers=sorted(allowed),
                    num_consumers=0,
                )
            )

        part(A, 1, x, z, [x, z])        # frozen
        part(A, 2, z, x, [z, x, y])     # movable follower x -> y
        part(B, 1, y, x, [y, x])        # frozen
        part(B, 2, x, y, [x, y, z])     # movable follower y -> z
        part(C, 1, z, y, [z, y])        # frozen
        part(C, 2, y, z, [y, z, x])     # movable follower z -> x
    return PartitionList(version=1, partitions=parts)
