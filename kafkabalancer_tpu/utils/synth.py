"""Synthetic cluster generation for benchmarks and dry runs.

Produces deliberately unbalanced assignments in the shape of the reference's
fixture (test/test.json: a few brokers hot, most cold) scaled to arbitrary
partition/broker counts. Deterministic per seed.
"""

from __future__ import annotations

import random


from kafkabalancer_tpu.models import Partition, PartitionList


def synth_cluster(
    n_partitions: int,
    n_brokers: int,
    rf: int = 3,
    seed: int = 0,
    weighted: bool = True,
    skew: float = 3.0,
    num_consumers_max: int = 0,
) -> PartitionList:
    """An unbalanced ``n_partitions`` × ``n_brokers`` assignment.

    Brokers are skewed: low-ID brokers are ``skew``× likelier to hold
    replicas, mimicking a cluster that grew by adding brokers (the
    README.md:109-124 scenario at scale).
    """
    rng = random.Random(seed)
    brokers = list(range(1, n_brokers + 1))
    # population weights: broker i gets weight skew..1 linearly
    bw = [skew - (skew - 1.0) * i / max(1, n_brokers - 1) for i in range(n_brokers)]
    parts = []
    for i in range(n_partitions):
        replicas: list = []
        while len(replicas) < min(rf, n_brokers):
            (b,) = rng.choices(brokers, weights=bw)
            if b not in replicas:
                replicas.append(b)
        parts.append(
            Partition(
                topic=f"t{i % max(1, n_partitions // 50)}",
                partition=i,
                replicas=replicas,
                weight=round(rng.uniform(0.5, 2.0), 3) if weighted else 0.0,
                num_consumers=(
                    rng.randint(0, num_consumers_max) if num_consumers_max else 0
                ),
            )
        )
    return PartitionList(version=1, partitions=parts)
