#!/usr/bin/env bash
# Pre-merge correctness gate for kafkabalancer-tpu.
#
# Runs, in order:
#   1. jaxlint          — the project's JAX-aware linter (per-module
#                         rules, --list-rules lint), over the package
#                         AND bench.py
#   1b. contracts       — the whole-program contract analyzer
#                         (--list-rules contracts): import-purity
#                         reachability, lock-order + thread-role
#                         concurrency lint, schema-drift vs goldens
#                         (docs/static-analysis.md)
#   2. annotation floor — strict-annotation coverage of the typed
#                         subpackages (every package in $typed_pkgs);
#                         the dependency-free half of the typing gate
#   3. mypy --strict    — on the same subpackages, when mypy is installed
#   4. ruff check       — when ruff is installed
#   5. cold-start smoke — fresh single-move CLI subprocess against a
#                         temp AOT store, cache-cold then cache-warm
#                         (docs/cold-start.md)
#   6. observability    — run the CLI with -stats -metrics-json - on the
#      smoke               example input; the metrics line must parse
#                         and carry the schema version + lifecycle spans
#                         (docs/observability.md)
#   6b. explain smoke   — -explain on the example input: plan bytes
#                         pinned unchanged, the explain/1 document
#                         schema-valid and internally reconciled, and a
#                         forced no-move exit classified in both the
#                         document and the plan.no_move_reason gauge
#   7. serve smoke      — start the planning daemon, plan through it,
#                         assert byte parity with the in-process path,
#                         clean shutdown (docs/serving.md)
#   7b. e2e-trace smoke — a served invocation with -trace: ONE merged
#                         Perfetto doc with client + daemon process
#                         tracks under a single trace id, daemon spans
#                         parented under the client's serve.forward
#                         span and never starting before it, and the
#                         daemon-written -metrics-json carrying the
#                         trace id + client.phase.* edge attribution
#                         (docs/observability.md § End-to-end tracing)
#   8. fused-shard      — byte parity of the sharded session vs the
#      parity smoke       single-device plan, on real multi-device
#                         hosts or a faked 2-device CPU mesh (skips on
#                         a single non-CPU device)
#   8b. sharded-scale   — the SCALE tier pin: a 20000-row cluster split
#      parity             across a faked 2-device CPU mesh
#                         (plan_sharded(scale=True): fine-ladder
#                         buckets, lean membership, sharded upload,
#                         row-chunked scoring) byte-identical to the
#                         single-device plan (docs/ENGINES.md)
#   9. continuous       — K concurrent clients against a daemon with a
#      batching +         deterministic admission hold: per-client
#      live-scrape        served attribution + byte parity vs
#      smoke              -no-daemon, fused occupancy > 1 via the
#                         export-time re-snapshotted -metrics-json
#                         gauges; PLUS the live telemetry scrape —
#                         -serve-stats-json mid- and post-traffic
#                         (phase histograms present, request counts
#                         reconciling exactly with serve.requests) and
#                         -serve-dump-trace producing valid Perfetto
#                         JSON (docs/observability.md)
#  10. resident-session — the protocol-v2 session ladder end to end:
#      smoke              register, two outer-loop delta moves (byte
#                         parity vs -no-daemon at every step),
#                         serve.delta_hits >= 1 and session bytes
#                         present via -serve-stats-json
#  10b. speculative     — register -> 3 outer-loop moves with
#      plan-ahead smoke    memoizable answers: >= 1 serve.spec hit via
#                         the serve-stats/8 scrape (attribution
#                         required), the speculation identity exact,
#                         byte parity vs -no-daemon at every step
#  10c. watch-mode      — a -watch daemon over the fake-ZK seam emits
#      smoke              one plan with ZERO client plan ops, byte-
#                         identical to -no-daemon on the same state;
#                         watch lag observable via the `watch` op
#  10d. edge-residency — the client shadow digest cache end to end:
#      smoke              the same unchanged input served 3x through a
#                         daemon; runs 2+3 must stamp
#                         client.edge_cache_hit=true into the
#                         daemon-written -metrics-json (the O(P) client
#                         read+parse+digest skipped via the stat rung),
#                         a .kbec entry persisted beside the socket,
#                         byte parity vs -no-daemon on every run
#  11. replay smoke     — seeded 3-tenant churn replay against a
#                         private daemon: serve-stats/8 schema,
#                         per-tenant counts reconciling exactly with
#                         the driver, scrape-vs-flight latency within
#                         one histogram bucket, plan byte parity vs
#                         -no-daemon on a sampled request
#  12. overload + chaos — seeded --chaos replay: fault injection (lane
#      smoke               crash, dispatch delays, socket drops,
#                         transfer failure) + sustained overload past
#                         the queue cap; sheds observed with a
#                         retry-after estimate, plan-byte parity on
#                         EVERY answered request, shed/requeue/
#                         quarantine accounting reconciled exactly,
#                         daemon alive at the end
#  13. session          — register -> delta -> SIGKILL -> restart ->
#      durability smoke   delta answered from a warm spill restore
#                         (restore_hits via -serve-stats-json, byte
#                         parity vs -no-daemon at every step), plus a
#                         seeded spill_corrupt restart replay that
#                         must answer cold-but-correct
#  14. tier-1 tests     — the ROADMAP.md verify suite (skip: --no-tests)
#
# Exit 0 only when every stage that ran passed. Optional tools that are
# not installed SKIP with a notice instead of failing: the gate must be
# meaningful in the hermetic build image (no mypy/ruff) and strict on a
# dev box (both present). See docs/static-analysis.md.

set -u -o pipefail
cd "$(dirname "$0")/.."

# python3-only hosts (stock Debian/Ubuntu) have no bare `python`
PYTHON=${PYTHON:-$(command -v python3 || echo python)}

run_tests=1
for arg in "$@"; do
  case "$arg" in
    --no-tests) run_tests=0 ;;
    *) echo "usage: scripts/gate.sh [--no-tests]" >&2; exit 2 ;;
  esac
done

fail=0
step() { printf '\n== %s\n' "$1"; }

# stage labels name the rules they run so the gate output and the
# analyzer cannot drift apart — both lists come from --list-rules
lint_rules=$("$PYTHON" -m kafkabalancer_tpu.analysis --list-rules lint)
contract_rules=$("$PYTHON" -m kafkabalancer_tpu.analysis --list-rules contracts)

step "jaxlint ($lint_rules)"
# bench.py rides along: it is outside the package tree but carries the
# same jax-dtype/dispatch idioms the rules police
"$PYTHON" -m kafkabalancer_tpu.analysis kafkabalancer_tpu/ bench.py || fail=1

step "contracts ($contract_rules)"
# whole-program pass: import-purity reachability vs the declared
# manifest, lock-order + thread-role concurrency lint over serve/+obs/,
# schema drift vs the golden pins. Zero unsuppressed findings to merge;
# every suppression must carry a reason (SUP).
"$PYTHON" -m kafkabalancer_tpu.analysis --contracts || fail=1

# the typed subpackages — one list feeds both the annotation floor and
# the mypy stage so they cannot drift apart
typed_pkgs="kafkabalancer_tpu/models kafkabalancer_tpu/ops \
  kafkabalancer_tpu/codecs kafkabalancer_tpu/obs kafkabalancer_tpu/serve \
  kafkabalancer_tpu/balancer kafkabalancer_tpu/solvers \
  kafkabalancer_tpu/parallel kafkabalancer_tpu/replay \
  kafkabalancer_tpu/utils"

step "annotation coverage (mypy --strict floor)"
# shellcheck disable=SC2086  # word-splitting the path list is the point
"$PYTHON" -m kafkabalancer_tpu.analysis --annotations $typed_pkgs || fail=1

step "mypy --strict (typed subpackages)"
if command -v mypy >/dev/null 2>&1; then
  # shellcheck disable=SC2086
  mypy --strict $typed_pkgs || fail=1
else
  echo "mypy not installed — skipped (annotation-coverage floor ran above)"
fi

step "ruff check"
if command -v ruff >/dev/null 2>&1; then
  ruff check . || fail=1
else
  echo "ruff not installed — skipped"
fi

step "cold-start smoke (fresh CLI, temp AOT store)"
# The stateless deployment unit end to end, twice against one throwaway
# store: the first subprocess is cache-COLD (jit path + async store
# write), the second cache-WARM (store hit / clean fallback). Both must
# exit 0 — this is the stage that catches a cold-path regression (a
# prefetch crash, a corrupt-store crash, a store write that poisons the
# next invocation) before merge. Sync saves so run 1's write has landed
# before run 2 reads it.
smoke_tmp=$(mktemp -d)
cold_smoke() {
  JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$smoke_tmp" \
  KAFKABALANCER_TPU_AOT_SYNC_SAVE=1 \
  "$PYTHON" -m kafkabalancer_tpu -input-json -input tests/data/test.json \
    -fused -fused-batch=4 -max-reassign=4 -no-daemon >/dev/null
}
if cold_smoke; then
  echo "cache-cold invocation: OK"
  if cold_smoke; then
    echo "cache-warm invocation: OK"
  else
    echo "cache-warm invocation FAILED"; fail=1
  fi
else
  echo "cache-cold invocation FAILED"; fail=1
fi
rm -rf "$smoke_tmp"

step "observability smoke (-stats -metrics-json -)"
# The flag trio end to end on the example input: the metrics line must
# be the LAST stdout line (the plan precedes it), parse as JSON, and
# carry the schema version + lifecycle spans — this is the stage that
# catches a broken exporter or a schema drift before merge
# (docs/observability.md).
obs_out=$(JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
  -input tests/data/test.json -stats -metrics-json - -no-daemon \
  2>/dev/null | tail -n 1)
if printf '%s' "$obs_out" | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
assert p["schema"] == "kafkabalancer-tpu.metrics/1", p.get("schema")
assert p["rc"] == 0, p.get("rc")
names = {s["name"] for s in p["spans"]}
assert {"parse_input", "plan", "emit"} <= names, sorted(names)
'; then
  echo "metrics JSON: OK"
else
  echo "observability smoke FAILED"; fail=1
fi

step "explain smoke (-explain: schema, reconciliation, plan-byte parity)"
# The plan-explanation document end to end (docs/observability.md):
# a fused plan with -explain must (a) leave the plan bytes untouched,
# (b) emit a schema-valid kafkabalancer-tpu.explain/1 document whose
# per-move scores reconcile internally (score_delta == after - before,
# src/dst load deltas consistent), and (c) classify a no-move exit
# (plan.no_move_reason) instead of leaving it indistinguishable from a
# converged one. The new modules (obs/convergence.py, serve/devmem.py)
# ride the jaxlint/annotation/mypy sweeps above by location.
ex_tmp=$(mktemp -d)
ex_plain=$(JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
  -input tests/data/test.json -fused -fused-batch=4 -max-reassign=4 \
  -no-daemon 2>/dev/null)
ex_out=$(JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
  -input tests/data/test.json -fused -fused-batch=4 -max-reassign=4 \
  -no-daemon "-explain=$ex_tmp/explain.json" 2>/dev/null)
if [ -n "$ex_plain" ] && [ "$ex_plain" = "$ex_out" ]; then
  echo "plan-byte parity with -explain: OK"
else
  echo "plan-byte parity with -explain FAILED"; fail=1
fi
if "$PYTHON" - "$ex_tmp/explain.json" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1]))
assert doc["schema"] == "kafkabalancer-tpu.explain/1", doc.get("schema")
assert doc["moves_applied"] == len(doc["moves"]) > 0, doc["moves_applied"]
assert doc["moves_emitted"] == sum(m["emitted"] for m in doc["moves"]) > 0
for m in doc["moves"]:
    assert m["score_delta"] == m["unbalance_after"] - m["unbalance_before"]
    for k in ("topic", "partition", "kind", "src", "dst",
              "unbalance_before", "unbalance_after"):
        assert k in m, (k, sorted(m))
assert doc["no_move_reason"] is None
assert doc["stop"]["reason"], doc["stop"]
assert doc["candidates"]["scored"] > 0, doc["candidates"]
PYEOF
then
  echo "explain document schema + reconciliation: OK"
else
  echo "explain document validation FAILED"; fail=1
fi
# no-move exit: a sky-high threshold must classify as below_threshold
# in BOTH the explain stanza and the -metrics-json gauge
JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
  -input tests/data/test.json -fused -fused-batch=4 -max-reassign=4 \
  -min-unbalance=999999 -no-daemon "-explain=$ex_tmp/nomove.json" \
  "-metrics-json=$ex_tmp/nomove.metrics.json" >/dev/null 2>&1
if "$PYTHON" - "$ex_tmp" <<'PYEOF'
import json, sys
doc = json.load(open(sys.argv[1] + "/nomove.json"))
assert doc["moves_emitted"] == 0, doc["moves_emitted"]
assert doc["no_move_reason"]["reason"] == "below_threshold", doc["no_move_reason"]
m = json.load(open(sys.argv[1] + "/nomove.metrics.json"))
assert m["gauges"]["plan.no_move_reason"] == "below_threshold", m["gauges"]
PYEOF
then
  echo "no-move classification (explain + metrics gauge): OK"
else
  echo "no-move classification FAILED"; fail=1
fi
rm -rf "$ex_tmp"

step "serve smoke (daemon parity + clean shutdown)"
# The persistent planning daemon end to end: start it on a private
# socket, plan the example input THROUGH it, assert byte parity with
# the in-process path (-no-daemon), then shut it down cleanly. This is
# the stage that catches a forwarding/parity regression — the outer
# loop's contract is that a served plan is indistinguishable from a
# stateless one (docs/serving.md).
serve_tmp=$(mktemp -d)
serve_sock="$serve_tmp/kb.sock"
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$serve_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$serve_sock" \
  -serve-idle-timeout=120 >"$serve_tmp/daemon.log" 2>&1 &
serve_pid=$!
serve_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$serve_sock') else 1)" 2>/dev/null; then
    serve_ready=1; break
  fi
  sleep 0.25
done
if [ "$serve_ready" = 1 ]; then
  served_out=$(JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu \
    -input-json -input tests/data/test.json "-serve-socket=$serve_sock" \
    "-metrics-json=$serve_tmp/served.metrics.json" 2>/dev/null)
  local_out=$(JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu \
    -input-json -input tests/data/test.json -no-daemon 2>/dev/null)
  if [ -n "$served_out" ] && [ "$served_out" = "$local_out" ]; then
    echo "served plan parity: OK"
  else
    echo "served plan parity FAILED"; fail=1
  fi
  # byte parity alone is satisfied by the in-process FALLBACK — assert
  # the plan actually went through the daemon (served: true gauge),
  # otherwise a broken forwarding path sails through this stage
  if "$PYTHON" -c "import json, sys
m = json.load(open('$serve_tmp/served.metrics.json'))
sys.exit(0 if m.get('gauges', {}).get('served') else 1)" 2>/dev/null; then
    echo "served attribution: OK"
  else
    echo "served attribution MISSING — plan fell back in-process"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$serve_sock')" || true
  if wait "$serve_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $serve_tmp/daemon.log)"
  cat "$serve_tmp/daemon.log" 2>/dev/null | tail -20
  kill "$serve_pid" 2>/dev/null
  fail=1
fi
rm -rf "$serve_tmp"

step "e2e-trace smoke (merged client+daemon timeline, one trace id)"
# The end-to-end tracing tentpole (docs/observability.md § End-to-end
# tracing): a forwarded invocation with -trace must write ONE merged
# Perfetto document — the client's edge phase chain plus the daemon's
# reply-footer span subtree on a second process track, aligned by the
# handshake clock-offset estimate — and the daemon-written
# -metrics-json line must carry the same trace id with client.phase.*
# edge attribution. A subprocess daemon: two processes, two clocks.
et_tmp=$(mktemp -d)
et_sock="$et_tmp/kb.sock"
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$et_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$et_sock" \
  -serve-idle-timeout=120 -serve-lanes=1 >"$et_tmp/daemon.log" 2>&1 &
et_pid=$!
et_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$et_sock') else 1)" 2>/dev/null; then
    et_ready=1; break
  fi
  sleep 0.25
done
if [ "$et_ready" = 1 ]; then
  if JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu \
      -input-json -input tests/data/test.json "-serve-socket=$et_sock" \
      "-trace=$et_tmp/merged.trace.json" \
      "-metrics-json=$et_tmp/served.metrics.json" \
      >/dev/null 2>"$et_tmp/client.log" \
    && "$PYTHON" - "$et_tmp" <<'EOF'
import json, os, sys
tmp = sys.argv[1]
doc = json.load(open(os.path.join(tmp, "merged.trace.json")))
other = doc["otherData"]
assert other["served"] is True, "forward fell back in-process"
tid = other["trace_id"]
assert isinstance(tid, str) and len(tid) == 16
xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
daemon_x = [e for e in xs if e.get("args", {}).get("daemon")]
client_x = [e for e in xs if not e.get("args", {}).get("daemon")]
assert daemon_x, "no daemon track in the merged doc"
names = {e["name"] for e in client_x}
for p in ("client.input_read", "client.send", "client.receive"):
    assert p in names, sorted(names)
fwd = [e for e in client_x if e["name"] == "serve.forward"]
assert len(fwd) == 1 and fwd[0]["args"]["trace_id"] == tid
fwd_sid = next(e["args"]["parent_sid"] for e in client_x
               if e["name"] == "client.send")
for e in daemon_x:
    assert e["args"]["trace_id"] == tid
    assert e["args"]["parent_sid"] == fwd_sid
    assert e["ts"] >= fwd[0]["ts"], "daemon span precedes its parent"
m = json.load(open(os.path.join(tmp, "served.metrics.json")))
g = m["gauges"]
assert g["trace_id"] == tid, "metrics line / trace doc id mismatch"
assert any(k.startswith("client.phase.") for k in g), sorted(g)
print("merged timeline: OK "
      f"(trace {tid}, {len(daemon_x)} daemon spans, "
      f"offset {other['clock_offset_ns']}ns rtt {other['clock_rtt_ns']}ns)")
EOF
  then
    echo "e2e-trace smoke: OK"
  else
    echo "e2e-trace smoke FAILED (see $et_tmp)"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$et_sock')" || true
  wait "$et_pid" 2>/dev/null
else
  echo "daemon never became ready (see $et_tmp/daemon.log)"
  tail -20 "$et_tmp/daemon.log" 2>/dev/null
  kill "$et_pid" 2>/dev/null
  fail=1
fi
if [ "$fail" = 0 ]; then rm -rf "$et_tmp"; fi

step "serve throughput smoke (2 concurrent clients, lane attribution)"
# The multi-lane/microbatch serving path end to end: daemon up (default
# auto lanes + microbatching), TWO concurrent clients with DISTINCT
# inputs, both must complete with served: true and serve.lanes >= 1 in
# their -metrics-json — the stage that catches a scheduler wedge, a
# fused-dispatch crash, or lost lane attribution before merge
# (docs/serving.md).
rps_tmp=$(mktemp -d)
rps_sock="$rps_tmp/kb.sock"
# distinct second input: same shape bucket, different content
"$PYTHON" - "$rps_tmp" <<'PYEOF'
import json, sys
with open("tests/data/test.json") as f:
    data = json.load(f)
p0 = data["partitions"][0]
p0["replicas"] = list(reversed(p0["replicas"]))
with open(sys.argv[1] + "/variant.json", "w") as f:
    json.dump(data, f)
PYEOF
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$rps_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$rps_sock" \
  -serve-idle-timeout=120 >"$rps_tmp/daemon.log" 2>&1 &
rps_pid=$!
rps_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$rps_sock') else 1)" 2>/dev/null; then
    rps_ready=1; break
  fi
  sleep 0.25
done
if [ "$rps_ready" = 1 ]; then
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input tests/data/test.json "-serve-socket=$rps_sock" \
    "-metrics-json=$rps_tmp/m1.json" >/dev/null 2>&1 &
  c1=$!
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$rps_tmp/variant.json" "-serve-socket=$rps_sock" \
    "-metrics-json=$rps_tmp/m2.json" >/dev/null 2>&1 &
  c2=$!
  rps_ok=1
  wait "$c1" || rps_ok=0
  wait "$c2" || rps_ok=0
  if [ "$rps_ok" = 1 ] && "$PYTHON" -c "import json, sys
for p in ('$rps_tmp/m1.json', '$rps_tmp/m2.json'):
    g = json.load(open(p)).get('gauges', {})
    assert g.get('served') is True, (p, 'not served')
    assert float(g.get('serve.lanes', 0)) >= 1, (p, 'no lane attribution')
" 2>/dev/null; then
    echo "concurrent served clients + lane attribution: OK"
  else
    echo "throughput smoke FAILED (clients rc=$rps_ok; see $rps_tmp)"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$rps_sock')" || true
  if wait "$rps_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $rps_tmp/daemon.log)"
  tail -20 "$rps_tmp/daemon.log" 2>/dev/null
  kill "$rps_pid" 2>/dev/null
  fail=1
fi
rm -rf "$rps_tmp"

step "fused-shard parity smoke (sharded session vs single-device plan)"
# MULTICHIP confirms healthy multi-device hosts, but nothing pre-merge
# ever exercised the sharded session: pin `-fused-shard` byte parity
# against the single-device plan. Real multi-device hosts use their
# ambient devices; a single-CPU host fakes a 2-device mesh the way the
# test suite does (conftest.py); a single non-CPU device skips cleanly.
shard_tmp=$(mktemp -d)
shard_probe=$(timeout 120 "$PYTHON" -c "import jax
d = jax.devices()
print(len(d), d[0].platform)" 2>/dev/null || echo "0 unknown")
shard_ndev=${shard_probe%% *}
shard_plat=${shard_probe##* }
shard_run=1
if [ "${shard_ndev:-0}" -ge 2 ] 2>/dev/null; then
  shard_env="JAX_COMPILATION_CACHE_DIR=$shard_tmp"
  echo "using $shard_ndev ambient $shard_plat devices"
elif [ "$shard_plat" = "cpu" ] || [ "$shard_plat" = "unknown" ]; then
  shard_env="JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=2 JAX_COMPILATION_CACHE_DIR=$shard_tmp"
  echo "1 visible device — faking a 2-device CPU mesh"
else
  echo "single $shard_plat device — skipped (needs >= 2 devices)"
  shard_run=0
fi
if [ "$shard_run" = 1 ]; then
  sharded_out=$(env $shard_env "$PYTHON" -m kafkabalancer_tpu \
    -input-json -input tests/data/test.json -fused -fused-shard \
    -fused-batch=4 -max-reassign=4 -no-daemon 2>/dev/null)
  single_out=$(env $shard_env "$PYTHON" -m kafkabalancer_tpu \
    -input-json -input tests/data/test.json -fused \
    -fused-batch=4 -max-reassign=4 -no-daemon 2>/dev/null)
  if [ -n "$sharded_out" ] && [ "$sharded_out" = "$single_out" ]; then
    echo "fused-shard byte parity: OK"
  else
    echo "fused-shard parity FAILED"; fail=1
  fi
fi
rm -rf "$shard_tmp"

step "sharded-scale parity (20000-row cluster split across a faked 2-device mesh)"
# The SCALE tier pre-merge pin (ISSUE 13): a 20000-partition synthetic
# cluster planned through plan_sharded(scale=True) — fine-ladder
# buckets, lean on-device membership, mesh-sharded upload, row-chunked
# scoring — must be BYTE-identical (move log and final assignment) to
# the single-device plan of the same input. Runs on a faked 2-device
# CPU mesh so every host exercises it; the tier-1 twin covers the
# 8-device 100k case (tests/test_parallel.py).
scale_tmp=$(mktemp -d)
if env JAX_PLATFORMS=cpu \
    XLA_FLAGS=--xla_force_host_platform_device_count=2 \
    JAX_COMPILATION_CACHE_DIR="$scale_tmp" JAX_ENABLE_X64=1 \
    timeout 600 "$PYTHON" - <<'PYEOF'
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.parallel.mesh import make_mesh
from kafkabalancer_tpu.parallel.shard_session import plan_sharded
from kafkabalancer_tpu.solvers.scan import plan
from kafkabalancer_tpu.utils.synth import synth_cluster


def fresh():
    pl = synth_cluster(20_000, 24, rf=3, seed=13, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    cfg.allow_leader_rebalancing = True
    return pl, cfg


def log_of(opl):
    return [
        (p.topic, p.partition, tuple(p.replicas))
        for p in (opl.partitions or [])
    ]


mesh = make_mesh(2, shape=(1, 2))
pl_s, cfg_s = fresh()
opl_s = plan_sharded(
    pl_s, cfg_s, 300, mesh, batch=32, scale=True, row_chunk=2048
)
pl_1, cfg_1 = fresh()
opl_1 = plan(pl_1, cfg_1, 300, batch=32)
assert log_of(opl_s), "scale-tier plan produced no moves"
assert log_of(opl_s) == log_of(opl_1), "move logs diverged"
assert pl_s == pl_1, "final assignments diverged"
print(f"sharded-scale parity: {len(log_of(opl_s))} moves byte-identical")
PYEOF
then
  echo "sharded-scale byte parity: OK"
else
  echo "sharded-scale parity FAILED"; fail=1
fi
rm -rf "$scale_tmp"

step "continuous batching + live-scrape smoke (3 held clients)"
# The continuous batcher end to end: a daemon with a deterministic
# admission hold (-serve-admission-hold=3 — the lane keeps its queue
# intact until the full batch arrives, no scheduler-timing luck), three
# concurrent clients with DISTINCT same-bucket inputs. Every client
# must be served (served: true), byte-identical to its own -no-daemon
# plan, and the metrics counters must show a fused dispatch of
# occupancy > 1 (serve.microbatched >= 2) plus the residency gauge —
# the stage that catches an admission wedge, a padding regression, or
# lost batching attribution before merge (docs/serving.md).
cb_tmp=$(mktemp -d)
cb_sock="$cb_tmp/kb.sock"
"$PYTHON" - "$cb_tmp" <<'PYEOF'
import json, sys
with open("tests/data/test.json") as f:
    data = json.load(f)
for i in (1, 2, 3):
    variant = json.loads(json.dumps(data))
    # distinct content, same shape bucket: reverse a different row each
    p = variant["partitions"][i]
    p["replicas"] = list(reversed(p["replicas"]))
    with open(f"{sys.argv[1]}/variant{i}.json", "w") as f:
        json.dump(variant, f)
PYEOF
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$cb_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$cb_sock" \
  -serve-admission-hold=3 -serve-idle-timeout=180 \
  >"$cb_tmp/daemon.log" 2>&1 &
cb_pid=$!
cb_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$cb_sock') else 1)" 2>/dev/null; then
    cb_ready=1; break
  fi
  sleep 0.25
done
if [ "$cb_ready" = 1 ]; then
  # warm-up: pays the solo compile and establishes the bucket's lane
  # affinity (held up to the hold window, by design)
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$cb_tmp/variant1.json" -fused -fused-batch=4 -max-reassign=4 \
    "-serve-socket=$cb_sock" >/dev/null 2>&1
  cb_ok=1
  for i in 1 2 3; do
    JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$cb_tmp" \
      "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$cb_tmp/variant$i.json" -fused -fused-batch=4 \
      -max-reassign=4 -no-daemon >"$cb_tmp/local$i.out" 2>/dev/null
  done
  for i in 1 2 3; do
    JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$cb_tmp/variant$i.json" -fused -fused-batch=4 \
      -max-reassign=4 "-serve-socket=$cb_sock" \
      "-metrics-json=$cb_tmp/m$i.json" >"$cb_tmp/served$i.out" 2>/dev/null &
    eval "cbc$i=\$!"
  done
  # live scrape MID-TRAFFIC: the stats op answers on the connection
  # thread, never through the dispatcher — it must return while the
  # held batch is still forming/in flight, with the phase histograms
  # from the earlier requests already present (docs/observability.md)
  if "$PYTHON" -m kafkabalancer_tpu "-serve-socket=$cb_sock" \
      -serve-stats-json 2>/dev/null | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
assert p["schema"] == "kafkabalancer-tpu.serve-stats/8", p.get("schema")
assert "serve.request_s" in p["hists"], sorted(p["hists"])
assert "serve.phase.parse" in p["hists"], sorted(p["hists"])
assert isinstance(p["memory"], list) and p["memory"], p.get("memory")
'; then
    echo "mid-traffic stats scrape: OK"
  else
    echo "mid-traffic stats scrape FAILED"; cb_ok=0
  fi
  wait "$cbc1" || cb_ok=0
  wait "$cbc2" || cb_ok=0
  wait "$cbc3" || cb_ok=0
  for i in 1 2 3; do
    if ! cmp -s "$cb_tmp/served$i.out" "$cb_tmp/local$i.out"; then
      echo "client $i parity FAILED"; cb_ok=0
    fi
  done
  if [ "$cb_ok" = 1 ] && "$PYTHON" -c "import json, sys
fused = 0
for i in (1, 2, 3):
    m = json.load(open(f'$cb_tmp/m{i}.json'))
    g = m.get('gauges', {})
    assert g.get('served') is True, (i, 'not served')
    assert 'serve.residency_hits' in g, (i, 'no residency gauge')
    # the export-time re-snapshot (PR 8): each client's OWN gauges now
    # include the fusion it rode, so the gauge — not the counter
    # workaround — is the reader
    fused = max(fused, g.get('serve.mb_occupancy_max', 0))
assert fused >= 2, f'no fused dispatch of occupancy > 1 (gauge {fused})'
" 2>/dev/null; then
    echo "3 held clients: served + parity + fused occupancy > 1: OK"
  else
    echo "continuous batching smoke FAILED (see $cb_tmp)"; fail=1
  fi
  # POST-TRAFFIC scrape: phase histogram request counts must reconcile
  # EXACTLY with serve.requests (the acceptance invariant), and the
  # flight recorder must export a Perfetto-loadable trace of the
  # requests just served
  if "$PYTHON" -m kafkabalancer_tpu "-serve-socket=$cb_sock" \
      -serve-stats-json 2>/dev/null | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
assert p["requests"] >= 4, p["requests"]
assert p["hists"]["serve.request_s"]["count"] == p["requests"], (
    p["hists"]["serve.request_s"]["count"], p["requests"])
for name in ("serve.phase.read", "serve.phase.queue", "serve.phase.parse",
             "serve.phase.tensorize", "serve.phase.dispatch",
             "serve.phase.encode", "serve.phase.reply"):
    assert name in p["hists"], (name, sorted(p["hists"]))
    assert p["hists"][name]["p99"] >= 0.0
'; then
    echo "post-traffic scrape reconciliation: OK"
  else
    echo "post-traffic scrape reconciliation FAILED"; fail=1
  fi
  if "$PYTHON" -m kafkabalancer_tpu "-serve-socket=$cb_sock" \
      "-serve-dump-trace=$cb_tmp/flight.trace.json" >/dev/null 2>&1 \
    && "$PYTHON" -c '
import json, sys
doc = json.load(open("'"$cb_tmp"'/flight.trace.json"))
xs = [e for e in doc["traceEvents"] if e.get("ph") == "X"]
assert xs, "no spans in the flight trace"
for e in xs:
    assert {"name", "ts", "dur", "pid", "tid"} <= set(e), e
assert doc["otherData"]["requests"], "no request log"
'; then
    echo "flight-recorder dump-trace: OK"
  else
    echo "flight-recorder dump-trace FAILED"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$cb_sock')" || true
  if wait "$cb_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $cb_tmp/daemon.log)"
  tail -20 "$cb_tmp/daemon.log" 2>/dev/null
  kill "$cb_pid" 2>/dev/null
  fail=1
fi
rm -rf "$cb_tmp"

step "resident-session smoke (register + 2 delta moves, parity + attribution)"
# The protocol-v2 resident-session ladder end to end (docs/serving.md):
# an outer loop registers its cluster once, then applies each emitted
# move to the input and re-invokes — the steady-state requests must hit
# the delta fast path (serve.delta_hits through -serve-stats-json, with
# session bytes accounted), and EVERY step's plan must be byte-identical
# to a fresh -no-daemon run on the same state.
ss_tmp=$(mktemp -d "${TMPDIR:-/tmp}/kb-gate-sess.XXXXXX")
ss_sock="$ss_tmp/kb.sock"
cp tests/data/test.json "$ss_tmp/cluster.json"
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$ss_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$ss_sock" \
  -serve-idle-timeout=180 >"$ss_tmp/daemon.log" 2>&1 &
ss_pid=$!
ss_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$ss_sock') else 1)" 2>/dev/null; then
    ss_ready=1; break
  fi
  sleep 0.25
done
if [ "$ss_ready" = 1 ]; then
  ss_ok=1
  for stp in 0 1 2; do
    JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$ss_tmp" \
      "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$ss_tmp/cluster.json" -solver=tpu -max-reassign=1 \
      -no-daemon >"$ss_tmp/local$stp.out" 2>/dev/null
    JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$ss_tmp/cluster.json" -solver=tpu -max-reassign=1 \
      "-serve-socket=$ss_sock" >"$ss_tmp/served$stp.out" 2>/dev/null
    if ! cmp -s "$ss_tmp/served$stp.out" "$ss_tmp/local$stp.out"; then
      echo "session step $stp parity FAILED"; ss_ok=0
    fi
    # the outer loop's half of the contract: apply the emitted moves
    "$PYTHON" - "$ss_tmp" "$stp" <<'PYEOF'
import json, sys
tmp, stp = sys.argv[1], sys.argv[2]
state = json.load(open(f"{tmp}/cluster.json"))
plan = json.load(open(f"{tmp}/local{stp}.out"))
for entry in plan.get("partitions") or []:
    for row in state["partitions"]:
        if (row["topic"] == entry["topic"]
                and row["partition"] == entry["partition"]):
            row["replicas"] = list(entry["replicas"])
            break
json.dump(state, open(f"{tmp}/cluster.json", "w"))
PYEOF
  done
  if [ "$ss_ok" = 1 ] && "$PYTHON" -m kafkabalancer_tpu \
      "-serve-socket=$ss_sock" -serve-stats-json 2>/dev/null \
      | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
s = p["sessions"]
assert s["count"] >= 1, s
assert s["delta_hits"] >= 1, s
assert s["bytes"] > 0, s
assert isinstance(p["fallbacks"], dict)
'; then
    echo "register + 2 delta moves: parity + delta_hits + session bytes: OK"
  else
    echo "resident-session smoke FAILED (see $ss_tmp)"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$ss_sock')" || true
  if wait "$ss_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $ss_tmp/daemon.log)"
  tail -20 "$ss_tmp/daemon.log" 2>/dev/null
  kill "$ss_pid" 2>/dev/null
  fail=1
fi
rm -rf "$ss_tmp"

step "speculative plan-ahead smoke (register + 3 moves, memo hits + parity)"
# The tentpole fast path end to end (docs/serving.md § Speculative
# plan-ahead): an outer loop registers, then takes three moves with NO
# telemetry flags (memoizable answers). After each answered move the
# daemon plans the NEXT one during the idle window; the following
# digest-matching request must answer from the memo — serve.spec.hits
# >= 1 through the serve-stats/8 scrape (hit attribution REQUIRED, so
# a silent live-path fallback cannot masquerade), the speculation
# identity exact, and plan bytes identical to -no-daemon at EVERY step.
sp_tmp=$(mktemp -d "${TMPDIR:-/tmp}/kb-gate-spec.XXXXXX")
sp_sock="$sp_tmp/kb.sock"
cp tests/data/test.json "$sp_tmp/cluster.json"
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$sp_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$sp_sock" \
  -serve-idle-timeout=180 >"$sp_tmp/daemon.log" 2>&1 &
sp_pid=$!
sp_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$sp_sock') else 1)" 2>/dev/null; then
    sp_ready=1; break
  fi
  sleep 0.25
done
if [ "$sp_ready" = 1 ]; then
  sp_ok=1
  for stp in 0 1 2 3; do
    JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$sp_tmp/cluster.json" -serve-session=gate-spec \
      -max-reassign=1 -no-daemon >"$sp_tmp/local$stp.out" 2>/dev/null
    JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$sp_tmp/cluster.json" -serve-session=gate-spec \
      -max-reassign=1 "-serve-socket=$sp_sock" \
      >"$sp_tmp/served$stp.out" 2>/dev/null
    if ! cmp -s "$sp_tmp/served$stp.out" "$sp_tmp/local$stp.out"; then
      echo "speculative step $stp parity FAILED"; sp_ok=0
    fi
    "$PYTHON" - "$sp_tmp" "$stp" <<'PYEOF'
import json, sys
tmp, stp = sys.argv[1], sys.argv[2]
state = json.load(open(f"{tmp}/cluster.json"))
plan = json.load(open(f"{tmp}/local{stp}.out"))
for entry in plan.get("partitions") or []:
    for row in state["partitions"]:
        if (row["topic"] == entry["topic"]
                and row["partition"] == entry["partition"]):
            row["replicas"] = list(entry["replicas"])
            break
json.dump(state, open(f"{tmp}/cluster.json", "w"))
PYEOF
    # the idle window: let the speculator finish planning the next move
    "$PYTHON" - "$sp_sock" <<'PYEOF'
import sys, time
from kafkabalancer_tpu.serve.client import fetch_watch
deadline = time.monotonic() + 20
while time.monotonic() < deadline:
    doc = fetch_watch(sys.argv[1]) or {}
    spec = doc.get("speculation") or {}
    if spec.get("memos", 0) >= 1 and not spec.get("inflight"):
        break
    time.sleep(0.05)
PYEOF
  done
  if [ "$sp_ok" = 1 ] && "$PYTHON" -m kafkabalancer_tpu \
      "-serve-socket=$sp_sock" -serve-stats-json 2>/dev/null \
      | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
assert p["schema"] == "kafkabalancer-tpu.serve-stats/8", p.get("schema")
s = p["speculation"]
assert s["enabled"] is True, s
assert s["hits"] >= 1, s
assert s["attempts"] == (
    s["hits"] + s["misses"] + s["poisoned"] + s["memos"]), s
assert "serve.spec.hit_s" in p["hists"], sorted(p["hists"])
assert p["hists"]["serve.spec.hit_s"]["count"] == s["hits"], (
    p["hists"]["serve.spec.hit_s"]["count"], s)
# request_s still reconciles exactly WITH memo hits counted as requests
assert p["hists"]["serve.request_s"]["count"] == p["requests"]
'; then
    echo "register + 3 moves: parity + spec hits + exact identity: OK"
  else
    echo "speculative plan-ahead smoke FAILED (see $sp_tmp)"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$sp_sock')" || true
  if wait "$sp_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $sp_tmp/daemon.log)"
  tail -20 "$sp_tmp/daemon.log" 2>/dev/null
  kill "$sp_pid" 2>/dev/null
  fail=1
fi
rm -rf "$sp_tmp"

step "watch-mode smoke (fake ZK seam, zero client plan ops)"
# The continuous controller end to end (docs/serving.md § Watch mode):
# a -watch daemon reads a fake Zookeeper tree (the FileZkClient seam),
# plans, and emits a plan file with NO client planning request at all —
# the emitted bytes must equal a -no-daemon run on the same state, the
# scrape's `requests` must stay 0, and watch lag must be observable
# through the `watch` protocol op.
wm_tmp=$(mktemp -d "${TMPDIR:-/tmp}/kb-gate-watch.XXXXXX")
wm_sock="$wm_tmp/kb.sock"
mkdir -p "$wm_tmp/zk/brokers/topics" "$wm_tmp/plans"
"$PYTHON" - "$wm_tmp" <<'PYEOF'
import json, sys
tmp = sys.argv[1]
# a skewed 8-partition topic over 4 brokers: the planner has one
# obvious move; the same rows render the -no-daemon oracle input
parts = {str(i): [0, 1] for i in range(8)}
parts["0"] = [2, 3]
with open(f"{tmp}/zk/brokers/topics/gate", "w") as f:
    json.dump({"version": 1, "partitions": parts}, f)
rows = [
    {"topic": "gate", "partition": int(p), "replicas": parts[p]}
    for p in sorted(parts, key=int)
]
with open(f"{tmp}/oracle.json", "w") as f:
    json.dump({"version": 1, "partitions": rows}, f)
PYEOF
KAFKABALANCER_TPU_FAKE_ZK="$wm_tmp/zk" JAX_PLATFORMS=cpu \
  JAX_COMPILATION_CACHE_DIR="$wm_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$wm_sock" \
  "-watch=fake:2181" "-watch-emit=$wm_tmp/plans" -watch-poll=0.25 \
  -max-reassign=1 >"$wm_tmp/daemon.log" 2>&1 &
wm_pid=$!
wm_plan=""
for _ in $(seq 1 120); do
  wm_plan=$(ls "$wm_tmp/plans"/plan-*.json 2>/dev/null | head -1)
  if [ -n "$wm_plan" ]; then break; fi
  sleep 0.25
done
if [ -n "$wm_plan" ]; then
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$wm_tmp/oracle.json" -max-reassign=1 -no-daemon \
    >"$wm_tmp/oracle.out" 2>/dev/null
  if cmp -s "$wm_plan" "$wm_tmp/oracle.out"; then
    echo "watch-emitted plan byte parity vs -no-daemon: OK"
  else
    echo "watch-emitted plan parity FAILED"; fail=1
  fi
  if "$PYTHON" - "$wm_sock" <<'PYEOF'
import sys
from kafkabalancer_tpu.serve.client import fetch_stats, fetch_watch
doc = fetch_stats(sys.argv[1])
assert doc is not None, "no scrape"
# ZERO client plan ops: the daemon planned on its own
assert doc["requests"] == 0, doc["requests"]
w = doc["watch"]
assert w["enabled"] is True and w["plans_emitted"] >= 1, w
assert w["errors"] == 0, w
# watch lag observable through the dedicated protocol op too
lag = fetch_watch(sys.argv[1])
assert lag is not None and lag["watch"]["reads"] >= 1, lag
assert lag["watch"]["last_event_lag_s"] is not None, lag
PYEOF
  then
    echo "zero client plan ops + watch lag scrape: OK"
  else
    echo "watch scrape assertions FAILED"; fail=1
  fi
else
  echo "watch daemon never emitted a plan (see $wm_tmp/daemon.log)"
  tail -20 "$wm_tmp/daemon.log" 2>/dev/null
  fail=1
fi
"$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$wm_sock')" || true
wait "$wm_pid" 2>/dev/null
rm -rf "$wm_tmp"

step "edge-residency smoke (stat-hit steady state, parity + attribution)"
# The edge residency client cache end to end (docs/serving.md § Edge
# residency): the same unchanged input served three times through one
# daemon. Run 0 seeds the per-tenant shadow digest cache beside the
# socket; runs 1 and 2 must take the stat rung — the client skips the
# O(P) read+parse+digest entirely and says so through the daemon-
# written -metrics-json (client.edge_cache_hit) — and EVERY run's plan
# must be byte-identical to a -no-daemon run on the same state.
er_tmp=$(mktemp -d "${TMPDIR:-/tmp}/kb-gate-edge.XXXXXX")
er_sock="$er_tmp/kb.sock"
cp tests/data/test.json "$er_tmp/cluster.json"
# backdate past the same-tick rewrite-stability window so run 0 can
# persist a STABLE entry (a freshly-written mtime is never trusted)
touch -d "1 hour ago" "$er_tmp/cluster.json" 2>/dev/null \
  || touch -t 202001010000 "$er_tmp/cluster.json"
JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$er_tmp" \
  "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$er_sock" \
  -serve-idle-timeout=180 >"$er_tmp/daemon.log" 2>&1 &
er_pid=$!
er_ready=0
for _ in $(seq 1 60); do
  if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$er_sock') else 1)" 2>/dev/null; then
    er_ready=1; break
  fi
  sleep 0.25
done
if [ "$er_ready" = 1 ]; then
  er_ok=1
  JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$er_tmp" \
    "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$er_tmp/cluster.json" -solver=tpu -max-reassign=1 \
    -no-daemon >"$er_tmp/local.out" 2>/dev/null
  for stp in 0 1 2; do
    JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
      -input "$er_tmp/cluster.json" -solver=tpu -max-reassign=1 \
      "-serve-socket=$er_sock" "-metrics-json=$er_tmp/metrics$stp.json" \
      >"$er_tmp/served$stp.out" 2>/dev/null
    if ! cmp -s "$er_tmp/served$stp.out" "$er_tmp/local.out"; then
      echo "edge-residency run $stp parity FAILED"; er_ok=0
    fi
  done
  if [ "$er_ok" = 1 ] && "$PYTHON" - "$er_tmp" <<'PYEOF'
import glob, json, sys
tmp = sys.argv[1]
hits = [
    json.load(open(f"{tmp}/metrics{s}.json"))["gauges"]
    .get("client.edge_cache_hit")
    for s in (0, 1, 2)
]
assert hits[0] is False, hits  # the seeding run pays the full read once
assert hits[1] is True and hits[2] is True, hits
assert glob.glob(f"{tmp}/**/*.kbec", recursive=True), "no cache entry"
PYEOF
  then
    echo "seed miss + 2 stat hits + entry persisted + parity: OK"
  else
    echo "edge-residency smoke FAILED (see $er_tmp)"; fail=1
  fi
  "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$er_sock')" || true
  if wait "$er_pid"; then
    echo "daemon clean shutdown: OK"
  else
    echo "daemon exited nonzero"; fail=1
  fi
else
  echo "daemon never became ready (see $er_tmp/daemon.log)"
  tail -20 "$er_tmp/daemon.log" 2>/dev/null
  kill "$er_pid" 2>/dev/null
  fail=1
fi
rm -rf "$er_tmp"

step "replay smoke (seeded 3-tenant churn, per-tenant reconciliation)"
# The fleet-churn replay harness end to end (ROADMAP item 5,
# docs/observability.md § Per-tenant attribution): a seeded 3-tenant
# churn run — weight shifts, a topic storm, a broker failure — driven
# closed-loop through the real client against a private self-spawned
# daemon. Asserts the serve-stats/8 scrape schema, per-tenant request
# counts reconciling EXACTLY with the driver's issued counts, the
# scrape's per-tenant percentiles agreeing with the flight recorder's
# tenant-labeled request log within one histogram bucket, and plan
# byte parity vs -no-daemon on a sampled request (--check exits 2 when
# any of those fail).
rp_tmp=$(mktemp -d)
if JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu.replay \
    --tenants 3 --requests 24 --seed 7 --topic-storm-every 9 \
    --broker-failure-every 11 --check --out "$rp_tmp/replay.json" \
    >/dev/null 2>"$rp_tmp/replay.log" \
  && "$PYTHON" -c '
import json
a = json.load(open("'"$rp_tmp"'/replay.json"))
assert a["schema"] == "kafkabalancer-tpu.replay/5", a["schema"]
assert a["scrape_schema"] == "kafkabalancer-tpu.serve-stats/8", (
    a["scrape_schema"])
assert a["reconciled_counts"] is True
assert a["latency_checked"] is True
assert a["reconciled_latency"] is True
assert a["parity"] and a["parity"]["ok"] is True, a["parity"]
per = a["per_tenant"]
assert len(per) == 3, sorted(per)
assert all(e["counts_ok"] for e in per.values()), per
assert sum(e["issued"] for e in per.values()) == a["requests_issued"]
'; then
  echo "seeded 3-tenant churn: counts exact + latency + parity: OK"
else
  echo "replay smoke FAILED (see $rp_tmp)"
  tail -10 "$rp_tmp/replay.log" 2>/dev/null
  fail=1
fi
rm -rf "$rp_tmp"

step "overload + chaos smoke (seeded fault injection, sheds, parity)"
# The overload-hardened serving layer end to end (docs/serving.md §
# Overload and fault tolerance): a seeded --chaos replay arms the
# daemon's fault seam (lane crash + dispatch delays + socket drops +
# device-transfer failure), floods the 1-lane daemon past its queue
# cap with mixed tenants (the deterministic blocker+burst overload
# phase), and asserts: sheds observed (structured overload frames with
# a live retry-after estimate), EVERY answered plan byte-identical to
# -no-daemon, no tenant starved to zero, the daemon's
# shed/requeue/quarantine accounting reconciled exactly in the
# serve-stats/8 scrape, and the daemon alive at the end.
ch_tmp=$(mktemp -d)
if JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu.replay --chaos \
    --tenants 3 --requests 24 --seed 7 --arrival uniform --check \
    --out "$ch_tmp/chaos.json" >/dev/null 2>"$ch_tmp/chaos.log" \
  && "$PYTHON" -c '
import json
a = json.load(open("'"$ch_tmp"'/chaos.json"))
assert a["mode"] == "chaos", a["mode"]
assert a["scrape_schema"] == "kafkabalancer-tpu.serve-stats/8"
c = a["chaos"]
assert c["ok"] is True, c
assert c["wrong_plans"] == [], c["wrong_plans"]
assert c["answered"] == c["parity_checked"] >= 24
assert c["shed_total"] >= 1 and c["sheds"].get("overload", 0) >= 1
assert c["retry_after_ms_estimate"] >= 1
assert c["quarantines"] >= 1 and c["recoveries"] >= 1
assert c["daemon_alive_at_end"] is True
assert all(c["identities"].values()), c["identities"]
fired = c["faults_fired"]
assert fired.get("lane_crash", 0) >= 1, fired
assert fired.get("dispatch_delay", 0) >= 1, fired
# fairness: every churn tenant was actually SERVED by the daemon
# (daemon-side counts from the scrape — a tenant shed into oblivion
# would show issued > 0 with daemon_requests == 0)
per = a["per_tenant"]
assert all(e["issued"] >= 1 for e in per.values()), per
assert all(e["daemon_requests"] >= 1 for e in per.values()), per
assert not a["request_errors"], a["request_errors"]
'; then
  echo "chaos run: sheds + parity on every answer + reconciled + alive: OK"
else
  echo "overload/chaos smoke FAILED (see $ch_tmp)"
  tail -10 "$ch_tmp/chaos.log" 2>/dev/null
  fail=1
fi
rm -rf "$ch_tmp"

step "session durability smoke (register -> delta -> SIGKILL -> restore)"
# The warm session tier end to end (ISSUE 14, docs/serving.md §
# Session durability): an outer loop registers + takes one delta move
# against a spill-enabled daemon, the daemon is SIGKILLed (no shutdown
# flush — recovery must work from the continuous per-request spill),
# a second daemon takes over the same socket + spill dir (the PR-12
# pidfile-verified sweep), and the tenant's next digest-matching
# request restores from the spilled record: restore_hits >= 1 in the
# -serve-stats-json paging block, the conservation identity exact, and
# plan bytes identical to -no-daemon at EVERY step.
sd_tmp=$(mktemp -d "${TMPDIR:-/tmp}/kb-gate-spill.XXXXXX")
sd_sock="$sd_tmp/kb.sock"
sd_spill="$sd_tmp/spill"
cp tests/data/test.json "$sd_tmp/cluster.json"
sd_daemon() {
  JAX_PLATFORMS=cpu JAX_COMPILATION_CACHE_DIR="$sd_tmp" \
    "$PYTHON" -m kafkabalancer_tpu -serve "-serve-socket=$sd_sock" \
    "-serve-session-spill-dir=$sd_spill" -serve-warm-cap-mb=64 \
    -serve-lanes=1 -serve-idle-timeout=180 >>"$sd_tmp/daemon.log" 2>&1 &
  sd_pid=$!
  sd_ready=0
  for _ in $(seq 1 60); do
    if "$PYTHON" -c "import sys
from kafkabalancer_tpu.serve.client import daemon_alive
sys.exit(0 if daemon_alive('$sd_sock') else 1)" 2>/dev/null; then
      sd_ready=1; break
    fi
    sleep 0.25
  done
}
sd_step() {
  # one outer-loop step: served plan + -no-daemon oracle, byte parity,
  # then apply the emitted moves to the cluster state
  stp=$1
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$sd_tmp/cluster.json" -serve-session=gate-durable \
    -max-reassign=1 -no-daemon >"$sd_tmp/local$stp.out" 2>/dev/null
  JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu -input-json \
    -input "$sd_tmp/cluster.json" -serve-session=gate-durable \
    -max-reassign=1 "-serve-socket=$sd_sock" \
    >"$sd_tmp/served$stp.out" 2>/dev/null
  if ! cmp -s "$sd_tmp/served$stp.out" "$sd_tmp/local$stp.out"; then
    echo "durability step $stp parity FAILED"; sd_ok=0
  fi
  "$PYTHON" - "$sd_tmp" "$stp" <<'PYEOF'
import json, sys
tmp, stp = sys.argv[1], sys.argv[2]
state = json.load(open(f"{tmp}/cluster.json"))
plan = json.load(open(f"{tmp}/local{stp}.out"))
for entry in plan.get("partitions") or []:
    for row in state["partitions"]:
        if (row["topic"] == entry["topic"]
                and row["partition"] == entry["partition"]):
            row["replicas"] = list(entry["replicas"])
            break
json.dump(state, open(f"{tmp}/cluster.json", "w"))
PYEOF
}
sd_daemon
if [ "$sd_ready" = 1 ]; then
  sd_ok=1
  sd_step 0   # register
  sd_step 1   # delta fast path (also the spill the recovery will use)
  kill -9 "$sd_pid" 2>/dev/null
  wait "$sd_pid" 2>/dev/null
  sd_daemon   # same socket + spill dir: takeover + record adoption
  if [ "$sd_ready" = 1 ]; then
    sd_step 2  # must restore from spill, byte-identical
    if [ "$sd_ok" = 1 ] && "$PYTHON" -m kafkabalancer_tpu \
        "-serve-socket=$sd_sock" -serve-stats-json 2>/dev/null \
        | "$PYTHON" -c '
import json, sys
p = json.loads(sys.stdin.read())
pg = p["paging"]
assert pg["enabled"] is True, pg
assert pg["restore_hits"] >= 1, pg
assert pg["adopted"] >= 1, pg
assert pg["spills"] + pg["adopted"] == (
    pg["restores"] + pg["corrupt_drops"] + pg["evictions"]
    + pg["warm_entries"]), pg
assert p["sessions"]["count"] >= 1, p["sessions"]
'; then
      echo "SIGKILL -> restart -> spill restore: parity + restore_hits + identity: OK"
    else
      echo "session durability smoke FAILED (see $sd_tmp)"; fail=1
    fi
    "$PYTHON" -c "from kafkabalancer_tpu.serve.client import request_shutdown
request_shutdown('$sd_sock')" || true
    wait "$sd_pid" 2>/dev/null
  else
    echo "restarted daemon never became ready (see $sd_tmp/daemon.log)"
    tail -20 "$sd_tmp/daemon.log" 2>/dev/null
    kill "$sd_pid" 2>/dev/null
    fail=1
  fi
else
  echo "daemon never became ready (see $sd_tmp/daemon.log)"
  tail -20 "$sd_tmp/daemon.log" 2>/dev/null
  kill "$sd_pid" 2>/dev/null
  fail=1
fi
rm -rf "$sd_tmp"

# the corrupt-record half: a seeded spill_corrupt restart replay must
# answer every request cold-but-correct (record pruned + counted,
# plan bytes identical, paging identity exact) — driven through the
# replay harness's --restart mode
sc_tmp=$(mktemp -d)
if JAX_PLATFORMS=cpu "$PYTHON" -m kafkabalancer_tpu.replay --restart \
    --tenants 1 --requests 3 --kill-after 1 --arrival uniform \
    --weight-shift-every 0 --chaos-faults "spill_corrupt@1" --check \
    --out "$sc_tmp/restart.json" >/dev/null 2>"$sc_tmp/restart.log" \
  && "$PYTHON" -c '
import json
a = json.load(open("'"$sc_tmp"'/restart.json"))
assert a["mode"] == "restart", a["mode"]
r = a["restart"]
assert r["ok"] is True, r
assert r["wrong_plans"] == [], r["wrong_plans"]
assert r["corrupt_drops"] == 1 and r["restore_hits"] == 0, r
assert r["paging_identity_ok"] is True, r
assert not a["request_errors"], a["request_errors"]
'; then
  echo "seeded spill_corrupt restart: cold-but-correct + pruned + counted: OK"
else
  echo "spill_corrupt restart smoke FAILED (see $sc_tmp)"
  tail -10 "$sc_tmp/restart.log" 2>/dev/null
  fail=1
fi
rm -rf "$sc_tmp"

if [ "$run_tests" = 1 ]; then
  step "tier-1 tests"
  JAX_PLATFORMS=cpu "$PYTHON" -m pytest tests/ -q -m 'not slow' \
    --continue-on-collection-errors -p no:cacheprovider || fail=1
fi

step "gate result"
if [ "$fail" = 0 ]; then
  echo "GATE PASS"
else
  echo "GATE FAIL"
fi
exit "$fail"
