"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic
(mesh/shard_map paths) is exercised without TPU hardware, and with x64
enabled so the cost model matches the float64 greedy oracle bit-for-bit.
This must happen before the first ``import jax`` anywhere in the test
process.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")
