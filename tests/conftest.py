"""Test harness configuration.

Tests run on CPU with 8 virtual XLA devices so multi-chip sharding logic
(mesh/shard_map paths) is exercised without TPU hardware, and with x64
enabled so the cost model matches the float64 greedy oracle bit-for-bit.
This must happen before the first ``import jax`` anywhere in the test
process.
"""

import os

# unconditional: the ambient environment may point JAX at a real TPU (a
# sitecustomize can pre-register the plugin and pin JAX_PLATFORMS), but the
# suite must run on the virtual 8-device CPU mesh — override both the env
# var and the live jax config
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_ENABLE_X64", "1")

# daemon isolation: a developer's live planning daemon on the default
# per-uid socket must never serve the suite's cli.run() invocations (the
# tests must exercise THIS working tree, not whatever code the daemon
# loaded). Point the default socket at a path that cannot exist; tests
# that want a daemon pass -serve-socket explicitly, which overrides this.
os.environ["KAFKABALANCER_TPU_SOCKET"] = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "no-daemon-here.sock",
)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
