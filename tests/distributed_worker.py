"""Worker process for the 2-process jax.distributed test.

Usage: python distributed_worker.py <process_id> <coordinator_port>

Each worker joins a 2-process CPU runtime (2 virtual XLA devices per
process -> 4 global devices), builds a global (sweep, part) mesh with the
framework's make_mesh, and runs the partition-sharded candidate scorer
over a mesh that SPANS BOTH PROCESSES — the all_gather combine rides the
cross-process transport. The result is checked against the unsharded
single-process scorer on the same (deterministic) instance.

Must be launched with JAX_PLATFORMS=cpu and
--xla_force_host_platform_device_count=2 in XLA_FLAGS set at interpreter
startup (the test harness does this).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

process_id = int(sys.argv[1])
port = sys.argv[2]

from kafkabalancer_tpu.parallel.distributed import (  # noqa: E402
    initialize,
    is_multi_host,
)

initialize(f"127.0.0.1:{port}", num_processes=2, process_id=process_id)

import jax  # noqa: E402
import numpy as np  # noqa: E402
from jax.sharding import NamedSharding  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402

assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()
assert len(jax.local_devices()) == 2
assert is_multi_host()

from __graft_entry__ import _example_dense  # noqa: E402
from kafkabalancer_tpu.parallel.mesh import PART_AXIS, make_mesh  # noqa: E402
from kafkabalancer_tpu.parallel.shard_move import (  # noqa: E402
    sharded_score_moves,
)
from kafkabalancer_tpu.solvers.tpu import score_moves  # noqa: E402

mesh = make_mesh(4)  # (sweep=2, part=2) over both processes
assert mesh.devices.size == 4
assert {d.process_index for d in mesh.devices.flat} == {0, 1}

part = mesh.shape[PART_AXIS]
_pl, _cfg, _dp, args = _example_dense(n_parts=64, n_brokers=8, min_bucket=8 * part)

# promote the host-local (identical-on-both-processes) inputs to global
# arrays: per-partition tensors shard on the part axis, the rest replicate
pshard = NamedSharding(mesh, P(PART_AXIS))
rep = NamedSharding(mesh, P())
(loads, replicas, allowed, member, weights, nrep_cur, nrep_tgt, pvalid,
 bvalid, nb, min_replicas) = args
gargs = (
    jax.device_put(loads, rep),
    jax.device_put(replicas, pshard),
    jax.device_put(allowed, pshard),
    jax.device_put(member, pshard),
    jax.device_put(weights, pshard),
    jax.device_put(nrep_cur, pshard),
    jax.device_put(nrep_tgt, pshard),
    jax.device_put(pvalid, pshard),
    jax.device_put(bvalid, rep),
    nb,
    min_replicas,
)

u0, i0, su0, perm0 = score_moves(*args, leaders=False, tie_k=0)
u1, i1, su1, perm1 = sharded_score_moves(*gargs, leaders=False, mesh=mesh)
assert float(u0) == float(u1), (float(u0), float(u1))
assert int(i0) == int(i1), (int(i0), int(i1))
assert float(su0) == float(su1)
assert (np.asarray(perm0) == np.asarray(perm1)).all()

print(
    f"DIST_OK proc={process_id} processes={jax.process_count()} "
    f"global_devices={len(jax.devices())} best_u={float(u1):.12e}",
    flush=True,
)

# --- the whole sharded CONVERGE SESSION across the process boundary -------
# (VERDICT r3 missing #2: plan_sharded proven only single-process before)
import copy  # noqa: E402

from kafkabalancer_tpu.models import default_rebalance_config  # noqa: E402
from kafkabalancer_tpu.parallel.shard_session import plan_sharded  # noqa: E402
from kafkabalancer_tpu.solvers.scan import plan as scan_plan  # noqa: E402
from kafkabalancer_tpu.utils.synth import synth_cluster  # noqa: E402

# part axis spans both processes: shape (1, 4) puts all 4 devices on the
# part axis, 2 per process — every per-iteration all_gather combine in
# the session rides the cross-process transport
sess_mesh = make_mesh(4, shape=(1, 4))
assert {d.process_index for d in sess_mesh.devices.flat} == {0, 1}

pl_sh = synth_cluster(96, 8, rf=3, seed=71, weighted=True)
pl_1p = synth_cluster(96, 8, rf=3, seed=71, weighted=True)
cfg_sh = default_rebalance_config()
cfg_sh.min_unbalance = 1e-7
cfg_sh.allow_leader_rebalancing = True
opl_sh = plan_sharded(
    pl_sh, copy.deepcopy(cfg_sh), 800, sess_mesh, batch=8, chunk_moves=64
)
# the single-device batched session runs process-locally; the sharded
# cross-process move log must be bit-identical to it (the exactness
# contract of shard_session's total-order combine)
opl_1p = scan_plan(pl_1p, copy.deepcopy(cfg_sh), 800, batch=8, chunk_moves=64)
log_sh = [
    (p.topic, p.partition, tuple(p.replicas))
    for p in (opl_sh.partitions or [])
]
log_1p = [
    (p.topic, p.partition, tuple(p.replicas))
    for p in (opl_1p.partitions or [])
]
assert log_sh == log_1p, (len(log_sh), len(log_1p))
assert pl_sh == pl_1p
print(
    f"SESSION_OK proc={process_id} moves={len(log_sh)} "
    f"mesh=1x4 spans=2procs",
    flush=True,
)

# polish tail across processes: the sharded phase converges the move
# neighborhood cross-process, then the single-device polish tail runs
# process-locally on identical state
pl_pol = synth_cluster(96, 8, rf=3, seed=71, weighted=True)
opl_pol = plan_sharded(
    pl_pol, copy.deepcopy(cfg_sh), 800, sess_mesh, batch=8,
    chunk_moves=64, polish=True,
)
from kafkabalancer_tpu.balancer.costmodel import (  # noqa: E402
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)

u_moves = get_unbalance_bl(get_bl(get_broker_load(pl_sh)))
u_pol = get_unbalance_bl(get_bl(get_broker_load(pl_pol)))
assert u_pol <= u_moves, (u_pol, u_moves)
print(
    f"POLISH_OK proc={process_id} n={len(opl_pol)} "
    f"u_moves={u_moves:.6e} u_polish={u_pol:.6e}",
    flush=True,
)

# --- what-if sweep sharded over a cross-process mesh ----------------------
from kafkabalancer_tpu.parallel.sweep import sweep  # noqa: E402

pl = synth_cluster(24, 6, rf=2, seed=11, weighted=True)
cfg = default_rebalance_config()
observed = sorted({b for p in pl.partitions for b in p.replicas})
scenarios = [
    observed,
    observed + [max(observed) + 1],
    observed + [max(observed) + 1, max(observed) + 2],
    observed[1:],
]
results = sweep(pl, cfg, scenarios, max_reassign=64, mesh=mesh)
assert len(results) == len(scenarios)
assert any(r.feasible for r in results)
summary = ";".join(
    f"{int(r.feasible)}:{int(r.completed)}:{r.n_moves}:{r.unbalance:.9e}"
    for r in results
)
print(f"SWEEP_OK proc={process_id} {summary}", flush=True)
