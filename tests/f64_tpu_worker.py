"""Child process for the hardware f64-COVERAGE test (tests/test_pallas_tpu.py
pattern).

Round 5 found a latent hardware bug in a path no benchmark exercises:
the f64 what-if sweep failed to COMPILE on the TPU backend (the
f64->int32 objective bitcast lowers through a u64 the backend's X64
rewriting does not implement) because the suite always measures f32 —
the f64 parity mode existed only on the CPU test mesh. This worker runs
a compact instance of every f64 device path on the real chip so that
class of backend-specific f64 lowering failure turns into a failing
test, not a user-facing crash:

- the batch=1 reference-parity session and the batched+polish session
  (solvers/scan.py, solvers/polish.py),
- the fused -rebalance-leader session (solvers/leader.py),
- the single-move window scorer's f64 tier (solvers/tpu.py — the
  retry tier the f32 window falls back to, normally dormant),
- the what-if sweep (parallel/sweep.py — the objective rides a
  separate output in 64-bit mode, the r5 fix),
- the sharded XLA session at a small bucket (parallel/shard_session.py
  — f64 requests resolve auto to the XLA shard body, which is healthy
  below the documented 131072x256 crash buckets).

Exit codes: 0 = all paths ran, 77 = no TPU here (parent skips),
anything else = real failure. Prints one JSON line.
"""

import copy
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NO_TPU = 77


def main() -> int:
    try:
        import jax

        devs = jax.devices()
    except Exception as exc:
        print(json.dumps({"skip": f"backend init failed: {exc!r}"}))
        return NO_TPU
    platform = devs[0].platform.lower()
    if "tpu" not in platform and "axon" not in platform:
        print(json.dumps({"skip": f"platform is {platform!r}, not tpu"}))
        return NO_TPU

    import numpy as np

    import jax.numpy as jnp

    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.balancer.steps import fill_defaults, validate_weights
    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.ops.tensorize import tensorize
    from kafkabalancer_tpu.parallel.mesh import make_mesh
    from kafkabalancer_tpu.parallel.shard_session import plan_sharded
    from kafkabalancer_tpu.parallel.sweep import sweep
    from kafkabalancer_tpu.solvers import tpu as tpu_solver
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    out = {}

    def uof(pl):
        return get_unbalance_bl(get_bl(get_broker_load(pl)))

    # batch=1 reference-parity session
    pl = synth_cluster(300, 16, rf=3, seed=31, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-7
    opl = plan(pl, copy.deepcopy(cfg), 1000, dtype=jnp.float64, batch=1)
    assert len(opl) > 0
    out["batch1_u"] = uof(pl)

    # batched + polish (move/swap/shuffle alternation)
    pl2 = synth_cluster(300, 16, rf=3, seed=31, weighted=True)
    cfg2 = default_rebalance_config()
    cfg2.min_unbalance = 0.0
    cfg2.allow_leader_rebalancing = True
    opl2 = plan(pl2, copy.deepcopy(cfg2), 3000, dtype=jnp.float64,
                batch=8, polish=True)
    assert len(opl2) > 0
    # non-strict: this is a compile-coverage worker, not an optimality
    # test — two independently-planned runs may tie at a shared optimum
    out["polish_u"] = uof(pl2)
    assert out["polish_u"] <= out["batch1_u"]

    # fused -rebalance-leader Balance loop
    pl3 = synth_cluster(200, 12, rf=3, seed=77, weighted=True)
    cfg3 = default_rebalance_config()
    cfg3.rebalance_leaders = True
    cfg3.min_unbalance = 1e-6
    opl3 = plan(pl3, copy.deepcopy(cfg3), 300, dtype=jnp.float64, batch=4)
    out["leader_moves"] = len(opl3)

    # the single-move window scorer's f64 tier, invoked directly (the
    # f32 tier rarely overflows, so the retry tier is normally dormant)
    pl4 = synth_cluster(2000, 40, rf=3, seed=3, weighted=True)
    cfg4 = default_rebalance_config()
    validate_weights(pl4, cfg4)
    fill_defaults(pl4, cfg4)
    dp = tensorize(pl4, cfg4)
    loads_map = tpu_solver._oracle_loads(pl4, cfg4)
    loads = np.zeros(dp.bvalid.shape[0])
    for bid, load in loads_map.items():
        loads[dp.broker_index(bid)] = load
    ints, f64, allowed_arg, all_allowed = tpu_solver._pack_window_args(
        dp, loads, cfg4
    )
    tier = np.asarray(
        tpu_solver._score_window_jit(
            ints, f64, allowed_arg, leaders=False, all_allowed=all_allowed
        )
    )
    assert np.isfinite(tier[0])
    out["window_f64_umin"] = float(tier[0])

    # the what-if sweep (64-bit objective rides a separate output)
    pl5 = synth_cluster(300, 16, rf=3, seed=9, weighted=True)
    observed = sorted({b for p in pl5.partitions for b in p.replicas})
    res = sweep(pl5, default_rebalance_config(),
                [observed, observed + [99]], max_reassign=500)
    assert all(r.feasible for r in res)
    out["sweep_u"] = [r.unbalance for r in res]

    # the sharded XLA body at a small bucket (f64 resolves auto to it)
    pl6 = synth_cluster(300, 16, rf=3, seed=31, weighted=True)
    cfg6 = default_rebalance_config()
    cfg6.min_unbalance = 1e-7
    opl6 = plan_sharded(pl6, cfg6, 1000, make_mesh(1, shape=(1, 1)),
                        batch=8, dtype=jnp.float64)
    assert len(opl6) > 0
    out["shard_u"] = uof(pl6)

    print(json.dumps({"ok": True, **out}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
