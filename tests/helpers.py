"""Shared test helpers: random cluster-instance generation.

Instances are always valid inputs (all-or-nothing weights, no duplicate
replicas within a partition) and are generated *post-defaults-shaped* when
``filled=True`` (weights ≥ smallest positive, brokers set, num_replicas =
len(replicas)) so solver-layer tests can skip the pipeline head.
"""

import random

from kafkabalancer_tpu.models import Partition, PartitionList


def random_partition_list(
    rng: random.Random,
    n_partitions: int,
    n_brokers: int,
    max_rf: int = 3,
    weighted: bool = True,
    with_consumers: bool = False,
    restrict_brokers: bool = False,
    filled: bool = False,
) -> PartitionList:
    broker_ids = sorted(rng.sample(range(1, n_brokers * 3), n_brokers))
    parts = []
    for i in range(n_partitions):
        rf = rng.randint(1, min(max_rf, n_brokers))
        replicas = rng.sample(broker_ids, rf)
        brokers = None
        if restrict_brokers and rng.random() < 0.3:
            extra = [b for b in broker_ids if b not in replicas]
            brokers = sorted(replicas + rng.sample(extra, min(len(extra), 2)))
        p = Partition(
            topic=f"topic{i % max(1, n_partitions // 4)}",
            partition=i,
            replicas=replicas,
            weight=round(rng.uniform(0.5, 4.0), 3) if weighted else 0.0,
            num_consumers=rng.randint(0, 3) if with_consumers else 0,
            brokers=brokers,
        )
        if filled:
            if not weighted:
                p.weight = 1.0
            if p.brokers is None:
                p.brokers = list(broker_ids)
            p.num_replicas = len(p.replicas)
        parts.append(p)
    return PartitionList(version=1, partitions=parts)
