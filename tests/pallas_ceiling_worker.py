"""Child process for the hardware kernel-CEILING tests (VERDICT r3 #6).

The whole-session kernel's documented capacity (solvers/scan.py
PALLAS_VMEM_CELLS / _RESTRICTED: 128k x 256 all-allowed, 64k x 128 with
a resident allowed matrix) and its scale-dependent batched-tie behavior
were pinned only by bench.py/suite.py until round 4 — a Mosaic VMEM
regression at the ceiling would have surfaced as a bad benchmark, not a
failing test. This worker compiles and runs BUDGET-CAPPED sessions at
exactly the gated ceiling buckets (a few committed batches each — the
compile is the test; the short session proves the executable runs), plus
one equal-weight tie-storm at >= 10k partitions compared across engines.

Launched by tests/test_pallas_tpu.py with the harness CPU pins scrubbed.
Exit codes: 0 = all cases checked, 77 = no TPU here (parent skips),
anything else = real failure. Prints one JSON line.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NO_TPU = 77


def main() -> int:
    try:
        import jax

        devs = jax.devices()
    except Exception as exc:  # no usable backend at all
        print(json.dumps({"skip": f"backend init failed: {exc!r}"}))
        return NO_TPU
    platform = devs[0].platform.lower()
    if "tpu" not in platform and "axon" not in platform:
        print(json.dumps({"skip": f"platform is {platform!r}, not tpu"}))
        return NO_TPU

    import time

    import jax.numpy as jnp

    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.ops import tensorize
    from kafkabalancer_tpu.solvers.pallas_session import TILE_P
    from kafkabalancer_tpu.solvers.scan import (
        PALLAS_VMEM_CELLS,
        PALLAS_VMEM_CELLS_RESTRICTED,
        plan,
    )
    from kafkabalancer_tpu.utils.synth import synth_cluster

    def run_capped(pl, budget, batch, allow_leader=True):
        """Budget-capped pallas-engine plan; returns (seconds, result)."""
        before = {
            (p.topic, p.partition): tuple(p.replicas)
            for p in pl.iter_partitions()
        }
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        cfg.allow_leader_rebalancing = allow_leader
        t0 = time.perf_counter()
        opl = plan(
            pl, cfg, budget, dtype=jnp.float32, batch=batch, engine="pallas"
        )
        dt = time.perf_counter() - t0
        emitted = {(e.topic, e.partition) for e in (opl.partitions or [])}
        changed = {
            (p.topic, p.partition)
            for p in pl.iter_partitions()
            if tuple(p.replicas) != before[(p.topic, p.partition)]
        }
        valid = changed <= emitted and all(
            len(set(e.replicas)) == len(e.replicas)
            for e in (opl.partitions or [])
        )
        return dt, {
            "n_moves": len(opl),
            "unbalance": get_unbalance_bl(get_bl(get_broker_load(pl))),
            "valid": valid,
        }

    out = {"platform": platform}

    # --- case A: 128k x 256 all-allowed ceiling --------------------------
    # the instance buckets to EXACTLY the gated capacity; if the constant
    # or the kernel's VMEM footprint regresses, plan() either falls back
    # (caught by the gate asserts below) or raises BalanceError (caught by
    # the parent as a failure)
    pl = synth_cluster(130_000, 250, rf=3, seed=77, weighted=True)
    cfg_probe = default_rebalance_config()
    dp = tensorize(pl, cfg_probe, min_bucket=TILE_P)
    P, B = dp.replicas.shape[0], dp.bvalid.shape[0]
    assert (P, B) == (131072, 256), (P, B)
    assert P * max(B, 128) <= PALLAS_VMEM_CELLS, "gate no longer admits 128k x 256"
    assert dp.allowed[:, : dp.nb].all(axis=1)[: dp.np_].all(), "must be all-allowed"
    dt, res = run_capped(pl, budget=384, batch=128)
    res["seconds"] = round(dt, 3)
    res["bucket"] = [P, B]
    assert res["n_moves"] > 0 and res["valid"], res
    out["ceiling_all_allowed"] = res

    # --- case B: 64k x 128 restricted ceiling ----------------------------
    # per-partition broker restrictions keep the int8 allowed matrix
    # resident in the kernel (the lower gated capacity)
    pl = synth_cluster(65_000, 125, rf=3, seed=78, weighted=True)
    universe = sorted({b for p in pl.partitions for b in p.replicas})
    for i, p in enumerate(pl.partitions):
        # forbid one broker it doesn't hold — keeps the instance feasible
        # while flipping the all-allowed detection off for the whole run
        banned = universe[i % len(universe)]
        if banned in p.replicas:
            banned = next(b for b in universe if b not in p.replicas)
        p.brokers = [b for b in universe if b != banned]
    dp = tensorize(pl, cfg_probe, min_bucket=TILE_P)
    P, B = dp.replicas.shape[0], dp.bvalid.shape[0]
    assert (P, B) == (65536, 128), (P, B)
    assert P * max(B, 128) <= PALLAS_VMEM_CELLS_RESTRICTED, (
        "gate no longer admits restricted 64k x 128"
    )
    assert not dp.allowed[:, : dp.nb].all(), "must be restricted"
    dt, res = run_capped(pl, budget=256, batch=64)
    res["seconds"] = round(dt, 3)
    res["bucket"] = [P, B]
    assert res["n_moves"] > 0 and res["valid"], res
    out["ceiling_restricted"] = res

    # --- case C: batched tie storm at >= 10k partitions ------------------
    # equal weights make nearly every candidate an exact float tie; the
    # kernel's f32 selection and the XLA engine's must agree on count and
    # objective at scale (logs may diverge on exact ties — the documented
    # hardware contract)
    results = {}
    for eng in ("pallas", "xla"):
        pl = synth_cluster(12_000, 64, rf=3, seed=79, weighted=False)
        before = {
            (p.topic, p.partition): tuple(p.replicas)
            for p in pl.iter_partitions()
        }
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        t0 = time.perf_counter()
        opl = plan(
            pl, cfg, 512, dtype=jnp.float32, batch=32, engine=eng
        )
        dt = time.perf_counter() - t0
        emitted = {(e.topic, e.partition) for e in (opl.partitions or [])}
        changed = {
            (p.topic, p.partition)
            for p in pl.iter_partitions()
            if tuple(p.replicas) != before[(p.topic, p.partition)]
        }
        results[eng] = {
            "n_moves": len(opl),
            "unbalance": get_unbalance_bl(get_bl(get_broker_load(pl))),
            "valid": changed <= emitted,
            "seconds": round(dt, 3),
        }
    out["tie_storm"] = results

    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
