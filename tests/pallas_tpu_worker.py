"""Child process for the hardware Pallas parity test.

Launched by tests/test_pallas_tpu.py with the test harness's CPU pins
scrubbed so the ambient backend (the real TPU, when one is attached)
initializes instead. Exit codes: 0 = parity checked, 77 = no TPU here
(parent skips), anything else = real failure.

Runs the same 1k x 32 session through the compiled Mosaic kernel
(engine='pallas') and the XLA batch path (engine='xla') and prints one
JSON line with both results. The documented hardware-vs-interpreter
caveat (solvers/pallas_session.py: float reduction order may resolve
exact candidate ties differently on hardware) means move LOGS may
diverge; move count, final unbalance (to f32 round-off) and plan
validity must not.
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

NO_TPU = 77


def main() -> int:
    try:
        import jax

        devs = jax.devices()
    except Exception as exc:  # no usable backend at all
        print(json.dumps({"skip": f"backend init failed: {exc!r}"}))
        return NO_TPU
    platform = devs[0].platform.lower()
    if "tpu" not in platform and "axon" not in platform:
        print(json.dumps({"skip": f"platform is {platform!r}, not tpu"}))
        return NO_TPU

    import jax.numpy as jnp

    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    def run(engine):
        pl = synth_cluster(1000, 32, rf=3, seed=123, weighted=True)
        # snapshot BEFORE planning: opl entries alias the live partitions
        # (the reference's aliasing-visible output), so comparing entry
        # vs live is vacuous — the real invariant is that every CHANGED
        # partition appears in the emitted plan
        before = {
            (p.topic, p.partition): tuple(p.replicas)
            for p in pl.iter_partitions()
        }
        cfg = default_rebalance_config()
        cfg.min_unbalance = 0.0
        cfg.allow_leader_rebalancing = True
        opl = plan(pl, cfg, 2048, dtype=jnp.float32, batch=32, engine=engine)
        emitted = {(e.topic, e.partition) for e in (opl.partitions or [])}
        changed = {
            (p.topic, p.partition)
            for p in pl.iter_partitions()
            if tuple(p.replicas) != before[(p.topic, p.partition)]
        }
        valid = changed <= emitted and all(
            len(set(e.replicas)) == len(e.replicas)
            for e in (opl.partitions or [])
        )
        return {
            "n_moves": len(opl),
            "unbalance": get_unbalance_bl(get_bl(get_broker_load(pl))),
            "valid": valid,
        }

    out = {"platform": platform}
    out["pallas"] = run("pallas")
    out["xla"] = run("xla")
    print(json.dumps(out))
    return 0


if __name__ == "__main__":
    sys.exit(main())
