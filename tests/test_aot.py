"""AOT executable store (ops/aot.py): save/load round trip, keying, and
fallback behavior — on the CPU backend with a temp cache dir."""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafkabalancer_tpu.ops import aot


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("KAFKABALANCER_TPU_NO_AOT", raising=False)
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    yield str(tmp_path)
    # no background writer may outlive its temp store
    aot.flush_saves(30.0)
    aot.flush_prefetches(30.0)
    jax.config.update("jax_compilation_cache_dir", old)
    aot._loaded.clear()


def test_roundtrip(cache_dir):
    """maybe_save writes an executable; try_load returns a callable whose
    output matches the jit path exactly."""
    fn = jax.jit(
        lambda a, b, s: (a * b).sum() + s, static_argnames=()
    )
    a = np.arange(8.0)
    b = np.ones(8)
    args = (a, b, 2.0)
    statics = {}
    assert aot.try_load("t", args, statics) is None  # nothing stored yet
    path = aot.maybe_save("t", fn, args, statics)
    assert path is not None and os.path.exists(path)
    aot._loaded.clear()
    compiled = aot.try_load("t", args, statics)
    assert compiled is not None
    got = np.asarray(compiled(*args))
    want = np.asarray(fn(*args))
    np.testing.assert_array_equal(got, want)
    # in-process memo: second load returns the same object
    assert aot.try_load("t", args, statics) is compiled


def test_multi_output(cache_dir):
    fn = jax.jit(lambda a: (a + 1, (a * 2).sum()))
    args = (np.arange(4.0),)
    assert aot.maybe_save("m", fn, args, {}) is not None
    aot._loaded.clear()
    compiled = aot.try_load("m", args, {}, out_leaves=2)
    assert compiled is not None
    g1, g2 = compiled(*args)
    w1, w2 = fn(*args)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(w1))
    np.testing.assert_array_equal(np.asarray(g2), np.asarray(w2))


def test_key_separates_shapes_statics(cache_dir):
    """Different arg shapes/dtypes/statics and None-vs-array args key
    differently; identical calls key identically."""
    k = aot.aot_key("f", (np.zeros(4), None), {"x": 1})
    assert k == aot.aot_key("f", (np.zeros(4), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(5), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4, np.float32), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4), np.zeros(1)), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4), None), {"x": 2})
    assert k != aot.aot_key("f", (np.zeros(4), None), {"x": jnp.float32})
    assert k != aot.aot_key("g", (np.zeros(4), None), {"x": 1})


def test_corrupt_entry_pruned(cache_dir):
    """A corrupt blob is removed and the caller falls back (returns None)."""
    args = (np.zeros(3),)
    path = os.path.join(
        cache_dir, "aot", aot.aot_key("c", args, {}) + ".bin"
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"not an executable")
    assert aot.try_load("c", args, {}) is None
    assert not os.path.exists(path)


def test_disabled_by_env(cache_dir, monkeypatch):
    monkeypatch.setenv("KAFKABALANCER_TPU_NO_AOT", "1")
    fn = jax.jit(lambda a: a + 1)
    args = (np.zeros(2),)
    assert aot.maybe_save("d", fn, args, {}) is None
    assert aot.try_load("d", args, {}) is None


def test_no_cache_dir_disables(monkeypatch):
    monkeypatch.delenv("KAFKABALANCER_TPU_NO_AOT", raising=False)
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        assert aot.aot_dir() is None
        assert aot.try_load("x", (np.zeros(1),), {}) is None
    finally:
        jax.config.update("jax_compilation_cache_dir", old)


# --- store v2 ------------------------------------------------------------


def _store_one(cache_dir, name="v", n=6.0):
    fn = jax.jit(lambda a: a * 2)
    args = (np.arange(n),)
    path = aot.maybe_save(name, fn, args, {})
    assert path is not None
    return fn, args, aot.aot_key(name, args, {})


def test_v2_manifest_and_shards(cache_dir):
    """Saves write compressed shard files plus a versioned manifest
    entry whose metadata (codec, sizes, sig) matches the blob."""
    _fn, _args, key = _store_one(cache_dir)
    d = aot.aot_dir()
    entries = aot._manifest_read(d)
    assert key in entries
    e = entries[key]
    assert e["name"] == "v"
    assert e["codec"] in ("zstd", "gzip", "raw")
    assert e["raw_bytes"] > 0 and e["stored_bytes"] > 0
    for shard in e["shards"]:
        assert os.path.exists(os.path.join(d, shard))
    # the manifest carries the human-readable key parts
    assert e["sig"][0] == "v"


def test_v2_multi_shard_roundtrip(cache_dir, monkeypatch):
    """A blob larger than the shard size splits into several shards and
    reassembles to a working executable."""
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_SHARD_MB", "0.001")  # 1 kB
    fn, args, key = _store_one(cache_dir, name="ms")
    d = aot.aot_dir()
    e = aot._manifest_read(d)[key]
    assert len(e["shards"]) > 1
    aot._loaded.clear()
    compiled = aot.try_load("ms", args, {})
    assert compiled is not None
    np.testing.assert_array_equal(
        np.asarray(compiled(*args)), np.asarray(fn(*args))
    )


def test_truncated_shard_recompiles_cleanly(cache_dir):
    """Corrupt/truncated blob => the entry is dropped and the dispatch
    falls back to a clean recompile — never a crash."""
    fn, args, key = _store_one(cache_dir, name="tr")
    d = aot.aot_dir()
    shard = aot._manifest_read(d)[key]["shards"][0]
    with open(os.path.join(d, shard), "wb") as f:
        f.write(b"\x1f\x8b garbage")
    aot._loaded.clear()
    assert aot.try_load("tr", args, {}) is None  # pruned, no crash
    assert key not in aot._manifest_read(d)
    assert not os.path.exists(os.path.join(d, shard))
    # the dispatch path recompiles cleanly after the prune
    out = aot.call_or_compile("tr", fn, args, {})
    np.testing.assert_array_equal(np.asarray(out), np.asarray(fn(*args)))


def test_manifest_version_mismatch_ignored(cache_dir):
    """A manifest from a different store version is IGNORED (empty
    store), not migrated and not crashed on; a save then rewrites it at
    the current version."""
    d = os.path.join(cache_dir, "aot")
    os.makedirs(d, exist_ok=True)
    with open(os.path.join(d, "manifest.json"), "w") as f:
        json.dump({"version": 99, "entries": {"bogus": {}}}, f)
    assert aot._manifest_read(d) == {}
    fn = jax.jit(lambda a: a + 3)
    args = (np.zeros(4),)
    assert aot.try_load("vm", args, {}) is None
    assert aot.maybe_save("vm", fn, args, {}) is not None
    with open(os.path.join(d, "manifest.json")) as f:
        obj = json.load(f)
    assert obj["version"] == aot.STORE_VERSION
    assert "bogus" not in obj["entries"]


def test_legacy_v1_blob_still_loads(cache_dir):
    """A bare v1 ``<key>.bin`` (raw serialized executable, no manifest)
    written by an older build keeps serving hits."""
    from jax.experimental.serialize_executable import serialize

    fn = jax.jit(lambda a: a - 1)
    args = (np.arange(5.0),)
    blob, _, _ = serialize(fn.lower(*args).compile())
    d = os.path.join(cache_dir, "aot")
    os.makedirs(d, exist_ok=True)
    key = aot.aot_key("l1", args, {})
    with open(os.path.join(d, key + ".bin"), "wb") as f:
        f.write(blob)
    compiled = aot.try_load("l1", args, {})
    assert compiled is not None
    np.testing.assert_array_equal(
        np.asarray(compiled(*args)), np.asarray(fn(*args))
    )


def test_eviction_honors_size_cap(cache_dir, monkeypatch):
    """With a cap smaller than two entries, saving the second evicts the
    least-recently-used first entry (manifest entry AND shard files)."""
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_CAP_MB", "0.004")  # 4 kB
    d = aot.aot_dir()
    fn1, args1, k1 = _store_one(cache_dir, name="e1", n=6.0)
    assert k1 in aot._manifest_read(d)
    # make e1 strictly older than e2's write
    def backdate(e):
        e[k1]["last_used"] = 1.0

    aot._manifest_update(d, backdate)
    fn2, args2, k2 = _store_one(cache_dir, name="e2", n=7.0)
    entries = aot._manifest_read(d)
    assert k2 in entries  # the just-written entry is exempt
    assert k1 not in entries  # LRU victim
    assert not any(f.startswith(k1) for f in os.listdir(d) if f.endswith(".bin"))


def test_eviction_counts_legacy_blobs_and_sweeps_orphans(cache_dir, monkeypatch):
    """The cap accounting covers the whole directory: legacy v1 blobs
    count toward (and are evictable under) the cap by mtime, and
    crash-orphaned tmp/shard files older than the age gate are swept."""
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_CAP_MB", "0.004")  # 4 kB
    d = os.path.join(cache_dir, "aot")
    os.makedirs(d, exist_ok=True)
    legacy = os.path.join(d, "f" * 32 + ".bin")
    with open(legacy, "wb") as f:
        f.write(b"x" * 5000)
    os.utime(legacy, (1.0, 1.0))  # ancient: first in the LRU order
    old_orphan = os.path.join(d, "e" * 32 + ".s03.bin")  # no manifest entry
    with open(old_orphan, "wb") as f:
        f.write(b"y" * 100)
    os.utime(old_orphan, (1.0, 1.0))
    fresh_orphan = os.path.join(d, "ab12cd.tmp")  # maybe a write in flight
    with open(fresh_orphan, "wb") as f:
        f.write(b"z")
    _fn, _args, key = _store_one(cache_dir, name="lv")  # save runs eviction
    assert not os.path.exists(legacy)  # counted, oldest, evicted
    assert not os.path.exists(old_orphan)  # unreferenced + old: swept
    assert os.path.exists(fresh_orphan)  # young: left for its writer
    assert key in aot._manifest_read(aot.aot_dir())  # new entry exempt


def test_async_save_lands_and_loads(cache_dir, monkeypatch):
    """save_async writes off the critical path; after flush_saves the
    entry is loadable from a cold in-process state."""
    monkeypatch.delenv("KAFKABALANCER_TPU_AOT_SYNC_SAVE", raising=False)
    fn = jax.jit(lambda a: a * 5)
    args = (np.arange(4.0),)
    aot.save_async("as", fn, args, {})
    aot.flush_saves(60.0)
    key = aot.aot_key("as", args, {})
    assert key in aot._manifest_read(aot.aot_dir())
    aot._loaded.clear()
    compiled = aot.try_load("as", args, {})
    assert compiled is not None
    np.testing.assert_array_equal(
        np.asarray(compiled(*args)), np.asarray(fn(*args))
    )


def test_prefetch_by_dummy_signature(cache_dir):
    """prefetch keyed by shape/dtype-matched dummy args loads the stored
    executable in the background; the real dispatch then executes with
    real values (dummies are never staged or executed)."""
    fn, args, key = _store_one(cache_dir, name="pf", n=9.0)
    aot._loaded.clear()
    aot.stats.clear()
    assert aot.prefetch("pf", (np.zeros(9),), {}) == key
    aot.flush_prefetches(60.0)
    assert key in aot._loaded
    assert aot.stats["pf"].get("prefetch") == 1.0
    out = aot.call_or_compile("pf", fn, args, {})
    np.testing.assert_array_equal(np.asarray(out), np.asarray(fn(*args)))
    # an unknown signature is not prefetchable: no entry, no thread
    assert aot.prefetch("pf", (np.zeros(10),), {}) is None


def test_codec_fallback_chain(cache_dir, monkeypatch):
    """KAFKABALANCER_TPU_AOT_CODEC selects the codec; zstd degrades to
    gzip when the module is absent; raw stores uncompressed."""
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_CODEC", "raw")
    fn, args, key = _store_one(cache_dir, name="cr")
    e = aot._manifest_read(aot.aot_dir())[key]
    assert e["codec"] == "raw" and e["stored_bytes"] == e["raw_bytes"]
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_CODEC", "zstd")
    # this container has no zstandard module: documented gzip fallback
    if aot._zstd() is None:
        assert aot._codec() == "gzip"


def test_zstd_entry_without_module_is_miss_not_corruption(cache_dir, monkeypatch):
    """A reader without the zstandard module must treat a zstd-coded
    entry as a MISS (recompile path), never as corruption: the blob is
    valid for capable readers (prewarm may run on a fuller image) and
    must not be deleted."""
    fn, args, key = _store_one(cache_dir, name="zr")
    d = aot.aot_dir()

    def force_zstd(e):
        e[key]["codec"] = "zstd"

    aot._manifest_update(d, force_zstd)
    shard = aot._manifest_read(d)[key]["shards"][0]
    monkeypatch.setattr(aot, "_zstd_mod", None)  # simulate absent module
    aot._loaded.clear()
    assert aot.try_load("zr", args, {}) is None  # miss, not a crash
    assert key in aot._manifest_read(d)  # entry preserved
    assert os.path.exists(os.path.join(d, shard))  # shards preserved


def test_manifest_cache_tracks_rapid_writes(cache_dir):
    """Two manifest writes inside one filesystem-timestamp tick: the
    in-process cache must reflect the LAST write (a stale snapshot keyed
    by an identical mtime would resurrect the pre-write entry set on the
    next read-modify-write, orphaning the newer entry's shards)."""
    d = os.path.join(cache_dir, "aot")
    os.makedirs(d, exist_ok=True)
    aot._manifest_update(d, lambda e: e.update(k1={"shards": []}))
    aot._manifest_update(d, lambda e: e.update(k2={"shards": []}))
    with open(os.path.join(d, "manifest.json")) as f:
        on_disk = json.load(f)["entries"]
    assert set(on_disk) >= {"k1", "k2"}
    assert set(aot._manifest_read(d)) == set(on_disk)
    cached = aot._manifest_cache
    assert cached is not None and set(cached[2]) == set(on_disk)


# --- platform-keyed load gating (noload.json sidecar) --------------------


def test_save_records_platform_and_digest(cache_dir):
    """Every v2.1 entry carries the saving backend platform and a blob
    md5 — the two facts the read path classifies deserialize failures
    with."""
    _fn, _args, key = _store_one(cache_dir, name="plat")
    entry = aot._manifest_read(aot.aot_dir())[key]
    assert entry["platform"] == aot._platform()
    assert len(entry["md5"]) == 32


def test_other_platform_entry_is_clean_miss(cache_dir):
    """An entry saved by a DIFFERENT platform is skipped without a blob
    read or a prune — the saving platform still serves from it."""
    fn, args, key = _store_one(cache_dir, name="xplat")
    d = aot.aot_dir()

    def fake_platform(e):
        e[key]["platform"] = "definitely-not-this-one"

    aot._manifest_update(d, fake_platform)
    aot._loaded.clear()
    from kafkabalancer_tpu import obs

    before = obs.metrics.counter_get("aot.platform_skips")
    assert aot.try_load("xplat", args, {}) is None
    assert obs.metrics.counter_get("aot.platform_skips") == before + 1
    assert key in aot._manifest_read(d)  # entry preserved


def test_own_platform_deserialize_failure_records_noload(
    cache_dir, monkeypatch
):
    """The satellite's core pin: a deserialize failure on an INTACT blob
    this very platform saved becomes a lasting noload verdict — the
    entry survives, later loads are clean misses (no deserialize
    attempt), prefetch declines, and maybe_save stops re-serializing."""
    import jax.experimental.serialize_executable as se

    fn, args, key = _store_one(cache_dir, name="doomed")
    d = aot.aot_dir()
    aot._loaded.clear()

    calls = []

    def boom(*a, **kw):
        calls.append(1)
        raise RuntimeError("Symbols not found simulated")

    monkeypatch.setattr(se, "deserialize_and_load", boom)
    # failure 1: records the verdict, KEEPS the entry
    assert aot.try_load("doomed", args, {}) is None
    assert len(calls) == 1
    assert key in aot._manifest_read(d)
    assert os.path.exists(os.path.join(d, "noload.json"))
    with open(os.path.join(d, "noload.json")) as f:
        verdicts = json.load(f)
    # scoped to platform AND jax version: an upgrade re-earns the load
    assert "doomed" in verdicts[aot._noload_key()]
    assert aot._noload_key().startswith(aot._platform() + "|")
    # later loads: clean miss, deserialize never called again
    from kafkabalancer_tpu import obs

    before = obs.metrics.counter_get("aot.noload_skips")
    assert aot.try_load("doomed", args, {}) is None
    assert len(calls) == 1
    assert obs.metrics.counter_get("aot.noload_skips") == before + 1
    # prefetch declines instead of spawning a doomed loader
    assert aot.prefetch("doomed", args, {}) is None
    # a save this platform can never read back is skipped
    aot._manifest_update(d, lambda e: e.pop(key, None))
    assert aot.maybe_save("doomed", fn, args, {}) is None


def test_resident_executables_lru_bounded(monkeypatch):
    """aot._loaded is LRU-bounded: a long-lived daemon drifting across
    shape buckets must not accumulate device-resident executables
    forever. Hits refresh recency; inserts past the cap evict the
    least-recently-used entry."""
    monkeypatch.setenv("KAFKABALANCER_TPU_LOADED_CAP", "2")
    monkeypatch.setattr(aot, "_loaded", {})
    aot._loaded_put("a", "exe-a")
    aot._loaded_put("b", "exe-b")
    assert aot._loaded_get("a") == "exe-a"  # refreshes a's recency
    aot._loaded_put("c", "exe-c")  # evicts b (now least recent)
    assert set(aot._loaded) == {"a", "c"}
    assert aot._loaded_get("b") is None
    # cap <= 0 disables the bound
    monkeypatch.setenv("KAFKABALANCER_TPU_LOADED_CAP", "0")
    for i in range(8):
        aot._loaded_put(f"k{i}", i)
    assert len(aot._loaded) == 10


def test_transient_deserialize_failure_records_no_verdict(
    cache_dir, monkeypatch
):
    """A transient-looking failure (resource pressure, relay
    connectivity) proves nothing about the deserializer — no lasting
    verdict, the intact entry survives, and the next process simply
    retries the load."""
    import jax.experimental.serialize_executable as se

    _fn, args, key = _store_one(cache_dir, name="flaky")
    d = aot.aot_dir()
    aot._loaded.clear()

    monkeypatch.setattr(
        se, "deserialize_and_load",
        lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("RESOURCE_EXHAUSTED: backend unavailable")
        ),
    )
    assert aot.try_load("flaky", args, {}) is None
    assert not os.path.exists(os.path.join(d, "noload.json"))
    assert not aot._load_blocked(d, "flaky")
    assert key in aot._manifest_read(d)  # intact entry survives for retry


def test_unrecognized_deserialize_failure_records_no_verdict(
    cache_dir, monkeypatch
):
    """Verdicts come from an ALLOWLIST of known-deterministic
    signatures: an unrecognized failure (a relay hiccup surfacing as a
    generic error) fails open — no lasting verdict, entry kept, next
    process retries."""
    import jax.experimental.serialize_executable as se

    _fn, args, key = _store_one(cache_dir, name="oddball")
    d = aot.aot_dir()
    aot._loaded.clear()

    monkeypatch.setattr(
        se, "deserialize_and_load",
        lambda *a, **kw: (_ for _ in ()).throw(
            RuntimeError("Connection reset by peer")
        ),
    )
    assert aot.try_load("oddball", args, {}) is None
    assert not os.path.exists(os.path.join(d, "noload.json"))
    assert not aot._load_blocked(d, "oddball")
    assert key in aot._manifest_read(d)


def test_corrupted_blob_still_prunes_not_noload(cache_dir, monkeypatch):
    """A deserialize failure whose blob digest does NOT match the saved
    md5 is corruption: pruned and recompiled as ever — no lasting
    platform verdict from damaged bytes."""
    import jax.experimental.serialize_executable as se

    _fn, args, key = _store_one(cache_dir, name="damaged")
    d = aot.aot_dir()
    aot._loaded.clear()

    def lie(e):
        e[key]["md5"] = "0" * 32

    aot._manifest_update(d, lie)
    monkeypatch.setattr(
        se, "deserialize_and_load",
        lambda *a, **kw: (_ for _ in ()).throw(RuntimeError("boom")),
    )
    assert aot.try_load("damaged", args, {}) is None
    assert key not in aot._manifest_read(d)  # pruned
    assert not os.path.exists(os.path.join(d, "noload.json"))


def test_sidecars_survive_orphan_sweep(cache_dir, monkeypatch):
    """The eviction sweep reclaims blob shards and .tmp orphans only —
    aged sidecar files (noload.json, pallas_gate.json) are not its to
    delete."""
    d = os.path.join(cache_dir, "aot")
    os.makedirs(d, exist_ok=True)
    for fname in ("noload.json", "pallas_gate.json"):
        with open(os.path.join(d, fname), "w") as f:
            f.write("{}")
    orphan = os.path.join(d, "deadbeef.s00.bin")
    with open(orphan, "wb") as f:
        f.write(b"x" * 16)
    old = 1.0  # epoch 1970: well past the orphan age
    for fname in ("noload.json", "pallas_gate.json", "deadbeef.s00.bin"):
        os.utime(os.path.join(d, fname), (old, old))
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_CAP_MB", "0.00001")
    aot._evict_to_cap(d)
    assert not os.path.exists(orphan)
    assert os.path.exists(os.path.join(d, "noload.json"))
    assert os.path.exists(os.path.join(d, "pallas_gate.json"))


# --- per-lane execution pinning (serve device lanes) ----------------------


class _FakeDev:
    def __init__(self, id_):
        self.id = id_


def test_resident_key_carries_execution_device():
    """The disk key stays device-free (one blob serves every lane); the
    resident key carries the pinned device so one lane's deserialized
    copy never answers for another's."""
    assert aot._resident_key("abc") == "abc"
    aot.set_execution_device(_FakeDev(3))
    try:
        assert aot._resident_key("abc") == "abc@dev3"
    finally:
        aot.set_execution_device(None)
    assert aot._resident_key("abc") == "abc"


def test_pinned_lanes_hold_separate_resident_copies(cache_dir):
    """Two lane pins load the same stored blob into two resident slots;
    the unpinned path keeps its own."""
    fn = jax.jit(lambda a: a + 1, static_argnames=())
    args = (np.arange(4.0),)
    aot.maybe_save("lane_t", fn, args, {})
    aot._loaded.clear()
    base = aot.try_load("lane_t", args, {})
    assert base is not None
    dev0 = jax.devices()[0]
    aot.set_execution_device(dev0)
    try:
        pinned = aot.try_load("lane_t", args, {})
        assert pinned is not None
        key = aot.aot_key("lane_t", args, {})
        assert key in aot._loaded
        assert f"{key}@dev{dev0.id}" in aot._loaded
    finally:
        aot.set_execution_device(None)


def test_staging_cache_reuses_prestaged_buffers(cache_dir):
    """stage_host_arrays ships arrays ahead of time; _stage_args then
    CONSUMES the device-resident buffer by content digest (pop — staged
    buffers are single-use) instead of paying a second transfer.
    Content drift is a harmless miss."""
    cache = {}
    a = np.arange(16.0)
    b = np.ones((4, 4), dtype=bool)
    assert aot.stage_host_arrays(cache, (a, None, b)) == 2
    prestaged_a = cache[aot._stage_key(a)]
    aot.set_staging_cache(cache)
    try:
        staged = aot._stage_args((np.arange(16.0), None, b))
        assert staged is not None
        assert staged[0] is prestaged_a  # digest hit, no second transfer
        assert staged[1] is None
        # consumed: the cache no longer pins the device buffers
        assert aot._stage_key(a) not in cache
        assert cache == {}
        # changed content: clean miss, fresh transfer
        c = np.arange(16.0) * 3
        staged2 = aot._stage_args((c,))
        assert staged2 is not None
        np.testing.assert_array_equal(np.asarray(staged2[0]), c)
    finally:
        aot.set_staging_cache(None)
    # without the thread-local cache, _stage_args is the plain transfer
    staged3 = aot._stage_args((a,))
    assert staged3 is not None and staged3[0] is not prestaged_a
    # mispredicted leftovers are dropped past the cap at the next stage
    big = {("junk", i): object() for i in range(aot._STAGE_CACHE_CAP + 1)}
    aot.stage_host_arrays(big, (a,))
    assert len(big) == 1  # cleared, then the fresh entry staged
