"""AOT executable store (ops/aot.py): save/load round trip, keying, and
fallback behavior — on the CPU backend with a temp cache dir."""

import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from kafkabalancer_tpu.ops import aot


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("KAFKABALANCER_TPU_NO_AOT", raising=False)
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    yield str(tmp_path)
    jax.config.update("jax_compilation_cache_dir", old)
    aot._loaded.clear()


def test_roundtrip(cache_dir):
    """maybe_save writes an executable; try_load returns a callable whose
    output matches the jit path exactly."""
    fn = jax.jit(
        lambda a, b, s: (a * b).sum() + s, static_argnames=()
    )
    a = np.arange(8.0)
    b = np.ones(8)
    args = (a, b, 2.0)
    statics = {}
    assert aot.try_load("t", args, statics) is None  # nothing stored yet
    path = aot.maybe_save("t", fn, args, statics)
    assert path is not None and os.path.exists(path)
    aot._loaded.clear()
    compiled = aot.try_load("t", args, statics)
    assert compiled is not None
    got = np.asarray(compiled(*args))
    want = np.asarray(fn(*args))
    np.testing.assert_array_equal(got, want)
    # in-process memo: second load returns the same object
    assert aot.try_load("t", args, statics) is compiled


def test_multi_output(cache_dir):
    fn = jax.jit(lambda a: (a + 1, (a * 2).sum()))
    args = (np.arange(4.0),)
    assert aot.maybe_save("m", fn, args, {}) is not None
    aot._loaded.clear()
    compiled = aot.try_load("m", args, {}, out_leaves=2)
    assert compiled is not None
    g1, g2 = compiled(*args)
    w1, w2 = fn(*args)
    np.testing.assert_array_equal(np.asarray(g1), np.asarray(w1))
    np.testing.assert_array_equal(np.asarray(g2), np.asarray(w2))


def test_key_separates_shapes_statics(cache_dir):
    """Different arg shapes/dtypes/statics and None-vs-array args key
    differently; identical calls key identically."""
    k = aot.aot_key("f", (np.zeros(4), None), {"x": 1})
    assert k == aot.aot_key("f", (np.zeros(4), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(5), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4, np.float32), None), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4), np.zeros(1)), {"x": 1})
    assert k != aot.aot_key("f", (np.zeros(4), None), {"x": 2})
    assert k != aot.aot_key("f", (np.zeros(4), None), {"x": jnp.float32})
    assert k != aot.aot_key("g", (np.zeros(4), None), {"x": 1})


def test_corrupt_entry_pruned(cache_dir):
    """A corrupt blob is removed and the caller falls back (returns None)."""
    args = (np.zeros(3),)
    path = os.path.join(
        cache_dir, "aot", aot.aot_key("c", args, {}) + ".bin"
    )
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "wb") as f:
        f.write(b"not an executable")
    assert aot.try_load("c", args, {}) is None
    assert not os.path.exists(path)


def test_disabled_by_env(cache_dir, monkeypatch):
    monkeypatch.setenv("KAFKABALANCER_TPU_NO_AOT", "1")
    fn = jax.jit(lambda a: a + 1)
    args = (np.zeros(2),)
    assert aot.maybe_save("d", fn, args, {}) is None
    assert aot.try_load("d", args, {}) is None


def test_no_cache_dir_disables(monkeypatch):
    monkeypatch.delenv("KAFKABALANCER_TPU_NO_AOT", raising=False)
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", None)
    try:
        assert aot.aot_dir() is None
        assert aot.try_load("x", (np.zeros(1),), {}) is None
    finally:
        jax.config.update("jax_compilation_cache_dir", old)
