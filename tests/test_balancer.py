"""Golden solver-behaviour tests.

Ports all 16 table cases of the reference suite (balancer_test.go:25-214)
verbatim: full expected-output equality including filled defaults, which
pins tie-breaking and default-filling behaviour, plus expected-error cases.
Adds the disambiguation cases the reference lacks (SURVEY.md §2.5): a
multi-candidate AddMissingReplicas case pinning the descending (most-loaded
first) scan, and a MoveDisallowedReplicas case (untested in the reference).
"""


import pytest

from kafkabalancer_tpu.balancer import BalanceError, balance
from kafkabalancer_tpu.models import (
    Partition,
    PartitionList,
    default_rebalance_config,
)


def wrap(parts):
    return PartitionList(version=1, partitions=list(parts))


def P(topic, partition, replicas, weight=0.0, num_replicas=0, brokers=None,
      num_consumers=0):
    return Partition(
        topic=topic, partition=partition, replicas=list(replicas),
        weight=weight, num_replicas=num_replicas,
        brokers=None if brokers is None else list(brokers),
        num_consumers=num_consumers,
    )


def cfg_leader():
    c = default_rebalance_config()
    c.allow_leader_rebalancing = True
    return c


def cfg_3replicas():
    c = default_rebalance_config()
    c.min_replicas_for_rebalancing = 3
    return c


def cfg_6brokers():
    c = default_rebalance_config()
    c.brokers = [1, 2, 3, 4, 5, 6]
    return c


# (input partitions, expected plan partitions or None, expected error or None,
#  config factory or None) — ordering matches balancer_test.go:35-187.
CASES = [
    # leader move under AllowLeaderRebalancing (balancer_test.go:36-46)
    (
        [
            P("a", 1, [1, 2, 3], weight=1.0),
            P("a", 2, [1, 3, 2], weight=1.0),
            P("a", 3, [1, 4, 5], weight=1.0),
        ],
        [P("a", 1, [4, 2, 3], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5])],
        None,
        cfg_leader,
    ),
    # follower moves (balancer_test.go:48-77)
    (
        [
            P("a", 1, [1, 2, 3], weight=1.0),
            P("a", 2, [2, 1, 4], weight=1.0),
            P("a", 3, [1, 2, 5], weight=1.0),
        ],
        [P("a", 2, [2, 3, 4], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5])],
        None,
        None,
    ),
    (
        [
            P("a", 1, [1, 2, 3], weight=1.0),
            P("a", 2, [2, 3, 4], weight=1.0),
            P("a", 3, [1, 2, 5], weight=1.0),
        ],
        [P("a", 1, [1, 4, 3], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5])],
        None,
        None,
    ),
    (
        [
            P("a", 1, [1, 4, 3], weight=1.0),
            P("a", 2, [2, 3, 4], weight=1.0),
            P("a", 3, [1, 2, 5], weight=1.0),
        ],
        [P("a", 3, [1, 3, 5], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5])],
        None,
        None,
    ),
    # MinReplicas gating (balancer_test.go:79-89)
    (
        [
            P("a", 1, [1, 2], weight=1.0),
            P("a", 2, [2, 3], weight=1.0),
            P("b", 1, [4, 3, 2], weight=1.0),
        ],
        [P("b", 1, [4, 3, 1], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4])],
        None,
        cfg_3replicas,
    ),
    # explicit broker lists incl. empty new brokers (balancer_test.go:91-110)
    (
        [
            P("a", 1, [1, 2, 3], weight=1.0),
            P("a", 2, [1, 2, 3], weight=1.0),
        ],
        [P("a", 1, [1, 4, 3], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5, 6])],
        None,
        cfg_6brokers,
    ),
    (
        [
            P("a", 1, [1, 4, 3], weight=1.0),
            P("a", 2, [1, 2, 3], weight=1.0),
        ],
        [P("a", 1, [1, 4, 5], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4, 5, 6])],
        None,
        cfg_6brokers,
    ),
    # converged input -> empty plan (balancer_test.go:111-117)
    (
        [
            P("a", 1, [1, 4, 5], weight=1.0),
            P("a", 2, [1, 2, 3], weight=1.0),
        ],
        None,
        None,
        cfg_6brokers,
    ),
    # remove extra replica (balancer_test.go:120-127)
    (
        [P("a", 1, [1, 2, 3], weight=1.0, num_replicas=2)],
        [P("a", 1, [1, 3], weight=1.0, num_replicas=2, brokers=[1, 2, 3])],
        None,
        None,
    ),
    # add missing replica (balancer_test.go:129-137)
    (
        [P("a", 1, [1, 2], weight=1.0, num_replicas=3, brokers=[1, 2, 3])],
        [P("a", 1, [1, 2, 3], weight=1.0, num_replicas=3, brokers=[1, 2, 3])],
        None,
        None,
    ),
    # duplicate replicas (balancer_test.go:140-145)
    (
        [P("a", 1, [1, 1], weight=1.0, brokers=[1, 2])],
        None,
        "has duplicated replicas",
        None,
    ),
    # all weights missing (balancer_test.go:147-153)
    (
        [P("a", 1, [1, 2]), P("a", 2, [2, 1])],
        None,
        None,
        None,
    ),
    # one weight missing (balancer_test.go:155-169)
    (
        [P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1])],
        None,
        "has no weight",
        None,
    ),
    (
        [P("a", 1, [1, 2]), P("a", 2, [2, 1], weight=1.0)],
        None,
        "has no weight",
        None,
    ),
    # negative weight (balancer_test.go:171-178)
    (
        [P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1], weight=-1.0)],
        None,
        "has negative weight",
        None,
    ),
    # unable to add replica (balancer_test.go:180-186)
    (
        [P("a", 1, [1, 2], num_replicas=3)],
        None,
        "unable to pick replica to add",
        None,
    ),
]


@pytest.mark.parametrize("idx", range(len(CASES)))
def test_golden_case(idx):
    pl_parts, expected, err, cfg_factory = CASES[idx]
    pl = wrap(pl_parts)
    cfg = cfg_factory() if cfg_factory else default_rebalance_config()

    if err is not None:
        with pytest.raises(BalanceError, match=err):
            balance(pl, cfg)
        return

    ppl = balance(pl, cfg)
    if expected is None:
        # converged / nothing to do: reference returns an empty plan
        assert len(ppl) == 0
    else:
        assert ppl == wrap(expected)


# --- disambiguation cases missing from the reference suite (SURVEY.md §2.5) ---


def test_add_missing_replica_prefers_most_loaded():
    """AddMissingReplicas scans brokers descending by load (steps.go:102-106):
    with candidates {3,4} free and broker 4 more loaded, broker 4 is picked.
    The reference's only test is single-candidate and cannot disambiguate."""
    pl = wrap(
        [
            P("a", 1, [1, 2], weight=1.0, num_replicas=3, brokers=[1, 2, 3, 4]),
            P("b", 1, [4, 1], weight=1.0),  # makes broker 4 heavier than 3
        ]
    )
    ppl = balance(pl, default_rebalance_config())
    assert ppl.partitions[0].replicas == [1, 2, 4]


def test_move_disallowed_replica_targets_most_loaded_allowed():
    """MoveDisallowedReplicas (steps.go:117-143, untested in the reference):
    a replica on a broker outside the partition's allowed set moves to the
    most-loaded allowed broker not already in the replica set."""
    pl = wrap(
        [
            P("a", 1, [1, 5], weight=1.0, brokers=[1, 2, 3]),
            P("b", 1, [3, 1], weight=1.0),  # broker 3 loaded > broker 2
        ]
    )
    ppl = balance(pl, default_rebalance_config())
    # replica on disallowed broker 5 -> most-loaded allowed non-member = 3
    assert ppl.partitions[0].replicas == [1, 3]


def test_move_disallowed_replica_infeasible():
    """No eligible target -> 'unable to pick replica to replace' (steps.go:138),
    matching the README broker-removal dead-end scenario (README.md:136-137)."""
    pl = wrap([P("a", 1, [1, 2], weight=1.0, brokers=[1])])
    with pytest.raises(BalanceError, match="unable to pick replica to replace"):
        balance(pl, default_rebalance_config())


def test_remove_extra_replica_removes_least_loaded():
    """RemoveExtraReplicas removes the replica held by the least-loaded broker
    (ascending scan, steps.go:78-83). With broker 3 lightest, {1,2,3}->RF2
    drops broker 3 here (the reference's own pinned case drops broker 2
    because its fixture makes broker 2 lightest)."""
    pl = wrap(
        [
            P("a", 1, [1, 2, 3], weight=1.0, num_replicas=2),
            P("b", 1, [2, 1], weight=1.0),
        ]
    )
    ppl = balance(pl, default_rebalance_config())
    assert ppl.partitions[0].replicas == [1, 2]


def test_distribute_leaders_swap():
    """ReassignLeaders hands leadership from the heaviest broker to the
    globally least-loaded broker; when the target is already a follower the
    positions swap in place (steps.go:278 -> utils.go:181-188)."""
    cfg = default_rebalance_config()
    cfg.rebalance_leaders = True
    pl = wrap(
        [
            P("a", 1, [1, 2], weight=1.0),
            P("a", 2, [1, 2], weight=1.0),
            P("a", 3, [1, 3], weight=1.0),
        ]
    )
    ppl = balance(pl, cfg)
    # broker 1 is heaviest (leads all three); least-loaded is broker 3;
    # first led partition is a,1 whose replicas don't contain 3 -> overwrite
    assert ppl.partitions[0].topic == "a"
    assert ppl.partitions[0].partition == 1
    assert ppl.partitions[0].replicas == [3, 2]


def test_balance_error_prefixed_with_step_name():
    pl = wrap([P("a", 1, [1, 1], weight=1.0, brokers=[1, 2])])
    with pytest.raises(BalanceError, match="^ValidateReplicas: "):
        balance(pl, default_rebalance_config())
