"""Beam-search solver tests.

Beam is an extension (no reference parity contract): assertions cover
solution quality (never worse than greedy at equal budgets on these seeds),
pipeline integration via -solver=beam, sequence-level acceptance, and the
anti-colocation objective."""

import copy
import random

import pytest

from helpers import random_partition_list
from test_balancer import P, wrap

from kafkabalancer_tpu.balancer import balance
from kafkabalancer_tpu.balancer.costmodel import (
    get_bl,
    get_broker_load,
    get_unbalance_bl,
)
from kafkabalancer_tpu.cli import apply_assignment
from kafkabalancer_tpu.models import default_rebalance_config
from kafkabalancer_tpu.solvers.beam import beam_plan


def unbalance_of(pl):
    return get_unbalance_bl(get_bl(get_broker_load(pl)))


def greedy_session(pl, cfg, max_moves):
    n = 0
    while n < max_moves:
        ppl = balance(pl, cfg)
        if len(ppl) == 0:
            break
        for changed in ppl.partitions:
            apply_assignment(pl, changed)
        n += 1
    return n


@pytest.mark.parametrize("allow_leader", [False, True])
def test_beam_never_worse_than_greedy(allow_leader):
    rng = random.Random(2000 + allow_leader)
    for _ in range(4):
        pl = random_partition_list(
            rng, rng.randint(6, 20), rng.randint(3, 7),
            weighted=True, with_consumers=True,
        )
        cfg = default_rebalance_config()
        cfg.allow_leader_rebalancing = allow_leader
        pl_g, pl_b = copy.deepcopy(pl), copy.deepcopy(pl)
        n_g = greedy_session(pl_g, copy.deepcopy(cfg), 20)
        opl = beam_plan(pl_b, copy.deepcopy(cfg), 20)
        assert unbalance_of(pl_b) <= unbalance_of(pl_g) + 1e-9
        assert len(opl) <= 20 and n_g <= 20


def test_beam_cli_pipeline_step():
    """-solver=beam drives the pipeline tail; repairs still come first."""
    pl = wrap(
        [
            P("a", 1, [1, 2, 3], weight=1.0, num_replicas=2),
            P("a", 2, [1, 2], weight=1.0),
        ]
    )
    cfg = default_rebalance_config()
    cfg.solver = "beam"
    ppl = balance(pl, cfg)  # the repair fires before any beam search
    # RemoveExtraReplicas drops the replica on the least-loaded holder
    # (broker 3, steps.go:78-83)
    assert ppl.partitions[0].replicas == [1, 2]


def test_beam_converged_returns_empty():
    pl = wrap([P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1], weight=1.0)])
    cfg = default_rebalance_config()
    cfg.solver = "beam"
    assert len(balance(pl, cfg)) == 0
    pl2 = wrap([P("a", 1, [1, 2], weight=1.0), P("a", 2, [2, 1], weight=1.0)])
    assert len(beam_plan(pl2, default_rebalance_config(), 10)) == 0


def test_beam_respects_budget():
    rng = random.Random(2100)
    pl = random_partition_list(rng, 20, 6, weighted=True)
    cfg = default_rebalance_config()
    opl = beam_plan(pl, cfg, 3)
    assert len(opl) <= 3


def test_beam_finds_compound_improvement():
    """Width>1 lookahead matches or beats greedy on a tie-heavy instance
    (equal weights force many plateaus a single-step search can stall on)."""
    rng = random.Random(2200)
    pl = random_partition_list(rng, 24, 5, weighted=False)
    cfg = default_rebalance_config()
    cfg.beam_width = 8
    cfg.beam_depth = 4
    pl_g, pl_b = copy.deepcopy(pl), copy.deepcopy(pl)
    greedy_session(pl_g, copy.deepcopy(cfg), 30)
    beam_plan(pl_b, copy.deepcopy(cfg), 30)
    assert unbalance_of(pl_b) <= unbalance_of(pl_g) + 1e-9


def test_anti_colocation_penalty():
    """With the penalty on, the planner spreads same-topic replicas that
    pure load balancing would happily co-locate."""
    # topic "hot" has 4 partitions; brokers 1..4; loads are symmetric so
    # the unbalance objective alone is indifferent to which broker hosts
    # which replica — the penalty must break the tie toward spreading
    parts = [
        P("hot", 1, [1, 2], weight=1.0),
        P("hot", 2, [1, 2], weight=1.0),
        P("cold", 1, [3, 4], weight=1.0),
        P("cold", 2, [3, 4], weight=1.0),
    ]

    def colocations(pl):
        n = 0
        per = {}
        for p in pl.partitions:
            for b in p.replicas:
                per.setdefault((p.topic, b), 0)
                per[(p.topic, b)] += 1
        for c in per.values():
            n += max(0, c - 1)
        return n

    pl = wrap([copy.deepcopy(p) for p in parts])
    cfg = default_rebalance_config()
    cfg.anti_colocation = 0.5
    cfg.min_unbalance = 1e-9
    before = colocations(pl)
    beam_plan(pl, cfg, 10)
    after = colocations(pl)
    assert before == 4
    assert after < before


def test_beam_move_emission_invariant():
    """Every move emitted through the pipeline adapter improves the
    objective on its own (reference loop invariant, steps.go:227) — even
    though full sequences inside beam_plan may pass through uphill states."""
    rng = random.Random(2300)
    for _ in range(6):
        pl = random_partition_list(
            rng, rng.randint(5, 18), rng.randint(3, 6),
            weighted=bool(rng.getrandbits(1)),
        )
        cfg = default_rebalance_config()
        cfg.solver = "beam"
        cfg.allow_leader_rebalancing = bool(rng.getrandbits(1))
        for _move in range(4):
            before = None
            try:
                before = unbalance_of(pl)
            except ZeroDivisionError:
                pass
            ppl = balance(pl, cfg)
            if len(ppl) == 0:
                break
            for changed in ppl.partitions:
                apply_assignment(pl, changed)
            if before is not None and before == before:  # skip NaN
                assert unbalance_of(pl) < before - cfg.min_unbalance + 1e-12


def test_beam_siblings_mode():
    """Sibling expansion (second-best candidate per target joins the
    frontier) is a strict widening of the search: it must stay valid and
    converge at least as deep on the combined objective."""
    import copy

    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.solvers.beam import beam_plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    res = {}
    for sib in (False, True):
        pl = synth_cluster(60, 8, rf=2, seed=19, weighted=True)
        cfg = default_rebalance_config()
        cfg.min_unbalance = 1e-9
        cfg.beam_width = 4
        cfg.beam_depth = 3
        cfg.beam_siblings = sib
        opl = beam_plan(pl, cfg, 300)
        for p in pl.iter_partitions():
            assert len(set(p.replicas)) == len(p.replicas)
        res[sib] = get_unbalance_bl(get_bl(get_broker_load(pl)))
    # a wider frontier cannot end catastrophically worse; allow small
    # trajectory differences
    assert res[True] <= res[False] * 1.5 + 1e-9


def test_beam_chunked_no_premature_convergence():
    """Chunked beam dispatches must not misread a chunk-boundary depth
    truncation as convergence (near the boundary beam_session caps its
    lookahead at the leftover chunk budget, so 'stopped before the cap'
    can mean 'the improving sequence was longer than the leftover', not
    'no improving sequence exists'). After a chunked plan converges
    within a generous budget, a fresh full-depth search must find
    nothing."""
    from kafkabalancer_tpu.solvers.beam import _search_once

    # seed chosen so the first 8-move chunk stops at n=7 (a boundary
    # stop: 7 + depth > 8) with improving sequences still available — the
    # pre-fix code broke there and abandoned them
    rng = random.Random(9)
    pl = random_partition_list(rng, 24, 6, weighted=True)
    cfg = default_rebalance_config()
    cfg.min_unbalance = 1e-9
    cfg.beam_width = 4
    cfg.beam_depth = 4
    opl = beam_plan(pl, copy.deepcopy(cfg), 256, chunk_moves=8)
    assert len(opl) < 256  # converged within budget
    assert _search_once(pl, copy.deepcopy(cfg), depth=4) is None


def test_session_then_beam_pipeline_reaches_colocation_floor():
    """The deployment recipe for anti-colocation at scale (suite config
    4b): converge balance with the fused session first, then beam +
    anti-colocation from the balanced state. On a weighted zipf-topic
    instance the pipeline must reach the UNAVOIDABLE colocation floor
    without giving the balance back (beam spends its budget on
    colocation structure, not raw balance)."""
    import benchmarks.suite as suite
    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(240, 16, rf=3, seed=5, weighted=True,
                       zipf_topics=True)
    floor = suite.colocation_floor(pl, 16)
    start = suite.colocations(pl)
    assert start > floor  # instance has avoidable colocations

    cfg_bal = default_rebalance_config()
    cfg_bal.min_unbalance = 1e-7
    cfg_beam = default_rebalance_config()
    cfg_beam.min_unbalance = 1e-7
    cfg_beam.beam_width = 4
    cfg_beam.beam_depth = 4
    cfg_beam.beam_siblings = True
    cfg_beam.anti_colocation = 1e-3

    plan(pl, copy.deepcopy(cfg_bal), 2048, batch=16)
    u_mid = unbalance_of(pl)
    beam_plan(pl, copy.deepcopy(cfg_beam), 2048)
    assert suite.colocations(pl) == floor
    # colocation fixes may trade a little balance (lambda-priced), never
    # wreck it
    assert unbalance_of(pl) <= max(2 * u_mid, u_mid + 1e-3)


def test_rotation_locked_instances_need_beam():
    """VERDICT r4 weak #3 resolved by construction: the rotation-locked
    class (utils/synth.py rotation_locked_cluster) is where beam's
    uphill sequences are NECESSARY — every improvement is a 3-move
    rotation whose single steps are uphill for the combined objective
    and whose pair-swap partners are blocked, so the greedy colocation
    session WITH polish commits nothing, while beam (with the immediate-
    reversal bar this round added — without it the undo move outranked
    every true continuation and the search oscillated) resolves every
    cycle at fixed width."""
    import collections

    from kafkabalancer_tpu.solvers.scan import plan
    from kafkabalancer_tpu.utils.synth import rotation_locked_cluster

    def colo(pl):
        c = collections.Counter()
        for p in pl.iter_partitions():
            for b in p.replicas:
                c[(p.topic, b)] += 1
        return sum(v - 1 for v in c.values() if v > 1)

    lam = 0.015
    ng = 4

    # greedy combined-objective session + colocation-aware polish: locked
    pl_s = rotation_locked_cluster(ng)
    cfg_s = default_rebalance_config()
    cfg_s.min_unbalance = 1e-9
    start = colo(pl_s)
    assert start == 6 * ng
    opl_s = plan(pl_s, cfg_s, 10000, batch=8, anti_colocation=lam,
                 polish=True)
    assert len(opl_s) == 0
    assert colo(pl_s) == start

    # beam at FIXED width resolves every cycle (3 moves per group), and
    # load balance stays perfect (each rotation is load-neutral)
    pl_b = rotation_locked_cluster(ng)
    cfg_b = default_rebalance_config()
    cfg_b.min_unbalance = 1e-9
    cfg_b.anti_colocation = lam
    cfg_b.beam_width = 8
    cfg_b.beam_depth = 4
    cfg_b.beam_siblings = True
    opl_b = beam_plan(pl_b, cfg_b, 10000)
    assert len(opl_b) == 3 * ng
    assert colo(pl_b) == 3 * ng  # the resolvable half; the rest is frozen
    assert unbalance_of(pl_b) == 0.0
    for p in pl_b.iter_partitions():
        assert len(set(p.replicas)) == len(p.replicas)
        if p.brokers:
            assert set(p.replicas).issubset(set(p.brokers))
