"""CLI end-to-end tests.

Ports the reference's CLI suite (kafkabalancer_test.go:11-166): ``run()`` is
called directly with in-memory stdio and argument vectors — full-pipeline
integration without subprocesses — asserting exit codes and stderr
substrings. The fixture (tests/data/test.json) matches the reference's
test/test.json: 8 partitions / 2 topics / brokers {1..4}, deliberately
unbalanced toward broker 1.
"""

import io
import json
import os


from kafkabalancer_tpu.cli import run

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


def fixture_text():
    with open(FIXTURE) as fh:
        return fh.read()


class TestExitCodeMatrix:
    def test_help(self):  # kafkabalancer_test.go:11-21
        rv, _out, err = run_cli(["-help"], stdin=fixture_text())
        assert rv == 0
        assert "Usage of kafkabalancer:" in err

    def test_stdin(self):  # kafkabalancer_test.go:23-30
        rv, out, _err = run_cli(["-input-json"], stdin=fixture_text())
        assert rv == 0
        assert json.loads(out)["version"] == 1

    def test_file(self):  # kafkabalancer_test.go:32-38
        rv, _out, _err = run_cli(["-input-json", f"-input={FIXTURE}"])
        assert rv == 0

    def test_file_and_zk(self):  # kafkabalancer_test.go:40-49
        rv, _out, err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-from-zk=localhost:2282"]
        )
        assert rv == 3
        assert "can't specify both -input and -from-zk" in err

    def test_empty_input(self):  # kafkabalancer_test.go:51-60
        rv, _out, err = run_cli(["-input-json"], stdin="")
        assert rv == 2
        assert "failed getting partition list:" in err

    def test_malformed_input(self):  # kafkabalancer_test.go:62-71
        rv, _out, err = run_cli(["-input-json"], stdin="::malformed::")
        assert rv == 2
        assert "failed getting partition list:" in err

    def test_file_missing(self):  # kafkabalancer_test.go:73-79
        rv, _out, _err = run_cli(["-input-json", "-input=tests/data/missing.json"])
        assert rv == 1

    def test_broker_list(self):  # kafkabalancer_test.go:81-87
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-broker-ids=1,2,3"]
        )
        assert rv == 0

    def test_broker_list_malformed(self):  # kafkabalancer_test.go:89-98
        rv, _out, err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-broker-ids=malformed"]
        )
        assert rv == 3
        assert "failed parsing broker list" in err

    def test_max_reassign_malformed(self):  # kafkabalancer_test.go:100-109
        rv, _out, err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-max-reassign=-1"]
        )
        assert rv == 3
        assert "invalid number of max reassignments" in err

    def test_max_reassign_huge(self):  # kafkabalancer_test.go:111-117
        rv, _out, _err = run_cli(
            ["-input-json", f"-input={FIXTURE}", "-max-reassign=1000"]
        )
        assert rv == 0

    def test_full_output(self):  # kafkabalancer_test.go:119-125
        rv, out, _err = run_cli(["-input-json", f"-input={FIXTURE}", "-full-output"])
        assert rv == 0
        assert len(json.loads(out)["partitions"]) == 8

    def test_broken_output_stream(self):  # kafkabalancer_test.go:127-143
        class FailWriter:
            def write(self, _):
                raise OSError("fail")

        err = io.StringIO()
        rv = run(
            io.StringIO(""),
            FailWriter(),
            err,
            ["kafkabalancer", "-input-json", f"-input={FIXTURE}"],
        )
        assert rv == 4
        assert "failed writing partition list" in err.getvalue()

    def test_broken_zk_conn_string(self):  # kafkabalancer_test.go:145-154
        rv, _out, err = run_cli(["-from-zk=."])
        assert rv == 2
        assert "failed parsing zk connection string" in err

    def test_broken_data(self):  # kafkabalancer_test.go:156-166
        j = (
            '{"version":1,"partitions":[{"topic":"foo1","partition":1,'
            '"replicas":[1,2],"num_replicas":3}]}'
        )
        rv, _out, err = run_cli(["-input-json"], stdin=j)
        assert rv == 3
        assert "unable to pick replica to add" in err


class TestPlanOutput:
    def test_single_move_output(self):
        """One move on the fixture: broker 1 is overloaded; the plan moves a
        follower off it. Output is a version-1 reassignment JSON with exactly
        one partition (default -max-reassign=1, complete-partition keeps
        extending only while the same partition is chosen)."""
        rv, out, _err = run_cli(["-input-json"], stdin=fixture_text())
        assert rv == 0
        obj = json.loads(out)
        assert obj["version"] == 1
        assert len(obj["partitions"]) >= 1
        p = obj["partitions"][0]
        # the fixture's heavy broker is 1: the first accepted move takes a
        # follower away from it
        assert 1 not in p["replicas"] or p["replicas"][0] == 1

    def test_unique_filter(self):
        rv, out, _err = run_cli(
            ["-input-json", "-unique", "-max-reassign=10"], stdin=fixture_text()
        )
        assert rv == 0
        obj = json.loads(out)
        keys = [(p["topic"], p["partition"]) for p in obj["partitions"]]
        assert len(keys) == len(set(keys))

    def test_no_changes_emits_null_partitions(self):
        """A converged assignment produces {"version":1,"partitions":null} —
        the reference's nil-slice JSON encoding (kafkabalancer.go:177)."""
        j = json.dumps(
            {
                "version": 1,
                "partitions": [
                    {"topic": "a", "partition": 0, "replicas": [1, 2]},
                    {"topic": "a", "partition": 1, "replicas": [2, 1]},
                ],
            }
        )
        rv, out, _err = run_cli(["-input-json"], stdin=j)
        assert rv == 0
        assert out == '{"version":1,"partitions":null}\n'

    def test_max_reassign_zero(self):
        rv, out, _err = run_cli(
            ["-input-json", "-max-reassign=0"], stdin=fixture_text()
        )
        assert rv == 0
        assert out == '{"version":1,"partitions":null}\n'

    def test_multi_move_entries_alias_final_state(self):
        """With -max-reassign>1 every emitted entry for a partition shows its
        final replica set — the reference's aliasing behaviour (SURVEY.md
        §2.2), reproduced deliberately."""
        rv, out, _err = run_cli(
            ["-input-json", "-max-reassign=50"], stdin=fixture_text()
        )
        assert rv == 0
        obj = json.loads(out)
        final = {}
        for p in obj["partitions"]:
            final[(p["topic"], p["partition"])] = p["replicas"]
        for p in obj["partitions"]:
            assert p["replicas"] == final[(p["topic"], p["partition"])]

    def test_topics_filter_text_input(self):
        text = (
            "\tTopic: keep\tPartition: 0\tLeader: 1\tReplicas: 1,2\tIsr: 1,2\n"
            "\tTopic: drop\tPartition: 0\tLeader: 1\tReplicas: 1,2\tIsr: 1,2\n"
        )
        rv, out, _err = run_cli(
            ["-topics=keep", "-full-output"], stdin=text
        )
        assert rv == 0
        obj = json.loads(out)
        assert [p["topic"] for p in obj["partitions"]] == ["keep"]


class TestReviewRegressions:
    """Regression tests for parity bugs found in review."""

    def test_unavailable_solver_exits_3(self):
        rv, _out, err = run_cli(
            ["-input-json", "-solver=bogus"], stdin=fixture_text()
        )
        assert rv == 3
        assert "failed optimizing distribution" in err

    def test_config_log_matches_reference(self):
        """The reference never copies CompletePartition into cfg
        (kafkabalancer.go:167-173) so it always logs
        CompletePartition:false."""
        rv, _out, err = run_cli(["-input-json"], stdin=fixture_text())
        assert rv == 0
        assert (
            "rebalance config: {AllowLeaderRebalancing:false "
            "RebalanceLeaders:false MinReplicasForRebalancing:2 "
            "MinUnbalance:0.01 CompletePartition:false Brokers:[]}" in err
        )

    def test_go_strict_broker_ids(self):
        for bad in ["1,1_0", "1, 2", " 1", "1,+ 2"]:
            rv, _out, err = run_cli(
                ["-input-json", f"-broker-ids={bad}"], stdin=fixture_text()
            )
            assert rv == 3, bad
            assert "failed parsing broker list" in err

    def test_go_strict_max_reassign(self):
        # Go's flag package rejects underscores in -max-reassign; the parse
        # error prints usage and (ContinueOnError parity) execution continues
        # with the default value.
        rv, _out, err = run_cli(
            ["-input-json", "-max-reassign=1_0"], stdin=fixture_text()
        )
        assert 'invalid value "1_0" for flag -max-reassign' in err
        assert rv == 0


class TestEmptyReplicasEncoding:
    def test_empty_replicas_round_trip(self):
        """Go encodes a decoded empty replicas list as [] (non-nil slice)."""
        from kafkabalancer_tpu.codecs.writer import encode_partition_list
        from kafkabalancer_tpu.models import Partition, PartitionList

        out = encode_partition_list(
            PartitionList(
                version=1,
                partitions=[Partition(topic="a", partition=0, replicas=[])],
            )
        )
        assert '"replicas":[]' in out

    def test_duplicate_topic_partition_terminates(self):
        """Duplicate topic+partition entries are legal (-unique exists for
        them); the change must be applied to the partition instance the
        solver actually selected (identity match), or the repair loop never
        converges."""
        j = (
            '{"version":1,"partitions":['
            '{"topic":"t","partition":0,"replicas":[1,2]},'
            '{"topic":"t","partition":0,"replicas":[1,2,3],"num_replicas":2}]}'
        )
        rv, out, _err = run_cli(["-input-json"], stdin=j)
        assert rv == 0
        obj = json.loads(out)
        assert len(obj["partitions"]) >= 1
        # the over-replicated duplicate was shrunk
        assert obj["partitions"][0]["replicas"] == [1, 2]

    def test_noncomparing_move_still_applied_for_full_output(self):
        """A move rejected by the complete-partition comparison has already
        been applied in the reference (slice aliasing) before the loop
        breaks, so -full-output includes it (kafkabalancer.go:193-207)."""
        rv, out, err = run_cli(
            ["-input-json", "-full-output"], stdin=fixture_text()
        )
        assert rv == 0
        assert "did not compare" in err
        obj = json.loads(out)
        by_key = {
            (p["topic"], p["partition"]): p["replicas"]
            for p in obj["partitions"]
        }
        # the second (non-comparing) move rebalanced foo1,2 off broker 1
        assert by_key[("foo1", 2)] != [1, 2]

    def test_empty_replicas_partition_converges_like_reference(self):
        """All-zero/empty load tables propagate NaN through the objective
        exactly like Go (utils.go:130 divides 0/0 without panicking), so the
        planner reports no candidate changes and exits 0."""
        j = '{"version":1,"partitions":[{"topic":"t","partition":0,"replicas":[]}]}'
        rv, out, _err = run_cli(["-input-json"], stdin=j)
        assert rv == 0
        assert out == '{"version":1,"partitions":null}\n'
        # zero-filled explicit brokers with zero total load: same outcome
        rv, out, _err = run_cli(["-input-json", "-broker-ids=1,2"], stdin=j)
        assert rv == 0
        assert out == '{"version":1,"partitions":null}\n'

    def test_bool_flag_error_text(self):
        rv, _out, err = run_cli(
            ["-input-json=x"], stdin=fixture_text()
        )
        assert 'invalid boolean value "x" for -input-json: parse error' in err


def test_fused_session_cli(tmp_path):
    """-fused runs the whole session on device; output is a valid converged
    plan (trajectory may differ from greedy on ties, so no byte parity)."""
    import json

    out, err = io.StringIO(), io.StringIO()
    code = run(
        io.StringIO(), out, err,
        ["kb", "-input-json", "-input", FIXTURE, "-max-reassign=16", "-fused"],
    )
    assert code == 0
    plan = json.loads(out.getvalue())
    assert plan["version"] == 1
    assert plan["partitions"]
    assert "fused session:" in err.getvalue()

    # fused with a budget of 0 emits the empty plan
    out2 = io.StringIO()
    code = run(
        io.StringIO(), out2, io.StringIO(),
        ["kb", "-input-json", "-input", FIXTURE, "-max-reassign=0", "-fused"],
    )
    assert code == 0
    assert out2.getvalue() == '{"version":1,"partitions":null}\n'


def test_fused_reaches_greedy_quality(tmp_path):
    """Fused convergence matches the greedy loop's final unbalance on the
    fixture (same local optimum here)."""
    import json

    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader

    def final_unbalance(args):
        out = io.StringIO()
        assert run(io.StringIO(), out, io.StringIO(), args) == 0
        pl = get_partition_list_from_reader(io.StringIO(out.getvalue()), True, [])
        return get_unbalance_bl(get_bl(get_broker_load(pl)))

    base = ["kb", "-input-json", "-input", FIXTURE, "-max-reassign=64",
            "-full-output"]
    u_greedy = final_unbalance(base)
    u_fused = final_unbalance(base + ["-fused"])
    assert u_fused <= u_greedy + 1e-9


def test_jax_profile_flag(tmp_path):
    trace_dir = str(tmp_path / "trace")
    out, err = io.StringIO(), io.StringIO()
    code = run(
        io.StringIO(), out, err,
        ["kb", "-input-json", "-input", FIXTURE, "-solver=tpu",
         f"-jax-profile={trace_dir}"],
    )
    assert code == 0
    import os

    found = []
    for root, _dirs, files in os.walk(trace_dir):
        found.extend(files)
    assert found  # a device trace was written


def test_beam_cli_knobs():
    """-beam-width/-beam-depth/-anti-colocation reach the solver config."""
    out, err = io.StringIO(), io.StringIO()
    code = run(
        io.StringIO(), out, err,
        ["kb", "-input-json", "-input", FIXTURE, "-solver=beam",
         "-beam-width=4", "-beam-depth=2", "-anti-colocation=0.25",
         "-max-reassign=2"],
    )
    assert code == 0
    assert json.loads(out.getvalue())["version"] == 1


def test_fused_complete_partition():
    """-fused honors the complete-partition extension: when the budget cuts
    mid-stream, extra moves are granted while they keep targeting the same
    topic+partition (kafkabalancer.go:212-220). The single-replica fillers
    are below the min-replicas gate, so every move targets partition h/1 —
    guaranteeing the grant path actually fires."""
    data = {"version": 1, "partitions": [
        {"topic": "h", "partition": 1, "replicas": [1, 2, 3], "weight": 5},
        {"topic": "f", "partition": 1, "replicas": [1], "weight": 4},
        {"topic": "f", "partition": 2, "replicas": [2], "weight": 4},
        {"topic": "f", "partition": 3, "replicas": [3], "weight": 4},
    ]}
    raw = json.dumps(data)
    base = ["kb", "-input-json", "-max-reassign=1", "-broker-ids=1,2,3,4,5"]

    for extra in (["-fused"], []):
        out, err = io.StringIO(), io.StringIO()
        code = run(io.StringIO(raw), out, err,
                   base + ["-complete-partition"] + extra)
        assert code == 0
        plan = json.loads(out.getvalue())["partitions"]
        # two granted moves on the same partition, entries alias the final
        # replica set (reference state-threading semantics, SURVEY.md §2.2)
        assert [(p["topic"], p["partition"]) for p in plan] == [("h", 1)] * 2
        assert plan[0]["replicas"] == plan[1]["replicas"] == [1, 4, 5]
        assert "Forcing complete of Partition" in err.getvalue()

        out2 = io.StringIO()
        code = run(io.StringIO(raw), out2, io.StringIO(),
                   base + ["-complete-partition=false"] + extra)
        assert code == 0
        assert len(json.loads(out2.getvalue())["partitions"]) == 1


def test_pprof_writes_valid_pprof_protobuf(tmp_path, monkeypatch):
    """-pprof writes a gzipped profile.proto that pprof tooling can read
    (the reference's pkg/profile contract, kafkabalancer.go:100-102).
    Validated by an independent wire-format parse: sample_type pair,
    sample/location/function triples, string table, period."""
    import gzip

    monkeypatch.chdir(tmp_path)
    rv, _out, _err = run_cli(["-input-json", "-input", FIXTURE, "-pprof"])
    assert rv == 0
    data = gzip.open(tmp_path / "cpu.pprof", "rb").read()

    pos = 0
    counts = {}
    strings = []

    def varint():
        nonlocal pos
        n = shift = 0
        while True:
            b = data[pos]
            pos += 1
            n |= (b & 0x7F) << shift
            if not b & 0x80:
                return n
            shift += 7

    while pos < len(data):
        tag = varint()
        field, wire = tag >> 3, tag & 7
        counts[field] = counts.get(field, 0) + 1
        assert wire in (0, 2), f"unexpected wire type {wire}"
        if wire == 0:
            varint()
        else:
            ln = varint()
            if field == 6:
                strings.append(data[pos : pos + ln].decode("utf-8"))
            pos += ln

    # Profile: 1=sample_type 2=sample 4=location 5=function 6=string_table
    assert counts.get(1) == 2  # samples/count + cpu/nanoseconds
    assert counts.get(2, 0) > 0
    assert counts.get(2) == counts.get(4) == counts.get(5)
    assert counts.get(11) == 1 and counts.get(12) == 1  # period
    for needed in ("samples", "count", "cpu", "nanoseconds"):
        assert needed in strings
    # profiled frames include this package's own functions
    assert any("kafkabalancer_tpu" in t for t in strings)


def test_fused_polish_flag():
    """-fused -fused-polish runs the swap-polish session end to end and
    converges at least as deep as the plain fused session."""
    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader

    def final_unbalance(stdout):
        pl = get_partition_list_from_reader(io.StringIO(stdout), True, [])
        return get_unbalance_bl(get_bl(get_broker_load(pl)))

    base = [
        "-input-json", "-input", FIXTURE, "-fused", "-fused-batch=4",
        "-max-reassign=64", "-unique", "-min-unbalance=0", "-full-output",
    ]
    rv_p, out_p, err_p = run_cli(base + ["-fused-polish"])
    assert rv_p == 0, err_p
    assert "fused session:" in err_p
    rv_f, out_f, err_f = run_cli(base)
    assert rv_f == 0, err_f
    assert final_unbalance(out_p) <= final_unbalance(out_f) + 1e-12


def test_fused_rebalance_leader():
    """-fused with -rebalance-leader routes through the fused leader
    session (round 1 fell back to the host per-move pipeline).
    -fused-batch=1 replays the host pipeline trajectory exactly; the
    default batched mode may pick a different (convergent) trajectory —
    same contract the flag help documents for move sessions — so it is
    pinned on quality, not bytes."""
    from kafkabalancer_tpu.balancer.costmodel import (
        get_bl,
        get_broker_load,
        get_unbalance_bl,
    )
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader

    def final_unbalance(stdout):
        pl = get_partition_list_from_reader(io.StringIO(stdout), True, [])
        return get_unbalance_bl(get_bl(get_broker_load(pl)))

    rv_h, out_h, err_h = run_cli(
        [
            "-input-json", "-input", FIXTURE,
            "-rebalance-leader", "-max-reassign=4", "-unique",
        ]
    )
    assert rv_h == 0, err_h
    # batch=1: same plan as the host pipeline (parity pinned in
    # test_scan too)
    rv_f, out_f, err_f = run_cli(
        [
            "-input-json", "-input", FIXTURE, "-fused", "-fused-batch=1",
            "-rebalance-leader", "-max-reassign=4", "-unique",
        ]
    )
    assert rv_f == 0, err_f
    assert json.loads(out_f) == json.loads(out_h)
    # default batch: convergent batched extension — must end at least as
    # balanced as the host trajectory (leadership loads, leaders count
    # toward the premium objective)
    rv_b, out_b, err_b = run_cli(
        [
            "-input-json", "-input", FIXTURE, "-fused",
            "-rebalance-leader", "-max-reassign=4", "-unique",
            "-full-output",
        ]
    )
    assert rv_b == 0, err_b
    rv_hf, out_hf, err_hf = run_cli(
        [
            "-input-json", "-input", FIXTURE,
            "-rebalance-leader", "-max-reassign=4", "-unique",
            "-full-output",
        ]
    )
    assert rv_hf == 0, err_hf
    assert final_unbalance(out_b) <= final_unbalance(out_hf) + 1e-9


def test_fused_shard():
    """-fused -fused-shard runs the mesh-sharded converge session over
    the conftest 8-device virtual mesh; plans are bit-identical to the
    single-device batched session (shard_session's exactness contract);
    -fused-polish and -rebalance-leader both compose with it."""
    base = [
        "-input-json", "-input", FIXTURE, "-fused", "-fused-batch=8",
        "-max-reassign=8", "-unique",
    ]
    rv_s, out_s, err_s = run_cli(base + ["-fused-shard"])
    assert rv_s == 0, err_s
    rv_1, out_1, err_1 = run_cli(base)
    assert rv_1 == 0, err_1
    assert json.loads(out_s) == json.loads(out_1)

    # -fused-polish composes: the sharded session runs first, the polish
    # tail on one device after
    rv_p, out_p, err_p = run_cli(base + ["-fused-shard", "-fused-polish"])
    assert rv_p == 0, err_p
    assert json.loads(out_p)["version"] == 1

    # -rebalance-leader delegates to the fused leader session and must
    # match the non-sharded run exactly
    lead = [
        "-input-json", "-input", FIXTURE, "-fused", "-rebalance-leader",
        "-max-reassign=4", "-unique",
    ]
    rv_l, out_l, err_l = run_cli(lead + ["-fused-shard"])
    assert rv_l == 0, err_l
    assert "single-device" in err_l
    rv_l1, out_l1, err_l1 = run_cli(lead)
    assert rv_l1 == 0, err_l1
    assert json.loads(out_l) == json.loads(out_l1)

    # -fused-shard without -fused is a config error (exit 3), not a
    # silently ignored flag
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-fused-shard"]
    )
    assert rv == 3
    assert "-fused-shard requires -fused" in err


def test_cli_byte_parity_fuzz():
    """Randomized instances through the FULL CLI: -solver=tpu stdout must
    be byte-identical to -solver=greedy (and thus the Go reference) across
    shapes, weights, consumers, per-partition broker restrictions, and
    flag combinations — the tie-window contract at the outermost surface."""
    import random

    import sys as _sys

    _sys.path.insert(0, os.path.dirname(__file__))
    from helpers import random_partition_list

    from kafkabalancer_tpu.codecs.writer import write_partition_list

    rng = random.Random(20260730)
    flag_mixes = [
        ["-max-reassign=1"],
        ["-max-reassign=5", "-unique"],
        ["-max-reassign=3", "-allow-leader"],
        # complete-partition must be off with -rebalance-leader here: when
        # leadership ping-pongs on one partition, every "next move" targets
        # the same topic+partition and the completion extension
        # (kafkabalancer.go:212-220) grants +1 forever — a faithful
        # reproduction of the reference's own unbounded loop (documented
        # in README fidelity notes)
        ["-max-reassign=4", "-rebalance-leader", "-unique",
         "-complete-partition=false"],
        ["-max-reassign=2", "-full-output"],
    ]
    for trial in range(5):
        # fixed shape ranges keep the jit bucket constant across trials
        # (one compile, five reuses — the tpu path compiles per bucket)
        pl = random_partition_list(
            rng,
            rng.randint(12, 16),
            rng.randint(5, 6),
            max_rf=3,
            weighted=bool(trial % 2),
            with_consumers=True,
            restrict_brokers=True,
        )
        buf = io.StringIO()
        write_partition_list(buf, pl)
        raw = buf.getvalue()
        flags = flag_mixes[trial % len(flag_mixes)]
        rv_g, out_g, err_g = run_cli(
            ["-input-json", "-solver=greedy"] + flags, stdin=raw
        )
        rv_t, out_t, err_t = run_cli(
            ["-input-json", "-solver=tpu"] + flags, stdin=raw
        )
        assert rv_g == rv_t, (trial, flags, err_g, err_t)
        assert out_g == out_t, (trial, flags)


def test_fused_anti_colocation():
    """-fused -anti-colocation routes the colocation-aware batched
    session; it now COMPOSES with -fused-polish and -fused-shard (the
    r4 verdict's missing #1); invalid combinations exit 3 with a
    diagnostic."""
    base = [
        "-input-json", "-input", FIXTURE, "-fused", "-fused-batch=4",
        "-max-reassign=64", "-min-unbalance=0",
    ]
    rv, out, err = run_cli(base + ["-anti-colocation=0.001"])
    assert rv == 0, err
    assert "fused session:" in err

    rv, _out, err = run_cli(
        base + ["-anti-colocation=0.001", "-fused-polish"]
    )
    assert rv == 0, err
    assert "fused session:" in err
    rv, _out, err = run_cli(
        base + ["-anti-colocation=0.001", "-fused-shard"]
    )
    assert rv == 0, err
    assert "fused session:" in err
    rv, _out, err = run_cli(
        base + ["-anti-colocation=0.001", "-fused-shard", "-fused-polish"]
    )
    assert rv == 0, err
    assert "fused session:" in err
    rv, _out, err = run_cli(
        base + ["-anti-colocation=0.001", "-rebalance-leader"]
    )
    assert rv == 3 and "excludes" in err
    rv, _out, err = run_cli(
        ["-input-json", "-input", FIXTURE, "-fused", "-fused-batch=1",
         "-anti-colocation=0.001"]
    )
    assert rv == 3 and "requires -fused-batch>1" in err
