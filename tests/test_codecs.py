"""Codec tests.

Ports the reference's codec suite (codecs_test.go:9-62) and adds coverage
for the Go-byte-compatible writer (float formatting, omitempty, null
partitions, HTML escaping) and the unique filter.
"""

import io

import pytest

from kafkabalancer_tpu.codecs import (
    CodecError,
    filter_partition_list,
    get_partition_list_from_reader,
    write_partition_list,
)
from kafkabalancer_tpu.codecs.writer import encode_partition_list, format_go_float
from kafkabalancer_tpu.codecs.zookeeper import parse_zk_connection_string
from kafkabalancer_tpu.models import Partition, PartitionList

JSON_FIXTURE = """{"version":1,
   "partitions":[{"topic":"foo1","partition":2,"replicas":[1,2]},
                 {"topic":"foo1","partition":0,"replicas":[1,2]},
                 {"topic":"foo2","partition":2,"replicas":[1,2]},
                 {"topic":"foo2","partition":0,"replicas":[1,3]},
                 {"topic":"foo1","partition":1,"replicas":[1,3]},
                 {"topic":"foo2","partition":1,"replicas":[1,4]}]
  }"""

TEXT_FIXTURE = """Topic:test\tPartitionCount:9\tReplicationFactor:3\tConfigs:
\tTopic: test\tPartition: 0\tLeader: 2\tReplicas: 2,0,1\tIsr: 0,1,2
\tTopic: test\tPartition: 1\tLeader: 0\tReplicas: 0,1,2\tIsr: 0,1,2
\tTopic: test\tPartition: 2\tLeader: 1\tReplicas: 1,2,0\tIsr: 0,1,2
\tTopic: test\tPartition: 3\tLeader: 2\tReplicas: 2,1,0\tIsr: 0,1,2
\tTopic: test\tPartition: 4\tLeader: 0\tReplicas: 0,2,1\tIsr: 0,1,2
\tTopic: test\tPartition: 5\tLeader: 1\tReplicas: 1,0,2\tIsr: 0,1,2
\tTopic: test\tPartition: 6\tLeader: 2\tReplicas: 2,0,1\tIsr: 0,1,2
\tTopic: test\tPartition: 7\tLeader: 0\tReplicas: 0,1,2\tIsr: 0,1,2
\tTopic: test\tPartition: 8\tLeader: 1\tReplicas: 1,2,0\tIsr: 0,1,2"""


class TestParsingJSON:
    def test_parses(self):
        pl = get_partition_list_from_reader(JSON_FIXTURE, True, [])
        assert pl.version == 1
        assert len(pl) == 6
        assert pl.partitions[0] == Partition(topic="foo1", partition=2, replicas=[1, 2])

    def test_wrong_version(self):
        with pytest.raises(
            CodecError,
            match="wrong partition list version: expected 1, got 2",
        ):
            get_partition_list_from_reader('{"version":2,"partitions":[]}', True, [])

    def test_malformed(self):
        with pytest.raises(CodecError, match="failed parsing json"):
            get_partition_list_from_reader("::malformed::", True, [])

    def test_empty(self):
        with pytest.raises(CodecError, match="empty partition list"):
            get_partition_list_from_reader('{"version":1,"partitions":[]}', True, [])

    def test_extension_fields(self):
        j = (
            '{"version":1,"partitions":[{"topic":"t","partition":0,"replicas":[1,2],'
            '"weight":2.5,"num_replicas":3,"brokers":[1,2,3],"num_consumers":4}]}'
        )
        pl = get_partition_list_from_reader(j, True, [])
        p = pl.partitions[0]
        assert p.weight == 2.5
        assert p.num_replicas == 3
        assert p.brokers == [1, 2, 3]
        assert p.num_consumers == 4


class TestWritingJSON:
    def test_round_trip(self):
        pl = get_partition_list_from_reader(JSON_FIXTURE, True, [])
        out = io.StringIO()
        write_partition_list(out, pl)
        assert out.getvalue() == (
            '{"version":1,"partitions":['
            '{"topic":"foo1","partition":2,"replicas":[1,2]},'
            '{"topic":"foo1","partition":0,"replicas":[1,2]},'
            '{"topic":"foo2","partition":2,"replicas":[1,2]},'
            '{"topic":"foo2","partition":0,"replicas":[1,3]},'
            '{"topic":"foo1","partition":1,"replicas":[1,3]},'
            '{"topic":"foo2","partition":1,"replicas":[1,4]}]}\n'
        )

    def test_nil_partitions_encodes_null(self):
        # Go marshals a nil slice as null (kafkabalancer.go:42 has no
        # omitempty): an empty plan prints {"version":1,"partitions":null}.
        assert (
            encode_partition_list(PartitionList(version=1, partitions=None))
            == '{"version":1,"partitions":null}\n'
        )

    def test_version_forced_to_1(self):
        out = encode_partition_list(PartitionList(version=7, partitions=[]))
        assert out == '{"version":1,"partitions":[]}\n'

    def test_omitempty_fields(self):
        p = Partition(
            topic="t", partition=0, replicas=[1, 2], weight=1.0,
            num_replicas=2, brokers=[1, 2, 3], num_consumers=0,
        )
        out = encode_partition_list(PartitionList(version=1, partitions=[p]))
        assert out == (
            '{"version":1,"partitions":[{"topic":"t","partition":0,'
            '"replicas":[1,2],"weight":1,"num_replicas":2,"brokers":[1,2,3]}]}\n'
        )

    def test_html_escaping(self):
        p = Partition(topic="a<b>&c", partition=0, replicas=[1])
        out = encode_partition_list(PartitionList(version=1, partitions=[p]))
        assert '"topic":"a\\u003cb\\u003e\\u0026c"' in out

    def test_write_failure(self):
        class FailWriter:
            def write(self, _):
                raise OSError("fail")

        with pytest.raises(CodecError, match="failed serializing json"):
            write_partition_list(FailWriter(), PartitionList(version=1, partitions=[]))


class TestGoFloatFormat:
    @pytest.mark.parametrize(
        "value,expected",
        [
            (1.0, "1"),
            (1.5, "1.5"),
            (0.3, "0.3"),
            (-2.0, "-2"),
            (100.0, "100"),
            (0.0, "0"),
            (5e-05, "0.00005"),
            (1.25e-05, "0.0000125"),
            (1e-06, "0.000001"),
            (9.9e-07, "9.9e-7"),
            (1e-07, "1e-7"),
            (1e-10, "1e-10"),
            (1e21, "1e+21"),
            (1.5e21, "1.5e+21"),
            (1e20, "100000000000000000000"),
            (123456.789, "123456.789"),
            (0.1 + 0.2, "0.30000000000000004"),
        ],
    )
    def test_matches_go(self, value, expected):
        assert format_go_float(value) == expected


class TestParsingText:
    def test_describe_output(self):
        pl = get_partition_list_from_reader(TEXT_FIXTURE, False, [])
        assert len(pl) == 9
        assert pl.partitions[0] == Partition(
            topic="test", partition=0, replicas=[2, 0, 1]
        )
        assert pl.partitions[8] == Partition(
            topic="test", partition=8, replicas=[1, 2, 0]
        )

    def test_topic_filter(self):
        with pytest.raises(CodecError, match="empty partition list"):
            get_partition_list_from_reader(TEXT_FIXTURE, False, ["other"])
        pl = get_partition_list_from_reader(TEXT_FIXTURE, False, ["test"])
        assert len(pl) == 9

    def test_non_matching_lines_skipped(self):
        with pytest.raises(CodecError, match="empty partition list"):
            get_partition_list_from_reader("random\nnoise\n", False, [])


class TestFilterPartitionList:
    def test_first_wins(self):
        pl = PartitionList(
            version=1,
            partitions=[
                Partition(topic="a", partition=1, replicas=[1, 2]),
                Partition(topic="a", partition=1, replicas=[3, 4]),
                Partition(topic="b", partition=1, replicas=[5]),
                Partition(topic="a", partition=2, replicas=[6]),
                Partition(topic="a", partition=1, replicas=[7]),
            ],
        )
        out = filter_partition_list(pl)
        assert [p.replicas for p in out.partitions] == [[1, 2], [5], [6]]
        assert out.version == 1


class TestZkConnString:
    def test_valid(self):
        nodes, chroot = parse_zk_connection_string("zk1:2181,zk2:2181/kafka")
        assert nodes == [("zk1", 2181), ("zk2", 2181)]
        assert chroot == "/kafka"

    def test_no_chroot(self):
        nodes, chroot = parse_zk_connection_string("localhost:2282")
        assert nodes == [("localhost", 2282)]
        assert chroot == ""

    @pytest.mark.parametrize(
        "bad", [".", "", "host", "host:", "host:x", ":2181", "h:0"]
    )
    def test_invalid(self, bad):
        with pytest.raises(ValueError):
            parse_zk_connection_string(bad)
