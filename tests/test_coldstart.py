"""Cold-invocation contract: deferred backend init, AOT prefetch
prediction, and the prewarm subcommand (ops/coldstart.py, prewarm.py).

The load-bearing pins:

- error-path exits (argument errors -> exit 2/3, input failures ->
  exit 1/2) must never import jax — a fresh process paying backend init
  just to print a usage error was the r5 cold-path finding;
- the background prefetch's PREDICTED signature must hit the exact store
  entry a real dispatch writes (predictor drift = silent cold-path
  regression, not an error — only this test makes it loud).
"""

import io
import json
import os
import subprocess
import sys

import pytest

import jax

from kafkabalancer_tpu.ops import aot

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")


@pytest.fixture
def cache_dir(tmp_path, monkeypatch):
    monkeypatch.delenv("KAFKABALANCER_TPU_NO_AOT", raising=False)
    monkeypatch.setenv("KAFKABALANCER_TPU_AOT_SYNC_SAVE", "1")
    old = getattr(jax.config, "jax_compilation_cache_dir", None)
    jax.config.update("jax_compilation_cache_dir", str(tmp_path))
    yield str(tmp_path)
    aot.flush_saves(30.0)
    aot.flush_prefetches(30.0)
    jax.config.update("jax_compilation_cache_dir", old)
    aot._loaded.clear()
    aot.stats.clear()


def _run_cli(args, stdin=""):
    from kafkabalancer_tpu.cli import run

    out, err = io.StringIO(), io.StringIO()
    rv = run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


# --- error paths must not pay backend init -------------------------------


def _assert_no_jax_subprocess(args, stdin, want_rc):
    """Run the CLI in a FRESH interpreter and assert both the exit code
    and that jax was never imported on the way out."""
    code = (
        "import io, sys\n"
        "from kafkabalancer_tpu.cli import run\n"
        f"rc = run(io.StringIO({stdin!r}), io.StringIO(), io.StringIO(),\n"
        f"         ['kafkabalancer'] + {args!r})\n"
        f"assert rc == {want_rc}, f'exit {{rc}} != {want_rc}'\n"
        "bad = [m for m in sys.modules if m == 'jax' or m.startswith('jax.')]\n"
        "assert not bad, f'jax imported on an error exit: {bad[:3]}'\n"
        "assert 'kafkabalancer_tpu.solvers.scan' not in sys.modules\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_exit2_codec_error_skips_backend_init():
    """A get-partition-list failure (exit 2) with a device solver
    selected exits WITHOUT initializing the JAX backend: the warmup/
    prefetch thread starts only after the input parses."""
    _assert_no_jax_subprocess(
        ["-input-json", "-solver=tpu", "-max-reassign=1"], "::malformed::", 2
    )


def test_exit3_flag_errors_skip_backend_init():
    """Argument errors (exit 3) never import jax, for every device
    backend spelling."""
    _assert_no_jax_subprocess(
        ["-input-json", "-solver=tpu", "-max-reassign=-1"], "", 3
    )
    _assert_no_jax_subprocess(
        ["-input-json", "-fused", "-fused-engine=bogus"], "", 3
    )
    _assert_no_jax_subprocess(["-input-json", "-fused-shard"], "", 3)


def test_exit1_input_open_failure_skips_backend_init():
    _assert_no_jax_subprocess(
        ["-input-json", "-solver=tpu", "-input=/nonexistent/x.json"], "", 1
    )


# --- prefetch prediction pins --------------------------------------------


def test_hints_predict_tensorize_buckets():
    """prefetch_hints' jax-free bucket arithmetic matches what tensorize
    actually produces for the parsed fixture."""
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader
    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.ops.coldstart import prefetch_hints
    from kafkabalancer_tpu.ops.tensorize import all_allowed_of, tensorize
    from kafkabalancer_tpu.solvers.scan import _settle_head

    with open(FIXTURE) as fh:
        pl = get_partition_list_from_reader(fh, True, [])
    hints = prefetch_hints(pl, None)
    cfg = default_rebalance_config()
    _settle_head(pl, cfg, 0)
    dp = tensorize(pl, cfg)
    assert hints["P"] == dp.replicas.shape[0]
    assert hints["R"] == dp.replicas.shape[1]
    assert hints["B"] == dp.bvalid.shape[0]
    assert hints["nb"] == dp.nb
    assert hints["all_allowed"] == all_allowed_of(dp)


@pytest.mark.parametrize(
    "flags,kwargs",
    [
        (
            ["-fused", "-fused-batch=4", "-max-reassign=4"],
            dict(batch=4, polish=False, allow_leader=False, max_reassign=4),
        ),
        (
            ["-fused", "-fused-batch=4", "-fused-polish", "-allow-leader",
             "-max-reassign=8"],
            dict(batch=4, polish=True, allow_leader=True, max_reassign=8),
        ),
    ],
)
def test_fused_prefetch_prediction_hits_stored_entry(cache_dir, monkeypatch, flags, kwargs):
    """Predictor pin: a real -fused CLI run stores its session
    executable; the coldstart prediction from the raw parsed input must
    compute EXACTLY that entry's key. Pinned at the key level because
    XLA:CPU cannot deserialize the while_loop session program ("Symbols
    not found" — a backend limitation; TPU deserializes it, BENCH_r05's
    aot_load_s, and the CPU-deserializable window scorer carries the
    end-to-end load pin in the test below)."""
    from kafkabalancer_tpu.codecs import get_partition_list_from_reader
    from kafkabalancer_tpu.ops import coldstart

    rv, _out, err = _run_cli(
        ["-input-json", "-input", FIXTURE] + flags,
    )
    assert rv == 0, err
    aot.flush_saves(60.0)
    entries = aot._manifest_read(aot.aot_dir())
    keys = [k for k, e in entries.items() if e["name"] == "session_packed"]
    assert len(keys) == 1, entries

    predicted = []
    monkeypatch.setattr(
        aot, "prefetch",
        lambda name, args, statics, out_leaves=1: predicted.append(
            aot.aot_key(name, args, statics)
        ),
    )
    with open(FIXTURE) as fh:
        pl = get_partition_list_from_reader(fh, True, [])
    coldstart.warm_and_prefetch(
        coldstart.prefetch_hints(pl, None),
        solver="greedy",
        fused=True,
        shard=False,
        engine="auto",
        rebalance_leaders=False,
        anti_colocation=0.0,
        min_replicas=2,
        **kwargs,
    )
    assert predicted == keys  # the predicted key IS the stored key


def test_window_prefetch_prediction_hits_stored_entry(cache_dir, monkeypatch):
    """Same pin for the -solver=tpu per-move window scorer: store via a
    forced-device find_best_move, then predict-and-prefetch."""
    import numpy as np

    from kafkabalancer_tpu.codecs import get_partition_list_from_reader
    from kafkabalancer_tpu.ops.coldstart import prefetch_hints, warm_and_prefetch
    from kafkabalancer_tpu.solvers import tpu

    # the fixture is tiny; drop the host-scan routing floor so the CLI
    # path actually dispatches (and stores) the device scorer
    monkeypatch.setattr(tpu, "MIN_DEVICE_CANDIDATES", 0)
    import kafkabalancer_tpu.ops.coldstart as coldstart

    rv, _out, err = _run_cli(
        ["-input-json", "-input", FIXTURE, "-solver=tpu", "-max-reassign=1"],
    )
    assert rv == 0, err
    aot.flush_saves(60.0)
    entries = aot._manifest_read(aot.aot_dir())
    # the f32 tier of the follower pass is the first dispatch
    f32_keys = [
        k for k, e in entries.items()
        if e["name"] == "score_window" and "<f4" in "".join(e["sig"])
        and "leaders=False" in "".join(e["sig"])
    ]
    assert len(f32_keys) == 1, entries

    aot._loaded.clear()
    aot.stats.clear()
    with open(FIXTURE) as fh:
        pl = get_partition_list_from_reader(fh, True, [])
    hints = prefetch_hints(pl, None)
    coldstart._prefetch_window(hints, allow_leader=False)
    aot.flush_prefetches(60.0)
    assert f32_keys[0] in aot._loaded
    assert aot.stats["score_window"].get("prefetch") == 1.0


def test_cli_second_run_cold_path_smoke(cache_dir):
    """Cache-cold then cache-warm -fused CLI invocations both exit 0 and
    produce identical plans (the gate.sh cold-start smoke, in-process)."""
    args = ["-input-json", "-input", FIXTURE, "-fused", "-fused-batch=4",
            "-max-reassign=4"]
    rv1, out1, err1 = _run_cli(args)
    assert rv1 == 0, err1
    aot.flush_saves(60.0)
    aot._loaded.clear()
    rv2, out2, err2 = _run_cli(args)
    assert rv2 == 0, err2
    assert out1 == out2


# --- prewarm -------------------------------------------------------------


def test_prewarm_populates_expected_keys(cache_dir, capsys):
    """prewarm writes the window-scorer tiers and the fused session
    program for the shape grid; -verify reloads each; a second run is
    all hits."""
    from kafkabalancer_tpu import prewarm

    argv = [
        "-shapes", "24x4", "-rf", "2", "-max-reassign", "8",
        "-batch", "4", "-verify",
    ]
    rc = prewarm.run(argv)
    assert rc == 0
    summary = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    names = [k["name"] for k in summary["keys"]]
    # two score_window precision tiers + one fused session
    assert names.count("score_window") == 2
    assert names.count("session_packed") == 1
    assert summary["written"] == 3 and summary["failed"] == 0
    assert summary["verified"] == 3
    entries = aot._manifest_read(aot.aot_dir())
    assert {e["name"] for e in entries.values()} == {
        "score_window", "session_packed",
    }
    # idempotent: the second run hits every key
    rc = prewarm.run(argv)
    assert rc == 0
    summary2 = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert summary2["written"] == 0 and summary2["hit"] == 3


def test_prewarm_without_store_exits_2(monkeypatch, capsys):
    monkeypatch.setenv("KAFKABALANCER_TPU_NO_AOT", "1")
    from kafkabalancer_tpu import prewarm

    assert prewarm.run(["-shapes", "8x2"]) == 2
