"""Tests for the whole-program contract analyzer (R6–R9 + SUP).

Each contract rule gets FAILING and PASSING fixture trees built under
tmp_path against a test-owned ``ContractManifest`` — R6 chain reporting
including the lazy-import exemption and PEP-562 lazy re-exports, R7
cycle detection (lexical and interprocedural) plus the RLock exemption,
R8 role propagation with boundary stops and the thread-factory
non-edge, R9 drift against a doctored golden/version/flag-table — plus
the acceptance-bar seeded violations injected into a COPY of the real
tree (a module-level numpy import in serve/state.py, a reversed lock
nesting against the shipped HistFamily→StreamingHist order, a backend
attach reachable from an accept-loop-role function, a key added to a
snapshot builder but not its golden), the shipped-tree-clean assertion,
and the differential pin that R6's jax-free verdict for serve/client.py
agrees with the no-jax subprocess oracle (tests/test_serve.py's runtime
twin).

Pure stdlib under test: none of this imports jax.
"""

import json
import os
import shutil
import subprocess
import sys
import textwrap

import pytest

from kafkabalancer_tpu.analysis.contracts import (
    SUP_RULE_ID,
    load_program,
    run_contracts,
)
from kafkabalancer_tpu.analysis.manifest import (
    ContractManifest,
    BuilderSpec,
    Boundary,
    FlagTableSpec,
    PuritySet,
    RoleRule,
    SchemaGolden,
    VersionAuthority,
    default_manifest,
)
from kafkabalancer_tpu.analysis.rules import CONTRACT_RULES, r6_import_purity

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

ACCEPT_RULE = RoleRule(
    role="accept-loop",
    forbidden=("jax.*",),
    why="accept threads must never attach the backend",
)


def write_tree(root, files):
    for rel, src in files.items():
        p = root / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return str(root)


def manifest(**kw):
    kw.setdefault("package", "pkg")
    return ContractManifest(**kw)


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- R6


def test_r6_reports_full_import_chain(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/client.py": "from pkg import core\n",
            "pkg/core.py": "import numpy as np\n",
        },
    )
    m = manifest(purity=(PuritySet("client", ("numpy",), ("pkg.client",)),))
    fs = run_contracts(root, m)
    assert rules_of(fs) == ["R6"]
    (f,) = fs
    # anchored at the import statement that pulls the module in
    assert f.path == "pkg/core.py" and f.line == 1
    assert "'pkg.client'" in f.message and "'numpy'" in f.message
    assert "pkg.client → pkg.core" in f.message
    assert "pkg.core → numpy" in f.message


def test_r6_function_local_import_is_exempt(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/client.py": "from pkg import core\n",
            "pkg/core.py": (
                "def load():\n    import numpy\n    return numpy\n"
            ),
        },
    )
    m = manifest(purity=(PuritySet("client", ("numpy",), ("pkg.client",)),))
    assert run_contracts(root, m) == []


PEP562_INIT = '''
def __getattr__(name):
    if name in ("heavy", "light"):
        from pkg import _impl
        return getattr(_impl, name)
    raise AttributeError(name)
'''


def test_r6_pep562_lazy_export_fires_only_when_pulled(tmp_path):
    files = {
        "pkg/__init__.py": PEP562_INIT,
        "pkg/_impl.py": "import numpy\nheavy = light = None\n",
        "pkg/clean.py": "import pkg\n",
        "pkg/client.py": "from pkg import heavy\n",
    }
    root = write_tree(tmp_path, files)
    m = manifest(
        purity=(
            PuritySet("clean", ("numpy",), ("pkg.clean",)),
            PuritySet("client", ("numpy",), ("pkg.client",)),
        )
    )
    fs = run_contracts(root, m)
    # pkg.clean imports only the package __init__ (no lazy name is
    # touched at module level) — clean; pkg.client's ``from pkg import
    # heavy`` triggers the deferred _impl import at import time
    assert rules_of(fs) == ["R6"]
    assert all("'pkg.client'" in f.message for f in fs)


def test_r6_star_import_triggers_every_lazy_export(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": PEP562_INIT,
            "pkg/_impl.py": "import numpy\nheavy = light = None\n",
            "pkg/client.py": "from pkg import *\n",
        },
    )
    m = manifest(purity=(PuritySet("c", ("numpy",), ("pkg.client",)),))
    assert rules_of(run_contracts(root, m)) == ["R6"]


def test_r6_unknown_member_is_manifest_drift(tmp_path):
    root = write_tree(tmp_path, {"pkg/__init__.py": ""})
    m = manifest(purity=(PuritySet("c", ("numpy",), ("pkg.ghost",)),))
    fs = run_contracts(root, m)
    assert rules_of(fs) == ["R6"]
    assert "unknown module 'pkg.ghost'" in fs[0].message


def test_r6_suppressible_with_reason(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/client.py": (
                "import numpy  # jaxlint: disable=R6 — vendored shim\n"
            ),
        },
    )
    m = manifest(purity=(PuritySet("c", ("numpy",), ("pkg.client",)),))
    assert run_contracts(root, m) == []


# ---------------------------------------------------------------- R7


R7_CLASSES = """
import threading


class A:
    def __init__(self):
        self._lock = threading.Lock()


class B:
    def __init__(self):
        self._lock = threading.Lock()

"""


def test_r7_lexical_cycle_names_both_paths(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": R7_CLASSES
            + """
def fwd(a: A, b: B):
    with a._lock:
        with b._lock:
            pass


def rev(a: A, b: B):
    with b._lock:
        with a._lock:
            pass
""",
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == ["R7"]
    (f,) = fs
    assert "lock-order cycle" in f.message
    assert "pkg.mod.A._lock" in f.message
    assert "pkg.mod.B._lock" in f.message
    # both witness paths named
    assert "pkg.mod.fwd" in f.message and "pkg.mod.rev" in f.message


def test_r7_consistent_order_is_clean(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": R7_CLASSES
            + """
def one(a: A, b: B):
    with a._lock:
        with b._lock:
            pass


def two(a: A, b: B):
    with a._lock:
        with b._lock:
            pass
""",
        },
    )
    assert run_contracts(root, manifest()) == []


def test_r7_interprocedural_cycle(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": R7_CLASSES
            + """
def fwd(a: A, b: B):
    with a._lock:
        with b._lock:
            pass


def helper(a: A):
    with a._lock:
        pass


def rev(a: A, b: B):
    with b._lock:
        helper(a)
""",
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == ["R7"]
    assert any("via call to pkg.mod.helper" in f.message for f in fs)


def test_r7_self_nesting_flagged_rlock_exempt(tmp_path):
    src = """
import threading


class C:
    def __init__(self):
        self._lock = threading.{factory}()


def pair(x: C, y: C):
    with x._lock:
        with y._lock:
            pass
"""
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": src.format(factory="Lock"),
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == ["R7"]
    assert "non-reentrant" in fs[0].message
    root2 = write_tree(
        tmp_path / "re",
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": src.format(factory="RLock"),
        },
    )
    assert run_contracts(root2, manifest()) == []


# ---------------------------------------------------------------- R8


def test_r8_role_propagates_through_call_graph(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": """
import jax


# thread-role: accept-loop
def loop():
    helper()


# thread-role: any
def helper():
    attach()


def attach():
    jax.devices()
""",
        },
    )
    fs = run_contracts(root, manifest(role_rules=(ACCEPT_RULE,)))
    assert rules_of(fs) == ["R8"]
    (f,) = fs
    assert "'jax.devices'" in f.message
    # the full chain, through the role-agnostic 'any' helper
    assert "pkg.mod.loop" in f.message
    assert "pkg.mod.helper" in f.message
    assert "pkg.mod.attach" in f.message
    assert f.snippet == "jax.devices()"  # anchored at the attach site


def test_r8_boundary_stops_descent(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": """
import jax


# thread-role: accept-loop
def loop():
    attach()


def attach():
    jax.devices()
""",
        },
    )
    m = manifest(
        role_rules=(ACCEPT_RULE,),
        boundaries=(Boundary("pkg.mod.attach", "latched behind warm"),),
    )
    assert run_contracts(root, m) == []


def test_r8_thread_factory_is_not_a_call_edge(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": """
import threading

import jax


# thread-role: accept-loop
def loop():
    t = threading.Thread(target=worker)
    t.start()


def worker():
    jax.devices()
""",
        },
    )
    assert run_contracts(root, manifest(role_rules=(ACCEPT_RULE,))) == []


def test_r8_unknown_role_is_flagged(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": (
                "# thread-role: bogus-role\ndef f():\n    pass\n"
            ),
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == ["R8"]
    assert "unknown thread-role 'bogus-role'" in fs[0].message
    assert "accept-loop" in fs[0].message  # vocabulary named


# ---------------------------------------------------------------- R9


def _r9_tree(tmp_path, golden_keys):
    return write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/snap.py": """
def build():
    out = {}
    out["a"] = 1
    out["b"] = 2
    return out
""",
            "golden.json": json.dumps({"top_level_keys": golden_keys}),
        },
    )


R9_GOLDEN = SchemaGolden(
    golden="golden.json",
    keysets=("top_level_keys",),
    builders=(BuilderSpec("pkg/snap.py", "build", var="out"),),
)


def test_r9_builder_key_missing_from_golden(tmp_path):
    root = _r9_tree(tmp_path, ["a"])
    fs = run_contracts(root, manifest(goldens=(R9_GOLDEN,)))
    assert rules_of(fs) == ["R9"]
    (f,) = fs
    assert "emits key 'b'" in f.message and "golden.json" in f.message
    assert f.path == "pkg/snap.py"


def test_r9_golden_key_no_builder_emits(tmp_path):
    root = _r9_tree(tmp_path, ["a", "b", "c"])
    fs = run_contracts(root, manifest(goldens=(R9_GOLDEN,)))
    assert rules_of(fs) == ["R9"]
    assert "'c'" in fs[0].message and "build" in fs[0].message


def test_r9_matching_golden_is_clean(tmp_path):
    root = _r9_tree(tmp_path, ["a", "b"])
    assert run_contracts(root, manifest(goldens=(R9_GOLDEN,))) == []


def test_r9_version_drift_in_module_and_docs(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/ver.py": "VER = 3\n",
            "pkg/mod.py": 'MSG = "kafkabalancer-tpu.stats/2"\n',
            "DOC.md": "emits kafkabalancer-tpu.stats/1 documents\n",
        },
    )
    m = manifest(
        versions=(VersionAuthority("stats", "pkg/ver.py", "VER"),),
        text_files=("DOC.md",),
    )
    fs = run_contracts(root, m)
    assert rules_of(fs) == ["R9"] and len(fs) == 2
    assert {f.path for f in fs} == {"pkg/mod.py", "DOC.md"}
    assert all("declares version 3" in f.message for f in fs)


def test_r9_flag_table_drift_both_directions(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/cli.py": """
class FlagSet:
    pass


fs = FlagSet()
fs.bool("foo", False, "")
fs.string("bar", "", "")
""",
            "README.md": """
# tool

### Flags

| flag | meaning |
| ---- | ------- |
| `-foo` | does foo |
| `-baz` | ghost row |

Exit codes
""",
        },
    )
    m = manifest(
        flag_table=FlagTableSpec(
            readme="README.md",
            registrar="pkg/cli.py",
            section_start="### Flags",
            section_end="Exit codes",
        )
    )
    fs = run_contracts(root, m)
    assert rules_of(fs) == ["R9"] and len(fs) == 2
    msgs = " / ".join(f.message for f in fs)
    assert "'-bar' is registered here but never named" in msgs
    assert "'-baz' but pkg/cli.py registers no such flag" in msgs


# ---------------------------------------------------------------- SUP


def test_sup_suppression_without_reason(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": "X = 1  # jaxlint: disable=R6\n",
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == [SUP_RULE_ID]
    assert "carries no reason" in fs[0].message


def test_sup_unpunctuated_reason_parses_as_unknown_rules(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": "X = 1  # jaxlint: disable=R6 stale import\n",
        },
    )
    fs = run_contracts(root, manifest())
    assert rules_of(fs) == [SUP_RULE_ID]
    assert "unknown rule id(s)" in fs[0].message


def test_sup_reasoned_suppression_is_clean(tmp_path):
    root = write_tree(
        tmp_path,
        {
            "pkg/__init__.py": "",
            "pkg/mod.py": (
                "X = 1  # jaxlint: disable=R6 — fixture exemption\n"
            ),
        },
    )
    assert run_contracts(root, manifest()) == []


# ------------------------------------ seeded violations, real tree


@pytest.fixture()
def tree_copy(tmp_path):
    """A copy of the shipped tree (package + goldens + docs + README +
    bench.py) the seeded-violation tests mutate. The unmutated copy is
    contract-clean by test_shipped_tree_is_contract_clean."""
    root = tmp_path / "tree"
    shutil.copytree(
        os.path.join(REPO, "kafkabalancer_tpu"),
        root / "kafkabalancer_tpu",
        ignore=shutil.ignore_patterns("__pycache__"),
    )
    shutil.copytree(os.path.join(REPO, "docs"), root / "docs")
    shutil.copytree(
        os.path.join(REPO, "tests", "data"), root / "tests" / "data"
    )
    shutil.copy(os.path.join(REPO, "bench.py"), root / "bench.py")
    shutil.copy(os.path.join(REPO, "README.md"), root / "README.md")
    return root


def test_seeded_numpy_import_in_serve_state(tree_copy):
    state = tree_copy / "kafkabalancer_tpu" / "serve" / "state.py"
    state.write_text("import numpy\n" + state.read_text())
    fs = run_contracts(str(tree_copy))
    assert rules_of(fs) == ["R6"]
    (f,) = [
        f for f in fs if f.path == "kafkabalancer_tpu/serve/state.py"
    ]
    assert f.line == 1 and "'numpy'" in f.message
    assert "→" in f.message  # the import chain is printed


def test_seeded_reversed_lock_nesting(tree_copy):
    # the shipped order is HistFamily._lock → StreamingHist._lock
    # (HistFamily.snapshot calls hist.snapshot under its lock); acquire
    # the pair the other way round
    (tree_copy / "kafkabalancer_tpu" / "obs" / "zfixture.py").write_text(
        textwrap.dedent(
            """
            from kafkabalancer_tpu.obs.hist import HistFamily, StreamingHist


            def reversed_pair(h: StreamingHist, fam: HistFamily):
                with h._lock:
                    with fam._lock:
                        pass
            """
        )
    )
    fs = run_contracts(str(tree_copy))
    assert rules_of(fs) == ["R7"]
    msgs = " / ".join(f.message for f in fs)
    assert "kafkabalancer_tpu.obs.hist.HistFamily._lock" in msgs
    assert "kafkabalancer_tpu.obs.hist.StreamingHist._lock" in msgs
    assert "reversed_pair" in msgs


def test_seeded_accept_loop_backend_attach(tree_copy):
    (
        tree_copy / "kafkabalancer_tpu" / "serve" / "zfixture.py"
    ).write_text(
        textwrap.dedent(
            """
            import jax


            # thread-role: accept-loop
            def probe():
                jax.devices()
            """
        )
    )
    fs = run_contracts(str(tree_copy))
    assert rules_of(fs) == ["R8"]
    (f,) = fs
    assert "'jax.devices'" in f.message and "accept-loop" in f.message


def test_seeded_builder_key_not_in_golden(tree_copy):
    daemon = tree_copy / "kafkabalancer_tpu" / "serve" / "daemon.py"
    src = daemon.read_text()
    anchor = 'out: Dict[str, Any] = {'
    assert src.count(anchor) == 1
    daemon.write_text(
        src.replace(anchor, anchor + '\n            "zz_drift_probe": 1,')
    )
    fs = run_contracts(str(tree_copy))
    assert rules_of(fs) == ["R9"]
    (f,) = fs
    assert "emits key 'zz_drift_probe'" in f.message
    assert "serve_stats_schema_v8.json" in f.message


# ------------------------------------------------- the real tree


def test_shipped_tree_is_contract_clean():
    """The acceptance criterion: ``--contracts`` exits 0 on the shipped
    tree (every remaining exception suppressed WITH a reason — an
    unreasoned one would surface here as SUP)."""
    fs = run_contracts(REPO)
    assert fs == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in fs
    )


def test_r6_verdict_agrees_with_no_jax_subprocess_oracle():
    """The differential pin: R6's static jax/numpy-free verdict for the
    forwarded client path must agree with the runtime oracle — a fresh
    process importing serve.client must have imported neither."""
    program = load_program(REPO)
    m = default_manifest()
    static_clean = r6_import_purity.verdict(
        program, m, "kafkabalancer_tpu.serve.client"
    )
    code = (
        "import sys\n"
        "import kafkabalancer_tpu.serve.client\n"
        "bad = [m for m in sys.modules if m == 'numpy' or m == 'jax' "
        "or m.startswith('jax.')]\n"
        "sys.exit(1 if bad else 0)\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True,
        text=True,
        cwd=REPO,
        timeout=120,
    )
    oracle_clean = proc.returncode == 0
    assert static_clean == oracle_clean, proc.stderr[-2000:]
    assert static_clean  # and both verdicts are "pure"


def test_contracts_cli_exits_zero_on_shipped_tree():
    proc = subprocess.run(
        [sys.executable, "-m", "kafkabalancer_tpu.analysis", "--contracts"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout


def test_contract_rule_registry():
    assert sorted(CONTRACT_RULES) == ["R6", "R7", "R8", "R9"]


def test_list_rules_is_the_shared_stage_source():
    """gate.sh labels both stages from --list-rules; pin the lists so
    the gate output and the registries cannot drift."""
    out = {}
    for mode in ("lint", "contracts"):
        proc = subprocess.run(
            [
                sys.executable,
                "-m",
                "kafkabalancer_tpu.analysis",
                "--list-rules",
                mode,
            ],
            capture_output=True,
            text=True,
            cwd=REPO,
        )
        assert proc.returncode == 0
        out[mode] = proc.stdout.split()
    assert out["lint"] == ["R1", "R2", "R3", "R4", "R5"]
    assert out["contracts"] == ["R6", "R7", "R8", "R9", SUP_RULE_ID]
