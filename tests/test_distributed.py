"""Two-process jax.distributed integration (VERDICT r1 missing #10).

Spawns two real worker processes that join one JAX runtime via
parallel.distributed.initialize, build a global mesh with make_mesh, and
run the partition-sharded scorer over a mesh spanning both processes —
proving the distributed backend is more than a wrapper: the same
shard_map program runs cross-process with the all_gather combine riding
the inter-process transport, matching the single-process result exactly.
"""

import os
import socket
import subprocess
import sys

import pytest

WORKER = os.path.join(os.path.dirname(__file__), "distributed_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_scoring():
    port = _free_port()
    env = dict(os.environ)
    # fresh interpreters must come up on the CPU platform with 2 virtual
    # devices BEFORE any jax import: scrub the ambient TPU plugin hooks
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    env["JAX_ENABLE_X64"] = "1"

    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(i), str(port)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
            env=env,
        )
        for i in range(2)
    ]
    outs = []
    for p in procs:
        out, _ = p.communicate(timeout=300)
        outs.append(out)
    if any(
        "Multiprocess computations aren't implemented" in out for out in outs
    ):
        pytest.skip(
            "this jaxlib's CPU backend lacks multiprocess collectives"
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"worker {i} failed:\n{out}"
        assert f"DIST_OK proc={i} processes=2 global_devices=4" in out, out
    # both processes computed the identical global best
    best = [
        line.split("best_u=")[1]
        for out in outs
        for line in out.splitlines()
        if "DIST_OK" in line
    ]
    assert len(best) == 2 and best[0] == best[1]

    # the whole sharded converge session ran over a part axis spanning
    # both processes (every per-iteration all_gather combine crossed the
    # process boundary) and its move log matched the single-device
    # batched session bit-for-bit; the polish tail then improved on the
    # move floor
    for i, out in enumerate(outs):
        assert f"SESSION_OK proc={i}" in out, out
        assert f"POLISH_OK proc={i}" in out, out
    sess = [
        line.split(" ", 1)[1]
        for out in outs
        for line in out.splitlines()
        if "SESSION_OK" in line or "POLISH_OK" in line
    ]
    # identical markers modulo the proc id (already split off above is the
    # full remainder including proc=; compare with proc stripped)
    norm = [s.replace("proc=0", "proc=x").replace("proc=1", "proc=x") for s in sess]
    assert norm[: len(norm) // 2] == norm[len(norm) // 2 :]

    # the what-if sweep ran sharded over the cross-process mesh, with
    # replicated results identical on both processes AND identical to a
    # single-process run of the same scenarios (this test process runs on
    # the 8-virtual-device conftest mesh)
    sweeps = [
        line.split(" ", 2)[2]
        for out in outs
        for line in out.splitlines()
        if "SWEEP_OK" in line
    ]
    assert len(sweeps) == 2 and sweeps[0] == sweeps[1]

    from kafkabalancer_tpu.models import default_rebalance_config
    from kafkabalancer_tpu.parallel.sweep import sweep
    from kafkabalancer_tpu.utils.synth import synth_cluster

    pl = synth_cluster(24, 6, rf=2, seed=11, weighted=True)
    cfg = default_rebalance_config()
    observed = sorted({b for p in pl.partitions for b in p.replicas})
    scenarios = [
        observed,
        observed + [max(observed) + 1],
        observed + [max(observed) + 1, max(observed) + 2],
        observed[1:],
    ]
    results = sweep(pl, cfg, scenarios, max_reassign=64)
    expected = ";".join(
        f"{int(r.feasible)}:{int(r.completed)}:{r.n_moves}:{r.unbalance:.9e}"
        for r in results
    )
    assert sweeps[0] == expected
