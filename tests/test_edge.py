"""Client-side edge telemetry (obs/edge.py) + the merged Perfetto
export (obs/export.py merged_trace).

Pins (ISSUE 18 satellite: clock-offset estimation under adversarial
inputs):

- the min-RTT NTP estimator recovers a pure clock-base skew EXACTLY
  under symmetric delays, bounds its error by rtt/2 under asymmetric
  delays, prefers the least-queued sample across many, accepts a
  degenerate single sample, and discards negative-RTT garbage;
- the stitched timeline NEVER shows a daemon span starting before its
  client parent (the causality clamp), including the degenerate
  no-offset fallback that pins the daemon track to the forward span;
- the edge recorder's observer seam folds phases always-on while
  CHAINING to a previously installed observer (the in-process daemon's
  flight feed), and the trace context only ships pre-send phases.
"""

import os

from kafkabalancer_tpu.obs import metrics
from kafkabalancer_tpu.obs.edge import (
    PRE_SEND_PHASES,
    EdgeContext,
    estimate_offset,
    new_trace_id,
)
from kafkabalancer_tpu.obs.export import merged_trace
from kafkabalancer_tpu.obs.trace import TRACER, Tracer


# --- the min-RTT NTP offset estimator --------------------------------------


def test_estimator_recovers_pure_skew_exactly():
    """Symmetric delays, skewed clock bases: the midpoint formula is
    exact, whatever the skew's sign or size."""
    for true_offset in (0, 1_000, -123_456_789, 7_000_000_000_000):
        t_send = 50_000
        d_recv = t_send + 400 + true_offset   # 400ns uplink
        d_send = d_recv + 90                  # daemon think time
        t_recv = t_send + 400 + 90 + 400      # 400ns downlink (symmetric)
        est = estimate_offset([(t_send, d_recv, d_send, t_recv)])
        assert est is not None
        offset, rtt = est
        assert offset == true_offset
        assert rtt == 800  # uplink + downlink, think time excluded


def test_estimator_error_bounded_by_half_rtt_under_asymmetry():
    """Fully one-sided delay (the worst case): the estimate is off by
    exactly half the path imbalance — never more than rtt/2."""
    true_offset = 5_000_000
    up, down = 10_000, 0  # all delay on the uplink
    t_send = 0
    d_recv = t_send + up + true_offset
    d_send = d_recv
    t_recv = t_send + up + down
    offset, rtt = estimate_offset([(t_send, d_recv, d_send, t_recv)])
    assert rtt == up + down
    assert abs(offset - true_offset) == (up - down) // 2
    assert abs(offset - true_offset) <= rtt // 2


def test_estimator_min_rtt_sample_wins():
    """Across many requests the least-queued exchange carries the
    tightest bound — a stable multi-sample session converges on it."""
    true_offset = 42_000
    samples = []
    for i, (up, down) in enumerate(
        [(9_000, 5_000), (300, 300), (50_000, 1_000), (2_000, 2_500)]
    ):
        t_send = i * 1_000_000
        d_recv = t_send + up + true_offset
        d_send = d_recv + 10
        t_recv = t_send + up + 10 + down
        samples.append((t_send, d_recv, d_send, t_recv))
    offset, rtt = estimate_offset(samples)
    assert rtt == 600  # the (300, 300) sample
    assert offset == true_offset  # and it is symmetric, so: exact
    # order independence: the minimum is the minimum
    assert estimate_offset(reversed(samples)) == (offset, rtt)


def test_estimator_degenerate_and_garbage_inputs():
    # a single sample IS the minimum
    assert estimate_offset([(0, 100, 110, 220)]) == (-5, 210)
    # negative RTT (clock garbage, not physics) is discarded
    assert estimate_offset([(0, 100, 10_100, 200)]) is None
    # malformed shapes/types are skipped, good samples still land
    assert estimate_offset(
        [(0,), ("x", 1, 2, 3), None, (0, 100, 110, 220)]  # type: ignore[list-item]
    ) == (-5, 210)
    assert estimate_offset([]) is None


# --- the edge recorder ------------------------------------------------------


def _fresh_registry():
    metrics.reset()
    metrics.reset_hists()


def test_edge_phases_fold_always_on_and_chain_observer():
    """Phase spans are timed with tracing DISABLED (the observer seam
    makes them real), fold into client.phase.* hists, and chain through
    to a pre-installed observer."""
    _fresh_registry()
    seen = []
    TRACER.set_observer(lambda sp: seen.append(sp.name))
    try:
        assert not TRACER.enabled
        edge = EdgeContext()
        with edge.install():
            with edge.phase("digest"):
                pass
            with edge.phase("connect"):
                pass
        assert set(edge.phases) == {"digest", "connect"}
        assert all(v >= 0.0 for v in edge.phases.values())
        snap = metrics.snapshot()
        hists = metrics.hist_snapshot()
        assert hists["client.phase.digest"]["count"] == 1
        assert hists["client.phase.connect"]["count"] == 1
        assert set(snap["phases"]["client.phase"]) == {"digest", "connect"}
        # the chained previous observer saw both spans too
        assert seen == ["client.digest", "client.connect"]
        # and install() restored it on exit
        assert TRACER._observer is not None
        with TRACER.span("client.late"):
            pass
        assert seen[-1] == "client.late"
    finally:
        TRACER.set_observer(None)


def test_trace_context_ships_only_pre_send_phases():
    edge = EdgeContext()
    for name in ("input_read", "digest", "receive", "wait_first_byte"):
        edge.phases[name] = 0.002
    edge.parent_sid = 7
    ctx = edge.trace_context()
    assert len(ctx["id"]) == 16 and int(ctx["id"], 16) >= 0
    assert ctx["parent"] == 7
    assert set(ctx["phases"]) == {"input_read", "digest"}
    assert set(ctx["phases"]) <= set(PRE_SEND_PHASES)
    assert ctx["edge_pre_ms"] == 4.0
    assert "rtt_ns" not in ctx  # no handshake sample yet
    edge.note_clock_sample(0, {"recv_ns": 100, "send_ns": 110}, 220)
    assert edge.trace_context()["rtt_ns"] == 210


def test_clock_sample_validation_and_finish_gauges():
    _fresh_registry()
    edge = EdgeContext()
    edge.note_clock_sample(0, None, 10)          # no clock block
    edge.note_clock_sample(0, {"recv_ns": "x"}, 10)  # malformed
    assert edge.clock_samples == [] and edge.clock_offset() is None
    edge.footer = None
    edge.finish({"id": edge.trace_id, "wall_s": 0.0, "spans": []})
    assert edge.e2e_s is not None and edge.e2e_s >= 0.0
    gauges = metrics.snapshot()["gauges"]
    # the replay reconciliation anchor + the edge gauge
    assert gauges["client.trace_id"] == edge.trace_id
    assert gauges["serve.edge_ms"] >= 0.0
    assert metrics.hist_snapshot()["client.edge_s"]["count"] == 1


def test_note_fallback_records_the_wasted_edge_wall():
    _fresh_registry()
    edge = EdgeContext()
    edge.note_fallback()
    assert edge.phases["fallback"] > 0.0
    assert metrics.hist_snapshot()["client.phase.fallback"]["count"] == 1


def test_trace_ids_are_distinct():
    ids = {new_trace_id() for _ in range(64)}
    assert len(ids) == 64


# --- the merged Perfetto export ---------------------------------------------


def _client_tracer_with_forward():
    """A private tracer holding one completed serve.forward span;
    returns (tracer, forward_sid, forward_start_us)."""
    t = Tracer()
    t.reset(enabled=True)
    with t.span("cli.run"):
        with t.span("serve.forward") as fwd:
            pass
    rows = {sp["name"]: sp for sp in t.snapshot()}
    sid = rows["serve.forward"]["sid"]
    return t, sid, float(rows["serve.forward"]["start_us"])


def _symmetric_sample(t_send, offset, delay=500, think=50):
    d_recv = t_send + delay + offset
    d_send = d_recv + think
    return (t_send, d_recv, d_send, t_send + 2 * delay + think)


def test_merged_trace_aligns_and_clamps_daemon_spans():
    """The causality pin: with a KNOWN daemon clock offset, mapped
    daemon spans land at their true client-clock position — and a span
    whose estimate-mapped start precedes the client parent is clamped
    to the forward span's start, never shown before it."""
    tracer, fwd_sid, fwd_start_us = _client_tracer_with_forward()
    offset = 3_600_000_000_000  # daemon clock 1h ahead
    edge = EdgeContext()
    edge.parent_sid = fwd_sid
    edge.clock_samples.append(_symmetric_sample(tracer.base_ns, offset))
    assert edge.clock_offset()[0] == offset
    fwd_start_ns = tracer.base_ns + int(fwd_start_us * 1e3)
    # span A: truly 10us after the forward start (daemon clockspace);
    # span B: engineered to map 50us BEFORE the client parent (what a
    # worst-case asymmetric estimate produces) -> must clamp
    a0 = fwd_start_ns + 10_000 + offset
    b0 = fwd_start_ns - 50_000 + offset
    edge.footer = {
        "id": edge.trace_id, "wall_s": 0.0002,
        "spans": [
            {"name": "serve.request", "t0_ns": b0, "t1_ns": b0 + 90_000},
            {"name": "serve.phase.plan", "t0_ns": a0, "t1_ns": a0 + 20_000},
            {"name": "bogus", "t0_ns": None, "t1_ns": 1},  # skipped
        ],
    }
    doc = merged_trace(tracer, edge)
    dpid = os.getpid() + 1
    daemon_x = [
        e for e in doc["traceEvents"]
        if e["ph"] == "X" and e["pid"] == dpid
    ]
    assert [e["name"] for e in daemon_x] == [
        "serve.request", "serve.phase.plan"
    ]
    for e in daemon_x:
        assert e["args"]["daemon"] is True
        assert e["args"]["trace_id"] == edge.trace_id
        assert e["args"]["parent_sid"] == fwd_sid
        # the pin: never earlier than the client parent
        assert e["ts"] >= round(fwd_start_us, 1)
    clamped = daemon_x[0]
    assert clamped["ts"] == round(fwd_start_us, 1)
    aligned = daemon_x[1]
    assert abs(aligned["ts"] - (fwd_start_us + 10.0)) <= 0.2
    assert abs(aligned["dur"] - 20.0) <= 0.2
    meta = [
        e for e in doc["traceEvents"]
        if e["ph"] == "M" and e["pid"] == dpid
        and e["name"] == "process_name"
    ]
    assert meta and meta[0]["args"]["name"] == "kafkabalancer-tpu daemon"
    other = doc["otherData"]
    assert other["served"] is True
    assert other["trace_id"] == edge.trace_id
    assert other["clock_offset_ns"] == offset
    assert other["daemon_wall_s"] == 0.0002


def test_merged_trace_degenerate_no_offset_pins_to_forward_start():
    """No usable handshake sample: the earliest daemon span is pinned
    AT the forward span's start (relative daemon timing preserved) and
    the offset is reported null."""
    tracer, fwd_sid, fwd_start_us = _client_tracer_with_forward()
    edge = EdgeContext()
    edge.parent_sid = fwd_sid
    d0 = 999_000_000_000  # unrelated daemon clockspace
    edge.footer = {
        "id": edge.trace_id, "wall_s": 0.0001,
        "spans": [
            {"name": "serve.request", "t0_ns": d0, "t1_ns": d0 + 80_000},
            {"name": "serve.phase.plan", "t0_ns": d0 + 30_000,
             "t1_ns": d0 + 60_000},
        ],
    }
    doc = merged_trace(tracer, edge)
    daemon_x = [
        e for e in doc["traceEvents"]
        if e["ph"] == "X" and e.get("args", {}).get("daemon")
    ]
    assert len(daemon_x) == 2
    earliest = min(e["ts"] for e in daemon_x)
    assert earliest == round(fwd_start_us, 1)
    # relative offsets inside the daemon track survive the pin
    assert abs(daemon_x[1]["ts"] - daemon_x[0]["ts"] - 30.0) <= 0.2
    assert doc["otherData"]["clock_offset_ns"] is None
    assert doc["otherData"]["clock_rtt_ns"] is None
    for e in daemon_x:
        assert e["ts"] >= round(fwd_start_us, 1)  # the causality pin


def test_merged_trace_without_footer_is_plain_chrome_trace():
    """A fallback (or -no-daemon) invocation: no footer, no daemon
    track, no served marker — the doc is exactly the client's own."""
    tracer, _sid, _us = _client_tracer_with_forward()
    edge = EdgeContext()
    doc = merged_trace(tracer, edge)
    assert all(e["pid"] != os.getpid() + 1 for e in doc["traceEvents"])
    assert "served" not in doc.get("otherData", {})
