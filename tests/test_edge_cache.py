"""Edge residency cache pins (serve/edge_cache.py).

The cache's one contract: it NEVER yields a digest/canon the full
serve/state.py parse of the same bytes would not — on any doubt it
degrades to a miss (the caller re-reads and re-parses), so every test
here is differential: whatever rung answers (stat hit, content hit,
incremental splice, zk payload index), the result is compared field for
field against ``client_state`` over the same text, or against
``read_cluster`` over the same fake-ZK tree.
"""

import json
import os

import pytest

from kafkabalancer_tpu.serve import edge_cache as ec
from kafkabalancer_tpu.serve import state as sstate

TENANT = "tenant-a"


@pytest.fixture(autouse=True)
def _fresh_memory():
    # the in-memory layer is process-wide; tests must not hit a
    # previous test's entry through it
    ec.reset_memory_layer()
    yield
    ec.reset_memory_layer()


@pytest.fixture
def sock(tmp_path):
    return str(tmp_path / "kb.sock")


def _mk_rows(n, topic_prefix="t"):
    """Rows exercising the canonicalization corners: unicode topics,
    absent-vs-null-vs-present brokers, float weights."""
    rows = []
    for i in range(n):
        topic = f"{topic_prefix}{i % 7}" if i % 11 else f"tøpic-ü{i % 7}"
        row = {
            "topic": topic,
            "partition": i,
            "replicas": [1 + i % 5, 2 + i % 5, 3 + i % 5],
            "weight": 1.0 + 0.25 * (i % 9),
        }
        if i % 3 == 0:
            row["brokers"] = [1, 2, 3, 4, 5]
        elif i % 3 == 1:
            row["brokers"] = None  # null != absent in canonical bytes
        rows.append(row)
    return rows


def _text(rows):
    return json.dumps({"version": 1, "partitions": rows})


def _full(text):
    st = sstate.client_state(text, True, [])
    assert st is not None
    return st


def _write(path, text, backdate_s=5.0):
    """Write the input; backdating the mtime past UNSTABLE_WINDOW_NS
    makes the subsequent persist land a STABLE entry (a fresh mtime
    would be same-tick-suspect by design)."""
    with open(path, "w") as f:
        f.write(text)
    if backdate_s:
        st = os.stat(path)
        t = st.st_mtime_ns - int(backdate_s * 1e9)
        os.utime(path, ns=(t, t))


def _seed(sock, path, text, tenant=TENANT, topics=None):
    """The cli.py miss path: probe, full-parse, persist."""
    probe = ec.probe_file(sock, tenant, str(path), True, topics or [])
    assert probe.needs_text
    st = _full(text)
    ec.persist_state(
        sock, tenant, str(path), True, topics or [], text, st, probe.stat
    )
    return st


def _assert_state_matches(state, text):
    want = _full(text)
    assert state.digest == want.digest
    assert state.version == want.version
    assert list(state.canon) == list(want.canon)
    assert list(state.row_hashes) == sstate.hashes_of(want.canon)


# --- rung 1: the stat hit --------------------------------------------------


def test_stat_hit_skips_read(sock, tmp_path):
    path = tmp_path / "cluster.json"
    text = _text(_mk_rows(40))
    _write(path, text)
    _seed(sock, path, text)
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_hit"
    assert probe.hit and not probe.needs_text
    _assert_state_matches(probe.state, text)
    # the hit survives a process restart (no memory layer): same
    # answer straight from the entry file
    ec.reset_memory_layer()
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_hit" and not probe.needs_text
    _assert_state_matches(probe.state, text)


def test_miss_content_hit_promotion_then_stat_hit(sock, tmp_path):
    """The residency cycle: miss -> persist -> touched file (same
    bytes, new mtime) content-hits and RE-KEYS the entry -> the next
    probe stat-hits without a read."""
    path = tmp_path / "cluster.json"
    text = _text(_mk_rows(30))
    _write(path, text)
    _seed(sock, path, text)
    # touch: same bytes, new stat point (still backdated => stable)
    _write(path, text, backdate_s=3.0)
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_changed" and probe.needs_text
    state, hit = ec.resolve_text(probe, text)
    assert hit and state is not None
    _assert_state_matches(state, text)
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_hit" and not probe.needs_text


def test_entry_identity_mismatch_is_a_miss(sock, tmp_path):
    """Same tenant, different request shape (topics filter / format
    flag): the entry must not answer for a request it was not keyed
    to."""
    path = tmp_path / "cluster.json"
    text = _text(_mk_rows(12))
    _write(path, text)
    _seed(sock, path, text)
    probe = ec.probe_file(sock, TENANT, str(path), True, ["only-this"])
    assert probe.needs_text and probe.state is None
    probe = ec.probe_file(sock, TENANT, str(path), False, [])
    assert probe.needs_text and probe.state is None


# --- rung 3: the incremental splice, differentially ------------------------


def _churn_cases():
    def edit_weight(rows):
        rows[7]["weight"] = 123.625

    def edit_replicas(rows):
        rows[3]["replicas"] = list(reversed(rows[3]["replicas"]))

    def add_row_middle(rows):
        rows.insert(11, {"topic": "new-tøpic", "partition": 99,
                         "replicas": [9, 8, 7], "weight": 2.5})

    def add_row_end(rows):
        rows.append({"topic": "zz", "partition": 100,
                     "replicas": [1, 2], "brokers": None})

    def delete_row(rows):
        del rows[5]

    def reorder_rows(rows):
        rows[2], rows[17] = rows[17], rows[2]

    def unicode_edit(rows):
        rows[11]["topic"] = "tøpic-ü-渋谷"

    def brokers_absent_to_null(rows):
        # row 2 (i%3==2) has NO brokers key; null must change the
        # canonical bytes (absent-vs-null is reader-visible)
        assert "brokers" not in rows[2]
        rows[2]["brokers"] = None

    def brokers_null_to_absent(rows):
        assert rows[1]["brokers"] is None
        del rows[1]["brokers"]

    return [
        edit_weight, edit_replicas, add_row_middle, add_row_end,
        delete_row, reorder_rows, unicode_edit,
        brokers_absent_to_null, brokers_null_to_absent,
    ]


@pytest.mark.parametrize("churn", _churn_cases(), ids=lambda f: f.__name__)
def test_splice_differential(sock, tmp_path, churn):
    """The O(changed) rung: every churn shape must produce EXACTLY the
    digest/canon/hashes of a full re-parse of the new bytes."""
    path = tmp_path / "cluster.json"
    rows = _mk_rows(25)
    text_a = _text(rows)
    _write(path, text_a)
    _seed(sock, path, text_a)
    churn(rows)
    text_b = _text(rows)
    assert text_b != text_a
    _write(path, text_b)
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_changed" and probe.needs_text
    state, hit = ec.resolve_text(probe, text_b)
    assert state is not None and not hit
    _assert_state_matches(state, text_b)
    # and the persisted splice result stat-hits next time, still right
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "stat_hit"
    _assert_state_matches(probe.state, text_b)


def test_splice_chain_accumulates_no_drift(sock, tmp_path):
    """Churn generations resolved incrementally, each on top of the
    PREVIOUS generation's spliced entry: the digest never drifts from
    the full parse no matter how many splices compound."""
    path = tmp_path / "cluster.json"
    rows = _mk_rows(25)
    text = _text(rows)
    _write(path, text)
    _seed(sock, path, text)
    for gen, churn in enumerate(_churn_cases()):
        churn(rows)
        rows[gen % len(rows)]["weight"] = 50.0 + gen
        text = _text(rows)
        _write(path, text)
        probe = ec.probe_file(sock, TENANT, str(path), True, [])
        state, _hit = ec.resolve_text(probe, text)
        assert state is not None, f"generation {gen}"
        _assert_state_matches(state, text)


# --- the same-tick rewrite guard (the mtime-granularity hole) --------------


def test_same_tick_rewrite_never_serves_stale_digest(sock, tmp_path):
    """A rewrite forced onto the SAME (mtime_ns, size, inode) stat key
    as the cached entry: the unstable marker keeps rung 1 from trusting
    the stat key, and content verification resolves to the NEW bytes'
    digest."""
    path = tmp_path / "cluster.json"
    rows = _mk_rows(20)
    text_a = _text(rows)
    # fresh mtime: the persist lands unstable by design
    _write(path, text_a, backdate_s=0)
    _seed(sock, path, text_a)
    st_a = os.stat(path)
    # same-length rewrite: "2.0" -> "7.5", byte count identical
    assert rows[4]["weight"] == 2.0
    rows[4]["weight"] = 7.5
    text_b = _text(rows)
    assert len(text_b) == len(text_a) and text_b != text_a
    with open(path, "w") as f:
        f.write(text_b)
    # pin the rewrite onto the ORIGINAL stat key (same inode via
    # in-place truncate, same size by construction, mtime forced back)
    os.utime(path, ns=(st_a.st_mtime_ns, st_a.st_mtime_ns))
    st_b = os.stat(path)
    assert (st_b.st_ino, st_b.st_mtime_ns, st_b.st_size) == (
        st_a.st_ino, st_a.st_mtime_ns, st_a.st_size
    )
    # the dangerous case this guard exists for: identical stat key,
    # different bytes — the entry must answer "verify me", never
    # "proven hit"
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    assert probe.note == "unstable"
    assert probe.needs_text and not probe.hit
    state, hit = ec.resolve_text(probe, text_b)
    assert not hit and state is not None
    _assert_state_matches(state, text_b)
    assert _full(text_b).digest != _full(text_a).digest


def test_persist_requires_matching_pre_stat(sock, tmp_path):
    """No pre-read stat, or a file that moved between read and persist:
    the entry must NOT land (it would key the read bytes to a stat
    point they no longer belong to)."""
    path = tmp_path / "cluster.json"
    text = _text(_mk_rows(10))
    _write(path, text)
    st = _full(text)
    ec.persist_state(sock, TENANT, str(path), True, [], text, st, None)
    assert not os.path.exists(ec.entry_path(sock, TENANT))
    pre = ec.probe_file(sock, TENANT, str(path), True, []).stat
    _write(path, _text(_mk_rows(11)), backdate_s=1.0)  # moved underfoot
    ec.persist_state(sock, TENANT, str(path), True, [], text, st, pre)
    assert not os.path.exists(ec.entry_path(sock, TENANT))


# --- entry poison matrix ---------------------------------------------------


def _corruptions():
    def truncate_head(buf):
        return buf[:10]

    def truncate_half(buf):
        return buf[: len(buf) // 2]

    def flip_magic(buf):
        return b"XXXX" + buf[4:]

    def flip_header_byte(buf):
        i = 16
        return buf[:i] + bytes([buf[i] ^ 0x5A]) + buf[i + 1:]

    def flip_tail_byte(buf):
        i = len(buf) - 8
        return buf[:i] + bytes([buf[i] ^ 0x5A]) + buf[i + 1:]

    def empty(buf):
        return b""

    return [truncate_head, truncate_half, flip_magic, flip_header_byte,
            flip_tail_byte, empty]


@pytest.mark.parametrize(
    "corrupt", _corruptions(), ids=lambda f: f.__name__
)
def test_poisoned_entry_degrades_never_lies(sock, tmp_path, corrupt):
    """Any byte damage to the entry file: the probe may miss (caller
    re-parses — correct by construction) or may still answer from an
    intact head, but whatever it answers must match the full parse."""
    path = tmp_path / "cluster.json"
    text = _text(_mk_rows(30))
    _write(path, text)
    _seed(sock, path, text)
    ep = ec.entry_path(sock, TENANT)
    with open(ep, "rb") as f:
        buf = f.read()
    with open(ep, "wb") as f:
        f.write(corrupt(buf))
    ec.reset_memory_layer()  # force the disk read to see the damage
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    if probe.needs_text:
        state, _hit = ec.resolve_text(probe, text)
        if state is not None:
            _assert_state_matches(state, text)
    else:
        _assert_state_matches(probe.state, text)


def test_body_corruption_behind_intact_head_reparses(sock, tmp_path):
    """The two-phase read: a stat hit verifies only the entry HEAD, so
    body damage may surface lazily — the lazy canon/hash accessors must
    fall back to a full re-parse of the INPUT, not serve garbage."""
    path = tmp_path / "cluster.json"
    # enough rows that the packed body extends past the verified head
    text = _text(_mk_rows(400))
    _write(path, text)
    _seed(sock, path, text)
    ep = ec.entry_path(sock, TENANT)
    with open(ep, "rb") as f:
        buf = f.read()
    assert len(buf) > 8192
    i = len(buf) - 200  # deep in the row-hash/canon region
    with open(ep, "wb") as f:
        f.write(buf[:i] + bytes([buf[i] ^ 0x5A]) + buf[i + 1:])
    ec.reset_memory_layer()
    probe = ec.probe_file(sock, TENANT, str(path), True, [])
    if not probe.needs_text:
        # head verified: the digest is trustworthy; materializing the
        # rows discovers the damage and re-derives from the input file
        _assert_state_matches(probe.state, text)
    else:
        state, _hit = ec.resolve_text(probe, text)
        if state is not None:
            _assert_state_matches(state, text)


# --- the -from-zk fast path ------------------------------------------------


ZK_CONN = "localhost:2181"


@pytest.fixture
def zk_root(tmp_path, monkeypatch):
    root = tmp_path / "zk"
    (root / "brokers" / "topics").mkdir(parents=True)
    monkeypatch.setenv("KAFKABALANCER_TPU_FAKE_ZK", str(root))
    return root


def _zk_write(root, topic, part_map):
    p = root / "brokers" / "topics" / topic
    p.write_text(json.dumps({"version": 1, "partitions": part_map}))


def _zk_reference_digest(topics):
    from kafkabalancer_tpu.codecs import zookeeper as zkc

    zk = zkc.make_zk_client(ZK_CONN)
    try:
        pl = zkc.read_cluster(zk, topics or [])
    finally:
        zk.stop()
        zk.close()
    canon = [
        sstate.canonical_row_bytes(*sstate.partition_fields(p))
        for p in pl.iter_partitions()
    ]
    # the fast path synthesizes the version-1 JSON document the daemon
    # would otherwise receive via -input-json, so version 1 keys the
    # digest (read_cluster's PartitionList itself reports version 0)
    return sstate.rows_digest(1, canon), canon


def test_zk_miss_then_full_hit(sock, zk_root):
    _zk_write(zk_root, "alpha", {"0": [1, 2], "1": [2, 3]})
    _zk_write(zk_root, "beta", {"0": [3, 1]})
    want, canon = _zk_reference_digest([])
    res = ec.probe_zk(sock, ZK_CONN, [])
    assert res is not None and not res.hit
    assert res.state.digest == want
    assert list(res.state.canon) == canon
    res = ec.probe_zk(sock, ZK_CONN, [])
    assert res is not None and res.hit and res.changed_topics == 0
    assert res.state.digest == want


def test_zk_one_changed_topic_redecodes_only_it(sock, zk_root):
    _zk_write(zk_root, "alpha", {"0": [1, 2], "1": [2, 3]})
    _zk_write(zk_root, "beta", {"0": [3, 1]})
    _zk_write(zk_root, "gamma", {"0": [2, 1], "1": [1, 3]})
    assert ec.probe_zk(sock, ZK_CONN, []) is not None
    _zk_write(zk_root, "beta", {"0": [1, 3], "1": [2, 1]})
    want, canon = _zk_reference_digest([])
    res = ec.probe_zk(sock, ZK_CONN, [])
    assert res is not None and not res.hit
    assert res.changed_topics == 1
    assert res.state.digest == want
    assert list(res.state.canon) == canon


def test_zk_topic_filter_and_set_drift(sock, zk_root):
    _zk_write(zk_root, "alpha", {"0": [1, 2]})
    _zk_write(zk_root, "beta", {"0": [3, 1]})
    want_a, _ = _zk_reference_digest(["alpha"])
    res = ec.probe_zk(sock, ZK_CONN, ["alpha"])
    assert res is not None and res.state.digest == want_a
    # topic added: the cached index cannot prove the cluster unchanged
    _zk_write(zk_root, "gamma", {"0": [9, 8]})
    want_all, _ = _zk_reference_digest([])
    res = ec.probe_zk(sock, ZK_CONN, [])
    assert res is not None and res.state.digest == want_all
    # topic removed
    os.unlink(zk_root / "brokers" / "topics" / "beta")
    want_less, canon_less = _zk_reference_digest([])
    res = ec.probe_zk(sock, ZK_CONN, [])
    assert res is not None
    assert res.state.digest == want_less
    assert list(res.state.canon) == canon_less


def test_zk_unreachable_is_none(sock, tmp_path, monkeypatch):
    monkeypatch.setenv(
        "KAFKABALANCER_TPU_FAKE_ZK", str(tmp_path / "absent")
    )
    assert ec.probe_zk(sock, ZK_CONN, []) is None
