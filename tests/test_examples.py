"""The multi-host example's loopback rehearsal is a real jax.distributed
run (docs/MULTIHOST.md) — guard it so the deployment story can't rot."""

import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "multihost_sweep.py")
FIXTURE = os.path.join(REPO, "tests", "data", "test.json")


def test_multihost_sweep_local_demo():
    proc = subprocess.run(
        [
            sys.executable, EXAMPLE, "--local-demo", "2",
            "--input", FIXTURE, "--add-brokers", "1",
            "--remove-brokers", "1",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        env=dict(os.environ),
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    # rank 0 printed the ranked table exactly once (replicated results)
    assert out.count("feasible") == 1, out
    # baseline + one add + one remove scenario rows
    assert out.count("True") + out.count("False") == 3, out
