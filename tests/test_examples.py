"""The multi-host example's loopback rehearsal is a real jax.distributed
run (docs/MULTIHOST.md) — guard it so the deployment story can't rot."""

import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "examples", "multihost_sweep.py")
FIXTURE = os.path.join(REPO, "tests", "data", "test.json")


def test_multihost_sweep_local_demo():
    proc = subprocess.run(
        [
            sys.executable, EXAMPLE, "--local-demo", "2",
            "--input", FIXTURE, "--add-brokers", "1",
            "--remove-brokers", "1",
        ],
        capture_output=True,
        text=True,
        timeout=420,
        env=dict(os.environ),
    )
    if "Multiprocess computations aren't implemented" in (
        proc.stderr + proc.stdout
    ):
        pytest.skip(
            "this jaxlib's CPU backend lacks multiprocess collectives"
        )
    assert proc.returncode == 0, proc.stderr[-2000:]
    out = proc.stdout
    # rank 0 printed the ranked table exactly once (replicated results)
    assert out.count("feasible") == 1, out
    # baseline + one add + one remove scenario rows
    assert out.count("True") + out.count("False") == 3, out


def test_shard_scaling_script_runs():
    """benchmarks/shard_scaling.py (the MULTIHOST scaling-curve
    generator, VERDICT r4 missing #3) regenerates its table: BENCH_FAST
    runs the S∈{1,2} rows on the virtual mesh and must emit one JSON
    line per S plus the table."""
    import json

    env = dict(os.environ)
    env["BENCH_FAST"] = "1"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmarks", "shard_scaling.py")],
        capture_output=True,
        text=True,
        timeout=420,
        env=env,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    rows = [
        json.loads(line)
        for line in proc.stderr.splitlines()
        if line.startswith("{")
    ]
    assert [r["S"] for r in rows] == [1, 2]
    assert rows[0]["rows_per_shard"] == 2 * rows[1]["rows_per_shard"]
    assert all(r["iter_ms"] > 0 for r in rows)
    assert "rows/shard" in proc.stdout
