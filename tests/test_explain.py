"""The ``-explain`` plan-explanation document (obs/convergence.py).

Load-bearing pins:

- **oracle reconciliation** (the acceptance criterion): every emitted
  move's ``unbalance_before/after`` and src/dst loads must agree BIT-
  EXACTLY with an independent scalar replay of the emitted plan through
  the oracle's ``get_unbalance_bl`` — same contribution rule (leader
  premium on slot 0, utils.go:96-101), same dynamic broker-table
  membership, same float-op order;
- **plan-byte parity**: enabling ``-explain`` changes no plan bytes;
- **golden schema**: the document layout is versioned
  (``kafkabalancer-tpu.explain/1``); changing keys requires a bump and
  a new golden;
- **no-move classification**: a below-threshold exit, a converged one
  and an infeasible one are distinguishable — in the document AND in
  the ``plan.no_move_reason`` metrics gauge (the satellite).
"""

import io
import json
import os
import random

import pytest

from kafkabalancer_tpu import cli
from kafkabalancer_tpu.balancer.costmodel import get_bl, get_unbalance_bl
from kafkabalancer_tpu.models import RebalanceConfig
from kafkabalancer_tpu.obs import convergence
from tests.helpers import random_partition_list

FIXTURE = os.path.join(os.path.dirname(__file__), "data", "test.json")
GOLDEN = os.path.join(
    os.path.dirname(__file__), "data", "explain_schema_v1.json"
)


def run_cli(args, stdin=""):
    out, err = io.StringIO(), io.StringIO()
    rv = cli.run(io.StringIO(stdin), out, err, ["kafkabalancer"] + args)
    return rv, out.getvalue(), err.getvalue()


def _fused_doc(pl, cfg, max_reassign=50, batch=4, **plan_kw):
    """Run the fused session with a recorder installed; returns
    (opl, doc)."""
    from kafkabalancer_tpu.solvers.scan import plan

    rec = convergence.ConvergenceRecorder()
    convergence.install(rec)
    try:
        convergence.clear_outcome()
        rec.attach(
            pl, cfg, mode="fused", solver="tpu", engine="xla",
            batch=batch, max_reassign=max_reassign,
        )
        opl = plan(pl, cfg, max_reassign, batch=batch, **plan_kw)
        doc = rec.finalize()
    finally:
        convergence.uninstall()
        convergence.clear_outcome()
    return opl, doc


# --- the independent oracle replay (the differential pin) ------------------


def _replay_and_check(initial_replicas, parts, cfg, doc):
    """Replay the document's move list from the pre-plan assignment,
    scoring each step with the scalar oracle — every comparison below
    is EXACT equality (bit-for-bit), not a tolerance."""
    loads, counts = {}, {}
    state = [list(r) for r in initial_replicas]
    weights = [p.weight for p in parts]
    ncons = [p.num_consumers for p in parts]

    def shift(reps, w, nc, sign):
        n = len(reps)
        for i, b in enumerate(reps):
            c = w * (n + nc) if i == 0 else w
            loads[b] = loads.get(b, 0.0) + (sign * c)
            counts[b] = counts.get(b, 0) + sign

    for row, reps in enumerate(state):
        shift(reps, weights[row], ncons[row], 1)
    always = set(cfg.brokers or [])
    for b in always:
        loads.setdefault(b, 0.0)

    def unbalance():
        live = {
            b: v for b, v in loads.items()
            if counts.get(b, 0) > 0 or b in always
        }
        return get_unbalance_bl(get_bl(live))

    u = unbalance()
    assert doc["unbalance_initial"] == u
    for m in doc["moves"]:
        row = m["row"]
        reps = state[row]
        old = list(reps)
        kind, slot = m["kind"], m["slot"]
        if kind == "move":
            assert reps[slot] == m["src"]
            reps[slot] = m["dst"]
        elif kind == "swap":
            j = reps.index(m["dst"])
            assert reps[slot] == m["src"]
            reps[slot], reps[j] = m["dst"], m["src"]
        elif kind == "add":
            reps.insert(slot, m["dst"])
        elif kind == "remove":
            reps.remove(m["src"])
        else:
            pytest.fail(f"unexpected kind {kind!r}")
        assert m["unbalance_before"] == u
        if m["src"] is not None:
            assert m["src_load_before"] == loads.get(m["src"])
        if m["dst"] is not None:
            assert m["dst_load_before"] == loads.get(m["dst"], 0.0)
        shift(old, weights[row], ncons[row], -1)
        shift(reps, weights[row], ncons[row], 1)
        u = unbalance()
        assert m["unbalance_after"] == u
        assert m["score_delta"] == u - m["unbalance_before"]
        if m["src"] is not None:
            assert m["src_load_after"] == loads.get(m["src"])
        if m["dst"] is not None:
            assert m["dst_load_after"] == loads.get(m["dst"])
    assert doc["unbalance_final"] == u
    # the emitted plan's final state must agree with the replayed state
    for row, p in enumerate(parts):
        assert list(p.replicas) == state[row], row


@pytest.mark.parametrize("seed", [1, 7, 23])
def test_explain_reconciles_with_scalar_oracle_fused(seed):
    rng = random.Random(seed)
    pl = random_partition_list(
        rng, 24, 6, weighted=True, with_consumers=True, filled=True
    )
    parts = list(pl.iter_partitions())
    initial = [list(p.replicas) for p in parts]
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1,
        allow_leader_rebalancing=bool(seed % 2), solver="tpu",
    )
    opl, doc = _fused_doc(pl, cfg, max_reassign=40, batch=4)
    assert doc["schema"] == "kafkabalancer-tpu.explain/1"
    assert doc["moves_emitted"] == len(doc["moves"]) == len(opl)
    assert doc["moves_emitted"] > 0
    # JSON round trip preserves every float bit (repr round trip)
    doc = json.loads(json.dumps(doc, sort_keys=True, default=str))
    _replay_and_check(initial, parts, cfg, doc)


def test_explain_reconciles_restricted_brokers():
    rng = random.Random(99)
    pl = random_partition_list(
        rng, 20, 5, restrict_brokers=True, filled=True
    )
    parts = list(pl.iter_partitions())
    initial = [list(p.replicas) for p in parts]
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1, solver="tpu",
    )
    _opl, doc = _fused_doc(pl, cfg, max_reassign=30, batch=4)
    _replay_and_check(initial, parts, cfg, doc)
    # restricted allowlists must show up in the masking breakdown
    assert doc["candidates"]["masked"]["broker_allowlist"] > 0


def test_explain_reconciles_leader_session_swaps():
    """The fused rebalance-leaders session emits leadership SWAPS
    (SWAP_SLOT) — the replay must score the premium transfer exactly."""
    rng = random.Random(5)
    pl = random_partition_list(rng, 16, 4, filled=True, max_rf=3)
    parts = list(pl.iter_partitions())
    initial = [list(p.replicas) for p in parts]
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1,
        rebalance_leaders=True, solver="tpu",
    )
    _opl, doc = _fused_doc(pl, cfg, max_reassign=20, batch=1)
    _replay_and_check(initial, parts, cfg, doc)


# --- schema golden ---------------------------------------------------------


def test_explain_schema_golden():
    rv, out, _err = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-fused-batch=4",
         "-max-reassign=4", "-no-daemon", "-explain=-"]
    )
    assert rv == 0
    doc = json.loads(out.strip().splitlines()[-1])
    with open(GOLDEN) as f:
        golden = json.load(f)
    assert doc["schema"] == golden["schema"]
    assert set(doc) == set(golden["top_level_keys"]), sorted(doc)
    assert set(doc["config"]) == set(golden["config_keys"])
    assert set(doc["rounds"]) == set(golden["rounds_keys"])
    assert set(doc["candidates"]) == set(golden["candidates_keys"])
    assert set(doc["candidates"]["masked"]) == set(golden["masked_keys"])
    assert doc["moves"], "fixture plan should emit moves"
    for m in doc["moves"]:
        assert set(m) == set(golden["move_keys"]), sorted(m)
        for alt in m["alternatives"] or ():
            assert set(alt) == set(golden["alternative_keys"])


# --- plan-byte parity ------------------------------------------------------


@pytest.mark.parametrize(
    "extra",
    [[], ["-fused", "-fused-batch=4"], ["-solver=tpu"]],
    ids=["greedy", "fused", "tpu"],
)
def test_explain_changes_no_plan_bytes(tmp_path, extra):
    args = ["-input-json", f"-input={FIXTURE}", "-max-reassign=3",
            "-no-daemon"] + extra
    rv1, out1, _ = run_cli(args)
    path = str(tmp_path / "explain.json")
    rv2, out2, err2 = run_cli(args + [f"-explain={path}"])
    assert (rv1, out1) == (rv2, out2)
    assert "plan explanation" in err2  # the human stderr rendering
    doc = json.load(open(path))
    assert doc["moves_applied"] == len(doc["moves"])
    assert doc["moves_emitted"] == sum(m["emitted"] for m in doc["moves"])
    # with "-": the plan bytes precede the document, byte-identical
    rv3, out3, _ = run_cli(args + ["-explain=-"])
    assert rv3 == rv1
    assert out3.startswith(out1)
    tail = out3[len(out1):]
    assert json.loads(tail)["schema"] == "kafkabalancer-tpu.explain/1"


def test_explain_before_metrics_json_line(tmp_path):
    """-metrics-json='-' stays the LAST stdout line (its documented
    contract); the explain line rides between plan and metrics."""
    rv, out, _ = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=2",
         "-no-daemon", "-explain=-", "-metrics-json", "-"]
    )
    assert rv == 0
    lines = out.strip().splitlines()
    assert json.loads(lines[-1])["schema"] == "kafkabalancer-tpu.metrics/1"
    assert (
        json.loads(lines[-2])["schema"] == "kafkabalancer-tpu.explain/1"
    )


def test_complete_partition_probe_marked_applied_not_emitted():
    """The reference's complete-partition probe move is APPLIED to the
    live list (slice aliasing, kafkabalancer.go:193-207) but kept out
    of the plan when its compare fails — the document must show both:
    the trajectory replay needs the applied move, the plan does not
    contain it."""
    # default -complete-partition with -max-reassign=1: the follow-up
    # balance call proposes a DIFFERENT partition, which fails the
    # compare — one emitted move, two applied
    rv, out, err = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=1",
         "-no-daemon", "-explain=-"]
    )
    assert rv == 0
    assert "did not compare" in err
    doc = json.loads(out.strip().splitlines()[-1])
    assert doc["moves_applied"] == 2
    assert doc["moves_emitted"] == 1
    assert [m["emitted"] for m in doc["moves"]] == [True, False]
    assert "[applied, not emitted]" in err
    # the plan itself carries exactly the emitted move
    plan = json.loads(out.strip().splitlines()[0])
    assert len(plan["partitions"]) == doc["moves_emitted"]


def test_explain_unwritable_path_exits_4(tmp_path):
    rv, _out, err = run_cli(
        ["-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=2",
         "-no-daemon", f"-explain={tmp_path}/no/such/dir/x.json"]
    )
    assert rv == 4
    assert "failed writing explain document" in err


# --- no-move classification (the plan.no_move_reason satellite) ------------


def _gauges(args):
    rv, out, _err = run_cli(args + ["-metrics-json", "-"])
    assert rv == 0
    return json.loads(out.strip().splitlines()[-1])["gauges"]


@pytest.mark.parametrize("mode", [[], ["-fused"], ["-solver=tpu"]],
                         ids=["greedy", "fused", "tpu"])
def test_no_move_reason_below_threshold(mode):
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=2",
         "-min-unbalance=999999", "-no-daemon"] + mode
    )
    assert g["plan.no_move_reason"] == "below_threshold"
    assert g["plan.stop_reason"] == "below_threshold"


@pytest.mark.parametrize("mode", [[], ["-fused"]], ids=["greedy", "fused"])
def test_no_move_reason_no_feasible_candidate(mode):
    # min-replicas above every partition's RF: nothing is movable
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=2",
         "-min-replicas=9", "-no-daemon"] + mode
    )
    assert g["plan.no_move_reason"] == "no_feasible_candidate"


def test_beam_converged_plan_not_misreported_as_budget_exhausted():
    """Review fix: beam's decline notes an outcome too — a converged
    -solver=beam plan must not fall through to the budget_exhausted
    fallback heuristic."""
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-solver=beam",
         "-max-reassign=50", "-no-daemon"]
    )
    assert g["plan.stop_reason"] == "converged"
    assert "plan.no_move_reason" not in g
    # and a zero-move beam decline classifies (lazy feasibility)
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-solver=beam",
         "-max-reassign=2", "-min-replicas=9", "-no-daemon"]
    )
    assert g["plan.no_move_reason"] == "no_feasible_candidate"


def test_stop_reason_budget_exhausted():
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=1",
         "-no-daemon"]
    )
    assert g["plan.stop_reason"] == "budget_exhausted"
    assert "plan.no_move_reason" not in g


def test_converged_plan_reports_stop_reason():
    # budget far above need: the plan converges and says why it stopped
    g = _gauges(
        ["-input-json", f"-input={FIXTURE}", "-max-reassign=50",
         "-no-daemon"]
    )
    assert g["plan.stop_reason"] in ("already_balanced", "below_threshold")
    # moves were emitted, so this was not a no-move exit: gauge absent
    assert "plan.no_move_reason" not in g


def test_no_move_doc_stanza_and_stats_render():
    path_args = [
        "-input-json", f"-input={FIXTURE}", "-fused", "-max-reassign=2",
        "-min-unbalance=999999", "-no-daemon", "-explain=-",
    ]
    rv, out, err = run_cli(path_args + ["-stats"])
    assert rv == 0
    doc = json.loads(out.strip().splitlines()[-1])
    nm = doc["no_move_reason"]
    assert nm["reason"] == "below_threshold"
    assert nm["best_unbalance"] < nm["unbalance"]
    assert "no move emitted: below_threshold" in err
    # the gauge renders in the -stats human summary too
    assert "gauge plan.no_move_reason: below_threshold" in err


# --- alternatives ----------------------------------------------------------


def test_alternatives_ranked_and_chosen_is_rank0():
    rng = random.Random(3)
    pl = random_partition_list(rng, 12, 4, filled=True)
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1, solver="tpu",
    )
    _opl, doc = _fused_doc(pl, cfg, max_reassign=10, batch=1)
    assert doc["moves"]
    for m in doc["moves"]:
        alts = m["alternatives"]
        assert alts, m
        deltas = [a["delta"] for a in alts]
        assert deltas == sorted(deltas)
        # batch=1 takes the globally best single move: the chosen move
        # must be the rank-0 alternative (rank-1 scoring agrees with the
        # oracle's ordering away from exact ties)
        assert (alts[0]["row"], alts[0]["dst"]) == (m["row"], m["dst"])
    assert doc["alternatives_truncated"] is False
    assert doc["alternatives_moves_covered"] == doc["moves_emitted"]


def test_alternatives_budget_truncates_loudly():
    rng = random.Random(4)
    pl = random_partition_list(rng, 12, 4, filled=True)
    parts = list(pl.iter_partitions())
    initial = [list(p.replicas) for p in parts]
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1, solver="tpu",
    )
    from kafkabalancer_tpu.solvers.scan import plan

    rec = convergence.ConvergenceRecorder(alt_budget=1)  # nothing fits
    convergence.install(rec)
    try:
        convergence.clear_outcome()
        rec.attach(pl, cfg, mode="fused", max_reassign=10)
        plan(pl, cfg, 10, batch=4)
        doc = rec.finalize()
    finally:
        convergence.uninstall()
        convergence.clear_outcome()
    assert doc["moves_emitted"] > 0
    assert all(m["alternatives"] is None for m in doc["moves"])
    assert doc["alternatives_truncated"] is True
    assert doc["alternatives_moves_covered"] == 0
    # the trajectory pin is budget-independent
    _replay_and_check(initial, parts, cfg, doc)


# --- masking + rounds ------------------------------------------------------


def test_masking_min_replicas_counted():
    rng = random.Random(11)
    pl = random_partition_list(rng, 10, 4, max_rf=2, filled=True)
    # allow_leader makes rf-1 partitions movable (their leader slot), so
    # min_replicas=2 visibly masks them; followers-only would leave rf-1
    # partitions with zero movable slots and nothing to count
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=2,
        allow_leader_rebalancing=True, solver="tpu",
    )
    _opl, doc = _fused_doc(pl, cfg, max_reassign=10, batch=4)
    masked = doc["candidates"]["masked"]
    # rf-1 partitions exist with overwhelming probability at max_rf=2
    assert masked["min_replicas"] > 0
    assert doc["rounds"]["count"] >= 1
    assert doc["rounds"]["samples"]


def test_tie_window_recorded_for_tpu_solver(monkeypatch):
    """The tpu per-move solver feeds tie-window sizes; force the device
    path by dropping the small-instance routing floor."""
    from kafkabalancer_tpu.solvers import tpu as tpu_mod

    monkeypatch.setattr(tpu_mod, "MIN_DEVICE_CANDIDATES", 0)
    rng = random.Random(13)
    pl = random_partition_list(rng, 16, 4, filled=True)
    cfg = RebalanceConfig(
        min_unbalance=1e-9, min_replicas_for_rebalancing=1, solver="tpu",
    )
    from kafkabalancer_tpu.balancer import balance
    from kafkabalancer_tpu.cli import apply_assignment

    rec = convergence.ConvergenceRecorder()
    convergence.install(rec)
    try:
        convergence.clear_outcome()
        rec.attach(pl, cfg, mode="per-move", solver="tpu", max_reassign=3)
        for _ in range(3):
            ppl = balance(pl, cfg)
            if len(ppl) == 0:
                break
            for changed in ppl.partitions:
                apply_assignment(pl, changed)
        doc = rec.finalize()
    finally:
        convergence.uninstall()
        convergence.clear_outcome()
    assert doc["rounds"]["tie_window_count"] >= 1
    assert doc["rounds"]["tie_windows"]
    assert doc["moves_emitted"] >= 1
    assert all(m["origin"] == "step" for m in doc["moves"])


def test_per_move_greedy_masking_and_threshold_counts():
    """The greedy scan's recorder feeds: scored/masked totals and the
    min_unbalance threshold bucket (improving-but-not-clearing)."""
    rng = random.Random(17)
    pl = random_partition_list(rng, 10, 4, filled=True)
    cfg = RebalanceConfig(
        min_unbalance=1e6, min_replicas_for_rebalancing=1, solver="greedy",
    )
    from kafkabalancer_tpu.balancer import balance

    rec = convergence.ConvergenceRecorder()
    convergence.install(rec)
    try:
        convergence.clear_outcome()
        rec.attach(pl, cfg, mode="per-move", solver="greedy", max_reassign=1)
        ppl = balance(pl, cfg)
        assert len(ppl) == 0  # threshold blocks everything
        doc = rec.finalize()
    finally:
        convergence.uninstall()
        convergence.clear_outcome()
    assert doc["candidates"]["scored"] > 0
    assert doc["candidates"]["masked"]["min_unbalance"] > 0
    assert doc["no_move_reason"]["reason"] == "below_threshold"
