"""The pre-merge gate itself, as a (slow) test.

Shells out to ``scripts/gate.sh --no-tests`` — the static stages only
(jaxlint, annotation coverage, mypy/ruff when installed). The tier-1
pytest stage is skipped because THIS test runs inside that suite's
``slow``-marked complement; the full gate is what CI / a pre-merge hook
runs directly.
"""

import os
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
GATE = os.path.join(REPO, "scripts", "gate.sh")


@pytest.mark.slow
def test_gate_static_stages_pass():
    proc = subprocess.run(
        ["bash", GATE, "--no-tests"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "GATE PASS" in proc.stdout


@pytest.mark.slow
def test_gate_rejects_unknown_flags():
    proc = subprocess.run(
        ["bash", GATE, "--bogus"],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 2
