"""Tests for the JAX-aware linter (kafkabalancer_tpu/analysis/).

Each rule R1–R5 gets at least one FAILING and one PASSING fixture
(ISSUE acceptance criterion), plus coverage of the machinery the gate
depends on: trace-context detection (decorated, lax-combinator bodies,
nested defs, module-local call-graph propagation), inline suppressions,
the baseline file, JSON output, the annotation-coverage checker, and —
the contract the whole subsystem exists for — the shipped tree being
clean under the gate.

Pure stdlib under test: none of this imports jax.
"""

import json
import os
import subprocess
import sys

from kafkabalancer_tpu.analysis import (
    ALL_RULES,
    lint_paths,
    lint_source,
    load_baseline,
    subtract_baseline,
    write_baseline,
)
from kafkabalancer_tpu.analysis.annotations import check_paths
from kafkabalancer_tpu.analysis.jaxlint import format_json, main

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PACKAGE = os.path.join(REPO, "kafkabalancer_tpu")


def rules_of(findings):
    return sorted({f.rule for f in findings})


# ---------------------------------------------------------------- R1


R1_FAIL = """
import jax

@jax.jit  # jaxlint: disable=R2
def f(x):
    return float(x) + 1
"""

R1_FAIL_ITEM_IN_SCAN = """
from jax import lax

def body(carry, x):
    return carry + x.item(), None

def outer(xs):
    return lax.scan(body, 0.0, xs)
"""

R1_PASS_STATIC_SHAPE = """
import jax

@jax.jit  # jaxlint: disable=R2
def f(x):
    n = int(x.shape[0])
    m = float(len(x.shape))
    return x * n * m
"""

R1_PASS_HOST = """
def decode(packed):
    return int(packed[-1]), float(packed[0])
"""


def test_r1_flags_traced_coercion():
    assert rules_of(lint_source(R1_FAIL)) == ["R1"]


def test_r1_flags_item_in_scan_body():
    assert rules_of(lint_source(R1_FAIL_ITEM_IN_SCAN)) == ["R1"]


def test_r1_passes_static_shape_coercion():
    assert lint_source(R1_PASS_STATIC_SHAPE) == []


def test_r1_passes_host_code():
    assert lint_source(R1_PASS_HOST) == []


# ---------------------------------------------------------------- R2


R2_FAIL_BARE_DECORATOR = """
import jax

@jax.jit
def f(x):
    return x
"""

R2_FAIL_CALL = """
import jax

def f(x):
    return x

g = jax.jit(f)
"""

R2_PASS_PARTIAL = """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("n",))
def f(x, n):
    return x

@partial(jax.jit, static_argnames=())
def g(x):
    return x

h = jax.jit(g, donate_argnums=(0,))
"""


def test_r2_flags_bare_decorator():
    assert rules_of(lint_source(R2_FAIL_BARE_DECORATOR)) == ["R2"]


def test_r2_flags_undeclared_call():
    assert rules_of(lint_source(R2_FAIL_CALL)) == ["R2"]


def test_r2_passes_declared_sites():
    assert lint_source(R2_PASS_PARTIAL) == []


# ---------------------------------------------------------------- R3


R3_FAIL_NUMPY = """
import jax
import numpy as np

@jax.jit  # jaxlint: disable=R2
def f(x):
    return np.sum(x)
"""

R3_FAIL_SYNC_IN_LOOP_BODY = """
import jax
from jax import lax

def body(carry, x):
    jax.block_until_ready(carry)
    return carry, x

def outer(xs):
    return lax.scan(body, 0.0, xs)
"""

R3_PASS_HOST_NUMPY = """
import numpy as np

def decode(packed):
    return np.asarray(packed)
"""

R3_PASS_NP_CONSTANTS = """
import jax
import jax.numpy as jnp
import numpy as np

@jax.jit  # jaxlint: disable=R2
def f(x):
    return jnp.where(x > 0, x, np.inf)
"""


def test_r3_flags_numpy_in_jit():
    assert rules_of(lint_source(R3_FAIL_NUMPY)) == ["R3"]


def test_r3_flags_sync_in_scan_body():
    assert rules_of(lint_source(R3_FAIL_SYNC_IN_LOOP_BODY)) == ["R3"]


def test_r3_passes_host_numpy():
    assert lint_source(R3_PASS_HOST_NUMPY) == []


def test_r3_passes_numpy_scalar_constants():
    assert lint_source(R3_PASS_NP_CONSTANTS) == []


def test_r3_callgraph_propagation():
    src = """
import jax
import numpy as np

def helper(y):
    return np.asarray(y)

@jax.jit  # jaxlint: disable=R2
def f(x):
    return helper(x)
"""
    fs = lint_source(src)
    assert rules_of(fs) == ["R3"]
    assert "helper" not in fs[0].snippet or "np.asarray" in fs[0].snippet


# ---------------------------------------------------------------- R4


R4_FAIL_ATTR = """
import jax.numpy as jnp

def f(x):
    return x.astype(jnp.float64)
"""

R4_FAIL_STRING = """
import numpy as np

def f(x):
    return np.zeros(3, dtype="float32")
"""

R4_PASS_POLICY = """
from kafkabalancer_tpu.models.config import HOST_FLOAT_DTYPE, default_dtype
import numpy as np

def f(x):
    return np.zeros(3, dtype=HOST_FLOAT_DTYPE).astype(default_dtype())
"""

R4_PASS_INT_DTYPES = """
import jax.numpy as jnp

def f(x):
    return x.astype(jnp.int32)
"""


def test_r4_flags_float_dtype_attribute():
    assert rules_of(lint_source(R4_FAIL_ATTR)) == ["R4"]


def test_r4_flags_float_dtype_string():
    assert rules_of(lint_source(R4_FAIL_STRING)) == ["R4"]


def test_r4_flags_positional_dtype_string():
    src = """
import numpy as np

def f(x):
    return np.zeros(3, "float64"), x.astype("float32")
"""
    fs = lint_source(src)
    assert [f.rule for f in fs] == ["R4", "R4"]


def test_r4_passes_policy_routing():
    assert lint_source(R4_PASS_POLICY) == []


def test_r4_passes_integer_dtypes():
    assert lint_source(R4_PASS_INT_DTYPES) == []


def test_r4_ignores_non_dtype_string_uses():
    src = """
import logging

def f(s, log):
    log.warning("float32")
    return s.startswith("float64")
"""
    assert lint_source(src) == []


def test_r4_flags_from_import_spelling():
    src = """
from numpy import float64
import numpy as np

def f():
    return np.zeros(3, float64)
"""
    assert rules_of(lint_source(src)) == ["R4"]


def test_r4_exempts_the_policy_module():
    src = "import jax.numpy as jnp\nDTYPE = jnp.float64\n"
    assert lint_source(src, path="kafkabalancer_tpu/models/config.py") == []
    assert rules_of(lint_source(src, path="other.py")) == ["R4"]


# ---------------------------------------------------------------- R5


R5_FAIL = """
import jax

@jax.jit  # jaxlint: disable=R2
def f(x):
    return x[x > 0]
"""

R5_FAIL_COMPOUND = """
import jax

@jax.jit  # jaxlint: disable=R2
def f(x, m):
    return x[(x > 0) & (x < m)]
"""

R5_PASS_WHERE = """
import jax
import jax.numpy as jnp

@jax.jit  # jaxlint: disable=R2
def f(x):
    return jnp.where(x > 0, x, 0.0)
"""

R5_PASS_HOST = """
def f(x):
    return x[x > 0]
"""


def test_r5_flags_boolean_mask_indexing():
    assert rules_of(lint_source(R5_FAIL)) == ["R5"]


def test_r5_flags_compound_masks():
    assert rules_of(lint_source(R5_FAIL_COMPOUND)) == ["R5"]


def test_r5_passes_where():
    assert lint_source(R5_PASS_WHERE) == []


def test_r5_passes_host_mask_indexing():
    assert lint_source(R5_PASS_HOST) == []


# ------------------------------------------------------- machinery


def test_shard_map_decorated_body_is_traced():
    """The @partial(shard_map, ...) idiom — the spelling of the three
    sharded compute bodies in parallel/ — is a traced context for
    R1/R3/R5."""
    src = """
from functools import partial
import numpy as np
from kafkabalancer_tpu.parallel.mesh import shard_map

@partial(shard_map, mesh=None, in_specs=(), out_specs=())
def body(x):
    return np.sum(x) + float(x), x[x > 0]
"""
    assert rules_of(lint_source(src)) == ["R1", "R3", "R5"]


def test_nested_defs_inherit_trace_context():
    src = """
import jax

@jax.jit  # jaxlint: disable=R2
def f(x):
    def inner(y):
        return float(y)
    return inner(x)
"""
    assert rules_of(lint_source(src)) == ["R1"]


def test_inline_suppression_with_reason():
    src = """
import jax

@jax.jit  # jaxlint: disable=R2 — wrapper is retrace-free by design
def f(x):
    return x
"""
    assert lint_source(src) == []


def test_suppression_is_rule_specific():
    src = """
import jax

@jax.jit  # jaxlint: disable=R5
def f(x):
    return x
"""
    assert rules_of(lint_source(src)) == ["R2"]


def test_suppression_covers_multiline_calls():
    """A disable on the call head suppresses findings anchored anywhere
    in the call — keyword and positional dtype spellings behave the
    same."""
    src = """
import numpy as np

def f():
    a = np.zeros(  # jaxlint: disable=R4
        3,
        dtype="float64",
    )
    b = np.zeros(  # jaxlint: disable=R4
        3,
        "float64",
    )
    return a, b
"""
    assert lint_source(src) == []


def test_suppression_accepts_space_separated_rules():
    src = """
import jax
import jax.numpy as jnp

@jax.jit  # jaxlint: disable=R2 R4
def f(x):
    return x.astype(jnp.float64)
"""
    # R2 suppressed on the decorator line; R4 anchors inside the body
    # on its own line, so only it reports
    assert rules_of(lint_source(src)) == ["R4"]
    src_ok = src.replace(
        "return x.astype(jnp.float64)",
        "return x.astype(jnp.float64)  # jaxlint: disable=R4 R1",
    )
    assert lint_source(src_ok) == []


def test_directives_in_string_literals_are_inert():
    """Only COMMENT tokens carry directives: a docstring quoting
    '# jaxlint: skip-file' or 'disable=all' must not disable linting."""
    src = (
        '"""Docs: put # jaxlint: skip-file at the top to skip."""\n'
        "import jax\n"
        "\n"
        'HELP = "# jaxlint: disable=all"\n'
        "@jax.jit\n"
        "def f(x):\n"
        "    return x\n"
    )
    assert rules_of(lint_source(src)) == ["R2"]


def test_skip_file_pragma():
    src = (
        "# jaxlint: skip-file\nimport jax\n\n"
        "@jax.jit\ndef f(x):\n    return float(x)\n"
    )
    assert lint_source(src) == []


def test_select_subset_of_rules():
    fs = lint_source(R2_FAIL_BARE_DECORATOR, rules=("R5",))
    assert fs == []


def test_baseline_roundtrip(tmp_path):
    fs = lint_source(R2_FAIL_BARE_DECORATOR, path="mod.py")
    assert len(fs) == 1
    path = str(tmp_path / "baseline.json")
    write_baseline(fs, path)
    baseline = load_baseline(path)
    assert subtract_baseline(fs, baseline) == []
    # a NEW finding on top of the grandfathered one still reports
    fs2 = fs + lint_source(R4_FAIL_ATTR, path="mod2.py")
    left = subtract_baseline(fs2, baseline)
    assert rules_of(left) == ["R4"]


def test_json_output_schema():
    fs = lint_source(R4_FAIL_ATTR, path="mod.py")
    data = json.loads(format_json(fs))
    assert data["count"] == 1
    (entry,) = data["findings"]
    assert entry["rule"] == "R4"
    assert entry["path"] == "mod.py"
    assert entry["line"] > 0 and entry["message"]


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("import jax\n\n@jax.jit\ndef f(x):\n    return x\n")
    good = tmp_path / "good.py"
    good.write_text("def f(x):\n    return x\n")
    assert main([str(good)]) == 0
    assert main([str(bad)]) == 1
    assert main(["--select", "NOPE", str(bad)]) == 2


def test_registry_covers_r1_to_r5():
    assert sorted(ALL_RULES) == ["R1", "R2", "R3", "R4", "R5"]


def test_syntax_error_reported_not_raised(tmp_path):
    p = tmp_path / "broken.py"
    p.write_text("def f(:\n")
    fs = lint_paths([str(p)])
    assert len(fs) == 1 and fs[0].rule == "E0"


# ------------------------------------------- annotation coverage


def test_annotation_checker_flags_and_passes(tmp_path):
    bad = tmp_path / "bad.py"
    bad.write_text("def f(x):\n    return x\n")
    good = tmp_path / "good.py"
    good.write_text(
        "class C:\n"
        "    def m(self, x: int) -> int:\n"
        "        return x\n"
        "def f(x: int, *args: int, **kw: int) -> int:\n"
        "    return x\n"
    )
    assert [f.rule for f in check_paths([str(bad)])] == ["ANN"]
    assert check_paths([str(good)]) == []


def test_annotation_checker_suppressible(tmp_path):
    p = tmp_path / "mod.py"
    p.write_text("def f(x):  # jaxlint: disable=ANN\n    return x\n")
    assert check_paths([str(p)]) == []


def test_annotation_finding_not_suppressed_by_interior_comments(tmp_path):
    """A disable buried in the body (e.g. silencing R4 on one line) must
    not exempt the enclosing function from the typing floor."""
    p = tmp_path / "mod.py"
    p.write_text(
        "def f(x):\n"
        "    y = 1  # jaxlint: disable=all\n"
        "    return x + y\n"
    )
    assert [f.rule for f in check_paths([str(p)])] == ["ANN"]


def test_unreadable_file_is_exit_2_not_findings(tmp_path):
    p = tmp_path / "mod.py"
    p.write_bytes(b"\xff\xfe invalid utf8 \xff")
    assert main([str(p)]) == 2
    assert main(["--annotations", str(p)]) == 2


# ------------------------------------------------- the real tree


def test_shipped_package_is_clean_under_the_gate():
    """The merged tree lints clean: the acceptance criterion that
    ``python -m kafkabalancer_tpu.analysis kafkabalancer_tpu/`` exits 0."""
    findings = lint_paths([PACKAGE])
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: [{f.rule}] {f.message}" for f in findings
    )


def test_shipped_typed_subpackages_have_full_annotation_coverage():
    paths = [os.path.join(PACKAGE, d) for d in ("models", "ops", "codecs")]
    findings = check_paths(paths)
    assert findings == [], "\n".join(
        f"{f.path}:{f.line}: {f.message}" for f in findings
    )


def test_module_entry_point_exits_zero_on_package():
    proc = subprocess.run(
        [sys.executable, "-m", "kafkabalancer_tpu.analysis", PACKAGE],
        capture_output=True,
        text=True,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "clean" in proc.stdout
